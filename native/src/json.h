// Minimal JSON DOM for the native control-plane core.
//
// The store (store.cc) keeps whole API objects as JSON and needs to
// introspect metadata (labels, finalizers, ownerReferences), so the native
// tier carries its own parser/serializer rather than depending on a
// system library (none is baked into the image). Supports the full JSON
// grammar with UTF-8 passthrough and \uXXXX escapes (incl. surrogate
// pairs). Not exported over the C ABI — internal to libkftpu_core.

#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <variant>
#include <vector>

namespace kftpu {

class Json;
using JsonArray = std::vector<Json>;
// std::map keeps serialization deterministic (sorted keys) — handy for
// golden tests and stable resourceVersion-independent diffing.
using JsonObject = std::map<std::string, Json>;

class Json {
 public:
  enum class Type { kNull, kBool, kNumber, kString, kArray, kObject };

  Json() : v_(nullptr) {}
  Json(std::nullptr_t) : v_(nullptr) {}
  Json(bool b) : v_(b) {}
  Json(double d) : v_(d) {}
  Json(int i) : v_(static_cast<double>(i)) {}
  Json(int64_t i) : v_(static_cast<double>(i)) {}
  Json(const char* s) : v_(std::string(s)) {}
  Json(std::string s) : v_(std::move(s)) {}
  Json(JsonArray a) : v_(std::move(a)) {}
  Json(JsonObject o) : v_(std::move(o)) {}

  Type type() const { return static_cast<Type>(v_.index()); }
  bool is_null() const { return type() == Type::kNull; }
  bool is_bool() const { return type() == Type::kBool; }
  bool is_number() const { return type() == Type::kNumber; }
  bool is_string() const { return type() == Type::kString; }
  bool is_array() const { return type() == Type::kArray; }
  bool is_object() const { return type() == Type::kObject; }

  bool as_bool() const { return std::get<bool>(v_); }
  double as_number() const { return std::get<double>(v_); }
  const std::string& as_string() const { return std::get<std::string>(v_); }
  const JsonArray& as_array() const { return std::get<JsonArray>(v_); }
  JsonArray& as_array() { return std::get<JsonArray>(v_); }
  const JsonObject& as_object() const { return std::get<JsonObject>(v_); }
  JsonObject& as_object() { return std::get<JsonObject>(v_); }

  // Object convenience: get(key) returns null Json when absent/not object.
  const Json& get(const std::string& key) const;
  bool has(const std::string& key) const;
  // get(key).as_string() with a default when absent or not a string.
  std::string get_string(const std::string& key,
                         const std::string& def = "") const;

  std::string dump() const;

  // Returns false (and fills err with position info) on malformed input.
  static bool Parse(const std::string& text, Json* out, std::string* err);

 private:
  std::variant<std::nullptr_t, bool, double, std::string, JsonArray,
               JsonObject>
      v_;
};

}  // namespace kftpu
