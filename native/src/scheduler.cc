#include "scheduler.h"

#include <algorithm>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Node {
  std::string name;
  std::string pool;
  int32_t x = 0;
  int32_t y = 0;
  int32_t chips = 0;
  int32_t reserved = 0;  // chips currently reserved by gangs
};

struct Scheduler {
  std::mutex mu;
  std::map<std::string, Node> nodes;
  // job -> (node name, chips) reservations, one entry per worker.
  std::map<std::string, std::vector<std::pair<std::string, int32_t>>> gangs;
  // pool -> (width, height) torus dims; absent/0 = flat on that axis.
  std::map<std::string, std::pair<int32_t, int32_t>> pool_topo;
};

// Per-axis hop count: wraparound when the pool declared a torus dim
// (real v5e pod slices wrap their ICI links — a ring crossing the seam
// is ONE hop, not width-1). Coordinates are reduced mod size so an
// out-of-range x still lands on the torus instead of going negative.
int64_t AxisDist(int64_t d, int32_t size) {
  d = std::abs(d);
  if (size > 1) {
    d %= size;
    return std::min(d, (int64_t)size - d);
  }
  return d;
}

int64_t Dist(const Scheduler& s, const Node& a, const Node& b) {
  int32_t w = 0, h = 0;
  auto it = s.pool_topo.find(a.pool);
  if (it != s.pool_topo.end()) {
    w = it->second.first;
    h = it->second.second;
  }
  return AxisDist((int64_t)a.x - b.x, w) + AxisDist((int64_t)a.y - b.y, h);
}

// A placement slot: a (node, worker capacity) pair expanded per worker.
struct Slot {
  const Node* node;
};

}  // namespace

extern "C" {

void* kftpu_sched_new() { return new Scheduler(); }

void kftpu_sched_free(void* s) { delete static_cast<Scheduler*>(s); }

int32_t kftpu_sched_add_node(void* sp, const char* name, const char* pool,
                             int32_t x, int32_t y, int32_t chips) {
  if (!sp || !name || !pool || chips < 0) return -1;
  auto* s = static_cast<Scheduler*>(sp);
  std::lock_guard<std::mutex> lock(s->mu);
  auto [it, inserted] = s->nodes.emplace(name, Node{name, pool, x, y, chips, 0});
  (void)it;
  return inserted ? 0 : -1;
}

int32_t kftpu_sched_remove_node(void* sp, const char* name) {
  if (!sp || !name) return -1;
  auto* s = static_cast<Scheduler*>(sp);
  std::lock_guard<std::mutex> lock(s->mu);
  return s->nodes.erase(name) ? 0 : -1;
}

int32_t kftpu_sched_set_pool_topology(void* sp, const char* pool,
                                      int32_t width, int32_t height) {
  if (!sp || !pool || width < 0 || height < 0) return -1;
  auto* s = static_cast<Scheduler*>(sp);
  std::lock_guard<std::mutex> lock(s->mu);
  s->pool_topo[pool] = {width, height};
  return 0;
}

int64_t kftpu_sched_place_gang(void* sp, const char* job, const char* pool,
                               int32_t workers, int32_t chips_per_worker,
                               char* out, int32_t out_len) {
  if (!sp || !job || !pool || workers <= 0 || chips_per_worker < 0 || !out)
    return -3;
  auto* s = static_cast<Scheduler*>(sp);
  std::lock_guard<std::mutex> lock(s->mu);
  if (s->gangs.count(job)) return -3;

  // Free slots in the pool in serpentine (boustrophedon) order: row-major
  // but with odd rows reversed, so the end of each row is physically
  // adjacent to the start of the next — consecutive ranks stay one ICI
  // hop apart even across row boundaries.
  std::vector<const Node*> pool_nodes;
  for (auto& [_, n] : s->nodes)
    if (n.pool == pool) pool_nodes.push_back(&n);
  std::sort(pool_nodes.begin(), pool_nodes.end(),
            [](const Node* a, const Node* b) {
              if (a->y != b->y) return a->y < b->y;
              const bool reversed = (a->y & 1) != 0;
              if (a->x != b->x) return reversed ? a->x > b->x : a->x < b->x;
              return a->name < b->name;
            });

  std::vector<Slot> slots;
  for (const Node* n : pool_nodes) {
    int32_t cap = chips_per_worker == 0
                      ? (n->chips >= n->reserved ? workers : 0)  // cpu-only
                      : (n->chips - n->reserved) / chips_per_worker;
    for (int32_t i = 0; i < cap && (int32_t)slots.size() < workers * 2 + 1024;
         ++i)
      slots.push_back(Slot{n});
  }
  if ((int32_t)slots.size() < workers) return -1;

  // Best window: minimize the ring cost — the sum of Manhattan distances
  // between consecutive ranks. Consecutive ranks exchange the most data
  // (ring collectives), so they should be physical neighbors.
  int64_t best_cost = -1;
  size_t best_start = 0;
  for (size_t start = 0; start + workers <= slots.size(); ++start) {
    int64_t cost = 0;
    for (int32_t i = 1; i < workers; ++i)
      cost += Dist(*s, *slots[start + i - 1].node, *slots[start + i].node);
    if (best_cost < 0 || cost < best_cost) {
      best_cost = cost;
      best_start = start;
    }
  }

  // Serialize assignment and reserve atomically.
  std::string result;
  for (int32_t i = 0; i < workers; ++i) {
    if (i) result += ';';
    result += slots[best_start + i].node->name;
  }
  if ((int32_t)result.size() + 1 > out_len) return -2;

  auto& gang = s->gangs[job];
  for (int32_t i = 0; i < workers; ++i) {
    // const_cast is safe: slots reference nodes owned by s->nodes.
    auto* n = const_cast<Node*>(slots[best_start + i].node);
    n->reserved += chips_per_worker;
    gang.emplace_back(n->name, chips_per_worker);
  }
  std::memcpy(out, result.c_str(), result.size() + 1);
  return best_cost;
}

int32_t kftpu_sched_reserve(void* sp, const char* job, const char* node,
                            int32_t chips) {
  if (!sp || !job || !node || chips < 0) return -1;
  auto* s = static_cast<Scheduler*>(sp);
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->nodes.find(node);
  if (it == s->nodes.end()) return -1;
  it->second.reserved += chips;
  s->gangs[job].emplace_back(node, chips);
  return 0;
}

int32_t kftpu_sched_release_gang(void* sp, const char* job) {
  if (!sp || !job) return -1;
  auto* s = static_cast<Scheduler*>(sp);
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->gangs.find(job);
  if (it == s->gangs.end()) return -1;
  for (auto& [node_name, chips] : it->second) {
    auto nit = s->nodes.find(node_name);
    if (nit != s->nodes.end()) nit->second.reserved -= chips;
  }
  int32_t n = (int32_t)it->second.size();
  s->gangs.erase(it);
  return n;
}

int64_t kftpu_sched_free_chips(void* sp, const char* pool) {
  if (!sp || !pool) return -1;
  auto* s = static_cast<Scheduler*>(sp);
  std::lock_guard<std::mutex> lock(s->mu);
  int64_t total = 0;
  for (auto& [_, n] : s->nodes)
    if (n.pool == pool) total += std::max(0, n.chips - n.reserved);
  return total;
}

}  // extern "C"
