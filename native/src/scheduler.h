// Topology-aware gang scheduler for TPU slices — the native scheduling
// core of the kubeflow-tpu platform.
//
// The reference's scheduling story was "tf-operator gangs replicas but
// knows no topology" (SURVEY.md §2.2 "Gang scheduling / topology
// awareness: Minimal"); TPU slices make placement a first-class problem:
// a gang must land on ICI-adjacent hosts, all-or-nothing, and consecutive
// ranks should be physical neighbors so ring collectives (ring attention,
// reduce-scatter rings) ride single ICI hops.
//
// C ABI for ctypes consumption from the Python control plane.

#pragma once
#include <cstdint>

extern "C" {

// Opaque scheduler handle. Thread-safe.
void* kftpu_sched_new();
void kftpu_sched_free(void* s);

// Register a host: `pool` groups interchangeable nodes (accelerator type +
// topology), (x, y) are the host's coordinates in the pool's physical
// mesh, `chips` its TPU chip count. Returns 0, or -1 if the name exists.
int32_t kftpu_sched_add_node(void* s, const char* name, const char* pool,
                             int32_t x, int32_t y, int32_t chips);

// Remove a host (e.g. failure detected). Gangs holding it keep their
// reservation records; callers re-place after release. Returns 0 or -1.
int32_t kftpu_sched_remove_node(void* s, const char* name);

// Declare pool `pool`'s physical topology as a WIDTH x HEIGHT 2D TORUS:
// ring cost between hosts then uses wraparound distance per axis
// (min(d, size-d)), the way real v5e pod slices wrap their ICI links.
// A dimension of 0/1 means no wrap on that axis; undeclared pools use
// flat Manhattan distance. Returns 0, or -1 on bad args.
int32_t kftpu_sched_set_pool_topology(void* s, const char* pool,
                                      int32_t width, int32_t height);

// Atomically place a gang of `workers` workers needing `chips_per_worker`
// chips each onto pool `pool`. On success writes a ';'-separated node-name
// list (one entry per worker, rank order) into out (size out_len) and
// reserves capacity. Returns:
//   >=0  total ring cost (sum of Manhattan distances between consecutive
//        ranks — lower is better ICI locality)
//   -1   insufficient capacity (nothing reserved)
//   -2   output buffer too small
//   -3   job already placed / bad args
int64_t kftpu_sched_place_gang(void* s, const char* job, const char* pool,
                               int32_t workers, int32_t chips_per_worker,
                               char* out, int32_t out_len);

// Release a gang's reservation. Returns freed worker count, or -1.
int32_t kftpu_sched_release_gang(void* s, const char* job);

// Directly reserve `chips` on a named node for `job` — used to rebuild
// scheduler state from observed placements (existing pods' nodeName)
// rather than trusting a long-lived in-memory mirror. Returns 0, or -1 if
// the node is unknown.
int32_t kftpu_sched_reserve(void* s, const char* job, const char* node,
                            int32_t chips);

// Free chips in a pool.
int64_t kftpu_sched_free_chips(void* s, const char* pool);

}  // extern "C"
