// Native-tier unit tests (run via ctest).
#include "scheduler.h"

#include <cassert>
#include <cstdio>
#include <cstring>
#include <string>

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      std::fprintf(stderr, "FAILED: %s (line %d)\n", #cond, __LINE__); \
      return 1;                                                        \
    }                                                                  \
  } while (0)

int main() {
  void* s = kftpu_sched_new();
  // A v5e-16 pool: 4 hosts in a row, 4 chips each.
  for (int i = 0; i < 4; ++i) {
    std::string name = "host-" + std::to_string(i);
    CHECK(kftpu_sched_add_node(s, name.c_str(), "v5e-4x4", i, 0, 4) == 0);
  }
  CHECK(kftpu_sched_add_node(s, "host-0", "v5e-4x4", 0, 0, 4) == -1);  // dup
  CHECK(kftpu_sched_free_chips(s, "v5e-4x4") == 16);

  char out[512];
  // Full-slice gang: 4 workers x 4 chips; contiguous row => ring cost 3.
  long cost = kftpu_sched_place_gang(s, "job-a", "v5e-4x4", 4, 4, out, 512);
  CHECK(cost == 3);
  CHECK(std::string(out) == "host-0;host-1;host-2;host-3");
  CHECK(kftpu_sched_free_chips(s, "v5e-4x4") == 0);

  // No capacity left: all-or-nothing refusal.
  CHECK(kftpu_sched_place_gang(s, "job-b", "v5e-4x4", 1, 4, out, 512) == -1);
  // Duplicate job id refused.
  CHECK(kftpu_sched_place_gang(s, "job-a", "v5e-4x4", 1, 4, out, 512) == -3);

  // Release frees everything.
  CHECK(kftpu_sched_release_gang(s, "job-a") == 4);
  CHECK(kftpu_sched_free_chips(s, "v5e-4x4") == 16);
  CHECK(kftpu_sched_release_gang(s, "job-a") == -1);

  // Topology preference: with a hole in the middle, placement picks the
  // contiguous pair, not the fragmented one.
  kftpu_sched_place_gang(s, "hole", "v5e-4x4", 1, 4, out, 512);
  // "hole" takes host-0 (first best single). Now 2-worker gang should pick
  // host-1,host-2 or host-2,host-3 (cost 1), never host-1,host-3 (cost 2).
  cost = kftpu_sched_place_gang(s, "pair", "v5e-4x4", 2, 4, out, 512);
  CHECK(cost == 1);

  // Multi-worker per node when chips allow: 2 workers x 2 chips on one
  // remaining 4-chip host => ring cost 0.
  CHECK(kftpu_sched_release_gang(s, "pair") == 2);
  cost = kftpu_sched_place_gang(s, "packed", "v5e-4x4", 2, 2, out, 512);
  CHECK(cost == 0);
  std::string assigned(out);
  CHECK(assigned.find(';') != std::string::npos);

  // Node removal.
  CHECK(kftpu_sched_remove_node(s, "host-3") == 0);
  CHECK(kftpu_sched_remove_node(s, "host-3") == -1);

  kftpu_sched_free(s);

  // --- Torus wraparound (v5e pod slices wrap their ICI links) -------------
  void* t = kftpu_sched_new();
  // A 6-wide ring. Free capacity at the SEAM (x=0 and x=5) plus one
  // off-row host (x=2, y=1).
  CHECK(kftpu_sched_add_node(t, "t0", "6x1", 0, 0, 4) == 0);
  CHECK(kftpu_sched_add_node(t, "t5", "6x1", 5, 0, 4) == 0);
  CHECK(kftpu_sched_add_node(t, "t2b", "6x1", 2, 1, 4) == 0);
  char tout[512];
  // WITHOUT the torus declaration (flat Manhattan) the seam pair costs 5,
  // so placement prefers t5->t2b (3+1=4): the wrong physical choice on
  // wrapped hardware.
  long flat = kftpu_sched_place_gang(t, "flat", "6x1", 2, 4, tout, 512);
  CHECK(flat == 4);
  CHECK(std::string(tout) == "t5;t2b");
  CHECK(kftpu_sched_release_gang(t, "flat") == 2);
  // WITH the torus declared, the seam pair is ONE wrap hop and wins.
  CHECK(kftpu_sched_set_pool_topology(t, "6x1", 6, 1) == 0);
  long wrapped = kftpu_sched_place_gang(t, "wrap", "6x1", 2, 4, tout, 512);
  CHECK(wrapped == 1);
  CHECK(std::string(tout) == "t0;t5");
  CHECK(kftpu_sched_set_pool_topology(t, "6x1", -1, 1) == -1);  // bad args
  kftpu_sched_free(t);

  std::printf("all native scheduler tests passed\n");
  return 0;
}
