#include "store.h"

#include <atomic>
#include <chrono>
#include <cstdio>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <tuple>
#include <vector>

#include "json.h"

namespace {

using kftpu::Json;
using kftpu::JsonArray;
using kftpu::JsonObject;

thread_local int32_t tls_status = KFTPU_STORE_OK;
thread_local std::string tls_error;
thread_local std::string tls_result;

const char* Ok(std::string result) {
  tls_status = KFTPU_STORE_OK;
  tls_error.clear();
  tls_result = std::move(result);
  return tls_result.c_str();
}

const char* Err(int32_t code, std::string msg) {
  tls_status = code;
  tls_error = std::move(msg);
  return nullptr;
}

double NowSeconds() {
  return std::chrono::duration<double>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

using Key = std::tuple<std::string, std::string, std::string>;  // kind,ns,name

std::string KeyStr(const Key& k) {
  return std::get<0>(k) + " " + std::get<1>(k) + "/" + std::get<2>(k);
}

struct Event {
  int64_t seq;
  std::string type;
  Json object;
};

// Metadata accessors over the JSON doc -------------------------------------

Json& Meta(Json& obj) { return obj.as_object()["metadata"]; }

const Json& Meta(const Json& obj) { return obj.get("metadata"); }

bool ExtractKey(const Json& obj, Key* out, std::string* why) {
  if (!obj.is_object()) {
    *why = "object is not a JSON object";
    return false;
  }
  std::string kind = obj.get_string("kind");
  const Json& meta = Meta(obj);
  std::string name = meta.get_string("name");
  std::string ns = meta.get_string("namespace", "default");
  if (kind.empty() || name.empty()) {
    *why = "kind and metadata.name are required";
    return false;
  }
  *out = Key{kind, ns, name};
  return true;
}

int64_t MetaInt(const Json& obj, const std::string& field) {
  const Json& v = Meta(obj).get(field);
  return v.is_number() ? static_cast<int64_t>(v.as_number()) : 0;
}

bool HasFinalizers(const Json& obj) {
  const Json& f = Meta(obj).get("finalizers");
  return f.is_array() && !f.as_array().empty();
}

bool DeletionPending(const Json& obj) {
  return Meta(obj).get("deletionTimestamp").is_number();
}

bool LabelsMatch(const Json& obj, const Json& selector) {
  if (!selector.is_object() || selector.as_object().empty()) return true;
  const Json& labels = Meta(obj).get("labels");
  for (const auto& [k, v] : selector.as_object()) {
    const Json& have = labels.get(k);
    if (!have.is_string() || !v.is_string() ||
        have.as_string() != v.as_string())
      return false;
  }
  return true;
}

class Store {
 public:
  const char* Create(const char* obj_json) {
    Json obj;
    std::string err;
    if (!Json::Parse(obj_json ? obj_json : "", &obj, &err))
      return Err(KFTPU_STORE_BAD_OBJECT, "parse: " + err);
    Key key;
    if (!ExtractKey(obj, &key, &err))
      return Err(KFTPU_STORE_BAD_OBJECT, err);
    std::string result;
    {
      std::lock_guard<std::mutex> lock(mu_);
      if (objects_.count(key))
        return Err(KFTPU_STORE_ALREADY_EXISTS, KeyStr(key) + " already exists");
      JsonObject& meta = Meta(obj).is_object()
                             ? Meta(obj).as_object()
                             : (Meta(obj) = Json(JsonObject{})).as_object();
      char uid[32];
      std::snprintf(uid, sizeof(uid), "uid-%llu",
                    static_cast<unsigned long long>(++uid_counter_));
      meta["uid"] = Json(std::string(uid));
      meta["resourceVersion"] = Json(static_cast<int64_t>(++rv_));
      meta["generation"] = Json(1);
      meta["creationTimestamp"] = Json(NowSeconds());
      objects_[key] = obj;
      Append("ADDED", obj);
      result = obj.dump();
    }
    return Ok(std::move(result));
  }

  const char* Get(const char* kind, const char* ns, const char* name) {
    // Exact namespace match: "" IS the cluster scope (FakeApiServer
    // parity) — coercing it to "default" made cluster-scoped objects
    // (Leases, Nodes, ClusterRoles) unreachable by get/delete.
    Key key{kind ? kind : "", ns ? ns : "", name ? name : ""};
    std::lock_guard<std::mutex> lock(mu_);
    auto it = objects_.find(key);
    if (it == objects_.end())
      return Err(KFTPU_STORE_NOT_FOUND, KeyStr(key) + " not found");
    return Ok(it->second.dump());
  }

  const char* Update(const char* obj_json, bool status_only) {
    Json obj;
    std::string err;
    if (!Json::Parse(obj_json ? obj_json : "", &obj, &err))
      return Err(KFTPU_STORE_BAD_OBJECT, "parse: " + err);
    Key key;
    if (!ExtractKey(obj, &key, &err))
      return Err(KFTPU_STORE_BAD_OBJECT, err);
    std::string result;
    {
      std::lock_guard<std::mutex> lock(mu_);
      auto it = objects_.find(key);
      if (it == objects_.end())
        return Err(KFTPU_STORE_NOT_FOUND, KeyStr(key) + " not found");
      Json& stored = it->second;
      int64_t incoming_rv = MetaInt(obj, "resourceVersion");
      int64_t current_rv = MetaInt(stored, "resourceVersion");
      if (incoming_rv != 0 && incoming_rv != current_rv) {
        char msg[160];
        std::snprintf(msg, sizeof(msg),
                      "%s: stale resourceVersion %lld != %lld",
                      KeyStr(key).c_str(),
                      static_cast<long long>(incoming_rv),
                      static_cast<long long>(current_rv));
        return Err(KFTPU_STORE_CONFLICT, msg);
      }
      JsonObject& smeta = Meta(stored).as_object();
      JsonObject& sobj = stored.as_object();
      JsonObject& iobj = obj.as_object();
      if (status_only) {
        sobj["status"] = iobj.count("status") ? iobj["status"]
                                              : Json(JsonObject{});
      } else {
        Json& ispec = iobj["spec"];
        if (!ispec.is_object()) ispec = Json(JsonObject{});
        if (sobj["spec"].dump() != ispec.dump()) {
          smeta["generation"] =
              Json(MetaInt(stored, "generation") + 1);
        }
        sobj["spec"] = ispec;
        const JsonObject& imeta = Meta(obj).is_object()
                                      ? Meta(obj).as_object()
                                      : JsonObject{};
        for (const char* field :
             {"labels", "annotations", "finalizers", "ownerReferences"}) {
          auto fit = imeta.find(field);
          smeta[field] = fit == imeta.end() ? Json() : fit->second;
        }
      }
      smeta["resourceVersion"] = Json(static_cast<int64_t>(++rv_));
      if (MaybeFinalize(key)) {
        result = last_removed_.dump();
      } else {
        Append("MODIFIED", stored);
        result = stored.dump();
      }
    }
    return Ok(std::move(result));
  }

  const char* List(const char* kind, const char* ns,
                   const char* selector_json) {
    Json selector;
    if (selector_json && *selector_json) {
      std::string err;
      if (!Json::Parse(selector_json, &selector, &err))
        return Err(KFTPU_STORE_BAD_OBJECT, "selector parse: " + err);
    }
    // ns == nullptr means ALL namespaces; ns == "" is the cluster
    // scope, matched exactly like any other namespace (Get/Delete
    // semantics; FakeApiServer parity).
    JsonArray out;
    {
      std::lock_guard<std::mutex> lock(mu_);
      for (const auto& [key, obj] : objects_) {
        if (std::get<0>(key) != (kind ? kind : "")) continue;
        if (ns != nullptr && std::get<1>(key) != ns) continue;
        if (!LabelsMatch(obj, selector)) continue;
        out.push_back(obj);
      }
    }
    return Ok(Json(std::move(out)).dump());
  }

  int32_t Delete(const char* kind, const char* ns, const char* name) {
    Key key{kind ? kind : "", ns ? ns : "", name ? name : ""};
    std::lock_guard<std::mutex> lock(mu_);
    return DeleteLocked(key);
  }

  const char* Events(int64_t cursor, int64_t* new_cursor) {
    JsonArray out;
    int64_t last = cursor;
    {
      std::lock_guard<std::mutex> lock(mu_);
      for (const Event& ev : journal_) {
        if (ev.seq <= cursor) continue;
        JsonObject e;
        e["seq"] = Json(ev.seq);
        e["type"] = Json(ev.type);
        e["object"] = ev.object;
        out.push_back(Json(std::move(e)));
        last = ev.seq;
      }
    }
    if (new_cursor) *new_cursor = last;
    return Ok(Json(std::move(out)).dump());
  }

  void Trim(int64_t cursor) {
    std::lock_guard<std::mutex> lock(mu_);
    while (!journal_.empty() && journal_.front().seq <= cursor)
      journal_.pop_front();
  }

  int64_t Len() {
    std::lock_guard<std::mutex> lock(mu_);
    return static_cast<int64_t>(objects_.size());
  }

 private:
  // All helpers below run with mu_ held.

  int32_t DeleteLocked(const Key& key) {
    auto it = objects_.find(key);
    if (it == objects_.end()) {
      Err(KFTPU_STORE_NOT_FOUND, KeyStr(key) + " not found");
      return KFTPU_STORE_NOT_FOUND;
    }
    Json& obj = it->second;
    if (HasFinalizers(obj)) {
      if (!DeletionPending(obj)) {
        JsonObject& meta = Meta(obj).as_object();
        meta["deletionTimestamp"] = Json(NowSeconds());
        meta["resourceVersion"] = Json(static_cast<int64_t>(++rv_));
        Append("MODIFIED", obj);
      }
      tls_status = KFTPU_STORE_OK;
      return KFTPU_STORE_OK;
    }
    Remove(key, /*emit_delete=*/true);
    tls_status = KFTPU_STORE_OK;
    return KFTPU_STORE_OK;
  }

  bool MaybeFinalize(const Key& key) {
    Json& stored = objects_.at(key);
    if (DeletionPending(stored) && !HasFinalizers(stored)) {
      last_removed_ = stored;
      // The caller's update cleared the last finalizer of a
      // deletion-pending object: that update IS the deletion. The
      // finalizing update already bumped rv onto last_removed_, so the
      // DELETED event is journal-ordered without another bump — but it
      // must be appended BEFORE Remove() runs the owner-ref cascade:
      // cascaded children get fresh (higher) rvs, and the journal must
      // stay rv-sorted (the Python wrapper's resume bisects on rv).
      Append("DELETED", last_removed_);
      Remove(key, /*emit_delete=*/false);
      return true;
    }
    return false;
  }

  void Remove(const Key& key, bool emit_delete) {
    Json obj = objects_.at(key);
    objects_.erase(key);
    if (emit_delete) {
      // Deletion is a state transition of its own: stamp the DELETED
      // event with a FRESH rv (FakeApiServer._remove parity) so a
      // watcher resuming from the object's last-seen version still
      // observes the removal — with the stale rv, events_since(rv)
      // would silently skip it and the watcher caches the object
      // forever.
      Meta(obj).as_object()["resourceVersion"] =
          Json(static_cast<int64_t>(++rv_));
      Append("DELETED", obj);
    }
    Cascade(obj);
    if (std::get<0>(key) == "Namespace") DrainNamespace(std::get<2>(key));
  }

  void Cascade(const Json& owner) {
    std::string uid = Meta(owner).get_string("uid");
    if (uid.empty()) return;
    std::vector<Key> dependents;
    for (const auto& [key, obj] : objects_) {
      const Json& refs = Meta(obj).get("ownerReferences");
      if (!refs.is_array()) continue;
      for (const Json& ref : refs.as_array()) {
        if (ref.get_string("uid") == uid) {
          dependents.push_back(key);
          break;
        }
      }
    }
    for (const Key& key : dependents)
      if (objects_.count(key)) DeleteLocked(key);
  }

  void DrainNamespace(const std::string& ns) {
    std::vector<Key> inside;
    for (const auto& [key, obj] : objects_)
      if (std::get<1>(key) == ns) inside.push_back(key);
    for (const Key& key : inside)
      if (objects_.count(key)) DeleteLocked(key);
  }

  void Append(const std::string& type, const Json& obj) {
    journal_.push_back(Event{++seq_, type, obj});
  }

  std::mutex mu_;
  std::map<Key, Json> objects_;
  std::deque<Event> journal_;
  Json last_removed_;
  int64_t rv_ = 0;
  int64_t seq_ = 0;
  uint64_t uid_counter_ = 0;
};

}  // namespace

extern "C" {

void* kftpu_store_new() { return new Store(); }
void kftpu_store_free(void* s) { delete static_cast<Store*>(s); }

const char* kftpu_store_create(void* s, const char* obj_json) {
  return static_cast<Store*>(s)->Create(obj_json);
}

const char* kftpu_store_get(void* s, const char* kind, const char* ns,
                            const char* name) {
  return static_cast<Store*>(s)->Get(kind, ns, name);
}

const char* kftpu_store_update(void* s, const char* obj_json,
                               int32_t status_only) {
  return static_cast<Store*>(s)->Update(obj_json, status_only != 0);
}

const char* kftpu_store_list(void* s, const char* kind, const char* ns,
                             const char* selector_json) {
  return static_cast<Store*>(s)->List(kind, ns, selector_json);
}

int32_t kftpu_store_delete(void* s, const char* kind, const char* ns,
                           const char* name) {
  return static_cast<Store*>(s)->Delete(kind, ns, name);
}

const char* kftpu_store_events(void* s, int64_t cursor,
                               int64_t* new_cursor) {
  return static_cast<Store*>(s)->Events(cursor, new_cursor);
}

void kftpu_store_trim(void* s, int64_t cursor) {
  static_cast<Store*>(s)->Trim(cursor);
}

int64_t kftpu_store_len(void* s) { return static_cast<Store*>(s)->Len(); }

int32_t kftpu_store_status() { return tls_status; }

const char* kftpu_store_error() { return tls_error.c_str(); }

}  // extern "C"
