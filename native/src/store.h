// Native API-object store — the storage core of the control plane.
//
// Implements the K8s resource-model semantics every controller's
// correctness depends on (the subset the reference leaned on envtest for,
// `profile-controller/controllers/suite_test.go:29-54`), compiled:
//
//   - optimistic concurrency (resourceVersion conflict on stale writes)
//   - spec vs status as separate update surfaces; generation bumps on
//     spec change only
//   - label-selector list
//   - finalizers: delete marks deletionTimestamp; removal happens when
//     the last finalizer is cleared
//   - owner references: cascading delete of dependents; namespace
//     deletion drains all namespaced objects
//   - a watch journal: every ADDED/MODIFIED/DELETED event is appended to
//     a cursor-addressable log that clients poll and trim
//
// Objects are whole JSON documents ({apiVersion, kind, metadata, spec,
// status}); the store introspects metadata itself (json.h). C ABI for
// ctypes. All functions are thread-safe.
//
// Result-buffer convention: calls returning `const char*` hand back a
// pointer to a thread-local buffer valid until the SAME thread's next
// store call — callers must copy (ctypes' c_char_p restype does).
// NULL means error; fetch the code/message with kftpu_store_status /
// kftpu_store_error (also thread-local).

#pragma once
#include <cstdint>

extern "C" {

// Status codes (kftpu_store_status after a NULL/negative return).
enum kftpu_store_code {
  KFTPU_STORE_OK = 0,
  KFTPU_STORE_NOT_FOUND = -1,
  KFTPU_STORE_ALREADY_EXISTS = -2,
  KFTPU_STORE_CONFLICT = -3,
  KFTPU_STORE_BAD_OBJECT = -4,  // malformed JSON / missing kind or name
};

void* kftpu_store_new();
void kftpu_store_free(void* s);

// Create; fills uid/resourceVersion/generation/creationTimestamp.
// Returns the stored object.
const char* kftpu_store_create(void* s, const char* obj_json);

// Get one object.
const char* kftpu_store_get(void* s, const char* kind, const char* ns,
                            const char* name);

// Update. status_only=1 replaces only .status; otherwise replaces spec
// (generation++ when it changed), labels, annotations, finalizers and
// ownerReferences. An incoming nonzero metadata.resourceVersion must
// match the stored one. Returns the stored object.
const char* kftpu_store_update(void* s, const char* obj_json,
                               int32_t status_only);

// List as a JSON array, sorted by (kind, ns, name). ns=NULL or "" lists
// all namespaces. selector_json is a {"label": "value", ...} object
// (NULL/empty = no filter); all pairs must match.
const char* kftpu_store_list(void* s, const char* kind, const char* ns,
                             const char* selector_json);

// Delete (finalizer-aware, cascading). Returns KFTPU_STORE_OK or a code.
int32_t kftpu_store_delete(void* s, const char* kind, const char* ns,
                           const char* name);

// Watch journal: JSON array [{"seq": N, "type": "ADDED", "object": {...}},
// ...] of events with seq > cursor; *new_cursor is set to the last seq
// returned (or cursor when none).
const char* kftpu_store_events(void* s, int64_t cursor,
                               int64_t* new_cursor);

// Drop journal entries with seq <= cursor (consumed by all pollers).
void kftpu_store_trim(void* s, int64_t cursor);

// Object count (all kinds).
int64_t kftpu_store_len(void* s);

// Thread-local status/message for the calling thread's last store call.
int32_t kftpu_store_status();
const char* kftpu_store_error();

}  // extern "C"
