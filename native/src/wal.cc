#include "wal.h"

#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>
#include <mutex>
#include <string>

namespace {

thread_local std::string tls_error;
thread_local std::string tls_result;

const char* Ok(std::string result) {
  tls_error.clear();
  tls_result = std::move(result);
  return tls_result.c_str();
}

int32_t IoErr(const std::string& what) {
  tls_error = what + ": " + std::strerror(errno);
  return -1;
}

// write(2) until done (short writes are legal on regular files under
// signal interruption; loop rather than corrupt a record).
bool WriteAll(int fd, const char* data, size_t len) {
  while (len > 0) {
    ssize_t n = ::write(fd, data, len);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    data += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool ReadFile(const std::string& path, std::string* out) {
  out->clear();
  int fd = ::open(path.c_str(), O_RDONLY);
  if (fd < 0) return errno == ENOENT;  // absent = empty, not an error
  char buf[1 << 16];
  for (;;) {
    ssize_t n = ::read(fd, buf, sizeof(buf));
    if (n < 0) {
      if (errno == EINTR) continue;
      ::close(fd);
      return false;
    }
    if (n == 0) break;
    out->append(buf, static_cast<size_t>(n));
  }
  ::close(fd);
  return true;
}

class Wal {
 public:
  explicit Wal(std::string dir) : dir_(std::move(dir)) {}

  ~Wal() {
    if (wal_fd_ >= 0) ::close(wal_fd_);
    if (dir_fd_ >= 0) ::close(dir_fd_);
  }

  bool Open() {
    if (::mkdir(dir_.c_str(), 0700) != 0 && errno != EEXIST) {
      IoErr("mkdir " + dir_);
      return false;
    }
    dir_fd_ = ::open(dir_.c_str(), O_RDONLY | O_DIRECTORY);
    if (dir_fd_ < 0) {
      IoErr("open dir " + dir_);
      return false;
    }
    wal_fd_ = ::open(WalPath().c_str(), O_WRONLY | O_APPEND | O_CREAT, 0600);
    if (wal_fd_ < 0) {
      IoErr("open " + WalPath());
      return false;
    }
    // Make the wal.log DIRENT durable now: fdatasync on appends makes
    // the file's data durable, but a file created and never dir-fsynced
    // can vanish wholesale on crash — losing every acked pre-snapshot
    // write at once.
    if (::fsync(dir_fd_) != 0) {
      IoErr("fsync dir " + dir_);
      return false;
    }
    return true;
  }

  int32_t Append(const char* line) {
    std::lock_guard<std::mutex> lock(mu_);
    std::string rec = line ? line : "";
    rec.push_back('\n');
    if (!WriteAll(wal_fd_, rec.data(), rec.size()))
      return IoErr("append " + WalPath());
    if (::fdatasync(wal_fd_) != 0) return IoErr("fdatasync " + WalPath());
    return 0;
  }

  int32_t Snapshot(const char* snapshot_json) {
    std::lock_guard<std::mutex> lock(mu_);
    const std::string tmp = dir_ + "/snapshot.json.tmp";
    int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0600);
    if (fd < 0) return IoErr("open " + tmp);
    const char* data = snapshot_json ? snapshot_json : "";
    if (!WriteAll(fd, data, std::strlen(data))) {
      ::close(fd);
      return IoErr("write " + tmp);
    }
    if (::fsync(fd) != 0) {
      ::close(fd);
      return IoErr("fsync " + tmp);
    }
    ::close(fd);
    if (::rename(tmp.c_str(), SnapPath().c_str()) != 0)
      return IoErr("rename " + tmp);
    if (::fsync(dir_fd_) != 0) return IoErr("fsync dir " + dir_);
    // Snapshot is durable; now the WAL may shrink. A crash before this
    // point leaves pre-snapshot records in the WAL — harmless, the
    // reader skips records at-or-below the snapshot rv.
    int fresh = ::open(WalPath().c_str(),
                       O_WRONLY | O_APPEND | O_CREAT | O_TRUNC, 0600);
    if (fresh < 0) return IoErr("truncate " + WalPath());
    ::close(wal_fd_);
    wal_fd_ = fresh;
    if (::fsync(dir_fd_) != 0) return IoErr("fsync dir " + dir_);
    return 0;
  }

  const char* ReadSnapshot() {
    std::lock_guard<std::mutex> lock(mu_);
    std::string out;
    if (!ReadFile(SnapPath(), &out)) {
      IoErr("read " + SnapPath());
      return nullptr;
    }
    return Ok(std::move(out));
  }

  const char* ReadJournal() {
    std::lock_guard<std::mutex> lock(mu_);
    std::string out;
    if (!ReadFile(WalPath(), &out)) {
      IoErr("read " + WalPath());
      return nullptr;
    }
    return Ok(std::move(out));
  }

 private:
  std::string WalPath() const { return dir_ + "/wal.log"; }
  std::string SnapPath() const { return dir_ + "/snapshot.json"; }

  std::string dir_;
  std::mutex mu_;
  int wal_fd_ = -1;
  int dir_fd_ = -1;
};

}  // namespace

extern "C" {

void* kftpu_wal_open(const char* dir) {
  auto* w = new Wal(dir ? dir : "");
  if (!w->Open()) {
    delete w;
    return nullptr;
  }
  return w;
}

void kftpu_wal_free(void* w) { delete static_cast<Wal*>(w); }

int32_t kftpu_wal_append(void* w, const char* line) {
  return static_cast<Wal*>(w)->Append(line);
}

int32_t kftpu_wal_snapshot(void* w, const char* snapshot_json) {
  return static_cast<Wal*>(w)->Snapshot(snapshot_json);
}

const char* kftpu_wal_read_snapshot(void* w) {
  return static_cast<Wal*>(w)->ReadSnapshot();
}

const char* kftpu_wal_read_journal(void* w) {
  return static_cast<Wal*>(w)->ReadJournal();
}

const char* kftpu_wal_error() { return tls_error.c_str(); }

}  // extern "C"
