// Durable write-ahead log + snapshot for the control-plane store.
//
// The reference control plane rides etcd for durability (its envtest
// fixture spins a real etcd+apiserver even for unit tests,
// `profile-controller/controllers/suite_test.go:29-54`); this module is
// the compiled persistence tier our apiserver stores through instead:
//
//   <dir>/snapshot.json   full state, written atomically (tmp+rename)
//   <dir>/wal.log         one JSON record per committed write, fsync'd
//
// Crash-safety contract:
//   - append() returns only after the record is fdatasync'd.
//   - snapshot() writes tmp, fsyncs, renames over snapshot.json, fsyncs
//     the directory, and only THEN truncates the WAL. A crash between
//     rename and truncate leaves pre-snapshot records in the WAL; the
//     reader must skip records at-or-below the snapshot's rv (records
//     carry their rv for exactly this reason).
//   - a torn final record (crash mid-append) is the reader's problem:
//     stop replay at the first undecodable line.
//
// C ABI for ctypes. Calls returning const char* use the store result
// convention (thread-local buffer, valid until the same thread's next
// wal call; NULL = error, message via kftpu_wal_error).

#pragma once
#include <cstdint>

extern "C" {

// Opens (creating if needed) the log directory. NULL on error.
void* kftpu_wal_open(const char* dir);
void kftpu_wal_free(void* w);

// Append one record line (no trailing newline needed) and fdatasync.
// Returns 0 on success, -1 on IO error.
int32_t kftpu_wal_append(void* w, const char* line);

// Atomically replace the snapshot with `snapshot_json`, then truncate
// the WAL. Returns 0 on success, -1 on IO error.
int32_t kftpu_wal_snapshot(void* w, const char* snapshot_json);

// Full contents of snapshot.json ("" when none exists yet).
const char* kftpu_wal_read_snapshot(void* w);

// Full contents of wal.log ("" when empty/absent), newline-separated.
const char* kftpu_wal_read_journal(void* w);

// Message for the calling thread's last failed wal call.
const char* kftpu_wal_error();

}  // extern "C"
