#include "workqueue.h"

#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <map>
#include <mutex>
#include <queue>
#include <set>
#include <string>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;
using Ms = std::chrono::milliseconds;

struct Entry {
  Clock::time_point ready;
  uint64_t seq;  // FIFO tiebreak among equally-ready keys
  std::string key;
  bool operator>(const Entry& o) const {
    return ready != o.ready ? ready > o.ready : seq > o.seq;
  }
};

class WorkQueue {
 public:
  WorkQueue(int64_t base_ms, int64_t max_ms)
      : base_(Ms(base_ms)), max_(Ms(max_ms)) {}

  void Add(const std::string& key, Ms delay) {
    std::lock_guard<std::mutex> lock(mu_);
    if (down_) return;
    if (inflight_.count(key)) {
      dirty_.insert(key);  // re-queue on Done()
      return;
    }
    Clock::time_point ready = Clock::now() + delay;
    auto it = queued_.find(key);
    if (it != queued_.end() && it->second <= ready) return;  // sooner wins
    queued_[key] = ready;
    heap_.push(Entry{ready, seq_++, key});
    cv_.notify_all();
  }

  // 1 = got key, 0 = timeout/shutdown, -2 = buffer too small.
  int32_t Get(char* out, int32_t out_len, Ms timeout) {
    std::unique_lock<std::mutex> lock(mu_);
    Clock::time_point deadline = Clock::now() + timeout;
    while (true) {
      if (down_) return 0;
      PruneStale();
      if (!heap_.empty()) {
        const Entry& top = heap_.top();
        Clock::time_point now = Clock::now();
        if (top.ready <= now) {
          if (static_cast<int32_t>(top.key.size()) + 1 > out_len) return -2;
          std::string key = top.key;
          heap_.pop();
          queued_.erase(key);
          inflight_.insert(key);
          std::memcpy(out, key.c_str(), key.size() + 1);
          return 1;
        }
        // Sleep until the earliest entry matures or the deadline.
        Clock::time_point until = std::min(top.ready, deadline);
        if (until <= now) return 0;
        cv_.wait_until(lock, until);
      } else {
        if (timeout.count() == 0 || Clock::now() >= deadline) return 0;
        cv_.wait_until(lock, deadline);
      }
    }
  }

  void Done(const std::string& key) {
    std::lock_guard<std::mutex> lock(mu_);
    inflight_.erase(key);
    if (dirty_.erase(key) && !down_) {
      Clock::time_point ready = Clock::now();
      auto it = queued_.find(key);
      if (it == queued_.end() || it->second > ready) {
        queued_[key] = ready;
        heap_.push(Entry{ready, seq_++, key});
        cv_.notify_all();
      }
    }
  }

  int64_t RequeueError(const std::string& key) {
    Ms backoff;
    {
      std::lock_guard<std::mutex> lock(mu_);
      int n = ++failures_[key];
      int shift = std::min(n - 1, 30);
      auto raw = base_.count() << shift;
      backoff = Ms(std::min<int64_t>(raw, max_.count()));
    }
    // Schedule the retry; bypass the in-flight dirty path so the backoff
    // applies even though the key is currently being processed: record it
    // as queued directly.
    std::lock_guard<std::mutex> lock(mu_);
    if (down_) return backoff.count();
    Clock::time_point ready = Clock::now() + backoff;
    auto it = queued_.find(key);
    if (it == queued_.end() || it->second > ready) {
      queued_[key] = ready;
      heap_.push(Entry{ready, seq_++, key});
      cv_.notify_all();
    }
    dirty_.erase(key);  // the scheduled retry covers any dirty re-add
    return backoff.count();
  }

  void Forget(const std::string& key) {
    std::lock_guard<std::mutex> lock(mu_);
    failures_.erase(key);
  }

  int64_t Len() {
    std::lock_guard<std::mutex> lock(mu_);
    return static_cast<int64_t>(queued_.size());
  }

  int64_t NextReadyMs() {
    std::lock_guard<std::mutex> lock(mu_);
    PruneStale();
    if (heap_.empty()) return -1;
    auto delta = std::chrono::duration_cast<Ms>(heap_.top().ready -
                                                Clock::now())
                     .count();
    return delta < 0 ? 0 : delta;
  }

  void Shutdown() {
    std::lock_guard<std::mutex> lock(mu_);
    down_ = true;
    cv_.notify_all();
  }

 private:
  // Drop heap entries superseded by a sooner re-add (their (key, ready)
  // no longer matches queued_). Caller holds mu_.
  void PruneStale() {
    while (!heap_.empty()) {
      const Entry& top = heap_.top();
      auto it = queued_.find(top.key);
      if (it != queued_.end() && it->second == top.ready) return;
      heap_.pop();
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::priority_queue<Entry, std::vector<Entry>, std::greater<Entry>> heap_;
  std::map<std::string, Clock::time_point> queued_;
  std::set<std::string> inflight_;
  std::set<std::string> dirty_;
  std::map<std::string, int> failures_;
  Ms base_, max_;
  uint64_t seq_ = 0;
  bool down_ = false;
};

}  // namespace

extern "C" {

void* kftpu_wq_new(int64_t base_backoff_ms, int64_t max_backoff_ms) {
  if (base_backoff_ms < 1) base_backoff_ms = 1;
  if (max_backoff_ms < base_backoff_ms) max_backoff_ms = base_backoff_ms;
  return new WorkQueue(base_backoff_ms, max_backoff_ms);
}

void kftpu_wq_free(void* q) { delete static_cast<WorkQueue*>(q); }

void kftpu_wq_add(void* q, const char* key) {
  static_cast<WorkQueue*>(q)->Add(key, Ms(0));
}

void kftpu_wq_add_after(void* q, const char* key, int64_t delay_ms) {
  static_cast<WorkQueue*>(q)->Add(key, Ms(delay_ms < 0 ? 0 : delay_ms));
}

int32_t kftpu_wq_get(void* q, char* out, int32_t out_len,
                     int64_t timeout_ms) {
  return static_cast<WorkQueue*>(q)->Get(out, out_len,
                                         Ms(timeout_ms < 0 ? 0 : timeout_ms));
}

void kftpu_wq_done(void* q, const char* key) {
  static_cast<WorkQueue*>(q)->Done(key);
}

int64_t kftpu_wq_requeue_error(void* q, const char* key) {
  return static_cast<WorkQueue*>(q)->RequeueError(key);
}

void kftpu_wq_forget(void* q, const char* key) {
  static_cast<WorkQueue*>(q)->Forget(key);
}

int64_t kftpu_wq_len(void* q) { return static_cast<WorkQueue*>(q)->Len(); }

int64_t kftpu_wq_next_ready_ms(void* q) {
  return static_cast<WorkQueue*>(q)->NextReadyMs();
}

void kftpu_wq_shutdown(void* q) { static_cast<WorkQueue*>(q)->Shutdown(); }

}  // extern "C"
