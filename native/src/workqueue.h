// Rate-limited delaying workqueue — the reconcile engine's heart.
//
// The reference's controllers all ride client-go's workqueue (a Go
// component; e.g. `notebook_controller.go:82` via controller-runtime).
// This is the platform's compiled equivalent: keyed dedup, delayed adds
// with supersede-by-sooner semantics, per-key exponential error backoff,
// and a blocking Get so worker threads (Python, via ctypes — which
// releases the GIL during the call) park in native code.
//
// C ABI for ctypes consumption. All functions are thread-safe.

#pragma once
#include <cstdint>

extern "C" {

// max_backoff_ms bounds the per-key exponential error backoff;
// base_backoff_ms is the first retry's delay.
void* kftpu_wq_new(int64_t base_backoff_ms, int64_t max_backoff_ms);
void kftpu_wq_free(void* q);

// Enqueue key for immediate processing. A key already queued sooner-or-
// equal is left alone; a later-scheduled pending entry is superseded
// (a fresh watch event must not wait out an old error backoff).
void kftpu_wq_add(void* q, const char* key);

// Enqueue key to become ready after delay_ms (same supersede semantics).
void kftpu_wq_add_after(void* q, const char* key, int64_t delay_ms);

// Block up to timeout_ms for a ready key; copy it into out (out_len incl.
// NUL). Returns:
//   1   a key was dequeued
//   0   timed out (or queue shut down) — out untouched
//  -2   out buffer too small (key left queued)
// timeout_ms == 0 polls without blocking: it returns a key only if one is
// ready now. A dequeued key is "in flight": re-adds while in flight are
// recorded and the key is re-queued when kftpu_wq_done is called (client-go
// dirty-set semantics — no lost wakeups, no concurrent reconciles of one
// key).
int32_t kftpu_wq_get(void* q, char* out, int32_t out_len,
                     int64_t timeout_ms);

// Mark an in-flight key finished; re-queues it if it was re-added while
// processing.
void kftpu_wq_done(void* q, const char* key);

// Record a reconcile failure: bumps the key's failure count and schedules
// a retry after the (exponential, capped) backoff. Returns the backoff ms
// used. Call INSTEAD of a plain add, then kftpu_wq_done.
int64_t kftpu_wq_requeue_error(void* q, const char* key);

// Clear a key's failure count (after a successful reconcile).
void kftpu_wq_forget(void* q, const char* key);

// Number of keys queued (ready or delayed), excluding in-flight.
int64_t kftpu_wq_len(void* q);

// Milliseconds until the earliest queued key becomes ready: 0 if one is
// ready now, -1 if the queue is empty.
int64_t kftpu_wq_next_ready_ms(void* q);

// Wake all blocked Gets (they return 0); subsequent Gets return 0
// immediately. Adds become no-ops.
void kftpu_wq_shutdown(void* q);

}  // extern "C"
