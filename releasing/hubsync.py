"""Registry sync — the `releasing/hubsync.py` analog: mirror released
image tags from the build registry to the public one. The copy operation
is injectable (gcloud/crane/skopeo in production; recorded calls in
tests)."""

from __future__ import annotations

import argparse
import logging
import pathlib
import subprocess
import sys
from typing import Callable

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from releasing.releaser import IMAGES  # noqa: E402

log = logging.getLogger(__name__)

# Derived from the release build list — the two stages must not drift.
DEFAULT_IMAGES = tuple(name for name, _, _ in IMAGES)


def default_copy(src: str, dst: str) -> None:
    subprocess.run(["crane", "copy", src, dst], check=True)


def sync(
    version: str,
    *,
    source: str,
    dest: str,
    images: tuple[str, ...] = DEFAULT_IMAGES,
    copy: Callable[[str, str], None] = default_copy,
) -> list[tuple[str, str]]:
    """Mirror every image:version from source to dest; returns the pairs
    copied. Failures propagate — a half-synced release must be loud."""
    copied = []
    for name in images:
        src = f"{source}/{name}:{version}"
        dst = f"{dest}/{name}:{version}"
        log.info("sync %s -> %s", src, dst)
        copy(src, dst)
        copied.append((src, dst))
    return copied


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="hubsync")
    parser.add_argument("--version", required=True)
    parser.add_argument("--source", default="gcr.io/kubeflow-tpu-images")
    parser.add_argument("--dest", default="docker.io/kubeflowtpu")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    pairs = sync(args.version, source=args.source, dest=args.dest)
    print(f"synced {len(pairs)} images")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
