"""Release workflows — the `releasing/releaser` analog.

The reference releases components through ksonnet Argo workflows
(`releasing/releaser/components/{centraldashboard,...}.jsonnet`): build
each image, run its tests, then push/tag. Here the same DAG is a
`Workflow` CR for the platform's workflow engine: build steps fan out per
image, the test gate depends on all builds, and tagging only happens
after the gate — with teardown of the build namespace in the exit
handler.

    python releasing/releaser.py --version v1.2.0   # print the CR
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from kubeflow_tpu.api.objects import Resource, new_resource  # noqa: E402
from kubeflow_tpu.api.workflow import KIND, StepSpec, WorkflowSpec  # noqa: E402

# Image build targets: (name, context dir, dockerfile).
IMAGES: tuple[tuple[str, str, str], ...] = (
    ("platform", ".", "images/platform/Dockerfile"),
    ("jax-notebook", "images/jax-notebook", "images/jax-notebook/Dockerfile"),
    # Dockerfile paths are cwd(repo-root)-relative: docker resolves -f
    # against the cwd, not the build context.
    (
        "kaggle-notebook",
        "images/contrib/kaggle-notebook",
        "images/contrib/kaggle-notebook/Dockerfile",
    ),
    (
        "datascience-notebook",
        "images/contrib/datascience-notebook",
        "images/contrib/datascience-notebook/Dockerfile",
    ),
)


def release_workflow(
    version: str,
    *,
    registry: str = "kubeflow-tpu",
    namespace: str = "kubeflow-releasing",
) -> Resource:
    build_steps = tuple(
        StepSpec(
            name=f"build-{name}",
            command=("docker", "build"),
            args=("-t", f"{registry}/{name}:{version}", "-f", dockerfile, ctx),
            retries=1,
        )
        for name, ctx, dockerfile in IMAGES
    )
    # Container-stable interpreter: this step runs in the ci-runner image,
    # not on the machine that rendered the CR.
    test_gate = StepSpec(
        name="test",
        command=("python", "-m", "pytest", "tests/", "-q"),
        dependencies=tuple(s.name for s in build_steps),
    )
    push_steps = tuple(
        StepSpec(
            name=f"push-{name}",
            command=("docker", "push"),
            args=(f"{registry}/{name}:{version}",),
            dependencies=(test_gate.name,),
            retries=2,
        )
        for name, _, _ in IMAGES
    )
    tag = StepSpec(
        name="tag-release",
        command=("git", "tag", "-a", version, "-m", f"release {version}"),
        dependencies=tuple(s.name for s in push_steps),
    )
    spec = WorkflowSpec(
        steps=build_steps + (test_gate,) + push_steps + (tag,),
        on_exit=StepSpec(
            name="cleanup",
            command=("docker", "system", "prune", "-f"),
        ),
    )
    return new_resource(
        KIND, f"release-{version}", namespace, spec=spec.to_dict()
    )


if __name__ == "__main__":
    import argparse

    import yaml

    parser = argparse.ArgumentParser()
    parser.add_argument("--version", required=True)
    parser.add_argument("--registry", default="kubeflow-tpu")
    args = parser.parse_args()
    print(
        yaml.safe_dump(
            release_workflow(args.version, registry=args.registry).to_dict(),
            sort_keys=True,
        ),
        end="",
    )
