#!/bin/bash
# Autoformat / static hygiene — the `scripts/autoformat_jsonnet.sh` +
# `run_gofmt.sh` analog: byte-compile every python source (syntax gate),
# normalize version-config JSON, and run the boilerplate checker.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q kubeflow_tpu tests releasing scripts

# Canonicalize the notebook version matrix (sorted keys, 2-space indent).
python - <<'EOF'
import json, pathlib
for p in pathlib.Path("images").rglob("version-config.json"):
    cfg = json.loads(p.read_text())
    p.write_text(json.dumps(cfg, indent=2, sort_keys=True) + "\n")
    print(f"formatted {p}")
EOF

python scripts/check_boilerplate.py --root kubeflow_tpu
echo "autoformat ok"
