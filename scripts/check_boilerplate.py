"""Source-file boilerplate checker — the `build/check_boilerplate.sh`
analog, as a portable script.

Policy for this repo: every Python source must open with a module
docstring (the codebase's documentation convention), and every shell
script with a `#`-comment block after the shebang. `--license <file>`
switches to the reference's mode: require the given header verbatim.

    python scripts/check_boilerplate.py [--root DIR] [--license FILE]
"""

from __future__ import annotations

import argparse
import ast
import pathlib
import sys

SKIP_DIRS = {".git", "__pycache__", "build", ".pytest_cache", "node_modules"}
SKIP_FILES = {"__init__.py", "__main__.py", "conftest.py"}


def iter_sources(root: pathlib.Path):
    for path in sorted(root.rglob("*")):
        if any(part in SKIP_DIRS for part in path.parts):
            continue
        if path.suffix in (".py", ".sh") and path.is_file():
            yield path


def has_docstring(path: pathlib.Path) -> bool:
    try:
        tree = ast.parse(path.read_text())
    except SyntaxError:
        return False
    return ast.get_docstring(tree) is not None


def has_comment_block(path: pathlib.Path) -> bool:
    lines = path.read_text().splitlines()
    for line in lines[:5]:
        stripped = line.strip()
        if stripped.startswith("#") and not stripped.startswith("#!"):
            return True
    return False


def check(root: pathlib.Path, license_text: str | None = None) -> list[str]:
    bad = []
    for path in iter_sources(root):
        if path.name in SKIP_FILES:
            continue
        if license_text is not None:
            ok = license_text in path.read_text()
        elif path.suffix == ".py":
            ok = has_docstring(path)
        else:
            ok = has_comment_block(path)
        if not ok:
            bad.append(str(path.relative_to(root)))
    return bad


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--root", default=".")
    parser.add_argument(
        "--license", help="require this header file's contents verbatim"
    )
    args = parser.parse_args(argv)
    license_text = (
        pathlib.Path(args.license).read_text() if args.license else None
    )
    bad = check(pathlib.Path(args.root).resolve(), license_text)
    if bad:
        print("files missing boilerplate:")
        for f in bad:
            print(f"  {f}")
        return 1
    print("boilerplate ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
