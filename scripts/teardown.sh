#!/bin/bash
# Platform teardown — the `scripts/gke/teardown.sh` analog: delete the
# deployed platform (and its TPU node pools) from a PlatformSpec file.
# Safe to re-run; delete is idempotent like second apply.
set -euo pipefail
cd "$(dirname "$0")/.."

SPEC="${1:?usage: teardown.sh <platform-spec.yaml>}"
python -m kubeflow_tpu.deploy delete -f "${SPEC}"
