"""Test fixtures: a virtual 8-device CPU mesh.

The reference tested distributed behavior only against a real GKE cluster
(SURVEY.md §4.3); the simulated multi-host fixture it lacked is this file.
Env vars must be set before jax is first imported, hence the assignments at
module import time (pytest imports conftest before test modules).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# jax may already be imported (the image's sitecustomize registers the TPU
# backend at interpreter startup), in which case the env var above came too
# late — force the platform through the config API as well.
jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    """2x2x2 mesh: dp=2, fsdp=2, tp=2 — exercises every collective family."""
    from kubeflow_tpu.parallel import MeshSpec, build_mesh

    return build_mesh(MeshSpec(dp=2, fsdp=2, tp=2), devices)


@pytest.fixture(scope="session")
def tls_paths(tmp_path_factory):
    """One platform CA + server cert for the whole test session: every
    secure-facade test serves HTTPS with these and pins the CA — bearer
    tokens never ride plaintext, mirroring the launcher's boot path."""
    from kubeflow_tpu.web import tls

    return tls.ensure_tls_dir(str(tmp_path_factory.mktemp("tls")))
