"""The durable apiserver as its own OS process — the kill target.

Boots FakeApiServer over a persistent state directory (WAL+snapshot,
`testing/persist.py`) behind the secure HTTP facade, so the restart e2e
can SIGKILL this process mid-gang and bring it back with state — the
property the reference's control plane inherits from etcd
(`profile-controller/controllers/suite_test.go:29-54` spins the real
thing even for unit tests).

Env contract:
    KFTPU_REPO        repo root (sys.path bootstrap)
    KFTPU_STATE_DIR   persistence directory (same across restarts)
    KFTPU_TOKEN_FILE  kube-style token,user CSV (same across restarts)
    KFTPU_PORT        fixed port (same across restarts, so clients and
                      watch streams reconnect without rediscovery)
    KFTPU_LOG_ROOT    optional pod-log containment root

Prints "apiserver ready <port>" once serving. First boot (empty store)
seeds the RBAC roles + a system:admin binding; on restart they are
restored from disk — the e2e asserts that, so don't reseed.
"""

import faulthandler
import os
import signal
import sys

sys.path.insert(0, os.environ["KFTPU_REPO"])

# Diagnostics for a hung shutdown: SIGUSR1 dumps every thread's stack to
# stderr (the e2e sends it before killing a worker that missed its
# SIGTERM deadline, so the captured output names the stuck frame).
faulthandler.register(signal.SIGUSR1)

from kubeflow_tpu.api.rbac import (  # noqa: E402
    make_cluster_role_binding,
    seed_cluster_roles,
)
from kubeflow_tpu.api.tokens import TokenRegistry  # noqa: E402
from kubeflow_tpu.testing.apiserver_http import ApiServerApp  # noqa: E402
from kubeflow_tpu.testing.fake_apiserver import FakeApiServer  # noqa: E402
from kubeflow_tpu.web.wsgi import serve  # noqa: E402


def main() -> None:
    api = FakeApiServer(
        persist_dir=os.path.join(os.environ["KFTPU_STATE_DIR"], "store")
    )
    tokens = TokenRegistry.load(os.environ["KFTPU_TOKEN_FILE"])
    tokens.autosave(os.environ["KFTPU_TOKEN_FILE"])
    tokens.watch_profiles(api)
    if api.current_rv == 0:
        seed_cluster_roles(api)
        api.create(
            make_cluster_role_binding(
                "boot-admin", "kubeflow-admin", "system:admin"
            )
        )
    app = ApiServerApp(
        api, tokens=tokens, log_root=os.environ.get("KFTPU_LOG_ROOT")
    )
    # TLS rides the state dir: a restart reuses the SAME CA, so clients
    # that pinned it reconnect without re-trusting anything.
    from kubeflow_tpu.web import tls

    paths = tls.ensure_tls_dir(
        os.path.join(os.environ["KFTPU_STATE_DIR"], "tls")
    )
    server, _ = serve(
        app,
        host="127.0.0.1",
        port=int(os.environ["KFTPU_PORT"]),
        tls=paths,
    )
    print(f"apiserver ready {server.server_port}", flush=True)
    from kubeflow_tpu.utils import signals as sigutil

    # Poll-not-park graceful stop (utils/signals.py has the rationale —
    # this worker's hang is the reproduction that motivated it).
    sigutil.wait_for_shutdown(sigutil.install_shutdown_handlers())
    # Stage markers: if shutdown wedges, the captured stdout shows how
    # far it got (paired with the SIGUSR1 stack dump above).
    print("shutting down: server", flush=True)
    server.shutdown()
    print("shutting down: store", flush=True)
    api.close()  # graceful path folds the WAL into a snapshot
    print("shutdown complete", flush=True)


if __name__ == "__main__":
    main()
