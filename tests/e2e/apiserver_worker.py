"""The durable apiserver as its own OS process — the kill target.

Boots FakeApiServer over a persistent state directory (WAL+snapshot,
`testing/persist.py`) behind the secure HTTP facade, so the restart e2e
can SIGKILL this process mid-gang and bring it back with state — the
property the reference's control plane inherits from etcd
(`profile-controller/controllers/suite_test.go:29-54` spins the real
thing even for unit tests).

Env contract:
    KFTPU_REPO        repo root (sys.path bootstrap)
    KFTPU_STATE_DIR   persistence directory (same across restarts)
    KFTPU_TOKEN_FILE  kube-style token,user CSV (same across restarts)
    KFTPU_PORT        fixed port (same across restarts, so clients and
                      watch streams reconnect without rediscovery)
    KFTPU_LOG_ROOT    optional pod-log containment root

Prints "apiserver ready <port>" once serving. First boot (empty store)
seeds the RBAC roles + a system:admin binding; on restart they are
restored from disk — the e2e asserts that, so don't reseed.

HA mode (`testing/failover.py`) — set KFTPU_HA_IDENTITY and run N
copies over the SAME state dir (each with its own KFTPU_PORT):

    KFTPU_HA_IDENTITY     this replica's identity; presence enables HA
    KFTPU_LEASE_DURATION  apiserver lease TTL seconds (default 3)
    KFTPU_RENEW_DEADLINE  default 2

The replica prints "standby <identity>", parks in the lease acquire
loop serving NOTHING (it does not even bind its port), and on winning
the lease performs the takeover — replay WAL, checkpoint (rotating the
log inode out from under any deposed predecessor), serve fenced to this
term — printing "apiserver ready <port>" once serving and then
"leading <identity>" (wait for THAT marker: it is the last boot line).
On leadership loss it exits 2 WITHOUT closing the store (a deposed
active checkpointing would be exactly the late write fencing exists to
stop); the supervisor restarts it as a fresh standby.
"""

import faulthandler
import os
import signal
import sys

sys.path.insert(0, os.environ["KFTPU_REPO"])

# Diagnostics for a hung shutdown: SIGUSR1 dumps every thread's stack to
# stderr (the e2e sends it before killing a worker that missed its
# SIGTERM deadline, so the captured output names the stuck frame).
faulthandler.register(signal.SIGUSR1)

from kubeflow_tpu.api.rbac import (  # noqa: E402
    make_cluster_role_binding,
    seed_cluster_roles,
)
from kubeflow_tpu.api.tokens import TokenRegistry  # noqa: E402
from kubeflow_tpu.testing.apiserver_http import ApiServerApp  # noqa: E402
from kubeflow_tpu.testing.fake_apiserver import FakeApiServer  # noqa: E402
from kubeflow_tpu.web.wsgi import serve  # noqa: E402


def _serve(api):
    """Token registry + secure facade + TLS server on KFTPU_PORT;
    returns the serving `_HttpServer`."""
    tokens = TokenRegistry.load(os.environ["KFTPU_TOKEN_FILE"])
    tokens.autosave(os.environ["KFTPU_TOKEN_FILE"])
    tokens.watch_profiles(api)
    if api.current_rv == 0:
        seed_cluster_roles(api)
        api.create(
            make_cluster_role_binding(
                "boot-admin", "kubeflow-admin", "system:admin"
            )
        )
    app = ApiServerApp(
        api, tokens=tokens, log_root=os.environ.get("KFTPU_LOG_ROOT")
    )
    # TLS rides the state dir: a restart (or the standby of an HA pair)
    # reuses the SAME CA, so clients that pinned it reconnect without
    # re-trusting anything. KFTPU_TLS=0 serves plaintext instead —
    # loopback-only rigs (clients then need KFTPU_ALLOW_PLAINTEXT=1),
    # and the only option where the TLS toolchain is absent.
    paths = None
    if os.environ.get("KFTPU_TLS", "1") != "0":
        from kubeflow_tpu.web import tls

        paths = tls.ensure_tls_dir(
            os.path.join(os.environ["KFTPU_STATE_DIR"], "tls")
        )
    server, _ = serve(
        app,
        host="127.0.0.1",
        port=int(os.environ["KFTPU_PORT"]),
        tls=paths,
    )
    print(f"apiserver ready {server.server_port}", flush=True)
    return server


def _shutdown(server, api) -> None:
    # Stage markers: if shutdown wedges, the captured stdout shows how
    # far it got (paired with the SIGUSR1 stack dump above).
    print("shutting down: server", flush=True)
    server.shutdown()
    print("shutting down: store", flush=True)
    api.close()  # graceful path folds the WAL into a snapshot
    print("shutdown complete", flush=True)


def main() -> None:
    from kubeflow_tpu.utils import signals as sigutil

    store_dir = os.path.join(os.environ["KFTPU_STATE_DIR"], "store")
    identity = os.environ.get("KFTPU_HA_IDENTITY")
    # Poll-not-park graceful stop (utils/signals.py has the rationale —
    # this worker's hang is the reproduction that motivated it).
    stop = sigutil.install_shutdown_handlers()

    if identity is None:
        api = FakeApiServer(persist_dir=store_dir)
        server = _serve(api)
        sigutil.wait_for_shutdown(stop)
        _shutdown(server, api)
        return

    # -- HA mode: standby until the apiserver lease is won ----------------
    from kubeflow_tpu.controllers.leader import LeaderElector
    from kubeflow_tpu.testing.failover import (
        FileLeaseStore,
        open_active_store,
    )

    leases = FileLeaseStore(
        os.path.join(os.environ["KFTPU_STATE_DIR"], "lease")
    )
    elector = LeaderElector(
        leases,
        "apiserver",
        identity,
        lease_duration=float(os.environ.get("KFTPU_LEASE_DURATION", "3")),
        renew_deadline=float(os.environ.get("KFTPU_RENEW_DEADLINE", "2")),
        retry_period=0.25,
    )
    print(f"standby {identity}", flush=True)
    if not elector.acquire(stop):
        return  # stopped while parked; never served, nothing to clean
    api = open_active_store(
        store_dir, leases, "apiserver", identity, elector.transitions
    )
    server = _serve(api)
    print(f"leading {identity} gen {elector.transitions}", flush=True)
    elector.hold(stop)  # renew until stop or loss
    if not stop.is_set():
        # Deposed: the fenced store is (or is about to be) fail-stopped;
        # closing it would checkpoint into the successor's term. Exit
        # hard and let the supervisor restart a fresh standby —
        # client-go's RunOrDie posture, same as the controller workers.
        print(f"deposed {identity}", flush=True)
        server.shutdown()
        sys.exit(2)
    from kubeflow_tpu.testing.failover import WalFenced
    from kubeflow_tpu.testing.fake_apiserver import Unavailable

    try:
        _shutdown(server, api)
    except (WalFenced, Unavailable):
        # SIGTERM raced deposition: hold() returned for the stop flag,
        # but the term had already moved, so the close-path checkpoint
        # hit the WAL fence (WalFenced → fail-stop → Unavailable out of
        # close()). That is the fence WORKING — take the deposed exit,
        # not a traceback, and leave the lease to the successor. Only
        # the fence's exceptions qualify: a disk-full OSError or a
        # shutdown bug must still surface as the failure it is.
        print(f"deposed {identity}", flush=True)
        sys.exit(2)
    # Checkpoint done (it needed the still-held term) — NOW hand the
    # lease over so the standby acquires on its next poll instead of
    # waiting out the TTL (client-go's ReleaseOnCancel).
    elector.release()


if __name__ == "__main__":
    main()
