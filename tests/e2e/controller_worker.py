"""Out-of-process TpuJob operator: the controller binary.

Runs the exact same TpuJobController the in-process tests use, but over
the HTTP apiserver facade's watch stream — the distributed-control-plane
topology the reference runs in production (controller pod ↔ apiserver,
`notebook_controller.go:516` SetupWithManager watches). The only loop in
this process is the workqueue's blocking get: every reconcile is caused
by a watch event (or a reconcile-requested timed requeue), never by list
polling.
"""

import os
import signal
import sys
import threading

sys.path.insert(0, os.environ["KFTPU_REPO"])

from kubeflow_tpu.controllers.tpujob import TpuJobController  # noqa: E402
from kubeflow_tpu.testing.apiserver_http import (  # noqa: E402
    HttpApiClient,
    endpoints_from_env,
)


def main() -> None:
    client = HttpApiClient(
        endpoints_from_env(os.environ["KFTPU_APISERVER"]),
        watch_poll_timeout=2.0,
        watch_retry=0.1,
    )
    ctl = TpuJobController(client)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    print("controller ready", flush=True)
    ctl.controller.run(stop)
    client.close()


if __name__ == "__main__":
    main()
