"""Curve-reporting trial worker for the early-stopping E2E.

Simulates training: reports a per-step loss curve via
launcher.report_metrics over the HTTP apiserver facade. A diverging
configuration (--lr >= 1.0) reports exploding losses and then blocks
"training" far longer than the test budget — only an external prune
(Study controller deletes the trial, pod runner kills this process) ends
it. Healthy configurations converge and report a final observation.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.environ["KFTPU_REPO"])

from kubeflow_tpu.launcher.launcher import (  # noqa: E402
    report_metrics,
    report_observation,
)
from kubeflow_tpu.testing.apiserver_http import (  # noqa: E402
    HttpApiClient,
    endpoints_from_env,
)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--lr", type=float, required=True)
    args = parser.parse_args()

    api = HttpApiClient(endpoints_from_env(os.environ["KFTPU_APISERVER"]))
    job = os.environ["TPUJOB_NAME"]
    ns = os.environ["TPUJOB_NAMESPACE"]
    diverges = args.lr >= 1.0

    for step in range(1, 4):
        loss = (
            10.0 ** step if diverges
            else (args.lr - 0.05) ** 2 + 1.0 / step
        )
        report_metrics(api, job, ns, step, {"loss": loss})
        time.sleep(0.3)

    if diverges:
        # "Training" that would never finish inside the test budget: the
        # prune must kill us. Exiting 0 here would mask a missing prune.
        time.sleep(600)
        return

    report_observation(api, job, ns, {"loss": (args.lr - 0.05) ** 2})


if __name__ == "__main__":
    main()
