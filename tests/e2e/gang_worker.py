"""Worker script for the E2E gang test: joins the gang via the TPUJOB_*
contract, runs a cross-process psum on a dp mesh, verifies it, exits 0.

(The payload of SURVEY.md §7.2's minimum slice, shrunk to a collective —
ResNet training through the same path is covered on-mesh elsewhere.)
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# Don't inherit the parent test harness's virtual-device flags: each gang
# member is one process with its own (single) local device.
os.environ["XLA_FLAGS"] = ""
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

sys.path.insert(0, os.environ["KFTPU_REPO"])

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel import MeshSpec, build_mesh, initialize_from_env


def main() -> int:
    pe = initialize_from_env()
    assert jax.process_count() == pe.num_processes, (
        jax.process_count(), pe.num_processes,
    )
    mesh = build_mesh(MeshSpec(dp=-1))
    arr = jax.make_array_from_callback(
        (jax.device_count(),),
        NamedSharding(mesh, P("dp")),
        lambda idx: jnp.ones((1,)) * (pe.process_id + 1),
    )
    total = float(
        jax.jit(lambda x: x.sum(), out_shardings=NamedSharding(mesh, P()))(arr)
    )
    expected = sum(range(1, pe.num_processes + 1))
    assert total == expected, (total, expected)
    print(f"rank {pe.process_id}: psum ok ({total})", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
