"""Leader-elected controller replica: the HA controller binary.

Run N copies of this worker against one facade and exactly one
reconciles at a time (the others are hot standbys parked in the lease
acquire loop) — the `-enable-leader-election` deployment shape every
reference controller ships (`notebook-controller/main.go:51-62`). On
acquiring the lease the worker arms the client's lease guard, so if it
is ever deposed mid-write (partition, GC pause) the write is fenced
server-side instead of landing in the successor's term.

Reconciles `HAJob` CRs: ensure one labeled child Pod exists (generated
name — the duplicate-detection surface: two concurrently-active
replicas would both list-empty-then-create, yielding two pods), then
mark status.phase=Done with the worker's identity. KFTPU_RECONCILE_DELAY
widens the read→write window so the e2e can SIGKILL mid-reconcile.
"""

import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.environ["KFTPU_REPO"])

from kubeflow_tpu.api.objects import new_resource  # noqa: E402
from kubeflow_tpu.controllers.leader import LeaderElector  # noqa: E402
from kubeflow_tpu.controllers.runtime import Controller, Result  # noqa: E402
from kubeflow_tpu.testing.apiserver_http import (  # noqa: E402
    HttpApiClient,
    endpoints_from_env,
)
from kubeflow_tpu.testing.fake_apiserver import (  # noqa: E402
    Conflict,
    NotFound,
)

IDENTITY = os.environ["KFTPU_IDENTITY"]
DELAY = float(os.environ.get("KFTPU_RECONCILE_DELAY", "0"))


def reconcile(capi, key):
    ns, name = key
    try:
        job = capi.get("HAJob", name, ns)
    except NotFound:
        return Result()
    if job.status.get("phase") == "Done":
        return Result()
    if DELAY:
        time.sleep(DELAY)  # the SIGKILL-mid-reconcile window
    pods = capi.list("Pod", namespace=ns, label_selector={"hajob": name})
    if not pods:
        pod = new_resource(
            "Pod", f"{name}-{os.urandom(4).hex()}", ns,
            spec={"containers": [{"name": "w"}], "createdBy": IDENTITY},
        )
        pod.metadata.labels["hajob"] = name
        capi.create(pod)
    fresh = capi.get("HAJob", name, ns)
    fresh.status["phase"] = "Done"
    fresh.status["by"] = IDENTITY
    capi.update_status(fresh)
    return Result()


def main() -> None:
    client = HttpApiClient(
        endpoints_from_env(os.environ["KFTPU_APISERVER"]),
        watch_poll_timeout=2.0,
        watch_retry=0.1,
    )
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    elector = LeaderElector(
        client,
        "hajob-controller",
        IDENTITY,
        lease_duration=float(os.environ.get("KFTPU_LEASE_DURATION", "3")),
        renew_deadline=float(os.environ.get("KFTPU_RENEW_DEADLINE", "2")),
        retry_period=0.25,
    )
    print(f"standby {IDENTITY}", flush=True)

    def start_leading(el):
        # Fencing armed BEFORE the first reconcile: every write this
        # term makes carries (lease, holder, generation).
        client.set_lease_guard(el.guard)
        print(f"leading {IDENTITY} gen {el.transitions}", flush=True)
        ctl = Controller(client, "HAJob", reconcile, name="hajob-controller")
        t = threading.Thread(
            target=ctl.run, args=(stop,), daemon=True
        )
        t.start()

    try:
        lost = elector.run(stop, start_leading)
    except Conflict:
        lost = True
    if lost:
        # Deposed: the only safe continuation is none (client-go's
        # RunOrDie posture). The supervisor restarts us fresh.
        print(f"deposed {IDENTITY}", flush=True)
        sys.exit(2)
    client.close()


if __name__ == "__main__":
    main()
