"""Shared process driver for the HA e2es: a worker subprocess plus a
thread-draining stdout reader and marker waits.

One implementation because the failover and HA × preemption e2es both
supervise marker-printing replicas (a select+readline loop can strand
lines in the text-IO buffer; a reader thread can't), and the teardown
diagnostics (SIGUSR1 stack dump on a missed SIGTERM deadline) must not
drift between them.
"""

import os
import signal
import socket
import subprocess
import sys
import threading
import time


def free_port() -> int:
    """An OS-assigned free TCP port (bind-then-release; the winner must
    re-bind promptly — see the soak's SO_REUSEADDR retry loop for the
    restart-on-same-port case)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class MarkeredProc:
    """One supervised replica: Popen + stdout drain + marker waits."""

    def __init__(self, identity: str, argv: list[str], env: dict):
        self.identity = identity
        self.lines: list[str] = []
        self._cv = threading.Condition()
        self.proc = subprocess.Popen(
            argv,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        threading.Thread(target=self._drain, daemon=True).start()

    def _drain(self) -> None:
        for line in self.proc.stdout:
            with self._cv:
                self.lines.append(line.strip())
                self._cv.notify_all()

    def wait_marker(self, prefix: str, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        with self._cv:
            while not any(ln.startswith(prefix) for ln in self.lines):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise AssertionError(
                        f"{self.identity}: no {prefix!r} line in "
                        f"{timeout}s; got {self.lines}"
                    )
                self._cv.wait(remaining)

    def kill(self) -> None:
        """SIGKILL — the no-warning death the failover story is about."""
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=10)

    def stop(self, timeout: float = 30.0) -> None:
        """Graceful SIGTERM; a missed deadline dumps stacks (SIGUSR1)
        before the hard kill so the hang is diagnosable from stdout."""
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.send_signal(signal.SIGUSR1)  # stack dump
            time.sleep(2)
            self.proc.kill()
            self.proc.wait()
            raise AssertionError(
                f"{self.identity} missed the SIGTERM deadline; "
                f"output: {self.lines}"
            )

    def cleanup(self) -> None:
        """Best-effort teardown for finally blocks: un-SIGSTOP (a test
        may have partitioned this replica), then SIGKILL whatever is
        still alive — teardown must never hang the suite."""
        try:
            os.kill(self.proc.pid, signal.SIGCONT)
        except (ProcessLookupError, PermissionError):
            pass
        if self.proc.poll() is None:
            self.proc.kill()
        self.proc.wait(timeout=10)


def python_worker(script: str, identity: str, env: dict) -> MarkeredProc:
    """Spawn `script` with this interpreter and `{**os.environ, **env}`."""
    return MarkeredProc(
        identity, [sys.executable, script], {**os.environ, **env}
    )
