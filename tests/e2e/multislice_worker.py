"""Worker for the multi-slice gang E2E: a 2-slice x 2-process TpuJob
whose members build a HYBRID mesh — dp split across slices (DCN axis)
and within each slice (ICI axis) — and run a global psum plus a sharded
training step across all four real processes.

This exercises the full multi-slice path on CPU: the operator's
TPUJOB_NUM_SLICES/TPUJOB_SLICE_ID env injection, the MEGASCALE_* export
in `initialize_from_env`, and `build_hybrid_mesh`'s virtual-slice
fallback (SURVEY.md §2.2: ICI in-slice, DCN across slices).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = ""
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

sys.path.insert(0, os.environ["KFTPU_REPO"])

import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from kubeflow_tpu.parallel import (  # noqa: E402
    MeshSpec,
    build_hybrid_mesh,
    initialize_from_env,
)


def main() -> int:
    pe = initialize_from_env()
    assert pe.num_slices == 2, pe
    assert pe.slice_id == pe.process_id // (pe.num_processes // pe.num_slices)
    # initialize_from_env exported the DCN transport hints.
    assert os.environ["MEGASCALE_NUM_SLICES"] == "2"
    assert os.environ["MEGASCALE_SLICE_ID"] == str(pe.slice_id)

    # dp = 2 (DCN, across slices) x 2 (ICI, within slice) = 4 global.
    mesh = build_hybrid_mesh(MeshSpec(dp=-1), MeshSpec(dp=2))
    assert mesh.shape["dp"] == 4, dict(mesh.shape)

    arr = jax.make_array_from_callback(
        (jax.device_count(),),
        NamedSharding(mesh, P("dp")),
        lambda idx: jnp.ones((1,)) * (pe.process_id + 1),
    )
    total = float(
        jax.jit(lambda x: x.sum(), out_shardings=NamedSharding(mesh, P()))(arr)
    )
    expected = float(sum(range(1, pe.num_processes + 1)))
    assert total == expected, (total, expected)

    # A sharded computation over the combined axis: mean of per-process
    # shards — every member must agree on the replicated result.
    mean = float(
        jax.jit(lambda x: x.mean(), out_shardings=NamedSharding(mesh, P()))(arr)
    )
    assert abs(mean - expected / pe.num_processes) < 1e-6
    print(
        f"rank {pe.process_id} slice {pe.slice_id}: hybrid psum ok "
        f"({total})",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
