"""Leader-elected TpuJob-operator replica — the HA × preemption rig.

Run N copies against one facade and exactly one runs the REAL
`TpuJobController` (gang placement through the compiled scheduler,
priority preemption, the whole reconcile); the rest are hot standbys in
the lease acquire loop. On acquiring, the worker arms the client's
lease guard so every write this term makes is fenced at the storage
boundary — the surface `tests/e2e/test_ha_preemption_e2e.py` attacks by
killing/SIGSTOPping the leader in the widest-damage window preemption
has: victims evicted, preemptor not yet placed.

KFTPU_PREEMPT_STALL widens that window deterministically (the
controller's `preempt_stall` seam fires after the evictions commit); the
worker prints "evicted <identity>" on entering it so the e2e knows
exactly when to strike.

Env: KFTPU_REPO, KFTPU_APISERVER (endpoint list — comma separated),
KFTPU_IDENTITY, KFTPU_LEASE_DURATION, KFTPU_RENEW_DEADLINE,
KFTPU_PREEMPT_STALL (seconds, default 0).
"""

import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.environ["KFTPU_REPO"])

from kubeflow_tpu.controllers.leader import LeaderElector  # noqa: E402
from kubeflow_tpu.controllers.tpujob import TpuJobController  # noqa: E402
from kubeflow_tpu.testing.apiserver_http import (  # noqa: E402
    HttpApiClient,
    endpoints_from_env,
)
from kubeflow_tpu.testing.fake_apiserver import Conflict  # noqa: E402

IDENTITY = os.environ["KFTPU_IDENTITY"]
STALL = float(os.environ.get("KFTPU_PREEMPT_STALL", "0"))


def preempt_stall() -> None:
    # Victims are evicted and durably committed; the preemptor is not
    # yet placed. Announce the window, then hold it open.
    print(f"evicted {IDENTITY}", flush=True)
    if STALL:
        time.sleep(STALL)


def main() -> None:
    client = HttpApiClient(
        endpoints_from_env(os.environ["KFTPU_APISERVER"]),
        watch_poll_timeout=2.0,
        watch_retry=0.1,
    )
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    elector = LeaderElector(
        client,
        "tpujob-controller",
        IDENTITY,
        lease_duration=float(os.environ.get("KFTPU_LEASE_DURATION", "3")),
        renew_deadline=float(os.environ.get("KFTPU_RENEW_DEADLINE", "2")),
        retry_period=0.25,
    )
    print(f"standby {IDENTITY}", flush=True)

    def start_leading(el):
        # Fencing armed BEFORE the first reconcile: every write this
        # term makes carries (lease, holder, generation).
        client.set_lease_guard(el.guard)
        print(f"leading {IDENTITY} gen {el.transitions}", flush=True)
        ctl = TpuJobController(client, preempt_stall=preempt_stall)
        threading.Thread(
            target=ctl.controller.run, args=(stop,), daemon=True
        ).start()

    try:
        lost = elector.run(stop, start_leading)
    except Conflict:
        lost = True
    if lost:
        # Deposed: a stale leader's in-flight preemption state belongs
        # to a dead term — exit and let the supervisor restart fresh
        # (client-go's RunOrDie posture).
        print(f"deposed {IDENTITY}", flush=True)
        sys.exit(2)
    client.close()


if __name__ == "__main__":
    main()
