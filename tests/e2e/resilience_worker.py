"""One incarnation of the kill-and-resume soak: a REAL guarded `fit()`.

The driver (`test_train_resilience_e2e.py` / `bench.py --workload
resilience`) runs this worker repeatedly against one checkpoint
directory, injecting the seeded `TrainFaultSchedule`: the worker
self-delivers its scheduled crash signal from inside the data iterator
(a genuine SIGKILL between steps / SIGTERM mid-step — not a simulated
exit), trains through deterministic per-position batches with scheduled
loss spikes, and appends a JSONL trace (boot, every step with its data
position, final state summary) that the driver reconstructs the run
from: final-loss parity, zero repeated/skipped batches, goodput.

Exit codes: 0 = completed; 75 = preempted (fit returned `Preempted`);
killed-by-signal otherwise.
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = ""
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["KFTPU_REPO"])

import jax.numpy as jnp  # noqa: E402

from kubeflow_tpu.parallel import MeshSpec, build_mesh  # noqa: E402
from kubeflow_tpu.testing.chaos import (  # noqa: E402
    ResumableWrapper,
    SpikedData,
)
from kubeflow_tpu.testing.tinymodels import TinyMLP  # noqa: E402
from kubeflow_tpu.train import (  # noqa: E402
    Checkpointer,
    Preempted,
    SyntheticImages,
    TrainConfig,
    Trainer,
    fit,
)
from kubeflow_tpu.train.guard import AnomalyGuard, GuardConfig  # noqa: E402


class CrashInjector(ResumableWrapper):
    """Self-delivers `signum` when the batch at `at_step` comes up.
    SIGKILL lands between steps (preemption without warning); SIGTERM
    is flagged by fit's handler and honored at the boundary AFTER the
    in-flight step (the graceful-preemption case)."""

    def __init__(self, data, at_step: int, signum: int):
        super().__init__(data)
        self.at_step = at_step
        self.signum = signum
        self._fired = False

    def transform(self, pos: int, batch):
        if not self._fired and pos >= self.at_step:
            self._fired = True
            os.kill(os.getpid(), self.signum)
        return batch


def main() -> int:
    total_steps = int(os.environ["KFTPU_TOTAL_STEPS"])
    save_interval = int(os.environ["KFTPU_SAVE_INTERVAL"])
    seed = int(os.environ["KFTPU_DATA_SEED"])
    spikes = [
        int(s) for s in os.environ.get("KFTPU_SPIKE_STEPS", "").split(",") if s
    ]
    crash_step = os.environ.get("KFTPU_CRASH_STEP")
    crash_signal = os.environ.get("KFTPU_CRASH_SIGNAL")
    incarnation = int(os.environ.get("KFTPU_INCARNATION", "0"))
    trace_path = os.environ["KFTPU_TRACE_FILE"]

    trace = open(trace_path, "a")

    def emit(event: str, **fields) -> None:
        trace.write(
            json.dumps(
                {"event": event, "incarnation": incarnation,
                 "t": time.time(), **fields}
            ) + "\n"
        )
        trace.flush()
        os.fsync(trace.fileno())

    emit("boot")

    mesh = build_mesh(MeshSpec(dp=1), jax.devices()[:1])
    config = TrainConfig(
        batch_size=8,
        learning_rate=0.05,
        warmup_steps=2,
        total_steps=total_steps,
        fsdp_params=False,
        weight_decay=0.0,
    )
    guard = AnomalyGuard(GuardConfig(
        ewma_alpha=0.2,
        warmup_steps=2,
        loss_spike_factor=3.0,
        grad_spike_factor=6.0,
        max_consecutive_skips=3,
    ))
    trainer = Trainer(
        TinyMLP(),
        config,
        mesh,
        example_input_shape=(2, 8, 8, 3),
        guard=guard,
    )
    data = SyntheticImages(
        mesh, config.batch_size, image_size=8, num_classes=10,
        seed=seed, vary_per_step=True,
    )
    data = SpikedData(data, spikes, scale=1e3)
    if crash_step is not None:
        import signal as signal_module

        signum = (
            signal_module.SIGKILL
            if crash_signal == "kill"
            else signal_module.SIGTERM
        )
        data = CrashInjector(data, int(crash_step), signum)

    ckpt = Checkpointer(
        os.environ["KFTPU_CKPT_DIR"],
        save_interval_steps=save_interval,
        max_to_keep=3,
    )

    def on_metrics(step: int, rec: dict) -> None:
        emit(
            "step",
            step=step,
            position=data.state_dict()["position"],
            loss=rec["loss"],
            skips=rec["guard_skipped_total"],
        )

    result = fit(
        trainer, data, total_steps=total_steps,
        checkpointer=ckpt, log_every=1, on_metrics=on_metrics,
    )
    ckpt.close()

    if isinstance(result, Preempted):
        emit("preempted", step=int(result.state.step), signum=result.signum)
        print(f"PREEMPTED step={int(result.state.step)}", flush=True)
        return 75

    params_l1 = float(
        sum(jnp.sum(jnp.abs(p)) for p in jax.tree_util.tree_leaves(
            result.state.params
        ))
    )
    emit(
        "done",
        step=int(result.state.step),
        position=data.state_dict()["position"],
        final_loss=result.history[-1]["loss"],
        params_l1=params_l1,
        skips=guard.skipped_total(result.state.guard),
        resumed_from=result.resumed_from,
    )
    print(f"DONE step={int(result.state.step)} l1={params_l1:.6f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
