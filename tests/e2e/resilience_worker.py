"""One incarnation of the kill-and-resume soak: a REAL guarded `fit()`.

The driver (`test_train_resilience_e2e.py` / `bench.py --workload
resilience`) runs this worker repeatedly against one checkpoint
directory, injecting the seeded `TrainFaultSchedule`: the worker
self-delivers its scheduled crash signal from inside the data iterator
(a genuine SIGKILL between steps / SIGTERM mid-step — not a simulated
exit), trains through deterministic per-position batches with scheduled
loss spikes, and appends a JSONL trace (boot, every step with its data
position, final state summary) that the driver reconstructs the run
from: final-loss parity, zero repeated/skipped batches, goodput.

ELASTIC mode (ISSUE 9): with KFTPU_ELASTIC_PLAN (a JSON list of staged
resize proposals) and/or KFTPU_RESIZE_FILE (a live proposal file the
scheduler-side driver writes), the worker runs `fit()` with an
`ElasticResize` — a `preempt_shrink` entry self-delivers a REAL SIGTERM
at its position and the staged shrink target lets fit ABSORB it by
reshaping the mesh instead of exiting; `grow_back` entries resize
upward unprompted. Each completed resize is traced (`resize` events)
and, with KFTPU_ACK_FILE set, acked to the driver — the gang worker's
half of the controller handshake. KFTPU_DP sets the starting dp.

Exit codes: 0 = completed; 75 = preempted (fit returned `Preempted`);
killed-by-signal otherwise.
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
# 8 virtual CPU devices so elastic runs can host dp up to 8; the
# legacy dp=1 soak keeps using the first device only.
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["KFTPU_REPO"])

import jax.numpy as jnp  # noqa: E402

from kubeflow_tpu.parallel import MeshSpec, build_mesh  # noqa: E402
from kubeflow_tpu.testing.chaos import (  # noqa: E402
    ResumableWrapper,
    SpikedData,
)
from kubeflow_tpu.testing.tinymodels import TinyMLP  # noqa: E402
from kubeflow_tpu.train import (  # noqa: E402
    Checkpointer,
    ElasticResize,
    Preempted,
    ResizeProposal,
    SyntheticImages,
    TrainConfig,
    Trainer,
    fit,
)
from kubeflow_tpu.train.guard import AnomalyGuard, GuardConfig  # noqa: E402


class CrashInjector(ResumableWrapper):
    """Self-delivers `signum` when the batch at `at_step` comes up.
    SIGKILL lands between steps (preemption without warning); SIGTERM
    is flagged by fit's handler and honored at the boundary AFTER the
    in-flight step (the graceful-preemption case)."""

    def __init__(self, data, at_step: int, signum: int):
        super().__init__(data)
        self.at_step = at_step
        self.signum = signum
        self._fired = False

    def transform(self, pos: int, batch):
        if not self._fired and pos >= self.at_step:
            self._fired = True
            os.kill(os.getpid(), self.signum)
        return batch


class SigtermAtSteps(ResumableWrapper):
    """Self-delivers a REAL SIGTERM at each exact position in
    `positions` — the preemption signal of a `preempt_shrink` fault.
    Exact-position matching makes the wrapper rebind-safe: after the
    resize the stream continues PAST the position, so the signal can
    never refire from the rebound clone."""

    def __init__(self, data, positions):
        super().__init__(data)
        self.positions = frozenset(int(p) for p in positions)

    def transform(self, pos: int, batch):
        if pos in self.positions:
            import signal as signal_module

            os.kill(os.getpid(), signal_module.SIGTERM)
        return batch


class DelayData(ResumableWrapper):
    """Per-batch wall-clock delay (the negotiated e2e paces the worker
    so the driver's controller round-trips fit between boundaries)."""

    def __init__(self, data, seconds: float):
        super().__init__(data)
        self.seconds = seconds

    def transform(self, pos: int, batch):
        time.sleep(self.seconds)
        return batch


def main() -> int:
    total_steps = int(os.environ["KFTPU_TOTAL_STEPS"])
    save_interval = int(os.environ["KFTPU_SAVE_INTERVAL"])
    seed = int(os.environ["KFTPU_DATA_SEED"])
    spikes = [
        int(s) for s in os.environ.get("KFTPU_SPIKE_STEPS", "").split(",") if s
    ]
    crash_step = os.environ.get("KFTPU_CRASH_STEP")
    crash_signal = os.environ.get("KFTPU_CRASH_SIGNAL")
    incarnation = int(os.environ.get("KFTPU_INCARNATION", "0"))
    trace_path = os.environ["KFTPU_TRACE_FILE"]
    dp0 = int(os.environ.get("KFTPU_DP", "1"))
    elastic_plan = json.loads(os.environ.get("KFTPU_ELASTIC_PLAN") or "[]")
    resize_file = os.environ.get("KFTPU_RESIZE_FILE")
    ack_file = os.environ.get("KFTPU_ACK_FILE")
    step_delay = float(os.environ.get("KFTPU_STEP_DELAY") or 0)

    trace = open(trace_path, "a")

    def emit(event: str, **fields) -> None:
        trace.write(
            json.dumps(
                {"event": event, "incarnation": incarnation,
                 "t": time.time(), **fields}
            ) + "\n"
        )
        trace.flush()
        os.fsync(trace.fileno())

    emit("boot")

    mesh = build_mesh(MeshSpec(dp=dp0), jax.devices()[:dp0])
    config = TrainConfig(
        batch_size=8,
        learning_rate=0.05,
        warmup_steps=2,
        total_steps=total_steps,
        fsdp_params=False,
        weight_decay=0.0,
    )
    guard = AnomalyGuard(GuardConfig(
        ewma_alpha=0.2,
        warmup_steps=2,
        loss_spike_factor=3.0,
        grad_spike_factor=6.0,
        max_consecutive_skips=3,
    ))
    trainer = Trainer(
        TinyMLP(),
        config,
        mesh,
        example_input_shape=(2, 8, 8, 3),
        guard=guard,
    )
    data = SyntheticImages(
        mesh, config.batch_size, image_size=8, num_classes=10,
        seed=seed, vary_per_step=True,
    )
    data = SpikedData(data, spikes, scale=1e3)
    if crash_step is not None:
        import signal as signal_module

        signum = (
            signal_module.SIGKILL
            if crash_signal == "kill"
            else signal_module.SIGTERM
        )
        data = CrashInjector(data, int(crash_step), signum)
    shrink_steps = [
        int(e["at_step"]) for e in elastic_plan
        if e.get("cls") == "preempt_shrink"
    ]
    if shrink_steps:
        # The preemption signal of every staged shrink is REAL: the
        # process SIGTERMs itself at the scheduled position and fit()
        # must absorb it by resizing at the boundary.
        data = SigtermAtSteps(data, shrink_steps)
    if step_delay:
        data = DelayData(data, step_delay)

    # fit() swaps its data iterable on every resize; the trace must
    # read positions from whatever stack is CURRENT, not the boot one.
    current = {"data": data}

    elastic = None
    if elastic_plan or resize_file:
        # A fault at position p is delivered while FETCHING p's batch
        # (the crash-injector convention), so its signal is honored —
        # and its staged proposal consulted — at the boundary after
        # step p+1.
        staged = {int(e["at_step"]) + 1: e for e in elastic_plan}

        def propose(step: int, preempted: bool):
            entry = staged.get(step)
            if entry is not None:
                return ResizeProposal(
                    dp=int(entry["dp"]),
                    source=entry.get("source", "live"),
                )
            if resize_file and os.path.exists(resize_file):
                # Negotiated mode: the scheduler-side driver stages the
                # live proposal (the TpuJob status.resize analog).
                try:
                    with open(resize_file) as f:
                        j = json.load(f)
                except (OSError, ValueError):
                    return None
                if j.get("dp"):
                    return ResizeProposal(
                        dp=int(j["dp"]), source=j.get("source", "live")
                    )
            return None

        def on_resize(event) -> None:
            emit(
                "resize",
                step=event.step,
                from_dp=event.from_dp,
                to_dp=event.to_dp,
                source=event.source,
                absorbed_signum=event.absorbed_signum,
                restored_step=event.restored_step,
                seconds=event.seconds,
            )
            if ack_file:
                # The gang worker's ack half of the handshake, durably
                # visible to the driver (atomic rename).
                tmp = ack_file + ".tmp"
                with open(tmp, "w") as f:
                    json.dump({"dp": event.to_dp, "step": event.step}, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, ack_file)

        def data_factory(new_mesh, d):
            rebound = d.rebind(new_mesh)
            current["data"] = rebound
            return rebound

        elastic = ElasticResize(
            mesh_factory=lambda dp: build_mesh(
                MeshSpec(dp=dp), jax.devices()[:dp]
            ),
            data_factory=data_factory,
            propose=propose,
            on_resize=on_resize,
        )

    ckpt = Checkpointer(
        os.environ["KFTPU_CKPT_DIR"],
        save_interval_steps=save_interval,
        max_to_keep=3,
    )

    def on_metrics(step: int, rec: dict) -> None:
        emit(
            "step",
            step=step,
            position=current["data"].state_dict()["position"],
            loss=rec["loss"],
            skips=rec["guard_skipped_total"],
        )

    result = fit(
        trainer, data, total_steps=total_steps,
        checkpointer=ckpt, log_every=1, on_metrics=on_metrics,
        elastic=elastic,
    )
    ckpt.close()

    if isinstance(result, Preempted):
        emit("preempted", step=int(result.state.step), signum=result.signum)
        print(f"PREEMPTED step={int(result.state.step)}", flush=True)
        return 75

    params_l1 = float(
        sum(jnp.sum(jnp.abs(p)) for p in jax.tree_util.tree_leaves(
            result.state.params
        ))
    )
    emit(
        "done",
        step=int(result.state.step),
        position=current["data"].state_dict()["position"],
        final_loss=result.history[-1]["loss"],
        params_l1=params_l1,
        skips=guard.skipped_total(result.state.guard),
        resumed_from=result.resumed_from,
        resizes=len(result.resizes),
    )
    print(f"DONE step={int(result.state.step)} l1={params_l1:.6f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
