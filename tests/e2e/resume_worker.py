"""Checkpoint-resume gang worker for the apiserver-restart e2e.

Incarnation 1: runs a REAL (tiny) `fit()` with a `Checkpointer` — the
production resume path, not a file-touch toy — then exits nonzero (a
simulated preemption). The TpuJob operator's whole-gang restart then
re-creates the gang; incarnation 2 finds the checkpoint via
`restore_latest` (manifest-verified), resumes the step sequence and
completes — proving a training job rides through a control-plane outage
and resumes from its checkpoint with no operator intervention.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = ""
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["KFTPU_REPO"])

from kubeflow_tpu.models.resnet import tiny_resnet  # noqa: E402
from kubeflow_tpu.parallel import MeshSpec, build_mesh  # noqa: E402
from kubeflow_tpu.train import (  # noqa: E402
    Checkpointer,
    SyntheticImages,
    TrainConfig,
    Trainer,
    fit,
)

PREEMPT_STEP = 2
TOTAL_STEPS = 4


def main() -> int:
    # Each rank trains its own tiny model into its own checkpoint dir
    # (the gang contract under test is restart/resume, not collectives —
    # test_gang_e2e covers the real multi-process mesh).
    rank = os.environ.get("TPUJOB_PROCESS_ID", "0")
    ckpt_dir = os.path.join(os.environ["CKPT_DIR"], f"rank-{rank}")

    mesh = build_mesh(MeshSpec(dp=1), jax.devices()[:1])
    config = TrainConfig(
        batch_size=4, learning_rate=0.05, warmup_steps=1,
        total_steps=TOTAL_STEPS, fsdp_params=False,
    )
    trainer = Trainer(
        tiny_resnet(), config, mesh, example_input_shape=(2, 16, 16, 3)
    )
    data = SyntheticImages(
        mesh, config.batch_size, image_size=16, num_classes=10,
        vary_per_step=True,
    )

    ckpt = Checkpointer(ckpt_dir, save_interval_steps=PREEMPT_STEP)
    if ckpt.latest_step() is None:
        # Incarnation 1: train to the preemption point (the final-step
        # force-save makes the checkpoint durable), then die nonzero.
        result = fit(
            trainer, data, total_steps=PREEMPT_STEP,
            checkpointer=ckpt, log_every=1,
        )
        ckpt.close()
        assert result.steps_done == PREEMPT_STEP
        print("checkpoint written; simulating preemption", flush=True)
        return 1

    # Incarnation 2: the production resume path — restore_latest inside
    # fit() verifies the manifest, repositions the data stream, and the
    # run completes only the remaining steps.
    result = fit(
        trainer, data, total_steps=TOTAL_STEPS,
        checkpointer=ckpt, log_every=1,
    )
    ckpt.close()
    assert result.resumed_from == PREEMPT_STEP, result
    assert result.steps_done == TOTAL_STEPS - PREEMPT_STEP
    assert int(result.state.step) == TOTAL_STEPS
    assert data.state_dict()["position"] == TOTAL_STEPS
    print(f"resumed from checkpoint step={result.resumed_from}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
