"""Checkpoint-resume gang worker for the apiserver-restart e2e.

Incarnation 1: does a few seconds of "work", writes a per-rank
checkpoint, and exits nonzero (a simulated preemption). The TpuJob
operator's whole-gang restart then re-creates the gang; incarnation 2
finds the checkpoint and completes — proving a training job rides
through a control-plane outage and resumes from its checkpoint with no
operator intervention.
"""

import os
import sys
import time


def main() -> int:
    rank = os.environ.get("TPUJOB_PROCESS_ID", "0")
    path = os.path.join(os.environ["CKPT_DIR"], f"ckpt-{rank}")
    time.sleep(float(os.environ.get("WORK_SECONDS", "2")))
    if os.path.exists(path):
        with open(path) as f:
            print(f"resumed from checkpoint step={f.read()}", flush=True)
        return 0
    with open(path, "w") as f:
        f.write("100")
    print("checkpoint written; simulating preemption", flush=True)
    return 1


if __name__ == "__main__":
    sys.exit(main())
