"""One RL study trial: a REAL actor–learner loop, chaos self-delivered.

The soak driver (`test_rl_soak_e2e.py` / `bench.py --workload rl`) runs
a StudyJob whose trials exec THIS worker. Each trial stands up the full
in-process RL stack — a ServingDeployment-materialized policy fleet
behind the router/batcher, actor threads rolling out through it, a
stock guarded `fit()` learner on the replay queue, checkpoint→
modelVersion-bump→drain-roll publication — sweeps the learning rate it
was assigned, and reports its mean return as the study objective over
the HTTP apiserver facade (the same `report_observation` contract every
trial uses).

Chaos is SELF-DERIVED, never transported: with KFTPU_RL_CHAOS_SEED set,
the worker reconstructs the driver's `RLFaultSchedule` from
(seed, trials) and looks up its own trial index (read off its TpuJob's
trial label) — so the fault plan can't be lost between processes:

- ``trial_kill``: first incarnation SIGKILLs itself before training;
  the gang restart (spec.maxRestarts) reschedules the trial and the
  second incarnation reports the evidence.
- ``learner_kill``: mid-fit SIGKILL; the restarted incarnation resumes
  from the committed checkpoint (same replay position, proven by
  ``resumed_from``) and finishes the SAME trial.
- ``actor_kill``: a serving replica hard-killed mid-fit (in-flight
  predicts fail like process death); the serving controller's resync
  re-ensures it while actors retry through the router.

Evidence rides the observation row (``fault_*`` fields): the driver's
coverage gate counts only what a worker reported actually happening.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["KFTPU_REPO"])

import argparse  # noqa: E402
import math  # noqa: E402
import signal  # noqa: E402
import time  # noqa: E402

from kubeflow_tpu.api import serving as serving_api  # noqa: E402
from kubeflow_tpu.controllers.serving import (  # noqa: E402
    ServingDeploymentController,
)
from kubeflow_tpu.controllers.study import LABEL_TRIAL  # noqa: E402
from kubeflow_tpu.launcher.launcher import report_observation  # noqa: E402
from kubeflow_tpu.parallel import MeshSpec, build_mesh  # noqa: E402
from kubeflow_tpu.rl import (  # noqa: E402
    EnvConfig,
    PolicyCheckpointPublisher,
    ReplayQueue,
    RLConfig,
    build_learner,
    run_actor_learner,
)
from kubeflow_tpu.serving.replica import LocalReplicaRuntime  # noqa: E402
from kubeflow_tpu.serving.router import Router  # noqa: E402
from kubeflow_tpu.testing.apiserver_http import (  # noqa: E402
    HttpApiClient,
    endpoints_from_env,
)
from kubeflow_tpu.testing.chaos import (  # noqa: E402
    ACTOR_KILL,
    LEARNER_KILL,
    TRIAL_KILL,
    RLFaultSchedule,
)
from kubeflow_tpu.testing.fake_apiserver import FakeApiServer  # noqa: E402
from kubeflow_tpu.train import Checkpointer, Preempted  # noqa: E402

REPLICAS = 2


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--lr", type=float, required=True)
    args = parser.parse_args()

    study_api_client = HttpApiClient(
        endpoints_from_env(os.environ["KFTPU_APISERVER"])
    )
    job_name = os.environ["TPUJOB_NAME"]
    namespace = os.environ["TPUJOB_NAMESPACE"]
    job = study_api_client.get("TpuJob", job_name, namespace)
    trial = int(job.metadata.labels[LABEL_TRIAL])
    restarts = int(job.status.get("restarts", 0) or 0)
    total_steps = int(os.environ.get("KFTPU_RL_STEPS", "18"))
    publish_every = int(os.environ.get("KFTPU_RL_PUBLISH_EVERY", "6"))

    fault = None
    if os.environ.get("KFTPU_RL_CHAOS_SEED"):
        sched = RLFaultSchedule(
            int(os.environ["KFTPU_RL_CHAOS_SEED"]),
            trials=int(os.environ["KFTPU_RL_TRIALS"]),
        )
        faults = sched.for_trial(trial)
        fault = faults[0] if faults else None

    evidence: dict[str, float] = {}
    if fault is not None and fault.cls == TRIAL_KILL:
        if restarts == 0:
            # Die before any training happened: the study's whole-gang
            # restart must reschedule this trial from scratch.
            os.kill(os.getpid(), signal.SIGKILL)
        evidence["fault_trial_kill"] = 1.0

    workdir = os.path.join(
        os.environ.get("KFTPU_RL_WORKDIR", "/tmp/kftpu-rl"),
        f"trial-{trial}",
    )
    ckpt_dir = os.path.join(workdir, "ckpt")
    os.makedirs(workdir, exist_ok=True)

    cfg = RLConfig(
        env=EnvConfig(
            seed=1000 + trial, obs_dim=8, n_actions=4, n_envs=8, horizon=3
        ),
        hidden=16,
        learning_rate=args.lr,
        total_steps=total_steps,
        publish_every=publish_every,
        staleness_bound=2 * publish_every,
        n_actors=2,
        dp=2,
    )
    mesh = build_mesh(MeshSpec(dp=cfg.dp), jax.devices()[: cfg.dp])
    trainer = build_learner(cfg, mesh)

    # The policy fleet's control plane is in-process (the OUTER facade is
    # the study plane; a trial owns its own serving stack the way each
    # Sebulba learner owns its actor fleet).
    fleet_api = FakeApiServer()
    router = Router()
    publisher = PolicyCheckpointPublisher(
        ckpt_dir,
        trainer.abstract_state,
        obs_dim=cfg.env.obs_dim,
        n_actions=cfg.env.n_actions,
        hidden=cfg.hidden,
        device=jax.devices("cpu")[0],
    )
    ctl = ServingDeploymentController(
        fleet_api, runtime=LocalReplicaRuntime(router, publisher)
    )
    fleet_api.create(
        serving_api.make_serving_deployment(
            "pol", model="policy", replicas=REPLICAS, max_batch=8,
            batch_timeout_ms=1.0,
        )
    )
    ctl.controller.run_until_idle()

    ckpt = Checkpointer(ckpt_dir, save_interval_steps=cfg.publish_every)
    resumed_from = int(ckpt.latest_step() or 0)
    queue = ReplayQueue(
        capacity=cfg.replay_capacity,
        staleness_bound=cfg.staleness_bound,
        mesh=mesh,
        stall_timeout_s=60,
    )

    kill_at = None
    if fault is not None and fault.cls == LEARNER_KILL and restarts == 0:
        # Past the first publish (so resume has a committed checkpoint
        # to prove continuity against), short of the end.
        kill_at = min(
            max(publish_every + 1,
                math.ceil(fault.at_fraction * total_steps)),
            total_steps - 2,
        )
    actor_kill_at = None
    if fault is not None and fault.cls == ACTOR_KILL and restarts == 0:
        actor_kill_at = min(
            max(2, math.ceil(fault.at_fraction * total_steps)),
            total_steps - 2,
        )
    actor_killed: list[str] = []

    def fault_hook(step: int) -> None:
        if kill_at is not None and step >= kill_at:
            os.kill(os.getpid(), signal.SIGKILL)
        if actor_kill_at is not None and step >= actor_kill_at \
                and not actor_killed:
            ready = router.ready_names()
            if ready:
                name = ready[0]
                replica = router.replica(name)
                replica.kill()  # in-flight callers fail like SIGKILL
                router.remove(name)
                actor_killed.append(name)

    try:
        result = run_actor_learner(
            api=fleet_api,
            deployment="pol",
            router=router,
            trainer=trainer,
            checkpointer=ckpt,
            queue=queue,
            cfg=cfg,
            reconcile=ctl.controller.run_until_idle,
            fault_hook=fault_hook,
        )
    finally:
        ckpt.close()

    if isinstance(result.fit_result, Preempted):
        sys.exit(75)

    # The healed fleet is part of the actor_kill evidence: the resync
    # re-ensure must have brought the fleet back to spec strength.
    if actor_killed:
        deadline = time.time() + 10
        while time.time() < deadline and \
                len(router.ready_names()) < REPLICAS:
            ctl.controller.run_until_idle()
            time.sleep(0.05)
        if len(router.ready_names()) >= REPLICAS:
            evidence["fault_actor_kill"] = 1.0
            evidence["healed_replicas"] = float(len(actor_killed))
    if fault is not None and fault.cls == LEARNER_KILL and restarts > 0 \
            and resumed_from > 0:
        evidence["fault_learner_kill"] = 1.0
        evidence["resumed_from"] = float(resumed_from)

    observation = {
        "return": result.mean_return,
        "actor_steps": float(result.actor_steps),
        "stale_dropped": float(result.stale_dropped),
        "publishes": float(len(result.publishes)),
        **evidence,
    }
    if result.publish_latencies:
        observation["publish_latency_s"] = max(result.publish_latencies)
    report_observation(
        study_api_client, job_name, namespace, observation
    )
    print(
        f"rl trial {trial} done lr={args.lr} return={result.mean_return:.3f} "
        f"restarts={restarts} evidence={sorted(evidence)}"
    )


if __name__ == "__main__":
    main()
