"""Apiserver failover e2e: SIGKILL the ACTIVE facade mid-load.

The last SPOF (round-5 verdict): controllers and the webhook went HA in
round 5, but the facade itself was one process with no standby and
`HttpApiClient` hard-wired to one URL. Here the full active-passive
story (`testing/failover.py`) is proven the only way that counts — a
real SIGKILL under live load:

- two `apiserver_worker.py` replicas over ONE durable state dir; the
  active serves, the standby parks in the apiserver-lease acquire loop
  serving nothing (it doesn't even bind its port);
- CLI-writer threads, streaming watchers, and a level-triggered
  controller all drive one endpoint-list client fleet;
- the active is SIGKILLed mid-load; the standby replays the WAL, takes
  over within the lease TTL, and every client resumes via endpoint
  rotation + the normal 410-relist path;
- ZERO acknowledged writes lost — proven against the durable state
  itself (a fresh store booted over the dir after shutdown must hold
  every acked object: the WAL diff), and zero duplicate side effects —
  every reconciled object has exactly ONE generated-name child (two
  concurrently-believing actives, or a double-applied retry, would
  have created two).

The seeded nightly soak (`slow`) repeats the kill through an
`apiserver_kill` fault plan (`FaultSchedule(classes=(APISERVER_KILL,))`)
— kill, takeover, restart the corpse as a fresh standby, kill again —
and gates on plan coverage, reproducible from the one printed integer
(KFTPU_FAILOVER_SEED), driven nightly by `bench.py --workload
controlplane` which publishes the measured failover seconds.
"""

import os
import signal
import socket
import sys
import threading
import time

import pytest

from tests.e2e.ha_driver import MarkeredProc, free_port as _free_port

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.tokens import TokenRegistry
from kubeflow_tpu.controllers.runtime import Controller, Result, retry_on_conflict
from kubeflow_tpu.testing.apiserver_http import HttpApiClient
from kubeflow_tpu.testing.fake_apiserver import (
    AlreadyExists,
    FakeApiServer,
    NotFound,
    Unavailable,
)

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
APISERVER = os.path.join(REPO, "tests", "e2e", "apiserver_worker.py")

LEASE_DURATION = 2.0
DEFAULT_SEED = 20260804

WRITERS = 3
OBJECTS_PER_WRITER = 25
WATCHERS = 2


class _Replica(MarkeredProc):
    """One HA facade replica (shared driver: `ha_driver.MarkeredProc`)."""

    def __init__(self, identity: str, port: int, tmp_path):
        self.port = port
        self.url = f"http://127.0.0.1:{port}"
        super().__init__(
            identity,
            [sys.executable, APISERVER],
            {
                **os.environ,
                "KFTPU_REPO": REPO,
                "KFTPU_STATE_DIR": str(tmp_path / "state"),
                "KFTPU_TOKEN_FILE": str(tmp_path / "tokens"),
                "KFTPU_PORT": str(port),
                "KFTPU_TLS": "0",  # loopback rig; TLS is restart e2e's job
                "KFTPU_HA_IDENTITY": identity,
                "KFTPU_LEASE_DURATION": str(LEASE_DURATION),
                "KFTPU_RENEW_DEADLINE": str(LEASE_DURATION * 0.6),
            },
        )


def _boot_pair(tmp_path) -> tuple["_Replica", "_Replica", str]:
    tokens = TokenRegistry()
    admin_token = tokens.issue("system:admin")
    tokens.save(str(tmp_path / "tokens"))
    a = _Replica("facade-a", _free_port(), tmp_path)
    a.wait_marker("standby facade-a")
    a.wait_marker("leading facade-a")
    b = _Replica("facade-b", _free_port(), tmp_path)
    b.wait_marker("standby facade-b")
    return a, b, admin_token


def _client(endpoints, token, **kw) -> HttpApiClient:
    kw.setdefault("timeout", 5.0)
    kw.setdefault("watch_poll_timeout", 1.0)
    kw.setdefault("watch_retry", 0.1)
    kw.setdefault("retry_base", 0.02)
    kw.setdefault("breaker_cooldown", 0.5)
    return HttpApiClient(
        endpoints, token=token, allow_plaintext_token=True, **kw
    )


def _create_acked(client: HttpApiClient, obj, deadline_s: float = 60.0):
    """A CLI writer's posture across a control-plane outage: the client-
    level bounded retry absorbs blips; anything longer (the failover
    window itself) is ridden out at this level, the way a controller's
    workqueue requeue would. AlreadyExists here can only be OUR earlier
    attempt that committed before its ack was lost (names are writer-
    unique), so it counts as acked."""
    import http.client as _hc

    deadline = time.monotonic() + deadline_s
    while True:
        try:
            return client.create(obj)
        except AlreadyExists:
            return None  # earlier ambiguous attempt committed
        except (Unavailable, _hc.HTTPException, OSError):
            if time.monotonic() > deadline:
                raise
            time.sleep(0.1)


def _reconcile(capi, key):
    """Level-triggered side-effect surface: one GENERATED-name child per
    FailObj (list-empty-then-create — a double-active or double-applied
    retry yields TWO children), then status Done."""
    ns, name = key
    try:
        obj = capi.get("FailObj", name, ns)
    except NotFound:
        return Result()
    if obj.status.get("phase") == "Done":
        return Result()
    children = capi.list(
        "ChildObj", namespace=ns, label_selector={"child-of": name}
    )
    if not children:
        child = new_resource(
            "ChildObj", f"{name}-{os.urandom(4).hex()}", ns, spec={}
        )
        child.metadata.labels["child-of"] = name
        capi.create(child)

    def mark_done():
        fresh = capi.get("FailObj", name, ns)
        fresh.status["phase"] = "Done"
        capi.update_status(fresh)

    retry_on_conflict(mark_done)
    return Result()


def test_kill_active_mid_load_fails_over_without_losing_acked_writes(
    tmp_path,
):
    a, b, token = _boot_pair(tmp_path)
    endpoints = [a.url, b.url]
    admin = _client(endpoints, token)
    ctl_client = _client(endpoints, token)
    watch_clients = [_client(endpoints, token) for _ in range(WATCHERS)]
    acked: list[str] = []
    acked_lock = threading.Lock()
    kill_at = threading.Event()
    writer_errors: list[Exception] = []
    seen: list[dict[str, bool]] = [dict() for _ in range(WATCHERS)]

    for i, wc in enumerate(watch_clients):
        def handler(event, obj, i=i):
            if obj.kind == "FailObj":
                seen[i][obj.metadata.name] = True

        wc.watch(handler, "FailObj")

    ctl = Controller(ctl_client, "FailObj", _reconcile, name="failover-ctl")
    ctl_stop = threading.Event()
    ctl_thread = threading.Thread(
        target=ctl.run, args=(ctl_stop,), daemon=True
    )
    ctl_thread.start()

    def writer(w: int) -> None:
        client = _client(endpoints, token)
        try:
            for i in range(OBJECTS_PER_WRITER):
                name = f"obj-{w}-{i}"
                _create_acked(
                    client,
                    new_resource("FailObj", name, "load", spec={"w": w}),
                )
                with acked_lock:
                    acked.append(name)
                    if len(acked) >= WRITERS * OBJECTS_PER_WRITER // 3:
                        kill_at.set()
                time.sleep(0.02)  # spread the load across the kill
        except Exception as e:  # surfaced in the assert below
            writer_errors.append(e)
        finally:
            client.close()

    threads = [
        threading.Thread(target=writer, args=(w,)) for w in range(WRITERS)
    ]
    try:
        for t in threads:
            t.start()
        # -- the kill: mid-load, no warning, no release -------------------
        assert kill_at.wait(30), "writers never reached the kill point"
        t_kill = time.monotonic()
        a.kill()
        b.wait_marker("leading facade-b", timeout=LEASE_DURATION + 10)
        failover = time.monotonic() - t_kill
        assert failover < LEASE_DURATION + 5, (
            f"takeover took {failover:.1f}s (lease TTL {LEASE_DURATION}s)"
        )
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "writers hung"
        assert not writer_errors, writer_errors

        # Every acked write is serveable from the standby.
        with acked_lock:
            acked_names = set(acked)
        assert acked_names == {
            f"obj-{w}-{i}"
            for w in range(WRITERS)
            for i in range(OBJECTS_PER_WRITER)
        }
        names = {o.metadata.name for o in admin.list("FailObj", "load")}
        missing = acked_names - names
        assert not missing, f"acked writes lost across failover: {missing}"

        # The controller converged THROUGH the failover: every object
        # Done with exactly one child — zero duplicate side effects.
        deadline = time.monotonic() + 90
        def undone():
            return [
                o.metadata.name
                for o in admin.list("FailObj", "load")
                if o.status.get("phase") != "Done"
            ]
        while undone():
            assert time.monotonic() < deadline, (
                f"controller never converged: {undone()[:5]}..."
            )
            time.sleep(0.2)
        children = admin.list("ChildObj", "load")
        per_obj: dict[str, int] = {}
        for c in children:
            per_obj[c.metadata.labels["child-of"]] = (
                per_obj.get(c.metadata.labels["child-of"], 0) + 1
            )
        dupes = {k: v for k, v in per_obj.items() if v != 1}
        assert not dupes, f"duplicate side effects across failover: {dupes}"
        assert set(per_obj) == acked_names

        # Streaming watchers resumed on the standby and converged.
        deadline = time.monotonic() + 60
        while not all(
            acked_names <= set(seen[i]) for i in range(WATCHERS)
        ):
            assert time.monotonic() < deadline, (
                f"watchers never converged: {[len(s) for s in seen]}"
                f"/{len(acked_names)}"
            )
            time.sleep(0.2)

        assert admin.failovers >= 1, "client never rotated endpoints"
        assert a.proc.returncode == -signal.SIGKILL
        print(
            f"# apiserver failover: takeover {failover:.2f}s (TTL "
            f"{LEASE_DURATION}s), {len(acked_names)} acked writes kept, "
            f"{len(children)} children, "
            f"admin failovers={admin.failovers}"
        )
    finally:
        ctl_stop.set()
        ctl_thread.join(timeout=10)
        for c in (admin, ctl_client, *watch_clients):
            c.close()
        a.stop() if a.proc.poll() is None else None
        b.stop()

    # -- the WAL diff: durable truth, read with no server alive ----------
    # B's graceful stop checkpointed; a fresh store over the same dir
    # must hold every acked object and every child. This is the
    # zero-acked-writes-lost proof at the storage layer, independent of
    # anything a live facade claimed.
    restored = FakeApiServer(
        persist_dir=str(tmp_path / "state" / "store")
    )
    try:
        durable = {o.metadata.name for o in restored.list("FailObj", "load")}
        assert acked_names <= durable, (
            f"durable state lost acked writes: {acked_names - durable}"
        )
        assert len(restored.list("ChildObj", "load")) == len(acked_names)
    finally:
        restored.close()


@pytest.mark.slow
def test_failover_soak_nightly(tmp_path):
    """Seeded kill-cycle soak: an `apiserver_kill` fault plan drives
    repeated active-facade SIGKILLs under continuous writer load; after
    each kill the standby takes over and the corpse restarts as a fresh
    standby. Gates: plan coverage (every planned kill actually fired),
    convergence (every acked write present at the end, durably), and
    reproducibility (the plan is a pure function of the printed seed)."""
    from kubeflow_tpu.testing.chaos import APISERVER_KILL, FaultSchedule

    seed = int(os.environ.get("KFTPU_FAILOVER_SEED") or DEFAULT_SEED)
    print(f"# failover soak seed={seed}")
    kills = 3
    schedule = FaultSchedule(
        seed, faults_per_class=kills, classes=(APISERVER_KILL,)
    )
    assert schedule.plan == FaultSchedule(
        seed, faults_per_class=kills, classes=(APISERVER_KILL,)
    ).plan

    a, b, token = _boot_pair(tmp_path)
    replicas = {a.identity: a, b.identity: b}
    active = a.identity
    endpoints = [a.url, b.url]
    admin = _client(endpoints, token)
    acked: list[str] = []
    stop_writing = threading.Event()
    writer_errors: list[Exception] = []

    def writer() -> None:
        client = _client(endpoints, token)
        i = 0
        try:
            while not stop_writing.is_set():
                name = f"soak-{i}"
                _create_acked(
                    client, new_resource("FailObj", name, "soak", spec={})
                )
                acked.append(name)
                i += 1
                time.sleep(0.01)
        except Exception as e:
            writer_errors.append(e)
        finally:
            client.close()

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    takeover_seconds: list[float] = []
    try:
        while not schedule.exhausted:
            fault = schedule.next_fault("GET", "/apis/_", "")
            if fault is None:
                time.sleep(0.05)  # gap cooldown: let load make progress
                continue
            assert fault.cls == APISERVER_KILL
            time.sleep(0.3)  # in-flight load at the kill moment
            corpse = replicas[active]
            t_kill = time.monotonic()
            corpse.kill()
            schedule.mark_injected(fault)
            survivor = next(
                r for r in replicas.values() if r.identity != active
            )
            survivor.wait_marker(
                f"leading {survivor.identity}",
                timeout=LEASE_DURATION + 15,
            )
            takeover_seconds.append(time.monotonic() - t_kill)
            active = survivor.identity
            # Restart the corpse as a fresh standby on its old port.
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                try:
                    with socket.socket() as s:
                        s.setsockopt(
                            socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
                        )
                        s.bind(("127.0.0.1", corpse.port))
                    break
                except OSError:
                    time.sleep(0.2)
            fresh = _Replica(corpse.identity, corpse.port, tmp_path)
            fresh.wait_marker(f"standby {corpse.identity}")
            replicas[corpse.identity] = fresh
        stop_writing.set()
        t.join(timeout=60)
        # The gate below reads `acked`; a wedged writer still mutating
        # it would turn the zero-loss check into a race (an ack landing
        # after the list reads as "lost" and won't reproduce from the
        # seed).
        assert not t.is_alive(), "writer hung past its retry deadline"
        names = {o.metadata.name for o in admin.list("FailObj", "soak")}
        missing = set(acked) - names
        # Metrics BEFORE the gates: the nightly driver (`bench.py
        # --workload controlplane`, same contract as the resilience
        # soak's KFTPU_RESILIENCE_METRICS) gets the measured economics —
        # including a nonzero acked_lost — even from a run the asserts
        # below fail, so a red nightly still reports what happened.
        metrics_path = os.environ.get("KFTPU_FAILOVER_METRICS")
        if metrics_path and takeover_seconds:
            import json

            with open(metrics_path, "w") as f:
                json.dump(
                    {
                        "kills": kills,
                        "lease_ttl_seconds": LEASE_DURATION,
                        "failover_seconds_mean": sum(takeover_seconds)
                        / len(takeover_seconds),
                        "failover_seconds_max": max(takeover_seconds),
                        "acked_writes": len(acked),
                        "acked_lost": len(missing),
                        "coverage": schedule.coverage(),
                    },
                    f,
                )
        assert not writer_errors, writer_errors
        assert schedule.coverage()[APISERVER_KILL] == kills, (
            f"coverage gate: {schedule.coverage()} (seed {seed})"
        )
        assert not missing, (
            f"acked writes lost (seed {seed}): {sorted(missing)[:5]}"
        )
        print(
            f"# failover soak: {kills} kills survived, "
            f"{len(acked)} acked writes kept, takeover "
            f"{max(takeover_seconds):.2f}s worst (seed {seed})"
        )
    finally:
        stop_writing.set()
        admin.close()
        for r in replicas.values():
            if r.proc.poll() is None:
                r.stop()
