"""Control-plane durability e2e: SIGKILL the apiserver, restart with state.

The last Kubernetes property everything else in this platform assumed
and nothing provided (round-3 verdict): the reference's apiserver rides
etcd, so killing it loses nothing
(`profile-controller/controllers/suite_test.go:29-54`). These tests pin
the same property for our WAL-backed store across a REAL process kill:

1. CRs, uids and resourceVersions survive; a pre-restart watch bookmark
   gets a clean 410 Gone and the informer client recovers by relisting.
2. A running TpuJob gang rides through the outage: the out-of-process
   controller reconnects, reconciles the failure that happened while the
   control plane was dark, and the restarted gang resumes from its
   checkpoint — no operator intervention.
"""

import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.api import make_tpujob
from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.rbac import make_cluster_role, make_cluster_role_binding
from kubeflow_tpu.api.tokens import TokenRegistry, service_account
from kubeflow_tpu.api.tpujob import KIND
from kubeflow_tpu.runtime import LocalPodRunner
from kubeflow_tpu.testing.apiserver_http import HttpApiClient
from kubeflow_tpu.testing.fake_apiserver import Gone

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
APISERVER = os.path.join(REPO, "tests", "e2e", "apiserver_worker.py")
CONTROLLER = os.path.join(REPO, "tests", "e2e", "controller_worker.py")
RESUME_WORKER = os.path.join(REPO, "tests", "e2e", "resume_worker.py")

CONTROLLER_RULES = [
    {"verbs": ["get", "list", "watch"], "resources": ["tpujobs"]},
    {"verbs": ["update"], "resources": ["tpujobs/status"]},
    {"verbs": ["get", "list", "watch", "create", "delete"],
     "resources": ["pods"]},
    {"verbs": ["get", "list", "watch", "create"], "resources": ["services"]},
    {"verbs": ["list"], "resources": ["nodes"]},
    {"verbs": ["create"], "resources": ["events"]},
]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _boot(tmp_path, port: int) -> subprocess.Popen:
    proc = subprocess.Popen(
        [sys.executable, APISERVER],
        env={
            **os.environ,
            "KFTPU_REPO": REPO,
            "KFTPU_STATE_DIR": str(tmp_path / "state"),
            "KFTPU_TOKEN_FILE": str(tmp_path / "tokens"),
            "KFTPU_PORT": str(port),
        },
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    line = proc.stdout.readline().strip()
    if line != f"apiserver ready {port}":
        proc.kill()
        proc.communicate()  # reap; don't leak a worker on a failed boot
        raise AssertionError(line)
    return proc


def _boot_fresh(tmp_path) -> tuple[subprocess.Popen, int]:
    """First boot: pick a port and start the worker, retrying on the
    inherent _free_port()→bind race (another process — e.g. a parallel
    pytest run — can steal the port in between). RESTART boots must
    reuse the original port and don't retry: clients hold the URL."""
    last: Exception | None = None
    for _ in range(3):
        port = _free_port()
        try:
            return _boot(tmp_path, port), port
        except AssertionError as e:
            last = e
    raise AssertionError(f"could not boot the apiserver worker: {last}")


def _ca(tmp_path) -> str:
    return str(tmp_path / "state" / "tls" / "ca.crt")


def _sigkill_and_wait(proc: subprocess.Popen, port: int) -> None:
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=10)
    # The old process is really gone (no graceful shutdown ran).
    with pytest.raises(OSError):
        with socket.create_connection(("127.0.0.1", port), timeout=2):
            pass


def _wait_port_free(port: int, timeout: float = 10.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        with socket.socket() as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                s.bind(("127.0.0.1", port))
                return
            except OSError:
                time.sleep(0.2)
    raise TimeoutError(f"port {port} still busy")


def test_sigkill_restart_preserves_state_and_watch_recovers(tmp_path):
    tokens = TokenRegistry()
    admin_token = tokens.issue("system:admin")
    tokens.save(str(tmp_path / "tokens"))
    proc, port = _boot_fresh(tmp_path)
    base_url = f"https://127.0.0.1:{port}"
    admin = HttpApiClient(
        base_url, token=admin_token, watch_poll_timeout=2.0,
        watch_retry=0.2, ca=_ca(tmp_path),
    )
    try:
        created = admin.create(
            new_resource("Profile", "team-a", "", spec={"owner": "a@x.co"})
        )
        rv_early = created.metadata.resource_version
        # More writes land AFTER the bookmark a slow watcher would hold.
        admin.create(new_resource("ConfigMap", "cm-1", spec={"k": "v"}))
        job = admin.create(make_tpujob("held", replicas=2,
                                       tpu_chips_per_worker=0))
        uid_before = job.metadata.uid

        _sigkill_and_wait(proc, port)
        _wait_port_free(port)
        proc = _boot(tmp_path, port)

        # State restored: same objects, same uids, same resourceVersions.
        restored = admin.get(KIND, "held")
        assert restored.metadata.uid == uid_before
        assert restored.spec["replicas"] == 2
        assert admin.get("Profile", "team-a", "").spec == {"owner": "a@x.co"}
        # RBAC objects were restored from disk, not reseeded: the admin
        # binding still authorizes writes (this create would 403 if RBAC
        # state had been lost).
        admin.create(new_resource("ConfigMap", "cm-2", spec={}))

        # A pre-restart bookmark is history the fresh journal can't
        # serve: the apiserver answers 410 Gone, never a silent gap.
        with pytest.raises(Gone):
            admin._call(
                "GET",
                f"/apis/_?watch=true&resourceVersion={rv_early}"
                "&timeoutSeconds=2",
            )

        # The informer client recovers exactly the way kube informers
        # do: relist (synthetic MODIFIED for existing state), re-watch
        # (live events for new writes).
        seen: list[tuple[str, str]] = []
        got_existing = threading.Event()
        got_live = threading.Event()

        def handler(event, obj):
            seen.append((event, obj.metadata.name))
            if obj.metadata.name == "cm-1":
                got_existing.set()
            if event == "ADDED" and obj.metadata.name == "cm-live":
                got_live.set()

        admin.watch(handler, "ConfigMap")
        assert got_existing.wait(30), seen
        admin.create(new_resource("ConfigMap", "cm-live", spec={}))
        assert got_live.wait(30), seen
    finally:
        admin.close()
        proc.send_signal(signal.SIGTERM)
        try:
            out = proc.communicate(timeout=30)[0]
        except subprocess.TimeoutExpired:
            # Collect WHERE it wedged before killing: SIGUSR1 triggers
            # the worker's faulthandler all-thread stack dump.
            proc.send_signal(signal.SIGUSR1)
            time.sleep(2)
            proc.kill()
            out = proc.communicate()[0]
            raise AssertionError(
                f"apiserver worker missed the SIGTERM deadline; "
                f"stacks/markers:\n{out}"
            )
    # Graceful shutdown checkpointed the store.
    assert (tmp_path / "state" / "store" / "snapshot.json").exists(), out


def test_sigkill_mid_gang_job_resumes_from_checkpoint(tmp_path):
    tokens = TokenRegistry()
    admin_token = tokens.issue("system:admin")
    ctl_user = service_account("kubeflow", "tpujob-controller")
    ctl_token = tokens.issue(ctl_user)
    tokens.save(str(tmp_path / "tokens"))
    proc, port = _boot_fresh(tmp_path)
    base_url = f"https://127.0.0.1:{port}"
    admin = HttpApiClient(
        base_url, token=admin_token, watch_poll_timeout=2.0,
        watch_retry=0.2, ca=_ca(tmp_path),
    )
    admin.create(make_cluster_role("tpujob-controller", CONTROLLER_RULES))
    admin.create(
        make_cluster_role_binding(
            "tpujob-controller", "tpujob-controller", ctl_user
        )
    )
    ctl_proc = subprocess.Popen(
        [sys.executable, CONTROLLER],
        env={
            **os.environ,
            "KFTPU_REPO": REPO,
            "KFTPU_APISERVER": base_url,
            "KFTPU_TOKEN": ctl_token,
            "KFTPU_CA": _ca(tmp_path),
        },
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    ckpt_dir = tmp_path / "ckpt"
    ckpt_dir.mkdir()
    runner = LocalPodRunner(
        admin,
        extra_env={"KFTPU_REPO": REPO},
        capture_dir=str(tmp_path / "logs"),
    )
    outage_done = False
    try:
        assert ctl_proc.stdout.readline().strip() == "controller ready"
        admin.create(
            make_tpujob(
                "resume",
                replicas=2,
                tpu_chips_per_worker=0,
                max_restarts=2,
                command=(sys.executable, RESUME_WORKER),
                env=(("CKPT_DIR", str(ckpt_dir)),),
            )
        )
        deadline = time.time() + 240
        phase = None
        final_status: dict = {}
        while time.time() < deadline:
            try:
                runner.step()
                job = admin.get(KIND, "resume")
                final_status = dict(job.status)
                phase = final_status.get("phase")
            except (OSError, urllib.error.URLError):
                time.sleep(0.2)  # control-plane outage in progress
                continue
            if not outage_done and runner.running_count() == 2:
                # Both incarnation-0 workers are live: kill the control
                # plane under a running gang. The workers keep computing
                # (and "preempt" themselves) while the apiserver is dark.
                _sigkill_and_wait(proc, port)
                _wait_port_free(port)
                time.sleep(4.0)  # workers checkpoint + exit during outage
                proc = _boot(tmp_path, port)
                outage_done = True
                continue
            if phase in ("Succeeded", "Failed"):
                break
            time.sleep(0.2)
    finally:
        runner.shutdown()
        ctl_proc.send_signal(signal.SIGTERM)
        try:
            ctl_out = ctl_proc.communicate(timeout=15)[0]
        except subprocess.TimeoutExpired:
            ctl_proc.kill()
            ctl_out = ctl_proc.communicate()[0]
        proc.send_signal(signal.SIGTERM)
        try:
            proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()

    logs = {
        p.name: p.read_text() for p in (tmp_path / "logs").glob("*.log")
    }
    assert outage_done, "gang never reached 2 running workers"
    assert phase == "Succeeded", (phase, ctl_out, logs)
    # The whole-gang restart consumed exactly one restart, and the second
    # incarnation resumed from the checkpoints written pre-outage.
    assert final_status.get("restarts") == 1, final_status
    resumed = [
        name for name, text in logs.items() if "resumed from checkpoint" in text
    ]
    assert len(resumed) == 2, logs
