"""Chaos soak e2e: a controller fleet converging under injected faults.

Topology (the full production shape, with a fault layer spliced in):

    store backend (FakeApiServer | NativeApiServer)
        └─ ApiServerApp facade, HTTP/1.1 keep-alive + streaming watch
            └─ ChaosProxy       ← seeded fault schedule lives here
                └─ HttpApiClient (hardened: retries, breakers, stream
                   degrade/re-probe)
                    └─ Notebook + TpuJob controllers (threaded manager)
                       + quota admission registered at the store

The soak drives a workload through the proxy while the schedule injects
every fault class (5xx bursts, mid-response resets, stale 410s, slow and
truncated watch streams, delayed writes, crash-before-ack), then asserts:

1. CONVERGENCE — every notebook has exactly its StatefulSet + Service +
   VirtualService, every gang has exactly `replicas` workers, quota held
   its cap and published status.used.
2. ZERO DUPLICATE SIDE EFFECTS — no object was ever live twice, and
   retried event emissions collapsed onto one Event.
3. COVERAGE — every fault class actually fired (a soak that quietly
   exercised nothing fails its own gate), and the schedule is exhausted.

Reproducibility: the schedule is a pure function of the printed seed
(KFTPU_CHAOS_SEED overrides), and the test asserts plan identity for the
same seed. This is the first suite where the native store is the spine
under failure rather than a parity exhibit.
"""

import os
import threading
import time
from collections import Counter

import pytest

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.tpujob import KIND as TPUJOB_KIND
from kubeflow_tpu.api.tpujob import make_tpujob
from kubeflow_tpu.controllers import quota
from kubeflow_tpu.controllers.notebook import NotebookController
from kubeflow_tpu.controllers.runtime import ControllerManager
from kubeflow_tpu.controllers.tpujob import LABEL_JOB, TpuJobController
from kubeflow_tpu.testing.apiserver_http import ApiServerApp, HttpApiClient
from kubeflow_tpu.testing.lockgraph import maybe_witness
from kubeflow_tpu.testing.chaos import (
    FAULT_CLASSES,
    ChaosProxy,
    FaultSchedule,
)
from kubeflow_tpu.testing.fake_apiserver import (
    Conflict,
    FakeApiServer,
    Invalid,
)
from kubeflow_tpu.web.wsgi import serve

# Fixed default so CI runs are deterministic; any failure prints the
# seed, and KFTPU_CHAOS_SEED reruns the identical schedule.
DEFAULT_SEED = 20260804


def _seed() -> int:
    return int(os.environ.get("KFTPU_CHAOS_SEED") or DEFAULT_SEED)


@pytest.fixture(params=["python", "native"])
def backend(request):
    """Both store backends under the SAME fault schedule — the native
    store as the spine under failure, not a parity exhibit."""
    if request.param == "native":
        try:
            from kubeflow_tpu.native.apiserver import NativeApiServer

            api = NativeApiServer()
        except Exception as e:  # toolchain/build unavailable in this env
            pytest.skip(f"native store unavailable: {e}")
        return request.param, api
    return request.param, FakeApiServer()


class _SideEffectLedger:
    """Counts ADDED/DELETED per object key straight off the store's
    watch (behind every retry/replay layer): `adds - dels > 1` for any
    key at any moment means two live instances of one identity — the
    duplicate a replayed write would create."""

    def __init__(self):
        self.adds = Counter()
        self.dels = Counter()
        self.violations: list[tuple] = []
        # Copy-on-write deflake guard (docs/perf.md): in-process watch
        # delivers the SHARED frozen snapshot. A mutable delivery here
        # would mean a fault landing mid-fan-out could expose a
        # half-written object to some other consumer.
        self.mutable_deliveries: list[tuple] = []
        self._lock = threading.Lock()

    def __call__(self, event: str, obj) -> None:
        key = (obj.kind, obj.metadata.namespace, obj.metadata.name)
        with self._lock:
            if not getattr(obj, "frozen", False):
                self.mutable_deliveries.append((event, key))
            if event == "ADDED":
                self.adds[key] += 1
                if self.adds[key] - self.dels[key] > 1:
                    self.violations.append(key)
            elif event == "DELETED":
                self.dels[key] += 1

    def live(self, key) -> int:
        with self._lock:
            return self.adds[key] - self.dels[key]


def _poll(pred, timeout, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _soak_body(
    api,
    backend_name: str,
    seed: int,
    *,
    faults_per_class: int,
    n_notebooks: int,
    n_jobs: int,
    deadline: float,
) -> None:
    repro = (
        f"[chaos seed={seed} backend={backend_name}; reproduce with "
        f"KFTPU_CHAOS_SEED={seed}]"
    )
    print(f"chaos soak starting {repro}")
    schedule = FaultSchedule(seed, faults_per_class=faults_per_class)
    # The repro contract itself: same seed → byte-identical plan.
    assert (
        FaultSchedule(seed, faults_per_class=faults_per_class).plan
        == schedule.plan
    ), repro

    quota.register(api)
    ledger = _SideEffectLedger()
    api.watch(ledger)

    app = ApiServerApp(api)
    # Short stream lifetimes so the soak cycles enough stream requests
    # to consume every stream-class fault inside its deadline.
    app.STREAM_DURATION = 6.0
    app.STREAM_SLICE = 0.3
    server, _ = serve(app, host="127.0.0.1", port=0)
    proxy = ChaosProxy("127.0.0.1", server.server_port, schedule).start()
    client = HttpApiClient(
        proxy.base_url,
        timeout=5.0,
        watch_poll_timeout=1.0,
        watch_retry=0.05,
        retry_base=0.02,
        breaker_threshold=4,
        breaker_cooldown=0.3,
        stream_failure_threshold=2,
        stream_degraded_seconds=0.5,
    )
    nb_ctl = NotebookController(client)
    job_ctl = TpuJobController(client, quota_retry_seconds=1.0)
    manager = ControllerManager()
    manager.add(nb_ctl.controller)
    manager.add(job_ctl.controller)
    manager.start()

    nb_names = [("default", f"soak-nb-{i}") for i in range(n_notebooks)]
    nb_names += [("team-a", "quota-nb-0"), ("team-a", "quota-nb-1")]
    job_names = [f"soak-job-{i}" for i in range(n_jobs)]
    try:
        # -- workload (the user side writes straight to the store; the
        # fault schedule targets the CONTROLLERS' client) --------------
        api.create(new_resource("Namespace", "team-a", ""))
        api.create(
            new_resource(
                "ResourceQuota", quota.QUOTA_NAME, "team-a",
                spec={"hard": {"count/notebooks": 2}},
            )
        )
        for ns, name in nb_names:
            api.create(
                new_resource(
                    "Notebook", name, ns, spec={"image": "jax-nb:v0"}
                )
            )
        # The cap actually holds while the fleet churns under faults.
        with pytest.raises(Invalid):
            api.create(
                new_resource(
                    "Notebook", "quota-nb-overflow", "team-a",
                    spec={"image": "jax-nb:v0"},
                )
            )
        for name in job_names:
            api.create(
                make_tpujob(
                    name, replicas=2, tpu_chips_per_worker=0,
                    command=("sleep", "60"),
                )
            )

        # -- soak: churn until the schedule is exhausted ----------------
        churn_deadline = time.monotonic() + deadline
        i = 0
        while not schedule.exhausted:
            assert time.monotonic() < churn_deadline, (
                f"fault schedule not exhausted before the deadline: "
                f"{schedule} {repro}"
            )
            i += 1
            ns, name = nb_names[i % len(nb_names)]
            try:
                nb = api.get("Notebook", name, ns).thaw()
                nb.spec["image"] = f"jax-nb:v{i}"
                api.update(nb)
            except (Conflict, Invalid):
                pass  # racing the controllers is the point
            time.sleep(0.25)
        print(f"schedule exhausted after {i} churn rounds {repro}")

        # -- convergence ------------------------------------------------
        final_images = {}
        for ns, name in nb_names:
            final_images[(ns, name)] = api.get(
                "Notebook", name, ns
            ).spec["image"]

        def converged() -> bool:
            for ns, name in nb_names:
                children = (
                    ("StatefulSet", name),
                    ("Service", name),
                    ("VirtualService", f"notebook-{ns}-{name}"),
                )
                for kind, child in children:
                    try:
                        api.get(kind, child, ns)
                    except Exception:
                        return False
                sts = api.get("StatefulSet", name, ns)
                image = sts.spec["template"]["spec"]["containers"][0][
                    "image"
                ]
                if image != final_images[(ns, name)]:
                    return False  # last churned spec not yet applied
            for name in job_names:
                job = api.get(TPUJOB_KIND, name, "default")
                pods = api.list(
                    "Pod", "default", label_selector={LABEL_JOB: name}
                )
                if len(pods) != 2:
                    return False
                if job.status.get("phase") != "Pending":
                    return False
            rq = api.get("ResourceQuota", quota.QUOTA_NAME, "team-a")
            if rq.status.get("used", {}).get("count/notebooks") != 2:
                return False
            return True

        assert _poll(
            converged, timeout=max(30.0, deadline / 3)
        ), (
            f"fleet did not converge {repro}; "
            f"breakers={client.breaker_state()} "
            f"retries={client.retries_total}"
        )
    finally:
        manager.stop()
        client.close()
        proxy.stop()
        server.shutdown()

    # -- coverage gate: every fault class actually fired ---------------
    coverage = schedule.coverage()
    assert schedule.exhausted and all(
        coverage[c] >= 1 for c in FAULT_CLASSES
    ), f"incomplete fault coverage: {coverage} {repro}"

    # -- zero duplicate side effects ------------------------------------
    flush = getattr(api, "flush", None)
    if flush is not None:
        flush()
    assert ledger.violations == [], (
        f"an object identity was live twice: {ledger.violations} {repro}"
    )
    assert ledger.mutable_deliveries == [], (
        f"watch delivered non-frozen objects (copy-on-write contract "
        f"broken): {ledger.mutable_deliveries[:5]} {repro}"
    )
    # Exactly one child set per notebook, exactly one worker set per
    # gang — no strays left behind by retried/replayed writes.
    for ns in ("default", "team-a"):
        nbs = {n for s, n in nb_names if s == ns}
        for kind, expected in (
            ("StatefulSet", nbs),
            ("VirtualService", {f"notebook-{ns}-{n}" for n in nbs}),
        ):
            got = {o.metadata.name for o in api.list(kind, ns)}
            assert got == expected, (
                f"{kind} set diverged in {ns!r}: expected {expected}, "
                f"got {got} {repro}"
            )
    for name in job_names:
        pods = api.list("Pod", "default", label_selector={LABEL_JOB: name})
        indexes = sorted(
            p.metadata.labels.get("kubeflow-tpu.org/worker-index")
            for p in pods
        )
        assert indexes == ["0", "1"], (name, indexes, repro)
        # A replayed GangCreated collapsed onto one Event (content-
        # derived names): gang creation happened exactly once as far as
        # any observer can tell.
        gang_created = [
            e
            for e in api.list("Event", "default")
            if e.spec.get("reason") == "GangCreated"
            and e.spec.get("involvedObject", {}).get("name") == name
        ]
        assert len(gang_created) == 1, (name, gang_created, repro)
    print(
        f"chaos soak converged: coverage={coverage} "
        f"client_retries={client.retries_total} "
        f"breakers={client.breaker_state()} {repro}"
    )


def _run_soak(api, backend_name, seed, **kwargs) -> None:
    """Run the soak, optionally under the dynamic lock-graph witness
    (KFTPU_LOCKGRAPH=1): on a green soak the observed lock-acquisition
    edges must be acyclic and a subset of the static lock-order graph
    (ci/lint/concurrency.py) — the under-approximation check for
    kftpu-race on the exact paths chaos exercises."""
    with maybe_witness():
        _soak_body(api, backend_name, seed, **kwargs)


def test_chaos_soak_converges(backend):
    """Tier-1 soak: both backends, identical (seeded) fault schedule."""
    name, api = backend
    _run_soak(
        api,
        name,
        _seed(),
        faults_per_class=2,
        n_notebooks=3,
        n_jobs=2,
        deadline=120.0,
    )


@pytest.mark.slow
def test_chaos_soak_nightly(backend):
    """The long soak (`bench.py --workload chaos` / nightly CI): a
    bigger fleet under a 3x-denser schedule. Prints its seed so any
    failure reproduces with KFTPU_CHAOS_SEED=<seed>."""
    name, api = backend
    seed = int(os.environ.get("KFTPU_CHAOS_SEED") or (time.time_ns() % 2**31))
    _run_soak(
        api,
        name,
        seed,
        faults_per_class=6,
        n_notebooks=6,
        n_jobs=3,
        deadline=480.0,
    )
