"""Controller-manager binary e2e: the production deployment shape.

`python -m kubeflow_tpu.controllers --leader-elect` is the reference's
kubebuilder manager binary with `-enable-leader-election`
(`notebook-controller/main.go:51-62`): two replicas against the secure
facade, exactly one reconciling; SIGKILL the leader and the standby
takes over within the lease TTL and keeps reconciling.
"""

import os
import subprocess
import sys
import time

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.rbac import make_cluster_role_binding, seed_cluster_roles
from kubeflow_tpu.api.tokens import TokenRegistry
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.testing.apiserver_http import ApiServerApp, HttpApiClient
from kubeflow_tpu.web.wsgi import serve

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

LEASE_DURATION = "3"


def _spawn(identity, base, token, ca):
    return subprocess.Popen(
        [sys.executable, "-m", "kubeflow_tpu.controllers",
         "--apiserver", base,
         "--controllers", "notebook,tensorboard",
         "--leader-elect", "--identity", identity,
         "--lease-duration", LEASE_DURATION,
         "--renew-deadline", "2", "--retry-period", "0.25"],
        env={
            **os.environ,
            "PYTHONPATH": REPO,
            "KFTPU_TOKEN": token,
            "KFTPU_CA": ca,
        },
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def _read_until(proc, prefix, timeout=30.0):
    """Read stdout lines until one starts with `prefix`. select()-gated:
    a spawned binary that hangs SILENT must fail this assertion at the
    deadline, not block readline forever and hang the whole run."""
    import select as _select

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        ready, _, _ = _select.select(
            [proc.stdout], [], [], min(0.5, max(0.0, deadline - time.monotonic()))
        )
        if not ready:
            continue
        line = proc.stdout.readline()
        if not line:
            time.sleep(0.05)
            continue
        if line.strip().startswith(prefix):
            return line.strip()
    raise AssertionError(f"no {prefix!r} line from worker in {timeout}s")


def _wait(pred, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.1)
    return False


def test_manager_binary_leader_elected_failover(tls_paths):
    api = FakeApiServer()
    seed_cluster_roles(api)
    tokens = TokenRegistry()
    token = tokens.issue("system:manager")
    api.create(
        make_cluster_role_binding("mgr", "kubeflow-admin", "system:manager")
    )
    server, _ = serve(
        ApiServerApp(api, tokens=tokens), host="127.0.0.1", port=0,
        tls=tls_paths,
    )
    base = f"https://127.0.0.1:{server.server_port}"
    admin = HttpApiClient(base, token=token, ca=tls_paths.ca_cert)

    a = _spawn("mgr-a", base, token, tls_paths.ca_cert)
    b = None
    try:
        _read_until(a, "leading mgr-a")
        _read_until(a, "manager ready")
        b = _spawn("mgr-b", base, token, tls_paths.ca_cert)
        _read_until(b, "standby mgr-b")

        # The ACTIVE replica reconciles: Notebook → StatefulSet.
        admin.create(new_resource(
            "Notebook", "nb1", "default",
            spec={"template": {"spec": {"containers": [
                {"name": "nb", "image": "jax"}]}}},
        ))
        assert _wait(
            lambda: any(
                s.metadata.name == "nb1"
                for s in api.list("StatefulSet", "default")
            )
        ), "leader never reconciled the Notebook"

        a.kill()  # SIGKILL: standby must wait out the lease TTL
        _read_until(b, "leading mgr-b", timeout=20)
        _read_until(b, "manager ready", timeout=20)
        admin.create(new_resource(
            "Notebook", "nb2", "default",
            spec={"template": {"spec": {"containers": [
                {"name": "nb", "image": "jax"}]}}},
        ))
        assert _wait(
            lambda: any(
                s.metadata.name == "nb2"
                for s in api.list("StatefulSet", "default")
            )
        ), "standby never reconciled after takeover"
    finally:
        for p in (a, b):
            if p is not None:
                p.kill()
                p.wait(timeout=10)
        admin.close()
        server.shutdown()
