"""Control-plane scale test — the facade under concurrent load.

The reference shipped loadtest harnesses for its hot paths
(`notebook-controller/loadtest/start_notebooks.py`,
`testing/test_deploy_app.py:566`); round 2's NotebookLoadTest ran
in-process only. This drives the HTTP facade the way a busy cluster
does — K writer threads churning M objects while N remote watchers hold
multiplexed long-poll streams — and asserts the two properties the
off-lock dispatcher exists for:

- writers never stall (p99 write latency bounded even with laggy
  consumers attached), and
- every watcher still observes a complete, ordered event stream
  (resumable-journal semantics hold under concurrency).
"""

import os
import threading
import time

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.testing.apiserver_http import ApiServerApp, HttpApiClient
from kubeflow_tpu.testing.fake_apiserver import FakeApiServer
from kubeflow_tpu.web.wsgi import serve

WRITERS = 4
OBJECTS_PER_WRITER = 40
WATCHERS = 6


def _run_writers(base: str, write_one) -> tuple[list[float], float]:
    """Run WRITERS threads, each calling `write_one(client, w, i)` for
    OBJECTS_PER_WRITER objects; returns (per-call latencies, wall
    seconds for the whole write phase) and asserts no writer errored.
    Shared by the plain and durable load tests so thresholds and
    percentile math live in one place."""
    latencies: list[float] = []
    lat_lock = threading.Lock()
    errors: list[Exception] = []

    def writer(w: int) -> None:
        client = HttpApiClient(base)
        try:
            for i in range(OBJECTS_PER_WRITER):
                for call in write_one(client, w, i):
                    t0 = time.monotonic()
                    call()
                    with lat_lock:
                        latencies.append(time.monotonic() - t0)
        except Exception as e:  # pragma: no cover - surfaced in assert
            errors.append(e)

    threads = [
        threading.Thread(target=writer, args=(w,)) for w in range(WRITERS)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    wall = time.monotonic() - t0
    # A wedged writer still appending would race the sort below into an
    # obscure crash; fail as what it is.
    assert not any(t.is_alive() for t in threads), "writer hung"
    assert not errors, errors
    latencies.sort()
    return latencies, wall


# Measured-plus-margin thresholds (VERDICT round 5 weak #4): on the CI
# host the facade serves p50 ≈ 44 ms / p99 ≈ 48 ms per call and ≈ 90
# calls/s aggregate, durable (per-write fsync) within noise of plain —
# the old `p99 < 1.0 s` bound predated keep-alive and would wave a 20×
# regression through. The MEDIAN carries the 3×-regression gate: it is
# immune to a single scheduler stall inflating a few tail samples (the
# failure mode that flaked the fixed-deadline watch test under
# full-suite load), yet a uniform transport slowdown — losing
# connection reuse, a handshake per request, a serializing lock on the
# write path — moves it directly (3 × 44 ms = 132 ms > 100 ms). p99
# stays as the gross-stall catch, and the throughput floor (~3× under
# measured) backs both against failure modes that add waits without
# touching per-call latency.
WRITE_P50_BOUND_S = 0.10
WRITE_P99_BOUND_S = 0.50
WRITE_CALLS_PER_S_FLOOR = 30.0


def test_facade_under_watcher_and_writer_load():
    api = FakeApiServer()
    server, _ = serve(ApiServerApp(api), host="127.0.0.1", port=0)
    base = f"http://127.0.0.1:{server.server_port}"

    # N remote watchers, each a multiplexed long-poll informer stream.
    # List-then-watch guarantees every object's FINAL STATE is observed
    # (a watcher syncing late sees one synthetic MODIFIED instead of the
    # object's full event history) — so convergence is measured per
    # object, not by counting historical events.
    watchers = []
    seen: list[dict[str, bool]] = [dict() for _ in range(WATCHERS)]
    done = threading.Event()
    for i in range(WATCHERS):
        client = HttpApiClient(base, watch_poll_timeout=1.0, watch_retry=0.05)

        def handler(event, obj, i=i):
            if obj.kind == "LoadObj" and event in ("ADDED", "MODIFIED"):
                seen[i][obj.metadata.name] = bool(obj.spec.get("touched"))

        client.watch(handler, kind="LoadObj")
        watchers.append(client)

    # An in-process laggy consumer rides along: it must slow down nobody.
    api.watch(lambda e, o: time.sleep(0.002))

    def write_one(client, w, i):
        obj = new_resource(
            "LoadObj", f"obj-{w}-{i}", "load", spec={"w": w, "i": i}
        )
        holder = {}

        def do_create():
            holder["created"] = client.create(obj)

        def do_update():
            created = holder["created"]
            created.spec["touched"] = True
            client.update(created)

        return (do_create, do_update)

    t_start = time.monotonic()
    latencies, write_wall = _run_writers(base, write_one)

    total_objects = WRITERS * OBJECTS_PER_WRITER
    deadline = time.monotonic() + 30

    def converged(i: int) -> bool:
        return (
            len(seen[i]) == total_objects
            and all(seen[i].values())  # final (touched) state observed
        )

    try:
        while not all(converged(i) for i in range(WATCHERS)):
            assert time.monotonic() < deadline, (
                "watchers did not converge: "
                f"{[len(s) for s in seen]} objects, "
                f"{[sum(s.values()) for s in seen]} final, "
                f"want {total_objects}"
            )
            time.sleep(0.1)
        delivery_lag = time.monotonic() - t_start - write_wall
    finally:
        for c in watchers:
            c.close()
        done.set()
        server.shutdown()

    p50 = latencies[len(latencies) // 2]
    p99 = latencies[int(len(latencies) * 0.99)]
    throughput = len(latencies) / write_wall
    assert p50 < WRITE_P50_BOUND_S, f"write p50 {p50 * 1000:.0f}ms"
    assert p99 < WRITE_P99_BOUND_S, f"write p99 {p99 * 1000:.0f}ms"
    assert throughput > WRITE_CALLS_PER_S_FLOOR, (
        f"write throughput {throughput:.0f} calls/s "
        f"({len(latencies)} calls in {write_wall:.1f}s)"
    )
    assert delivery_lag < 20.0, f"event delivery lagged {delivery_lag:.1f}s"
    print(
        f"# load: {total_objects} objects x {WRITERS} writers, "
        f"{WATCHERS} watchers, write p50={p50 * 1000:.1f}ms "
        f"p99={p99 * 1000:.1f}ms, {throughput:.0f} calls/s, "
        f"delivery lag={delivery_lag:.2f}s"
    )


def test_watcher_survives_journal_compaction_under_load():
    """A tiny journal forces 410 Gone mid-stream; the informer client
    must relist and still converge on the final state of every object."""
    api = FakeApiServer(journal_size=50)
    server, _ = serve(ApiServerApp(api), host="127.0.0.1", port=0)
    base = f"http://127.0.0.1:{server.server_port}"
    client = HttpApiClient(base, watch_poll_timeout=0.5, watch_retry=0.05)
    latest: dict[str, int] = {}

    def handler(event, obj):
        if obj.kind == "CompactObj":
            latest[obj.metadata.name] = obj.spec.get("v", -1)

    client.watch(handler, kind="CompactObj")
    try:
        for v in range(6):
            for i in range(30):
                name = f"c{i}"
                try:
                    obj = api.get("CompactObj", name, "load").thaw()
                    obj.spec["v"] = v
                    api.update(obj)
                except Exception:
                    api.create(new_resource(
                        "CompactObj", name, "load", spec={"v": v}
                    ))
        deadline = time.monotonic() + 30
        while any(latest.get(f"c{i}") != 5 for i in range(30)):
            assert time.monotonic() < deadline, latest
            time.sleep(0.1)
    finally:
        client.close()
        server.shutdown()


def test_durable_facade_write_latency_bounded(tmp_path):
    """The durability tax is bounded: with WAL persistence ON (fsync per
    committed write), concurrent writers through the facade still see
    bounded latency, and the post-load store restores completely. This
    is the etcd-role equivalent of the off-lock-dispatch property above
    — durability must not serialize the control plane."""
    api = FakeApiServer(
        persist_dir=str(tmp_path / "state"), snapshot_every=100
    )
    server, _ = serve(ApiServerApp(api), host="127.0.0.1", port=0)
    base = f"http://127.0.0.1:{server.server_port}"

    def write_one(client, w, i):
        obj = new_resource(
            "DurObj", f"d-{w}-{i}", "load", spec={"w": w, "i": i}
        )
        return (lambda: client.create(obj),)

    latencies, write_wall = _run_writers(base, write_one)
    server.shutdown()
    p50 = latencies[len(latencies) // 2]
    p99 = latencies[int(len(latencies) * 0.99)]
    throughput = len(latencies) / write_wall
    # Same measured-plus-margin gates as the plain facade: durability
    # (per-write fsync) measures within noise of plain here, so a
    # durable-path-only regression (fsync serializing the commit lock,
    # snapshot pauses blocking the world) trips the same bounds.
    assert p50 < WRITE_P50_BOUND_S, f"durable write p50 {p50 * 1000:.0f}ms"
    assert p99 < WRITE_P99_BOUND_S, f"durable write p99 {p99 * 1000:.0f}ms"
    assert throughput > WRITE_CALLS_PER_S_FLOOR, (
        f"durable write throughput {throughput:.0f} calls/s "
        f"({len(latencies)} calls in {write_wall:.1f}s)"
    )
    print(
        f"# durable load: {WRITERS * OBJECTS_PER_WRITER} fsync'd writes, "
        f"p50={p50 * 1000:.1f}ms p99={p99 * 1000:.1f}ms, "
        f"{throughput:.0f} calls/s"
    )
    # Graceful release: close() checkpoints and frees the WAL handles
    # before a second server opens the same directory (the server object
    # still references api, so relying on GC here would silently skip
    # cleanup for any future WAL backend that buffers until close).
    api.close()
    restored = FakeApiServer(persist_dir=str(tmp_path / "state"))
    assert len(restored.list("DurObj")) == WRITERS * OBJECTS_PER_WRITER


def test_tls_handshakes_o1_per_client_under_load(tls_paths):
    """Round-5 transport property: keep-alive means handshakes scale
    with CLIENTS, not with requests. The TLS facade serves WRITERS
    concurrent clients × OBJECTS_PER_WRITER writes each plus a watcher,
    and the server-side handshake counter stays O(clients) — before
    keep-alive this was one full TCP+TLS handshake per request and per
    5-second watch poll."""
    api = FakeApiServer()
    server, _ = serve(
        ApiServerApp(api), host="127.0.0.1", port=0, tls=tls_paths
    )
    base = f"https://127.0.0.1:{server.server_port}"
    os.environ["KFTPU_CA"] = tls_paths.ca_cert
    try:
        watcher = HttpApiClient(base, ca=tls_paths.ca_cert)
        seen = []
        watcher.watch(lambda ev, obj: seen.append(obj.metadata.name),
                      "LoadObj")

        def write_one(client, w, i):
            return (
                lambda: client.create(
                    new_resource("LoadObj", f"h-{w}-{i}", "load")
                ),
            )

        _run_writers(base, write_one)
        total_requests = WRITERS * OBJECTS_PER_WRITER
        deadline = time.monotonic() + 30
        while len(seen) < total_requests and time.monotonic() < deadline:
            time.sleep(0.1)
        assert len(seen) >= total_requests
        assert server.requests_served >= total_requests
        # O(1) per client: each writer dials ~1 connection (+1 retry
        # margin), the watcher 1 stream + 1 CRUD conn. O(requests)
        # would be ≥ 320 here.
        budget = 3 * (WRITERS + 1) + 4
        assert server.tls_handshakes <= budget, (
            f"{server.tls_handshakes} handshakes for "
            f"{server.requests_served} requests"
        )
        print(
            f"# tls keep-alive: {server.requests_served} requests over "
            f"{server.tls_handshakes} handshakes "
            f"({WRITERS + 1} clients)"
        )
    finally:
        os.environ.pop("KFTPU_CA", None)
        watcher.close()
        server.shutdown()
