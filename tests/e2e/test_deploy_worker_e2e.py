"""Deploy-worker isolation e2e — the StatefulSet-per-deployment analog.

The reference's router spawns one kfctl pod per deployment
(`router.go:275`) so a crashed apply is contained and recovered by the
pod controller. Here the DeployServer in `worker_mode="process"` spawns
one worker PROCESS per deployment over the secure HTTP facade; these
tests SIGKILL a worker mid-apply and assert the babysitter respawns it
and the deployment still converges from the PlatformDeployment CR —
crash containment WITH state recovery.
"""

import os
import signal
import time

import pytest

from kubeflow_tpu.deploy.kfdef import NodePool, PlatformSpec
from kubeflow_tpu.deploy.provisioner import FakeCloud
from kubeflow_tpu.deploy.server import DeployServer
from kubeflow_tpu.testing.fake_apiserver import FakeApiServer
from kubeflow_tpu.web.wsgi import TestClient


def _spec(name="kf-proc"):
    return PlatformSpec(
        name=name, project="p", zone="z",
        node_pools=[
            NodePool(name="pool-a", accelerator="v5e", topology="2x2"),
        ],
    ).to_dict()


@pytest.fixture
def server(monkeypatch):
    # Widen the kill window: the worker sleeps 2s before the PLATFORM
    # phase, so a SIGKILL at +1s always lands mid-apply.
    monkeypatch.setenv("KFTPU_WORKER_APPLY_DELAY", "2.0")
    api = FakeApiServer()
    srv = DeployServer(api, FakeCloud(api), worker_mode="process")
    yield api, srv
    srv.shutdown_workers()


def test_sigkill_mid_apply_respawns_and_converges(server):
    api, srv = server
    client = TestClient(srv)
    resp = client.post("/kfctl/apps/v1/create", _spec())
    assert resp.status == 200, resp.body

    worker = srv._workers["kf-proc"]
    time.sleep(1.0)  # inside the apply-delay window
    assert worker.alive()
    os.kill(worker.proc.pid, signal.SIGKILL)

    srv.wait_idle(timeout=120)
    assert worker.respawns >= 1
    dep = api.get("PlatformDeployment", "kf-proc", "")
    assert dep.status["phase"] == "Ready", dep.status
    assert dep.status["observedGeneration"] == dep.metadata.generation
    # The platform really materialized: the pool's host Node exists.
    nodes = api.list("Node", "")
    assert any(n.metadata.name.startswith("kf-proc-pool-a") for n in nodes)

    status = client.get("/kfctl/apps/v1/status/kf-proc").json()
    assert status["status"]["phase"] == "Ready"


def test_worker_crash_does_not_touch_server_or_neighbors(server, monkeypatch):
    """Two deployments, two workers; killing one repeatedly leaves the
    other's apply (and the server process) untouched — the containment
    property the per-deployment split exists for."""
    monkeypatch.setenv("KFTPU_WORKER_APPLY_DELAY", "0")
    api, srv = server
    client = TestClient(srv)
    assert client.post("/kfctl/apps/v1/create", _spec("kf-a")).status == 200
    assert client.post("/kfctl/apps/v1/create", _spec("kf-b")).status == 200
    victim = srv._workers["kf-a"]
    for _ in range(2):
        if victim.alive():
            os.kill(victim.proc.pid, signal.SIGKILL)
        time.sleep(0.2)
    srv.wait_idle(timeout=120)
    for name in ("kf-a", "kf-b"):
        dep = api.get("PlatformDeployment", name, "")
        assert dep.status["phase"] == "Ready", (name, dep.status)
    assert srv._workers["kf-a"].proc.pid != srv._workers["kf-b"].proc.pid


def test_respec_bumps_generation_and_reapplies(server, monkeypatch):
    monkeypatch.setenv("KFTPU_WORKER_APPLY_DELAY", "0")
    api, srv = server
    client = TestClient(srv)
    client.post("/kfctl/apps/v1/create", _spec())
    srv.wait_idle(timeout=120)
    gen1 = api.get("PlatformDeployment", "kf-proc", "").metadata.generation

    spec = _spec()
    spec["spec"]["nodePools"].append(
        {"name": "pool-b", "accelerator": "v5e", "topology": "2x2"}
    )
    client.post("/kfctl/apps/v1/create", spec)
    srv.wait_idle(timeout=120)
    dep = api.get("PlatformDeployment", "kf-proc", "")
    assert dep.metadata.generation > gen1
    assert dep.status["observedGeneration"] == dep.metadata.generation
    nodes = api.list("Node", "")
    assert any("pool-b" in n.metadata.name for n in nodes)


def test_gc_collects_converged_process_deployments(server, monkeypatch):
    monkeypatch.setenv("KFTPU_WORKER_APPLY_DELAY", "0")
    api, srv = server
    client = TestClient(srv)
    client.post("/kfctl/apps/v1/create", _spec())
    srv.wait_idle(timeout=120)
    worker = srv._workers["kf-proc"]
    assert srv.gc_older_than(3600) == []  # too fresh once observed
    assert srv.gc_older_than(-1) == ["kf-proc"]
    assert "kf-proc" not in srv._workers
    time.sleep(0.1)
    assert not worker.alive()
    # Its platform was torn down (gc sends deletes on the spec's provider).
    assert api.list("Node", "") == []
