"""The minimum end-to-end slice (SURVEY.md §7.2), fully in-process:

TpuJob CR → operator creates the gang + env contract → local runner execs
N real JAX processes → gloo collectives across them → pod phases flow back
→ operator marks the job Succeeded.
"""

import os
import sys
import time

import pytest

from kubeflow_tpu.api import make_tpujob
from kubeflow_tpu.api.tpujob import KIND
from kubeflow_tpu.controllers.tpujob import TpuJobController
from kubeflow_tpu.runtime import LocalPodRunner
from kubeflow_tpu.testing import FakeApiServer

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
WORKER = os.path.join(REPO, "tests", "e2e", "gang_worker.py")


def test_tpujob_gang_end_to_end(tmp_path):
    api = FakeApiServer()
    ctl = TpuJobController(api)
    runner = LocalPodRunner(
        api,
        extra_env={"KFTPU_REPO": REPO},
        capture_dir=str(tmp_path / "logs"),
    )

    api.create(
        make_tpujob(
            "e2e",
            replicas=2,
            tpu_chips_per_worker=0,  # CPU gang
            command=(sys.executable, WORKER),
        )
    )

    deadline = time.time() + 150
    try:
        while time.time() < deadline:
            ctl.controller.run_until_idle()
            runner.step()
            phase = api.get(KIND, "e2e").status.get("phase")
            if phase in ("Succeeded", "Failed"):
                break
            time.sleep(0.2)
    finally:
        runner.shutdown()

    logs = {
        p.name: p.read_text() for p in (tmp_path / "logs").glob("*.log")
    }
    assert api.get(KIND, "e2e").status.get("phase") == "Succeeded", logs
    assert "psum ok" in logs.get("e2e-worker-0.log", ""), logs
    assert "psum ok" in logs.get("e2e-worker-1.log", ""), logs


def test_distributed_training_end_to_end(tmp_path, tls_paths):
    """TpuJob gang of 2 real processes trains a tiny ResNet over a dp
    mesh (gloo collectives), and rank 0's reported observation flows back
    onto the job — training results, not just liveness, cross the
    process boundary."""
    from kubeflow_tpu.api.rbac import (
        make_cluster_role,
        make_cluster_role_binding,
    )
    from kubeflow_tpu.api.tokens import TokenRegistry, service_account
    from kubeflow_tpu.testing.apiserver_http import ApiServerApp
    from kubeflow_tpu.web.wsgi import serve

    api = FakeApiServer()
    # Secure facade: rank 0 reports its observation with a least-privilege
    # worker token (read the job + write its status — nothing else).
    tokens = TokenRegistry()
    worker_user = service_account("default", "train-worker")
    api.create(make_cluster_role("train-worker", [
        {"verbs": ["get"], "resources": ["tpujobs"]},
        {"verbs": ["update"], "resources": ["tpujobs/status"]},
    ]))
    api.create(
        make_cluster_role_binding("train-worker", "train-worker", worker_user)
    )
    server, _ = serve(
        ApiServerApp(api, tokens=tokens), host="127.0.0.1", port=0,
        tls=tls_paths,
    )
    ctl = TpuJobController(api)
    runner = LocalPodRunner(
        api,
        extra_env={
            "KFTPU_REPO": REPO,
            "KFTPU_APISERVER": f"https://127.0.0.1:{server.server_port}",
            "KFTPU_TOKEN": tokens.issue(worker_user),
            "KFTPU_CA": tls_paths.ca_cert,
        },
        capture_dir=str(tmp_path / "logs"),
    )
    api.create(
        make_tpujob(
            "train",
            replicas=2,
            tpu_chips_per_worker=0,
            command=(
                sys.executable,
                os.path.join(REPO, "tests", "e2e", "train_worker.py"),
            ),
        )
    )
    deadline = time.time() + 240
    try:
        while time.time() < deadline:
            ctl.controller.run_until_idle()
            runner.step()
            phase = api.get(KIND, "train").status.get("phase")
            if phase in ("Succeeded", "Failed"):
                break
            time.sleep(0.2)
    finally:
        runner.shutdown()
        server.shutdown()

    logs = {
        p.name: p.read_text() for p in (tmp_path / "logs").glob("*.log")
    }
    job = api.get(KIND, "train")
    assert job.status.get("phase") == "Succeeded", logs
    observation = job.status.get("observation") or {}
    assert observation.get("loss") is not None, (job.status, logs)
    assert observation["loss"] < observation["first_loss"], observation


def test_multislice_gang_end_to_end(tmp_path):
    """A 2-slice x 2-process TpuJob: the operator injects slice structure,
    initialize_from_env exports the DCN transport hints, and all four real
    processes agree on collectives over a hybrid ICI x DCN mesh."""
    api = FakeApiServer()
    ctl = TpuJobController(api)
    runner = LocalPodRunner(
        api,
        extra_env={"KFTPU_REPO": REPO},
        capture_dir=str(tmp_path / "logs"),
    )
    api.create(
        make_tpujob(
            "ms",
            replicas=4,
            num_slices=2,
            tpu_chips_per_worker=0,
            command=(
                sys.executable,
                os.path.join(REPO, "tests", "e2e", "multislice_worker.py"),
            ),
        )
    )
    deadline = time.time() + 240
    try:
        while time.time() < deadline:
            ctl.controller.run_until_idle()
            runner.step()
            phase = api.get(KIND, "ms").status.get("phase")
            if phase in ("Succeeded", "Failed"):
                break
            time.sleep(0.2)
    finally:
        runner.shutdown()

    logs = {
        p.name: p.read_text() for p in (tmp_path / "logs").glob("*.log")
    }
    assert api.get(KIND, "ms").status.get("phase") == "Succeeded", logs
    for rank in range(4):
        assert "hybrid psum ok" in logs.get(f"ms-worker-{rank}.log", ""), logs
