"""HA × preemption interaction e2e (round-5 verdict #6).

The two hardest subsystems — leader-elected controller HA and gang
priority preemption — proven AGAINST each other: the leader dies
mid-preemption, in the widest-damage window the platform has (victims
evicted, their chips free, the preemptor not yet placed). A wrong
successor here does real damage: re-evicting a gang that already paid
(double eviction), evicting a bystander whose chips were never needed,
or letting the deposed leader's late placement writes land in the new
term. Two variants:

- SIGKILL: the leader dies inside the window; the standby takes over
  within the lease TTL and completes the placement with the victim set
  UNCHANGED — the bystander gang's pods survive untouched (same uids),
  the victim stays evicted with its restart budget intact.
- SIGSTOP: the leader is partitioned (GC-pause analog) inside the
  window, the standby takes over and places, then the stale leader
  resumes mid-preemption and tries to finish — every late write is
  FENCED at the storage boundary (lease-generation precondition) and
  the worker exits deposed; the successor's placement is untouched.

Chip math (one pool, 4 nodes × 4 chips = 16): bystander (prio 1,
1×4 chips, oldest) + victim (prio 1, 2×4 chips, younger) leave 4 free;
the preemptor (prio 10, 3×4 = 12 chips) can be unblocked by evicting
the victim ALONE — youngest-first within the tier — so any touch of the
bystander is a double-eviction bug, which the uid assertions catch.
"""

import os
import signal
import sys
import time

from tests.e2e.ha_driver import MarkeredProc

from kubeflow_tpu.api import make_tpujob
from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.tpujob import KIND
from kubeflow_tpu.controllers.tpujob import LABEL_JOB
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.testing.apiserver_http import ApiServerApp
from kubeflow_tpu.web.wsgi import serve

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
WORKER = os.path.join(REPO, "tests", "e2e", "preempt_ha_worker.py")

LEASE_DURATION = 2.0
STALL = 6.0  # the evicted-but-not-placed window the leader dies inside


class _Worker(MarkeredProc):
    """One controller replica (shared driver: `ha_driver.MarkeredProc`)."""

    def __init__(self, identity: str, base_url: str):
        super().__init__(
            identity,
            [sys.executable, WORKER],
            {
                **os.environ,
                "KFTPU_REPO": REPO,
                "KFTPU_APISERVER": base_url,
                "KFTPU_IDENTITY": identity,
                "KFTPU_LEASE_DURATION": str(LEASE_DURATION),
                "KFTPU_RENEW_DEADLINE": str(LEASE_DURATION * 0.6),
                "KFTPU_PREEMPT_STALL": str(STALL),
            },
        )


def _wait(pred, timeout=30.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _cluster(api, nodes=4, chips=4):
    for i in range(nodes):
        node = new_resource(
            "Node", f"n{i}", "",
            spec={"pool": "default", "chips": chips, "x": i, "y": 0},
        )
        node.status = {"ready": True}
        api.create(node)


def _job(name, *, priority, replicas, chips=4):
    return make_tpujob(
        name, replicas=replicas, tpu_chips_per_worker=chips,
        command=("true",), priority=priority,
    )


def _pods(api, name):
    return api.list("Pod", "default", label_selector={LABEL_JOB: name})


def _stage(api, a: "_Worker"):
    """Common prologue: bystander + victim placed by the leader, then
    the preemptor arrives and the leader enters the evicted-but-not-
    placed stall. Returns the bystander's pod uids (the must-not-touch
    set)."""
    api.create(_job("bystander", priority=1, replicas=1))
    assert _wait(lambda: len(_pods(api, "bystander")) == 1), (
        "leader never placed the bystander gang"
    )
    time.sleep(0.05)  # strictly younger creation timestamp for the victim
    api.create(_job("victim", priority=1, replicas=2))
    assert _wait(lambda: len(_pods(api, "victim")) == 2), (
        "leader never placed the victim gang"
    )
    bystander_uids = {p.metadata.uid for p in _pods(api, "bystander")}
    assert all(p.spec.get("nodeName") for p in _pods(api, "victim"))

    api.create(_job("preemptor", priority=10, replicas=3))
    # The leader evicts the victim, then stalls (KFTPU_PREEMPT_STALL)
    # before the preemptor can place — the death window.
    a.wait_marker("evicted preempt-a", timeout=30)
    assert _wait(lambda: len(_pods(api, "victim")) == 0), (
        "victim pods not evicted"
    )
    assert len(_pods(api, "preemptor")) == 0, (
        "preemptor placed before the window closed — stall seam broken"
    )
    return bystander_uids


def _assert_converged(api, bystander_uids):
    """The successor completed placement with the victim set unchanged."""
    assert _wait(
        lambda: len(_pods(api, "preemptor")) == 3, timeout=40
    ), [p.metadata.name for p in api.list("Pod", "default")]
    assert all(p.spec.get("nodeName") for p in _pods(api, "preemptor"))
    # No double eviction: the bystander's pods are the SAME pods.
    assert {
        p.metadata.uid for p in _pods(api, "bystander")
    } == bystander_uids, "bystander gang was disturbed across the handover"
    # The victim stays evicted (no capacity) with its restart budget
    # intact — preemption is not a failure.
    victim = api.get(KIND, "victim", "default")
    assert len(_pods(api, "victim")) == 0
    assert victim.status.get("restarts", 0) == 0, victim.status
    assert victim.status.get("phase") != "Failed", victim.status


def _serve_open(api):
    server, _ = serve(ApiServerApp(api), host="127.0.0.1", port=0)
    return server, f"http://127.0.0.1:{server.server_port}"


def test_sigkill_leader_mid_preemption_successor_places_no_double_eviction():
    api = FakeApiServer()
    _cluster(api)
    server, base = _serve_open(api)
    a = _Worker("preempt-a", base)
    b = None
    try:
        a.wait_marker("leading preempt-a")
        b = _Worker("preempt-b", base)
        b.wait_marker("standby preempt-b")

        bystander_uids = _stage(api, a)

        t_kill = time.monotonic()
        a.proc.kill()  # SIGKILL inside the window: no release, no warning
        b.wait_marker("leading preempt-b", timeout=LEASE_DURATION + 10)
        failover = time.monotonic() - t_kill
        assert failover < LEASE_DURATION + 5, f"failover {failover:.1f}s"

        _assert_converged(api, bystander_uids)
        print(
            f"# HA×preemption SIGKILL: failover {failover:.2f}s, "
            "placement completed by the successor, victim set unchanged"
        )
    finally:
        for w in (a, b):
            if w is not None:
                w.cleanup()
        server.shutdown()
        api.close()


def test_sigstop_leader_mid_preemption_late_writes_fenced():
    api = FakeApiServer()
    _cluster(api)
    server, base = _serve_open(api)
    a = _Worker("preempt-a", base)
    b = None
    try:
        a.wait_marker("leading preempt-a")
        b = _Worker("preempt-b", base)
        b.wait_marker("standby preempt-b")

        bystander_uids = _stage(api, a)

        os.kill(a.proc.pid, signal.SIGSTOP)  # the partition begins
        b.wait_marker("leading preempt-b", timeout=LEASE_DURATION + 10)
        _assert_converged(api, bystander_uids)
        preemptor_uids = {p.metadata.uid for p in _pods(api, "preemptor")}

        # The stale leader resumes INSIDE its preemption pass and tries
        # to finish the term it lost: its guarded writes (events, status,
        # pod creates) are fenced server-side, and the elector's next
        # renewal reads the successor's generation — exit 2, deposed.
        os.kill(a.proc.pid, signal.SIGCONT)
        assert a.proc.wait(timeout=30) == 2, (
            f"stale leader did not exit deposed: {a.lines}"
        )
        # Nothing the deposed leader did after resuming moved the world:
        # the successor's placement is byte-for-byte the one that stands.
        assert {
            p.metadata.uid for p in _pods(api, "preemptor")
        } == preemptor_uids
        assert {
            p.metadata.uid for p in _pods(api, "bystander")
        } == bystander_uids
        assert len(_pods(api, "victim")) == 0
        print(
            "# HA×preemption SIGSTOP: deposed leader fenced (exit 2), "
            "successor placement untouched"
        )
    finally:
        for w in (a, b):
            if w is not None:
                w.cleanup()
        server.shutdown()
        api.close()
