"""HA control plane e2e: leader election across real processes.

Round-4 verdict item 1: durability (round 4) made crash recovery real,
but one-of-everything meant a crash still took the platform down until a
restart. Here two controller REPLICAS run as separate OS processes
against the durable TLS facade; exactly one reconciles (the Lease), a
SIGKILL of the leader mid-reconcile fails over to the standby within the
lease TTL with zero duplicate side effects, and a deposed leader's
in-flight write is fenced. Reference shape:
`notebook-controller/main.go:51-62` (-enable-leader-election).
"""

import os
import subprocess
import sys
import time

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.rbac import make_cluster_role, make_cluster_role_binding
from kubeflow_tpu.api.tokens import TokenRegistry, service_account
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.testing.apiserver_http import ApiServerApp, HttpApiClient
from kubeflow_tpu.web.wsgi import serve

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
WORKER = os.path.join(REPO, "tests", "e2e", "ha_controller_worker.py")

# Least-privilege for the HA worker: its kinds, its status subresource,
# events, plus get/create/update on leases — the coordination grant every
# reference controller's RBAC adds for -enable-leader-election.
RULES = [
    {"verbs": ["get", "list", "watch"], "resources": ["hajobs"]},
    {"verbs": ["update"], "resources": ["hajobs/status"]},
    {"verbs": ["get", "list", "watch", "create", "delete"],
     "resources": ["pods"]},
    {"verbs": ["get", "create", "update"], "resources": ["leases"]},
    {"verbs": ["create"], "resources": ["events"]},
]

LEASE_DURATION = 3.0


def _spawn(identity, base_url, token, ca, delay="0"):
    return subprocess.Popen(
        [sys.executable, WORKER],
        env={
            **os.environ,
            "KFTPU_REPO": REPO,
            "KFTPU_APISERVER": base_url,
            "KFTPU_TOKEN": token,
            "KFTPU_CA": ca,
            "KFTPU_IDENTITY": identity,
            "KFTPU_LEASE_DURATION": str(LEASE_DURATION),
            "KFTPU_RENEW_DEADLINE": "2",
            "KFTPU_RECONCILE_DELAY": delay,
        },
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def _read_until(proc, prefix, timeout=30.0):
    """Read stdout lines until one starts with `prefix`. select()-gated:
    a spawned binary that hangs SILENT must fail this assertion at the
    deadline, not block readline forever and hang the whole run."""
    import select as _select

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        ready, _, _ = _select.select(
            [proc.stdout], [], [], min(0.5, max(0.0, deadline - time.monotonic()))
        )
        if not ready:
            continue
        line = proc.stdout.readline()
        if not line:
            time.sleep(0.05)
            continue
        if line.strip().startswith(prefix):
            return line.strip()
    raise AssertionError(f"no {prefix!r} line from worker in {timeout}s")


def _wait(pred, timeout=30.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def test_leader_failover_no_duplicate_side_effects(tmp_path, tls_paths):
    """Two replicas, one active; SIGKILL the leader mid-reconcile; the
    standby acquires within the lease TTL and finishes ALL work; every
    job ends with exactly ONE child pod (generated names — concurrent
    actives would have created two) and a Done status."""
    api = FakeApiServer(persist_dir=str(tmp_path / "state"))
    tokens = TokenRegistry()
    user = service_account("kubeflow", "hajob-controller")
    api.create(make_cluster_role("hajob-controller", RULES))
    api.create(
        make_cluster_role_binding("hajob-controller", "hajob-controller",
                                  user)
    )
    server, _ = serve(
        ApiServerApp(api, tokens=tokens), host="127.0.0.1", port=0,
        tls=tls_paths,
    )
    base = f"https://127.0.0.1:{server.server_port}"
    token = tokens.issue(user)

    # Replica A first (wins the lease), B second (hot standby). A
    # reconciles slowly so the SIGKILL lands mid-reconcile.
    a = _spawn("replica-a", base, token, tls_paths.ca_cert, delay="0.5")
    b = None
    try:
        _read_until(a, "standby replica-a")
        _read_until(a, "leading replica-a")
        b = _spawn("replica-b", base, token, tls_paths.ca_cert)
        _read_until(b, "standby replica-b")

        for i in range(6):
            api.create(new_resource("HAJob", f"job{i}", "default",
                                    spec={"i": i}))
        # A is mid-stream (0.5 s per reconcile): wait for evidence it is
        # actively working (≥1 done, not all) then kill it -9.
        assert _wait(
            lambda: sum(
                1 for j in api.list("HAJob", "default")
                if j.status.get("phase") == "Done"
            ) >= 1
        )
        done_before = sum(
            1 for j in api.list("HAJob", "default")
            if j.status.get("phase") == "Done"
        )
        assert done_before < 6, "leader finished too fast to kill mid-work"
        a.kill()  # SIGKILL: no release, standby must wait out the TTL
        t_kill = time.monotonic()
        _read_until(b, "leading replica-b", timeout=LEASE_DURATION + 10)
        failover = time.monotonic() - t_kill
        # TTL bound: the standby polls every 0.25 s, so takeover lands
        # within lease_duration + a poll + CI slack.
        assert failover < LEASE_DURATION + 5, f"failover took {failover:.1f}s"

        assert _wait(
            lambda: all(
                j.status.get("phase") == "Done"
                for j in api.list("HAJob", "default")
            )
        ), [j.status for j in api.list("HAJob", "default")]
        # No duplicate side effects across the handover: exactly one
        # child pod per job (two concurrent actives would both have
        # list-empty-then-created), and the standby finished the rest.
        for i in range(6):
            pods = api.list("Pod", "default",
                            label_selector={"hajob": f"job{i}"})
            assert len(pods) == 1, (
                f"job{i}: {len(pods)} pods — duplicate side effects"
            )
        finishers = {
            j.status["by"] for j in api.list("HAJob", "default")
        }
        assert "replica-b" in finishers  # the standby did real work
        print(f"# failover after SIGKILL: {failover:.2f}s "
              f"(lease TTL {LEASE_DURATION}s)")
    finally:
        for p in (a, b):
            if p is not None:
                p.kill()
                p.wait(timeout=10)
        server.shutdown()
        api.close()


def test_partitioned_stale_leader_writes_are_fenced(tmp_path, tls_paths):
    """The split-brain half: SIGSTOP the leader (a network partition /
    GC pause it never notices), let the standby take over, then SIGCONT.
    The stale leader's in-flight guarded write is rejected by lease
    fencing and the worker exits deposed; the store shows only the
    successor's term."""
    api = FakeApiServer(persist_dir=str(tmp_path / "state"))
    tokens = TokenRegistry()
    user = service_account("kubeflow", "hajob-controller")
    api.create(make_cluster_role("hajob-controller", RULES))
    api.create(
        make_cluster_role_binding("hajob-controller", "hajob-controller",
                                  user)
    )
    server, _ = serve(
        ApiServerApp(api, tokens=tokens), host="127.0.0.1", port=0,
        tls=tls_paths,
    )
    base = f"https://127.0.0.1:{server.server_port}"
    token = tokens.issue(user)

    # The stale leader reconciles VERY slowly: its in-flight write will
    # resume only after the successor owns the term.
    a = _spawn("replica-a", base, token, tls_paths.ca_cert, delay="8")
    b = None
    try:
        _read_until(a, "leading replica-a")
        b = _spawn("replica-b", base, token, tls_paths.ca_cert)
        _read_until(b, "standby replica-b")

        api.create(new_resource("HAJob", "contested", "default", spec={}))
        time.sleep(1.0)  # a is now inside its 8 s reconcile sleep
        os.kill(a.pid, 19)  # SIGSTOP: the partition begins
        _read_until(b, "leading replica-b", timeout=LEASE_DURATION + 10)
        assert _wait(
            lambda: api.get("HAJob", "contested", "default")
            .status.get("phase") == "Done"
        )
        os.kill(a.pid, 18)  # SIGCONT: the stale leader resumes mid-write
        # Its guarded create/update is fenced server-side; the elector
        # then fails renewal and the worker exits deposed.
        assert a.wait(timeout=30) == 2, "stale leader did not exit deposed"

        # Only the successor's side effects exist.
        pods = api.list("Pod", "default",
                        label_selector={"hajob": "contested"})
        assert len(pods) == 1
        assert pods[0].spec["createdBy"] == "replica-b"
        assert (
            api.get("HAJob", "contested", "default").status["by"]
            == "replica-b"
        )
    finally:
        for p in (a, b):
            if p is not None:
                try:
                    os.kill(p.pid, 18)  # un-stop before kill
                except (ProcessLookupError, PermissionError):
                    pass
                p.kill()
                p.wait(timeout=10)
        server.shutdown()
        api.close()
