"""`spec.runtime: process` e2e: the serving fleet as REAL worker
processes.

One ServingDeployment with ``runtime: process`` must materialize into a
`python -m kubeflow_tpu.serving` worker that joins over the HTTP
apiserver facade, advertises its endpoint through its ServingReplica
object, serves predictions through the driver's drain-aware router
(`HttpReplica`), SELF-rolls on a modelVersion config push (no runtime
roll surface — the watch machinery is the transport), and is reaped on
CR delete. This is the production split the local runtime only
simulates: controller and workers share no memory, only the API.
"""

import os
import time

import numpy as np

from kubeflow_tpu.api import serving as serving_api
from kubeflow_tpu.controllers.serving import ServingDeploymentController
from kubeflow_tpu.serving.replica import ProcessReplicaRuntime
from kubeflow_tpu.serving.router import Router
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.testing.apiserver_http import ApiServerApp
from kubeflow_tpu.web.wsgi import serve

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _drive(ctl, predicate, *, timeout=90.0, what=""):
    """Reconcile-poll until the predicate holds (worker startup and
    status stamping are asynchronous — the controller converges on its
    resync requeue, exactly as it would in production)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        ctl.controller.run_until_idle()
        if predicate():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


def test_process_runtime_serves_rolls_and_reaps(tmp_path):
    api = FakeApiServer()
    server, _ = serve(ApiServerApp(api), host="127.0.0.1", port=0)
    url = f"http://127.0.0.1:{server.server_port}"
    router = Router()
    procs = ProcessReplicaRuntime(
        api, url, router=router, extra_env={"PYTHONPATH": REPO}
    )
    ctl = ServingDeploymentController(api, process_runtime=procs)
    rname = serving_api.replica_name("pfleet", 0)
    try:
        api.create(
            serving_api.make_serving_deployment(
                "pfleet", model="demo", replicas=1, runtime="process",
            )
        )

        def fleet_ready():
            dep = api.get(serving_api.KIND, "pfleet", "default")
            return dep.status.get("readyReplicas") == 1

        _drive(ctl, fleet_ready, what="process replica ready")
        # The worker advertised a real endpoint and the runtime put it
        # behind the router as an HttpReplica — predictions flow over
        # HTTP through the same router surface local replicas use.
        assert router.ready_names() == [rname]
        out = router.predict(np.zeros((2, 32, 32, 3), np.float32))
        assert np.asarray(out).shape == (2, 10)
        robj = api.get(serving_api.REPLICA_KIND, rname, "default")
        assert robj.status["pid"] == procs._procs[rname].pid
        first_pid = robj.status["pid"]

        # modelVersion bump: the controller pushes the new replica spec
        # through the object; the WORKER swaps the servable itself (the
        # process runtime has no roll surface on purpose).
        dep = api.get(serving_api.KIND, "pfleet", "default").thaw()
        dep.spec = {**dep.spec, "modelVersion": 5}
        api.update(dep)

        def rolled():
            status = api.get(
                serving_api.KIND, "pfleet", "default"
            ).status
            rows = status.get("replicas") or []
            return rows and rows[0]["version"] == 5 and rows[0]["ready"]

        _drive(ctl, rolled, what="worker self-roll to version 5")
        # Self-roll is a hot swap, not a respawn.
        assert procs._procs[rname].pid == first_pid

        api.delete(serving_api.KIND, "pfleet", "default")
        _drive(
            ctl,
            lambda: procs.names() == [] and router.ready_names() == [],
            what="teardown reaps the worker",
        )
        assert procs._procs == {}
    finally:
        procs.shutdown()
        server.shutdown()
