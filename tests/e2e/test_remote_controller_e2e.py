"""Distributed control plane e2e: the TpuJob operator in its own process.

Parent process = the "cluster": FakeApiServer behind the HTTP facade plus
the LocalPodRunner materializing pods as real OS processes. Child process
= the operator, connected only through HTTP, reconciling purely off the
watch stream (tests/e2e/controller_worker.py). This is the topology the
reference's controllers run in against a real apiserver
(`notebook_controller.go:516`); round 1 only had in-process controllers.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

from kubeflow_tpu.api import make_tpujob
from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.rbac import make_cluster_role, make_cluster_role_binding
from kubeflow_tpu.api.tokens import TokenRegistry, service_account
from kubeflow_tpu.api.tpujob import KIND
from kubeflow_tpu.runtime import LocalPodRunner
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.testing.apiserver_http import ApiServerApp, HttpApiClient
from kubeflow_tpu.web.wsgi import serve

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
CONTROLLER = os.path.join(REPO, "tests", "e2e", "controller_worker.py")
GANG_WORKER = os.path.join(REPO, "tests", "e2e", "gang_worker.py")


# Exactly what TpuJobController's reconcile touches — nothing more (the
# least-privilege RBAC the reference grants its controllers via
# `config/rbac/role.yaml` manifests; status is a distinct subresource).
CONTROLLER_RULES = [
    {"verbs": ["get", "list", "watch"], "resources": ["tpujobs"]},
    {"verbs": ["update"], "resources": ["tpujobs/status"]},
    {"verbs": ["get", "list", "watch", "create", "delete"],
     "resources": ["pods"]},
    {"verbs": ["get", "list", "watch", "create"], "resources": ["services"]},
    {"verbs": ["list"], "resources": ["nodes"]},
    {"verbs": ["create"], "resources": ["events"]},
]


def test_out_of_process_controller_runs_gang(tmp_path, tls_paths):
    api = FakeApiServer()
    tokens = TokenRegistry()
    ctl_user = service_account("kubeflow", "tpujob-controller")
    api.create(make_cluster_role("tpujob-controller", CONTROLLER_RULES))
    api.create(
        make_cluster_role_binding("tpujob-controller", "tpujob-controller",
                                  ctl_user)
    )
    # The production topology all the way: the cross-process credential
    # rides TLS with the platform CA pinned, never plaintext.
    server, _ = serve(
        ApiServerApp(api, tokens=tokens), host="127.0.0.1", port=0,
        tls=tls_paths,
    )
    base_url = f"https://127.0.0.1:{server.server_port}"

    # The secure boundary actually holds: no token → no write.
    with pytest.raises(PermissionError):
        HttpApiClient(base_url, token="", ca=tls_paths.ca_cert).create(
            new_resource("ConfigMap", "x", "default", spec={})
        )

    proc = subprocess.Popen(
        [sys.executable, CONTROLLER],
        env={
            **os.environ,
            "KFTPU_REPO": REPO,
            "KFTPU_APISERVER": base_url,
            # Least-privilege credential: the controller runs with its own
            # serviceaccount token, not cluster-admin.
            "KFTPU_TOKEN": tokens.issue(ctl_user),
            "KFTPU_CA": tls_paths.ca_cert,
        },
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    runner = LocalPodRunner(
        api,
        extra_env={"KFTPU_REPO": REPO},
        capture_dir=str(tmp_path / "logs"),
    )
    try:
        assert proc.stdout.readline().strip() == "controller ready"
        # The CR is created AFTER the controller's initial sync: from here
        # on, every reconcile in the child is watch-event-driven.
        api.create(
            make_tpujob(
                "remote",
                replicas=2,
                tpu_chips_per_worker=0,
                command=(sys.executable, GANG_WORKER),
            )
        )
        deadline = time.time() + 150
        phase = None
        while time.time() < deadline:
            runner.step()  # parent materializes pods; child reconciles
            phase = api.get(KIND, "remote").status.get("phase")
            if phase in ("Succeeded", "Failed"):
                break
            time.sleep(0.2)
    finally:
        runner.shutdown()
        proc.send_signal(signal.SIGTERM)
        try:
            out = proc.communicate(timeout=15)[0]
        except subprocess.TimeoutExpired:
            proc.kill()
            out = proc.communicate()[0]
        server.shutdown()

    logs = {
        p.name: p.read_text() for p in (tmp_path / "logs").glob("*.log")
    }
    assert phase == "Succeeded", (phase, out, logs)
    # The gang actually ran: both workers did a real cross-process psum.
    assert "psum ok" in logs.get("remote-worker-0.log", ""), logs
    assert "psum ok" in logs.get("remote-worker-1.log", ""), logs
    # The child operator wrote through the facade: its Events are visible
    # in the parent's store.
    reasons = {e.spec["reason"] for e in api.list("Event", "default")}
    assert "GangCreated" in reasons, reasons
