"""RL study soak: a StudyJob of real actor–learner trials under chaos.

The full stack is load-bearing at once — study controller suggesting
trials, TpuJob operator ganging them, LocalPodRunner exec'ing real
worker processes (`rl_trial_worker.py`), each worker running its own
serving-stack policy fleet and guarded `fit()` learner — while the
seeded `RLFaultSchedule` kills a different layer in each victim trial:
a serving replica (heal), the learner process (resume), a whole trial
pre-training (reschedule).

The gate is ZERO LOST STUDIES: the study must land Succeeded with every
trial scored, and `coverage()` — counted from worker-REPORTED evidence
only — must show every RL fault class actually fired. A kill the study
absorbed so smoothly the driver can't find its evidence counts as a
coverage failure, not a success.

`test_rl_soak_small` is the tier-1 fixed-seed variant; the nightly
(slow) variant is what `bench.py --workload rl` drives for
`rl_studies_per_hour`, honoring KFTPU_RL_SEED / KFTPU_RL_METRICS.
"""

import json
import os
import sys
import time

import pytest

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.study import KIND, ParameterSpec, StudySpec
from kubeflow_tpu.controllers.study import StudyController, trial_name
from kubeflow_tpu.controllers.tpujob import TpuJobController
from kubeflow_tpu.runtime import LocalPodRunner
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.testing.apiserver_http import ApiServerApp
from kubeflow_tpu.testing.chaos import RL_FAULT_CLASSES, RLFaultSchedule
from kubeflow_tpu.web.wsgi import serve

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
WORKER = os.path.join(REPO, "tests", "e2e", "rl_trial_worker.py")


def _run_rl_study_soak(
    tmp_path,
    *,
    seed: int,
    trials: int = 3,
    steps: int = 12,
    publish_every: int = 4,
    deadline_s: float = 240.0,
) -> dict:
    """One chaos-gated RL study end to end; returns the soak metrics."""
    schedule = RLFaultSchedule(seed, trials=trials)
    api = FakeApiServer()
    server, _ = serve(ApiServerApp(api), host="127.0.0.1", port=0)
    study_ctl = StudyController(api)
    job_ctl = TpuJobController(api)
    runner = LocalPodRunner(
        api,
        extra_env={
            "KFTPU_REPO": REPO,
            "KFTPU_APISERVER": f"http://127.0.0.1:{server.server_port}",
            "KFTPU_RL_CHAOS_SEED": str(seed),
            "KFTPU_RL_TRIALS": str(trials),
            "KFTPU_RL_STEPS": str(steps),
            "KFTPU_RL_PUBLISH_EVERY": str(publish_every),
            "KFTPU_RL_WORKDIR": str(tmp_path / "rl"),
        },
        capture_dir=str(tmp_path / "logs"),
    )

    spec = StudySpec(
        parameters=(
            ParameterSpec(
                "lr", "double", min=0.02, max=0.08, grid_points=trials
            ),
        ),
        objective_metric="return",
        goal="maximize",
        algorithm="grid",
        parallelism=2,
        trial_template={
            "replicas": 1,
            "image": "local",
            "command": [sys.executable, WORKER],
            "args": ["--lr", "${trialParameters.lr}"],
            "tpu": {"chipsPerWorker": 0},
            # Every fault class costs its victim trial one gang restart
            # (SIGKILL -> whole-gang restart is the operator's contract).
            "maxRestarts": 2,
        },
    )
    api.create(new_resource(KIND, "rl-sweep", "default", spec=spec.to_dict()))

    t0 = time.perf_counter()
    deadline = time.time() + deadline_s
    try:
        while time.time() < deadline:
            study_ctl.controller.run_until_idle()
            job_ctl.controller.run_until_idle()
            runner.step()
            phase = api.get(KIND, "rl-sweep").status.get("phase")
            if phase in ("Succeeded", "Failed"):
                break
            time.sleep(0.1)
    finally:
        runner.shutdown()
        server.shutdown()
    elapsed = time.perf_counter() - t0

    study = api.get(KIND, "rl-sweep")
    # ZERO lost studies: terminal, Succeeded, every trial scored.
    assert study.status.get("phase") == "Succeeded", study.status
    rows = study.status.get("trials") or []
    assert len(rows) == trials, rows
    assert all("objective" in r for r in rows), rows

    # Coverage from worker-reported evidence only.
    returns = []
    publish_latency = 0.0
    for idx in range(trials):
        trial = api.get("TpuJob", trial_name("rl-sweep", idx), "default")
        observation = trial.status.get("observation") or {}
        returns.append(float(observation.get("return", 0.0)))
        publish_latency = max(
            publish_latency, float(observation.get("publish_latency_s", 0.0))
        )
        for cls in RL_FAULT_CLASSES:
            if observation.get(f"fault_{cls}"):
                schedule.mark_injected(cls)
    coverage = schedule.coverage()
    missing = [c for c in RL_FAULT_CLASSES if coverage[c] < 1]
    assert not missing, (
        f"fault classes with no worker-reported evidence: {missing} "
        f"(coverage={coverage}, plan={schedule.plan})"
    )

    return {
        "seed": seed,
        "trials": trials,
        "elapsed_seconds": elapsed,
        "studies_per_hour": 3600.0 / elapsed,
        "coverage": coverage,
        "returns": returns,
        "publish_latency_s": publish_latency,
        "best_return": study.status["bestTrial"]["objective"],
    }


def test_rl_soak_small(tmp_path):
    """Tier-1: fixed seed, three trials — one victim per fault class."""
    m = _run_rl_study_soak(tmp_path, seed=7, trials=3)
    assert m["best_return"] > 0, m


@pytest.mark.slow
def test_rl_soak_nightly(tmp_path):
    """The bench-driven variant (`bench.py --workload rl`): seed from
    KFTPU_RL_SEED (printed-seed repro contract), metrics out through
    KFTPU_RL_METRICS."""
    seed = int(os.environ.get("KFTPU_RL_SEED", "7"))
    m = _run_rl_study_soak(
        tmp_path, seed=seed, trials=4, steps=18, publish_every=6,
        deadline_s=420.0,
    )
    path = os.environ.get("KFTPU_RL_METRICS")
    if path:
        with open(path, "w") as f:
            json.dump(m, f)
