"""Spawn-path E2E — the `testing/test_jwa.py:32-300` analog.

The reference drives the spawner UI with Selenium against a live
cluster. This image ships no browser or JS engine, so the equivalent
here is two-layered:

1. `test_spawn_path_over_live_servers` boots the REAL platform-in-a-box
   process (`python -m kubeflow_tpu.apps`: all web apps + controllers +
   pod materializer as one server process) and walks the full user
   journey over live HTTP — issuing exactly the requests the SPA issues
   (the frontend drift gate in tests/test_frontends.py pins that the
   SPA's calls and these routes agree): register workgroup → spawner
   config → create notebook → poll the row to Running (the Poller's
   endpoint) → connect URL → cull (stop) → restart → snapshot →
   delete.
2. `test_spa_module_imports_resolve` is the no-JS-engine stand-in for
   "the page's JS loads": every name a page imports from ui.js must be
   exported there — the breakage class a browser smoke test catches
   first (a bad import kills the whole module).
"""

import json
import os
import pathlib
import re
import subprocess
import sys
import time
import urllib.error
import urllib.request

REPO = pathlib.Path(__file__).resolve().parent.parent.parent
STATIC = REPO / "kubeflow_tpu" / "apps" / "static"
USER = "alice@corp.com"


def _req(url, body=None, method=None, token=None, ca=None):
    data = json.dumps(body).encode() if body is not None else None
    headers = {"Content-Type": "application/json"} if data else {}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    r = urllib.request.Request(url, data=data, method=method, headers=headers)
    ctx = None
    if ca:
        from kubeflow_tpu.web import tls as tlsmod

        ctx = tlsmod.client_context(ca)
    with urllib.request.urlopen(r, timeout=20, context=ctx) as resp:
        raw = resp.read()
        return resp.status, json.loads(raw) if raw.strip() else {}


def _read_boot_secrets(proc, timeout=30):
    """The launcher prints the minted facade credential AND the CA path
    at boot (secure-and-TLS by default); scrape them like an operator
    would: (token, ca_path)."""
    deadline = time.time() + timeout
    token = ca = None
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            time.sleep(0.1)
            continue
        m = re.match(r"apiserver admin token: (\S+)", line)
        if m:
            token = m.group(1)
        m = re.match(r"apiserver CA .*: (\S+)", line)
        if m:
            ca = m.group(1)
        if token and ca:
            return token, ca
    raise TimeoutError("launcher never printed the token + CA lines")


def _wait(pred, timeout=90, interval=0.5):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        ok, last = pred()
        if ok:
            return last
        time.sleep(interval)
    raise TimeoutError(f"condition not reached; last={last!r}")


def test_spawn_path_over_live_servers(tmp_path):
    port = 18400
    proc = subprocess.Popen(
        [sys.executable, "-m", "kubeflow_tpu.apps",
         "--port-base", str(port), "--anonymous", USER],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO, env={**os.environ},
    )
    dash = f"http://127.0.0.1:{port}"
    jup = f"http://127.0.0.1:{port + 2}"
    try:
        token, ca = _read_boot_secrets(proc)
        _wait(lambda: _probe_up(f"{dash}/healthz"), timeout=60)

        # 1. Fresh user: no workgroup yet → register (dashboard flow).
        _, info = _req(f"{dash}/api/workgroup/env-info")
        assert info["user"] == USER
        if not info.get("hasWorkgroup"):
            _req(f"{dash}/api/workgroup/create", body={}, method="POST")
        ns = _wait(lambda: _ns_ready(dash))

        # 2. Spawner page boot: config + namespaces (the selector).
        _, cfg = _req(f"{jup}/api/config")
        assert cfg["config"]["image"]["options"]
        _, nss = _req(f"{jup}/api/namespaces")
        assert ns in nss["namespaces"]

        # 3. Spawn a notebook with a new workspace volume — the exact
        #    body jupyter.html posts.
        _req(
            f"{jup}/api/namespaces/{ns}/notebooks",
            method="POST",
            body={
                "name": "my-nb",
                "image": cfg["config"]["image"]["options"][0],
                "cpu": "1.0",
                "memory": "2Gi",
                "tpu": "none",
                "workspaceVolume": {
                    "type": "New", "name": "{name}-workspace",
                    "size": "1Gi", "mountPath": "/home/jovyan",
                    "accessMode": "ReadWriteOnce",
                },
                "configurations": [],
            },
        )

        def row(status=None):
            _, data = _req(f"{jup}/api/namespaces/{ns}/notebooks")
            rows = {n["name"]: n for n in data["notebooks"]}
            nb = rows.get("my-nb")
            return (nb is not None and (status is None
                                        or nb["status"] == status), nb)

        # 4. The poller's view reaches Running (materializer backs it).
        nb = _wait(lambda: row("running"))
        # The workspace PVC is mounted (the admin config may add more,
        # e.g. the dshm emptyDir).
        assert "my-nb-workspace" in nb["volumes"], nb

        # 5. Connect URL routes: the Connect button opens
        #    /notebook/{ns}/my-nb/, which the controller's
        #    VirtualService carries (generateVirtualService parity,
        #    notebook_controller.go:379) — read it off the facade.
        facade = f"https://127.0.0.1:{port + 4}"
        # The facade is secure AND TLS: plaintext is a handshake error,
        # no token → 401, and the minted admin token (over TLS with the
        # pinned CA) reads the controller-created VirtualService.
        try:
            _req(f"http://127.0.0.1:{port + 4}/healthz")
        except urllib.error.HTTPError:
            # An HTTP status IS a plaintext response — exactly the
            # regression this guards against (HTTPError is an OSError
            # subclass, so it must be caught before the refusal case).
            raise AssertionError("facade served plaintext HTTP")
        except OSError:
            pass  # handshake-level refusal — the TLS port stayed TLS
        else:
            raise AssertionError("facade answered plaintext HTTP")
        try:
            _req(f"{facade}/apis/VirtualService/{ns}/notebook-{ns}-my-nb",
                 ca=ca)
            raise AssertionError("facade served an unauthenticated read")
        except urllib.error.HTTPError as e:
            assert e.code == 401, e.code
        _, vs = _req(
            f"{facade}/apis/VirtualService/{ns}/notebook-{ns}-my-nb",
            token=token, ca=ca,
        )
        assert f"/notebook/{ns}/my-nb/" in json.dumps(vs["spec"]), vs

        # 6. Cull: stop → row shows stopped; restart → running again.
        _req(f"{jup}/api/namespaces/{ns}/notebooks/my-nb",
             method="PATCH", body={"stopped": True})
        _wait(lambda: row("stopped"))
        _req(f"{jup}/api/namespaces/{ns}/notebooks/my-nb",
             method="PATCH", body={"stopped": False})
        _wait(lambda: row("running"))

        # 7. Snapshot the workspace (the row's Snapshot action), then
        #    delete the notebook.
        _req(f"{jup}/api/namespaces/{ns}/snapshots", method="POST",
             body={"pvc": "my-nb-workspace"})
        _, snaps = _req(f"{jup}/api/namespaces/{ns}/snapshots")
        assert any(
            s["source"] == "my-nb-workspace" for s in snaps["snapshots"]
        )
        _req(f"{jup}/api/namespaces/{ns}/notebooks/my-nb",
             method="DELETE")
        _wait(lambda: (row()[1] is None, row()[1]))
    finally:
        proc.kill()
        proc.wait(timeout=10)


def _probe_up(url):
    try:
        return _req(url)[0] == 200, None
    except (urllib.error.URLError, ConnectionError) as e:
        return False, str(e)


def _ns_ready(dash):
    _, info = _req(f"{dash}/api/workgroup/env-info")
    nss = info.get("namespaces") or []
    return (bool(nss), nss[0] if nss else None)


def test_spa_module_imports_resolve():
    """No JS engine in CI, so pin the first thing a browser would catch:
    every symbol a page imports from ./ui.js exists as an export."""
    exported = set(
        re.findall(
            r"export\s+(?:async\s+)?(?:function|class|const|let)\s+"
            r"([A-Za-z_$][\w$]*)",
            (STATIC / "ui.js").read_text(),
        )
    )
    assert exported, "ui.js exports nothing?"
    for page in ("jupyter.html", "tensorboards.html"):
        text = (STATIC / page).read_text()
        for block in re.findall(
            r"import\s*\{([^}]+)\}\s*from\s*\"\./ui\.js\"", text
        ):
            for name in re.split(r"[,\s]+", block.strip()):
                if name:
                    assert name in exported, (
                        f"{page} imports {name!r} which ui.js does not "
                        f"export (exports: {sorted(exported)})"
                    )
