"""Katib-analog E2E, fully in-process (the reference needed a live GKE
cluster for this — `testing/katib_studyjob_test.py`):

Study CR → StudyController suggests trials → TpuJob operator gangs them →
local runner execs real trial processes → each reports its objective over
the HTTP apiserver facade → controller harvests observations, spawns the
next wave, and lands on Succeeded with the true best trial.
"""

import os
import sys
import time

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.study import KIND, ParameterSpec, StudySpec
from kubeflow_tpu.controllers.study import StudyController
from kubeflow_tpu.controllers.tpujob import TpuJobController
from kubeflow_tpu.runtime import LocalPodRunner
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.testing.apiserver_http import ApiServerApp
from kubeflow_tpu.web.wsgi import serve

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
WORKER = os.path.join(REPO, "tests", "e2e", "trial_worker.py")


def test_study_end_to_end(tmp_path):
    api = FakeApiServer()
    server, _ = serve(ApiServerApp(api), host="127.0.0.1", port=0)
    study_ctl = StudyController(api)
    job_ctl = TpuJobController(api)
    runner = LocalPodRunner(
        api,
        extra_env={
            "KFTPU_REPO": REPO,
            "KFTPU_APISERVER": f"http://127.0.0.1:{server.server_port}",
        },
        capture_dir=str(tmp_path / "logs"),
    )

    spec = StudySpec(
        parameters=(
            ParameterSpec("lr", "double", min=0.01, max=0.09, grid_points=3),
        ),
        objective_metric="loss",
        goal="minimize",
        algorithm="grid",
        parallelism=2,
        trial_template={
            "replicas": 1,
            "image": "local",
            "command": [sys.executable, WORKER],
            "args": ["--lr", "${trialParameters.lr}"],
            "tpu": {"chipsPerWorker": 0},
            "maxRestarts": 0,
        },
    )
    api.create(new_resource(KIND, "sweep", "default", spec=spec.to_dict()))

    deadline = time.time() + 150
    try:
        while time.time() < deadline:
            study_ctl.controller.run_until_idle()
            job_ctl.controller.run_until_idle()
            runner.step()
            phase = api.get(KIND, "sweep").status.get("phase")
            if phase in ("Succeeded", "Failed"):
                break
            time.sleep(0.2)
    finally:
        runner.shutdown()
        server.shutdown()

    study = api.get(KIND, "sweep")
    assert study.status.get("phase") == "Succeeded", study.status
    # grid over lr = {0.01, 0.05, 0.09}; loss=(lr-0.05)^2 minimized at 0.05.
    best = study.status["bestTrial"]
    assert abs(best["objective"]) < 1e-12, best
    assert len(study.status["trials"]) == 3
    assert study.status["conditions"][-1]["type"] == "Completed"


def test_early_stopping_prunes_diverging_trial_mid_run(tmp_path):
    """VERDICT-#10 e2e: real trial processes report learning curves over
    the facade; the diverging trial would sleep 600s — far past the test
    budget — so the study can only complete if early stopping prunes it
    MID-RUN (CR deleted → pod runner kills the live process)."""
    CURVE_WORKER = os.path.join(REPO, "tests", "e2e",
                                "curve_trial_worker.py")
    api = FakeApiServer()
    server, _ = serve(ApiServerApp(api), host="127.0.0.1", port=0)
    study_ctl = StudyController(api)
    job_ctl = TpuJobController(api)
    runner = LocalPodRunner(
        api,
        extra_env={
            "KFTPU_REPO": REPO,
            "KFTPU_APISERVER": f"http://127.0.0.1:{server.server_port}",
        },
        capture_dir=str(tmp_path / "logs"),
    )

    # Exactly ONE diverging config (>= 1.0): the conservative
    # strictly-worst-than-all-peers rule prunes stragglers one at a time,
    # so a tie of two identical diverging curves would be kept (by
    # design — bulk elimination belongs to halving's rung boundaries).
    spec = StudySpec(
        parameters=(
            ParameterSpec("lr", "categorical", values=(0.02, 0.08, 2.0)),
        ),
        objective_metric="loss",
        goal="minimize",
        algorithm="grid",
        max_trials=3,
        parallelism=3,
        early_stopping={"minSteps": 2, "minPeers": 2},
        trial_template={
            "replicas": 1,
            "image": "local",
            "command": [sys.executable, CURVE_WORKER],
            "args": ["--lr", "${trialParameters.lr}"],
            "tpu": {"chipsPerWorker": 0},
            "maxRestarts": 0,
        },
    )
    api.create(new_resource(KIND, "es-sweep", "default", spec=spec.to_dict()))

    deadline = time.time() + 150
    try:
        while time.time() < deadline:
            study_ctl.controller.run_until_idle()
            job_ctl.controller.run_until_idle()
            runner.step()
            phase = api.get(KIND, "es-sweep").status.get("phase")
            if phase in ("Succeeded", "Failed"):
                break
            time.sleep(0.2)
    finally:
        runner.shutdown()
        server.shutdown()

    study = api.get(KIND, "es-sweep")
    assert study.status.get("phase") == "Succeeded", study.status
    pruned = study.status.get("prunedTrials", {})
    # lr=2.0 diverges, is strictly worse than both healthy peers, and is
    # pruned mid-run — its process (otherwise sleeping 600s) was killed,
    # or the study could not have finished inside the deadline.
    assert pruned, study.status
    pruned_lrs = {e["assignment"]["lr"] for e in pruned.values()}
    assert pruned_lrs == {2.0}, pruned
    best = study.status["bestTrial"]
    assert abs(best["objective"] - (0.08 - 0.05) ** 2) < 1e-9, best
