"""Kill-and-resume training soak: real `fit()` runs through a seeded
training fault schedule, asserted against an uninterrupted baseline.

Topology: this driver runs `resilience_worker.py` incarnations against
ONE checkpoint directory while consuming a `TrainFaultSchedule`
(`kubeflow_tpu/testing/chaos.py`):

- process faults: the worker self-delivers SIGKILL between steps /
  SIGTERM mid-step at the scheduled position (fit must exit `Preempted`
  after an emergency save for the latter);
- storage faults: between incarnations the driver truncates or
  byte-flips the newest checkpoint, or garbles its manifest —
  `restore_latest` must quarantine and fall back, never crash or load
  torn state;
- data faults: scheduled loss-spike batches (identical in the baseline
  run) the AnomalyGuard must skip on device.

Asserts, from the workers' JSONL traces:

1. PARITY — the chaos run's final params (L1) and final loss equal the
   uninterrupted baseline's: kills, corruption and preemption cost
   recomputed steps, never a different model.
2. ZERO REPEATED/SKIPPED BATCHES — the authoritative (step -> data
   position) mapping is the identity over every step, reconstructed
   across incarnations from the resumable-data state.
3. COVERAGE — every training fault class actually fired.
4. The guard skipped exactly the scheduled spikes (counted device-side,
   survived checkpoint/restore).

Reproducibility: the schedule is a pure function of the printed seed
(KFTPU_RESILIENCE_SEED overrides), matching the chaos-soak convention.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from kubeflow_tpu.testing.chaos import (
    TRAIN_FAULT_CLASSES,
    TrainFaultSchedule,
    apply_checkpoint_fault,
)

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
WORKER = os.path.join(REPO, "tests", "e2e", "resilience_worker.py")

DEFAULT_SEED = 20260804


def _seed() -> int:
    return int(os.environ.get("KFTPU_RESILIENCE_SEED") or DEFAULT_SEED)


def _run_worker(
    *, ckpt_dir, trace_file, incarnation, total_steps, save_interval,
    seed, spikes, crash=None,
) -> subprocess.CompletedProcess:
    env = {
        **os.environ,
        "KFTPU_REPO": REPO,
        "KFTPU_CKPT_DIR": str(ckpt_dir),
        "KFTPU_TRACE_FILE": str(trace_file),
        "KFTPU_INCARNATION": str(incarnation),
        "KFTPU_TOTAL_STEPS": str(total_steps),
        "KFTPU_SAVE_INTERVAL": str(save_interval),
        "KFTPU_DATA_SEED": str(seed),
        "KFTPU_SPIKE_STEPS": ",".join(str(s) for s in spikes),
    }
    env.pop("KFTPU_CRASH_STEP", None)
    env.pop("KFTPU_CRASH_SIGNAL", None)
    if crash is not None:
        env["KFTPU_CRASH_STEP"] = str(crash.at_step)
        env["KFTPU_CRASH_SIGNAL"] = crash.cls
    return subprocess.run(
        [sys.executable, WORKER], env=env, capture_output=True, text=True,
        timeout=240,
    )


def _read_trace(trace_file) -> list[dict]:
    with open(trace_file) as f:
        return [json.loads(line) for line in f if line.strip()]


def _final_summary(events: list[dict]) -> dict:
    done = [e for e in events if e["event"] == "done"]
    assert len(done) == 1, done
    return done[0]


def _run_soak(
    tmp_path, seed: int, *, total_steps, save_interval, faults_per_class,
    deadline,
) -> dict:
    repro = (
        f"[resilience seed={seed}; reproduce with "
        f"KFTPU_RESILIENCE_SEED={seed}]"
    )
    print(f"resilience soak starting {repro}")
    schedule = TrainFaultSchedule(
        seed, total_steps, save_interval=save_interval,
        faults_per_class=faults_per_class,
    )
    # The repro contract itself: same seed -> identical plan.
    assert TrainFaultSchedule(
        seed, total_steps, save_interval=save_interval,
        faults_per_class=faults_per_class,
    ).plan == schedule.plan, repro
    spikes = schedule.spike_steps
    common = dict(
        total_steps=total_steps, save_interval=save_interval,
        seed=seed, spikes=spikes,
    )

    # -- uninterrupted baseline (same data, same spikes, no faults) -----
    base_trace = tmp_path / "baseline.jsonl"
    proc = _run_worker(
        ckpt_dir=tmp_path / "ckpt-base", trace_file=base_trace,
        incarnation=0, **common,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr, repro)
    baseline = _final_summary(_read_trace(base_trace))
    assert baseline["skips"] == len(spikes), (baseline, repro)

    # -- chaos run: one incarnation per crash fault, then completion ----
    ckpt_dir = tmp_path / "ckpt"
    trace_file = tmp_path / "chaos.jsonl"
    t0 = time.monotonic()
    incarnation = 0
    crashes = list(schedule.crash_faults)
    while True:
        assert time.monotonic() - t0 < deadline, (
            f"soak missed its deadline at incarnation {incarnation} "
            f"{schedule} {repro}"
        )
        fault = crashes[incarnation] if incarnation < len(crashes) else None
        proc = _run_worker(
            ckpt_dir=ckpt_dir, trace_file=trace_file,
            incarnation=incarnation, crash=fault, **common,
        )
        if fault is None:
            assert proc.returncode == 0, (proc.stdout, proc.stderr, repro)
            break
        if fault.cls == "kill":
            assert proc.returncode == -9, (
                f"expected SIGKILL death at step {fault.at_step}, got rc="
                f"{proc.returncode}", proc.stdout, proc.stderr, repro,
            )
        else:  # sigterm: fit must exit with the distinct Preempted result
            assert proc.returncode == 75, (
                f"expected Preempted exit (75) at step {fault.at_step}, "
                f"got rc={proc.returncode}", proc.stdout, proc.stderr,
                repro,
            )
        schedule.mark_injected(fault)
        for storage in schedule.storage_after(incarnation):
            desc = apply_checkpoint_fault(
                ckpt_dir, storage.cls, offset=storage.offset
            )
            assert desc is not None, (
                f"storage fault found nothing to damage: {storage} {repro}"
            )
            print(f"applied {desc} {repro}")
            schedule.mark_injected(storage)
        incarnation += 1
    elapsed = time.monotonic() - t0

    events = _read_trace(trace_file)
    final = _final_summary(events)

    # -- the guard skipped exactly the scheduled spikes -----------------
    assert final["skips"] == len(spikes), (final, repro)
    for fault in schedule.spike_faults:
        schedule.mark_injected(fault)

    # -- coverage gate: every training fault class actually fired -------
    coverage = schedule.coverage()
    assert all(coverage[c] >= 1 for c in TRAIN_FAULT_CLASSES), (
        f"incomplete fault coverage: {coverage} {repro}"
    )

    # -- parity with the uninterrupted baseline -------------------------
    np.testing.assert_allclose(
        final["params_l1"], baseline["params_l1"], rtol=1e-6,
        err_msg=f"final params diverged from the uninterrupted run {repro}",
    )
    np.testing.assert_allclose(
        final["final_loss"], baseline["final_loss"], rtol=1e-5,
        err_msg=f"final loss diverged from the uninterrupted run {repro}",
    )

    # -- zero repeated/skipped batches ----------------------------------
    # Authoritative (step -> position): later incarnations overwrite the
    # steps they legitimately redo after a rollback-to-checkpoint; the
    # final mapping must be the identity (position p consumed by step p,
    # each exactly once along the applied trajectory).
    steps = [e for e in events if e["event"] == "step"]
    mapping: dict[int, int] = {}
    for e in steps:
        mapping[e["step"]] = e["position"]
    assert mapping == {s: s for s in range(1, total_steps + 1)}, (
        f"batch sequence diverged (repeated or skipped data) {repro}: "
        f"{sorted(set(range(1, total_steps + 1)) ^ set(mapping))[:10]}"
    )
    # Each resumed incarnation starts exactly one past its restore point
    # (no silent fast-forward, no replay of applied steps).
    boots: dict[int, float] = {}
    first_step: dict[int, dict] = {}
    last_step: dict[int, int] = {}
    for e in events:
        inc = e["incarnation"]
        if e["event"] == "boot":
            boots[inc] = e["t"]
        elif e["event"] == "step":
            first_step.setdefault(inc, e)
            last_step[inc] = e["step"]
    for inc in range(1, incarnation + 1):
        assert first_step[inc]["step"] <= last_step[inc - 1] + 1, (
            f"incarnation {inc} skipped ahead: first step "
            f"{first_step[inc]['step']} after {last_step[inc - 1]} {repro}"
        )

    # -- resilience metrics ---------------------------------------------
    executed = len(steps)
    lost = executed - total_steps
    kills = len(crashes)
    recovery = [
        first_step[inc]["t"] - boots[inc]
        for inc in range(1, incarnation + 1)
    ]
    metrics = {
        "seed": seed,
        "goodput": total_steps / executed,
        "steps_lost_per_kill": lost / kills,
        "recovery_seconds": sum(recovery) / len(recovery),
        "kills": kills,
        "incarnations": incarnation + 1,
        "elapsed_seconds": elapsed,
        "coverage": coverage,
    }
    print(f"resilience soak converged: {json.dumps(metrics)} {repro}")
    out = os.environ.get("KFTPU_RESILIENCE_METRICS")
    if out:
        with open(out, "w") as f:
            json.dump(metrics, f)
    return metrics


def test_resilience_soak_kill_and_resume(tmp_path):
    """Tier-1 soak: the full fault matrix at its smallest size, fixed
    seed for determinism."""
    metrics = _run_soak(
        tmp_path, _seed(),
        total_steps=32, save_interval=4, faults_per_class=1,
        deadline=300.0,
    )
    assert 0.0 < metrics["goodput"] <= 1.0


@pytest.mark.slow
def test_resilience_soak_nightly(tmp_path):
    """The long soak (`bench.py --workload resilience` / nightly CI): a
    denser schedule over a longer run. Prints its seed so any failure
    reproduces with KFTPU_RESILIENCE_SEED=<seed>."""
    seed = int(
        os.environ.get("KFTPU_RESILIENCE_SEED") or (time.time_ns() % 2**31)
    )
    _run_soak(
        tmp_path, seed,
        total_steps=80, save_interval=5, faults_per_class=2,
        deadline=900.0,
    )
