"""Kill-and-resume training soak: real `fit()` runs through a seeded
training fault schedule, asserted against an uninterrupted baseline.

Topology: this driver runs `resilience_worker.py` incarnations against
ONE checkpoint directory while consuming a `TrainFaultSchedule`
(`kubeflow_tpu/testing/chaos.py`):

- process faults: the worker self-delivers SIGKILL between steps /
  SIGTERM mid-step at the scheduled position (fit must exit `Preempted`
  after an emergency save for the latter);
- storage faults: between incarnations the driver truncates or
  byte-flips the newest checkpoint, or garbles its manifest —
  `restore_latest` must quarantine and fall back, never crash or load
  torn state;
- data faults: scheduled loss-spike batches (identical in the baseline
  run) the AnomalyGuard must skip on device.

Asserts, from the workers' JSONL traces:

1. PARITY — the chaos run's final params (L1) and final loss equal the
   uninterrupted baseline's: kills, corruption and preemption cost
   recomputed steps, never a different model.
2. ZERO REPEATED/SKIPPED BATCHES — the authoritative (step -> data
   position) mapping is the identity over every step, reconstructed
   across incarnations from the resumable-data state.
3. COVERAGE — every training fault class actually fired.
4. The guard skipped exactly the scheduled spikes (counted device-side,
   survived checkpoint/restore).

Reproducibility: the schedule is a pure function of the printed seed
(KFTPU_RESILIENCE_SEED overrides), matching the chaos-soak convention.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from kubeflow_tpu.testing.chaos import (
    ELASTIC_FAULT_CLASSES,
    TRAIN_FAULT_CLASSES,
    TrainFaultSchedule,
    apply_checkpoint_fault,
)

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
WORKER = os.path.join(REPO, "tests", "e2e", "resilience_worker.py")

DEFAULT_SEED = 20260804


def _seed() -> int:
    return int(os.environ.get("KFTPU_RESILIENCE_SEED") or DEFAULT_SEED)


def _worker_env(
    *, ckpt_dir, trace_file, incarnation, total_steps, save_interval,
    seed, spikes, crash=None, dp=None, elastic_plan=None,
) -> dict:
    env = {
        **os.environ,
        "KFTPU_REPO": REPO,
        "KFTPU_CKPT_DIR": str(ckpt_dir),
        "KFTPU_TRACE_FILE": str(trace_file),
        "KFTPU_INCARNATION": str(incarnation),
        "KFTPU_TOTAL_STEPS": str(total_steps),
        "KFTPU_SAVE_INTERVAL": str(save_interval),
        "KFTPU_DATA_SEED": str(seed),
        "KFTPU_SPIKE_STEPS": ",".join(str(s) for s in spikes),
    }
    for stale in (
        "KFTPU_CRASH_STEP", "KFTPU_CRASH_SIGNAL", "KFTPU_DP",
        "KFTPU_ELASTIC_PLAN", "KFTPU_RESIZE_FILE", "KFTPU_ACK_FILE",
        "KFTPU_STEP_DELAY",
    ):
        env.pop(stale, None)
    if crash is not None:
        env["KFTPU_CRASH_STEP"] = str(crash.at_step)
        env["KFTPU_CRASH_SIGNAL"] = crash.cls
    if dp is not None:
        env["KFTPU_DP"] = str(dp)
    if elastic_plan is not None:
        env["KFTPU_ELASTIC_PLAN"] = json.dumps(list(elastic_plan))
    return env


def _run_worker(
    *, ckpt_dir, trace_file, incarnation, total_steps, save_interval,
    seed, spikes, crash=None, dp=None, elastic_plan=None,
) -> subprocess.CompletedProcess:
    env = _worker_env(
        ckpt_dir=ckpt_dir, trace_file=trace_file, incarnation=incarnation,
        total_steps=total_steps, save_interval=save_interval, seed=seed,
        spikes=spikes, crash=crash, dp=dp, elastic_plan=elastic_plan,
    )
    return subprocess.run(
        [sys.executable, WORKER], env=env, capture_output=True, text=True,
        timeout=240,
    )


def _read_trace(trace_file) -> list[dict]:
    with open(trace_file) as f:
        return [json.loads(line) for line in f if line.strip()]


def _final_summary(events: list[dict]) -> dict:
    done = [e for e in events if e["event"] == "done"]
    assert len(done) == 1, done
    return done[0]


def _run_soak(
    tmp_path, seed: int, *, total_steps, save_interval, faults_per_class,
    deadline,
) -> dict:
    repro = (
        f"[resilience seed={seed}; reproduce with "
        f"KFTPU_RESILIENCE_SEED={seed}]"
    )
    print(f"resilience soak starting {repro}")
    schedule = TrainFaultSchedule(
        seed, total_steps, save_interval=save_interval,
        faults_per_class=faults_per_class,
    )
    # The repro contract itself: same seed -> identical plan.
    assert TrainFaultSchedule(
        seed, total_steps, save_interval=save_interval,
        faults_per_class=faults_per_class,
    ).plan == schedule.plan, repro
    spikes = schedule.spike_steps
    common = dict(
        total_steps=total_steps, save_interval=save_interval,
        seed=seed, spikes=spikes,
    )

    # -- uninterrupted baseline (same data, same spikes, no faults) -----
    base_trace = tmp_path / "baseline.jsonl"
    proc = _run_worker(
        ckpt_dir=tmp_path / "ckpt-base", trace_file=base_trace,
        incarnation=0, **common,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr, repro)
    baseline = _final_summary(_read_trace(base_trace))
    assert baseline["skips"] == len(spikes), (baseline, repro)

    # -- chaos run: one incarnation per crash fault, then completion ----
    ckpt_dir = tmp_path / "ckpt"
    trace_file = tmp_path / "chaos.jsonl"
    t0 = time.monotonic()
    incarnation = 0
    crashes = list(schedule.crash_faults)
    while True:
        assert time.monotonic() - t0 < deadline, (
            f"soak missed its deadline at incarnation {incarnation} "
            f"{schedule} {repro}"
        )
        fault = crashes[incarnation] if incarnation < len(crashes) else None
        proc = _run_worker(
            ckpt_dir=ckpt_dir, trace_file=trace_file,
            incarnation=incarnation, crash=fault, **common,
        )
        if fault is None:
            assert proc.returncode == 0, (proc.stdout, proc.stderr, repro)
            break
        if fault.cls == "kill":
            assert proc.returncode == -9, (
                f"expected SIGKILL death at step {fault.at_step}, got rc="
                f"{proc.returncode}", proc.stdout, proc.stderr, repro,
            )
        else:  # sigterm: fit must exit with the distinct Preempted result
            assert proc.returncode == 75, (
                f"expected Preempted exit (75) at step {fault.at_step}, "
                f"got rc={proc.returncode}", proc.stdout, proc.stderr,
                repro,
            )
        schedule.mark_injected(fault)
        for storage in schedule.storage_after(incarnation):
            desc = apply_checkpoint_fault(
                ckpt_dir, storage.cls, offset=storage.offset
            )
            assert desc is not None, (
                f"storage fault found nothing to damage: {storage} {repro}"
            )
            print(f"applied {desc} {repro}")
            schedule.mark_injected(storage)
        incarnation += 1
    elapsed = time.monotonic() - t0

    events = _read_trace(trace_file)
    final = _final_summary(events)

    # -- the guard skipped exactly the scheduled spikes -----------------
    assert final["skips"] == len(spikes), (final, repro)
    for fault in schedule.spike_faults:
        schedule.mark_injected(fault)

    # -- coverage gate: every training fault class actually fired -------
    coverage = schedule.coverage()
    assert all(coverage[c] >= 1 for c in TRAIN_FAULT_CLASSES), (
        f"incomplete fault coverage: {coverage} {repro}"
    )

    # -- parity with the uninterrupted baseline -------------------------
    np.testing.assert_allclose(
        final["params_l1"], baseline["params_l1"], rtol=1e-6,
        err_msg=f"final params diverged from the uninterrupted run {repro}",
    )
    np.testing.assert_allclose(
        final["final_loss"], baseline["final_loss"], rtol=1e-5,
        err_msg=f"final loss diverged from the uninterrupted run {repro}",
    )

    # -- zero repeated/skipped batches ----------------------------------
    # Authoritative (step -> position): later incarnations overwrite the
    # steps they legitimately redo after a rollback-to-checkpoint; the
    # final mapping must be the identity (position p consumed by step p,
    # each exactly once along the applied trajectory).
    steps = [e for e in events if e["event"] == "step"]
    mapping: dict[int, int] = {}
    for e in steps:
        mapping[e["step"]] = e["position"]
    assert mapping == {s: s for s in range(1, total_steps + 1)}, (
        f"batch sequence diverged (repeated or skipped data) {repro}: "
        f"{sorted(set(range(1, total_steps + 1)) ^ set(mapping))[:10]}"
    )
    # Each resumed incarnation starts exactly one past its restore point
    # (no silent fast-forward, no replay of applied steps).
    boots: dict[int, float] = {}
    first_step: dict[int, dict] = {}
    last_step: dict[int, int] = {}
    for e in events:
        inc = e["incarnation"]
        if e["event"] == "boot":
            boots[inc] = e["t"]
        elif e["event"] == "step":
            first_step.setdefault(inc, e)
            last_step[inc] = e["step"]
    for inc in range(1, incarnation + 1):
        assert first_step[inc]["step"] <= last_step[inc - 1] + 1, (
            f"incarnation {inc} skipped ahead: first step "
            f"{first_step[inc]['step']} after {last_step[inc - 1]} {repro}"
        )

    # -- resilience metrics ---------------------------------------------
    executed = len(steps)
    lost = executed - total_steps
    kills = len(crashes)
    recovery = [
        first_step[inc]["t"] - boots[inc]
        for inc in range(1, incarnation + 1)
    ]
    metrics = {
        "seed": seed,
        "goodput": total_steps / executed,
        "steps_lost_per_kill": lost / kills,
        "recovery_seconds": sum(recovery) / len(recovery),
        "kills": kills,
        "incarnations": incarnation + 1,
        "elapsed_seconds": elapsed,
        "coverage": coverage,
    }
    print(f"resilience soak converged: {json.dumps(metrics)} {repro}")
    out = os.environ.get("KFTPU_RESILIENCE_METRICS")
    if out:
        with open(out, "w") as f:
            json.dump(metrics, f)
    return metrics


def test_resilience_soak_kill_and_resume(tmp_path):
    """Tier-1 soak: the full fault matrix at its smallest size, fixed
    seed for determinism."""
    metrics = _run_soak(
        tmp_path, _seed(),
        total_steps=32, save_interval=4, faults_per_class=1,
        deadline=300.0,
    )
    assert 0.0 < metrics["goodput"] <= 1.0


@pytest.mark.slow
def test_resilience_soak_nightly(tmp_path):
    """The long soak (`bench.py --workload resilience` / nightly CI): a
    denser schedule over a longer run. Prints its seed so any failure
    reproduces with KFTPU_RESILIENCE_SEED=<seed>."""
    seed = int(
        os.environ.get("KFTPU_RESILIENCE_SEED") or (time.time_ns() % 2**31)
    )
    _run_soak(
        tmp_path, seed,
        total_steps=80, save_interval=5, faults_per_class=2,
        deadline=900.0,
    )


# ---------------------------------------------------------------------------
# Elastic resize (ISSUE 9): preemption absorbed by reshaping the mesh.
# ---------------------------------------------------------------------------


def _run_elastic_soak(
    tmp_path, seed: int, *, total_steps, save_interval, faults_per_class,
    deadline, dp_full=2, dp_shrunk=1,
) -> dict:
    """The resize soak: ONE worker incarnation trains through a seeded
    plan of shrink->grow cycles, each shrink under a REAL self-delivered
    SIGTERM that fit() must ABSORB by reshaping the mesh — the process
    never dies, so steps-lost-per-kill is ~0 and goodput ~1.0 (vs ~10
    steps/kill and ~0.67 for the restart-shaped soak above). Asserts
    exact final-params/loss parity vs an uninterrupted fixed-dp run,
    the zero repeated/skipped batches identity, and full elastic fault
    coverage."""
    import signal as signal_module

    repro = (
        f"[elastic resilience seed={seed}; reproduce with "
        f"KFTPU_RESILIENCE_SEED={seed}]"
    )
    print(f"elastic resize soak starting {repro}")
    schedule = TrainFaultSchedule(
        seed, total_steps, save_interval=save_interval,
        faults_per_class=faults_per_class, elastic=True,
        dp_full=dp_full, dp_shrunk=dp_shrunk,
    )
    # The repro contract itself: same seed -> identical plan.
    assert TrainFaultSchedule(
        seed, total_steps, save_interval=save_interval,
        faults_per_class=faults_per_class, elastic=True,
        dp_full=dp_full, dp_shrunk=dp_shrunk,
    ).plan == schedule.plan, repro
    spikes = schedule.spike_steps
    common = dict(
        total_steps=total_steps, save_interval=save_interval,
        seed=seed, spikes=spikes,
    )

    # -- uninterrupted baseline: fixed dp_full, same data + spikes ------
    base_trace = tmp_path / "baseline.jsonl"
    proc = _run_worker(
        ckpt_dir=tmp_path / "ckpt-base", trace_file=base_trace,
        incarnation=0, dp=dp_full, **common,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr, repro)
    baseline = _final_summary(_read_trace(base_trace))
    assert baseline["skips"] == len(spikes), (baseline, repro)

    # -- elastic run: one incarnation, the whole plan staged ------------
    trace_file = tmp_path / "elastic.jsonl"
    t0 = time.monotonic()
    proc = _run_worker(
        ckpt_dir=tmp_path / "ckpt", trace_file=trace_file,
        incarnation=0, dp=dp_full, elastic_plan=schedule.resize_plan,
        **common,
    )
    elapsed = time.monotonic() - t0
    assert elapsed < deadline, (
        f"elastic soak missed its deadline ({elapsed:.1f}s) {repro}"
    )
    # rc 0 IS the headline: real SIGTERMs arrived and the process
    # completed anyway — the preemptions were absorbed, not fatal.
    assert proc.returncode == 0, (proc.stdout, proc.stderr, repro)

    events = _read_trace(trace_file)
    final = _final_summary(events)
    resize_events = [e for e in events if e["event"] == "resize"]

    # -- every planned resize happened, with the right trigger ----------
    # A fault at position p lands at the boundary after step p+1 (the
    # crash-injector timing convention).
    for fault in schedule.resize_faults:
        match = [
            e for e in resize_events
            if e["step"] == fault.at_step + 1 and e["to_dp"] == fault.dp
        ]
        assert len(match) == 1, (fault, resize_events, repro)
        if fault.cls == "preempt_shrink":
            # The shrink ABSORBED a real SIGTERM at its boundary.
            assert match[0]["absorbed_signum"] == int(
                signal_module.SIGTERM
            ), (match[0], repro)
        else:
            assert match[0]["absorbed_signum"] is None, (match[0], repro)
        assert match[0]["source"] == "live", (match[0], repro)
        schedule.mark_injected(fault)

    # -- the guard skipped exactly the scheduled spikes -----------------
    assert final["skips"] == len(spikes), (final, repro)
    for fault in schedule.spike_faults:
        schedule.mark_injected(fault)

    # -- coverage gate: every elastic fault class actually fired --------
    coverage = schedule.coverage()
    assert all(coverage[c] >= 1 for c in ELASTIC_FAULT_CLASSES), (
        f"incomplete fault coverage: {coverage} {repro}"
    )

    # -- parity with the uninterrupted fixed-dp baseline ----------------
    np.testing.assert_allclose(
        final["params_l1"], baseline["params_l1"], rtol=1e-6,
        err_msg=f"final params diverged from the uninterrupted run {repro}",
    )
    np.testing.assert_allclose(
        final["final_loss"], baseline["final_loss"], rtol=1e-5,
        err_msg=f"final loss diverged from the uninterrupted run {repro}",
    )

    # -- zero repeated/skipped batches across every resize --------------
    steps = [e for e in events if e["event"] == "step"]
    mapping = {e["step"]: e["position"] for e in steps}
    assert mapping == {s: s for s in range(1, total_steps + 1)}, (
        f"batch sequence diverged (repeated or skipped data) {repro}: "
        f"{sorted(set(range(1, total_steps + 1)) ^ set(mapping))[:10]}"
    )

    # -- elastic resilience economics -----------------------------------
    executed = len(steps)
    lost = executed - total_steps
    shrinks = sum(
        1 for f in schedule.resize_faults if f.cls == "preempt_shrink"
    )
    metrics = {
        "seed": seed,
        "goodput": total_steps / executed,
        "steps_lost_per_kill": lost / shrinks,
        "resizes": len(resize_events),
        "resize_seconds": (
            sum(e["seconds"] for e in resize_events) / len(resize_events)
        ),
        "kills": shrinks,
        "incarnations": 1,
        "elapsed_seconds": elapsed,
        "coverage": coverage,
    }
    # The acceptance gate: an absorbed preemption costs (nearly) no
    # steps — vs ~10/kill for the restart-shaped contract.
    assert metrics["steps_lost_per_kill"] < 2.0, (metrics, repro)
    assert metrics["goodput"] > 0.95, (metrics, repro)
    print(f"elastic resize soak converged: {json.dumps(metrics)} {repro}")
    out = os.environ.get("KFTPU_RESILIENCE_METRICS")
    if out:
        with open(out, "w") as f:
            json.dump(metrics, f)
    return metrics


def test_resilience_soak_elastic_resize(tmp_path):
    """Tier-1 elastic soak: a seeded shrink->grow cycle under real
    SIGTERM, smallest size, fixed seed."""
    metrics = _run_elastic_soak(
        tmp_path, _seed(),
        total_steps=32, save_interval=4, faults_per_class=1,
        deadline=300.0,
    )
    assert metrics["resizes"] == 2  # one shrink, one grow-back


@pytest.mark.slow
def test_resilience_soak_elastic_nightly(tmp_path):
    """The elastic nightly (`bench.py --workload resilience` publishes
    its goodput/steps-lost as the `resilience_*_elastic` rows): denser
    shrink->grow cycles over a longer run, dp 4 -> 1. Prints its seed
    so any failure reproduces with KFTPU_RESILIENCE_SEED=<seed>."""
    seed = int(
        os.environ.get("KFTPU_RESILIENCE_SEED") or (time.time_ns() % 2**31)
    )
    _run_elastic_soak(
        tmp_path, seed,
        total_steps=80, save_interval=5, faults_per_class=2,
        deadline=900.0, dp_full=4, dp_shrunk=1,
    )


def _drive(ctl, passes=6):
    for _ in range(passes):
        ctl.controller.run_until_idle()


def _wait_for(pred, timeout: float, what: str):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = pred()
        if value:
            return value
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def test_elastic_resize_negotiated_with_scheduler(tmp_path):
    """The first e2e where the scheduler and the trainer NEGOTIATE: a
    real TpuJobController proposes a shrink-to-fit to a victim gang
    whose rank-0 worker is a live `fit()` subprocess; the driver plays
    the pod runner relaying proposal/ack between the two. The gang
    worker absorbs a real SIGTERM by resizing, the controller trims the
    released pod with ZERO evictions (no Preempted event, restart
    budget and incarnation untouched), and the grow-back handshake
    restores the gang when the preemptor leaves."""
    import signal as signal_module
    import subprocess as sp

    from kubeflow_tpu.api import make_tpujob
    from kubeflow_tpu.api.objects import new_resource
    from kubeflow_tpu.api.tpujob import KIND
    from kubeflow_tpu.controllers.tpujob import (
        LABEL_JOB,
        TpuJobController,
        ack_resize,
    )
    from kubeflow_tpu.testing import FakeApiServer

    api = FakeApiServer()
    for i in range(2):
        node = new_resource(
            "Node", f"n{i}", "",
            spec={"pool": "4x4", "chips": 4, "x": i, "y": 0},
        )
        node.status = {"ready": True}
        api.create(node)
    ctl = TpuJobController(
        api, resize_grace_seconds=60.0, grow_retry_seconds=0.2
    )

    def pods(name):
        return sorted(
            api.list("Pod", "default", label_selector={LABEL_JOB: name}),
            key=lambda p: p.metadata.name,
        )

    def mark_running(name):
        for p in pods(name):
            fresh = p.thaw()
            if fresh.status.get("phase") != "Running":
                fresh.status["phase"] = "Running"
                api.update_status(fresh)

    api.create(make_tpujob(
        "gang", replicas=2, tpu_chips_per_worker=4, topology="4x4",
        command=("python", "resilience_worker.py"),
        elastic_min_replicas=1,
    ))
    _drive(ctl)
    assert len(pods("gang")) == 2
    mark_running("gang")
    _drive(ctl)

    # The gang's rank-0 trainer, live: polls the proposal file at every
    # step boundary and acks completed resizes into the ack file.
    resize_file = tmp_path / "resize.json"
    ack_file = tmp_path / "ack.json"
    env = _worker_env(
        ckpt_dir=tmp_path / "ckpt", trace_file=tmp_path / "trace.jsonl",
        incarnation=0, total_steps=100000, save_interval=1000,
        seed=_seed(), spikes=(), dp=2,
    )
    env["KFTPU_RESIZE_FILE"] = str(resize_file)
    env["KFTPU_ACK_FILE"] = str(ack_file)
    env["KFTPU_STEP_DELAY"] = "0.01"
    proc = sp.Popen(
        [sys.executable, WORKER], env=env,
        stdout=sp.PIPE, stderr=sp.PIPE, text=True,
    )
    try:
        # Wait for the first STEP event — only then is fit()'s signal
        # handler installed (a SIGTERM before that would hit the
        # default disposition and kill the worker for real).
        def stepped():
            try:
                return any(
                    '"step"' in line
                    for line in open(tmp_path / "trace.jsonl")
                )
            except OSError:
                return False

        _wait_for(stepped, 120.0, "worker's first step")

        # A higher-priority gang arrives: the controller OFFERS the
        # victim a shrink instead of evicting it.
        api.create(make_tpujob(
            "urgent", priority=10, replicas=1, tpu_chips_per_worker=4,
            topology="4x4", command=("true",),
        ))
        _drive(ctl)
        proposal = api.get(KIND, "gang").status.get("resize")
        assert proposal is not None and proposal["replicas"] == 1
        assert proposal["forJob"] == "default/urgent"
        assert len(pods("gang")) == 2  # nothing touched yet

        # Pod runner relays the proposal to the trainer, then delivers
        # the preemption signal — a REAL SIGTERM the worker must absorb
        # by resizing at the next boundary.
        tmp = tmp_path / "resize.json.tmp"
        tmp.write_text(json.dumps({"dp": 1, "source": "live"}))
        os.replace(tmp, resize_file)
        proc.send_signal(signal_module.SIGTERM)
        ack = _wait_for(
            lambda: json.loads(ack_file.read_text())
            if ack_file.exists() else None,
            120.0, "worker shrink ack",
        )
        assert ack["dp"] == 1
        assert proc.poll() is None, (
            "worker died on the SIGTERM it should have absorbed",
            proc.poll(),
        )

        # Relay the ack to the apiserver; the controller trims the gang
        # and places the preemptor — zero evictions.
        assert ack_resize(api, "gang") == 1
        _drive(ctl)
        time.sleep(0.6)  # the preemptor's placement retry is timed
        _drive(ctl)
        gang = api.get(KIND, "gang")
        assert len(pods("gang")) == 1
        assert len(pods("urgent")) == 1
        assert gang.status.get("elasticReplicas") == 1
        assert gang.status.get("restarts", 0) == 0
        assert gang.status.get("phase") == "Running"
        reasons = {
            e.spec["reason"] for e in api.list("Event", "default")
        }
        assert "Resized" in reasons
        assert "Preempted" not in reasons
        assert "PreemptedLowerPriority" not in reasons
        assert "GangTornDown" not in reasons
        assert ctl.elastic_resizes.value(
            job="default/gang", direction="shrink"
        ) == 1

        # The preemptor finishes; capacity returns; the controller
        # offers the grow-back.
        api.delete(KIND, "urgent")
        for p in pods("urgent"):
            try:
                api.delete("Pod", p.metadata.name, "default")
            except Exception:
                pass
        ack_file.unlink()
        time.sleep(0.4)  # past the post-resize grow backoff
        _drive(ctl)
        grow = _wait_for(
            lambda: api.get(KIND, "gang").status.get("resize"),
            30.0, "grow-back proposal",
        )
        assert grow["replicas"] == 2
        assert grow["forJob"] == ""  # capacity returned, no preemptor

        # Relay to the trainer (no signal — growth is unprompted).
        tmp.write_text(json.dumps({"dp": 2, "source": "live"}))
        os.replace(tmp, resize_file)
        ack = _wait_for(
            lambda: json.loads(ack_file.read_text())
            if ack_file.exists() else None,
            120.0, "worker grow ack",
        )
        assert ack["dp"] == 2
        assert ack_resize(api, "gang") == 2
        _drive(ctl)
        gang = api.get(KIND, "gang")
        assert len(pods("gang")) == 2
        assert "elasticReplicas" not in gang.status
        assert gang.status.get("restarts", 0) == 0
        assert ctl.elastic_resizes.value(
            job="default/gang", direction="grow"
        ) == 1

        # The worker is still the SAME process — zero deaths across the
        # whole shrink -> grow negotiation.
        assert proc.poll() is None
    finally:
        # A plain SIGTERM now (no pending proposal: the file's dp
        # matches the current mesh) takes the normal Preempted exit.
        if proc.poll() is None:
            proc.send_signal(signal_module.SIGTERM)
        out, err = proc.communicate(timeout=120)
    assert proc.returncode == 75, (proc.returncode, out, err)

    trace = _read_trace(tmp_path / "trace.jsonl")
    resizes = [e for e in trace if e["event"] == "resize"]
    assert [r["to_dp"] for r in resizes] == [1, 2]
    assert resizes[0]["absorbed_signum"] == int(signal_module.SIGTERM)
    assert resizes[1]["absorbed_signum"] is None
