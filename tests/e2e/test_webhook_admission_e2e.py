"""Out-of-process admission e2e: the PodDefault mutator as its own
process, matching the reference's deployment shape — a standalone TLS
webhook server (`admission-webhook/main.go:443,597`) that the apiserver
calls out to, reading its PodDefault CRs through the authenticated
facade with a least-privilege token.

Flow: secure TLS facade in the parent; `python -m
kubeflow_tpu.controllers.webhook --register` as a child process (it
mints its own serving cert and creates the WebhookConfiguration pointing
at itself); a Pod created through the facade comes back with the
PodDefault's env injected by the CHILD. Then the webhook dies:
failurePolicy=Fail rejects creates; flipped to Ignore, creates pass
unmodified."""

import os
import subprocess
import sys
import time

import pytest

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.rbac import (
    make_cluster_role,
    make_cluster_role_binding,
    seed_cluster_roles,
)
from kubeflow_tpu.api.tokens import TokenRegistry, service_account
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.testing.apiserver_http import ApiServerApp, HttpApiClient
from kubeflow_tpu.web.wsgi import serve

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# Exactly what the webhook binary touches: PodDefault reads plus its own
# registration (the reference grants its webhook the same minimal set
# via manifests).
WEBHOOK_RULES = [
    {"verbs": ["get", "list", "watch"], "resources": ["poddefaults"]},
    {"verbs": ["create", "update", "patch"],
     "resources": ["webhookconfigurations"]},
]


def test_poddefault_mutation_via_separate_process(tmp_path, tls_paths):
    api = FakeApiServer()
    seed_cluster_roles(api)
    tokens = TokenRegistry()
    admin_token = tokens.issue("system:admin")
    api.create(
        make_cluster_role_binding("adm", "kubeflow-admin", "system:admin")
    )
    wh_user = service_account("kubeflow", "poddefault-webhook")
    api.create(make_cluster_role("poddefault-webhook", WEBHOOK_RULES))
    api.create(
        make_cluster_role_binding(
            "poddefault-webhook", "poddefault-webhook", wh_user
        )
    )
    server, _ = serve(
        ApiServerApp(api, tokens=tokens), host="127.0.0.1", port=0,
        tls=tls_paths,
    )
    base_url = f"https://127.0.0.1:{server.server_port}"
    admin = HttpApiClient(base_url, token=admin_token,
                          ca=tls_paths.ca_cert)

    admin.create(new_resource(
        "PodDefault", "add-proxy", "default",
        spec={
            "selector": {"matchLabels": {"inject": "yes"}},
            "env": [{"name": "HTTP_PROXY", "value": "http://proxy:80"}],
        },
    ))

    proc = subprocess.Popen(
        [sys.executable, "-m", "kubeflow_tpu.controllers.webhook",
         "--apiserver", base_url,
         "--tls-dir", str(tmp_path / "webhook-tls"),
         "--register"],
        env={
            **os.environ,
            "PYTHONPATH": REPO,
            "KFTPU_TOKEN": tokens.issue(wh_user),
            "KFTPU_CA": tls_paths.ca_cert,
        },
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        assert proc.stdout.readline().strip().startswith("webhook ready")

        # The callout really crossed process + TLS boundaries: the pod
        # comes back with the child's injection.
        matched = admin.create(new_resource(
            "Pod", "wants-proxy", "default",
            spec={"containers": [{"name": "w"}]},
            labels={"inject": "yes"},
        ))
        env = matched.spec["containers"][0].get("env", [])
        assert {"name": "HTTP_PROXY", "value": "http://proxy:80"} in env, env
        # Selector miss: admitted untouched.
        plain = admin.create(new_resource(
            "Pod", "plain", "default",
            spec={"containers": [{"name": "w"}]},
        ))
        assert "env" not in plain.spec["containers"][0]

        # Webhook dies. failurePolicy=Fail (the default): creates of the
        # webhook's kinds are refused — fail closed, like the reference's
        # failure policy.
        proc.terminate()
        proc.wait(timeout=15)
        from kubeflow_tpu.testing.fake_apiserver import Invalid

        with pytest.raises(Invalid, match="failurePolicy=Fail"):
            admin.create(new_resource(
                "Pod", "orphan", "default",
                spec={"containers": [{"name": "w"}]},
            ))
        # Other kinds are unaffected while the webhook is down.
        admin.create(new_resource("ConfigMap", "fine", spec={}))

        # Operator flips the policy to Ignore: creates pass, unmodified.
        cfg = admin.get("WebhookConfiguration", "poddefault-webhook", "")
        cfg.spec["failurePolicy"] = "Ignore"
        admin.update(cfg)
        degraded = admin.create(new_resource(
            "Pod", "degraded", "default",
            spec={"containers": [{"name": "w"}]},
            labels={"inject": "yes"},
        ))
        assert "env" not in degraded.spec["containers"][0]
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
        server.shutdown()


def test_leader_elected_webhook_failover(tmp_path, tls_paths):
    """--leader-elect: two webhook replicas, exactly one serving +
    registered. Kill the leader; the standby acquires the lease,
    registers ITS OWN url (re-aiming admission traffic), and mutation
    keeps working through the new replica."""
    api = FakeApiServer()
    seed_cluster_roles(api)
    tokens = TokenRegistry()
    admin_token = tokens.issue("system:admin")
    api.create(
        make_cluster_role_binding("adm", "kubeflow-admin", "system:admin")
    )
    wh_user = service_account("kubeflow", "poddefault-webhook")
    rules = WEBHOOK_RULES + [
        {"verbs": ["get", "create", "update"], "resources": ["leases"]},
    ]
    api.create(make_cluster_role("poddefault-webhook", rules))
    api.create(
        make_cluster_role_binding(
            "poddefault-webhook", "poddefault-webhook", wh_user
        )
    )
    server, _ = serve(
        ApiServerApp(api, tokens=tokens), host="127.0.0.1", port=0,
        tls=tls_paths,
    )
    base_url = f"https://127.0.0.1:{server.server_port}"
    admin = HttpApiClient(base_url, token=admin_token,
                          ca=tls_paths.ca_cert)
    admin.create(new_resource(
        "PodDefault", "add-proxy", "default",
        spec={
            "selector": {"matchLabels": {"inject": "yes"}},
            "env": [{"name": "HTTP_PROXY", "value": "http://proxy:80"}],
        },
    ))

    def spawn(identity, tls_sub):
        return subprocess.Popen(
            [sys.executable, "-m", "kubeflow_tpu.controllers.webhook",
             "--apiserver", base_url,
             "--tls-dir", str(tmp_path / tls_sub),
             "--register", "--leader-elect", "--identity", identity],
            env={
                **os.environ,
                "PYTHONPATH": REPO,
                "KFTPU_TOKEN": tokens.issue(wh_user),
                "KFTPU_CA": tls_paths.ca_cert,
            },
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )

    def read_until(proc, prefix, timeout=30.0):
        import select as _select

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            ready, _, _ = _select.select(
                [proc.stdout], [], [],
                min(0.5, max(0.0, deadline - time.monotonic())),
            )
            if not ready:
                continue
            line = proc.stdout.readline()
            if line and line.strip().startswith(prefix):
                return line.strip()
        raise AssertionError(f"no {prefix!r} from webhook in {timeout}s")

    a = spawn("wh-a", "tls-a")
    b = None
    try:
        read_until(a, "standby wh-a")
        read_until(a, "webhook ready")
        url_a = api.get(
            "WebhookConfiguration", "poddefault-webhook", ""
        ).spec["url"]
        b = spawn("wh-b", "tls-b")
        read_until(b, "standby wh-b")

        # Leader serves; standby is NOT serving (registration points at
        # exactly one replica).
        pod = admin.create(new_resource(
            "Pod", "via-leader", "default",
            spec={"containers": [{"name": "w"}]},
            labels={"inject": "yes"},
        ))
        assert {"name": "HTTP_PROXY", "value": "http://proxy:80"} in (
            pod.spec["containers"][0].get("env", [])
        )

        a.kill()  # SIGKILL: the lease must expire on its own
        read_until(b, "webhook ready", timeout=40)
        url_b = api.get(
            "WebhookConfiguration", "poddefault-webhook", ""
        ).spec["url"]
        assert url_b != url_a  # re-aimed at the survivor
        pod2 = admin.create(new_resource(
            "Pod", "via-standby", "default",
            spec={"containers": [{"name": "w"}]},
            labels={"inject": "yes"},
        ))
        assert {"name": "HTTP_PROXY", "value": "http://proxy:80"} in (
            pod2.spec["containers"][0].get("env", [])
        )
    finally:
        for p in (a, b):
            if p is not None:
                p.kill()
                p.wait(timeout=10)
        admin.close()
        server.shutdown()
