"""Workflow-engine E2E: a real diamond DAG of subprocesses sharing an
artifacts dir, with the exit handler always running — the in-process
analog of an Argo CI run (`kfctl_go_test.jsonnet` DAG + NFS volume +
exit-handler teardown)."""

import sys
import time

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.workflow import KIND, StepSpec, WorkflowSpec
from kubeflow_tpu.controllers.workflow import WorkflowController
from kubeflow_tpu.runtime import LocalPodRunner
from kubeflow_tpu.testing import FakeApiServer

def _write_step(name, deps=()):
    return StepSpec(
        name=name,
        command=(
            sys.executable,
            "-c",
            "import os,time,pathlib;"
            "d=pathlib.Path(os.environ['STEP_ARTIFACTS']);"
            "d.mkdir(parents=True,exist_ok=True);"
            "(d/(os.environ['STEP_NAME']+'.txt'))"
            ".write_text(str(time.time_ns()))",
        ),
        dependencies=tuple(deps),
    )


def _drive(api, ctl, runner, name, deadline_s=120):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        ctl.controller.run_until_idle()
        runner.step()
        phase = api.get(KIND, name, "ci").status.get("phase")
        if phase in ("Succeeded", "Failed"):
            return phase
        time.sleep(0.1)
    raise TimeoutError("workflow did not finish")


def test_diamond_dag_end_to_end(tmp_path):
    api = FakeApiServer()
    ctl = WorkflowController(api)
    runner = LocalPodRunner(api)
    artifacts = tmp_path / "artifacts"

    spec = WorkflowSpec(
        steps=(
            _write_step("a"),
            _write_step("b", deps=["a"]),
            _write_step("c", deps=["a"]),
            _write_step("d", deps=["b", "c"]),
        ),
        on_exit=_write_step("teardown"),
        artifacts_dir=str(artifacts),
    )
    api.create(new_resource(KIND, "diamond", "ci", spec=spec.to_dict()))
    try:
        phase = _drive(api, ctl, runner, "diamond")
    finally:
        runner.shutdown()

    assert phase == "Succeeded"
    stamps = {
        p.stem: int(p.read_text()) for p in artifacts.glob("*.txt")
    }
    assert set(stamps) == {"a", "b", "c", "d", "teardown"}
    assert stamps["a"] < stamps["b"] and stamps["a"] < stamps["c"]
    assert stamps["d"] > stamps["b"] and stamps["d"] > stamps["c"]


def test_failing_step_still_tears_down(tmp_path):
    api = FakeApiServer()
    ctl = WorkflowController(api)
    runner = LocalPodRunner(api)
    artifacts = tmp_path / "artifacts"

    spec = WorkflowSpec(
        steps=(
            StepSpec(
                name="boom",
                command=(sys.executable, "-c", "import sys; sys.exit(3)"),
            ),
            _write_step("never", deps=["boom"]),
        ),
        on_exit=_write_step("teardown"),
        artifacts_dir=str(artifacts),
    )
    api.create(new_resource(KIND, "failing", "ci", spec=spec.to_dict()))
    try:
        phase = _drive(api, ctl, runner, "failing")
    finally:
        runner.shutdown()

    assert phase == "Failed"
    files = {p.stem for p in artifacts.glob("*.txt")}
    assert "teardown" in files and "never" not in files
