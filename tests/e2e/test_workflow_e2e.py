"""Workflow-engine E2E: a real diamond DAG of subprocesses sharing an
artifacts dir, with the exit handler always running — the in-process
analog of an Argo CI run (`kfctl_go_test.jsonnet` DAG + NFS volume +
exit-handler teardown)."""

import sys
import time

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.workflow import KIND, StepSpec, WorkflowSpec
from kubeflow_tpu.controllers.workflow import WorkflowController
from kubeflow_tpu.runtime import LocalPodRunner
from kubeflow_tpu.testing import FakeApiServer

def _write_step(name, deps=()):
    return StepSpec(
        name=name,
        command=(
            sys.executable,
            "-c",
            "import os,time,pathlib;"
            "d=pathlib.Path(os.environ['STEP_ARTIFACTS']);"
            "d.mkdir(parents=True,exist_ok=True);"
            "(d/(os.environ['STEP_NAME']+'.txt'))"
            ".write_text(str(time.time_ns()))",
        ),
        dependencies=tuple(deps),
    )


def _drive(api, ctl, runner, name, deadline_s=120):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        ctl.controller.run_until_idle()
        runner.step()
        phase = api.get(KIND, name, "ci").status.get("phase")
        if phase in ("Succeeded", "Failed"):
            return phase
        time.sleep(0.1)
    raise TimeoutError("workflow did not finish")


def test_diamond_dag_end_to_end(tmp_path):
    api = FakeApiServer()
    ctl = WorkflowController(api)
    runner = LocalPodRunner(api)
    artifacts = tmp_path / "artifacts"

    spec = WorkflowSpec(
        steps=(
            _write_step("a"),
            _write_step("b", deps=["a"]),
            _write_step("c", deps=["a"]),
            _write_step("d", deps=["b", "c"]),
        ),
        on_exit=_write_step("teardown"),
        artifacts_dir=str(artifacts),
    )
    api.create(new_resource(KIND, "diamond", "ci", spec=spec.to_dict()))
    try:
        phase = _drive(api, ctl, runner, "diamond")
    finally:
        runner.shutdown()

    assert phase == "Succeeded"
    stamps = {
        p.stem: int(p.read_text()) for p in artifacts.glob("*.txt")
    }
    assert set(stamps) == {"a", "b", "c", "d", "teardown"}
    assert stamps["a"] < stamps["b"] and stamps["a"] < stamps["c"]
    assert stamps["d"] > stamps["b"] and stamps["d"] > stamps["c"]


def test_failing_step_still_tears_down(tmp_path):
    api = FakeApiServer()
    ctl = WorkflowController(api)
    runner = LocalPodRunner(api)
    artifacts = tmp_path / "artifacts"

    spec = WorkflowSpec(
        steps=(
            StepSpec(
                name="boom",
                command=(sys.executable, "-c", "import sys; sys.exit(3)"),
            ),
            _write_step("never", deps=["boom"]),
        ),
        on_exit=_write_step("teardown"),
        artifacts_dir=str(artifacts),
    )
    api.create(new_resource(KIND, "failing", "ci", spec=spec.to_dict()))
    try:
        phase = _drive(api, ctl, runner, "failing")
    finally:
        runner.shutdown()

    assert phase == "Failed"
    files = {p.stem for p in artifacts.glob("*.txt")}
    assert "teardown" in files and "never" not in files


def test_sharded_ci_fanout_with_junit_collection(tmp_path):
    """The VERDICT-#9 deliverable end-to-end: the CI DSL fans pytest
    shards out via withItems, each shard writes junit into the shared
    artifacts volume, and the join step merges them — real subprocesses
    throughout (the Argo DAG + NFS + Gubernator-copy shape of
    `kfctl_go_test.jsonnet`, run by our own engine)."""
    artifacts = tmp_path / "artifacts"
    artifacts.mkdir()
    from kubeflow_tpu.testing.workflows import sharded_unit_tests_workflow

    api = FakeApiServer()
    ctl = WorkflowController(api)
    runner = LocalPodRunner(api)
    wf = sharded_unit_tests_workflow(
        ("tests/test_overlays.py", "tests/test_records.py"),
        namespace="ci",
        artifacts_dir=str(artifacts),
    )
    api.create(wf)
    try:
        phase = _drive(api, ctl, runner, "unit-tests-sharded",
                       deadline_s=300)
    finally:
        runner.shutdown()

    assert phase == "Succeeded"
    # Each shard staged its junit in the shared volume; the collect step
    # merged them.
    shard_files = sorted(p.name for p in artifacts.glob("junit_tests*"))
    assert len(shard_files) == 2, shard_files
    merged = (artifacts / "junit_merged.xml").read_text()
    assert "testsuite" in merged
    status = api.get(KIND, "unit-tests-sharded", "ci").status
    assert status["steps"]["shard-0"]["state"] == "Succeeded"
    assert status["steps"]["collect-junit"]["state"] == "Succeeded"


def test_conditional_step_skipped_end_to_end(tmp_path):
    """`when` guard over a real step output: the probe reports healthy,
    remediation is skipped, the report still runs."""
    from kubeflow_tpu.testing.apiserver_http import ApiServerApp
    from kubeflow_tpu.web.wsgi import serve

    artifacts = tmp_path / "artifacts"
    artifacts.mkdir()
    api = FakeApiServer()
    server, _ = serve(ApiServerApp(api), host="127.0.0.1", port=0)
    ctl = WorkflowController(api)
    runner = LocalPodRunner(
        api,
        extra_env={
            "KFTPU_APISERVER": f"http://127.0.0.1:{server.server_port}"
        },
    )

    # The probe honors the output contract: report_step_output over the
    # facade BEFORE exiting 0, so the guard always sees the value.
    probe = StepSpec(
        name="probe",
        command=(
            sys.executable,
            "-c",
            "import os;"
            "from kubeflow_tpu.testing.apiserver_http import HttpApiClient;"
            "from kubeflow_tpu.controllers.workflow import report_step_output;"
            "report_step_output("
            "HttpApiClient(os.environ['KFTPU_APISERVER']),"
            "os.environ['POD_NAME'],os.environ['POD_NAMESPACE'],'healthy')",
        ),
    )
    spec = WorkflowSpec(
        steps=(
            probe,
            StepSpec(
                name="remediate",
                command=(sys.executable, "-c",
                         "import pathlib,os;"
                         "pathlib.Path(os.environ['STEP_ARTIFACTS'],"
                         "'remediated.txt').write_text('x')"),
                dependencies=("probe",),
                when="${steps.probe.output} == unhealthy",
            ),
            _write_step("report", deps=("remediate",)),
        ),
        artifacts_dir=str(artifacts),
    )
    api.create(new_resource(KIND, "guarded", "ci", spec=spec.to_dict()))

    try:
        _drive(api, ctl, runner, "guarded")
    finally:
        runner.shutdown()
        server.shutdown()

    status = api.get(KIND, "guarded", "ci").status
    assert status["phase"] == "Succeeded", status
    assert status["steps"]["remediate"]["state"] == "Skipped"
    assert not (artifacts / "remediated.txt").exists()
    assert (artifacts / "report.txt").exists()


def test_slice_step_runs_real_gang(tmp_path):
    """A CI DAG whose 'train' step is a TpuJob: the workflow controller
    materializes the gang, the TpuJob operator runs it as real
    processes, the worker reports its observation over the facade, and
    the downstream step receives it via ${steps.train.output}."""
    import os

    from kubeflow_tpu.controllers.tpujob import TpuJobController
    from kubeflow_tpu.testing.apiserver_http import ApiServerApp
    from kubeflow_tpu.web.wsgi import serve

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    artifacts = tmp_path / "artifacts"
    artifacts.mkdir()
    api = FakeApiServer()
    server, _ = serve(ApiServerApp(api), host="127.0.0.1", port=0)
    wf_ctl = WorkflowController(api)
    job_ctl = TpuJobController(api)
    runner = LocalPodRunner(
        api,
        extra_env={
            "KFTPU_REPO": repo,
            "KFTPU_APISERVER": f"http://127.0.0.1:{server.server_port}",
        },
        capture_dir=str(tmp_path / "logs"),
    )
    spec = WorkflowSpec(
        steps=(
            StepSpec(
                name="train",
                tpu_job={
                    "replicas": 1,
                    "image": "local",
                    "command": [
                        sys.executable,
                        os.path.join(repo, "tests", "e2e",
                                     "trial_worker.py"),
                        "--lr", "0.05",
                    ],
                    "tpu": {"chipsPerWorker": 0},
                    "maxRestarts": 0,
                },
            ),
            StepSpec(
                name="report",
                command=(
                    sys.executable, "-c",
                    "import os,pathlib;"
                    "pathlib.Path(os.environ['STEP_ARTIFACTS'],"
                    "'result.json').write_text(os.environ['TRAIN_RESULT'])",
                ),
                env=(("TRAIN_RESULT", "${steps.train.output}"),),
                dependencies=("train",),
            ),
        ),
        artifacts_dir=str(artifacts),
    )
    api.create(new_resource(KIND, "ci-train", "ci", spec=spec.to_dict()))
    deadline = time.time() + 150
    try:
        while time.time() < deadline:
            wf_ctl.controller.run_until_idle()
            job_ctl.controller.run_until_idle()
            runner.step()
            phase = api.get(KIND, "ci-train", "ci").status.get("phase")
            if phase in ("Succeeded", "Failed"):
                break
            time.sleep(0.2)
    finally:
        runner.shutdown()
        server.shutdown()

    status = api.get(KIND, "ci-train", "ci").status
    assert status["phase"] == "Succeeded", status
    result = (artifacts / "result.json").read_text()
    assert '"loss"' in result and "0.0" in result, result
