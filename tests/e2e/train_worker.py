"""Worker for the distributed-training E2E: joins the gang via the
TPUJOB_* contract, then runs REAL sharded training steps (tiny ResNet,
SGD) over a dp mesh spanning the gang's processes — the multi-process
fixture the reference never had (SURVEY.md §4.3: distributed behavior was
only ever tested against a live GKE cluster).

Every process executes the same SPMD program; gradients psum over dp via
gloo. Rank 0 reports the final loss as the job observation, so the
controller-side test can assert on training results end-to-end.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# One local device per process: the gang, not XLA's virtual-device flag,
# provides the parallelism here.
os.environ["XLA_FLAGS"] = ""
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

sys.path.insert(0, os.environ["KFTPU_REPO"])

import jax.numpy as jnp  # noqa: E402

from kubeflow_tpu.launcher.launcher import report_observation  # noqa: E402
from kubeflow_tpu.models.resnet import tiny_resnet  # noqa: E402
from kubeflow_tpu.parallel import (  # noqa: E402
    MeshSpec,
    build_mesh,
    initialize_from_env,
)
from kubeflow_tpu.testing.apiserver_http import (  # noqa: E402
    HttpApiClient,
    endpoints_from_env,
)
from kubeflow_tpu.train import SyntheticImages, TrainConfig, Trainer  # noqa: E402


def main() -> int:
    pe = initialize_from_env()
    assert jax.process_count() == pe.num_processes
    mesh = build_mesh(MeshSpec(dp=-1))

    config = TrainConfig(
        batch_size=4 * pe.num_processes,
        learning_rate=0.05,
        warmup_steps=1,
        total_steps=6,
        fsdp_params=False,
    )
    trainer = Trainer(
        tiny_resnet(),
        config,
        mesh,
        example_input_shape=(2, 32, 32, 3),
    )
    state = trainer.init_state(jax.random.PRNGKey(0))
    data = SyntheticImages(
        mesh, config.batch_size, image_size=32, num_classes=10
    )
    step = trainer.make_train_step()
    losses = []
    for batch in data:
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
        if len(losses) >= config.total_steps:
            break

    assert all(jnp.isfinite(jnp.asarray(losses))), losses
    assert losses[-1] < losses[0], losses  # it actually learned
    print(f"rank {pe.process_id}: losses {losses[0]:.4f} -> {losses[-1]:.4f}",
          flush=True)

    if pe.process_id == 0 and os.environ.get("KFTPU_APISERVER"):
        report_observation(
            HttpApiClient(endpoints_from_env(os.environ["KFTPU_APISERVER"])),
            os.environ["TPUJOB_NAME"],
            os.environ["TPUJOB_NAMESPACE"],
            {"loss": losses[-1], "first_loss": losses[0]},
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
