"""Study-trial worker: a stand-in training process.

Computes a deterministic objective from its --lr flag and reports it onto
its TpuJob's status.observation through the HTTP apiserver facade — the
exact contract a real trial uses (launcher.report_observation from
process 0 at job end)."""

import argparse
import os
import sys

sys.path.insert(0, os.environ["KFTPU_REPO"])

from kubeflow_tpu.launcher.launcher import report_observation  # noqa: E402
from kubeflow_tpu.testing.apiserver_http import (  # noqa: E402
    HttpApiClient,
    endpoints_from_env,
)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--lr", type=float, required=True)
    args = parser.parse_args()

    loss = (args.lr - 0.05) ** 2  # minimum at lr=0.05

    api = HttpApiClient(endpoints_from_env(os.environ["KFTPU_APISERVER"]))
    report_observation(
        api,
        os.environ["TPUJOB_NAME"],
        os.environ["TPUJOB_NAMESPACE"],
        {"loss": loss},
    )
    print(f"trial done lr={args.lr} loss={loss}")


if __name__ == "__main__":
    main()
