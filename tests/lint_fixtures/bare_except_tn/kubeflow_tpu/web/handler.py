"""True negative: narrow catches, and cleanup-then-reraise."""


def serve_once(handler):
    try:
        return handler()
    except Exception:
        return None


def drain(conn, queue):
    try:
        queue.flush()
    except BaseException:
        conn.close()  # cleanup-then-reraise does not swallow
        raise
