"""True positive: interrupt-swallowing except handlers."""


def serve_once(handler):
    try:
        return handler()
    except:  # noqa: E722  finding: bare except
        return None


def drain(queue):
    try:
        queue.flush()
    except BaseException as e:  # finding: swallowed BaseException
        return e
