"""True negative: the slow work happens outside the critical section;
only the cheap publish happens under the lock."""

import threading
import time
import urllib.request


class Cache:
    def __init__(self, url):
        self.url = url
        self._lock = threading.Lock()
        self.value = None

    def settle(self):
        time.sleep(0.5)
        with self._lock:
            self.value = 1

    def _fetch(self):
        with urllib.request.urlopen(self.url) as resp:
            return resp.read()

    def refresh(self):
        fresh = self._fetch()
        with self._lock:
            self.value = fresh
