"""True positive: blocking work under a lock — one direct, one reached
through an intra-class call (the interprocedural half of the pass)."""

import threading
import time
import urllib.request


class Cache:
    def __init__(self, url):
        self.url = url
        self._lock = threading.Lock()
        self.value = None

    def settle(self):
        with self._lock:
            time.sleep(0.5)  # direct: serializes every reader
            self.value = 1

    def _fetch(self):
        with urllib.request.urlopen(self.url) as resp:
            return resp.read()

    def refresh(self):
        with self._lock:
            self.value = self._fetch()  # transitive: HTTP under the lock
