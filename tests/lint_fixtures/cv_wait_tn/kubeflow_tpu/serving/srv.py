"""True negative: the wait re-checks its predicate in a while loop."""

import threading


class Box:
    def __init__(self):
        self._cv = threading.Condition()
        self._items = []

    def put(self, item):
        with self._cv:
            self._items.append(item)
            self._cv.notify()

    def take(self):
        with self._cv:
            while not self._items:
                self._cv.wait()
            return self._items.pop()
