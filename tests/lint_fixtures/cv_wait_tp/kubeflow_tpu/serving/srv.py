"""True positive: condition wait guarded by `if` — a spurious wakeup or
racing notify pops an empty list."""

import threading


class Box:
    def __init__(self):
        self._cv = threading.Condition()
        self._items = []

    def put(self, item):
        with self._cv:
            self._items.append(item)
            self._cv.notify()

    def take(self):
        with self._cv:
            if not self._items:
                self._cv.wait()
            return self._items.pop()
