"""Missing-hot-path fixture: a renamed hot function must not silently
drop its guard (one finding per missing name)."""


def select_journal_events(journal, floor):
    return [e for e in journal if e.rv > floor]


class FakeApiServer:
    def _emit(self, event, obj):
        self._journal.append((event, obj))

    def _dispatch(self):  # renamed from _dispatch_loop: finding
        while True:
            self._deliver(self._queue.get())

    def get(self, kind, name, namespace="default"):
        return self._objects[(kind, namespace, name)]

    def list(self, kind, namespace=None):
        return list(self._objects.values())
