"""True negative: hot paths share frozen snapshots."""

import copy


def select_journal_events(journal, floor):
    return [e for e in journal if e.rv > floor]


class FakeApiServer:
    def _emit(self, event, obj):
        assert obj.frozen
        self._journal.append((event, obj))  # shared, zero copies

    def _dispatch_loop(self):
        while True:
            self._deliver(self._queue.get())

    def get(self, kind, name, namespace="default"):
        return self._objects[(kind, namespace, name)]

    def list(self, kind, namespace=None):
        return list(self._objects.values())

    def _apply(self, obj):
        self._objects[obj.key] = copy.deepcopy(obj)  # commit point: fine
