"""True positive: deepcopy back in the fan-out/read hot paths."""

import copy


def select_journal_events(journal, floor):
    return [e for e in journal if e.rv > floor]


class FakeApiServer:
    def _emit(self, event, obj):
        snapshot = copy.deepcopy(obj)  # finding: O(watchers x events)
        self._journal.append((event, snapshot))

    def _dispatch_loop(self):
        while True:
            self._deliver(self._queue.get())

    def get(self, kind, name, namespace="default"):
        return self._objects[(kind, namespace, name)].deepcopy()  # finding

    def list(self, kind, namespace=None):
        return list(self._objects.values())

    def _apply(self, obj):
        # Not a hot path: commit-side copies are the ONE copy per write.
        self._objects[obj.key] = copy.deepcopy(obj)
