"""Backstop true positive: config threaded through a helper parameter
is invisible to per-scope dataflow, but this is a config-driven entry
point with no endpoints_from_env anywhere — file-level finding."""

from kubeflow_tpu.testing.apiserver_http import HttpApiClient


def _mk_client(server):
    return HttpApiClient(server)  # finding (file-level backstop)


def main(args):
    return _mk_client(args.server)
