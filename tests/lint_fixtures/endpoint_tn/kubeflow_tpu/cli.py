"""True negative: config strings parsed with endpoints_from_env."""

import os

from kubeflow_tpu.testing.apiserver_http import (
    HttpApiClient,
    endpoints_from_env,
)


def from_args(args):
    return HttpApiClient(endpoints_from_env(args.server))


def from_env():
    return HttpApiClient(
        endpoints_from_env(os.environ["KFTPU_APISERVER"])
    )


def hardcoded_test_only():
    # A literal (non-config) endpoint is out of the rule's scope.
    return HttpApiClient("http://127.0.0.1:8443")
