"""True positive: HttpApiClient built from bare config strings."""

import os

from kubeflow_tpu.testing.apiserver_http import HttpApiClient


def from_args(args):
    return HttpApiClient(args.server)  # finding: "url1,url2" = one bad URL


def from_env():
    return HttpApiClient(os.environ["KFTPU_APISERVER"])  # finding


def from_var(args):
    server = args.apiserver
    return HttpApiClient(server)  # finding: one hop through a local


def from_fstring(args):
    return HttpApiClient(f"https://{args.server}")  # finding: still config


def from_concat():
    url = "https://" + os.environ["KFTPU_APISERVER"]
    return HttpApiClient(url)  # finding: concat doesn't launder config
