"""True positive (e2e worker scope): env endpoint passed bare."""

import os

from kubeflow_tpu.testing.apiserver_http import HttpApiClient


def main():
    api = HttpApiClient(os.environ["KFTPU_APISERVER"])  # finding
    return api
