"""True negative: blockwise flash with pinned fused-kernel streams.

Doubles as the fused-kernel-streams true negative: the kernel below
carries exactly the contract's ref streams.
"""

import jax
import jax.numpy as jnp


def _lse_is_packed(shape):
    return True


def _pack_rows(x):
    return x


def _dqkv_kernel_fused(
    rows_ref, cols_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
    delta_ref, dq_ref, dk_ref, dv_ref,
):
    dq_ref[...] = jnp.zeros_like(q_ref)


def _fwd(q, bh, sq, d):
    # O(S*d) output tile and an O(S) lse tile: the legitimate shapes.
    out = jax.ShapeDtypeStruct((bh, sq, d), jnp.float32)
    lse = jax.ShapeDtypeStruct((bh, sq), jnp.float32)
    return out, lse
