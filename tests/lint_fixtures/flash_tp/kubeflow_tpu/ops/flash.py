"""True positive: dense-style score materialization in flash."""

import jax
import jax.numpy as jnp


def _lse_is_packed(shape):
    return True


def _pack_rows(x):
    return x


def _fwd(q, k, bh, sq, sk):
    scores = jnp.einsum("bqd,bkd->bqk", q, k)  # finding: dense formulation
    out_shape = jax.ShapeDtypeStruct((bh, sq, sk), jnp.float32)  # finding
    return scores, out_shape
