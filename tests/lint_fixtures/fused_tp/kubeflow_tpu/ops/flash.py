"""True positive: O back in the fused backward's streams."""

import jax.numpy as jnp


def _lse_is_packed(shape):
    return True


def _pack_rows(x):
    return x


def _dqkv_kernel_fused(
    rows_ref, cols_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
    delta_ref, dq_ref, dk_ref, dv_ref,
):
    # finding: o_ref = an S*d HBM re-stream per step (shared-delta
    # regression).
    dq_ref[...] = jnp.zeros_like(q_ref) + o_ref[...]
