"""True negative: device-side steps; host syncs only outside jit."""

import jax
import jax.numpy as jnp


@jax.jit
def clean_step(state, batch):
    loss = (batch["x"] ** 2).mean()
    # float() of constants is trace-time arithmetic, not a sync.
    scale = float(1e-4)
    return state, loss * scale


def make_step():
    def train_step(state, batch):
        return state, {"loss": jnp.mean(batch)}

    return jax.jit(train_step)


def log_metrics(metrics):
    # Outside any jitted function: syncing at the log boundary is the
    # pattern the rule exists to protect.
    host = jax.device_get(metrics)
    print("loss", float(host["loss"]))
    return host["loss"].item()
