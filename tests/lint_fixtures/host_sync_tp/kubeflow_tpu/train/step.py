"""True positive: host syncs inside jit-traced step functions."""

import functools

import jax
import numpy as np


@jax.jit
def decorated_step(state, batch):
    loss = (batch["x"] ** 2).mean()
    print("loss", loss)  # finding: print on a tracer
    return state, float(loss)  # finding: float() on a tracer


@functools.partial(jax.jit, static_argnames=("flag",))
def partial_jitted_step(x, flag=True):
    return np.asarray(x)  # finding: np.asarray inside jit


def make_step():
    def train_step(state, batch):
        metrics = {"loss": batch.sum()}
        host = jax.device_get(metrics)  # finding: device_get inside jit
        return state, host["loss"].item()  # finding: .item() inside jit

    return jax.jit(train_step, donate_argnums=0)
