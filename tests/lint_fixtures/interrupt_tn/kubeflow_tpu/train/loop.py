"""True negative: train/ catches only real exceptions."""


def fit_step(step):
    try:
        return step()
    except ValueError:
        return None
    except Exception as e:
        raise RuntimeError("step failed") from e
