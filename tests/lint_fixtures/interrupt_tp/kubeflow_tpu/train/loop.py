"""True positive: train/ intercepting interrupts (even re-raised)."""


def fit_step(step):
    try:
        return step()
    except KeyboardInterrupt:  # finding: interrupts bypass fit()'s handler
        raise
    except SystemExit:  # finding
        return None
