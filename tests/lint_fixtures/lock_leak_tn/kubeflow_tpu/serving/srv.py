"""True negative: explicit acquire paired with try/finally release (and
the `with` form, for good measure)."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def bump(self, delta):
        self._lock.acquire()
        try:
            self.count += int(delta)
        finally:
            self._lock.release()

    def read(self):
        with self._lock:
            return self.count
