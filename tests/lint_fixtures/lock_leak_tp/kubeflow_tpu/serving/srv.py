"""True positive: bare acquire/release — an exception between them
leaks the lock and wedges every later caller."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def bump(self, delta):
        self._lock.acquire()
        self.count += int(delta)  # a bad delta raises with the lock held
        self._lock.release()
