"""True negative: both paths honor one global a-before-b order."""

import threading


class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.hits = 0

    def forward(self):
        with self._a:
            with self._b:
                self.hits += 1

    def backward(self):
        with self._a:
            with self._b:
                self.hits -= 1
