"""True positive: two locks acquired in opposite orders (deadlock)."""

import threading


class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.hits = 0

    def forward(self):
        with self._a:
            with self._b:
                self.hits += 1

    def backward(self):
        with self._b:
            with self._a:
                self.hits -= 1
