"""True negative: lock discipline held (or helpers named *_locked)."""

import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}
        self._count = 0

    def add(self, key, value):
        with self._lock:
            self._entries[key] = value
            self._count += 1

    def evict(self, key):
        with self._lock:
            self._evict_locked(key)

    def _evict_locked(self, key):
        # Caller holds the lock — the *_locked suffix documents it.
        self._entries.pop(key, None)
        self._count -= 1

    def snapshot(self):
        # A lock-free READ of a guarded reference is the documented
        # GIL-atomic idiom, not a finding.
        return dict(self._entries)
