"""True positive: lock-guarded state written lock-free."""

import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}
        self._count = 0  # __init__ writes are exempt (pre-threading)

    def add(self, key, value):
        with self._lock:
            self._entries[key] = value
            self._count += 1

    def evict(self, key):
        # finding x2: both writes race add() without the lock
        self._entries.pop(key, None)
        self._count -= 1

    def reset(self):
        # finding x2: tuple unpacking is still a lock-free write to
        # both guarded attrs
        self._entries, self._count = {}, 0
