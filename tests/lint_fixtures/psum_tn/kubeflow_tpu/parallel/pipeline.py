"""True negative: scalar loss is the only cross-pp all-reduce."""

from jax import lax


def pipeline_step(state, local_loss, axis):
    moved = lax.ppermute(state, axis, [(0, 1), (1, 0)])
    loss = lax.psum(local_loss, axis)
    return moved, loss
