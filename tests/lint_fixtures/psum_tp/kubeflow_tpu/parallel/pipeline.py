"""True positive: non-scalar psum back in the pipeline layer."""

from jax import lax


def pipeline_step(outputs, local_loss, axis):
    total = lax.psum(outputs, axis)  # finding: activation-buffer psum
    loss = lax.psum(local_loss, axis)  # allowed: THE scalar reduction
    return total, loss
