"""Suppression fixture: a real finding silenced on its line."""


def serve_once(handler):
    try:
        return handler()
    except:  # noqa: E722  # kftpu-lint: disable=no-bare-except
        return None
