"""Docstring fixture: the text '# kftpu-lint: disable=no-bare-except'
inside a string is documentation, not a suppression — it must neither
silence findings nor trip unused-suppression."""

SYNTAX_EXAMPLE = "use '# kftpu-lint: disable=no-bare-except' on the line"


def describe():
    return SYNTAX_EXAMPLE
