"""True negative: the canonical thaw idiom (and plain reads)."""


def reconcile(api, name, ns):
    job = api.get("TpuJob", name, ns).thaw()
    job.status["phase"] = "Running"
    api.update(job)


def annotate(self, name, ns):
    fresh = self.api.get("TpuJob", name, ns)
    fresh = fresh.thaw()  # rebinding through thaw clears the tracking
    fresh.metadata.labels.update({"a": "b"})
    return fresh


def read_only(api, name, ns):
    job = api.get("TpuJob", name, ns)
    phase = job.status.get("phase")  # reads are fine on the snapshot
    settings = {}.get("x", {})  # dict.get is not a store read
    settings["y"] = 1
    return phase
