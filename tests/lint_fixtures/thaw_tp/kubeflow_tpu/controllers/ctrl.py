"""True positive: read-modify-write on store results without .thaw()."""


def reconcile(api, name, ns):
    job = api.get("TpuJob", name, ns)
    job.status["phase"] = "Running"  # finding: subscript store, no thaw
    api.update(job)


def annotate(self, name, ns):
    fresh = self.api.get("TpuJob", name, ns)
    fresh.metadata.labels.update({"a": "b"})  # finding: mutator call
    fresh.metadata.generation += 1  # finding: aug-assign into snapshot
    return fresh


def adopt_all(api, owner):
    for pod in api.list("Pod", owner.ns):
        pod.metadata.owner_references.append(owner.ref)  # finding
