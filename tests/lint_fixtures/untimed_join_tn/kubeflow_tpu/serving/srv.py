"""True negative: every join is bounded."""

import threading


class Pump:
    def __init__(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self.done = False

    def _run(self):
        self.done = True

    def stop(self):
        self._thread.join(timeout=10.0)
        return self._thread.is_alive()
