"""True positive: untimed joins — a stuck worker or a lost task_done
parks shutdown forever."""

import queue
import threading


class Pump:
    def __init__(self):
        self._q = queue.Queue()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while True:
            self._q.get()
            self._q.task_done()

    def stop(self):
        self._thread.join()
        self._q.join()
