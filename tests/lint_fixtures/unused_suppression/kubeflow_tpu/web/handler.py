"""Unused-suppression fixture: the disable comment silences nothing."""


def serve_once(handler):
    try:
        return handler()
    except Exception:  # kftpu-lint: disable=no-bare-except
        return None
