"""Facade authentication + authorization.

The reference never exposes an open apiserver: controllers carry
serviceaccount tokens, web backends SubjectAccessReview every request
(`crud_backend/authz.py:46-80`), and /metrics sits behind kube-rbac-proxy
(`notebook-controller/config/default/manager_auth_proxy_patch.yaml`).
These tests pin the same boundary on `ApiServerApp(tokens=...)`: no
token → 401, token without RBAC → 403, status is a distinct subresource,
and the multiplexed watch stream only delivers what the identity may
watch.
"""

import pytest

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.rbac import (
    make_cluster_role,
    make_cluster_role_binding,
    resource_for_kind,
    seed_cluster_roles,
)
from kubeflow_tpu.api.tokens import TokenRegistry, service_account
from kubeflow_tpu.testing.apiserver_http import ApiServerApp, HttpApiClient
from kubeflow_tpu.testing.fake_apiserver import FakeApiServer
from kubeflow_tpu.web.wsgi import TestClient, serve


def secure_app(api=None):
    api = api or FakeApiServer()
    seed_cluster_roles(api)
    tokens = TokenRegistry()
    return api, tokens, ApiServerApp(api, tokens=tokens)


def bearer(token):
    return {"Authorization": f"Bearer {token}"}


def grant(api, name, role, user):
    api.create(make_cluster_role_binding(name, role, user))


CM = {"kind": "ConfigMap", "apiVersion": "kubeflow-tpu.org/v1",
      "metadata": {"name": "cm1", "namespace": "default"}, "spec": {"a": 1},
      "status": {}}


def test_resource_for_kind_pluralization():
    assert resource_for_kind("Notebook") == "notebooks"
    assert resource_for_kind("Study") == "studies"
    assert resource_for_kind("Pod") == "pods"
    assert resource_for_kind("TpuJob") == "tpujobs"
    # vowel+y pluralizes with +s (K8s convention: gateways, not gatewaies)
    assert resource_for_kind("Gateway") == "gateways"


def test_edit_role_cannot_escalate_via_rbac_writes():
    """The `resources: ['*']` wildcard must not reach RBAC objects: an
    edit-bound identity POSTing a ClusterRoleBinding to cluster-admin
    would otherwise self-escalate (real K8s `edit` excludes RBAC
    resources for the same reason)."""
    api, tokens, app = secure_app()
    grant(api, "edit", "kubeflow-edit", "mallory")
    client = TestClient(app, headers=bearer(tokens.issue("mallory")))
    crb = make_cluster_role_binding("evil", "kubeflow-admin", "mallory")
    resp = client.post("/apis/ClusterRoleBinding", crb.to_dict())
    assert resp.status == 403, resp.body
    # ...and can't read or rewrite roles either via the wildcard.
    assert client.get("/apis/ClusterRole").status == 403
    # ...nor via RBAC-kind SUBRESOURCES (the guard matches on the base
    # resource, so /status of a ClusterRole is covered too).
    role = client.get("/apis/ClusterRole/_/kubeflow-admin")
    assert role.status == 403
    put = client.request(
        "PUT", "/apis/ClusterRole/_/kubeflow-admin/status",
        {"kind": "ClusterRole", "apiVersion": "kubeflow-tpu.org/v1",
         "metadata": {"name": "kubeflow-admin", "namespace": ""},
         "spec": {}, "status": {"pwned": True}})
    assert put.status == 403, put.body
    # Admin's explicit RBAC rule still grants it.
    grant(api, "adm", "kubeflow-admin", "system:admin")
    admin = TestClient(app, headers=bearer(tokens.issue("system:admin")))
    assert admin.post("/apis/ClusterRoleBinding", crb.to_dict()).status == 201


def test_unauthenticated_request_rejected():
    _, _, app = secure_app()
    client = TestClient(app)
    assert client.post("/apis/ConfigMap", CM).status == 401
    assert client.get("/apis/ConfigMap").status == 401
    # Probes stay open (kubelet has no identity header).
    assert client.get("/healthz").status == 200


def test_unknown_token_rejected():
    _, _, app = secure_app()
    client = TestClient(app, headers=bearer("not-a-real-token"))
    assert client.get("/apis/ConfigMap").status == 401


def test_admin_full_access():
    api, tokens, app = secure_app()
    grant(api, "admin", "kubeflow-admin", "system:admin")
    client = TestClient(app, headers=bearer(tokens.issue("system:admin")))
    assert client.post("/apis/ConfigMap", CM).status == 201
    assert client.get("/apis/ConfigMap/default/cm1").status == 200
    assert client.get("/debug/traces").status == 200
    assert client.delete("/apis/ConfigMap/default/cm1").status == 200


def test_viewer_reads_but_cannot_write():
    api, tokens, app = secure_app()
    grant(api, "view", "kubeflow-view", "alice")
    client = TestClient(app, headers=bearer(tokens.issue("alice")))
    assert client.get("/apis/ConfigMap").status == 200
    resp = client.post("/apis/ConfigMap", CM)
    assert resp.status == 403
    assert "not allowed to create configmaps" in resp.json()["log"]
    assert client.delete("/apis/ConfigMap/default/x").status == 403
    # The traces drain clears the shared buffer — a write verb, so a
    # read-only identity must not reach it.
    assert client.get("/debug/traces").status == 403


def test_status_is_a_distinct_subresource():
    """Granting `tpujobs` update does NOT grant `tpujobs/status`; only the
    owning runtime identity's role carries the status rule (reference
    controllers get `.../status` verbs in their RBAC manifests)."""
    api, tokens, app = secure_app()
    api.create(make_cluster_role("editor", [
        {"verbs": ["get", "create", "update"], "resources": ["tpujobs"]},
    ]))
    api.create(make_cluster_role("tpujob-runtime", [
        {"verbs": ["get"], "resources": ["tpujobs"]},
        {"verbs": ["update"], "resources": ["tpujobs/status"]},
    ]))
    grant(api, "ed", "editor", "editor-user")
    ctl_user = service_account("kubeflow", "tpujob-controller")
    grant(api, "ctl", "tpujob-runtime", ctl_user)

    job = {"kind": "TpuJob", "apiVersion": "kubeflow-tpu.org/v1",
           "metadata": {"name": "j1", "namespace": "default"},
           "spec": {"replicas": 1,
                    "template": {"spec": {"containers": [
                        {"name": "w", "command": ["true"]}]}}},
           "status": {}}
    editor = TestClient(app, headers=bearer(tokens.issue("editor-user")))
    controller = TestClient(app, headers=bearer(tokens.issue(ctl_user)))
    assert editor.post("/apis/TpuJob", job).status == 201

    fetched = editor.get("/apis/TpuJob/default/j1").json()
    fetched["status"]["phase"] = "Running"
    put = "/apis/TpuJob/default/j1/status"
    assert editor.request("PUT", put, fetched).status == 403
    assert controller.request("PUT", put, fetched).status == 200
    # ...and the runtime identity cannot touch spec.
    assert controller.request(
        "PUT", "/apis/TpuJob/default/j1", fetched
    ).status == 403


def test_concrete_watch_requires_permission():
    api, tokens, app = secure_app()
    api.create(make_cluster_role("nb-only", [
        {"verbs": ["list", "watch"], "resources": ["notebooks"]},
    ]))
    grant(api, "nb", "nb-only", "bob")
    client = TestClient(app, headers=bearer(tokens.issue("bob")))
    ok = client.get(
        "/apis/Notebook?watch=true&resourceVersion=0&timeoutSeconds=0.05"
    )
    assert ok.status == 200
    denied = client.get(
        "/apis/Pod?watch=true&resourceVersion=0&timeoutSeconds=0.05"
    )
    assert denied.status == 403


def test_multiplexed_watch_filters_by_permission():
    """One `_` stream per client, but events only for kinds the identity
    may watch — a least-privilege controller needs no cluster-wide read."""
    api, tokens, app = secure_app()
    api.create(make_cluster_role("nb-only", [
        {"verbs": ["list", "watch"], "resources": ["notebooks"]},
    ]))
    grant(api, "nb", "nb-only", "bob")
    client = TestClient(app, headers=bearer(tokens.issue("bob")))

    api.create(new_resource("Notebook", "n1", "default",
                            spec={"template": {"spec": {"containers": [
                                {"name": "nb", "image": "img"}]}}}))
    api.create(new_resource("Secretish", "s1", "default", spec={"x": 1}))
    resp = client.get(
        "/apis/_?watch=true&resourceVersion=0&timeoutSeconds=0.05"
    )
    assert resp.status == 200
    kinds = {ev["object"]["kind"] for ev in resp.json()["events"]}
    assert kinds == {"Notebook"}


def test_pod_log_scoped_to_role(tmp_path):
    log = tmp_path / "p.log"
    log.write_text("hello from pod\n")
    api = FakeApiServer()
    seed_cluster_roles(api)
    tokens = TokenRegistry()
    app = ApiServerApp(api, log_root=str(tmp_path), tokens=tokens)
    pod = new_resource("Pod", "p", "default", spec={})
    pod.status["logPath"] = str(log)
    api.create(pod)
    api.create(make_cluster_role("no-logs", [
        {"verbs": ["get", "list"], "resources": ["pods"]},
    ]))
    grant(api, "nl", "no-logs", "carol")
    grant(api, "adm", "kubeflow-admin", "system:admin")

    carol = TestClient(app, headers=bearer(tokens.issue("carol")))
    admin = TestClient(app, headers=bearer(tokens.issue("system:admin")))
    assert carol.get("/apis/Pod/default/p").status == 200
    assert carol.get("/apis/Pod/default/p/log").status == 403
    assert admin.get("/apis/Pod/default/p/log").body == b"hello from pod\n"


def test_traces_require_cluster_scope():
    api, tokens, app = secure_app()
    api.create(new_resource("Role", "ns-admin", "team",
                            spec={"rules": [{"verbs": ["*"],
                                             "resources": ["*"]}]}))
    api.create(new_resource(
        "RoleBinding", "ns-admin", "team",
        spec={"roleRef": {"kind": "Role", "name": "ns-admin"},
              "subjects": [{"kind": "User", "name": "dave"}]}))
    client = TestClient(app, headers=bearer(tokens.issue("dave")))
    assert client.get("/debug/traces").status == 403


def test_namespaced_rolebinding_scopes_access():
    api, tokens, app = secure_app()
    api.create(new_resource(
        "RoleBinding", "edit", "team",
        spec={"roleRef": {"kind": "ClusterRole", "name": "kubeflow-edit"},
              "subjects": [{"kind": "User", "name": "erin"}]}))
    client = TestClient(app, headers=bearer(tokens.issue("erin")))
    body = {"kind": "ConfigMap", "apiVersion": "kubeflow-tpu.org/v1",
            "metadata": {"name": "c", "namespace": "team"},
            "spec": {}, "status": {}}
    assert client.post("/apis/ConfigMap", body).status == 201
    other = dict(body, metadata={"name": "c", "namespace": "prod"})
    assert client.post("/apis/ConfigMap", other).status == 403
    # Namespaced list OK in the granted namespace; all-namespaces denied.
    assert client.get("/apis/ConfigMap?namespace=team").status == 200
    assert client.get("/apis/ConfigMap").status == 403


def test_token_registry_roundtrip(tmp_path):
    reg = TokenRegistry()
    t1 = reg.issue("alice")
    reg.add("static-token", service_account("kubeflow", "ctl"))
    path = str(tmp_path / "tokens")
    reg.save(path)
    import os
    import stat

    # Credential file is owner-only (kube-apiserver token-auth-file).
    assert stat.S_IMODE(os.stat(path).st_mode) == 0o600
    loaded = TokenRegistry.load(path)
    assert loaded.authenticate(t1) == "alice"
    assert loaded.authenticate("static-token") == (
        "system:serviceaccount:kubeflow:ctl"
    )
    loaded.revoke(t1)
    assert loaded.authenticate(t1) is None


def test_http_client_token_end_to_end(tls_paths):
    """Over a real TLS socket: admin token works, no token →
    PermissionError (and the token never rides plaintext)."""
    api, tokens, app = secure_app()
    grant(api, "admin", "kubeflow-admin", "system:admin")
    server, _ = serve(app, host="127.0.0.1", port=0, tls=tls_paths)
    base = f"https://127.0.0.1:{server.server_port}"
    try:
        admin = HttpApiClient(
            base, token=tokens.issue("system:admin"), ca=tls_paths.ca_cert
        )
        created = admin.create(
            new_resource("ConfigMap", "cm", "default", spec={"k": "v"})
        )
        assert created.metadata.name == "cm"
        anon = HttpApiClient(base, token="", ca=tls_paths.ca_cert)
        with pytest.raises(PermissionError):
            anon.create(new_resource("ConfigMap", "cm2", "default", spec={}))
        with pytest.raises(PermissionError):
            anon.get("ConfigMap", "cm", "default")
    finally:
        server.shutdown()


def test_create_cannot_forge_status():
    """POST with a pre-filled status must not persist it unless the
    identity also holds the `<resource>/status` grant — otherwise a
    create-only identity forges phase=Succeeded (the real apiserver drops
    status on create for subresource-enabled kinds)."""
    api, tokens, app = secure_app()
    api.create(make_cluster_role("creator", [
        {"verbs": ["get", "create"], "resources": ["tpujobs"]},
    ]))
    api.create(make_cluster_role("runtime", [
        {"verbs": ["get", "create"], "resources": ["tpujobs"]},
        {"verbs": ["update"], "resources": ["tpujobs/status"]},
    ]))
    grant(api, "cr", "creator", "creator-user")
    grant(api, "rt", "runtime", "runtime-user")
    body = {"kind": "TpuJob", "apiVersion": "kubeflow-tpu.org/v1",
            "metadata": {"name": "forged", "namespace": "default"},
            "spec": {"replicas": 1},
            "status": {"phase": "Succeeded"}}
    creator = TestClient(app, headers=bearer(tokens.issue("creator-user")))
    assert creator.post("/apis/TpuJob", body).status == 201
    assert api.get("TpuJob", "forged").status == {}
    # The owning runtime identity's status rides through (the remote
    # WorkloadMaterializer pattern: create already-Running objects).
    body2 = dict(body, metadata={"name": "ok", "namespace": "default"})
    runtime = TestClient(app, headers=bearer(tokens.issue("runtime-user")))
    assert runtime.post("/apis/TpuJob", body2).status == 201
    assert api.get("TpuJob", "ok").status == {"phase": "Succeeded"}
