"""Ring attention (sequence-parallel shard_map) vs dense reference."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from kubeflow_tpu.ops import dense_attention, ring_attention
from kubeflow_tpu.parallel import MeshSpec, build_mesh


def _qkv(key, b=2, s=16, h=4, d=8):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, s, h, d)
    return (
        jax.random.normal(kq, shape, jnp.float32),
        jax.random.normal(kk, shape, jnp.float32),
        jax.random.normal(kv, shape, jnp.float32),
    )


def test_dense_attention_matches_naive():
    q, k, v = _qkv(jax.random.PRNGKey(0))
    out = dense_attention(q, k, v, causal=False)
    # Naive per-query softmax.
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(q.shape[-1])
    w = np.exp(scores - scores.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bkhd->bqhd", w, v)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_dense_causal_ignores_future():
    q, k, v = _qkv(jax.random.PRNGKey(1))
    out = dense_attention(q, k, v, causal=True)
    # Changing future keys/values must not change earlier outputs.
    k2 = k.at[:, -1].set(100.0)
    v2 = v.at[:, -1].set(-3.0)
    out2 = dense_attention(q, k2, v2, causal=True)
    np.testing.assert_allclose(
        np.asarray(out[:, :-1]), np.asarray(out2[:, :-1]), rtol=1e-6
    )
    assert not np.allclose(np.asarray(out[:, -1]), np.asarray(out2[:, -1]))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sp", [2, 4])
def test_ring_matches_dense(devices, causal, sp):
    mesh = build_mesh(MeshSpec(dp=2, sp=sp, tp=8 // (2 * sp) or 1), devices)
    q, k, v = _qkv(jax.random.PRNGKey(2), b=4, s=32)
    ref = dense_attention(q, k, v, causal=causal)
    out = jax.jit(
        lambda a, b_, c: ring_attention(a, b_, c, mesh, causal=causal)
    )(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_ring_trivial_sp_falls_back(mesh8):
    q, k, v = _qkv(jax.random.PRNGKey(3))
    out = ring_attention(q, k, v, mesh8, causal=True)  # mesh8 has sp=1
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_ring_with_sharded_inputs(devices):
    # End-to-end under jit with inputs actually laid out over the mesh.
    mesh = build_mesh(MeshSpec(dp=2, sp=4), devices)
    q, k, v = _qkv(jax.random.PRNGKey(4), b=4, s=64)
    sh = NamedSharding(mesh, P(("dp", "fsdp"), "sp", None, None))
    qs, ks, vs = (jax.device_put(t, sh) for t in (q, k, v))
    out = jax.jit(
        lambda a, b_, c: ring_attention(a, b_, c, mesh, causal=True)
    )(qs, ks, vs)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )
