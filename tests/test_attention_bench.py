"""CI smoke for `bench.py --workload attention` (docs/perf.md): the
kernel microbench must run end-to-end at tiny interpreted shapes and emit
driver-parsable JSON metric lines, including the schedule accounting the
attention overhaul is gated on (compact grid steps, packed lse bytes)."""

import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_attention_bench_smoke_emits_parsable_metrics():
    result = subprocess.run(
        [
            sys.executable, "bench.py", "--workload", "attention",
            "--attn-seq-lens", "128,256", "--steps", "1",
            "--warmup-steps", "1", "--batch-size", "1",
            "--head-dim", "32", "--attn-heads", "2",
            "--flash-block-q", "128", "--flash-block-k", "128",
            "--roofline-seq", "128", "--roofline-batch", "1",
            "--roofline-layers", "2", "--roofline-d-model", "64",
            "--roofline-d-ff", "128", "--roofline-vocab", "512",
        ],
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True,
        text=True,
        timeout=280,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    metrics = {}
    for line in result.stdout.splitlines():
        if not line.startswith("{"):
            continue
        m = json.loads(line)
        # The driver's parse contract — same shape as every other bench.
        assert set(m) == {"metric", "value", "unit", "vs_baseline"}, m
        assert isinstance(m["value"], (int, float)) and m["value"] > 0, m
        metrics[m["metric"]] = m
    for s in (128, 256):
        for stem in (
            "attention_flash_fwd_tflops",
            "attention_flash_fwdbwd_tflops",
            "attention_causal_grid_steps",
            "attention_lse_hbm_bytes",
            "attention_bwd_hbm_bytes",
        ):
            assert f"{stem}_s{s}" in metrics, (stem, s, sorted(metrics))
    # The schedule accounting must show the overhaul: at S=256 with
    # 128-wide blocks the compact grid runs 3 of the rectangle's 4
    # steps, and the packed lse is 1/128th the replicated bytes.
    grid = metrics["attention_causal_grid_steps_s256"]
    assert grid["value"] == 3 and grid["vs_baseline"] == 0.75, grid
    lse = metrics["attention_lse_hbm_bytes_s256"]
    assert abs(lse["vs_baseline"] - 1 / 128) < 1e-6, lse
    # Dense ran at these lengths, so the TFLOP/s rows carry a real ratio.
    assert metrics["attention_flash_fwd_tflops_s256"]["vs_baseline"] > 0
    # Fused one-pass backward (ISSUE 7): the bwd HBM-byte row's ratio is
    # the fused/two-pass fraction — strictly < 1 whenever fused engages
    # (these shapes fuse; the run would have FAILED on the jaxpr gate if
    # dispatch and accounting drifted), and the unit names the path.
    for s in (128, 256):
        bwd = metrics[f"attention_bwd_hbm_bytes_s{s}"]
        assert 0 < bwd["vs_baseline"] < 1, bwd
        assert "fused one-pass" in bwd["unit"], bwd
    # Per-phase roofline rows (the mechanical docs/architecture.md
    # table): one ms row per phase, unit carrying TFLOP/GB/bound.
    for phase in ("attn_fwd", "attn_bwd", "mlp", "optimizer"):
        row = metrics[f"roofline_{phase}_ms_s128"]
        assert row["value"] > 0, row
        assert "bound:" in row["unit"], row
    # The roofline table itself rides stderr for humans.
    assert "| phase | ms | TFLOP | GB moved |" in result.stderr
    assert "roofline saturated phase" in result.stderr
