"""Chaos layer units: seeded schedules, the fault proxy, and the client
hardening each fault class forced (`kubeflow_tpu/testing/chaos.py`).

The full fleet-under-faults story is tests/e2e/test_chaos_soak_e2e.py;
these tests pin each mechanism in isolation so a soak failure bisects.
"""

import time

import pytest

from kubeflow_tpu.api.objects import ObjectMeta, Resource
from kubeflow_tpu.controllers.runtime import retry_on_conflict
from kubeflow_tpu.testing.apiserver_http import (
    ApiServerApp,
    CircuitBreaker,
    HttpApiClient,
    _stream_rejected,
)
from kubeflow_tpu.testing.chaos import (
    APISERVER_KILL,
    FAULT_CLASSES,
    HA_FAULT_CLASSES,
    ChaosProxy,
    Fault,
    FaultSchedule,
)
from kubeflow_tpu.testing.fake_apiserver import (
    Conflict,
    FakeApiServer,
    Unavailable,
)
from kubeflow_tpu.web.wsgi import Response, serve


def mk(name, kind="Widget", ns="default", spec=None):
    return Resource(
        kind=kind, metadata=ObjectMeta(name=name, namespace=ns),
        spec=spec or {"size": 1},
    )


def wait_for(pred, timeout=20.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


# -- schedule ---------------------------------------------------------------


def test_schedule_reproducible_from_seed():
    """The soak's repro contract: one integer reproduces the plan."""
    a, b = FaultSchedule(1234), FaultSchedule(1234)
    assert a.plan == b.plan
    assert a.plan != FaultSchedule(1235).plan
    # The first round carries one entry of EVERY class, so even a short
    # soak can reach 100% class coverage.
    first_round = {f.cls for f in a.plan[: len(FAULT_CLASSES)]}
    assert first_round == set(FAULT_CLASSES)


def test_schedule_eligibility_routing_and_coverage():
    sched = FaultSchedule(7, faults_per_class=1, max_gap=1)
    requests = [
        ("POST", "/apis/Pod", ""),
        ("GET", "/apis/_", "watch=true&stream=true&resourceVersion=0"),
        ("GET", "/apis/_", "watch=true&resourceVersion=0"),
        ("GET", "/apis/Pod", ""),
    ]
    seen: list[tuple[str, str]] = []
    for _ in range(200):
        if sched.exhausted:
            break
        for method, path, query in requests:
            fault = sched.next_fault(method, path, query)
            if fault is not None:
                seen.append((fault.cls, method))
                sched.mark_injected(fault)  # the proxy's effect report
    assert sched.exhausted, sched
    assert sched.coverage() == {c: 1 for c in FAULT_CLASSES}
    for cls, method in seen:
        if cls in ("delay_write", "crash_before_ack"):
            assert method == "POST"
        if cls in ("slow_stream", "truncate_stream", "stale_gone"):
            assert method == "GET"


def test_schedule_requeue_keeps_coverage_honest():
    """A consumed-but-ineffective fault goes back in the plan: coverage
    counts wire effects, never mere consumption, and the schedule is
    not exhausted while an injection is pending or in flight."""
    sched = FaultSchedule(3, faults_per_class=1, max_gap=1)
    fault = None
    while fault is None:  # skip gap cooldowns
        fault = sched.next_fault(
            "GET", "/apis/_", "watch=true&stream=true&resourceVersion=0"
        )
    assert not sched.exhausted  # in flight
    sched.requeue(fault)
    assert sched.coverage()[fault.cls] == 0
    assert not sched.exhausted
    again = None
    while again is None:
        again = sched.next_fault(
            "GET", "/apis/_", "watch=true&stream=true&resourceVersion=0"
        )
    assert again == fault  # requeued at the head
    sched.mark_injected(again)
    assert sched.coverage()[fault.cls] == 1


def test_empty_schedule_injects_nothing():
    sched = FaultSchedule(0, faults_per_class=0)
    assert sched.plan == ()
    assert sched.next_fault("GET", "/apis/Pod", "") is None
    assert sched.exhausted


# -- proxy ------------------------------------------------------------------


@pytest.fixture()
def proxied():
    """FakeApiServer behind the facade behind a chaos proxy, plus a
    hardened client pointed at the proxy. The schedule starts EMPTY;
    tests stage targeted faults via stage()."""
    api = FakeApiServer()
    server, _ = serve(ApiServerApp(api), host="127.0.0.1", port=0)
    schedule = FaultSchedule(0, faults_per_class=0)
    proxy = ChaosProxy(
        "127.0.0.1", server.server_port, schedule
    ).start()
    client = HttpApiClient(
        proxy.base_url,
        timeout=5.0,
        watch_poll_timeout=1.0,
        watch_retry=0.05,
        retry_base=0.02,
        breaker_cooldown=0.2,
        stream_degraded_seconds=0.3,
    )

    def stage(*faults):
        schedule._pending.extend(faults)

    yield api, client, stage, schedule
    client.close()
    proxy.stop()
    server.shutdown()


def test_proxy_passthrough_keepalive(proxied):
    """No faults staged: the proxy is invisible — CRUD works and the
    client's pooled connections survive end-to-end."""
    api, client, _, _ = proxied
    for i in range(10):
        client.create(mk(f"w{i}"))
    assert len(client.list("Widget")) == 10
    assert client.handshakes <= 2, client.handshakes
    got = client.get("Widget", "w3")
    got.status["phase"] = "Ready"
    client.update_status(got)
    assert api.get("Widget", "w3").status["phase"] == "Ready"


def test_ha_fault_classes_extend_the_default_plan():
    """HA_FAULT_CLASSES is the 7-class wire plan plus apiserver_kill,
    and a schedule built from it stays a pure function of its seed."""
    assert HA_FAULT_CLASSES == FAULT_CLASSES + (APISERVER_KILL,)
    a = FaultSchedule(7, faults_per_class=1, classes=HA_FAULT_CLASSES)
    b = FaultSchedule(7, faults_per_class=1, classes=HA_FAULT_CLASSES)
    assert a.plan == b.plan
    assert sum(1 for f in a.plan if f.cls == APISERVER_KILL) == 1


def test_proxy_apiserver_kill_runs_executor_aborts_and_retargets():
    """The kill_active seam, end to end: an apiserver_kill entry makes
    the proxy call the driver's executor and abort the in-flight
    connection (what a real SIGKILL does to that client); the executor
    returns the STANDBY's address and the proxy retargets, so the
    hardened client's fresh-connection retry is served by the new
    active — an active-passive pair on per-replica ports stays
    reachable through the one proxied address across the takeover."""
    active = FakeApiServer()
    active.create(mk("pre-kill"))
    standby = FakeApiServer()  # "took over": same world + one marker
    standby.create(mk("pre-kill"))
    standby.create(mk("served-by-standby"))
    server_a, _ = serve(ApiServerApp(active), host="127.0.0.1", port=0)
    server_b, _ = serve(ApiServerApp(standby), host="127.0.0.1", port=0)
    schedule = FaultSchedule(0, faults_per_class=0)
    kills = []

    def executor():
        kills.append(1)
        return ("127.0.0.1", server_b.server_port)

    proxy = ChaosProxy(
        "127.0.0.1", server_a.server_port, schedule, kill_active=executor
    ).start()
    client = HttpApiClient(proxy.base_url, timeout=5.0, retry_base=0.02)
    try:
        client.create(mk("held"))  # warm the pool: the retry is GET-safe
        schedule._pending.append(Fault(APISERVER_KILL, 0.0, 1))
        names = {o.metadata.name for o in client.list("Widget")}
        assert "served-by-standby" in names, names  # retargeted
        assert kills == [1]
        assert schedule.coverage().get(APISERVER_KILL) == 1
        assert schedule.exhausted
    finally:
        client.close()
        proxy.stop()
        server_a.shutdown()
        server_b.shutdown()


def test_proxy_apiserver_kill_without_executor_requeues(proxied):
    """A kill entry reaching a proxy with no executor is requeued, not
    silently dropped: traffic proceeds, coverage stays honest at zero,
    and the plan is NOT exhausted — the soak's coverage gate would fail
    loudly instead of reporting a kill that never happened."""
    api, client, stage, schedule = proxied
    stage(Fault(APISERVER_KILL, 0.0, 0))
    client.create(mk("through"))
    assert api.get("Widget", "through") is not None
    assert not schedule.coverage().get(APISERVER_KILL)
    assert not schedule.exhausted


def test_injected_503_burst_write_retries_once_landed(proxied):
    """A 5xx burst never reached the server: the bounded retry lands the
    write exactly once."""
    api, client, stage, _ = proxied
    stage(Fault("error_5xx", 2.0, 0))
    created = client.create(mk("burst-victim"))
    assert created.metadata.resource_version > 0
    assert len(api.list("Widget")) == 1
    assert client.retries_total >= 1


def test_crash_before_ack_create_recovers_without_duplicate(proxied):
    """The duplicate-side-effect trap: the create COMMITTED upstream but
    the ack died. The retry hits AlreadyExists, recognizes the stored
    object as its own write, and returns it — one object, no error."""
    api, client, stage, _ = proxied
    stage(Fault("crash_before_ack", 0.0, 0))
    created = client.create(mk("ambiguous", spec={"size": 9}))
    assert created.spec == {"size": 9}
    assert len(api.list("Widget")) == 1
    assert client.retries_total >= 1


def test_crash_before_ack_create_recovers_past_mutating_admission(proxied):
    """Admission that ADDS defaulted fields must not make the client
    disown its own committed create: recovery uses containment, not
    spec equality."""
    api, client, stage, _ = proxied

    def default_tier(obj):
        obj.spec.setdefault("tier", "standard")
        return obj

    api.register_admission(default_tier, "Widget")
    stage(Fault("crash_before_ack", 0.0, 0))
    created = client.create(mk("defaulted", spec={"size": 3}))
    assert created.spec == {"size": 3, "tier": "standard"}
    assert len(api.list("Widget")) == 1


def test_crash_before_ack_delete_recovers(proxied):
    api, client, stage, _ = proxied
    client.create(mk("doomed"))
    stage(Fault("crash_before_ack", 0.0, 0))
    client.delete("Widget", "doomed")  # must not raise NotFound
    assert api.list("Widget") == []


def test_reset_mid_response_read_survives(proxied):
    """A severed response on a read: the GET retries (reads are
    idempotent) or surfaces a clean error the caller's backoff absorbs;
    either way the next call works."""
    api, client, stage, _ = proxied
    client.create(mk("steady"))
    stage(Fault("reset_mid_response", 0.5, 0))
    try:
        client.get("Widget", "steady")
    except Exception:
        pass  # one failed read is allowed; the endpoint must recover
    assert client.get("Widget", "steady").metadata.name == "steady"


def test_stale_gone_watch_relists_and_streams_on(proxied):
    """An injected 410 forces the informer's relist path; no events are
    lost across it."""
    api, client, stage, _ = proxied
    seen = []
    client.watch(lambda ev, o: seen.append(o.metadata.name), "Widget")
    api.create(mk("before"))
    assert wait_for(lambda: "before" in seen), seen
    stage(Fault("stale_gone", 0.0, 0))
    api.create(mk("after-gone"))
    assert wait_for(lambda: "after-gone" in seen), seen


def test_truncated_stream_reconnects_no_loss(proxied):
    """A stream severed mid-body (no terminal chunk) is a transport
    failure: the client re-opens and resumes from its bookmark."""
    api, client, stage, _ = proxied
    seen = []
    client.watch(lambda ev, o: seen.append(o.metadata.name), "Widget")
    api.create(mk("first"))
    assert wait_for(lambda: "first" in seen), seen
    stage(Fault("truncate_stream", 64.0, 0))
    for i in range(5):
        api.create(mk(f"tail{i}"))
    assert wait_for(
        lambda: all(f"tail{i}" in seen for i in range(5)), timeout=30.0
    ), seen


def test_slow_stream_still_delivers(proxied):
    api, client, stage, _ = proxied
    seen = []
    client.watch(lambda ev, o: seen.append(o.metadata.name), "Widget")
    stage(Fault("slow_stream", 0.05, 0))
    api.create(mk("sluggish"))
    assert wait_for(lambda: "sluggish" in seen, timeout=30.0), seen


def test_delayed_write_still_exactly_once(proxied):
    api, client, stage, _ = proxied
    stage(Fault("delay_write", 0.2, 0))
    t0 = time.monotonic()
    client.create(mk("held"))
    assert time.monotonic() - t0 >= 0.15
    assert len(api.list("Widget")) == 1


# -- client hardening units -------------------------------------------------


def test_stream_rejection_classifier():
    """Only an AFFIRMATIVE stream rejection may trigger the long-poll
    fallback — the round-5 bug was any stray 400 disabling streaming
    for the process lifetime."""
    assert _stream_rejected('{"success": false, "log": "unknown parameter: stream"}')
    assert _stream_rejected("streaming watch not supported")
    assert _stream_rejected("invalid query parameter: stream")
    assert not _stream_rejected('{"log": "resourceVersion must be an integer"}')
    assert not _stream_rejected("chaos: injected apiserver outage")
    # An intermediary's "upstream" is not the stream parameter, and a
    # transient that HAPPENS to a stream is not a rejection OF streams.
    assert not _stream_rejected("upstream connect error or disconnect")
    assert not _stream_rejected("stream timeout")
    assert not _stream_rejected("stream reset by peer")
    # Non-object JSON bodies classify without crashing.
    assert not _stream_rejected("null")
    assert not _stream_rejected("[1, 2]")
    assert not _stream_rejected("")


def test_circuit_breaker_opens_half_opens_closes():
    br = CircuitBreaker(threshold=3, cooldown=0.1)
    assert br.allow()
    for _ in range(3):
        br.failure()
    assert br.trips == 1
    assert not br.allow()  # open: fail fast
    time.sleep(0.12)
    assert br.allow()       # half-open probe slot
    assert not br.allow()   # only ONE probe per cooldown window
    br.success()
    assert br.allow() and br.allow()  # closed again


def test_client_breaker_sheds_to_fail_fast():
    """Repeated transport failures open the endpoint's circuit: the
    client stops hammering a dead socket and fails fast with
    Unavailable until the cooldown probe."""
    import socket

    # A port with nothing behind it (bind, never accept, then close —
    # connects are refused immediately).
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    client = HttpApiClient(
        f"http://127.0.0.1:{port}",
        timeout=0.5,
        breaker_threshold=3,
        breaker_cooldown=30.0,
    )
    for _ in range(3):
        with pytest.raises(OSError):
            client.get("Widget", "x")
    with pytest.raises(Unavailable) as exc:
        client.get("Widget", "x")
    assert "circuit open" in str(exc.value)
    (trips, is_open), = [
        v for k, v in client.breaker_state().items() if "Widget" in k
    ]
    assert trips == 1 and is_open
    client.close()


def test_record_event_replay_is_idempotent():
    """Event names derive from content: a replayed emission (lost ack →
    retry) lands on the SAME Event instead of duplicating it; distinct
    occurrences still record separately."""
    api = FakeApiServer()
    about = api.create(mk("thing"))
    first = api.record_event(about, "Tested", "hello")
    again = api.record_event(about, "Tested", "hello")
    assert first.metadata.name == again.metadata.name
    assert len(api.list("Event")) == 1
    api.record_event(about, "Tested", "different message")
    assert len(api.list("Event")) == 2


def test_retry_on_conflict_rereads_until_success():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise Conflict("stale rv")
        return "landed"

    assert retry_on_conflict(flaky) == "landed"
    assert len(calls) == 3
    with pytest.raises(Conflict):
        retry_on_conflict(lambda: (_ for _ in ()).throw(Conflict("x")),
                          attempts=2)


def test_wsgi_skips_auto_content_length_when_framed():
    """A handler that sets its own framing header keeps it: the server
    must never emit two Content-Lengths (or Content-Length beside
    Transfer-Encoding) on a keep-alive connection."""
    import http.client

    from kubeflow_tpu.web.wsgi import App

    app = App("framing")
    body = b'{"ok": true}'

    @app.route("/framed")
    def framed(req):
        return Response(
            body, headers=[("Content-Length", str(len(body)))]
        )

    @app.route("/plain")
    def plain(req):
        return Response(body)

    server, _ = serve(app, host="127.0.0.1", port=0)
    try:
        for path in ("/framed", "/plain"):
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.server_port, timeout=5
            )
            conn.request("GET", path)
            resp = conn.getresponse()
            lengths = resp.headers.get_all("Content-Length")
            assert lengths == [str(len(body))], (path, lengths)
            assert resp.read() == body
            conn.close()
    finally:
        server.shutdown()


def test_replica_kill_schedule_reproducible_and_coverage_honest():
    """The serving chaos plan (ISSUE 11) shares the seeded-plan
    contract: same seed → identical plan; kills fire only when the load
    fraction passes their trigger; coverage counts landed kills only."""
    from kubeflow_tpu.testing.chaos import ReplicaKill, ReplicaKillSchedule

    a = ReplicaKillSchedule(97, kills=3, replicas=4)
    b = ReplicaKillSchedule(97, kills=3, replicas=4)
    assert a.plan == b.plan
    assert len(a.plan) == 3
    fractions = [k.at_fraction for k in a.plan]
    assert fractions == sorted(fractions)
    assert all(0.2 <= f <= 0.7 for f in fractions)
    assert all(0 <= k.victim < 4 for k in a.plan)
    assert ReplicaKillSchedule(98, kills=3, replicas=4).plan != a.plan

    # Nothing fires before its trigger point.
    assert a.due(0.0) is None
    first = a.due(a.plan[0].at_fraction + 0.01)
    assert first == a.plan[0]
    # At most one kill per poll, and coverage counts only landed kills.
    assert a.coverage() == {"replica_kill": 0}
    a.mark_injected(first)
    assert a.coverage() == {"replica_kill": 1}
    assert not a.exhausted
    assert a.due(1.0) == a.plan[1]
    assert a.due(1.0) == a.plan[2]
    assert a.due(1.0) is None
    assert a.exhausted

    targeted = ReplicaKillSchedule.from_plan(
        [ReplicaKill("replica_kill", 0.5, 1)]
    )
    assert targeted.due(0.4) is None
    assert targeted.due(0.6).victim == 1
