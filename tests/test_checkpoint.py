"""Orbax checkpoint manager + resumable fit() with divergence guard."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.resnet import tiny_resnet
from kubeflow_tpu.train import (
    Checkpointer,
    SyntheticImages,
    TrainConfig,
    Trainer,
    fit,
)


@pytest.fixture
def trainer(mesh8):
    config = TrainConfig(
        batch_size=16, learning_rate=0.05, warmup_steps=2, total_steps=20
    )
    return Trainer(
        tiny_resnet(), config, mesh8, example_input_shape=(2, 32, 32, 3)
    )


@pytest.fixture
def data(mesh8):
    return SyntheticImages(
        mesh8, batch_size=16, image_size=32, num_classes=10, dtype=jnp.float32
    )


def _params_close(a, b):
    flat_a = jax.tree_util.tree_leaves(a)
    flat_b = jax.tree_util.tree_leaves(b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)


def test_save_restore_roundtrip(trainer, data, tmp_path):
    state = trainer.init_state(jax.random.PRNGKey(0))
    step = trainer.make_train_step()
    state, _ = step(state, next(iter(data)))

    ckpt = Checkpointer(tmp_path / "ckpt", save_interval_steps=1)
    assert ckpt.save(1, state, force=True)
    ckpt.wait()

    restored, at, data_state = ckpt.restore_latest(trainer.abstract_state())
    assert at == 1
    assert data_state is None  # none was passed to save()
    assert int(restored.step) == 1
    _params_close(restored.params, state.params)
    _params_close(restored.opt_state, state.opt_state)
    # Restored arrays carry the mesh shardings from the abstract template.
    stem = restored.params["conv_stem"]["kernel"]
    assert "fsdp" in str(stem.sharding.spec)
    ckpt.close()


def test_fit_resumes_where_it_left_off(trainer, data, tmp_path):
    ckpt = Checkpointer(tmp_path / "ckpt", save_interval_steps=1)
    r1 = fit(trainer, data, total_steps=3, checkpointer=ckpt, log_every=1)
    assert r1.resumed_from is None and r1.steps_done == 3
    ckpt.wait()

    ckpt2 = Checkpointer(tmp_path / "ckpt", save_interval_steps=1)
    r2 = fit(trainer, data, total_steps=6, checkpointer=ckpt2, log_every=1)
    assert r2.resumed_from == 3
    assert r2.steps_done == 3  # only the remaining steps ran
    assert int(r2.state.step) == 6
    ckpt2.close()


def test_fit_without_checkpointer(trainer, data):
    r = fit(trainer, data, total_steps=2, log_every=1)
    assert r.steps_done == 2 and len(r.history) == 2
    assert r.history[-1]["examples_per_sec"] > 0


def test_resume_matches_uninterrupted(trainer, data, tmp_path):
    # train 4 straight vs train 2, "crash", resume to 4 — same params.
    straight = fit(trainer, data, total_steps=4, log_every=1).state

    ckpt = Checkpointer(tmp_path / "ck", save_interval_steps=1)
    fit(trainer, data, total_steps=2, checkpointer=ckpt, log_every=1)
    ckpt.wait()
    resumed = fit(
        trainer, data, total_steps=4,
        checkpointer=Checkpointer(tmp_path / "ck", save_interval_steps=1),
        log_every=1,
    ).state
    _params_close(straight.params, resumed.params)


def test_fit_noop_when_already_past_total_steps(trainer, data, tmp_path):
    ckpt = Checkpointer(tmp_path / "ck2", save_interval_steps=1)
    fit(trainer, data, total_steps=4, checkpointer=ckpt, log_every=1)
    ckpt.wait()
    r = fit(
        trainer, data, total_steps=2,
        checkpointer=Checkpointer(tmp_path / "ck2", save_interval_steps=1),
        log_every=1,
    )
    assert r.steps_done == 0 and r.resumed_from == 4
    assert int(r.state.step) == 4


def test_fit_short_data_raises(trainer, tmp_path):
    batches = []  # empty finite iterable
    import pytest as _pytest

    with _pytest.raises(ValueError, match="exhausted"):
        fit(trainer, batches, total_steps=2, log_every=1)


# -- restore_latest edge cases (ISSUE 5 satellite) -------------------------


def _save_steps(trainer, data, tmp_path, steps, interval=1):
    state = trainer.init_state(jax.random.PRNGKey(0))
    step_fn = trainer.make_train_step()
    it = iter(data)
    ckpt = Checkpointer(tmp_path / "ck", save_interval_steps=interval)
    for s in range(1, max(steps) + 1):
        state, _ = step_fn(state, next(it))
        if s in steps:
            ckpt.save(s, state, force=True, data_state={"position": s})
    ckpt.wait()
    ckpt.close()
    return state


def test_restore_latest_empty_directory(trainer, tmp_path):
    ckpt = Checkpointer(tmp_path / "empty", save_interval_steps=1)
    assert ckpt.restore_latest(trainer.abstract_state()) is None
    ckpt.close()


def test_restore_falls_back_past_corruption_and_resaves(
    trainer, data, tmp_path
):
    """A flipped byte in the newest checkpoint: restore must verify,
    QUARANTINE the bad step and fall back to the previous one — and a
    later save at the quarantined step number must not collide with the
    corpse."""
    from kubeflow_tpu.testing.chaos import apply_checkpoint_fault

    state = _save_steps(trainer, data, tmp_path, steps={1, 2, 3})
    assert apply_checkpoint_fault(tmp_path / "ck", "corrupt_checkpoint")

    ckpt = Checkpointer(tmp_path / "ck", save_interval_steps=1)
    restored = ckpt.restore_latest(trainer.abstract_state())
    assert restored.step == 2
    assert restored.data_state == {"position": 2}
    # The corpse is out of the numeric namespace, forensics preserved.
    quarantined = [
        p.name for p in (tmp_path / "ck").iterdir()
        if p.name.startswith("corrupt-")
    ]
    assert quarantined == ["corrupt-3"]
    # Re-saving step 3 after the fallback works (no StepAlreadyExists).
    assert ckpt.save(3, state, force=True)
    ckpt.wait()
    restored = ckpt.restore_latest(trainer.abstract_state())
    assert restored.step == 3
    ckpt.close()


def test_restore_falls_back_on_garbled_manifest(trainer, data, tmp_path):
    from kubeflow_tpu.testing.chaos import apply_checkpoint_fault

    _save_steps(trainer, data, tmp_path, steps={1, 2})
    assert apply_checkpoint_fault(tmp_path / "ck", "corrupt_manifest")
    ckpt = Checkpointer(tmp_path / "ck", save_interval_steps=1)
    restored = ckpt.restore_latest(trainer.abstract_state())
    assert restored.step == 1
    ckpt.close()


def test_restore_missing_manifest_treated_as_torn_write(
    trainer, data, tmp_path
):
    """A SIGKILL between orbax's commit and the manifest write leaves a
    complete-looking step with no manifest: restore must treat it as
    unverifiable and fall back — never load what it cannot certify."""
    from kubeflow_tpu.train.checkpoint import MANIFEST_NAME

    _save_steps(trainer, data, tmp_path, steps={1, 2})
    ((tmp_path / "ck") / "2" / MANIFEST_NAME).unlink()
    ckpt = Checkpointer(tmp_path / "ck", save_interval_steps=1)
    restored = ckpt.restore_latest(trainer.abstract_state())
    assert restored.step == 1
    ckpt.close()


def test_restore_survives_eviction_racing_it(trainer, data, tmp_path):
    """max_to_keep retention in another process can delete the step a
    restore just listed: a vanished step directory must fall back, not
    crash (FileNotFoundError) — the same path corruption takes."""
    import shutil

    _save_steps(trainer, data, tmp_path, steps={1, 2, 3})
    ckpt = Checkpointer(tmp_path / "ck", save_interval_steps=1)
    # The manager has listed the steps; now "another process" evicts
    # the newest before the restore reads it.
    assert ckpt.latest_step() == 3
    shutil.rmtree(tmp_path / "ck" / "3")
    restored = ckpt.restore_latest(trainer.abstract_state())
    assert restored.step == 2
    ckpt.close()


def test_read_only_restore_skips_without_quarantine(trainer, data, tmp_path):
    """A restore-only consumer (serving) walking a live training dir
    must never rename the writer's steps: invalid steps are skipped in
    place — a committed save whose manifest is still in flight would
    otherwise be destroyed by a racing reader."""
    from kubeflow_tpu.train.checkpoint import MANIFEST_NAME

    _save_steps(trainer, data, tmp_path, steps={1, 2})
    # Simulate the writer's manifest still being in flight for step 2.
    ((tmp_path / "ck") / "2" / MANIFEST_NAME).unlink()
    ckpt = Checkpointer(tmp_path / "ck", save_interval_steps=1,
                        read_only=True)
    restored = ckpt.restore_latest(trainer.abstract_state())
    assert restored.step == 1
    # Step 2 is untouched on disk — nothing was renamed away.
    assert (tmp_path / "ck" / "2").is_dir()
    assert not [
        p for p in (tmp_path / "ck").iterdir()
        if p.name.startswith("corrupt-")
    ]
    ckpt.close()


def test_vacuous_manifest_is_invalid_not_a_crash(trainer, data, tmp_path):
    """A manifest certifying ZERO files (a manifest write that raced
    eviction, or tampering) must fail verification and take the normal
    quarantine-and-fall-back path — pre-fix it verified trivially and
    the doomed orbax restore then crashed the whole resume."""
    import json as json_mod

    from kubeflow_tpu.train.checkpoint import MANIFEST_NAME

    _save_steps(trainer, data, tmp_path, steps={1, 2})
    (tmp_path / "ck" / "2" / MANIFEST_NAME).write_text(
        json_mod.dumps({"version": 1, "files": {}, "data_state": None})
    )
    ckpt = Checkpointer(tmp_path / "ck", save_interval_steps=1)
    restored = ckpt.restore_latest(trainer.abstract_state())
    assert restored.step == 1
    ckpt.close()


def test_update_data_state_rewrites_manifest_in_place(
    trainer, data, tmp_path
):
    """`update_data_state` swaps only the manifest's data_state (the
    rollback-salt durability path): the step must still verify and
    restore with the new state; a step without a manifest returns
    False instead of inventing one."""
    _save_steps(trainer, data, tmp_path, steps={1})
    ckpt = Checkpointer(tmp_path / "ck", save_interval_steps=1)
    assert ckpt.update_data_state(1, {"position": 1, "salt": 7})
    restored = ckpt.restore_latest(trainer.abstract_state())
    assert restored.step == 1
    assert restored.data_state == {"position": 1, "salt": 7}
    assert not ckpt.update_data_state(99, {"position": 0})
    ckpt.close()


def test_eviction_race_mid_checksum_is_not_a_durability_error(
    trainer, data, tmp_path, monkeypatch
):
    """Retention eviction deletes files before the directory, so the
    manifest worker can see FileNotFoundError on both attempts while
    the step dir is still mid-rmtree. A vanished-file failure must be
    treated as eviction in progress — never recorded as a manifest
    error that makes a successful run's clean-exit wait() raise."""
    import kubeflow_tpu.train.checkpoint as ckpt_mod

    state = trainer.init_state(jax.random.PRNGKey(0))
    ckpt = Checkpointer(tmp_path / "ck", save_interval_steps=1)
    assert ckpt.save(1, state, force=True)
    ckpt.wait()

    def vanished(path):
        raise FileNotFoundError(path)

    monkeypatch.setattr(ckpt_mod, "_file_digest", vanished)
    ckpt._enqueue_manifest(1, None)
    ckpt.wait()  # must not raise
    ckpt.close()


def test_manifest_error_does_not_mask_inflight_exception(
    trainer, data, tmp_path
):
    """fit()'s finally-block `checkpointer.wait()` can itself raise
    (manifest write failures surface as RuntimeError): during an
    exception unwind that must be demoted to a log line — a caller's
    `except TrainingDiverged` (or resilience_worker's exit-code
    mapping) must see the original exception, not a masking
    RuntimeError. On a CLEAN exit the durability failure still raises:
    a result claiming zero lost steps must not paper over an unsafe
    save."""
    from kubeflow_tpu.train import TrainingDiverged

    class FailingManifests(Checkpointer):
        # Inject the background error AFTER fit()'s initial
        # restore_latest (whose own wait() would surface it too early).
        def restore_latest(self, abstract_state):
            restored = super().restore_latest(abstract_state)
            self._manifest_errors.append(RuntimeError("boom"))
            return restored

    bad = next(iter(data))
    bad = dict(bad, image=bad["image"] * jnp.nan)
    ckpt = FailingManifests(tmp_path / "ck", save_interval_steps=100)
    with pytest.raises(TrainingDiverged):
        fit(trainer, [bad], total_steps=1, checkpointer=ckpt, log_every=1)
    ckpt.close()

    ckpt2 = FailingManifests(tmp_path / "ck2", save_interval_steps=100)
    with pytest.raises(RuntimeError, match="manifest"):
        fit(trainer, data, total_steps=1, checkpointer=ckpt2, log_every=1)
    ckpt2.close()  # errors were surfaced and cleared by the wait above


def test_read_only_is_actually_read_only(trainer, data, tmp_path):
    """read_only must enforce what it documents: a mistyped directory
    raises cleanly instead of mkdir-ing junk on the restore path, and
    save() is refused — the flag covers writes, not just quarantine."""
    with pytest.raises(FileNotFoundError, match="read_only"):
        Checkpointer(tmp_path / "nope", read_only=True)
    assert not (tmp_path / "nope").exists()

    state = _save_steps(trainer, data, tmp_path, steps={1})
    ckpt = Checkpointer(tmp_path / "ck", save_interval_steps=1,
                        read_only=True)
    assert not ckpt.should_save(2)
    with pytest.raises(RuntimeError, match="read_only"):
        ckpt.save(2, state, force=True)
    # Restore still works — the flag only removes the write paths.
    assert ckpt.restore_latest(trainer.abstract_state()).step == 1
    ckpt.close()


def test_restore_under_different_save_interval(trainer, data, tmp_path):
    """Checkpoints saved at save_interval_steps=3 must restore under a
    Checkpointer configured with a different interval (the interval is
    a write-side policy, not part of the on-disk format)."""
    _save_steps(trainer, data, tmp_path, steps={3, 6}, interval=3)
    ckpt = Checkpointer(tmp_path / "ck", save_interval_steps=5)
    restored = ckpt.restore_latest(trainer.abstract_state())
    assert restored.step == 6
    assert int(restored.state.step) == 6
    # And the new interval governs subsequent writes.
    assert not ckpt.should_save(7)
    ckpt.close()
