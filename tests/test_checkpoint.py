"""Orbax checkpoint manager + resumable fit() with divergence guard."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.resnet import tiny_resnet
from kubeflow_tpu.train import (
    Checkpointer,
    SyntheticImages,
    TrainConfig,
    Trainer,
    fit,
)


@pytest.fixture
def trainer(mesh8):
    config = TrainConfig(
        batch_size=16, learning_rate=0.05, warmup_steps=2, total_steps=20
    )
    return Trainer(
        tiny_resnet(), config, mesh8, example_input_shape=(2, 32, 32, 3)
    )


@pytest.fixture
def data(mesh8):
    return SyntheticImages(
        mesh8, batch_size=16, image_size=32, num_classes=10, dtype=jnp.float32
    )


def _params_close(a, b):
    flat_a = jax.tree_util.tree_leaves(a)
    flat_b = jax.tree_util.tree_leaves(b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)


def test_save_restore_roundtrip(trainer, data, tmp_path):
    state = trainer.init_state(jax.random.PRNGKey(0))
    step = trainer.make_train_step()
    state, _ = step(state, next(iter(data)))

    ckpt = Checkpointer(tmp_path / "ckpt", save_interval_steps=1)
    assert ckpt.save(1, state, force=True)
    ckpt.wait()

    restored, at = ckpt.restore_latest(trainer.abstract_state())
    assert at == 1
    assert int(restored.step) == 1
    _params_close(restored.params, state.params)
    _params_close(restored.opt_state, state.opt_state)
    # Restored arrays carry the mesh shardings from the abstract template.
    stem = restored.params["conv_stem"]["kernel"]
    assert "fsdp" in str(stem.sharding.spec)
    ckpt.close()


def test_fit_resumes_where_it_left_off(trainer, data, tmp_path):
    ckpt = Checkpointer(tmp_path / "ckpt", save_interval_steps=1)
    r1 = fit(trainer, data, total_steps=3, checkpointer=ckpt, log_every=1)
    assert r1.resumed_from is None and r1.steps_done == 3
    ckpt.wait()

    ckpt2 = Checkpointer(tmp_path / "ckpt", save_interval_steps=1)
    r2 = fit(trainer, data, total_steps=6, checkpointer=ckpt2, log_every=1)
    assert r2.resumed_from == 3
    assert r2.steps_done == 3  # only the remaining steps ran
    assert int(r2.state.step) == 6
    ckpt2.close()


def test_fit_without_checkpointer(trainer, data):
    r = fit(trainer, data, total_steps=2, log_every=1)
    assert r.steps_done == 2 and len(r.history) == 2
    assert r.history[-1]["examples_per_sec"] > 0


def test_resume_matches_uninterrupted(trainer, data, tmp_path):
    # train 4 straight vs train 2, "crash", resume to 4 — same params.
    straight = fit(trainer, data, total_steps=4, log_every=1).state

    ckpt = Checkpointer(tmp_path / "ck", save_interval_steps=1)
    fit(trainer, data, total_steps=2, checkpointer=ckpt, log_every=1)
    ckpt.wait()
    resumed = fit(
        trainer, data, total_steps=4,
        checkpointer=Checkpointer(tmp_path / "ck", save_interval_steps=1),
        log_every=1,
    ).state
    _params_close(straight.params, resumed.params)


def test_fit_noop_when_already_past_total_steps(trainer, data, tmp_path):
    ckpt = Checkpointer(tmp_path / "ck2", save_interval_steps=1)
    fit(trainer, data, total_steps=4, checkpointer=ckpt, log_every=1)
    ckpt.wait()
    r = fit(
        trainer, data, total_steps=2,
        checkpointer=Checkpointer(tmp_path / "ck2", save_interval_steps=1),
        log_every=1,
    )
    assert r.steps_done == 0 and r.resumed_from == 4
    assert int(r.state.step) == 4


def test_fit_short_data_raises(trainer, tmp_path):
    batches = []  # empty finite iterable
    import pytest as _pytest

    with _pytest.raises(ValueError, match="exhausted"):
        fit(trainer, batches, total_steps=2, log_every=1)
