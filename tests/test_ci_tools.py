"""CI utils (#27), releasing (#28), tools/scripts (#29)."""

import pathlib
import subprocess
import sys

import yaml

from kubeflow_tpu.api.workflow import WorkflowSpec
from kubeflow_tpu.ci.application_util import (
    MANIFEST_DIR,
    manifest_drift,
    regenerate_manifests,
    set_bundle_images,
)
from kubeflow_tpu.deploy.bundles import BUNDLES, bundle_resources
from kubeflow_tpu.deploy.kfdef import default_spec

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from releasing.hubsync import sync  # noqa: E402
from releasing.releaser import IMAGES, release_workflow  # noqa: E402


# -- manifests (regenerate_manifest_tests analog) --------------------------


def test_checked_in_manifests_match_generator():
    """The drift gate the reference ran in CI: goldens must equal the
    generator's output. Run `python -m kubeflow_tpu.ci regenerate` after
    changing bundles."""
    assert MANIFEST_DIR.exists(), "manifests/ goldens not generated"
    assert manifest_drift() == []


def test_regenerate_into_tmp(tmp_path):
    written = regenerate_manifests(tmp_path)
    assert {p.stem for p in written} == set(BUNDLES)
    docs = list(yaml.safe_load_all((tmp_path / "tpujob-operator.yaml").read_text()))
    assert any(d["kind"] == "CustomResourceDefinition" for d in docs)
    # Stale golden cleanup
    (tmp_path / "gone-bundle.yaml").write_text("x: 1\n")
    regenerate_manifests(tmp_path)
    assert not (tmp_path / "gone-bundle.yaml").exists()


def test_set_bundle_images_retags():
    resources = bundle_resources(default_spec(), ["centraldashboard"])
    set_bundle_images(
        resources, {"kubeflow-tpu/centraldashboard": "gcr.io/x/dash:v9"}
    )
    deployments = [r for r in resources if r.kind == "Deployment"]
    images = [
        c["image"]
        for r in deployments
        for c in r.spec["template"]["spec"]["containers"]
    ]
    assert "gcr.io/x/dash:v9" in images


# -- releasing -------------------------------------------------------------


def test_release_workflow_dag():
    wf = release_workflow("v1.0.0")
    spec = WorkflowSpec.from_dict(wf.spec)  # validates incl. cycles
    names = {s.name for s in spec.steps}
    for image, _, _ in IMAGES:
        assert f"build-{image}" in names and f"push-{image}" in names
    test_step = spec.step("test")
    assert set(test_step.dependencies) == {
        f"build-{n}" for n, _, _ in IMAGES
    }
    assert spec.step("tag-release").dependencies == tuple(
        f"push-{n}" for n, _, _ in IMAGES
    )
    assert spec.on_exit is not None


def test_hubsync_copies_all_images():
    calls = []
    pairs = sync(
        "v2", source="gcr.io/src", dest="docker.io/dst",
        copy=lambda s, d: calls.append((s, d)),
    )
    assert calls == pairs
    assert ("gcr.io/src/platform:v2", "docker.io/dst/platform:v2") in pairs
    assert len(pairs) == len(IMAGES)


# -- scripts/tools ---------------------------------------------------------


def test_boilerplate_checker(tmp_path):
    sys.path.insert(0, str(REPO / "scripts"))
    import check_boilerplate

    good = tmp_path / "good.py"
    good.write_text('"""Documented."""\nx = 1\n')
    bad = tmp_path / "bad.py"
    bad.write_text("x = 1\n")
    script = tmp_path / "s.sh"
    script.write_text("#!/bin/bash\n# does things\ntrue\n")
    assert check_boilerplate.check(tmp_path) == ["bad.py"]
    # License mode: verbatim header required.
    lic = "Copyright 2026"
    good.write_text(f"# {lic}\nx = 1\n")
    bad2 = check_boilerplate.check(tmp_path, license_text=lic)
    assert "good.py" not in bad2 and "bad.py" in bad2


def test_repo_passes_its_own_boilerplate_policy():
    result = subprocess.run(
        [sys.executable, "scripts/check_boilerplate.py", "--root", "kubeflow_tpu"],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stdout + result.stderr


def _engine_rule_clean(rule_id: str) -> None:
    """Thin wrapper: assert one kftpu-lint engine rule runs clean over
    the repo. The regex lints that used to live inline here migrated
    onto `kubeflow_tpu/ci/lint/` (ISSUE 8); these named tests remain so
    every CHANGES-referenced guard stays discoverable under its
    historical name, now enforcing the same contract through the
    engine (fixture-verified in tests/test_lint_engine.py)."""
    from kubeflow_tpu.ci.lint import lint_repo

    result = lint_repo(rules=[rule_id])
    assert result.clean, "\n" + result.render()


def test_no_deepcopy_in_dispatch_or_fanout_paths():
    """Perf gate (docs/perf.md) → engine rule `no-deepcopy-hot-path`:
    no deepcopy in the fan-out/read hot paths of either store backend
    (one creeping back silently restores O(watchers x events)
    copying)."""
    _engine_rule_clean("no-deepcopy-hot-path")


def test_flash_attention_hot_path_stays_blockwise():
    """Perf gate (docs/perf.md, ISSUE 3) → engine rule
    `flash-blockwise`: no einsum / no [S, S]-shaped kernel output /
    lane-packed lse helpers present in ops/flash.py."""
    _engine_rule_clean("flash-blockwise")


def test_fused_flash_bwd_shared_delta_and_single_kv_pass():
    """Perf gate (docs/perf.md, ISSUE 7) → engine rule
    `fused-kernel-streams` (ref streams pinned, no o_ref) plus the
    schedule-model half of the contract via the same `flash_schedule`
    accounting every bench shares: single KV pass when fused, two
    passes when not, fused bytes well under two-pass at deep
    triangles. (The traced-program half — fused kernel engaged in the
    grad jaxpr, remat no-forward-rerun — is the `fused-flash-grad`
    program contract in tests/test_program_contracts.py.)"""
    from kubeflow_tpu.ops import flash

    _engine_rule_clean("fused-kernel-streams")

    fused = flash.flash_schedule(4096, 4096, block_q=256, block_k=256)
    assert fused["bwd_fused"], fused
    assert fused["bwd_total_grid_steps"] == fused["bwd_grid_steps"], (
        "fused backward no longer single-KV-pass: "
        f"{fused['bwd_total_grid_steps']} total vs "
        f"{fused['bwd_grid_steps']} per pass"
    )
    two_pass = flash.flash_schedule(
        4096, 4096, block_q=256, block_k=256, causal=False
    )
    assert not two_pass["bwd_fused"]
    assert (
        two_pass["bwd_total_grid_steps"] == 2 * two_pass["bwd_grid_steps"]
    )
    assert (
        fused["bwd_hbm_bytes_fused"]
        <= 0.62 * fused["bwd_hbm_bytes_two_pass"]
    ), fused


def test_pipeline_hot_path_psums_scalars_only():
    """Perf gate (docs/perf.md, ISSUE 4) → engine rule
    `scalar-psum-only`: the ONLY `lax.psum` in parallel/pipeline.py is
    the scalar loss reduction, and models/transformer.py adds none.
    (The compiled-HLO half — no activation-sized all-reduce across pp
    — is the `pipeline-wire-*` program contract.)"""
    _engine_rule_clean("scalar-psum-only")


def test_train_loop_never_swallows_interrupts():
    """Robustness gate (docs/resilience.md, ISSUE 5) → engine rule
    `no-interrupt-swallow`: nothing under train/ catches bare /
    BaseException / KeyboardInterrupt / SystemExit — preemption flows
    to fit()'s step-boundary handler. The repo-wide `no-bare-except`
    rule (tests/test_lint_clean.py) generalizes the bare/BaseException
    half to every package."""
    _engine_rule_clean("no-interrupt-swallow")


def test_resilience_soak_is_slow_marked_with_seeded_nightly_entry():
    """The kill-and-resume soak follows the chaos-soak convention: the
    nightly variant is `slow`-marked (tier-1 runs only the small
    deterministic soak) and `bench.py --workload resilience` drives it
    with a printed seed so any failure reproduces from one integer."""
    soak = (
        REPO / "tests" / "e2e" / "test_train_resilience_e2e.py"
    ).read_text()
    assert "@pytest.mark.slow" in soak
    assert "KFTPU_RESILIENCE_SEED" in soak
    bench = (REPO / "bench.py").read_text()
    assert "test_resilience_soak_nightly" in bench
    assert "KFTPU_RESILIENCE_SEED" in bench
    # The seed is printed up front (the repro contract).
    assert "resilience soak seed=" in bench


def test_elastic_resize_soak_is_slow_marked_with_seeded_nightly_entry():
    """The elastic-resize soak (ISSUE 9) follows the same convention as
    the kill-and-resume and failover soaks: tier-1 runs the small
    fixed-seed shrink->grow cycle, the dense nightly variant is
    `slow`-marked, and `bench.py --workload resilience` drives it with
    a printed seed (publishing the `resilience_*_elastic` rows) so any
    failure reproduces from one integer."""
    soak = (
        REPO / "tests" / "e2e" / "test_train_resilience_e2e.py"
    ).read_text()
    assert "def test_resilience_soak_elastic_resize" in soak
    nightly = soak.split("def test_resilience_soak_elastic_nightly")
    assert len(nightly) == 2
    assert nightly[0].rstrip().endswith("@pytest.mark.slow")
    assert "KFTPU_RESILIENCE_SEED" in soak
    bench = (REPO / "bench.py").read_text()
    assert "test_resilience_soak_elastic_nightly" in bench
    assert "resilience_goodput_elastic" in bench
    assert "resilience_steps_lost_per_kill_elastic" in bench
    # The seed is printed up front (the repro contract).
    assert "resilience soak seed=" in bench


def test_failover_soak_is_slow_marked_with_seeded_nightly_entry():
    """The apiserver-failover soak follows the same convention as the
    chaos and resilience soaks: the kill-cycle nightly is `slow`-marked
    (tier-1 runs only the single-kill deterministic e2e) and `bench.py
    --workload controlplane` drives it with a printed seed so any
    failure reproduces from one integer."""
    soak = (
        REPO / "tests" / "e2e" / "test_apiserver_failover_e2e.py"
    ).read_text()
    assert "@pytest.mark.slow" in soak
    assert "KFTPU_FAILOVER_SEED" in soak
    bench = (REPO / "bench.py").read_text()
    assert "test_failover_soak_nightly" in bench
    assert "KFTPU_FAILOVER_SEED" in bench
    # The seed is printed up front (the repro contract).
    assert "failover soak seed=" in bench


def test_rl_soak_is_slow_marked_with_seeded_nightly_entry():
    """The RL study soak (ISSUE 12) follows the same convention as the
    chaos/resilience/failover soaks: tier-1 runs the small fixed-seed
    study, the nightly variant is `slow`-marked, and `bench.py
    --workload rl` drives it with a printed seed so any failure
    reproduces from one integer."""
    soak = (REPO / "tests" / "e2e" / "test_rl_soak_e2e.py").read_text()
    assert "@pytest.mark.slow" in soak
    assert "KFTPU_RL_SEED" in soak
    nightly = soak.split("def test_rl_soak_nightly")
    assert len(nightly) == 2
    assert nightly[0].rstrip().endswith("@pytest.mark.slow")
    bench = (REPO / "bench.py").read_text()
    assert "test_rl_soak_nightly" in bench
    assert "KFTPU_RL_SEED" in bench
    # The seed is printed up front (the repro contract).
    assert "rl soak seed=" in bench


def test_clients_built_from_config_take_endpoint_lists():
    """Resilience gate (docs/resilience.md, ISSUE 6) → engine rule
    `endpoint-list-clients`: every `HttpApiClient` built from
    operator-supplied config (`--apiserver`/`--server` flags, the e2e
    workers' KFTPU_APISERVER env) parses it with `endpoints_from_env`
    — that value IS the endpoint-list channel for active-passive HA
    pairs, and a bare `HttpApiClient(args.apiserver)` loses the
    failover the HA deployment exists to provide."""
    _engine_rule_clean("endpoint-list-clients")


def test_gcb_template():
    result = subprocess.run(
        [sys.executable, "tools/gcb/template.py", "--commit", "abc123"],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stderr
    doc = yaml.safe_load(result.stdout)
    assert len(doc["steps"]) == len(IMAGES)
    assert all(img.endswith(":abc123") for img in doc["images"])


def test_releaser_cli_emits_valid_workflow():
    result = subprocess.run(
        [sys.executable, "releasing/releaser.py", "--version", "v9.9.9"],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stderr
    doc = yaml.safe_load(result.stdout)
    WorkflowSpec.from_dict(doc["spec"])  # validates
