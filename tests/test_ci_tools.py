"""CI utils (#27), releasing (#28), tools/scripts (#29)."""

import pathlib
import subprocess
import sys

import yaml

from kubeflow_tpu.api.workflow import WorkflowSpec
from kubeflow_tpu.ci.application_util import (
    MANIFEST_DIR,
    manifest_drift,
    regenerate_manifests,
    set_bundle_images,
)
from kubeflow_tpu.deploy.bundles import BUNDLES, bundle_resources
from kubeflow_tpu.deploy.kfdef import default_spec

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from releasing.hubsync import sync  # noqa: E402
from releasing.releaser import IMAGES, release_workflow  # noqa: E402


# -- manifests (regenerate_manifest_tests analog) --------------------------


def test_checked_in_manifests_match_generator():
    """The drift gate the reference ran in CI: goldens must equal the
    generator's output. Run `python -m kubeflow_tpu.ci regenerate` after
    changing bundles."""
    assert MANIFEST_DIR.exists(), "manifests/ goldens not generated"
    assert manifest_drift() == []


def test_regenerate_into_tmp(tmp_path):
    written = regenerate_manifests(tmp_path)
    assert {p.stem for p in written} == set(BUNDLES)
    docs = list(yaml.safe_load_all((tmp_path / "tpujob-operator.yaml").read_text()))
    assert any(d["kind"] == "CustomResourceDefinition" for d in docs)
    # Stale golden cleanup
    (tmp_path / "gone-bundle.yaml").write_text("x: 1\n")
    regenerate_manifests(tmp_path)
    assert not (tmp_path / "gone-bundle.yaml").exists()


def test_set_bundle_images_retags():
    resources = bundle_resources(default_spec(), ["centraldashboard"])
    set_bundle_images(
        resources, {"kubeflow-tpu/centraldashboard": "gcr.io/x/dash:v9"}
    )
    deployments = [r for r in resources if r.kind == "Deployment"]
    images = [
        c["image"]
        for r in deployments
        for c in r.spec["template"]["spec"]["containers"]
    ]
    assert "gcr.io/x/dash:v9" in images


# -- releasing -------------------------------------------------------------


def test_release_workflow_dag():
    wf = release_workflow("v1.0.0")
    spec = WorkflowSpec.from_dict(wf.spec)  # validates incl. cycles
    names = {s.name for s in spec.steps}
    for image, _, _ in IMAGES:
        assert f"build-{image}" in names and f"push-{image}" in names
    test_step = spec.step("test")
    assert set(test_step.dependencies) == {
        f"build-{n}" for n, _, _ in IMAGES
    }
    assert spec.step("tag-release").dependencies == tuple(
        f"push-{n}" for n, _, _ in IMAGES
    )
    assert spec.on_exit is not None


def test_hubsync_copies_all_images():
    calls = []
    pairs = sync(
        "v2", source="gcr.io/src", dest="docker.io/dst",
        copy=lambda s, d: calls.append((s, d)),
    )
    assert calls == pairs
    assert ("gcr.io/src/platform:v2", "docker.io/dst/platform:v2") in pairs
    assert len(pairs) == len(IMAGES)


# -- scripts/tools ---------------------------------------------------------


def test_boilerplate_checker(tmp_path):
    sys.path.insert(0, str(REPO / "scripts"))
    import check_boilerplate

    good = tmp_path / "good.py"
    good.write_text('"""Documented."""\nx = 1\n')
    bad = tmp_path / "bad.py"
    bad.write_text("x = 1\n")
    script = tmp_path / "s.sh"
    script.write_text("#!/bin/bash\n# does things\ntrue\n")
    assert check_boilerplate.check(tmp_path) == ["bad.py"]
    # License mode: verbatim header required.
    lic = "Copyright 2026"
    good.write_text(f"# {lic}\nx = 1\n")
    bad2 = check_boilerplate.check(tmp_path, license_text=lic)
    assert "good.py" not in bad2 and "bad.py" in bad2


def test_repo_passes_its_own_boilerplate_policy():
    result = subprocess.run(
        [sys.executable, "scripts/check_boilerplate.py", "--root", "kubeflow_tpu"],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stdout + result.stderr


def test_no_deepcopy_in_dispatch_or_fanout_paths():
    """Lint-style perf gate (docs/perf.md): the copy-on-write rewrite
    removed every defensive deepcopy from the event fan-out and read
    hot paths of BOTH store backends. One creeping back in silently
    restores O(watchers x events) copying — fail loudly instead."""
    import inspect

    from kubeflow_tpu.native import apiserver as native_apiserver
    from kubeflow_tpu.testing import fake_apiserver

    hot_paths = {
        "FakeApiServer._emit": fake_apiserver.FakeApiServer._emit,
        "FakeApiServer._dispatch_loop":
            fake_apiserver.FakeApiServer._dispatch_loop,
        "FakeApiServer.get": fake_apiserver.FakeApiServer.get,
        "FakeApiServer.list": fake_apiserver.FakeApiServer.list,
        "select_journal_events": fake_apiserver.select_journal_events,
        "NativeApiServer._drain_events":
            native_apiserver.NativeApiServer._drain_events,
        "NativeApiServer.get": native_apiserver.NativeApiServer.get,
        "NativeApiServer.list": native_apiserver.NativeApiServer.list,
    }
    offenders = {
        name: fn
        for name, fn in hot_paths.items()
        if "deepcopy" in inspect.getsource(fn)
    }
    assert not offenders, (
        f"deepcopy reappeared in fan-out/read hot paths: "
        f"{sorted(offenders)} — these must share frozen snapshots "
        "(see docs/perf.md)"
    )


def test_flash_attention_hot_path_stays_blockwise():
    """Lint-style perf gate (docs/perf.md, ISSUE 3): the flash kernel's
    compiled path must never rematerialize attention's quadratic
    intermediates in HBM. Two regressions this pins:

    - a `jnp.einsum` creeping into ops/flash.py — the dense reference's
      score-matrix formulation (einsum lives in ops/attention.py, the
      O(S²) path flash exists to replace);
    - an [S, S]-shaped kernel output (`out_shape` carrying both sequence
      dims) — every legitimate output is O(S·d) or an O(S) lse/delta
      tile, so `(bh, sq, sk)`-ish ShapeDtypeStructs mean someone started
      writing scores back to HBM.
    """
    import inspect
    import re

    from kubeflow_tpu.ops import flash

    src = inspect.getsource(flash)
    assert "einsum" not in src, (
        "jnp.einsum reappeared in ops/flash.py — the score matrix must "
        "stay blockwise on-chip (dense formulations live in "
        "ops/attention.py)"
    )
    score_shaped = re.findall(
        r"ShapeDtypeStruct\(\s*\(\s*bh\s*,\s*s[qk]\s*,\s*s[qk]\b", src
    )
    assert not score_shaped, (
        f"[S, S]-shaped HBM output reappeared in ops/flash.py: "
        f"{score_shaped} — kernel outputs must be O(S·d) tiles or "
        "O(S) lse/delta tiles (see docs/perf.md)"
    )
    # The lane-packed lse layout is the hot-path layout; its helper
    # disappearing means the 128x-replicated buffer came back silently.
    assert "_lse_is_packed" in src and "_pack_rows" in src


def test_fused_flash_bwd_shared_delta_and_single_kv_pass():
    """Lint-style perf gate (docs/perf.md, ISSUE 7): the fused dq/dkv
    backward's contracts, pinned mechanically:

    - its input streams must not contain O — the shared-delta rewrite
      removed O from the backward (delta = rowsum(dO ∘ O) arrives
      precomputed), and an `o_ref` creeping back into the fused kernel
      silently restores an S·d HBM re-stream per step;
    - the backward walks the compact triangle ONCE: via the
      `flash_schedule` accounting every bench and test shares,
      `bwd_total_grid_steps` must equal the per-pass step count when
      fused (and exactly two passes when not).
    """
    import inspect

    from kubeflow_tpu.ops import flash

    params = list(
        inspect.signature(flash._dqkv_kernel_fused).parameters
    )
    refs = [p for p in params if p.endswith("_ref")]
    assert refs == [
        "rows_ref", "cols_ref", "q_ref", "k_ref", "v_ref", "do_ref",
        "lse_ref", "delta_ref", "dq_ref", "dk_ref", "dv_ref",
    ], f"fused kernel input/output streams changed: {refs}"
    assert "o_ref" not in params, (
        "O reappeared in the fused backward's streams (shared-delta "
        "regression — delta must arrive precomputed)"
    )

    fused = flash.flash_schedule(4096, 4096, block_q=256, block_k=256)
    assert fused["bwd_fused"], fused
    assert fused["bwd_total_grid_steps"] == fused["bwd_grid_steps"], (
        "fused backward no longer single-KV-pass: "
        f"{fused['bwd_total_grid_steps']} total vs "
        f"{fused['bwd_grid_steps']} per pass"
    )
    two_pass = flash.flash_schedule(
        4096, 4096, block_q=256, block_k=256, causal=False
    )
    assert not two_pass["bwd_fused"]
    assert (
        two_pass["bwd_total_grid_steps"] == 2 * two_pass["bwd_grid_steps"]
    )
    # The bench gate rides the same accounting: the fused model must
    # report well under the two-pass bytes at deep triangles.
    assert (
        fused["bwd_hbm_bytes_fused"]
        <= 0.62 * fused["bwd_hbm_bytes_two_pass"]
    ), fused


def test_pipeline_hot_path_psums_scalars_only():
    """Lint-style perf gate (docs/perf.md, ISSUE 4): the pipeline layer
    must never all-reduce a non-scalar buffer across pp. The seed design
    ended every step with `lax.psum(outputs, pp)` — an all-reduce of the
    entire [M, mb, ...] activation buffer for data only the last stage
    produced. The overhaul's contract: the ONLY `lax.psum` in
    parallel/pipeline.py is the scalar loss reduction (activations move
    by ppermute; the eval path broadcasts by ring rotation), and the
    transformer's pipelined path adds no psum of its own."""
    import inspect
    import re

    from kubeflow_tpu.models import transformer
    from kubeflow_tpu.parallel import pipeline

    src = inspect.getsource(pipeline)
    assert "lax.psum(outputs" not in src, (
        "the terminal activation-buffer all-reduce came back to "
        "parallel/pipeline.py — the loss path must psum scalars only "
        "(see docs/perf.md)"
    )
    psums = re.findall(r"lax\.psum\(\s*([A-Za-z_][A-Za-z0-9_]*)", src)
    assert psums == ["local_loss"], (
        f"unexpected lax.psum call(s) in parallel/pipeline.py: {psums} — "
        "the pipeline hot path's only cross-pp all-reduce is the scalar "
        "loss"
    )
    assert "lax.psum(" not in inspect.getsource(transformer), (
        "a psum appeared in models/transformer.py — the pipelined paths "
        "must leave cross-pp reduction to spmd_pipeline's scalar loss"
    )


def test_train_loop_never_swallows_interrupts():
    """Lint-style robustness gate (docs/resilience.md, ISSUE 5): the
    training tier's preemption contract depends on SIGTERM/SIGINT and
    process-exit flowing to the loop's boundary handler. Nothing under
    `train/` may intercept them:

    - no bare `except:` and no `except BaseException` (both catch
      KeyboardInterrupt/SystemExit, turning a preemption into a hang or
      a half-written save);
    - no explicit `except KeyboardInterrupt` / `except SystemExit` —
      the loop handles preemption via signal handlers at step
      boundaries, never by swallowing the exception mid-step.
    """
    import re

    train_dir = REPO / "kubeflow_tpu" / "train"
    offenders: list[str] = []
    for path in sorted(train_dir.glob("*.py")):
        for lineno, line in enumerate(path.read_text().splitlines(), 1):
            stripped = line.split("#", 1)[0]
            if re.search(r"\bexcept\s*:", stripped) or re.search(
                r"\bexcept\s+.*\b(BaseException|KeyboardInterrupt|"
                r"SystemExit)\b",
                stripped,
            ):
                offenders.append(f"{path.name}:{lineno}: {line.strip()}")
    assert not offenders, (
        "train/ must never swallow interrupts — preemption handling "
        "relies on SIGTERM/SIGINT reaching fit()'s boundary handler "
        f"(see docs/resilience.md): {offenders}"
    )


def test_resilience_soak_is_slow_marked_with_seeded_nightly_entry():
    """The kill-and-resume soak follows the chaos-soak convention: the
    nightly variant is `slow`-marked (tier-1 runs only the small
    deterministic soak) and `bench.py --workload resilience` drives it
    with a printed seed so any failure reproduces from one integer."""
    soak = (
        REPO / "tests" / "e2e" / "test_train_resilience_e2e.py"
    ).read_text()
    assert "@pytest.mark.slow" in soak
    assert "KFTPU_RESILIENCE_SEED" in soak
    bench = (REPO / "bench.py").read_text()
    assert "test_resilience_soak_nightly" in bench
    assert "KFTPU_RESILIENCE_SEED" in bench
    # The seed is printed up front (the repro contract).
    assert "resilience soak seed=" in bench


def test_failover_soak_is_slow_marked_with_seeded_nightly_entry():
    """The apiserver-failover soak follows the same convention as the
    chaos and resilience soaks: the kill-cycle nightly is `slow`-marked
    (tier-1 runs only the single-kill deterministic e2e) and `bench.py
    --workload controlplane` drives it with a printed seed so any
    failure reproduces from one integer."""
    soak = (
        REPO / "tests" / "e2e" / "test_apiserver_failover_e2e.py"
    ).read_text()
    assert "@pytest.mark.slow" in soak
    assert "KFTPU_FAILOVER_SEED" in soak
    bench = (REPO / "bench.py").read_text()
    assert "test_failover_soak_nightly" in bench
    assert "KFTPU_FAILOVER_SEED" in bench
    # The seed is printed up front (the repro contract).
    assert "failover soak seed=" in bench


def test_clients_built_from_config_take_endpoint_lists():
    """Everything that builds an `HttpApiClient` from operator-supplied
    config — the production entry points' `--apiserver`/`--server`
    flags AND the e2e workers' KFTPU_APISERVER env — parses it with
    `endpoints_from_env`, never as a bare string: that value IS the
    endpoint-list channel (comma-separated for active-passive HA
    pairs), so a `HttpApiClient(args.apiserver)` wiring would treat
    "url1,url2" as one malformed URL — or, handed only the active's
    URL, stall forever when that facade dies — silently losing the
    failover the HA deployment exists to provide."""
    import re

    offenders = []
    sources = sorted((REPO / "tests" / "e2e").glob("*worker*.py")) + [
        REPO / "kubeflow_tpu" / p
        for p in (
            "cli.py",
            "controllers/__main__.py",
            "controllers/webhook.py",
            "deploy/worker.py",
            "sidecar/__main__.py",
        )
    ]
    bare = re.compile(
        r"HttpApiClient\(\s*(?:os\.environ\[|args\.)"
    )
    for src in sources:
        text = src.read_text()
        if "HttpApiClient(" not in text:
            continue
        if bare.search(text):
            offenders.append(f"{src.name}: bare config-string endpoint")
        elif "endpoints_from_env" not in text:
            offenders.append(f"{src.name}: no endpoints_from_env")
    assert not offenders, (
        "config-driven clients must parse their apiserver address via "
        f"endpoints_from_env (failover rides the list): {offenders}"
    )


def test_gcb_template():
    result = subprocess.run(
        [sys.executable, "tools/gcb/template.py", "--commit", "abc123"],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stderr
    doc = yaml.safe_load(result.stdout)
    assert len(doc["steps"]) == len(IMAGES)
    assert all(img.endswith(":abc123") for img in doc["images"])


def test_releaser_cli_emits_valid_workflow():
    result = subprocess.run(
        [sys.executable, "releasing/releaser.py", "--version", "v9.9.9"],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stderr
    doc = yaml.safe_load(result.stdout)
    WorkflowSpec.from_dict(doc["spec"])  # validates
