"""kubectl-analog CLI against a live apiserver facade."""

import io
import sys

import pytest

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.cli import main, resolve_kind
from kubeflow_tpu.testing.apiserver_http import ApiServerApp
from kubeflow_tpu.testing.fake_apiserver import FakeApiServer
from kubeflow_tpu.web.wsgi import serve


@pytest.fixture
def server():
    api = FakeApiServer()
    httpd, _ = serve(ApiServerApp(api), host="127.0.0.1", port=0)
    yield api, f"http://127.0.0.1:{httpd.server_port}"
    httpd.shutdown()


def run(server_url, *argv, stdin=None):
    out, err = io.StringIO(), io.StringIO()
    old = sys.stdout, sys.stderr, sys.stdin
    sys.stdout, sys.stderr = out, err
    if stdin is not None:
        sys.stdin = io.StringIO(stdin)
    try:
        rc = main(["--server", server_url, *argv])
    finally:
        sys.stdout, sys.stderr, sys.stdin = old
    return rc, out.getvalue(), err.getvalue()


def test_kind_aliases():
    assert resolve_kind("notebooks") == "Notebook"
    assert resolve_kind("tj") == "TpuJob"
    assert resolve_kind("FancyNewKind") == "FancyNewKind"  # pass-through


def test_kind_fallback_singularizes_sibilant_plurals():
    """`-es`/`-ses` plurals must not derive impossible kinds (the old
    strip-one-s produced `Statuse`/`Classe`) — while silent-e stems
    (`caches`, `sizes`) keep their old correct derivation."""
    assert resolve_kind("statuses") == "Status"
    assert resolve_kind("classes") == "Class"
    assert resolve_kind("boxes") == "Box"
    assert resolve_kind("dishes") == "Dish"
    assert resolve_kind("caches") == "Cache"      # silent-e stem kept
    assert resolve_kind("sizes") == "Size"        # silent-e stem kept
    assert resolve_kind("policies") == "Policy"   # -ies unchanged
    assert resolve_kind("leases") == "Lease"      # table, and -s form
    assert resolve_kind("widgets") == "Widget"    # plain -s unchanged


def test_kind_fallback_disambiguates_against_live_objects(server):
    """Genuinely ambiguous plurals resolve to whichever candidate has
    live objects — the heuristic's runner-up wins when the cluster says
    so (`churches` is church+es, the -che reading's opposite)."""
    api, url = server

    class FakeClient:
        def list(self, kind, **kw):
            return ["obj"] if kind == "Church" else []

    assert resolve_kind("churches", FakeClient()) == "Church"
    # And the reverse ambiguity: live Cache objects beat the es-strip.
    class FakeClient2:
        def list(self, kind, **kw):
            return ["obj"] if kind == "Cache" else []

    assert resolve_kind("caches", FakeClient2()) == "Cache"


def test_kind_fallback_warns_when_no_live_objects(server):
    """A derived (guessed) kind with zero live objects warns on stderr —
    an empty table from a wrong guess must not look like a quiet
    cluster."""
    api, url = server
    rc, out, err = run(url, "get", "gizmos")
    assert rc == 0
    assert "no live 'Gizmo' objects" in err, err
    api.create(new_resource("Gizmo", "g1", "default", spec={}))
    rc, out, err = run(url, "get", "gizmos")
    assert rc == 0
    assert "no live" not in err, err
    assert "g1" in out


def test_get_table_and_yaml(server):
    api, url = server
    nb = new_resource("Notebook", "nb1", "team", spec={"image": "i"})
    nb.status = {"containerState": "Running"}
    api.create(nb)
    rc, out, _ = run(url, "get", "notebooks", "-n", "team")
    assert rc == 0
    assert "NAMESPACE" in out and "nb1" in out and "Running" in out
    rc, out, _ = run(url, "get", "nb", "nb1", "-n", "team", "-o", "yaml")
    assert rc == 0 and "image: i" in out


def test_get_at_api_version(server):
    api, url = server
    api.create(new_resource("Notebook", "nb2", "team", spec={"image": "x"}))
    rc, out, _ = run(url, "get", "notebook", "nb2", "-n", "team",
                     "--api-version", "v1alpha1")
    assert rc == 0 and "containerImage: x" in out


def test_apply_create_then_configure(server):
    api, url = server
    doc = """
apiVersion: kubeflow-tpu.org/v1
kind: Notebook
metadata: {name: nb3, namespace: team}
spec: {image: first}
"""
    rc, out, _ = run(url, "apply", "-f", "-", stdin=doc)
    assert rc == 0 and "notebook/nb3 created" in out
    rc, out, _ = run(url, "apply", "-f", "-",
                     stdin=doc.replace("first", "second"))
    assert rc == 0 and "notebook/nb3 configured" in out
    assert api.get("Notebook", "nb3", "team").spec["image"] == "second"


def test_apply_invalid_create_surfaces_real_error(server):
    # A new object written at an unserved version is a 422 — the CLI must
    # report the validation failure, not fall through to get+update and
    # mask it behind "not found" (ADVICE r1).
    _, url = server
    doc = """
apiVersion: kubeflow-tpu.org/v9000
kind: Notebook
metadata: {name: nb-bad, namespace: team}
spec: {image: x}
"""
    rc, out, err = run(url, "apply", "-f", "-", stdin=doc)
    assert rc == 1
    assert "not found" not in err
    assert "v9000" in err


def test_delete_and_missing_is_error(server):
    api, url = server
    api.create(new_resource("Notebook", "nb4", "team"))
    rc, out, _ = run(url, "delete", "notebook", "nb4", "-n", "team")
    assert rc == 0 and "deleted" in out
    rc, _, err = run(url, "delete", "notebook", "nb4", "-n", "team")
    assert rc == 1 and "not found" in err


def test_traces_listing(server):
    api, url = server
    api.create(new_resource("Notebook", "nb5", "team"))
    rc, out, _ = run(url, "traces")
    assert rc == 0
    assert "http" in out  # the create request's span


def test_unreachable_server_is_clean_error():
    rc, _, err = run("http://127.0.0.1:1", "get", "notebooks")
    assert rc == 1 and "cannot reach" in err


def test_cluster_scoped_kinds_listed_by_default(server):
    api, url = server
    api.create(new_resource("Node", "tpu-0", ""))
    rc, out, _ = run(url, "get", "nodes")
    assert rc == 0 and "tpu-0" in out
    # -n narrows to a namespace (and so hides cluster-scoped objects).
    rc, out, _ = run(url, "get", "nodes", "-n", "team")
    assert rc == 0 and "tpu-0" not in out


def test_get_watch_streams_events(server):
    """`get -w` (kubectl analog): initial table, then one row per event
    from the facade's watch stream — run as a real subprocess so the
    stream is actually consumed across the process boundary."""
    import os
    import signal
    import subprocess
    import time

    api, url = server
    api.create(new_resource("TpuJob", "pre", "ml", spec={"replicas": 1}))
    proc = subprocess.Popen(
        [sys.executable, "-m", "kubeflow_tpu.cli", "--server", url,
         "get", "tpujobs", "-w"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ},
    )
    try:
        header = proc.stdout.readline()
        assert "EVENT" in header
        assert "pre" in proc.stdout.readline()
        # Live events stream in as they happen.
        api.create(new_resource("TpuJob", "live", "ml",
                                spec={"replicas": 1}))
        line = proc.stdout.readline()
        assert "ADDED" in line and "live" in line, line
        api.delete("TpuJob", "live", "ml")
        deadline = time.time() + 10
        seen_delete = False
        while time.time() < deadline and not seen_delete:
            line = proc.stdout.readline()
            seen_delete = "DELETED" in line and "live" in line
        assert seen_delete
    finally:
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_get_watch_single_object_filters(server):
    """`get <kind> <name> -w` streams only the named object (kubectl's
    single-object watch), and survives quiet intervals longer than the
    client socket timeout (the long-poll must be shorter)."""
    import os
    import signal
    import subprocess
    import time

    api, url = server
    api.create(new_resource("TpuJob", "keep", "default",
                            spec={"replicas": 1}))
    api.create(new_resource("TpuJob", "noise", "default",
                            spec={"replicas": 1}))
    proc = subprocess.Popen(
        [sys.executable, "-m", "kubeflow_tpu.cli", "--server", url,
         "get", "tpujobs", "keep", "-w"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ},
    )
    try:
        assert "EVENT" in proc.stdout.readline()
        first = proc.stdout.readline()
        assert "keep" in first and "noise" not in first
        # Quiet for longer than the 10s socket timeout: the stream must
        # survive (empty long-polls), then deliver only 'keep' events.
        time.sleep(11)
        assert proc.poll() is None, "watch died during a quiet interval"
        api.create(new_resource("TpuJob", "noise2", "default",
                                spec={"replicas": 1}))
        fresh = api.get("TpuJob", "keep", "default").thaw()
        fresh.status["phase"] = "Running"
        api.update_status(fresh)
        line = proc.stdout.readline()
        assert "MODIFIED" in line and "keep" in line, line
    finally:
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_logs_command(tmp_path):
    """kubectl-logs analog: pod stdout served through the facade's
    log endpoint, including --job rank-ordered gang output. The facade
    serves only files under its configured log_root."""
    import time

    from kubeflow_tpu.api import make_tpujob
    from kubeflow_tpu.controllers.tpujob import TpuJobController
    from kubeflow_tpu.runtime import LocalPodRunner

    api = FakeApiServer()
    httpd, _ = serve(ApiServerApp(api, log_root=str(tmp_path)),
                     host="127.0.0.1", port=0)
    url = f"http://127.0.0.1:{httpd.server_port}"
    ctl = TpuJobController(api)
    runner = LocalPodRunner(api, capture_dir=str(tmp_path))
    api.create(
        make_tpujob(
            "talk", replicas=2, tpu_chips_per_worker=0,
            command=(sys.executable, "-c",
                     "import os; print('hello from', os.environ['TPU_WORKER_ID'])"),
        )
    )
    deadline = time.time() + 60
    try:
        while time.time() < deadline:
            ctl.controller.run_until_idle()
            runner.step()
            job = api.get("TpuJob", "talk")
            if job.status.get("phase") in ("Succeeded", "Failed"):
                break
            time.sleep(0.2)
    finally:
        runner.shutdown()
    assert api.get("TpuJob", "talk").status["phase"] == "Succeeded"

    rc, out, err = run(url, "logs", "talk-worker-0")
    assert rc == 0, err
    assert "hello from 0" in out

    rc, out, err = run(url, "logs", "talk", "--job")
    assert rc == 0, err
    assert out.index("hello from 0") < out.index("hello from 1")
    assert "==> talk-worker-1 <==" in out

    rc, _, err = run(url, "logs", "no-such-pod")
    assert rc == 1 and "not found" in err

    # Containment: a client-written logPath outside the capture root is
    # refused — status is client-writable, so this would otherwise be an
    # arbitrary-file-read primitive.
    victim = api.get("Pod", "talk-worker-0").thaw()
    victim.status["logPath"] = "/etc/hostname"
    api.update_status(victim)
    rc, _, err = run(url, "logs", "talk-worker-0")
    assert rc == 1 and "outside" in err
    httpd.shutdown()


def test_cli_token_against_secure_facade(tls_paths):
    """--token authenticates against a secure facade — over TLS with the
    pinned CA, the way the launcher boots it; without a token the CLI
    reports the 401 as a readable error instead of a traceback."""
    from kubeflow_tpu.api.rbac import (
        make_cluster_role_binding,
        seed_cluster_roles,
    )
    from kubeflow_tpu.api.tokens import TokenRegistry

    api = FakeApiServer()
    seed_cluster_roles(api)
    api.create(
        make_cluster_role_binding("adm", "kubeflow-admin", "system:admin")
    )
    tokens = TokenRegistry()
    token = tokens.issue("system:admin")
    httpd, _ = serve(
        ApiServerApp(api, tokens=tokens), host="127.0.0.1", port=0,
        tls=tls_paths,
    )
    url = f"https://127.0.0.1:{httpd.server_port}"
    api.create(new_resource("Notebook", "nb1", "team", spec={}))
    try:
        rc, out, _ = run(url, "--ca", tls_paths.ca_cert, "--token", token,
                         "get", "notebooks", "-n", "team")
        assert rc == 0 and "nb1" in out
        rc, _, err = run(url, "--ca", tls_paths.ca_cert,
                         "get", "notebooks", "-n", "team")
        assert rc == 1 and "bearer token" in err
        # Token + plaintext http:// = refused client-side, readably.
        rc, _, err = run(url.replace("https:", "http:"), "--token", token,
                         "get", "notebooks")
        assert rc == 1 and "plaintext" in err
    finally:
        httpd.shutdown()


def test_describe_golden(server):
    """kubectl-describe analog: object + conditions + events in one view."""
    api, url = server
    job = new_resource(
        "TpuJob", "train", "ml",
        spec={"replicas": 2}, labels={"team": "research"},
    )
    created = api.create(job).thaw()
    created.status = {
        "phase": "Running",
        "conditions": [{"type": "Created"}, {"type": "Running"}],
    }
    api.update_status(created)
    api.record_event(created, "GangCreated", "created 2 worker pods")
    api.record_event(
        created, "Unschedulable", "no capacity", type_="Warning"
    )

    rc, out, _ = run(url, "describe", "tpujob", "train", "-n", "ml")
    assert rc == 0
    lines = out.splitlines()
    assert "Name:         train" in lines
    assert "Namespace:    ml" in lines
    assert "Labels:       team=research" in lines
    assert any(l.startswith("  replicas: 2") for l in lines), out
    assert any(l.startswith("  phase: Running") for l in lines), out
    # Conditions table lists both transitions in order.
    ci = lines.index("Conditions:")
    assert "Created" in lines[ci + 2] and "Running" in lines[ci + 3], out
    # Events timeline, oldest first, with type and reason columns.
    ei = lines.index("Events:")
    assert "GangCreated" in lines[ei + 2], out
    assert "Warning" in lines[ei + 3] and "no capacity" in lines[ei + 3], out


def test_describe_no_events(server):
    api, url = server
    api.create(new_resource("Notebook", "nb", "team", spec={}))
    rc, out, _ = run(url, "describe", "notebook", "nb", "-n", "team")
    assert rc == 0 and "  <none>" in out.splitlines()


def test_describe_cluster_scoped(server):
    """`describe node tpu-node-0` must reach cluster scope (namespace "")
    without the user spelling an empty -n."""
    api, url = server
    node = new_resource("Node", "tpu-node-0", "", spec={"chips": 4})
    created = api.create(node)
    api.record_event(created, "NodeReady", "kubelet posted ready")
    rc, out, _ = run(url, "describe", "node", "tpu-node-0")
    assert rc == 0, out
    assert "Name:         tpu-node-0" in out
    assert "NodeReady" in out
    rc2, out2, _ = run(url, "get", "node", "tpu-node-0")
    assert rc2 == 0 and "chips: 4" in out2


def test_apply_continues_past_forbidden_doc(tls_paths):
    """One forbidden doc in a multi-doc apply is reported per-doc and the
    rest still apply (Forbidden is an ApiError, like 409/422/404)."""
    from kubeflow_tpu.api.rbac import make_cluster_role, make_cluster_role_binding
    from kubeflow_tpu.api.tokens import TokenRegistry

    api = FakeApiServer()
    api.create(make_cluster_role("nb-create", [
        {"verbs": ["create"], "resources": ["notebooks"]},
    ]))
    api.create(make_cluster_role_binding("nb", "nb-create", "frank"))
    tokens = TokenRegistry()
    httpd, _ = serve(
        ApiServerApp(api, tokens=tokens), host="127.0.0.1", port=0,
        tls=tls_paths,
    )
    url = f"https://127.0.0.1:{httpd.server_port}"
    docs = (
        "apiVersion: kubeflow-tpu.org/v1\n"
        "kind: TpuJob\nmetadata: {name: denied, namespace: default}\n"
        "spec: {replicas: 1}\n"
        "---\n"
        "apiVersion: kubeflow-tpu.org/v1\n"
        "kind: Notebook\nmetadata: {name: allowed, namespace: default}\n"
        "spec: {}\n"
    )
    try:
        rc, out, err = run(
            url, "--ca", tls_paths.ca_cert, "--token",
            tokens.issue("frank"), "apply", "-f", "-",
            stdin=docs,
        )
    finally:
        httpd.shutdown()
    assert rc == 1
    assert "TpuJob/denied" in err and "not allowed" in err
    assert "notebook/allowed created" in out
    assert api.get("Notebook", "allowed").metadata.name == "allowed"


def test_top_shows_fleet_chip_usage(server):
    api, url = server
    for i in range(2):
        node = new_resource(
            "Node", f"tpu-{i}", "", spec={"pool": "v5e", "chips": 4}
        )
        node.status = {"ready": True, "tpuDutyCycle": 0.5,
                       "cpuUtilization": 0.25}
        api.create(node)
    pod = new_resource("Pod", "w0", "default", spec={
        "nodeName": "tpu-0",
        "containers": [{"name": "w",
                        "resources": {"limits": {"google.com/tpu": 4}}}],
    })
    api.create(pod)
    rc, out, _ = run(url, "top")
    assert rc == 0
    lines = out.splitlines()
    assert lines[0].split() == [
        "NAME", "POOL", "CHIPS(USED/CAP)", "TPU-DUTY", "CPU", "STATUS"
    ]
    assert "tpu-0" in lines[1] and "4/4" in lines[1] and "50%" in lines[1]
    assert "tpu-1" in lines[2] and "0/4" in lines[2]
    assert "# 4/8 chips reserved across 2 node(s)" in out


def test_top_handles_odd_pods_and_vanished_nodes(server):
    api, url = server
    node = new_resource("Node", "tpu-0", "", spec={"pool": "v5e", "chips": 4})
    node.status = {"ready": True}
    api.create(node)
    # Empty containers list must not crash; multi-container limits sum.
    api.create(new_resource("Pod", "empty", "default",
                            spec={"nodeName": "tpu-0", "containers": []}))
    api.create(new_resource("Pod", "multi", "default", spec={
        "nodeName": "tpu-0",
        "containers": [
            {"name": "a"},
            {"name": "b", "resources": {"limits": {"google.com/tpu": 2}}},
        ],
    }))
    # A pod bound to a node that no longer exists: reported, not counted.
    api.create(new_resource("Pod", "ghost", "default", spec={
        "nodeName": "gone",
        "containers": [{"name": "w",
                        "resources": {"limits": {"google.com/tpu": 4}}}],
    }))
    rc, out, _ = run(url, "top")
    assert rc == 0, out
    assert "2/4" in out
    assert "# 2/4 chips reserved across 1 node(s); 4 chip(s) on vanished node(s)" in out


def test_describe_cluster_scoped_with_namespace_scoped_token(tls_paths):
    """ADVICE r3: a namespace-scoped token 403s the default-ns probe;
    the CLI must still fall through to cluster scope for objects the
    identity CAN read (`describe node x` with a node-reader token)."""
    from kubeflow_tpu.api.rbac import (
        make_cluster_role,
        make_cluster_role_binding,
    )
    from kubeflow_tpu.api.tokens import TokenRegistry

    api = FakeApiServer()
    api.create(make_cluster_role("node-reader", [
        {"verbs": ["get", "list"], "resources": ["nodes", "events"]},
    ]))
    api.create(make_cluster_role_binding("nr", "node-reader", "watcher"))
    node = new_resource("Node", "tpu-0", "", spec={"chips": 4})
    node.status = {"ready": True}
    api.create(node)
    tokens = TokenRegistry()
    httpd, _ = serve(
        ApiServerApp(api, tokens=tokens), host="127.0.0.1", port=0,
        tls=tls_paths,
    )
    url = f"https://127.0.0.1:{httpd.server_port}"
    try:
        rc, out, err = run(url, "--ca", tls_paths.ca_cert, "--token",
                           tokens.issue("watcher"),
                           "describe", "node", "tpu-0")
    finally:
        httpd.shutdown()
    assert rc == 0, (out, err)
    assert "tpu-0" in out
