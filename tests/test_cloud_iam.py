"""Cloud-IAM plugins: pure policy transforms + profile-controller wiring.

Table tests at the fidelity of the reference's
`plugin_iam_test.go:302` (trust-policy add/dedupe/remove) and
`plugin_workload_identity_test.go` (binding edits), plus end-to-end
apply/idempotence/revoke through the ProfileController finalizer.
"""

import pytest

from kubeflow_tpu.api import new_resource
from kubeflow_tpu.controllers.cloud_iam import (
    AWS_ANNOTATION_KEY,
    AwsIamPlugin,
    GCP_ANNOTATION_KEY,
    InMemoryAwsIam,
    InMemoryGcpIam,
    KIND_AWS_IAM,
    KIND_WORKLOAD_IDENTITY,
    PluginError,
    WORKLOAD_IDENTITY_ROLE,
    WorkloadIdentityPlugin,
    add_trusted_service_account,
    add_workload_identity_binding,
    gcp_project_from_sa,
    issuer_from_provider_arn,
    remove_trusted_service_account,
    remove_workload_identity_binding,
    role_name_from_arn,
    workload_identity_member,
)
from kubeflow_tpu.controllers.profile import KIND, ProfileController
from kubeflow_tpu.testing import FakeApiServer

ISSUER = "oidc.eks.us-west-2.amazonaws.com/id/DEADBEEF"
PROVIDER_ARN = f"arn:aws:iam::123456789012:oidc-provider/{ISSUER}"
ROLE_ARN = "arn:aws:iam::123456789012:role/kf-user-role"


def trust_doc(subs=None, extra_subs_key=True):
    cond = {"StringEquals": {f"{ISSUER}:aud": ["sts.amazonaws.com"]}}
    if subs is not None and extra_subs_key:
        cond["StringEquals"][f"{ISSUER}:sub"] = subs
    return {
        "Version": "2012-10-17",
        "Statement": [
            {
                "Effect": "Allow",
                "Action": "sts:AssumeRoleWithWebIdentity",
                "Principal": {"Federated": PROVIDER_ARN},
                "Condition": cond,
            }
        ],
    }


# -- GCP parsing -----------------------------------------------------------


@pytest.mark.parametrize(
    "email,project",
    [
        ("kf-user@my-proj.iam.gserviceaccount.com", "my-proj"),
        ("a@b.iam.gserviceaccount.com", "b"),
    ],
)
def test_gcp_project_extraction(email, project):
    assert gcp_project_from_sa(email) == project


@pytest.mark.parametrize(
    "bad",
    [
        "kf-user@my-proj.example.com",           # wrong suffix
        "not-an-email.iam.gserviceaccount.com",  # no @
        "",
    ],
)
def test_gcp_project_extraction_rejects(bad):
    with pytest.raises(PluginError):
        gcp_project_from_sa(bad)


def test_workload_identity_member_format():
    # plugin_workload_identity.go:123
    assert (
        workload_identity_member("my-proj", "team-a", "default-editor")
        == "serviceAccount:my-proj.svc.id.goog[team-a/default-editor]"
    )


# -- GCP binding table -----------------------------------------------------

MEMBER = "serviceAccount:p.svc.id.goog[ns/default-editor]"
OTHER = "serviceAccount:p.svc.id.goog[other/default-editor]"


@pytest.mark.parametrize(
    "before,expect_members,expect_changed",
    [
        # empty policy → fresh binding
        ({"bindings": []}, [MEMBER], True),
        # merge into existing role binding (NOT a duplicate binding object)
        (
            {"bindings": [{"role": WORKLOAD_IDENTITY_ROLE,
                           "members": [OTHER]}]},
            [OTHER, MEMBER],
            True,
        ),
        # already present → no-op
        (
            {"bindings": [{"role": WORKLOAD_IDENTITY_ROLE,
                           "members": [MEMBER]}]},
            [MEMBER],
            False,
        ),
    ],
)
def test_add_workload_identity_binding(before, expect_members, expect_changed):
    after, changed = add_workload_identity_binding(before, MEMBER)
    assert changed is expect_changed
    wi = [b for b in after["bindings"]
          if b["role"] == WORKLOAD_IDENTITY_ROLE]
    assert len(wi) == 1  # never a duplicate binding object
    assert wi[0]["members"] == expect_members


def test_add_preserves_unrelated_bindings_and_etag():
    before = {
        "etag": "abc123",
        "bindings": [{"role": "roles/viewer", "members": ["user:x"]}],
    }
    after, changed = add_workload_identity_binding(before, MEMBER)
    assert changed
    assert after["etag"] == "abc123"
    assert {"role": "roles/viewer", "members": ["user:x"]} in after["bindings"]
    assert before["bindings"] == [
        {"role": "roles/viewer", "members": ["user:x"]}
    ]  # input not mutated


@pytest.mark.parametrize(
    "before,expect_bindings,expect_changed",
    [
        # removes the member, keeps co-members
        (
            [{"role": WORKLOAD_IDENTITY_ROLE, "members": [MEMBER, OTHER]}],
            [{"role": WORKLOAD_IDENTITY_ROLE, "members": [OTHER]}],
            True,
        ),
        # last member → binding dropped entirely
        (
            [{"role": WORKLOAD_IDENTITY_ROLE, "members": [MEMBER]}],
            [],
            True,
        ),
        # absent → no-op
        (
            [{"role": "roles/viewer", "members": [MEMBER]}],
            [{"role": "roles/viewer", "members": [MEMBER]}],
            False,
        ),
    ],
)
def test_remove_workload_identity_binding(
    before, expect_bindings, expect_changed
):
    after, changed = remove_workload_identity_binding(
        {"bindings": before}, MEMBER
    )
    assert changed is expect_changed
    assert after["bindings"] == expect_bindings


# -- AWS ARN parsing -------------------------------------------------------


def test_issuer_and_role_parsing():
    assert issuer_from_provider_arn(PROVIDER_ARN) == ISSUER
    assert role_name_from_arn(ROLE_ARN) == "kf-user-role"
    with pytest.raises(PluginError):
        issuer_from_provider_arn("arn:aws:iam::1:oidc-provider")


# -- AWS trust-policy table (plugin_iam_test.go:302 analog) ----------------

SUBJECT = "system:serviceaccount:team-a:default-editor"
EXISTING = "system:serviceaccount:other:default-editor"


@pytest.mark.parametrize(
    "before_subs,expect_subs,expect_changed",
    [
        (None, [SUBJECT], True),                      # no :sub condition yet
        ([], [SUBJECT], True),                        # empty list
        ([EXISTING], [EXISTING, SUBJECT], True),      # append, preserve
        ([SUBJECT], [SUBJECT], False),                # dedupe → no-op
        # scalar string form: recognized as present, doc returned verbatim
        (SUBJECT, SUBJECT, False),
    ],
)
def test_add_trusted_service_account(before_subs, expect_subs, expect_changed):
    doc = trust_doc(before_subs, extra_subs_key=before_subs is not None)
    after, changed = add_trusted_service_account(doc, "team-a",
                                                 "default-editor")
    assert changed is expect_changed
    se = after["Statement"][0]["Condition"]["StringEquals"]
    assert se[f"{ISSUER}:sub"] == expect_subs
    assert se[f"{ISSUER}:aud"] == ["sts.amazonaws.com"]
    assert after["Version"] == "2012-10-17"
    assert (
        after["Statement"][0]["Principal"]["Federated"] == PROVIDER_ARN
    )


@pytest.mark.parametrize(
    "before_subs,expect_subs,expect_changed",
    [
        ([EXISTING, SUBJECT], [EXISTING], True),  # remove, preserve others
        ([SUBJECT], None, True),                  # last one → :sub key dropped
        ([EXISTING], [EXISTING], False),          # absent → no-op
    ],
)
def test_remove_trusted_service_account(
    before_subs, expect_subs, expect_changed
):
    doc = trust_doc(before_subs)
    after, changed = remove_trusted_service_account(
        doc, "team-a", "default-editor"
    )
    assert changed is expect_changed
    se = after["Statement"][0]["Condition"]["StringEquals"]
    if expect_subs is None:
        # Empty identity list must OMIT the key, not serialize null/[]
        # (plugin_iam.go:213-228).
        assert f"{ISSUER}:sub" not in se
    else:
        assert se[f"{ISSUER}:sub"] == expect_subs
    assert se[f"{ISSUER}:aud"] == ["sts.amazonaws.com"]


def test_malformed_trust_policy_raises():
    with pytest.raises(PluginError):
        add_trusted_service_account({"Statement": []}, "ns", "sa")
    with pytest.raises(PluginError):
        add_trusted_service_account(
            {"Statement": [{"Principal": {}}]}, "ns", "sa"
        )


# -- end-to-end through the ProfileController ------------------------------

GSA = "kf-user@my-proj.iam.gserviceaccount.com"
SA_RESOURCE = f"projects/my-proj/serviceAccounts/{GSA}"


def _profile(name="team-a", plugins=None):
    return new_resource(
        KIND,
        name,
        "default",
        spec={
            "owner": {"kind": "User", "name": "alice@example.com"},
            "plugins": plugins or [],
        },
    )


def _controller(api):
    gcp = InMemoryGcpIam()
    aws = InMemoryAwsIam({"kf-user-role": trust_doc([])})
    ctl = ProfileController(
        api,
        plugins={
            KIND_WORKLOAD_IDENTITY: WorkloadIdentityPlugin(gcp),
            KIND_AWS_IAM: AwsIamPlugin(aws),
        },
    )
    return ctl, gcp, aws


def test_workload_identity_apply_idempotent_and_revoke():
    api = FakeApiServer()
    ctl, gcp, aws = _controller(api)
    api.create(
        _profile(
            plugins=[
                {
                    "kind": KIND_WORKLOAD_IDENTITY,
                    "spec": {"gcpServiceAccount": GSA},
                }
            ]
        )
    )
    ctl.controller.run_until_idle()

    sa = api.get("ServiceAccount", "default-editor", "team-a")
    assert sa.metadata.annotations[GCP_ANNOTATION_KEY] == GSA
    member = workload_identity_member("my-proj", "team-a", "default-editor")
    assert gcp.policies[SA_RESOURCE]["bindings"] == [
        {"role": WORKLOAD_IDENTITY_ROLE, "members": [member]}
    ]
    set_calls = gcp.set_calls

    # Re-reconcile: policy must be a fixed point — no further writes.
    ctl.controller.enqueue(("default", "team-a"))
    ctl.controller.run_until_idle()
    assert gcp.set_calls == set_calls
    assert gcp.policies[SA_RESOURCE]["bindings"][0]["members"] == [member]

    # Finalize: binding revoked.
    api.delete(KIND, "team-a")
    ctl.controller.run_until_idle()
    assert gcp.policies[SA_RESOURCE]["bindings"] == []


def test_aws_iam_apply_idempotent_and_revoke():
    api = FakeApiServer()
    ctl, gcp, aws = _controller(api)
    api.create(
        _profile(
            plugins=[
                {"kind": KIND_AWS_IAM, "spec": {"awsIamRole": ROLE_ARN}}
            ]
        )
    )
    ctl.controller.run_until_idle()

    sa = api.get("ServiceAccount", "default-editor", "team-a")
    assert sa.metadata.annotations[AWS_ANNOTATION_KEY] == ROLE_ARN
    se = aws.roles["kf-user-role"]["Statement"][0]["Condition"][
        "StringEquals"
    ]
    assert se[f"{ISSUER}:sub"] == [SUBJECT]
    update_calls = aws.update_calls

    ctl.controller.enqueue(("default", "team-a"))
    ctl.controller.run_until_idle()
    assert aws.update_calls == update_calls  # idempotent re-apply

    api.delete(KIND, "team-a")
    ctl.controller.run_until_idle()
    se = aws.roles["kf-user-role"]["Statement"][0]["Condition"][
        "StringEquals"
    ]
    assert f"{ISSUER}:sub" not in se  # trust revoked on finalize


def test_both_plugins_compose():
    api = FakeApiServer()
    ctl, gcp, aws = _controller(api)
    api.create(
        _profile(
            plugins=[
                {
                    "kind": KIND_WORKLOAD_IDENTITY,
                    "spec": {"gcpServiceAccount": GSA},
                },
                {"kind": KIND_AWS_IAM, "spec": {"awsIamRole": ROLE_ARN}},
            ]
        )
    )
    ctl.controller.run_until_idle()
    sa = api.get("ServiceAccount", "default-editor", "team-a")
    assert sa.metadata.annotations[GCP_ANNOTATION_KEY] == GSA
    assert sa.metadata.annotations[AWS_ANNOTATION_KEY] == ROLE_ARN
    assert api.get(KIND, "team-a").status["condition"] == "Ready"
