"""Cloud Monitoring metrics provider: golden requests + dashboard wiring
(the `stackdriver_metrics_service.ts:15` analog behind MetricsService)."""

import pytest

from kubeflow_tpu.apps.cloud_metrics import CloudMonitoringMetricsService
from kubeflow_tpu.apps.dashboard import DashboardApp
from kubeflow_tpu.deploy.gke import RecordingTransport
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.web import TestClient
from kubeflow_tpu.web.wsgi import HttpError

NOW = 1_700_000_000.0

RESPONSE = {
    "timeSeries": [
        {
            "resource": {"labels": {"node_name": "tpu-node-0"}},
            "points": [
                {
                    "interval": {"endTime": "2023-11-14T22:12:00Z"},
                    "value": {"doubleValue": 0.83},
                },
                {
                    "interval": {"endTime": "2023-11-14T22:11:00Z"},
                    "value": {"doubleValue": 0.79},
                },
            ],
        }
    ]
}


def _service(**kw):
    transport = RecordingTransport(responses={"/timeSeries": RESPONSE})
    return (
        CloudMonitoringMetricsService(
            transport, "my-proj", now=lambda: NOW, **kw
        ),
        transport,
    )


def test_golden_request_construction():
    svc, _ = _service(cluster="kf-prod")
    req = svc.request_for("tpuduty", minutes=15)
    assert req.method == "GET"
    assert req.url == (
        "https://monitoring.googleapis.com/v3/projects/my-proj/timeSeries"
    )
    assert req.body == {
        "filter": (
            'metric.type = "kubernetes.io/node/accelerator/duty_cycle"'
            ' AND resource.labels.cluster_name = "kf-prod"'
        ),
        "interval.startTime": "2023-11-14T21:58:20Z",
        "interval.endTime": "2023-11-14T22:13:20Z",
        "aggregation.alignmentPeriod": "60s",
        "aggregation.perSeriesAligner": "ALIGN_MEAN",
    }


def test_metric_type_mapping():
    svc, _ = _service()
    assert "cpu/allocatable_utilization" in svc.request_for(
        "nodecpu", 5
    ).body["filter"]
    assert "memory/allocatable_utilization" in svc.request_for(
        "nodemem", 5
    ).body["filter"]
    with pytest.raises(HttpError):
        svc.request_for("bogus", 5)


def test_query_parses_time_series():
    svc, transport = _service()
    points = svc.query("tpuduty", 15)
    assert [p["value"] for p in points] == [0.79, 0.83]  # time-ordered
    assert all(p["node"] == "tpu-node-0" for p in points)
    assert transport.requests[0].url.endswith("/timeSeries")


def test_dashboard_serves_cloud_metrics():
    """The provider slots in behind DashboardApp's MetricsService seam —
    the factory-selected Stackdriver path of the reference."""
    api = FakeApiServer()
    svc, _ = _service()
    app = DashboardApp(api, metrics_service=svc)
    client = TestClient(
        app,
        headers={
            "x-goog-authenticated-user-email":
                "accounts.google.com:alice@x.co"
        },
    )
    resp = client.get("/api/metrics/tpuduty?window=15")
    assert resp.status == 200
    assert [p["value"] for p in resp.json()] == [0.79, 0.83]
