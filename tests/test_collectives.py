"""Named-axis collective wrappers on a virtual multi-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from kubeflow_tpu.parallel import collectives as col


def _smap(mesh, fn, in_specs, out_specs):
    return jax.jit(
        shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )
    )


def test_psum_across_dp(mesh8):
    x = np.ones((8, 4), np.float32)

    def f(xs):
        return col.psum(xs, ("dp", "fsdp"))

    y = _smap(mesh8, f, P(("dp", "fsdp"), None), P(("dp", "fsdp"), None))(x)
    np.testing.assert_allclose(np.asarray(y), 4.0 * x)


def test_all_gather_tiled(mesh8):
    x = np.arange(8, dtype=np.float32).reshape(8, 1)

    def f(xs):
        return col.all_gather(xs, "dp")

    # Shards of 4 rows (dp=2) -> gathered back to 8 rows on each shard.
    y = _smap(mesh8, f, P("dp", None), P(None, None))(x)
    np.testing.assert_allclose(np.asarray(y), x)


def test_reduce_scatter_roundtrip(mesh8):
    # On replicated input: reduce_scatter sums the tp copies and scatters
    # rows; all_gather reassembles — the FSDP gradient path in miniature.
    x = np.random.default_rng(0).normal(size=(8, 8)).astype(np.float32)

    def f(xs):
        rs = col.reduce_scatter(xs, "tp", scatter_axis=0)
        assert rs.shape == (4, 8)
        return col.all_gather(rs, "tp")

    y = _smap(mesh8, f, P(None, None), P(None, None))(x)
    np.testing.assert_allclose(np.asarray(y), 2.0 * x, rtol=1e-6)


def test_ppermute_ring_shift(mesh8):
    # Each tp shard emits its own index; after shift=1 each holds its left
    # neighbor's index (the input array is only a shape carrier).
    def f(_):
        idx = col.axis_index("tp").astype(jnp.float32).reshape(1)
        return col.ppermute_ring(idx, "tp", shift=1)

    y = _smap(mesh8, f, P("tp"), P("tp"))(np.zeros(2, np.float32))
    # tp has 2 shards: shard 0 receives from ... perm sends i -> i+1;
    # so shard 1 gets value 0, shard 0 gets value 1.
    np.testing.assert_allclose(np.asarray(y), [1.0, 0.0])


def test_all_to_all(mesh8):
    # 2 tp shards, each with (2, 2) -> exchange halves.
    x = np.arange(16, dtype=np.float32).reshape(4, 4)

    def f(xs):
        return col.all_to_all(xs, "tp", split_axis=1, concat_axis=0)

    y = _smap(mesh8, f, P("tp", None), P(None, "tp"))(x)
    assert np.asarray(y).shape == (4, 4)
    # Round-trip restores the original.
    def g(xs):
        z = col.all_to_all(xs, "tp", split_axis=1, concat_axis=0)
        return col.all_to_all(z, "tp", split_axis=0, concat_axis=1)

    y2 = _smap(mesh8, g, P("tp", None), P("tp", None))(x)
    np.testing.assert_allclose(np.asarray(y2), x)
