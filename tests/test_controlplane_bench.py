"""CI smoke for `bench.py --workload controlplane` (docs/perf.md): the
bench must run end-to-end at tiny scale and emit driver-parsable JSON
metric lines for every backend it covered."""

import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_controlplane_bench_smoke_emits_parsable_metrics():
    result = subprocess.run(
        [
            sys.executable, "bench.py", "--workload", "controlplane",
            "--cp-watchers", "3", "--cp-writers", "2", "--cp-events", "4",
            "--cp-objects", "40", "--cp-list-reps", "3",
            "--cp-payload", "64",
        ],
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True,
        text=True,
        timeout=280,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    metrics = [
        json.loads(line)
        for line in result.stdout.splitlines()
        if line.startswith("{")
    ]
    assert metrics, f"no metric lines in:\n{result.stdout}"
    for m in metrics:
        # The driver's parse contract — same shape as every other bench.
        assert set(m) == {"metric", "value", "unit", "vs_baseline"}, m
        assert isinstance(m["value"], (int, float)) and m["value"] > 0, m
    names = {m["metric"] for m in metrics}
    for stem in (
        "controlplane_fanout_deliveries_per_sec",
        "controlplane_list_p99_ms",
        "controlplane_delivery_p99_ms",
    ):
        assert f"{stem}_python" in names, (stem, names)
    # Native coverage is environment-dependent: when the toolchain is
    # absent the bench must SAY so rather than silently halving scope.
    if f"controlplane_fanout_deliveries_per_sec_native" not in names:
        assert "native backend unavailable" in result.stderr
