"""Copy-on-write store contract (docs/perf.md): one copy per commit,
zero copies per fan-out.

The regression these tests pin down: event dispatch used to deepcopy
per watcher per event (O(watchers x events x object size)); now every
consumer — journal, dispatch, watch handlers, get, list — shares one
frozen snapshot per commit, and copies-per-event stays O(1) as watcher
count grows. Mutating a frozen snapshot is a loud FrozenResourceError,
never silent corruption; `.thaw()` is the private-mutable-copy idiom.
"""

import pytest

from kubeflow_tpu.api.objects import (
    FrozenResourceError,
    Resource,
    new_resource,
)
from kubeflow_tpu.testing.fake_apiserver import FakeApiServer


def _make_api(name: str):
    if name == "native":
        try:
            from kubeflow_tpu.native.apiserver import NativeApiServer

            return NativeApiServer()
        except Exception as e:  # toolchain/build unavailable
            pytest.skip(f"native store unavailable: {e}")
    return FakeApiServer()


@pytest.fixture(params=["python", "native"])
def backend(request):
    return request.param


@pytest.fixture
def api(backend):
    return _make_api(backend)


def _flush(api) -> None:
    flush = getattr(api, "flush", None)
    if flush is not None:
        flush()


# -- copy counting ----------------------------------------------------------


def _count_copies(api, n_watchers: int, monkeypatch, events: int = 6) -> int:
    """Total Resource materializations (deepcopy + from_dict) across
    `events` create+update pairs with `n_watchers` subscribed."""
    for _ in range(n_watchers):
        api.watch(lambda event, obj: None)

    counts = {"n": 0}
    orig_deepcopy = Resource.deepcopy
    orig_from_dict = Resource.from_dict.__func__

    def counting_deepcopy(self):
        counts["n"] += 1
        return orig_deepcopy(self)

    def counting_from_dict(cls, d):
        counts["n"] += 1
        return orig_from_dict(cls, d)

    monkeypatch.setattr(Resource, "deepcopy", counting_deepcopy)
    monkeypatch.setattr(
        Resource, "from_dict", classmethod(counting_from_dict)
    )
    try:
        for i in range(events):
            obj = api.create(
                new_resource("CopyObj", f"c-{i}", "default", spec={"v": 0})
            )
            fresh = obj.thaw()
            fresh.spec["v"] = 1
            api.update(fresh)
        _flush(api)
    finally:
        monkeypatch.setattr(Resource, "deepcopy", orig_deepcopy)
        monkeypatch.setattr(
            Resource, "from_dict", classmethod(orig_from_dict)
        )
    return counts["n"]


def test_copies_per_event_constant_in_watcher_count(backend, monkeypatch):
    """THE tentpole property: the same workload costs the same number of
    Resource copies whether 1 or 32 watchers are subscribed."""
    per_count = {}
    for n in (1, 4, 32):
        api = _make_api(backend)
        per_count[n] = _count_copies(api, n, monkeypatch)
    assert per_count[1] == per_count[4] == per_count[32], (
        f"copies grew with watcher count: {per_count} — a per-watcher "
        "deepcopy crept back into the dispatch path"
    )


def test_all_watchers_share_one_frozen_snapshot(api):
    seen: list[tuple[int, bool]] = []
    for _ in range(4):
        api.watch(lambda event, obj: seen.append((id(obj), obj.frozen)))
    api.create(new_resource("ShareObj", "s-0", "default"))
    _flush(api)
    assert len(seen) == 4
    assert all(frozen for _, frozen in seen), "delivered object not frozen"
    assert len({oid for oid, _ in seen}) == 1, (
        "watchers received distinct objects — fan-out is copying again"
    )


# -- frozen-snapshot contract ----------------------------------------------


def test_get_list_and_returns_are_frozen(api):
    created = api.create(
        new_resource("FrozenObj", "f-0", "default", spec={"a": {"b": 1}})
    )
    assert created.frozen
    got = api.get("FrozenObj", "f-0")
    listed = api.list("FrozenObj")[0]
    for obj in (created, got, listed):
        assert obj.frozen
        with pytest.raises(FrozenResourceError):
            obj.spec["x"] = 1
        with pytest.raises(FrozenResourceError):
            obj.spec["a"]["b"] = 2  # nested structures frozen too
        with pytest.raises(FrozenResourceError):
            obj.metadata.labels["k"] = "v"
        with pytest.raises(FrozenResourceError):
            obj.status = {}
        with pytest.raises(FrozenResourceError):
            obj.metadata.finalizers.append("x")


def test_thaw_yields_private_mutable_copy(api):
    api.create(new_resource("ThawObj", "t-0", "default", spec={"v": 1}))
    fresh = api.get("ThawObj", "t-0").thaw()
    assert not fresh.frozen
    fresh.spec["v"] = 2
    # The store's snapshot is untouched until the write commits.
    assert api.get("ThawObj", "t-0").spec["v"] == 1
    updated = api.update(fresh)
    assert updated.frozen
    assert api.get("ThawObj", "t-0").spec["v"] == 2


def test_thaw_on_mutable_resource_is_identity():
    obj = new_resource("X", "x", "default")
    assert obj.thaw() is obj


def test_journal_events_are_frozen_snapshots(api):
    api.create(new_resource("JournalObj", "j-0", "default"))
    events, _rv = api.events_since(0, kind="JournalObj")
    assert events
    for _rv2, _etype, obj in events:
        assert obj.frozen
        with pytest.raises(FrozenResourceError):
            obj.spec["poison"] = True
    # The snapshot the journal shares IS the stored one.
    assert api.get("JournalObj", "j-0").spec.get("poison") is None


def test_handler_mutation_cannot_corrupt_other_watchers(api):
    """A misbehaving handler gets a loud error and the other handlers
    (and the store) still observe the committed state."""
    observed: list[dict] = []

    def bad_handler(event, obj):
        obj.spec["corrupted"] = True  # raises FrozenResourceError

    api.watch(bad_handler)
    api.watch(lambda event, obj: observed.append(dict(obj.spec)))
    api.create(
        new_resource("GuardObj", "g-0", "default", spec={"ok": True})
    )
    _flush(api)
    assert observed == [{"ok": True}]
    assert api.get("GuardObj", "g-0").spec == {"ok": True}


# -- shared watch cache (HTTP facade) ---------------------------------------


def test_watch_cache_serializes_each_event_once(api):
    """N long-poll consumers of the same events cost ONE serialization
    per event — the shared watch cache contract."""
    from kubeflow_tpu.testing.apiserver_http import ApiServerApp
    from kubeflow_tpu.web.wsgi import TestClient

    app = ApiServerApp(api)
    client = TestClient(app)
    for i in range(3):
        api.create(
            new_resource("CacheObj", f"w-{i}", "default", spec={"i": i})
        )
    for _ in range(5):  # five watchers replaying the same history
        resp = client.get(
            "/apis/CacheObj?watch=true&resourceVersion=0&timeoutSeconds=0.05"
        )
        assert resp.status == 200
        events = resp.json()["events"]
        assert [e["object"]["spec"]["i"] for e in events] == [0, 1, 2]
    assert app.watch_cache.serializations == 3, (
        f"{app.watch_cache.serializations} serializations for 3 events "
        f"x 5 watchers — the shared cache is not being hit "
        f"(hits={app.watch_cache.hits})"
    )
    assert app.watch_cache.hits >= 12
