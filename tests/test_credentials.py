"""Cloud credential plumbing: TokenSource, AuthTransport, and the GKE
ensure contracts over a fake GKE HTTP server.

The table tests mirror `bootstrap/cmd/bootstrap/app/tokenSource_test.go`
(empty-token rejection, access-check gating); the e2e mirrors the
kfctl deploy path (`kfctlServer.go:179-201` TokenSource injection,
`:219-294` PLATFORM apply) against a local stand-in for
container.googleapis.com.
"""

import http.server
import json
import threading
import time

import pytest

from kubeflow_tpu.deploy.credentials import (
    AuthTransport,
    CloudAuthError,
    CloudConflict,
    CloudNotFound,
    RefreshableTokenSource,
    StaticTokenSource,
    Token,
)
from kubeflow_tpu.deploy.gke import (
    GkeCloud,
    Request,
    node_pool_create_request,
)
from kubeflow_tpu.deploy.kfdef import NodePool, PlatformSpec
from kubeflow_tpu.deploy.provisioner import CloudError

SPEC = PlatformSpec(
    name="kf-test",
    project="my-proj",
    zone="us-central2-b",
    node_pools=[NodePool(name="pool-a", accelerator="v5e", topology="2x4")],
)


# -- Token ------------------------------------------------------------------


def test_token_validity_table():
    now = 1000.0
    cases = [
        (Token("t"), True),                      # static: never expires
        (Token("t", expiry=now + 3600), True),   # fresh
        (Token("t", expiry=now + 30), False),    # inside the 60s skew
        (Token("t", expiry=now - 1), False),     # expired
        (Token("", expiry=None), False),         # empty credential
    ]
    for token, want in cases:
        assert token.valid_at(now) is want, token


# -- RefreshableTokenSource (tokenSource_test.go table) ---------------------


def test_refresh_rejects_empty_token():
    ts = RefreshableTokenSource("my-proj")
    with pytest.raises(ValueError):
        ts.refresh(Token(""))


def test_refresh_rejects_insufficient_access_and_keeps_old():
    """A bad push must never clobber a working credential
    (tokenSource.go:52-64: IAM check before swap)."""
    ts = RefreshableTokenSource(
        "my-proj", checker=lambda project, tok: tok.access_token == "good"
    )
    ts.refresh(Token("good"))
    with pytest.raises(CloudAuthError):
        ts.refresh(Token("stolen"))
    assert ts.token().access_token == "good"


def test_project_is_required():
    with pytest.raises(ValueError):
        RefreshableTokenSource("")


def test_token_pull_refreshes_on_expiry():
    clock = [1000.0]
    minted = []

    def refresh_fn():
        minted.append(1)
        return Token(f"t{len(minted)}", expiry=clock[0] + 3600)

    ts = RefreshableTokenSource(
        "my-proj", refresh_fn=refresh_fn, clock=lambda: clock[0]
    )
    assert ts.token().access_token == "t1"
    assert ts.token().access_token == "t1"  # cached while valid
    clock[0] += 3600 - 30  # into the expiry skew
    assert ts.token().access_token == "t2"
    assert len(minted) == 2


def test_token_without_refresh_raises():
    ts = RefreshableTokenSource("my-proj")
    with pytest.raises(CloudAuthError):
        ts.token()
    ts.refresh(Token("pushed"))
    assert ts.token().access_token == "pushed"


def test_refresh_fn_returning_expired_token_raises():
    ts = RefreshableTokenSource(
        "my-proj",
        refresh_fn=lambda: Token("dead", expiry=0.0),
        clock=lambda: 1000.0,
    )
    with pytest.raises(CloudAuthError):
        ts.token()


# -- AuthTransport ----------------------------------------------------------


def fake_sender(script):
    """script: list of (status, body); records (method, url, headers)."""
    calls = []

    def send(method, url, headers, body):
        calls.append((method, url, headers, body))
        status, resp = script[min(len(calls), len(script)) - 1]
        return status, resp

    send.calls = calls
    return send


def test_auth_transport_stamps_bearer_and_returns_body():
    sender = fake_sender([(200, {"ok": True})])
    t = AuthTransport(StaticTokenSource("sekret"), sender=sender)
    out = t.send(Request("GET", "https://container.googleapis.com/v1/x"))
    assert out == {"ok": True}
    _, _, headers, _ = sender.calls[0]
    assert headers["Authorization"] == "Bearer sekret"


@pytest.mark.parametrize(
    "status,exc",
    [(401, CloudAuthError), (403, CloudAuthError), (404, CloudNotFound),
     (409, CloudConflict), (429, CloudError), (500, CloudError),
     (503, CloudError), (400, CloudError)],
)
def test_auth_transport_status_mapping(status, exc):
    t = AuthTransport(
        StaticTokenSource("t"), sender=fake_sender([(status, {"error": "x"})])
    )
    with pytest.raises(exc):
        t.send(Request("GET", "https://container.googleapis.com/v1/x"))


def test_auth_transport_api_base_override():
    sender = fake_sender([(200, {})])
    t = AuthTransport(
        StaticTokenSource("t"), sender=sender,
        api_base="http://127.0.0.1:9999/v1",
    )
    t.send(Request("GET", "https://container.googleapis.com/v1/projects/p"))
    assert sender.calls[0][1] == "http://127.0.0.1:9999/v1/projects/p"


def test_auth_transport_surfaces_missing_credential():
    t = AuthTransport(
        RefreshableTokenSource("my-proj"), sender=fake_sender([(200, {})])
    )
    with pytest.raises(CloudAuthError):
        t.send(Request("GET", "https://container.googleapis.com/v1/x"))


# -- GkeCloud ensure contracts ---------------------------------------------


def scripted_transport(script):
    """script: {(method, url-suffix): [(status, body), ...]} consumed in
    order; unmatched → 200 {}."""
    sender_calls = []

    class T:
        def send(self, request):
            sender_calls.append(request)
            for (method, suffix), responses in script.items():
                if request.method == method and request.url.endswith(suffix):
                    status, body = (
                        responses.pop(0) if responses else (200, {})
                    )
                    if status == 404:
                        raise CloudNotFound(request.url)
                    if status == 409:
                        raise CloudConflict(request.url)
                    if status >= 400:
                        raise CloudError(f"{status}")
                    return body
            return {}

    t = T()
    t.calls = sender_calls
    return t


def test_ensure_node_pool_treats_create_409_as_success():
    """The list/create race: another apply created the pool between our
    list and create — the documented idempotency contract."""
    t = scripted_transport({
        ("GET", "/nodePools"): [(200, {"nodePools": []})],
        ("POST", "/nodePools"): [(409, {})],
    })
    GkeCloud(t).ensure_node_pool(SPEC, SPEC.node_pools[0])  # no raise


def test_ensure_cluster_creates_when_missing():
    t = scripted_transport({
        ("GET", "/clusters/kf-test"): [(404, {})],
    })
    GkeCloud(t).ensure_cluster(SPEC)
    assert [r.method for r in t.calls] == ["GET", "POST"]
    assert t.calls[1].body["cluster"]["name"] == "kf-test"


def test_ensure_cluster_noops_when_present():
    t = scripted_transport({
        ("GET", "/clusters/kf-test"): [(200, {"name": "kf-test"})],
    })
    GkeCloud(t).ensure_cluster(SPEC)
    assert [r.method for r in t.calls] == ["GET"]


def test_ensure_cluster_records_create_on_recording_transport():
    """RecordingTransport returns {} for the GET (it can't raise 404), so
    ensure must still record the cluster create — recorded traffic stays
    identical to what a real transport would send on a fresh project."""
    from kubeflow_tpu.deploy.gke import RecordingTransport

    t = RecordingTransport()
    GkeCloud(t).ensure_cluster(SPEC)
    assert [r.method for r in t.requests] == ["GET", "POST"]
    assert t.requests[1].url.endswith("/clusters")


def test_ensure_cluster_treats_create_409_as_success():
    t = scripted_transport({
        ("GET", "/clusters/kf-test"): [(404, {})],
        ("POST", "/clusters"): [(409, {})],
    })
    GkeCloud(t).ensure_cluster(SPEC)  # no raise


# -- fake GKE server e2e ----------------------------------------------------


class FakeGke(http.server.BaseHTTPRequestHandler):
    """A local container.googleapis.com: clusters + nodePools CRUD with
    scriptable first-response failures (409 on cluster create, one 500 on
    pool create) — the retry paths the reference's deploy loop depends on
    (kfctlServer.go:290-294)."""

    state = None  # set per-test: dict(clusters=set(), pools=set(), log=[], flaky_pool_creates=N)

    def _reply(self, status, body):
        data = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):
        pass

    def _record(self):
        length = int(self.headers.get("Content-Length") or 0)
        body = json.loads(self.rfile.read(length)) if length else None
        FakeGke.state["log"].append(
            (self.command, self.path, self.headers.get("Authorization"), body)
        )
        return body

    def do_GET(self):
        self._record()
        s = FakeGke.state
        if self.path.endswith("/nodePools"):
            return self._reply(
                200, {"nodePools": [{"name": p} for p in sorted(s["pools"])]}
            )
        name = self.path.rsplit("/", 1)[-1]
        if name in s["clusters"]:
            return self._reply(200, {"name": name})
        return self._reply(404, {"error": "not found"})

    def do_POST(self):
        body = self._record()
        s = FakeGke.state
        if self.headers.get("Authorization") != "Bearer gcp-token":
            return self._reply(401, {"error": "bad credentials"})
        if self.path.endswith("/clusters"):
            name = body["cluster"]["name"]
            if name in s["clusters"]:
                return self._reply(409, {"error": "already exists"})
            s["clusters"].add(name)
            return self._reply(200, {"name": name})
        if self.path.endswith("/nodePools"):
            if s["flaky_pool_creates"] > 0:
                s["flaky_pool_creates"] -= 1
                return self._reply(500, {"error": "backend error"})
            s["pools"].add(body["nodePool"]["name"])
            return self._reply(200, {})
        return self._reply(404, {"error": "no route"})


@pytest.fixture
def fake_gke():
    FakeGke.state = {
        "clusters": set(), "pools": set(), "log": [],
        "flaky_pool_creates": 1,
    }
    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), FakeGke)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{server.server_port}/v1", FakeGke.state
    server.shutdown()


def test_deploy_apply_gke_end_to_end(fake_gke):
    """`deploy apply --provider gke` against a live (local) GKE API:
    bearer auth on the wire, cluster created, one 500 on pool create
    retried to success, and a second apply no-ops (list sees the pool)."""
    from kubeflow_tpu.deploy.apply import apply_platform
    from kubeflow_tpu.testing.fake_apiserver import FakeApiServer

    base, state = fake_gke
    transport = AuthTransport(
        StaticTokenSource("gcp-token"), api_base=base
    )
    cloud = GkeCloud(transport)
    spec = PlatformSpec(
        name="kf-gke", project="my-proj", zone="us-central2-b",
        provider="gke",
        node_pools=[
            NodePool(name="pool-a", accelerator="v5e", topology="2x4")
        ],
    )
    api = FakeApiServer()
    result = apply_platform(spec, api, cloud)
    assert result.succeeded, result.error
    assert state["clusters"] == {"kf-gke"}
    assert state["pools"] == {"pool-a"}
    # The flaky first create was retried: two POSTs to nodePools.
    pool_posts = [e for e in state["log"]
                  if e[0] == "POST" and e[1].endswith("/nodePools")]
    assert len(pool_posts) == 2
    # Every request carried the bearer token.
    assert all(e[2] == "Bearer gcp-token" for e in state["log"])

    # Second apply: idempotent (no new creates).
    creates_before = len([e for e in state["log"] if e[0] == "POST"])
    result2 = apply_platform(spec, api, cloud)
    assert result2.succeeded
    assert len([e for e in state["log"] if e[0] == "POST"]) == creates_before


def test_deploy_apply_gke_rejects_bad_token(fake_gke):
    from kubeflow_tpu.deploy.apply import apply_platform
    from kubeflow_tpu.testing.fake_apiserver import FakeApiServer

    base, state = fake_gke
    cloud = GkeCloud(
        AuthTransport(StaticTokenSource("wrong"), api_base=base)
    )
    spec = PlatformSpec(
        name="kf-bad", project="my-proj", zone="us-central2-b",
        provider="gke",
        node_pools=[
            NodePool(name="pool-a", accelerator="v5e", topology="2x4")
        ],
    )
    result = apply_platform(spec, FakeApiServer(), cloud, retries=1)
    assert not result.succeeded
    assert "PLATFORM phase" in result.error
    assert state["clusters"] == set()


def test_node_pool_request_against_urllib_sender(fake_gke):
    """The real urllib network edge works against a live HTTP server (not
    just the fake_sender seam)."""
    base, state = fake_gke
    state["flaky_pool_creates"] = 0
    state["clusters"].add("kf-test")
    t = AuthTransport(StaticTokenSource("gcp-token"), api_base=base)
    out = t.send(node_pool_create_request(SPEC, SPEC.node_pools[0]))
    assert out == {}
    assert state["pools"] == {"pool-a"}


def test_delete_node_pool_tolerates_missing():
    """Teardown retries and gc must be idempotent: a 404 on delete (pool
    already gone) is success, not a stuck deployment."""
    t = scripted_transport({
        ("DELETE", "/nodePools/pool-a"): [(404, {})],
    })
    GkeCloud(t).delete_node_pool(SPEC, "pool-a")  # no raise
