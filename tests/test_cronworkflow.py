"""CronWorkflow: schedule parsing + the scheduling controller
(the Prow-periodics / Argo-CronWorkflow analog)."""

import pytest

from kubeflow_tpu.api.cron import (
    KIND,
    CronSchedule,
    CronWorkflowSpec,
)
from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.workflow import KIND as WF_KIND
from kubeflow_tpu.controllers.cronworkflow import (
    LABEL_CRON,
    CronWorkflowController,
)
from kubeflow_tpu.testing import FakeApiServer

T0 = float(1_700_000_000 // 60 * 60)  # on a minute boundary

WF_SPEC = {"steps": [{"name": "tick", "command": ["/bin/echo", "ok"]}]}


# -- schedule parsing ------------------------------------------------------


def test_cron_parse_star_and_steps():
    s = CronSchedule.parse("*/15 * * * *")
    assert s.minute == frozenset({0, 15, 30, 45})
    assert s.hour == frozenset(range(24))


def test_cron_parse_ranges_and_lists():
    s = CronSchedule.parse("0 9-17 * * 1-5")
    assert s.minute == frozenset({0})
    assert s.hour == frozenset(range(9, 18))
    assert s.dow == frozenset(range(1, 6))
    s2 = CronSchedule.parse("5,35 0,12 1 1,6 *")
    assert s2.minute == frozenset({5, 35})
    assert s2.month == frozenset({1, 6})


@pytest.mark.parametrize(
    "bad",
    ["* * * *", "61 * * * *", "a * * * *", "* * * * 8", "*/0 * * * *",
     "5-2 * * * *"],
)
def test_cron_parse_rejects(bad):
    with pytest.raises(ValueError):
        CronSchedule.parse(bad)


def test_next_after_every_minute():
    s = CronSchedule.parse("* * * * *")
    assert s.next_after(T0) == T0 + 60
    assert s.next_after(T0 + 1) == T0 + 60  # rounds to the next minute


def test_next_after_quarter_hours():
    s = CronSchedule.parse("*/15 * * * *")
    nxt = s.next_after(T0)
    assert nxt > T0 and s.matches(nxt)
    import time as _time

    assert _time.localtime(nxt).tm_min % 15 == 0


def test_spec_validation():
    CronWorkflowSpec(schedule="* * * * *", workflow_spec=WF_SPEC).validate()
    with pytest.raises(ValueError):
        CronWorkflowSpec(schedule="* * * * *", workflow_spec={}).validate()
    with pytest.raises(ValueError):
        CronWorkflowSpec(
            schedule="* * * * *", workflow_spec=WF_SPEC,
            concurrency_policy="Sometimes",
        ).validate()


# -- controller ------------------------------------------------------------


class Clock:
    def __init__(self, t):
        self.t = t

    def __call__(self):
        return self.t


def _world(policy="Allow", suspend=False, history=3):
    api = FakeApiServer()
    clock = Clock(T0 + 1)
    ctl = CronWorkflowController(api, now=clock)
    spec = CronWorkflowSpec(
        schedule="* * * * *",
        workflow_spec=WF_SPEC,
        concurrency_policy=policy,
        suspend=suspend,
        history_limit=history,
    )
    api.create(new_resource(KIND, "nightly", "ci", spec=spec.to_dict()))
    ctl.controller.run_until_idle()
    return api, clock, ctl


def _tick(api, clock, ctl, dt=61):
    clock.t += dt
    ctl.controller.enqueue(("ci", "nightly"))
    ctl.controller.run_until_idle()


def spawned(api):
    return api.list(WF_KIND, "ci", label_selector={LABEL_CRON: "nightly"})


def test_first_reconcile_anchors_without_spawning():
    api, clock, ctl = _world()
    assert spawned(api) == []
    status = api.get(KIND, "nightly", "ci").status
    assert status["lastScheduleTime"] == clock.t


def test_tick_spawns_owned_workflow():
    api, clock, ctl = _world()
    _tick(api, clock, ctl)
    [wf] = spawned(api)
    assert wf.spec["steps"][0]["name"] == "tick"
    cw = api.get(KIND, "nightly", "ci")
    assert wf.metadata.owner_references[0]["uid"] == cw.metadata.uid
    reasons = [e.spec["reason"] for e in api.list("Event", "ci")]
    assert "WorkflowSpawned" in reasons


def test_many_missed_ticks_spawn_once():
    """A controller that was down must not burst a backfill: one
    catch-up run, anchored at the most recent missed tick."""
    api, clock, ctl = _world()
    _tick(api, clock, ctl, dt=3600)  # an hour of missed minutes
    assert len(spawned(api)) == 1
    status = api.get(KIND, "nightly", "ci").status
    assert clock.t - status["lastScheduleTime"] < 120


def test_forbid_skips_while_previous_runs():
    api, clock, ctl = _world(policy="Forbid")
    _tick(api, clock, ctl)
    assert len(spawned(api)) == 1
    _tick(api, clock, ctl)  # previous still non-terminal
    assert len(spawned(api)) == 1
    reasons = [e.spec["reason"] for e in api.list("Event", "ci")]
    assert "RunSkipped" in reasons
    # Finish the run → next tick fires again.
    wf = spawned(api)[0].thaw()
    wf.status["phase"] = "Succeeded"
    api.update_status(wf)
    _tick(api, clock, ctl)
    assert len(spawned(api)) == 2


def test_replace_deletes_running_run():
    api, clock, ctl = _world(policy="Replace")
    _tick(api, clock, ctl)
    first = spawned(api)[0].metadata.name
    _tick(api, clock, ctl)
    names = [w.metadata.name for w in spawned(api)]
    assert first not in names and len(names) == 1


def test_suspend_holds_fire():
    api, clock, ctl = _world(suspend=True)
    _tick(api, clock, ctl, dt=3600)
    assert spawned(api) == []


def test_history_gc():
    api, clock, ctl = _world(history=1)
    for _ in range(3):
        _tick(api, clock, ctl)
        for wf in spawned(api):
            if wf.status.get("phase") != "Succeeded":
                wf = wf.thaw()
                wf.status["phase"] = "Succeeded"
                api.update_status(wf)
    ctl.controller.enqueue(("ci", "nightly"))
    ctl.controller.run_until_idle()
    assert len(spawned(api)) == 1  # older finished runs collected


def test_invalid_spec_surfaces():
    api = FakeApiServer()
    ctl = CronWorkflowController(api, now=Clock(T0))
    api.create(
        new_resource(KIND, "bad", "ci",
                     spec={"schedule": "nope", "workflowSpec": WF_SPEC})
    )
    ctl.controller.run_until_idle()
    assert "error" in api.get(KIND, "bad", "ci").status
    reasons = [e.spec["reason"] for e in api.list("Event", "ci")]
    assert "InvalidSpec" in reasons


def test_spawned_workflow_actually_runs(tmp_path):
    """Integration: the cron tick materializes a Workflow the workflow
    controller drives to completion with real step processes."""
    import sys
    import time as _time

    from kubeflow_tpu.controllers.workflow import WorkflowController
    from kubeflow_tpu.runtime import LocalPodRunner

    api = FakeApiServer()
    clock = Clock(T0 + 1)
    cron_ctl = CronWorkflowController(api, now=clock)
    wf_ctl = WorkflowController(api)
    runner = LocalPodRunner(api, capture_dir=str(tmp_path))
    spec = CronWorkflowSpec(
        schedule="* * * * *",
        workflow_spec={
            "steps": [
                {
                    "name": "tick",
                    "command": [sys.executable, "-c", "print('tick ok')"],
                }
            ]
        },
    )
    api.create(new_resource(KIND, "nightly", "ci", spec=spec.to_dict()))
    cron_ctl.controller.run_until_idle()
    clock.t += 61
    cron_ctl.controller.enqueue(("ci", "nightly"))
    deadline = _time.time() + 60
    try:
        while _time.time() < deadline:
            cron_ctl.controller.run_until_idle()
            wf_ctl.controller.run_until_idle()
            runner.step()
            runs = spawned(api)
            if runs and runs[0].status.get("phase") == "Succeeded":
                break
            _time.sleep(0.1)
    finally:
        runner.shutdown()
    [wf] = spawned(api)
    assert wf.status["phase"] == "Succeeded", wf.status


def test_dom_dow_both_restricted_is_vixie_or():
    """'0 0 1,15 * 1' fires on the 1st, the 15th, AND every Monday
    (standard Vixie/Argo semantics: when both day fields are restricted,
    a match on either is a day match)."""
    import time as _time

    s = CronSchedule.parse("0 0 1,15 * 1")
    wed_first = _time.mktime((2026, 7, 1, 0, 0, 0, 0, 0, -1))  # Wed Jul 1
    monday = _time.mktime((2026, 7, 6, 0, 0, 0, 0, 0, -1))  # Mon Jul 6
    tue_20 = _time.mktime((2026, 7, 21, 0, 0, 0, 0, 0, -1))  # Tue Jul 21
    assert s.matches(wed_first)
    assert s.matches(monday)
    assert not s.matches(tue_20)
    # With dom='*', the classic AND applies: Mondays only.
    weekly = CronSchedule.parse("0 0 * * 1")
    assert weekly.matches(monday) and not weekly.matches(wed_first)


def test_next_after_sparse_schedule_is_cheap():
    """'0 0 29 2 *' (every 4th year) must resolve by day arithmetic, not
    a multi-million minute scan — reconciles call next_after every pass."""
    import time as _time

    s = CronSchedule.parse("0 0 29 2 *")
    start = _time.perf_counter()
    nxt = s.next_after(T0)
    assert _time.perf_counter() - start < 0.5
    tm = _time.localtime(nxt)
    assert (tm.tm_mon, tm.tm_mday, tm.tm_hour, tm.tm_min) == (2, 29, 0, 0)


def test_dow_seven_is_sunday():
    assert CronSchedule.parse("0 6 * * 7").dow == frozenset({0})
    assert CronSchedule.parse("0 6 * * 0,7").dow == frozenset({0})


def test_unsatisfiable_schedule_is_invalid_spec():
    """Field-valid but never-firing (Feb 31): terminal InvalidSpec, not
    a crash-loop in requeue backoff."""
    api = FakeApiServer()
    ctl = CronWorkflowController(api, now=Clock(T0))
    api.create(
        new_resource(
            KIND, "never", "ci",
            spec={"schedule": "0 0 31 2 *", "workflowSpec": WF_SPEC},
        )
    )
    ctl.controller.run_until_idle()
    status = api.get(KIND, "never", "ci").status
    assert "no matching time" in status["error"]


def test_spawn_adopts_existing_run_after_crash():
    """AlreadyExists on the recomputed run name (crash between create
    and the status write) is adoption, not an error loop."""
    api, clock, ctl = _world()
    _tick(api, clock, ctl)
    [wf] = spawned(api)
    # Simulate the crash: rewind lastScheduleTime so the same fire time
    # (and run name) is recomputed.
    cw = api.get(KIND, "nightly", "ci").thaw()
    cw.status["lastScheduleTime"] = cw.status["lastScheduleTime"] - 60
    api.update_status(cw)
    ctl.controller.enqueue(("ci", "nightly"))
    ctl.controller.run_until_idle()  # must not raise / hot-loop
    assert len(spawned(api)) == 1


def test_next_after_dst_edges_match_minute_scan():
    """Fall-back (ambiguous wall time → FIRST epoch) and spring-forward
    (skipped wall time → next real occurrence) agree with a brute-force
    minute scan."""
    import os
    import time as _time

    if not hasattr(_time, "tzset"):
        pytest.skip("no tzset on this platform")
    old = os.environ.get("TZ")
    os.environ["TZ"] = "America/New_York"
    _time.tzset()
    try:
        def brute(s, t):
            base = int(t // 60) * 60
            return float(next(
                base + i * 60 for i in range(1, 200_000)
                if s.matches(base + i * 60)
            ))

        fall = CronSchedule.parse("30 1 * * *")
        t1 = _time.mktime((2026, 10, 31, 23, 0, 0, 0, 0, -1))
        assert fall.next_after(t1) == brute(fall, t1)
        spring = CronSchedule.parse("30 2 * * *")
        t2 = _time.mktime((2026, 3, 7, 23, 0, 0, 0, 0, -1))
        assert spring.next_after(t2) == brute(spring, t2)
    finally:
        if old is None:
            os.environ.pop("TZ", None)
        else:
            os.environ["TZ"] = old
        _time.tzset()
