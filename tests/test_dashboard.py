"""Central dashboard API: namespaces, activities, metrics, workgroup flow."""

import pytest

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.rbac import (
    make_cluster_role_binding,
    seed_cluster_roles,
)
from kubeflow_tpu.apps.dashboard import DashboardApp
from kubeflow_tpu.controllers.profile import ProfileController
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.web import TestClient

HDR = "x-goog-authenticated-user-email"


def client(app, user):
    return TestClient(app, headers={HDR: f"accounts.google.com:{user}"})


@pytest.fixture
def world():
    api = FakeApiServer()
    seed_cluster_roles(api)
    api.create(make_cluster_role_binding("adm", "kubeflow-admin", "admin@x.co"))
    ctl = ProfileController(api)
    app = DashboardApp(api)
    return api, ctl, app


def test_registration_flow(world):
    """§3.4: exists → create → profile controller provisions → env-info."""
    api, ctl, app = world
    c = client(app, "alice@x.co")

    assert c.get("/api/workgroup/exists").json()["hasWorkgroup"] is False
    r = c.post("/api/workgroup/create", body={})
    assert r.status == 200
    assert r.json()["namespace"] == "alice"
    ctl.controller.run_until_idle()

    info = c.get("/api/workgroup/env-info").json()
    assert info["hasWorkgroup"] is True
    assert info["namespaces"] == ["alice"]
    assert info["isClusterAdmin"] is False
    assert c.get("/api/namespaces").json()["namespaces"] == ["alice"]


def test_activities_surface_events(world):
    api, ctl, app = world
    c = client(app, "alice@x.co")
    c.post("/api/workgroup/create", body={})
    ctl.controller.run_until_idle()
    nb = api.create(new_resource("Notebook", "nb", "alice"))
    api.record_event(nb, "Created", "notebook created")

    acts = c.get("/api/activities/alice").json()
    assert acts and acts[0]["reason"] == "Created"


def test_metrics_series(world):
    api, _, app = world
    node = new_resource("Node", "tpu-node-0", "")
    api.create(node)
    node = api.get("Node", "tpu-node-0", "")
    node.status = {
        "cpuUtilization": 0.4,
        "memoryUtilization": 0.6,
        "tpuDutyCycle": 0.95,
    }
    api.update_status(node)
    c = client(app, "alice@x.co")
    [pt] = c.get("/api/metrics/tpuduty").json()
    assert pt["value"] == 0.95
    assert c.get("/api/metrics/bogus").status == 400


def test_dashboard_links_configmap_override(world):
    api, _, app = world
    c = client(app, "alice@x.co")
    links = c.get("/api/dashboard-links").json()
    assert any("/jupyter/" in m["link"] for m in links["menuLinks"])

    api.create(
        new_resource(
            "ConfigMap",
            "dashboard-links",
            "kubeflow",
            spec={"data": {"menuLinks": [{"link": "/custom/", "text": "X"}]}},
        )
    )
    links = c.get("/api/dashboard-links").json()
    assert links["menuLinks"][0]["link"] == "/custom/"


def test_nuke_self_removes_profiles(world):
    api, ctl, app = world
    c = client(app, "alice@x.co")
    c.post("/api/workgroup/create", body={})
    ctl.controller.run_until_idle()
    assert c.request("DELETE", "/api/workgroup/nuke-self").status == 200
    ctl.controller.run_until_idle()
    assert api.list("Profile") == []
    assert c.get("/api/workgroup/exists").json()["hasWorkgroup"] is False
    assert c.request("DELETE", "/api/workgroup/nuke-self").status == 404


def test_activities_authz(world):
    api, ctl, app = world
    client(app, "alice@x.co").post("/api/workgroup/create", body={})
    ctl.controller.run_until_idle()
    # Another user cannot read alice's event stream.
    assert client(app, "bob@x.co").get("/api/activities/alice").status == 403


def test_registration_flow_disabled(world):
    api, _, _ = world
    app = DashboardApp(api, registration_flow=False)
    c = client(app, "alice@x.co")
    assert c.get("/api/workgroup/exists").json()["registrationFlowAllowed"] is False
    assert c.post("/api/workgroup/create", body={}).status == 403
    assert api.list("Profile") == []


def test_metrics_bad_window_is_400(world):
    _, _, app = world
    assert client(app, "a@x.co").get("/api/metrics/tpuduty?window=abc").status == 400


def test_all_namespaces_admin_only(world):
    api, ctl, app = world
    client(app, "alice@x.co").post("/api/workgroup/create", body={})
    ctl.controller.run_until_idle()
    assert client(app, "alice@x.co").get(
        "/api/workgroup/get-all-namespaces"
    ).status == 403
    rows = client(app, "admin@x.co").get(
        "/api/workgroup/get-all-namespaces"
    ).json()
    assert ["alice", "alice@x.co"] in rows
