"""Central dashboard API: namespaces, activities, metrics, workgroup flow."""

import pytest

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.rbac import (
    make_cluster_role_binding,
    seed_cluster_roles,
)
from kubeflow_tpu.apps.dashboard import DashboardApp
from kubeflow_tpu.controllers.profile import ProfileController
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.web import TestClient

HDR = "x-goog-authenticated-user-email"


def client(app, user):
    return TestClient(app, headers={HDR: f"accounts.google.com:{user}"})


@pytest.fixture
def world():
    api = FakeApiServer()
    seed_cluster_roles(api)
    api.create(make_cluster_role_binding("adm", "kubeflow-admin", "admin@x.co"))
    ctl = ProfileController(api)
    app = DashboardApp(api)
    return api, ctl, app


def test_registration_flow(world):
    """§3.4: exists → create → profile controller provisions → env-info."""
    api, ctl, app = world
    c = client(app, "alice@x.co")

    assert c.get("/api/workgroup/exists").json()["hasWorkgroup"] is False
    r = c.post("/api/workgroup/create", body={})
    assert r.status == 200
    assert r.json()["namespace"] == "alice"
    ctl.controller.run_until_idle()

    info = c.get("/api/workgroup/env-info").json()
    assert info["hasWorkgroup"] is True
    assert info["namespaces"] == ["alice"]
    assert info["isClusterAdmin"] is False
    assert c.get("/api/namespaces").json()["namespaces"] == ["alice"]


def test_activities_surface_events(world):
    api, ctl, app = world
    c = client(app, "alice@x.co")
    c.post("/api/workgroup/create", body={})
    ctl.controller.run_until_idle()
    nb = api.create(new_resource("Notebook", "nb", "alice"))
    api.record_event(nb, "Created", "notebook created")

    acts = c.get("/api/activities/alice").json()
    assert acts and acts[0]["reason"] == "Created"


def test_metrics_series(world):
    api, _, app = world
    node = new_resource("Node", "tpu-node-0", "")
    api.create(node)
    node = api.get("Node", "tpu-node-0", "").thaw()
    node.status = {
        "cpuUtilization": 0.4,
        "memoryUtilization": 0.6,
        "tpuDutyCycle": 0.95,
    }
    api.update_status(node)
    c = client(app, "alice@x.co")
    [pt] = c.get("/api/metrics/tpuduty").json()
    assert pt["value"] == 0.95
    assert c.get("/api/metrics/bogus").status == 400


def test_dashboard_links_configmap_override(world):
    api, _, app = world
    c = client(app, "alice@x.co")
    links = c.get("/api/dashboard-links").json()
    assert any("/jupyter/" in m["link"] for m in links["menuLinks"])

    api.create(
        new_resource(
            "ConfigMap",
            "dashboard-links",
            "kubeflow",
            spec={"data": {"menuLinks": [{"link": "/custom/", "text": "X"}]}},
        )
    )
    links = c.get("/api/dashboard-links").json()
    assert links["menuLinks"][0]["link"] == "/custom/"


def test_nuke_self_removes_profiles(world):
    api, ctl, app = world
    c = client(app, "alice@x.co")
    c.post("/api/workgroup/create", body={})
    ctl.controller.run_until_idle()
    assert c.request("DELETE", "/api/workgroup/nuke-self").status == 200
    ctl.controller.run_until_idle()
    assert api.list("Profile") == []
    assert c.get("/api/workgroup/exists").json()["hasWorkgroup"] is False
    assert c.request("DELETE", "/api/workgroup/nuke-self").status == 404


def test_activities_authz(world):
    api, ctl, app = world
    client(app, "alice@x.co").post("/api/workgroup/create", body={})
    ctl.controller.run_until_idle()
    # Another user cannot read alice's event stream.
    assert client(app, "bob@x.co").get("/api/activities/alice").status == 403


def test_registration_flow_disabled(world):
    api, _, _ = world
    app = DashboardApp(api, registration_flow=False)
    c = client(app, "alice@x.co")
    assert c.get("/api/workgroup/exists").json()["registrationFlowAllowed"] is False
    assert c.post("/api/workgroup/create", body={}).status == 403
    assert api.list("Profile") == []


def test_metrics_bad_window_is_400(world):
    _, _, app = world
    assert client(app, "a@x.co").get("/api/metrics/tpuduty?window=abc").status == 400


def test_all_namespaces_admin_only(world):
    api, ctl, app = world
    client(app, "alice@x.co").post("/api/workgroup/create", body={})
    ctl.controller.run_until_idle()
    assert client(app, "alice@x.co").get(
        "/api/workgroup/get-all-namespaces"
    ).status == 403
    rows = client(app, "admin@x.co").get(
        "/api/workgroup/get-all-namespaces"
    ).json()
    assert ["alice", "alice@x.co"] in rows


def test_workloads_table(world):
    """The home page's 'what is holding chips' table: TpuJobs, Studies,
    Workflows with phase + chip ask."""
    api, ctl, app = world
    c = client(app, "alice@x.co")
    c.post("/api/workgroup/create", body={})
    ctl.controller.run_until_idle()
    from kubeflow_tpu.api import make_tpujob

    job = make_tpujob("train", namespace="alice", replicas=4,
                      tpu_chips_per_worker=4, command=("python",))
    job.status = {}
    api.create(job)
    api.create(new_resource("Workflow", "ci", "alice",
                            spec={"steps": []}))
    rows = c.get("/api/workloads/alice").json()
    by_name = {r["name"]: r for r in rows}
    assert by_name["train"]["kind"] == "TpuJob"
    assert by_name["train"]["chips"] == 16
    assert by_name["train"]["phase"] == "Pending"
    assert by_name["ci"]["chips"] is None


def test_workloads_table_filters_by_per_kind_authorization(world):
    """A user who may list tpujobs but not workflows sees only the kinds
    they are authorized for; a user with no workload grants gets 403."""
    api, ctl, app = world
    from kubeflow_tpu.api import make_tpujob

    c = client(app, "alice@x.co")
    c.post("/api/workgroup/create", body={})
    ctl.controller.run_until_idle()
    api.create(make_tpujob("train", namespace="alice", replicas=1,
                           tpu_chips_per_worker=0, command=("python",)))
    api.create(new_resource("Workflow", "ci", "alice",
                            spec={"steps": []}))

    # Namespace admin sees everything.
    kinds = {r["kind"] for r in c.get("/api/workloads/alice").json()}
    assert kinds == {"TpuJob", "Workflow"}

    # Grant bob list on tpujobs only (a narrow Role, not a ClusterRole).
    api.create(new_resource(
        "Role", "jobs-only", "alice",
        spec={"rules": [{"verbs": ["list"], "resources": ["tpujobs"]}]},
    ))
    api.create(new_resource(
        "RoleBinding", "bob-jobs", "alice",
        spec={"roleRef": {"kind": "Role", "name": "jobs-only"},
              "subjects": [{"kind": "User", "name": "bob@x.co"}]},
    ))
    # Bob must also pass the mesh gate.
    api.create(new_resource(
        "AuthorizationPolicy", "bob-ap", "alice",
        spec={"action": "ALLOW",
              "rules": [{"from": [{"source": {"principals": [
                  "bob@x.co"]}}]}]},
    ))
    bob = client(app, "bob@x.co")
    rows = bob.get("/api/workloads/alice").json()
    assert {r["kind"] for r in rows} == {"TpuJob"}

    mallory = client(app, "mallory@x.co")
    assert mallory.get("/api/workloads/alice").status == 403
