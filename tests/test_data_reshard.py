"""The re-shard mapping in isolation (ISSUE 9 satellite).

The elastic soak's zero-repeated/skipped-batches guarantee reduces to
one data-layer invariant: batch CONTENT is a pure function of
(seed, salt, position) and never of the mesh. These tests prove it
independent of the e2e — `state_dict` saved on a dp=4 stream, loaded at
dp=2 and dp=8 (and via `rebind`), must continue the identical
per-position sequence, with `vary_per_step` on and off.
"""

import numpy as np
import pytest

from kubeflow_tpu.parallel import MeshSpec, build_mesh
from kubeflow_tpu.train import SyntheticImages, SyntheticTokens


def _mesh(dp, devices):
    return build_mesh(MeshSpec(dp=dp), devices[:dp])


def _take(stream, n):
    it = iter(stream)
    return [next(it) for _ in range(n)]


def _assert_batches_equal(a, b, msg=""):
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_array_equal(
            np.asarray(a[k]), np.asarray(b[k]), err_msg=f"{msg} field {k}"
        )


@pytest.mark.parametrize("new_dp", [2, 8])
def test_images_state_saved_at_dp4_loads_at_other_dp(devices, new_dp):
    """Positions 0..9 consumed at dp=4; a fresh stream on a new mesh
    loading that state continues at position 10 with BIT-identical
    content — the (step -> position) identity mapping holds across the
    resize."""
    kwargs = dict(
        batch_size=8, image_size=8, num_classes=10, seed=7,
        vary_per_step=True,
    )
    ref = SyntheticImages(_mesh(4, devices), **kwargs)
    consumed = _take(ref, 14)  # the full reference sequence 0..13

    src = SyntheticImages(_mesh(4, devices), **kwargs)
    _take(src, 10)
    state = src.state_dict()
    assert state == {"position": 10, "salt": 0}

    dst = SyntheticImages(_mesh(new_dp, devices), **kwargs)
    dst.load_state_dict(state)
    cont = _take(dst, 4)
    for i, batch in enumerate(cont):
        _assert_batches_equal(
            batch, consumed[10 + i], f"dp=4->{new_dp} position {10 + i}"
        )
    assert dst.state_dict()["position"] == 14


@pytest.mark.parametrize("new_dp", [2, 8])
def test_tokens_state_saved_at_dp4_loads_at_other_dp(devices, new_dp):
    kwargs = dict(batch_size=8, seq_len=16, vocab_size=64, seed=5,
                  vary_per_step=True)
    ref = SyntheticTokens(_mesh(4, devices), **kwargs)
    consumed = _take(ref, 8)

    src = SyntheticTokens(_mesh(4, devices), **kwargs)
    _take(src, 6)
    dst = SyntheticTokens(_mesh(new_dp, devices), **kwargs)
    dst.load_state_dict(src.state_dict())
    for i, batch in enumerate(_take(dst, 2)):
        _assert_batches_equal(
            batch, consumed[6 + i], f"dp=4->{new_dp} position {6 + i}"
        )


def test_rebind_transplants_position_and_salt(devices):
    stream = SyntheticImages(
        _mesh(4, devices), batch_size=8, image_size=8, num_classes=10,
        seed=7, vary_per_step=True,
    )
    _take(stream, 5)
    stream.perturb(3)
    clone = stream.rebind(_mesh(2, devices))
    assert clone.state_dict() == {"position": 5, "salt": 3}
    # The rebound stream and the original (same salt) agree on every
    # future position.
    a = _take(stream, 3)
    b = _take(clone, 3)
    for x, y in zip(a, b):
        _assert_batches_equal(x, y, "rebind continuation")


def test_rebind_lays_batches_out_on_the_new_mesh(devices):
    stream = SyntheticImages(
        _mesh(4, devices), batch_size=8, image_size=8, num_classes=10,
        seed=7, vary_per_step=True,
    )
    clone = stream.rebind(_mesh(2, devices))
    batch = _take(clone, 1)[0]
    assert set(batch["image"].sharding.device_set) <= set(devices[:2])


def test_fixed_stream_reshards_with_bookkeeping_intact(devices):
    """vary_per_step=False: every position yields the identical cached
    batch, so the mapping contract is pure bookkeeping — position
    carries over and the batch is the same one, laid out on the new
    mesh. perturb stays shadowed to None through the rebind (fit()'s
    rollback precondition must keep refusing)."""
    kwargs = dict(
        batch_size=8, image_size=8, num_classes=10, seed=7,
        vary_per_step=False,
    )
    src = SyntheticImages(_mesh(4, devices), **kwargs)
    first = _take(src, 3)
    assert src.perturb is None

    dst = SyntheticImages(_mesh(2, devices), **kwargs)
    dst.load_state_dict(src.state_dict())
    assert dst.state_dict()["position"] == 3
    _assert_batches_equal(_take(dst, 1)[0], first[0], "fixed stream")

    clone = src.rebind(_mesh(8, devices))
    assert clone.perturb is None
    assert clone.state_dict()["position"] == 3
    _assert_batches_equal(_take(clone, 1)[0], first[0], "fixed rebind")


def test_wrapped_streams_rebind_through_the_wrapper(devices):
    """ResumableWrapper.rebind rebinds the inner stream and keeps the
    wrapper's fault state: a spike staged past the resize still fires,
    one staged before it never refires."""
    from kubeflow_tpu.testing.chaos import SpikedData

    kwargs = dict(
        batch_size=8, image_size=8, num_classes=10, seed=7,
        vary_per_step=True,
    )
    plain = SyntheticImages(_mesh(4, devices), **kwargs)
    plain_batches = _take(plain, 8)

    wrapped = SpikedData(
        SyntheticImages(_mesh(4, devices), **kwargs), positions=(2, 6),
        scale=1e3,
    )
    before = _take(wrapped, 4)  # spike at position 2 fired
    clone = wrapped.rebind(_mesh(2, devices))
    after = _take(clone, 4)  # positions 4..7; spike at 6 must fire
    np.testing.assert_allclose(
        np.asarray(after[2]["image"]),
        np.asarray(plain_batches[6]["image"]) * 1e3,
        err_msg="staged spike lost across rebind",
    )
    _assert_batches_equal(after[0], plain_batches[4], "unspiked position")
    # And the pre-resize spike stayed where it was.
    np.testing.assert_allclose(
        np.asarray(before[2]["image"]),
        np.asarray(plain_batches[2]["image"]) * 1e3,
    )
