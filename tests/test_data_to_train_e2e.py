"""The full data path end-to-end: record files on disk → the native
compiled prefetching loader → device-sharded batches → Trainer.fit with
checkpointing — the platform's IO story feeding real training, plus
cross-topology checkpoint restore (save on one mesh layout, resume on
another — the elastic-recovery move the reference never had,
SURVEY.md §5 failure-detection row)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.resnet import tiny_resnet
from kubeflow_tpu.parallel import MeshSpec, build_mesh
from kubeflow_tpu.train import TrainConfig, Trainer
from kubeflow_tpu.train.checkpoint import Checkpointer
from kubeflow_tpu.train.loop import fit
from kubeflow_tpu.train.records import RecordDataset, RecordSpec, write_records


def _write_dataset(tmp_path, n=64, image=12):
    spec = RecordSpec.of(
        image=("float32", (image, image, 3)), label=("int32", ())
    )
    rng = np.random.RandomState(0)
    examples = []
    for i in range(n):
        # Learnable signal: label = 1 when the image mean is positive.
        img = rng.randn(image, image, 3).astype(np.float32)
        lbl = np.int32(1 if img.mean() > 0 else 0)
        examples.append({"image": img, "label": lbl})
    path = tmp_path / "train.rec"
    write_records(str(path), spec, examples)
    return spec, [str(path)]


def _trainer(mesh, *, image=12, fsdp_params=False, total_steps=30):
    config = TrainConfig(
        batch_size=16,
        learning_rate=0.05,
        warmup_steps=2,
        total_steps=total_steps,
        fsdp_params=fsdp_params,
    )
    return Trainer(
        tiny_resnet(num_classes=2),
        config,
        mesh,
        example_input_shape=(2, image, image, 3),
    )


def test_records_feed_training_and_loss_drops(tmp_path):
    spec, paths = _write_dataset(tmp_path)
    mesh = build_mesh(MeshSpec(dp=2), jax.devices()[:2])
    trainer = _trainer(mesh)
    dataset = RecordDataset(
        paths, spec, batch_size=16, seed=3, shuffle_buffer=32, drop_remainder=True, epochs=0
    )
    losses = []
    fit(
        trainer,
        dataset.device_iter(mesh),
        total_steps=30,
        on_metrics=lambda step, m: losses.append(float(m["loss"])),
        log_every=1,
    )
    assert len(losses) == 30 and all(np.isfinite(losses))
    # The label is a deterministic function of the image: 30 steps of SGD
    # must make clear progress (typ. 0.75 -> 0.60 here).
    assert min(losses[-5:]) < losses[0] * 0.87, losses[:3] + losses[-3:]


def test_cross_topology_checkpoint_restore(tmp_path):
    """Save on a dp=4/fsdp-sharded mesh, resume on dp=2: the abstract
    template carries the NEW mesh's shardings, so orbax re-shards on
    restore and training continues with identical math."""
    spec, paths = _write_dataset(tmp_path)

    mesh_a = build_mesh(MeshSpec(dp=2, fsdp=2), jax.devices()[:4])
    trainer_a = _trainer(mesh_a, fsdp_params=True, total_steps=6)
    data_a = RecordDataset(
        paths, spec, batch_size=16, seed=3, shuffle_buffer=32, drop_remainder=True, epochs=0
    )
    ckpt_a = Checkpointer(tmp_path / "ckpt", save_interval_steps=2)
    result_a = fit(
        trainer_a, data_a.device_iter(mesh_a), total_steps=6,
        checkpointer=ckpt_a,
    )
    ckpt_a.wait()
    ckpt_a.close()
    assert result_a.steps_done == 6

    # New topology: half the chips, no fsdp (pure DP, params replicated).
    mesh_b = build_mesh(MeshSpec(dp=2), jax.devices()[:2])
    trainer_b = _trainer(mesh_b, fsdp_params=False, total_steps=10)
    ckpt_b = Checkpointer(tmp_path / "ckpt", save_interval_steps=100)
    restored, at, _ = ckpt_b.restore_latest(trainer_b.abstract_state())
    assert at == 6
    # Restored arrays live on mesh_b with the pure-DP (replicated) layout.
    stem = restored.params["conv_stem"]["kernel"]
    assert stem.sharding.mesh.devices.size == 2

    data_b = RecordDataset(
        paths, spec, batch_size=16, seed=4, shuffle_buffer=32, drop_remainder=True, epochs=0
    )
    result_b = fit(
        trainer_b, data_b.device_iter(mesh_b), total_steps=10,
        checkpointer=ckpt_b,
    )
    ckpt_b.close()
    assert result_b.resumed_from == 6
    assert result_b.steps_done == 4  # 6 -> 10
    assert all(np.isfinite(m["loss"]) for m in result_b.history)
