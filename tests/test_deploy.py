"""Deploy tool: two-phase apply, idempotency, retries, readiness parity.

Mirrors the reference's deploy test suite (`testing/kfctl/`):
- `kf_is_ready_test.py:101-115` → test_platform_is_ready asserts the
  core deployment set;
- `kfctl_second_apply.py` → test_second_apply_idempotent;
- the retried K8S apply (`kfctlServer.go:290-294`) → flaky-cloud tests;
- `kfctl_delete_test.py` → teardown test.
"""

import pytest

from kubeflow_tpu.deploy import (
    FakeCloud,
    NodePool,
    PlatformSpec,
    apply_platform,
    delete_platform,
)
from kubeflow_tpu.deploy.bundles import BUNDLES, CORE_DEPLOYMENTS
from kubeflow_tpu.deploy.kfdef import default_spec, topology_chips
from kubeflow_tpu.deploy.provisioner import TOPOLOGY_LABEL, TPU_RESOURCE
from kubeflow_tpu.deploy.server import DeployServer
from kubeflow_tpu.testing import FakeApiServer, NotFound
from kubeflow_tpu.web import TestClient


@pytest.fixture
def api():
    return FakeApiServer()


def full_spec(name="kf-test"):
    spec = default_spec(name)
    spec.email = "admin@x.co"
    return spec


def test_platform_is_ready(api):
    """kf_is_ready_test parity: every core deployment must exist."""
    cloud = FakeCloud(api)
    result = apply_platform(full_spec(), api, cloud)
    assert result.succeeded, result.error

    deployed = {d.metadata.name for d in api.list("Deployment", "kubeflow")}
    for name in CORE_DEPLOYMENTS:
        assert name in deployed, f"missing core deployment {name}"
    # CRDs registered for every operator.
    crds = {c.metadata.name for c in api.list("CustomResourceDefinition", "")}
    for plural in ("tpujobs", "notebooks", "profiles", "tensorboards", "poddefaults"):
        assert f"{plural}.kubeflow-tpu.org" in crds

    dep = api.get("PlatformDeployment", "kf-test", "")
    assert dep.status["phase"] == "Ready"
    assert dep.status["conditions"][0]["type"] == "KfAvailable"


def test_tpu_node_pool_provisioning(api):
    """PLATFORM phase creates one Node per slice host with TPU capacity
    + topology labels (the scheduler's gang-matching inputs)."""
    cloud = FakeCloud(api)
    spec = PlatformSpec(
        name="kf",
        node_pools=[NodePool(name="pool-a", accelerator="v5e", topology="4x4")],
        applications=["namespace"],
    )
    assert apply_platform(spec, api, cloud).succeeded

    nodes = api.list("Node", "")
    assert len(nodes) == 4  # 16 chips / 4 per host
    total = sum(n.spec["capacity"][TPU_RESOURCE] for n in nodes)
    assert total == topology_chips("4x4") == 16
    assert all(n.metadata.labels[TOPOLOGY_LABEL] == "4x4" for n in nodes)


def test_second_apply_idempotent(api):
    cloud = FakeCloud(api)
    spec = full_spec()
    r1 = apply_platform(spec, api, cloud)
    rv_before = {
        (d.metadata.name): d.metadata.resource_version
        for d in api.list("Deployment", "kubeflow")
    }
    r2 = apply_platform(spec, api, cloud)
    assert r1.succeeded and r2.succeeded
    assert r1.applied_count == r2.applied_count
    # apply() is create-or-update with no-op detection: nothing rewritten.
    rv_after = {
        (d.metadata.name): d.metadata.resource_version
        for d in api.list("Deployment", "kubeflow")
    }
    assert rv_before == rv_after
    # Node pool not duplicated.
    assert len(api.list("Node", "")) == 4


def test_flaky_cloud_is_retried(api):
    cloud = FakeCloud(api, fail_next=2)  # first two calls blow up
    result = apply_platform(full_spec(), api, cloud)
    assert result.succeeded
    assert cloud.calls >= 3


def test_cloud_outage_fails_with_degraded_condition(api):
    cloud = FakeCloud(api, fail_next=10)  # more failures than retries
    result = apply_platform(full_spec(), api, cloud)
    assert not result.succeeded
    assert not result.platform_applied
    dep = api.get("PlatformDeployment", "kf-test", "")
    assert dep.status["phase"] == "Failed"
    assert dep.status["conditions"][0]["type"] == "KfDegraded"


def test_unknown_application_rejected(api):
    cloud = FakeCloud(api)
    spec = PlatformSpec(name="kf", applications=["nonsense"])
    result = apply_platform(spec, api, cloud)
    assert not result.succeeded
    assert "nonsense" in result.error


def test_delete_platform(api):
    cloud = FakeCloud(api)
    spec = full_spec()
    apply_platform(spec, api, cloud)
    delete_platform(spec, api, cloud)
    assert api.list("Deployment", "kubeflow") == []
    assert api.list("Node", "") == []
    with pytest.raises(NotFound):
        api.get("PlatformDeployment", "kf-test", "")


def test_deploy_server_flow(api):
    """Router → worker → status → delete (§3.1 call stack)."""
    cloud = FakeCloud(api)
    server = DeployServer(api, cloud)
    c = TestClient(server)

    r = c.post("/kfctl/apps/v1/create", body=full_spec("web-kf").to_dict())
    assert r.status == 200
    server.wait_idle()

    status = c.get("/kfctl/apps/v1/status/web-kf").json()
    assert status["status"]["phase"] == "Ready"
    assert {d.metadata.name for d in api.list("Deployment", "kubeflow")} >= set(
        CORE_DEPLOYMENTS
    )

    assert c.delete("/kfctl/apps/v1/delete/web-kf").status == 200
    assert c.get("/kfctl/apps/v1/status/web-kf").status == 404
    assert api.list("Deployment", "kubeflow") == []


def test_deploy_server_gc(api):
    cloud = FakeCloud(api)
    server = DeployServer(api, cloud)
    c = TestClient(server)
    c.post("/kfctl/apps/v1/create", body=full_spec("old-kf").to_dict())
    server.wait_idle()
    assert server.gc_older_than(0.0) == ["old-kf"]
    assert api.list("Deployment", "kubeflow") == []


def test_pool_respec_updates_nodes(api):
    """Re-apply after a topology change must refresh node labels."""
    cloud = FakeCloud(api)
    spec = PlatformSpec(
        name="kf",
        node_pools=[NodePool(name="p", topology="2x2")],
        applications=["namespace"],
    )
    apply_platform(spec, api, cloud)
    spec.node_pools = [NodePool(name="p", topology="2x2", preemptible=True)]
    apply_platform(spec, api, cloud)
    node = api.list("Node", "")[0]
    assert node.metadata.labels["cloud.google.com/gke-preemptible"] == "true"


def test_prefix_named_platforms_do_not_cross_delete(api):
    cloud = FakeCloud(api)
    a = PlatformSpec(
        name="kf", node_pools=[NodePool(name="pool-a")], applications=[]
    )
    b = PlatformSpec(
        name="kf-2", node_pools=[NodePool(name="pool-a")], applications=[]
    )
    apply_platform(a, api, cloud)
    apply_platform(b, api, cloud)
    delete_platform(a, api, cloud)
    remaining = {n.metadata.name for n in api.list("Node", "")}
    assert remaining == {"kf-2-pool-a-0"}


def test_deploy_server_rejects_missing_name(api):
    server = DeployServer(api, FakeCloud(api))
    c = TestClient(server)
    assert c.post("/kfctl/apps/v1/create", body={"spec": {}}).status == 400


def test_spec_yaml_roundtrip():
    spec = full_spec()
    again = PlatformSpec.from_yaml(spec.to_yaml())
    assert again == spec
    assert set(spec.applications) == set(BUNDLES)
