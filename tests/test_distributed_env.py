"""Process bootstrap env contract (the TF_CONFIG analog) parsing."""
import pytest

from kubeflow_tpu.parallel.distributed import (
    ENV_COORDINATOR,
    ENV_NUM_PROCESSES,
    ENV_PROCESS_ID,
    ProcessEnv,
    initialize_from_env,
)


def test_default_single_process():
    pe = ProcessEnv.from_env({})
    assert pe.num_processes == 1 and pe.is_coordinator


def test_parse_gang():
    pe = ProcessEnv.from_env({
        ENV_COORDINATOR: "job-0:8476",
        ENV_NUM_PROCESSES: "4",
        ENV_PROCESS_ID: "2",
    })
    assert pe.num_processes == 4 and pe.process_id == 2
    assert not pe.is_coordinator
    round_trip = ProcessEnv.from_env(pe.to_env())
    assert round_trip == pe


def test_missing_coordinator_rejected():
    with pytest.raises(ValueError):
        ProcessEnv.from_env({ENV_NUM_PROCESSES: "2", ENV_PROCESS_ID: "0"})


def test_bad_rank_rejected():
    with pytest.raises(ValueError):
        ProcessEnv.from_env({
            ENV_COORDINATOR: "a:1", ENV_NUM_PROCESSES: "2", ENV_PROCESS_ID: "5",
        })


def test_slices_must_divide():
    with pytest.raises(ValueError):
        ProcessEnv(coordinator="a:1", num_processes=4, num_slices=3).validate()


def test_initialize_noop_single_process():
    pe = initialize_from_env({})
    assert pe.num_processes == 1
