"""Endpoint-list client failover semantics, at unit scale.

`HttpApiClient` accepts an endpoint LIST (the kube client's multi-master
server list) and fails over between active-passive facades
(`testing/failover.py`). These tests pin the client-side contract the
failover e2e relies on, one rule per test:

- a plain-string single endpoint behaves exactly like the historical
  `base_url` (back-compat: no rotation, same error surface);
- a refused dial rotates to the next endpoint — for WRITES too, because
  nothing was sent (the one unambiguous transport failure);
- rotation is sticky: one takeover costs one rotation, not a probe per
  request;
- an OPEN circuit sheds requests to the next endpoint instead of
  failing fast into the caller (breakers are per-endpoint, so the dead
  active's history never gates its standby);
- a watch that dies mid-stream resumes on the next endpoint through the
  normal 410 → relist path, duplicate-free for new events.

The process-level version of the same story (real SIGKILL, WAL diff) is
`tests/e2e/test_apiserver_failover_e2e.py`.
"""

import socket
import threading
import time

import pytest

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.testing.apiserver_http import (
    ApiServerApp,
    HttpApiClient,
    endpoints_from_env,
)
from kubeflow_tpu.testing.fake_apiserver import (
    ApiError,
    FakeApiServer,
    Unavailable,
)
from kubeflow_tpu.web.wsgi import Response, serve


from tests.e2e.ha_driver import free_port as _free_port  # noqa: E402


def _wait_for(pred, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _mk(name, ns="default"):
    return new_resource("FailObj", name, ns, spec={"x": 1})


@pytest.fixture()
def live():
    """One real facade: (api, url)."""
    api = FakeApiServer()
    server, _ = serve(ApiServerApp(api), host="127.0.0.1", port=0)
    yield api, f"http://127.0.0.1:{server.server_port}"
    server.shutdown()
    api.close()


def _client(endpoints, **kw) -> HttpApiClient:
    kw.setdefault("timeout", 5.0)
    kw.setdefault("watch_poll_timeout", 0.5)
    kw.setdefault("watch_retry", 0.05)
    kw.setdefault("retry_base", 0.02)
    return HttpApiClient(endpoints, **kw)


# -- env contract ----------------------------------------------------------


def test_endpoints_from_env_parses_single_and_list():
    assert endpoints_from_env("http://a:1") == ["http://a:1"]
    assert endpoints_from_env(" http://a:1 , http://b:2 ") == [
        "http://a:1",
        "http://b:2",
    ]
    with pytest.raises(ValueError):
        endpoints_from_env(" , ")


# -- back-compat: a single endpoint is exactly the old client --------------


def test_single_endpoint_string_back_compat(live):
    api, url = live
    api.create(_mk("w0"))
    client = _client(url)  # plain string, the historical signature
    try:
        assert client.base_url == url
        assert client.endpoints == (url,)
        assert [o.metadata.name for o in client.list("FailObj")] == ["w0"]
        assert client.failovers == 0
    finally:
        client.close()


def test_single_endpoint_connect_refused_propagates():
    """With nowhere to rotate, a dial failure surfaces as the historical
    OSError — no silent retry loop hiding a down control plane."""
    client = _client(f"http://127.0.0.1:{_free_port()}", timeout=1.0)
    try:
        with pytest.raises(OSError):
            client.list("FailObj")
        assert client.failovers == 0
    finally:
        client.close()


class _Sick500App:
    """A facade that answers — with a 500 — so its breaker accumulates
    failures the endpoint-answered way (not via refused dials)."""

    def __init__(self):
        self.name = "sick"

    def handle(self, req) -> Response:
        return Response(b'{"log": "injected 500"}', status=500)


def test_single_endpoint_breaker_open_fails_fast():
    server, _ = serve(_Sick500App(), host="127.0.0.1", port=0)
    client = _client(
        f"http://127.0.0.1:{server.server_port}",
        breaker_threshold=1,
        breaker_cooldown=30.0,
    )
    try:
        with pytest.raises(ApiError):
            client.list("FailObj")
        served = server.requests_served
        # Circuit open, no standby: fail fast, without another dial.
        with pytest.raises(Unavailable):
            client.list("FailObj")
        assert server.requests_served == served
    finally:
        client.close()
        server.shutdown()


# -- rotation --------------------------------------------------------------


def test_rotates_on_connect_refused_reads_and_writes(live):
    """A refused dial is the one failure where NOTHING was sent, so both
    a read and a write may transparently try the next endpoint."""
    api, url = live
    dead = f"http://127.0.0.1:{_free_port()}"
    client = _client([dead, url])
    try:
        created = client.create(_mk("via-rotation"))
        assert created.metadata.name == "via-rotation"
        assert api.get("FailObj", "via-rotation") is not None
        assert client.failovers == 1
        assert client.base_url == url  # the answerer became active
    finally:
        client.close()


def test_rotation_is_sticky(live):
    """One takeover costs ONE rotation: after failing over, every
    subsequent request starts at the new active — the dead endpoint is
    not re-probed per call (no per-request dial tax on a dead peer)."""
    api, url = live
    dead_ep = f"http://127.0.0.1:{_free_port()}"
    client = _client([dead_ep, url])
    try:
        client.list("FailObj")
        assert client.failovers == 1
        dials_to_dead = client._endpoints[0].handshakes
        for _ in range(10):
            client.list("FailObj")
        assert client.failovers == 1
        assert client._endpoints[0].handshakes == dials_to_dead
    finally:
        client.close()


def test_breaker_open_sheds_to_next_endpoint():
    """An answering-but-sick active (5xx) is NOT walked away from per
    request — a 5xx is the server's answer, and masking it would hide
    real errors. Once its circuit OPENS, requests shed to the standby
    instead of failing fast into the caller; while open, the sick
    endpoint is not dialed at all (breakers are per-endpoint)."""
    sick, _ = serve(_Sick500App(), host="127.0.0.1", port=0)
    api = FakeApiServer()
    api.create(_mk("held"))
    good, _ = serve(ApiServerApp(api), host="127.0.0.1", port=0)
    client = _client(
        [
            f"http://127.0.0.1:{sick.server_port}",
            f"http://127.0.0.1:{good.server_port}",
        ],
        breaker_threshold=2,
        breaker_cooldown=30.0,
    )
    try:
        for _ in range(2):  # accumulate failures to the threshold
            with pytest.raises(ApiError):
                client.list("FailObj")
        assert client.failovers == 0
        served_by_sick = sick.requests_served
        # Circuit open: the walk skips the sick active entirely.
        assert [o.metadata.name for o in client.list("FailObj")] == ["held"]
        assert client.failovers == 1
        assert sick.requests_served == served_by_sick
        client.list("FailObj")
        assert sick.requests_served == served_by_sick
    finally:
        client.close()
        sick.shutdown()
        good.shutdown()
        api.close()


# -- mid-watch death → 410 relist ------------------------------------------


class _Forwarder:
    """A TCP forwarder whose `kill()` severs EVERY connection at once.

    A graceful in-proc `server.shutdown()` only stops the accept loop —
    established keep-alive connections (the watch stream!) live on in
    their handler threads, which is precisely what a SIGKILL does NOT
    do. Fronting the facade with this forwarder gives the unit test the
    e2e's kill semantics: held-open streams die mid-flight, pooled
    connections RST, and new dials are refused."""

    def __init__(self, upstream_port: int):
        self._upstream = upstream_port
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(16)
        self.port = self._listener.getsockname()[1]
        self._socks: list[socket.socket] = []
        self._lock = threading.Lock()
        self._dead = False
        self._accept_thread = threading.Thread(
            target=self._accept, daemon=True
        )
        self._accept_thread.start()

    def _accept(self) -> None:
        while True:
            try:
                client, _ = self._listener.accept()
            except OSError:
                return
            try:
                up = socket.create_connection(
                    ("127.0.0.1", self._upstream), timeout=5
                )
            except OSError:
                client.close()
                continue
            with self._lock:
                if self._dead:
                    client.close()
                    up.close()
                    return
                self._socks += [client, up]
            for a, b in ((client, up), (up, client)):
                threading.Thread(
                    target=self._pump, args=(a, b), daemon=True
                ).start()

    @staticmethod
    def _pump(src: socket.socket, dst: socket.socket) -> None:
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        for s in (src, dst):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def kill(self) -> None:
        with self._lock:
            self._dead = True
            socks, self._socks = self._socks, []
        # shutdown() FIRST: a bare close() while the accept thread is
        # blocked in accept() leaves the fd open (CPython holds it for
        # the in-progress call), so the kernel keeps completing
        # handshakes nobody will ever serve. Waking the thread and
        # joining it makes the port genuinely refuse — the SIGKILL
        # semantics this forwarder exists to provide.
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._accept_thread.join(timeout=5)
        self._listener.close()
        for s in socks:
            # Same deferred-close trap as the listener: a pump thread
            # blocked in recv holds the fd open, so close() alone would
            # leave the proxied stream ALIVE. shutdown() terminates the
            # flow now — the client sees its watch die immediately.
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            s.close()


def test_mid_watch_death_resumes_via_410_relist_duplicate_free(tmp_path):
    """The watcher's failover path, end to end at unit scale: the active
    dies mid-stream, the store advances while the watcher is dark (the
    WAL writes it can no longer see), and the standby — restored over
    the same durable dir — re-seeds its watch floor at the durable rv.
    The watcher's stale bookmark gets an honest 410, relists, and
    resumes: pre-death and dark-window objects arrive as synthetic
    MODIFIED (the relist, by construction duplicate-free for
    level-triggered consumers), and a genuinely new object arrives as
    ADDED exactly once."""
    store_dir = str(tmp_path / "store")
    api_a = FakeApiServer(persist_dir=store_dir)
    server_a, _ = serve(ApiServerApp(api_a), host="127.0.0.1", port=0)
    fwd = _Forwarder(server_a.server_port)
    port_b = _free_port()
    client = _client(
        [f"http://127.0.0.1:{fwd.port}", f"http://127.0.0.1:{port_b}"]
    )
    events: list[tuple[str, str]] = []
    ev_lock = threading.Lock()

    def handler(event, obj):
        with ev_lock:
            events.append((event, obj.metadata.name))

    def seen(name):
        with ev_lock:
            return {n for _, n in events} >= {name}

    server_b = None
    try:
        client.watch(handler, "FailObj")
        for i in range(3):
            client.create(_mk(f"pre-{i}"))
        assert _wait_for(lambda: seen("pre-2")), "watch never caught up"

        fwd.kill()  # the active is gone: stream RST, dials refused
        # The dark window: acked writes the dead watcher never saw.
        for i in range(2):
            api_a.create(_mk(f"tail-{i}"))

        # The standby takes over the durable dir: replay sets the watch
        # floor to the durable rv, past the watcher's bookmark.
        api_b = FakeApiServer(persist_dir=store_dir)
        assert len(api_b.list("FailObj")) == 5  # WAL replay complete
        server_b, _ = serve(
            ApiServerApp(api_b), host="127.0.0.1", port=port_b
        )

        assert _wait_for(lambda: seen("tail-1")), (
            f"watch never resumed on the standby: {events}"
        )
        fresh = client.create(_mk("fresh"))  # rides the rotated client
        assert fresh.metadata.resource_version > 0
        assert _wait_for(lambda: seen("fresh")), "post-failover event lost"
        client.create(_mk("fresh-2"))  # sentinel: stream moved past fresh
        assert _wait_for(lambda: seen("fresh-2"))

        with ev_lock:
            snapshot = list(events)
        # Dark-window objects came through the RELIST (synthetic
        # MODIFIED) — their ADDED happened while no watcher could see
        # it, and replaying it would be an invented event.
        tail_events = [e for e, n in snapshot if n.startswith("tail-")]
        assert tail_events and set(tail_events) == {"MODIFIED"}, snapshot
        # A post-failover create is delivered exactly once: the relist
        # already happened, so nothing re-delivers it.
        assert [n for _, n in snapshot].count("fresh") == 1, snapshot
        assert client.failovers >= 1
    finally:
        client.close()
        if server_b is not None:
            server_b.shutdown()
        server_a.shutdown()
        fwd.kill()
