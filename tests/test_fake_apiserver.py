"""FakeApiServer storage semantics: versions, conflicts, owners, GC."""
import pytest

from kubeflow_tpu.api import new_resource, owner_ref
from kubeflow_tpu.testing import AlreadyExists, Conflict, FakeApiServer, NotFound


@pytest.fixture(params=["python", "native"])
def api(request):
    """Every storage-semantics test runs against BOTH backends: the
    in-process Python store and the compiled C++ store
    (native/src/store.cc) behind the same API."""
    if request.param == "native":
        from kubeflow_tpu.native.apiserver import NativeApiServer

        return NativeApiServer()
    return FakeApiServer()


def test_create_get_roundtrip(api):
    obj = new_resource("Notebook", "nb1", "user1", spec={"image": "x"})
    created = api.create(obj)
    assert created.metadata.uid and created.metadata.resource_version > 0
    got = api.get("Notebook", "nb1", "user1")
    assert got.spec == {"image": "x"}


def test_create_duplicate_rejected(api):
    api.create(new_resource("Pod", "p", "ns"))
    with pytest.raises(AlreadyExists):
        api.create(new_resource("Pod", "p", "ns"))


def test_stale_update_conflicts(api):
    api.create(new_resource("Pod", "p"))
    a = api.get("Pod", "p").thaw()
    b = api.get("Pod", "p").thaw()
    a.spec["x"] = 1
    api.update(a)
    b.spec["x"] = 2
    with pytest.raises(Conflict):
        api.update(b)


def test_update_status_does_not_touch_spec(api):
    api.create(new_resource("Pod", "p", spec={"a": 1}))
    obj = api.get("Pod", "p").thaw()
    obj.spec["a"] = 99
    obj.status["phase"] = "Running"
    api.update_status(obj)
    fresh = api.get("Pod", "p")
    assert fresh.spec == {"a": 1}
    assert fresh.status == {"phase": "Running"}


def test_generation_bumps_only_on_spec_change(api):
    api.create(new_resource("Pod", "p", spec={"a": 1}))
    obj = api.get("Pod", "p").thaw()
    obj.metadata.labels["l"] = "v"
    updated = api.update(obj)
    assert updated.metadata.generation == 1
    updated = updated.thaw()  # store returns are frozen shared snapshots
    updated.spec["a"] = 2
    assert api.update(updated).metadata.generation == 2


def test_label_selector(api):
    api.create(new_resource("Pod", "a", labels={"job": "j1"}))
    api.create(new_resource("Pod", "b", labels={"job": "j2"}))
    assert [p.metadata.name for p in api.list("Pod", label_selector={"job": "j1"})] == ["a"]


def test_watch_events(api):
    events = []
    api.watch(lambda e, o: events.append((e, o.metadata.name)), "Pod")
    api.create(new_resource("Pod", "p"))
    api.create(new_resource("Service", "s"))  # different kind: not seen
    obj = api.get("Pod", "p").thaw()
    obj.spec["x"] = 1
    api.update(obj)
    api.delete("Pod", "p")
    # Python-store delivery is async (dispatcher thread); the native
    # backend delivers synchronously — flush is the common barrier.
    getattr(api, "flush", lambda: None)()
    assert events == [("ADDED", "p"), ("MODIFIED", "p"), ("DELETED", "p")]


def test_slow_watch_handler_does_not_stall_writers():
    """The dispatcher runs handlers OFF the store lock: a handler stuck
    for seconds must not delay other writers (the failure mode VERDICT
    round 2 flagged: fan-out under the RLock)."""
    import threading
    import time as _time

    from kubeflow_tpu.testing.fake_apiserver import FakeApiServer

    api = FakeApiServer()
    release = threading.Event()
    seen = []

    def slow(event, obj):
        seen.append(obj.metadata.name)
        release.wait(5.0)

    api.watch(slow, "Pod")
    api.create(new_resource("Pod", "p0"))  # dispatcher now blocks in slow()
    t0 = _time.monotonic()
    for i in range(1, 20):
        api.create(new_resource("Pod", f"p{i}"))
    write_time = _time.monotonic() - t0
    assert write_time < 1.0, f"writers stalled {write_time:.2f}s"
    release.set()
    api.flush()
    assert len(seen) == 20  # nothing lost, order preserved
    assert seen == [f"p{i}" for i in range(20)]


def test_finalizers_defer_deletion(api):
    obj = new_resource("Profile", "u1")
    obj.metadata.finalizers = ["cleanup"]
    api.create(obj)
    api.delete("Profile", "u1")
    pending = api.get("Profile", "u1")  # still there
    assert pending.metadata.deletion_timestamp is not None
    pending = pending.thaw()
    pending.metadata.finalizers = []
    api.update(pending)
    with pytest.raises(NotFound):
        api.get("Profile", "u1")


def test_owner_cascade(api):
    parent = api.create(new_resource("TpuJob", "job"))
    child = new_resource("Pod", "job-worker-0")
    child.metadata.owner_references = [owner_ref(parent)]
    api.create(child)
    grand = new_resource("ConfigMap", "cm")
    grand.metadata.owner_references = [owner_ref(api.get("Pod", "job-worker-0"))]
    api.create(grand)
    api.delete("TpuJob", "job")
    with pytest.raises(NotFound):
        api.get("Pod", "job-worker-0")
    with pytest.raises(NotFound):
        api.get("ConfigMap", "cm")


def test_apply_create_or_update(api):
    api.apply(new_resource("Service", "s", spec={"p": 1}))
    api.apply(new_resource("Service", "s", spec={"p": 2}))
    assert api.get("Service", "s").spec == {"p": 2}


def test_finalizer_cascade_journal_stays_rv_ordered(api):
    """Clearing the last finalizer of an owner WITH dependents emits the
    owner's DELETED before the cascaded children's: the journal must
    stay rv-sorted, or the bisect resume in select_journal_events would
    skip events a watcher never saw."""
    parent = new_resource("Profile", "p1")
    parent.metadata.finalizers = ["cleanup"]
    parent = api.create(parent)
    child = new_resource("Pod", "p1-child")
    child.metadata.owner_references = [owner_ref(parent)]
    api.create(child)
    api.delete("Profile", "p1")  # parks: finalizer pending
    pending = api.get("Profile", "p1").thaw()
    bookmark = pending.metadata.resource_version
    pending.metadata.finalizers = []
    api.update(pending)  # finalizes; owner-ref cascade deletes the child
    events, _ = api.events_since(bookmark)
    rvs = [rv for rv, _, _ in events]
    assert rvs == sorted(rvs), f"journal out of rv order: {rvs}"
    deleted = {
        (o.kind, o.metadata.name) for _, e, o in events if e == "DELETED"
    }
    assert {("Profile", "p1"), ("Pod", "p1-child")} <= deleted, deleted
