"""Pallas flash attention vs the dense reference (interpreter mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.ops.attention import dense_attention
from kubeflow_tpu.ops.flash import flash_attention, flash_usable


def _qkv(key, b, s, h, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, s, h, d)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("s,block", [(128, 64), (256, 128), (96, 32)])
def test_forward_matches_dense(causal, s, block):
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, s, 2, 32)
    out = flash_attention(
        q, k, v, causal=causal, block_q=block, block_k=block, interpret=True
    )
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_uneven_blocks():
    """block_q != block_k, including blocks that leave some rows fully
    masked inside an executed causal block."""
    q, k, v = _qkv(jax.random.PRNGKey(1), 1, 128, 1, 16)
    out = flash_attention(
        q, k, v, causal=True, block_q=64, block_k=32, interpret=True
    )
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    out = flash_attention(
        q, k, v, causal=True, block_q=32, block_k=64, interpret=True
    )
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_grads_match_dense(causal):
    q, k, v = _qkv(jax.random.PRNGKey(2), 1, 128, 2, 16)

    def loss_flash(q, k, v):
        o = flash_attention(
            q, k, v, causal=causal, block_q=64, block_k=64, interpret=True
        )
        return jnp.sum(o * jnp.cos(o))

    def loss_dense(q, k, v):
        o = dense_attention(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            gf, gd, atol=5e-5, rtol=5e-5, err_msg=f"d{name} mismatch"
        )


def test_bf16_inputs():
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 128, 2, 32, jnp.bfloat16)
    out = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(
        out.astype(jnp.float32), ref.astype(jnp.float32), atol=3e-2, rtol=3e-2
    )


def test_indivisible_seq_pads_and_matches_dense():
    # A sequence with NO 8-aligned divisor (1025 = 5^2 * 41: every
    # divisor is odd) used to raise; it now pads internally to the next
    # lane multiple, masks the tail, and matches dense numerics.
    q, k, v = _qkv(jax.random.PRNGKey(4), 1, 1025, 1, 16)
    assert flash_usable(1025, 1025)
    out = flash_attention(q, k, v, interpret=True)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-2, rtol=2e-2)


def test_usable_predicate():
    # Internal padding makes every positive shape flash-runnable; the
    # predicate stays as the dispatch contract for _attend.
    assert flash_usable(256, 256)
    assert flash_usable(4096, 4096)
    assert flash_usable(64, 64)  # block clamps to seq (8-aligned)
    assert flash_usable(320, 256)  # clamps to one 320-row block
    assert flash_usable(1664, 1664)  # degrades to the 128-divisor
    assert flash_usable(1344, 1344)  # degrades to the sublane divisor 672
    # Shapes with no 8-aligned divisor now pad instead of routing to
    # dense — the old silent O(S²) fallback for ragged lengths.
    assert flash_usable(100, 100)
    assert flash_usable(321, 321)
    assert flash_usable(1025, 1025)
    # The ring path cannot pad (chunks must stay congruent across
    # hops); its stricter predicate keeps the old semantics.
    from kubeflow_tpu.ops.flash import flash_kernel_tileable

    assert flash_kernel_tileable(256)
    assert flash_kernel_tileable(1344)
    assert not flash_kernel_tileable(100)
    assert not flash_kernel_tileable(1025)


def test_block_fallback_matches_dense():
    """A sequence the default block doesn't divide (1664 = 13 * 128)
    degrades to a dividing block and still matches dense numerics."""
    q, k, v = _qkv(jax.random.PRNGKey(5), 1, 1664, 1, 16)
    out = flash_attention(q, k, v, causal=True, block_q=1024, block_k=1024,
                          interpret=True)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        out.astype(np.float32), ref.astype(np.float32), atol=2e-2, rtol=2e-2
    )


# -- ring flash (sequence-parallel composition) ----------------------------


def _ring_mesh(sp):
    import numpy as np
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[: sp * 2]).reshape(2, sp)
    return Mesh(devs, ("dp", "sp"))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("sp", [2, 4])
def test_ring_flash_forward_matches_dense(causal, sp):
    from kubeflow_tpu.ops.attention import dense_attention
    from kubeflow_tpu.ops.flash import ring_flash_attention

    mesh = _ring_mesh(sp)
    q, k, v = _qkv(jax.random.PRNGKey(0), b=2, s=8 * sp, h=2, d=128)
    out = ring_flash_attention(
        q, k, v, mesh, causal=causal, heads_axis=None, interpret=True
    )
    want = dense_attention(q, k, v, causal=causal)
    assert jnp.allclose(out, want, atol=2e-2), (
        float(jnp.abs(out - want).max())
    )


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_grads_match_dense(causal):
    from kubeflow_tpu.ops.attention import dense_attention
    from kubeflow_tpu.ops.flash import ring_flash_attention

    mesh = _ring_mesh(2)
    q, k, v = _qkv(jax.random.PRNGKey(1), b=2, s=16, h=2, d=128)

    def ring_loss(q, k, v):
        out = ring_flash_attention(
            q, k, v, mesh, causal=causal, heads_axis=None, interpret=True
        )
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(
            dense_attention(q, k, v, causal=causal).astype(jnp.float32)
            ** 2
        )

    got = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        assert jnp.allclose(g, w, atol=5e-2), (
            name, float(jnp.abs(g - w).max())
        )


def test_ring_flash_trivial_ring_is_flash():
    from kubeflow_tpu.ops.flash import ring_flash_attention

    import numpy as np
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2, 1), ("dp", "sp"))
    q, k, v = _qkv(jax.random.PRNGKey(2), b=2, s=16, h=2, d=128)
    out = ring_flash_attention(q, k, v, mesh, interpret=True)
    assert out.shape == q.shape


def test_ring_flash_rejects_indivisible_sequence():
    from kubeflow_tpu.ops.flash import ring_flash_attention

    mesh = _ring_mesh(4)
    q, k, v = _qkv(jax.random.PRNGKey(3), b=1, s=18, h=2, d=128)
    with pytest.raises(ValueError, match="divide"):
        ring_flash_attention(q, k, v, mesh, interpret=True)
