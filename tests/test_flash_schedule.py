"""The long-context attention schedule (ISSUE 3): compacted causal grid,
lane-packed lse, shared-delta backward, and internal padding — interpret-mode
parity against the dense reference plus static-schedule regression gates
(grid-step count, lse HBM bytes)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.ops.attention import dense_attention
from kubeflow_tpu.ops.flash import (
    _LANES,
    _flash_delta_impl,
    _flash_fwd_impl,
    _grid_steps,
    flash_attention,
    flash_schedule,
)


def _qkv(key, b, s, h, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, s, h, d)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


def _grads(attn, q, k, v):
    def loss(q, k, v):
        o = attn(q, k, v)
        return jnp.sum(o.astype(jnp.float32) * jnp.cos(o.astype(jnp.float32)))

    return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)


# -- compacted causal grid --------------------------------------------------


@pytest.mark.parametrize("s,block", [(512, 128), (384, 128), (256, 64)])
def test_compact_causal_forward_and_grads_match_dense(s, block):
    """Square causal blocks run the compact triangular grid (asserted via
    the schedule) and must match dense numerics fwd + bwd."""
    sched = flash_schedule(s, s, block_q=block, block_k=block, causal=True)
    assert sched["compact"], sched
    assert sched["grid_steps"] < sched["rect_grid_steps"]

    q, k, v = _qkv(jax.random.PRNGKey(0), 2, s, 2, 32)
    attn = lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=block, block_k=block, interpret=True
    )
    np.testing.assert_allclose(
        attn(q, k, v), dense_attention(q, k, v, causal=True),
        atol=2e-5, rtol=2e-5,
    )
    got = _grads(attn, q, k, v)
    want = _grads(
        lambda q, k, v: dense_attention(q, k, v, causal=True), q, k, v
    )
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            g, w, atol=5e-5, rtol=5e-5, err_msg=f"d{name} mismatch"
        )


def test_uneven_blocks_fall_back_to_rectangular():
    """bq != bk cannot compact (block rows aren't triangular); the
    rectangular fallback with clamped DMAs must still match dense."""
    sched = flash_schedule(256, 256, block_q=64, block_k=128, causal=True)
    assert not sched["compact"]
    q, k, v = _qkv(jax.random.PRNGKey(1), 1, 256, 2, 16)
    out = flash_attention(
        q, k, v, causal=True, block_q=64, block_k=128, interpret=True
    )
    np.testing.assert_allclose(
        out, dense_attention(q, k, v, causal=True), atol=2e-5, rtol=2e-5
    )


def test_noncausal_is_rectangular_and_matches():
    sched = flash_schedule(256, 256, block_q=128, block_k=128, causal=False)
    assert not sched["compact"]
    assert sched["grid_steps"] == sched["rect_grid_steps"]
    q, k, v = _qkv(jax.random.PRNGKey(2), 2, 256, 2, 16)
    attn = lambda q, k, v: flash_attention(
        q, k, v, causal=False, block_q=128, block_k=128, interpret=True
    )
    np.testing.assert_allclose(
        attn(q, k, v), dense_attention(q, k, v, causal=False),
        atol=2e-5, rtol=2e-5,
    )
    got = _grads(attn, q, k, v)
    want = _grads(
        lambda q, k, v: dense_attention(q, k, v, causal=False), q, k, v
    )
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            g, w, atol=5e-5, rtol=5e-5, err_msg=f"d{name} mismatch"
        )


def test_grid_step_regression_causal_half_the_steps():
    """The acceptance gate: at S=4096 the compacted causal grid must run
    <= 0.6x the rectangular grid's steps (the triangular count
    nq(nq+1)/2 approaches half the rectangle as nq grows; 256-wide
    blocks give nq=16 -> 136/256 = 0.53)."""
    sched = flash_schedule(4096, 4096, block_q=256, block_k=256, causal=True)
    assert sched["compact"]
    ratio = sched["grid_steps"] / sched["rect_grid_steps"]
    assert ratio <= 0.6, sched
    # And with the default (1024) blocks compaction still engages.
    default = flash_schedule(4096, 4096, causal=True)
    assert default["compact"]
    assert default["grid_steps"] < default["rect_grid_steps"]
    # The schedule helper is the SAME accounting the impl builds its
    # grid from — pin the equivalence so the test can't drift from the
    # kernel.
    steps, rect, compact = _grid_steps(True, 4096, 4096, 256, 256)
    assert (steps, rect, compact) == (
        sched["grid_steps"], sched["rect_grid_steps"], True,
    )


# -- lane-packed lse --------------------------------------------------------


def test_lse_packed_layout_cuts_hbm_bytes_128x():
    """The packed [BH, S/128, 128] lse layout must be exactly 128x
    smaller than the lane-replicated [BH, S, 128] buffer, and the fwd
    impl must actually emit it (asserted from the returned shape, which
    is the kernel's out_shape/BlockSpec shape)."""
    sched = flash_schedule(1024, 1024, causal=True)
    assert sched["lse_packed"]
    assert sched["lse_replicated_bytes"] == 128 * sched["lse_bytes"]

    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 1024, 2, 16)
    qf = q.transpose(0, 2, 1, 3).reshape(2, 1024, 16)
    _, lse = _flash_fwd_impl(
        qf, qf, qf, True, 1024, 1024, True, None, True
    )
    assert lse.shape == (2, 1024 // _LANES, _LANES)

    # Un-lane-aligned blocks cannot pack; the replicated fallback stays.
    sched_small = flash_schedule(96, 96, block_q=32, block_k=32)
    assert not sched_small["lse_packed"]


def test_packed_lse_values_match_dense_logsumexp():
    """The packed tiles must hold the true per-row softmax statistics:
    unpacked lse == dense log-sum-exp of the scaled causal scores."""
    b, s, h, d = 1, 256, 1, 32
    q, k, v = _qkv(jax.random.PRNGKey(4), b, s, h, d)
    _, lse = flash_attention(
        q, k, v, causal=True, block_q=128, block_k=128, interpret=True,
        return_lse=True,
    )
    assert lse.shape == (b, h, s)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    mask = jnp.tril(jnp.ones((s, s), bool))
    scores = jnp.where(mask, scores, -jnp.inf)
    want = jax.scipy.special.logsumexp(scores.astype(jnp.float32), axis=-1)
    np.testing.assert_allclose(
        np.asarray(lse), np.asarray(want), atol=2e-5, rtol=2e-5
    )


# -- shared-delta backward --------------------------------------------------


def test_shared_delta_precompute_matches_rowsum():
    """The delta precompute kernel must emit rowsum(dO * O) in the lse
    layout — the single value both backward kernels consume."""
    bh, s, d = 2, 256, 16
    o = jax.random.normal(jax.random.PRNGKey(5), (bh, s, d))
    do = jax.random.normal(jax.random.PRNGKey(6), (bh, s, d))
    want = jnp.sum(do * o, axis=-1)

    packed = _flash_delta_impl(o, do, 128, True, True)
    assert packed.shape == (bh, s // _LANES, _LANES)
    np.testing.assert_allclose(
        packed.reshape(bh, s), want, atol=1e-5, rtol=1e-5
    )

    replicated = _flash_delta_impl(o, do, 64, True, False)
    assert replicated.shape == (bh, s, _LANES)
    np.testing.assert_allclose(
        replicated[:, :, 0], want, atol=1e-5, rtol=1e-5
    )


# -- internal padding (ragged sequence lengths) -----------------------------


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("s", [100, 321, 1025])
def test_ragged_sequences_pad_and_match_dense(causal, s):
    """Lengths with no 8-aligned divisor (previously a hard error →
    silent dense fallback at the model layer) pad to the next lane
    multiple, mask the tail, and match dense numerics fwd + bwd. The
    non-causal case is the one the tail mask exists for: without it the
    zero-padded keys would soak up softmax mass."""
    sched = flash_schedule(s, s, causal=causal)
    assert sched["padded_seq_q"] % _LANES == 0
    assert sched["padded_seq_q"] >= s

    q, k, v = _qkv(jax.random.PRNGKey(7), 1, s, 2, 16)
    attn = lambda q, k, v: flash_attention(
        q, k, v, causal=causal, interpret=True
    )
    out = attn(q, k, v)
    assert out.shape == q.shape
    np.testing.assert_allclose(
        out, dense_attention(q, k, v, causal=causal), atol=2e-4, rtol=2e-4
    )
    got = _grads(attn, q, k, v)
    want = _grads(
        lambda q, k, v: dense_attention(q, k, v, causal=causal), q, k, v
    )
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            g, w, atol=5e-4, rtol=5e-4, err_msg=f"d{name} mismatch (s={s})"
        )


def test_odd_head_counts():
    """Heads are flattened into the grid's bh dimension — odd counts must
    work (they exercise bh rows that share nothing 2-power-aligned)."""
    for h in (3, 5):
        q, k, v = _qkv(jax.random.PRNGKey(8), 2, 128, h, 16)
        out = flash_attention(
            q, k, v, causal=True, block_q=64, block_k=64, interpret=True
        )
        ref = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_bf16_compact_packed_path():
    q, k, v = _qkv(jax.random.PRNGKey(9), 1, 256, 2, 32, jnp.bfloat16)
    out = flash_attention(
        q, k, v, causal=True, block_q=128, block_k=128, interpret=True
    )
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        out.astype(np.float32), ref.astype(np.float32), atol=3e-2, rtol=3e-2
    )
