"""The long-context attention schedule (ISSUE 3) and the fused one-pass
backward (ISSUE 7): compacted causal grid, lane-packed lse, shared-delta
backward, fused dq/dkv kernel, and internal padding — interpret-mode
parity against the dense reference (and against the two-kernel backward)
plus static-schedule regression gates (grid-step count, lse HBM bytes,
backward HBM-byte halving, fused VMEM gating)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.ops.attention import dense_attention
from kubeflow_tpu.ops.flash import (
    _LANES,
    _bwd_fused,
    _flash_bwd_kernels,
    _flash_delta_impl,
    _flash_fwd_impl,
    _grid_steps,
    flash_attention,
    flash_schedule,
)


def _qkv(key, b, s, h, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, s, h, d)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


def _grads(attn, q, k, v):
    def loss(q, k, v):
        o = attn(q, k, v)
        return jnp.sum(o.astype(jnp.float32) * jnp.cos(o.astype(jnp.float32)))

    return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)


# -- compacted causal grid --------------------------------------------------


@pytest.mark.parametrize("s,block", [(512, 128), (384, 128), (256, 64)])
def test_compact_causal_forward_and_grads_match_dense(s, block):
    """Square causal blocks run the compact triangular grid (asserted via
    the schedule) and must match dense numerics fwd + bwd."""
    sched = flash_schedule(s, s, block_q=block, block_k=block, causal=True)
    assert sched["compact"], sched
    assert sched["grid_steps"] < sched["rect_grid_steps"]

    q, k, v = _qkv(jax.random.PRNGKey(0), 2, s, 2, 32)
    attn = lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=block, block_k=block, interpret=True
    )
    np.testing.assert_allclose(
        attn(q, k, v), dense_attention(q, k, v, causal=True),
        atol=2e-5, rtol=2e-5,
    )
    got = _grads(attn, q, k, v)
    want = _grads(
        lambda q, k, v: dense_attention(q, k, v, causal=True), q, k, v
    )
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            g, w, atol=5e-5, rtol=5e-5, err_msg=f"d{name} mismatch"
        )


def test_uneven_blocks_fall_back_to_rectangular():
    """bq != bk cannot compact (block rows aren't triangular); the
    rectangular fallback with clamped DMAs must still match dense."""
    sched = flash_schedule(256, 256, block_q=64, block_k=128, causal=True)
    assert not sched["compact"]
    q, k, v = _qkv(jax.random.PRNGKey(1), 1, 256, 2, 16)
    out = flash_attention(
        q, k, v, causal=True, block_q=64, block_k=128, interpret=True
    )
    np.testing.assert_allclose(
        out, dense_attention(q, k, v, causal=True), atol=2e-5, rtol=2e-5
    )


def test_noncausal_is_rectangular_and_matches():
    sched = flash_schedule(256, 256, block_q=128, block_k=128, causal=False)
    assert not sched["compact"]
    assert sched["grid_steps"] == sched["rect_grid_steps"]
    q, k, v = _qkv(jax.random.PRNGKey(2), 2, 256, 2, 16)
    attn = lambda q, k, v: flash_attention(
        q, k, v, causal=False, block_q=128, block_k=128, interpret=True
    )
    np.testing.assert_allclose(
        attn(q, k, v), dense_attention(q, k, v, causal=False),
        atol=2e-5, rtol=2e-5,
    )
    got = _grads(attn, q, k, v)
    want = _grads(
        lambda q, k, v: dense_attention(q, k, v, causal=False), q, k, v
    )
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            g, w, atol=5e-5, rtol=5e-5, err_msg=f"d{name} mismatch"
        )


def test_grid_step_regression_causal_half_the_steps():
    """The acceptance gate: at S=4096 the compacted causal grid must run
    <= 0.6x the rectangular grid's steps (the triangular count
    nq(nq+1)/2 approaches half the rectangle as nq grows; 256-wide
    blocks give nq=16 -> 136/256 = 0.53)."""
    sched = flash_schedule(4096, 4096, block_q=256, block_k=256, causal=True)
    assert sched["compact"]
    ratio = sched["grid_steps"] / sched["rect_grid_steps"]
    assert ratio <= 0.6, sched
    # And with the default (1024) blocks compaction still engages.
    default = flash_schedule(4096, 4096, causal=True)
    assert default["compact"]
    assert default["grid_steps"] < default["rect_grid_steps"]
    # The schedule helper is the SAME accounting the impl builds its
    # grid from — pin the equivalence so the test can't drift from the
    # kernel.
    steps, rect, compact = _grid_steps(True, 4096, 4096, 256, 256)
    assert (steps, rect, compact) == (
        sched["grid_steps"], sched["rect_grid_steps"], True,
    )


# -- lane-packed lse --------------------------------------------------------


def test_lse_packed_layout_cuts_hbm_bytes_128x():
    """The packed [BH, S/128, 128] lse layout must be exactly 128x
    smaller than the lane-replicated [BH, S, 128] buffer, and the fwd
    impl must actually emit it (asserted from the returned shape, which
    is the kernel's out_shape/BlockSpec shape)."""
    sched = flash_schedule(1024, 1024, causal=True)
    assert sched["lse_packed"]
    assert sched["lse_replicated_bytes"] == 128 * sched["lse_bytes"]

    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 1024, 2, 16)
    qf = q.transpose(0, 2, 1, 3).reshape(2, 1024, 16)
    _, lse = _flash_fwd_impl(
        qf, qf, qf, True, 1024, 1024, True, None, True
    )
    assert lse.shape == (2, 1024 // _LANES, _LANES)

    # Un-lane-aligned blocks cannot pack; the replicated fallback stays.
    sched_small = flash_schedule(96, 96, block_q=32, block_k=32)
    assert not sched_small["lse_packed"]


def test_packed_lse_values_match_dense_logsumexp():
    """The packed tiles must hold the true per-row softmax statistics:
    unpacked lse == dense log-sum-exp of the scaled causal scores."""
    b, s, h, d = 1, 256, 1, 32
    q, k, v = _qkv(jax.random.PRNGKey(4), b, s, h, d)
    _, lse = flash_attention(
        q, k, v, causal=True, block_q=128, block_k=128, interpret=True,
        return_lse=True,
    )
    assert lse.shape == (b, h, s)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    mask = jnp.tril(jnp.ones((s, s), bool))
    scores = jnp.where(mask, scores, -jnp.inf)
    want = jax.scipy.special.logsumexp(scores.astype(jnp.float32), axis=-1)
    np.testing.assert_allclose(
        np.asarray(lse), np.asarray(want), atol=2e-5, rtol=2e-5
    )


# -- shared-delta backward --------------------------------------------------


def test_shared_delta_precompute_matches_rowsum():
    """The delta precompute kernel must emit rowsum(dO * O) in the lse
    layout — the single value both backward kernels consume."""
    bh, s, d = 2, 256, 16
    o = jax.random.normal(jax.random.PRNGKey(5), (bh, s, d))
    do = jax.random.normal(jax.random.PRNGKey(6), (bh, s, d))
    want = jnp.sum(do * o, axis=-1)

    packed = _flash_delta_impl(o, do, 128, True, True)
    assert packed.shape == (bh, s // _LANES, _LANES)
    np.testing.assert_allclose(
        packed.reshape(bh, s), want, atol=1e-5, rtol=1e-5
    )

    replicated = _flash_delta_impl(o, do, 64, True, False)
    assert replicated.shape == (bh, s, _LANES)
    np.testing.assert_allclose(
        replicated[:, :, 0], want, atol=1e-5, rtol=1e-5
    )


# -- fused one-pass dq/dkv backward (ISSUE 7) -------------------------------


def _bwd_kernel_counts(attn, q, k, v):
    """(fused, two_pass_dq, two_pass_dkv) kernel-trace counts in the
    grad jaxpr — the same mechanical engagement check the attention
    bench gates on."""
    jaxpr = str(
        jax.make_jaxpr(
            jax.grad(
                lambda q, k, v: jnp.sum(
                    attn(q, k, v).astype(jnp.float32) ** 2
                ),
                argnums=(0, 1, 2),
            )
        )(q, k, v)
    )
    return (
        jaxpr.count("_dqkv_kernel_fused"),
        jaxpr.count("_dq_kernel"),
        jaxpr.count("_dkv_kernel"),
    )


@pytest.mark.parametrize(
    "s,block,packed",
    [(512, 128, True), (256, 64, False), (384, 128, True)],
)
def test_fused_bwd_engages_and_matches_dense(s, block, packed):
    """The compact causal grid now runs ONE backward kernel: the
    schedule reports it, the grad jaxpr contains exactly the fused
    kernel (neither two-pass kernel), and grads match dense — in both
    the lane-packed and the replicated lse layout."""
    sched = flash_schedule(
        s, s, block_q=block, block_k=block, causal=True,
        head_dim=32, dtype_bytes=4,
    )
    assert sched["bwd_fused"], sched
    assert sched["lse_packed"] == packed
    assert sched["bwd_total_grid_steps"] == sched["bwd_grid_steps"]

    q, k, v = _qkv(jax.random.PRNGKey(10), 2, s, 2, 32)
    attn = lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=block, block_k=block, interpret=True
    )
    fused, dq2, dkv2 = _bwd_kernel_counts(attn, q, k, v)
    assert fused == 1 and dq2 == 0 and dkv2 == 0, (fused, dq2, dkv2)

    got = _grads(attn, q, k, v)
    want = _grads(
        lambda q, k, v: dense_attention(q, k, v, causal=True), q, k, v
    )
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            g, w, atol=5e-5, rtol=5e-5, err_msg=f"d{name} mismatch"
        )


@pytest.mark.parametrize("packed", [True, False])
def test_fused_matches_two_kernel_path(packed):
    """Pin fused == two-pass on identical (lse, delta) inputs: the
    fusion must be a pure schedule change, not a numerics change. Both
    lse layouts (packed 128-blocks, replicated 64-blocks)."""
    bh, s, d = 2, 256, 32
    block = 128 if packed else 64
    keys = jax.random.split(jax.random.PRNGKey(11), 4)
    q, k, v, do = (
        jax.random.normal(kx, (bh, s, d)) for kx in keys
    )
    o, lse = _flash_fwd_impl(q, k, v, True, block, block, True, None, packed)
    delta = _flash_delta_impl(o, do, block, True, packed)
    fused = _flash_bwd_kernels(
        q, k, v, do, lse, delta, True, block, block, True, None, packed,
        True,
    )
    two = _flash_bwd_kernels(
        q, k, v, do, lse, delta, True, block, block, True, None, packed,
        False,
    )
    for f, t, name in zip(fused, two, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            f, t, atol=1e-5, rtol=1e-5, err_msg=f"{name} fused!=two-pass"
        )


def test_noncausal_and_uneven_blocks_stay_two_pass():
    """The rectangular fallback is preserved unchanged: non-causal and
    uneven-block configurations must not fuse (schedule AND traced
    program), and forcing fused there is a loud error."""
    sched = flash_schedule(256, 256, causal=False, head_dim=16,
                           dtype_bytes=4)
    assert not sched["bwd_fused"]
    assert sched["bwd_total_grid_steps"] == 2 * sched["bwd_grid_steps"]
    assert not flash_schedule(
        256, 256, block_q=64, block_k=128, causal=True
    )["bwd_fused"]

    q, k, v = _qkv(jax.random.PRNGKey(12), 1, 256, 2, 16)
    attn = lambda q, k, v: flash_attention(
        q, k, v, causal=False, block_q=128, block_k=128, interpret=True
    )
    fused, dq2, dkv2 = _bwd_kernel_counts(attn, q, k, v)
    assert fused == 0 and dq2 == 1 and dkv2 == 1, (fused, dq2, dkv2)

    qf = q.transpose(0, 2, 1, 3).reshape(2, 256, 16)
    o, lse = _flash_fwd_impl(qf, qf, qf, False, 128, 128, True, None, True)
    delta = _flash_delta_impl(o, jnp.ones_like(o), 128, True, True)
    with pytest.raises(ValueError, match="compact causal grid"):
        _flash_bwd_kernels(
            qf, qf, qf, jnp.ones_like(o), lse, delta, False, 128, 128,
            True, None, True, True,
        )


def test_fused_vmem_budget_gates_engagement(monkeypatch):
    """The dq ring costs S·d·4 bytes of VMEM, so fusion must fall back
    past the budget (32k × d=128 is a 16 MiB ring on a ~16 MiB core)
    — and the KFTPU_FLASH_FUSED_BWD=0 escape hatch pins two-pass
    everywhere."""
    assert flash_schedule(16384, 16384)["bwd_fused"]
    big = flash_schedule(32768, 32768)
    assert big["compact"] and not big["bwd_fused"]
    assert big["bwd_fused_vmem_bytes"] > 12 * 2**20
    # The impl-side predicate is the same function the schedule reports.
    assert _bwd_fused(True, 16384, 16384, 1024, 1024, 128, 2, True)
    assert not _bwd_fused(True, 32768, 32768, 1024, 1024, 128, 2, True)

    # Forcing fused=True past the budget is a LOUD error (the dq ring
    # would exhaust core VMEM with an opaque Mosaic failure otherwise).
    z = lambda shape: jnp.zeros(shape, jnp.float32)
    with pytest.raises(ValueError, match="over-budget"):
        _flash_bwd_kernels(
            z((1, 32768, 128)), z((1, 32768, 128)), z((1, 32768, 128)),
            z((1, 32768, 128)), z((1, 256, 128)), z((1, 256, 128)),
            True, 1024, 1024, True, None, True, True,
        )

    monkeypatch.setenv("KFTPU_FLASH_FUSED_BWD", "0")
    assert not flash_schedule(16384, 16384)["bwd_fused"]
    assert not _bwd_fused(True, 16384, 16384, 1024, 1024, 128, 2, True)


def test_bwd_hbm_byte_model_fused_halves_two_pass():
    """The acceptance gate (ISSUE 7): at the 16k flagship shape the
    fused backward must model ~half the two-pass HBM bytes (the
    per-step K/V re-streaming is gone; residents and output writes keep
    the ratio a little above 0.5), monotonically approaching 1/2 as the
    triangle deepens."""
    ratios = {}
    for s in (2048, 4096, 8192, 16384):
        sc = flash_schedule(s, s)
        assert sc["bwd_hbm_bytes_fused"] < sc["bwd_hbm_bytes_two_pass"]
        ratios[s] = sc["bwd_hbm_bytes_fused"] / sc["bwd_hbm_bytes_two_pass"]
    assert ratios[16384] <= 0.6, ratios
    assert ratios[8192] <= 0.6, ratios
    assert all(
        ratios[a] >= ratios[b]
        for a, b in ((2048, 4096), (4096, 8192), (8192, 16384))
    ), ratios
    # The chosen-path figure follows the fused flag.
    sc = flash_schedule(16384, 16384)
    assert sc["bwd_fused"] and sc["bwd_hbm_bytes"] == sc["bwd_hbm_bytes_fused"]


def test_fused_under_remat_flash_policy_never_reruns_fwd():
    """remat_policy="flash" × fused backward: a block checkpoint that
    pins the kernel's named (out, lse) residuals must still dead-code
    the forward kernel out of the backward — the fused kernel must not
    have changed the residual set. Asserted from the grad jaxpr: the
    checkpointed grad traces the forward kernel exactly as often as the
    un-checkpointed grad, and runs the fused backward."""
    from kubeflow_tpu.models.transformer import checkpoint_policy

    s, block = 256, 128
    q, k, v = _qkv(jax.random.PRNGKey(13), 1, s, 2, 32)

    def attn(q, k, v):
        return flash_attention(
            q, k, v, causal=True, block_q=block, block_k=block,
            interpret=True,
        )

    def loss_plain(q, k, v):
        return jnp.sum(attn(q, k, v).astype(jnp.float32) ** 2)

    loss_ckpt = jax.checkpoint(
        loss_plain, policy=checkpoint_policy("flash")
    )
    grads = lambda f: jax.grad(f, argnums=(0, 1, 2))
    jaxpr_plain = str(jax.make_jaxpr(grads(loss_plain))(q, k, v))
    jaxpr_ckpt = str(jax.make_jaxpr(grads(loss_ckpt))(q, k, v))
    assert (
        jaxpr_ckpt.count("_fwd_kernel") == jaxpr_plain.count("_fwd_kernel")
    ), "remat_policy='flash' re-runs the flash forward in the backward"
    assert jaxpr_ckpt.count("_dqkv_kernel_fused") == 1
    assert "_dq_kernel" not in jaxpr_ckpt
    # And the checkpointed grads equal the plain ones.
    for a, b, name in zip(
        grads(loss_ckpt)(q, k, v), grads(loss_plain)(q, k, v), "qkv"
    ):
        np.testing.assert_allclose(
            a, b, atol=1e-5, rtol=1e-5, err_msg=f"d{name} mismatch"
        )


def test_fused_handles_ragged_padded_tail():
    """Ragged S rides the fused kernel too: 321 pads to 384 (compact,
    square blocks, kv_len tail mask) and grads must match dense."""
    s = 321
    sched = flash_schedule(s, s, block_q=128, block_k=128, head_dim=16,
                           dtype_bytes=4)
    assert sched["padded_seq_q"] == 384 and sched["bwd_fused"], sched

    q, k, v = _qkv(jax.random.PRNGKey(14), 1, s, 2, 16)
    attn = lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=128, block_k=128, interpret=True
    )
    fused, dq2, dkv2 = _bwd_kernel_counts(attn, q, k, v)
    assert fused == 1 and dq2 == 0 and dkv2 == 0
    got = _grads(attn, q, k, v)
    want = _grads(
        lambda q, k, v: dense_attention(q, k, v, causal=True), q, k, v
    )
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            g, w, atol=5e-4, rtol=5e-4, err_msg=f"d{name} mismatch"
        )


# -- internal padding (ragged sequence lengths) -----------------------------


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("s", [100, 321, 1025])
def test_ragged_sequences_pad_and_match_dense(causal, s):
    """Lengths with no 8-aligned divisor (previously a hard error →
    silent dense fallback at the model layer) pad to the next lane
    multiple, mask the tail, and match dense numerics fwd + bwd. The
    non-causal case is the one the tail mask exists for: without it the
    zero-padded keys would soak up softmax mass."""
    sched = flash_schedule(s, s, causal=causal)
    assert sched["padded_seq_q"] % _LANES == 0
    assert sched["padded_seq_q"] >= s

    q, k, v = _qkv(jax.random.PRNGKey(7), 1, s, 2, 16)
    attn = lambda q, k, v: flash_attention(
        q, k, v, causal=causal, interpret=True
    )
    out = attn(q, k, v)
    assert out.shape == q.shape
    np.testing.assert_allclose(
        out, dense_attention(q, k, v, causal=causal), atol=2e-4, rtol=2e-4
    )
    got = _grads(attn, q, k, v)
    want = _grads(
        lambda q, k, v: dense_attention(q, k, v, causal=causal), q, k, v
    )
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            g, w, atol=5e-4, rtol=5e-4, err_msg=f"d{name} mismatch (s={s})"
        )


def test_odd_head_counts():
    """Heads are flattened into the grid's bh dimension — odd counts must
    work (they exercise bh rows that share nothing 2-power-aligned)."""
    for h in (3, 5):
        q, k, v = _qkv(jax.random.PRNGKey(8), 2, 128, h, 16)
        out = flash_attention(
            q, k, v, causal=True, block_q=64, block_k=64, interpret=True
        )
        ref = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_bf16_compact_packed_path():
    q, k, v = _qkv(jax.random.PRNGKey(9), 1, 256, 2, 32, jnp.bfloat16)
    out = flash_attention(
        q, k, v, causal=True, block_q=128, block_k=128, interpret=True
    )
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        out.astype(np.float32), ref.astype(np.float32), atol=3e-2, rtol=3e-2
    )
