"""Frontend serving: each web app ships its SPA (the reference's
Polymer/Angular tier) from the same backend that serves /api — the
crud_backend pattern of one container serving both."""

import pathlib
import re

import pytest

from kubeflow_tpu.apps.dashboard import DashboardApp
from kubeflow_tpu.apps.jupyter import JupyterApp
from kubeflow_tpu.apps.tensorboards import TensorboardsApp
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.web import App, Response, TestClient
from kubeflow_tpu.web.authn import HeaderAuthn

HDR = "x-goog-authenticated-user-email"
HEADERS = {HDR: "accounts.google.com:alice@x.co"}

STATIC = pathlib.Path("kubeflow_tpu/apps/static")


@pytest.fixture
def api():
    return FakeApiServer()


@pytest.mark.parametrize(
    "app_cls,marker",
    [
        (DashboardApp, "Kubeflow TPU"),
        (JupyterApp, "New Notebook"),
        (TensorboardsApp, "New Tensorboard"),
    ],
)
def test_index_served(api, app_cls, marker):
    client = TestClient(app_cls(api), headers=HEADERS)
    resp = client.get("/")
    assert resp.status == 200
    assert resp.content_type.startswith("text/html")
    assert marker in resp.body.decode()


def test_shared_assets_served(api):
    client = TestClient(JupyterApp(api), headers=HEADERS)
    assert "--accent" in client.get("/ui.css").body.decode()
    js = client.get("/ui.js")
    assert js.content_type.startswith(("text/javascript", "application/javascript"))
    assert "export class Poller" in js.body.decode()


def test_api_routes_win_over_static(api):
    client = TestClient(JupyterApp(api), headers=HEADERS)
    resp = client.get("/api/config")
    assert resp.json()["config"]


def test_traversal_refused(api):
    client = TestClient(JupyterApp(api), headers=HEADERS)
    resp = client.get("/../jupyter.py")
    assert resp.status == 404


def test_static_requires_identity():
    """The SPA sits behind the same authn hook as /api (unauthenticated
    clients cannot probe either surface)."""
    app = JupyterApp(FakeApiServer(), authn=HeaderAuthn())
    client = TestClient(app)  # no identity header
    assert client.get("/").status == 401


def test_frontends_reference_only_backend_routes():
    """Every fetch() the SPAs make has a matching backend route — keeps
    the pages and the APIs from drifting apart."""
    routes = {
        "jupyter.html": [
            "/api/config",
            "/api/namespaces/${ns}/notebooks",
            "/api/storageclasses",
            "/api/namespaces/${ns}/poddefaults",
        ],
        "tensorboards.html": [
            "/api/namespaces/${ns}/tensorboards",
            "/api/namespaces/${ns}/pvcs",
        ],
    }
    for page, expected in routes.items():
        text = (STATIC / page).read_text()
        for path in expected:
            assert path in text, f"{page} no longer calls {path}"


# -- frontend <-> backend route drift (VERDICT #4: params + verbs) ---------

CALL_RE = re.compile(
    r"""(?:api|fetch)\(\s*[`"']([^`"']+)[`"']\s*(?:,\s*\{(.{0,160}?)\})?""",
    re.S,
)


def _frontend_calls(*sources: str) -> set[tuple[str, str]]:
    """(method, normalized path) for every api()/fetch() call in the
    given JS/HTML sources. Template params `${x}` and string-concat
    tails (literal ending in '/') normalize to `{p}`; query strings are
    dropped."""
    calls = set()
    for text in sources:
        for m in CALL_RE.finditer(text):
            path, opts = m.group(1), m.group(2) or ""
            if not path.startswith("/api"):
                continue
            method = re.search(r'method:\s*"(\w+)"', opts)
            path = path.split("?")[0]
            path = re.sub(r"\$\{[^}]+\}", "{p}", path)
            if path.endswith("/"):
                path += "{p}"  # "/api/metrics/" + metric concat form
            calls.add(((method.group(1) if method else "GET").lower(), path))
    return calls


def _route_matches(routes: set, method: str, path: str) -> bool:
    for r_method, r_path in routes:
        if r_method != method:
            continue
        pattern = re.sub(r"\{[a-zA-Z_][a-zA-Z0-9_]*\}", "[^/]+", r_path)
        if re.fullmatch(pattern, re.sub(r"\{p\}", "x", path)):
            return True
    return False


@pytest.mark.parametrize(
    "app_cls,page",
    [
        (DashboardApp, "index.html"),
        (JupyterApp, "jupyter.html"),
        (TensorboardsApp, "tensorboards.html"),
    ],
)
def test_every_frontend_call_has_a_backend_route(api, app_cls, page):
    from kubeflow_tpu.web.openapi import route_table

    sources = [
        (STATIC / page).read_text(),
        (STATIC / "ui.js").read_text(),
    ]
    routes = route_table(app_cls(api))
    missing = [
        f"{m.upper()} {p}"
        for m, p in sorted(_frontend_calls(*sources))
        if not _route_matches(routes, m, p)
    ]
    assert not missing, f"frontend calls without backend routes: {missing}"


@pytest.mark.parametrize(
    "app_cls,page",
    [
        (JupyterApp, "jupyter.html"),
        (TensorboardsApp, "tensorboards.html"),
    ],
)
def test_every_backend_api_route_is_exercised_by_its_page(
    api, app_cls, page
):
    """The reverse gate: a CRUD backend route nothing in the SPA calls is
    dead surface (or the SPA is missing functionality — the round-1 gap)."""
    from kubeflow_tpu.web.openapi import route_table

    calls = _frontend_calls(
        (STATIC / page).read_text(), (STATIC / "ui.js").read_text()
    )
    unused = []
    for method, path in sorted(route_table(app_cls(api))):
        if not path.startswith("/api"):
            continue
        generic = re.sub(r"\{[a-zA-Z_][a-zA-Z0-9_]*\}", "{p}", path)
        if (method, generic) not in calls:
            unused.append(f"{method.upper()} {path}")
    assert not unused, f"backend routes the SPA never calls: {unused}"
