"""Frontend serving: each web app ships its SPA (the reference's
Polymer/Angular tier) from the same backend that serves /api — the
crud_backend pattern of one container serving both."""

import pathlib

import pytest

from kubeflow_tpu.apps.dashboard import DashboardApp
from kubeflow_tpu.apps.jupyter import JupyterApp
from kubeflow_tpu.apps.tensorboards import TensorboardsApp
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.web import App, Response, TestClient
from kubeflow_tpu.web.authn import HeaderAuthn

HDR = "x-goog-authenticated-user-email"
HEADERS = {HDR: "accounts.google.com:alice@x.co"}

STATIC = pathlib.Path("kubeflow_tpu/apps/static")


@pytest.fixture
def api():
    return FakeApiServer()


@pytest.mark.parametrize(
    "app_cls,marker",
    [
        (DashboardApp, "Kubeflow TPU"),
        (JupyterApp, "New Notebook"),
        (TensorboardsApp, "New Tensorboard"),
    ],
)
def test_index_served(api, app_cls, marker):
    client = TestClient(app_cls(api), headers=HEADERS)
    resp = client.get("/")
    assert resp.status == 200
    assert resp.content_type.startswith("text/html")
    assert marker in resp.body.decode()


def test_shared_assets_served(api):
    client = TestClient(JupyterApp(api), headers=HEADERS)
    assert "--accent" in client.get("/ui.css").body.decode()
    js = client.get("/ui.js")
    assert js.content_type.startswith(("text/javascript", "application/javascript"))
    assert "export class Poller" in js.body.decode()


def test_api_routes_win_over_static(api):
    client = TestClient(JupyterApp(api), headers=HEADERS)
    resp = client.get("/api/config")
    assert resp.json()["config"]


def test_traversal_refused(api):
    client = TestClient(JupyterApp(api), headers=HEADERS)
    resp = client.get("/../jupyter.py")
    assert resp.status == 404


def test_static_requires_identity():
    """The SPA sits behind the same authn hook as /api (unauthenticated
    clients cannot probe either surface)."""
    app = JupyterApp(FakeApiServer(), authn=HeaderAuthn())
    client = TestClient(app)  # no identity header
    assert client.get("/").status == 401


def test_frontends_reference_only_backend_routes():
    """Every fetch() the SPAs make has a matching backend route — keeps
    the pages and the APIs from drifting apart."""
    routes = {
        "jupyter.html": [
            "/api/config",
            "/api/namespaces/${ns}/notebooks",
            "/api/storageclasses",
            "/api/namespaces/${ns}/poddefaults",
        ],
        "tensorboards.html": [
            "/api/namespaces/${ns}/tensorboards",
            "/api/namespaces/${ns}/pvcs",
        ],
    }
    for page, expected in routes.items():
        text = (STATIC / page).read_text()
        for path in expected:
            assert path in text, f"{page} no longer calls {path}"
