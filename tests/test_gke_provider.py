"""GkeCloud: golden request construction + CloudProvider semantics.

The reference unit-tests GCP request construction without a cloud
(`bootstrap/cmd/bootstrap/app/gcpUtils_test.go`); these are the TPU
equivalents — the exact container-v1 payloads for slice node pools.
"""

import subprocess
import sys

from kubeflow_tpu.deploy.apply import apply_platform
from kubeflow_tpu.deploy.gke import (
    GkeCloud,
    RecordingTransport,
    cluster_create_request,
    dry_run_requests,
    node_pool_create_request,
    node_pool_delete_request,
)
from kubeflow_tpu.deploy.kfdef import NodePool, PlatformSpec
from kubeflow_tpu.deploy.provisioner import FakeCloud
from kubeflow_tpu.testing import FakeApiServer

SPEC = PlatformSpec(
    name="kf-prod",
    project="my-proj",
    zone="us-central2-b",
    node_pools=[NodePool(name="tpu-pool-0", accelerator="v5e",
                         topology="4x4")],
)


def test_multi_host_pool_golden_request():
    req = node_pool_create_request(
        SPEC, SPEC.node_pools[0]
    )
    assert req.method == "POST"
    assert req.url == (
        "https://container.googleapis.com/v1/projects/my-proj/locations/"
        "us-central2-b/clusters/kf-prod/nodePools"
    )
    assert req.body == {
        "nodePool": {
            "name": "tpu-pool-0",
            # 4x4 v5e = 16 chips at 4/host → exactly 4 hosts, not a knob.
            "initialNodeCount": 4,
            "config": {
                "machineType": "ct5lp-hightpu-4t",
                "spot": False,
                "labels": {
                    "kubeflow-tpu.org/platform": "kf-prod",
                    "cloud.google.com/tpu-node-pool": "tpu-pool-0",
                    "cloud.google.com/tpu-accelerator": "v5e",
                    "cloud.google.com/tpu-topology": "4x4",
                },
                "oauthScopes": [
                    "https://www.googleapis.com/auth/cloud-platform"
                ],
            },
            "management": {"autoRepair": True, "autoUpgrade": False},
            # Multi-host slice: one ICI domain.
            "placementPolicy": {"type": "COMPACT", "tpuTopology": "4x4"},
        }
    }


def test_single_host_pool_has_no_placement_policy():
    pool = NodePool(name="small", accelerator="v5e", topology="2x2",
                    preemptible=True)
    req = node_pool_create_request(SPEC, pool)
    body = req.body["nodePool"]
    assert body["initialNodeCount"] == 1
    assert "placementPolicy" not in body
    assert body["config"]["spot"] is True
    assert body["config"]["machineType"] == "ct5lp-hightpu-4t"


def test_v6e_and_v4_machine_types():
    assert (
        node_pool_create_request(
            SPEC, NodePool(name="p", accelerator="v6e", topology="2x2")
        ).body["nodePool"]["config"]["machineType"]
        == "ct6e-standard-4t"
    )
    assert (
        node_pool_create_request(
            SPEC, NodePool(name="p", accelerator="v4", topology="2x2x2")
        ).body["nodePool"]["config"]["machineType"]
        == "ct4p-hightpu-4t"
    )


def test_cluster_request_enables_workload_identity():
    req = cluster_create_request(SPEC)
    cluster = req.body["cluster"]
    assert (
        cluster["workloadIdentityConfig"]["workloadPool"]
        == "my-proj.svc.id.goog"
    )
    assert req.url.endswith("/locations/us-central2-b/clusters")


def test_delete_request():
    req = node_pool_delete_request(SPEC, "tpu-pool-0")
    assert req.method == "DELETE"
    assert req.url.endswith("/clusters/kf-prod/nodePools/tpu-pool-0")


def test_ensure_skips_existing_pool():
    transport = RecordingTransport(
        responses={"/nodePools": {"nodePools": [{"name": "tpu-pool-0"}]}}
    )
    cloud = GkeCloud(transport)
    cloud.ensure_node_pool(SPEC, SPEC.node_pools[0])
    # Only the list went out — idempotent second apply sends no create.
    assert [r.method for r in transport.requests] == ["GET"]


def test_ensure_creates_missing_pool():
    transport = RecordingTransport(
        responses={"/nodePools": {"nodePools": []}}
    )
    GkeCloud(transport).ensure_node_pool(SPEC, SPEC.node_pools[0])
    methods = [r.method for r in transport.requests]
    assert methods == ["GET", "POST"]


def test_gke_cloud_drives_platform_phase():
    """GkeCloud slots in behind apply_platform's CloudProvider seam: the
    PLATFORM phase emits exactly the expected create calls."""
    api = FakeApiServer()
    transport = RecordingTransport(responses={"/nodePools": {"nodePools": []}})
    spec = PlatformSpec(
        name="kf-prod", project="my-proj", zone="us-central2-b",
        node_pools=[NodePool(name="a", topology="4x4"),
                    NodePool(name="b", topology="2x2")],
        applications=[],
    )
    result = apply_platform(spec, api, GkeCloud(transport))
    assert result.succeeded
    # The PLATFORM phase ensures the cluster first (recorded GET + POST),
    # then the pools.
    pool_creates = [
        r for r in transport.requests
        if r.method == "POST" and r.url.endswith("/nodePools")
    ]
    assert [r.body["nodePool"]["name"] for r in pool_creates] == ["a", "b"]
    cluster_creates = [
        r for r in transport.requests
        if r.method == "POST" and r.url.endswith("/clusters")
    ]
    assert len(cluster_creates) == 1


def test_dry_run_cli_prints_payloads(tmp_path):
    spec_file = tmp_path / "platform.yaml"
    spec_file.write_text(SPEC.to_yaml())
    out = subprocess.run(
        [sys.executable, "-m", "kubeflow_tpu.deploy", "apply",
         "-f", str(spec_file), "--dry-run"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "ct5lp-hightpu-4t" in out.stdout
    assert "container.googleapis.com" in out.stdout
    assert "K8S phase would apply" in out.stdout
    assert dry_run_requests(SPEC)[0].body["cluster"]["name"] == "kf-prod"


def test_deploy_server_gke_provider_end_to_end():
    """spec.provider='gke' drives the deploy server's two-phase apply
    through GkeCloud: the PLATFORM phase emits real container-v1
    payloads on the transport (GKE materializes the nodes in
    production), the K8S phase applies bundles in-process."""
    import time as _time

    from kubeflow_tpu.deploy.server import DeployServer
    from kubeflow_tpu.web.wsgi import TestClient

    api = FakeApiServer()
    transport = RecordingTransport(responses={"/nodePools": {"nodePools": []}})
    server = DeployServer(api, FakeCloud(api), gke_transport=transport)
    client = TestClient(server)
    spec = PlatformSpec(
        name="kf-gke", project="my-proj", zone="us-central2-b",
        provider="gke",
        node_pools=[NodePool(name="pool0", topology="4x4")],
        applications=["tpujob-operator"] if "tpujob-operator" in _bundles()
        else [],
    )
    resp = client.post("/kfctl/apps/v1/create", body=spec.to_dict())
    assert resp.status == 200, resp.body
    deadline = _time.time() + 30
    while _time.time() < deadline:
        status = client.get("/kfctl/apps/v1/status/kf-gke")
        if status.status == 200 and status.json()["status"].get(
            "phase"
        ) in ("Ready", "Failed"):
            break
        _time.sleep(0.1)
    assert status.json()["status"]["phase"] == "Ready", status.json()
    pool_creates = [
        r for r in transport.requests
        if r.method == "POST" and r.url.endswith("/nodePools")
    ]
    assert pool_creates
    assert pool_creates[0].body["nodePool"]["name"] == "pool0"
    # No Nodes materialized in-process — that's GKE's job.
    assert api.list("Node", "") == []


def test_deploy_server_rejects_unknown_provider():
    from kubeflow_tpu.deploy.server import DeployServer
    from kubeflow_tpu.web.wsgi import TestClient

    api = FakeApiServer()
    client = TestClient(DeployServer(api, FakeCloud(api)))
    spec = PlatformSpec(name="x", provider="azure")
    assert client.post(
        "/kfctl/apps/v1/create", body=spec.to_dict()
    ).status == 400


def _bundles():
    from kubeflow_tpu.deploy.bundles import BUNDLES

    return BUNDLES


def test_provider_round_trips_spec():
    spec = PlatformSpec(name="p", provider="gke")
    assert PlatformSpec.from_dict(spec.to_dict()).provider == "gke"
