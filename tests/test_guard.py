"""AnomalyGuard: device-side per-step screening, skip-not-crash, the
never-persist-a-NaN regression, divergence rollback with seed
perturbation, and preemption-safe exit (ISSUE 5)."""

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.parallel import MeshSpec, build_mesh
from kubeflow_tpu.testing import chaos
from kubeflow_tpu.testing.tinymodels import TinyMLP
from kubeflow_tpu.train import (
    Checkpointer,
    Preempted,
    SyntheticImages,
    TrainConfig,
    Trainer,
    TrainingDiverged,
    fit,
)
from kubeflow_tpu.train.guard import AnomalyGuard, GuardConfig


class PoisonedData(chaos.ResumableWrapper):
    """Resumable wrapper over SyntheticImages that poisons scheduled
    positions: `nan_at` positions yield NaN images, and (under salt 0)
    every position >= `spike_from` yields hugely scaled images — a
    sustained divergence that a seed perturbation (salt != 0) cures, so
    rollback-with-perturbation is observable end to end."""

    def __init__(self, inner, nan_at=(), spike_from=None, scale=1e3):
        super().__init__(inner)
        self.nan_at = frozenset(nan_at)
        self.spike_from = spike_from
        self.scale = scale

    def transform(self, pos, batch):
        salt = self.state_dict()["salt"]
        if pos in self.nan_at:
            return dict(batch, image=batch["image"] * jnp.nan)
        if (
            self.spike_from is not None
            and pos >= self.spike_from
            and salt == 0
        ):
            return dict(batch, image=batch["image"] * self.scale)
        return batch


@pytest.fixture(scope="module")
def mesh1():
    return build_mesh(MeshSpec(dp=1), jax.devices()[:1])


def _trainer(mesh, total_steps=16, **guard_kwargs):
    guard = AnomalyGuard(GuardConfig(
        ewma_alpha=0.2, warmup_steps=2, loss_spike_factor=3.0,
        grad_spike_factor=6.0, max_consecutive_skips=3, **guard_kwargs,
    ))
    config = TrainConfig(
        batch_size=4, learning_rate=0.05, warmup_steps=2,
        total_steps=total_steps, fsdp_params=False, weight_decay=0.0,
    )
    return Trainer(
        TinyMLP(), config, mesh, example_input_shape=(2, 8, 8, 3),
        guard=guard,
    )


def _data(mesh, seed=0):
    return SyntheticImages(
        mesh, 4, image_size=8, num_classes=10, seed=seed, vary_per_step=True
    )


def _all_finite(tree) -> bool:
    return all(
        np.isfinite(np.asarray(leaf)).all()
        for leaf in jax.tree_util.tree_leaves(tree)
    )


# -- guard unit behavior ----------------------------------------------------


def test_guard_config_validation():
    with pytest.raises(ValueError, match="spike factors"):
        GuardConfig(loss_spike_factor=0.5)
    with pytest.raises(ValueError, match="ewma_alpha"):
        GuardConfig(ewma_alpha=0.0)
    with pytest.raises(ValueError, match="max_consecutive_skips"):
        GuardConfig(max_consecutive_skips=0)


def test_guard_skips_nonfinite_and_spikes_updates_ewma_on_accept_only():
    guard = AnomalyGuard(GuardConfig(
        ewma_alpha=0.5, warmup_steps=1, loss_spike_factor=2.0,
        max_consecutive_skips=2,
    ))
    g = guard.init_state()
    # First observation seeds the EWMA and is accepted.
    g, ok = guard.apply(g, jnp.float32(1.0), jnp.float32(1.0))
    assert bool(ok) and float(g["ewma_loss"]) == 1.0
    # Non-finite: skipped, EWMA untouched.
    g, ok = guard.apply(g, jnp.float32(np.nan), jnp.float32(1.0))
    assert not bool(ok)
    assert float(g["ewma_loss"]) == 1.0 and int(g["skipped_total"]) == 1
    # A spike (> 2x EWMA after warmup): skipped, EWMA untouched — the
    # rejected value must not drag the baseline toward the anomaly.
    g, ok = guard.apply(g, jnp.float32(10.0), jnp.float32(1.0))
    assert not bool(ok) and float(g["ewma_loss"]) == 1.0
    # Two consecutive skips = max_consecutive_skips: sticky divergence.
    assert guard.diverged(g)
    # An accepted step resets the consecutive counter but NOT the
    # sticky flag (only a rollback, restoring pre-divergence guard
    # state, clears it).
    g, ok = guard.apply(g, jnp.float32(1.1), jnp.float32(1.0))
    assert bool(ok) and int(g["consecutive_skips"]) == 0
    assert guard.diverged(g)
    # A non-finite UPDATE is rejected even when loss and grad-norm are
    # finite (the overflow-to-inf-params hole): the trainer feeds the
    # post-update params' finiteness through update_finite.
    g, ok = guard.apply(
        g, jnp.float32(1.0), jnp.float32(1.0),
        update_finite=jnp.bool_(False),
    )
    assert not bool(ok)


def test_negative_loss_objective_not_flagged_as_spike():
    """The multiplicative spike test assumes a positive baseline: with
    a negative accepted-loss EWMA (reward-style signed objectives) it
    must disarm rather than flag every ordinary step — pre-fix the
    threshold 2*(-1.0) sat below ANY loss, so a healthy run burned its
    rollback budget and raised TrainingDiverged."""
    guard = AnomalyGuard(GuardConfig(
        ewma_alpha=0.5, warmup_steps=1, loss_spike_factor=2.0,
        max_consecutive_skips=2,
    ))
    g = guard.init_state()
    for loss in (-1.0, -0.9, -0.8):  # ordinary signed-objective descent
        g, ok = guard.apply(g, jnp.float32(loss), jnp.float32(1.0))
        assert bool(ok), loss
    assert not guard.diverged(g)
    # Finiteness screening still covers the disarmed regime.
    g, ok = guard.apply(g, jnp.float32(np.nan), jnp.float32(1.0))
    assert not bool(ok)


def test_guarded_step_skips_poison_batch_without_touching_state(mesh1):
    trainer = _trainer(mesh1)
    state = trainer.init_state(jax.random.PRNGKey(0))
    step = trainer.make_train_step()
    data = iter(_data(mesh1))
    for _ in range(3):
        state, metrics = step(state, next(data))
    before = jax.tree_util.tree_map(np.asarray, state.params)
    opt_before = jax.tree_util.tree_map(np.asarray, state.opt_state)
    bad = next(data)
    bad = dict(bad, image=bad["image"] * jnp.nan)
    state, metrics = step(state, bad)
    assert int(metrics["guard_ok"]) == 0
    assert int(metrics["guard_skipped_total"]) == 1
    # Step counter advanced (bookkeeping stays aligned)...
    assert int(state.step) == 4
    # ...but params AND optimizer state are bit-identical: the poison
    # batch reached nothing.
    for a, b in zip(
        jax.tree_util.tree_leaves(before),
        jax.tree_util.tree_leaves(state.params),
    ):
        np.testing.assert_array_equal(a, np.asarray(b))
    for a, b in zip(
        jax.tree_util.tree_leaves(opt_before),
        jax.tree_util.tree_leaves(state.opt_state),
    ):
        np.testing.assert_array_equal(a, np.asarray(b))
    assert _all_finite(state.params)


def test_nonfinite_bn_stats_update_is_rejected(mesh1):
    """A zero-mean, huge-but-finite poison batch keeps loss, grads AND
    post-update params finite (BatchNorm normalizes it away: rsqrt(inf)
    = 0) while the f32 running-variance update overflows to inf — the
    verdict must screen batch_stats too, or the inf rides into every
    later checkpoint and breaks eval/serving (train=False)."""
    import flax.linen as nn

    class BNFirst(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
            x = nn.BatchNorm(use_running_average=not train)(x)
            return nn.Dense(10)(x)

    guard = AnomalyGuard(GuardConfig(
        ewma_alpha=0.2, warmup_steps=2, loss_spike_factor=3.0,
        grad_spike_factor=6.0, max_consecutive_skips=3,
    ))
    config = TrainConfig(
        batch_size=8, learning_rate=0.05, warmup_steps=2,
        total_steps=10, fsdp_params=False, weight_decay=0.0,
    )
    trainer = Trainer(
        BNFirst(), config, mesh1, example_input_shape=(2, 8, 8, 3),
        guard=guard,
    )
    state = trainer.init_state(jax.random.PRNGKey(0))
    step = trainer.make_train_step()
    data = iter(SyntheticImages(
        mesh1, 8, image_size=8, num_classes=10, vary_per_step=True
    ))
    for _ in range(3):
        state, metrics = step(state, next(data))
    before = jax.tree_util.tree_map(np.asarray, state.batch_stats)
    # +c / -c across the batch: per-feature mean is exactly 0 (finite),
    # mean-of-squares c^2 overflows f32 -> batch var = inf, normalized
    # activations = (x - 0) * rsqrt(inf) = 0 -> finite loss and grads.
    bad = next(data)
    sign = jnp.where(jnp.arange(8) % 2 == 0, 1.0, -1.0)[:, None, None, None]
    bad = dict(bad, image=jnp.broadcast_to(
        sign * jnp.float32(2e19), bad["image"].shape
    ))
    state, metrics = step(state, bad)
    # The trap this test pins: every scalar the OLD screen looked at is
    # finite, so only the batch_stats check can reject the step.
    assert np.isfinite(float(metrics["loss"]))
    assert np.isfinite(float(metrics["grad_norm"]))
    assert int(metrics["guard_ok"]) == 0
    for a, b in zip(
        jax.tree_util.tree_leaves(before),
        jax.tree_util.tree_leaves(state.batch_stats),
    ):
        np.testing.assert_array_equal(a, np.asarray(b))
    assert _all_finite(state.batch_stats)


# -- the fit() gap regression (satellite 1) ---------------------------------


def test_nan_at_non_log_step_never_persisted(mesh1, tmp_path):
    """The seed loop checked finiteness only at log/save steps — a NaN
    at step 3 with log_every=50 would poison every later checkpoint.
    With the guard, EVERY step is screened device-side: the poison
    update is skipped, and every checkpoint ever written restores to
    fully finite state."""
    trainer = _trainer(mesh1)
    data = PoisonedData(_data(mesh1), nan_at=(2,))  # step 3's batch
    ckpt = Checkpointer(tmp_path / "ck", save_interval_steps=5)
    result = fit(
        trainer, data, total_steps=10, checkpointer=ckpt, log_every=50
    )
    assert result.history[-1]["guard_skipped_total"] == 1
    # EVERY persisted checkpoint — not just the newest — restores to
    # fully finite state (each restored directly by step, bypassing
    # restore_latest's newest-first shortcut).
    import orbax.checkpoint as ocp

    trainer_b = _trainer(mesh1)
    steps = ckpt.all_steps()
    assert steps, "expected checkpoints at the save interval"
    with ocp.StandardCheckpointer() as sc:
        for step in steps:
            restored = sc.restore(
                tmp_path / "ck" / str(step) / "default",
                trainer_b.abstract_state(),
            )
            assert _all_finite(restored.params), step
            assert _all_finite(restored.opt_state), step
    ckpt.close()


# -- divergence rollback (the tentpole's escape hatch) ----------------------


def test_sustained_divergence_rolls_back_with_seed_perturbation(
    mesh1, tmp_path
):
    """Under salt 0 every batch from position 6 on is poison: the guard
    skips 3 in a row, flags divergence, and fit rolls back to the step-5
    checkpoint AND perturbs the data seed — under salt 1 the same
    positions are clean, so the run completes. The rollback is visible
    in the result and the final state is finite."""
    trainer = _trainer(mesh1)
    data = PoisonedData(_data(mesh1), spike_from=6)
    ckpt = Checkpointer(tmp_path / "ck", save_interval_steps=5)
    result = fit(
        trainer, data, total_steps=12, checkpointer=ckpt, log_every=1
    )
    ckpt.close()
    assert result.rollbacks == 1
    assert int(result.state.step) == 12
    assert _all_finite(result.state.params)
    # The perturbation moved the salt: the data sequence actually changed.
    assert data.state_dict()["salt"] == 1
    # And the salt is DURABLE: rollback rewrote the restored step's
    # manifest data_state in place (still verifying), so a crash right
    # after the rollback resumes onto the cured trajectory instead of
    # replaying the diverged one.
    from kubeflow_tpu.train.checkpoint import verify_manifest

    manifest = verify_manifest(tmp_path / "ck" / "5")
    assert manifest is not None
    assert manifest["data_state"]["salt"] == 1
    assert manifest["data_state"]["position"] == 5


def test_rollback_refuses_fixed_stream_without_perturb(mesh1, tmp_path):
    """A vary_per_step=False stream yields one cached batch forever, so
    perturb() could change nothing: the stream does not offer it
    (shadowed to None) and the rollback precondition refuses up front —
    every retry would replay a byte-identical diverging trajectory."""
    trainer = _trainer(mesh1)
    fixed = SyntheticImages(
        mesh1, 4, image_size=8, num_classes=10, vary_per_step=False
    )
    assert fixed.perturb is None
    data = PoisonedData(fixed, spike_from=6)
    ckpt = Checkpointer(tmp_path / "ck", save_interval_steps=5)
    with pytest.raises(TrainingDiverged, match="perturbable"):
        fit(trainer, data, total_steps=12, checkpointer=ckpt, log_every=1)
    ckpt.close()


def test_sustained_divergence_without_checkpoint_raises(mesh1):
    trainer = _trainer(mesh1)
    data = PoisonedData(_data(mesh1), spike_from=6)
    with pytest.raises(TrainingDiverged, match="divergence"):
        fit(trainer, data, total_steps=12, log_every=1)


# -- preemption-safe exit ---------------------------------------------------


def test_sigterm_returns_preempted_after_emergency_save(mesh1, tmp_path):
    trainer = _trainer(mesh1)
    data = _data(mesh1)
    ckpt = Checkpointer(tmp_path / "ck", save_interval_steps=100)

    def on_metrics(step, rec):
        if step == 4:
            os.kill(os.getpid(), signal.SIGTERM)

    result = fit(
        trainer, data, total_steps=12, checkpointer=ckpt,
        log_every=1, on_metrics=on_metrics,
    )
    assert isinstance(result, Preempted)
    assert result.signum == signal.SIGTERM
    # The emergency save landed at the boundary AFTER the in-flight
    # step: zero lost work, data state included.
    assert ckpt.latest_step() == 5
    ckpt.close()

    # Resume completes the run and continues the batch sequence exactly.
    trainer_b = _trainer(mesh1)
    data_b = _data(mesh1)
    ckpt_b = Checkpointer(tmp_path / "ck", save_interval_steps=100)
    result_b = fit(
        trainer_b, data_b, total_steps=12, checkpointer=ckpt_b, log_every=1
    )
    ckpt_b.close()
    assert not isinstance(result_b, Preempted)
    assert result_b.resumed_from == 5
    assert data_b.state_dict()["position"] == 12
    assert int(result_b.state.step) == 12


def test_resume_with_data_state_matches_uninterrupted(mesh1, tmp_path):
    """Preempt-and-resume equals the uninterrupted run EXACTLY when the
    data is per-position (the batch sequence neither repeats nor
    skips): the strongest form of the parity the soak asserts."""
    straight = fit(
        _trainer(mesh1), _data(mesh1), total_steps=8, log_every=1
    ).state

    ckpt = Checkpointer(tmp_path / "ck", save_interval_steps=3)
    fit(
        _trainer(mesh1), _data(mesh1), total_steps=4,
        checkpointer=ckpt, log_every=1,
    )
    ckpt.close()
    ckpt2 = Checkpointer(tmp_path / "ck", save_interval_steps=3)
    resumed = fit(
        _trainer(mesh1), _data(mesh1), total_steps=8,
        checkpointer=ckpt2, log_every=1,
    )
    ckpt2.close()
    assert resumed.resumed_from == 4
    for a, b in zip(
        jax.tree_util.tree_leaves(straight.params),
        jax.tree_util.tree_leaves(resumed.state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
