"""E2E harness utilities (the `testing/` toolbox parity, SURVEY.md §4)."""

import time
import xml.etree.ElementTree as ET

import pytest

from kubeflow_tpu.controllers.notebook import NotebookController
from kubeflow_tpu.deploy.apply import apply_platform
from kubeflow_tpu.deploy.kfdef import default_spec
from kubeflow_tpu.deploy.provisioner import FakeCloud
from kubeflow_tpu.deploy.server import DeployServer
from kubeflow_tpu.testing.e2e_util import (
    DeployProber,
    NotebookLoadTest,
    TestResult,
    junit_xml,
    kf_is_ready,
    missing_deployments,
    run_with_retry,
    wait_for,
    wait_for_deployments,
)
from kubeflow_tpu.testing.fake_apiserver import FakeApiServer
from kubeflow_tpu.web import TestClient


# -- retry / wait ----------------------------------------------------------


def test_run_with_retry_eventually_succeeds():
    calls = {"n": 0}
    slept = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("flake")
        return "ok"

    assert (
        run_with_retry(flaky, retries=3, delay_seconds=1.0, sleep=slept.append)
        == "ok"
    )
    assert slept == [1.0, 2.0]  # exponential backoff


def test_run_with_retry_exhausts():
    def always_fails():
        raise ValueError("nope")

    with pytest.raises(ValueError):
        run_with_retry(always_fails, retries=2, sleep=lambda s: None)


def test_wait_for_timeout():
    t = {"now": 0.0}

    def clock():
        return t["now"]

    def sleep(dt):
        t["now"] += dt

    with pytest.raises(TimeoutError, match="my condition"):
        wait_for(
            lambda: False, timeout_seconds=5, poll_seconds=1,
            desc="my condition", clock=clock, sleep=sleep,
        )


# -- kf_is_ready -----------------------------------------------------------


def test_kf_is_ready_after_full_apply():
    api = FakeApiServer()
    spec = default_spec("kf")
    result = apply_platform(spec, api, FakeCloud(api))
    assert result.succeeded
    assert kf_is_ready(api) == []
    wait_for_deployments(
        api, ["centraldashboard"], timeout_seconds=1, sleep=lambda s: None
    )


def test_kf_is_ready_reports_what_is_missing():
    api = FakeApiServer()
    problems = kf_is_ready(api)
    assert "deployment/tpu-job-operator" in problems
    assert "crd/tpujobs" in problems
    assert missing_deployments(api)  # nothing deployed


# -- junit -----------------------------------------------------------------


def test_junit_xml_well_formed():
    xml = junit_xml(
        "e2e",
        [
            TestResult("passes", 1.5),
            TestResult("fails", 0.2, failure="assert 1 == 2 <oops>"),
        ],
    )
    root = ET.fromstring(xml)
    assert root.attrib["tests"] == "2"
    assert root.attrib["failures"] == "1"
    cases = root.findall("testcase")
    assert cases[0].attrib["name"] == "passes"
    assert cases[1].find("failure").text == "assert 1 == 2 <oops>"


# -- notebook load test ----------------------------------------------------


def test_notebook_loadtest_spawns_and_cleans_up():
    api = FakeApiServer()
    ctl = NotebookController(api)
    lt = NotebookLoadTest(api)
    lt.spawn(10)
    ctl.controller.run_until_idle()
    assert lt.ready_count() == 10
    lt.cleanup()
    assert api.list("Notebook", "loadtest") == []


# -- deploy prober ---------------------------------------------------------


def test_deploy_prober_end_to_end():
    api = FakeApiServer()
    server = DeployServer(api, FakeCloud(api))
    client = TestClient(server)
    # Real clock: the deploy worker is a real background thread, so fake
    # time would burn the poll budget before it runs.
    prober = DeployProber(
        client, sleep=lambda dt: time.sleep(0.05), timeout_seconds=30
    )
    try:
        ok = prober.probe_once(default_spec("probe").to_dict())
        assert ok, "probe should deploy successfully"
        text = prober.metrics.expose_text()
        assert "deployment_service_status 1" in text
        # Second probe of the same spec (idempotent second apply).
        assert prober.probe_once(default_spec("probe").to_dict())
    finally:
        for worker in server._workers.values():
            worker.stop()


def test_deploy_prober_records_failure():
    class BrokenClient:
        def post(self, path, body=None):
            raise ConnectionError("service down")

        def get(self, path):
            raise ConnectionError("service down")

    prober = DeployProber(
        BrokenClient(), clock=lambda: 0.0, sleep=lambda s: None
    )
    assert prober.probe_once(default_spec("x").to_dict()) is False
    assert "deployment_service_status 0" in prober.metrics.expose_text()
    assert "deployment_probe_failures_total 1" in prober.metrics.expose_text()
