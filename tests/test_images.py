"""Notebook image matrix sanity (the tensorflow-notebook-image analog
#21-23): version configs parse, flavors are consistent, and the spawner
menu only offers images the matrix (or contrib set) defines."""

import json
import pathlib
import re

import yaml

REPO = pathlib.Path(__file__).resolve().parent.parent
NOTEBOOK = REPO / "images" / "jax-notebook"
SPAWNER = (
    REPO / "kubeflow_tpu" / "apps" / "config" / "spawner_ui_config.yaml"
)


def test_version_matrix_parses_and_is_consistent():
    versions = sorted((NOTEBOOK / "versions").iterdir())
    assert len(versions) >= 4
    for vdir in versions:
        cfg = json.loads((vdir / "version-config.json").read_text())
        assert "BASE_IMAGE" in cfg and "JAX_SPEC" in cfg, vdir.name
        if vdir.name.endswith("-tpu"):
            assert cfg["JAX_SPEC"].startswith("jax[tpu]"), vdir.name
        else:
            assert "[tpu]" not in cfg["JAX_SPEC"], vdir.name
        # Tag prefix must match the pinned jax minor version.
        tag_prefix = vdir.name.rsplit("-", 1)[0]
        assert re.search(
            rf"jax(\[tpu\])?=={re.escape(tag_prefix)}\.", cfg["JAX_SPEC"]
        ), (vdir.name, cfg["JAX_SPEC"])


def test_every_flavor_has_cpu_and_tpu():
    names = {d.name for d in (NOTEBOOK / "versions").iterdir()}
    prefixes = {n.rsplit("-", 1)[0] for n in names}
    for p in prefixes:
        assert f"{p}-cpu" in names and f"{p}-tpu" in names


def test_spawner_menu_images_exist_in_matrix():
    cfg = yaml.safe_load(SPAWNER.read_text())
    options = cfg["spawnerFormDefaults"]["image"]["options"]
    matrix_tags = {d.name for d in (NOTEBOOK / "versions").iterdir()}
    contrib = {
        f"kubeflow-tpu/{d.name}:latest"
        for d in (REPO / "images" / "contrib").iterdir()
    }
    for image in options:
        if image in contrib:
            continue
        repo_name, _, tag = image.partition(":")
        assert repo_name == "kubeflow-tpu/jax-notebook", image
        assert tag in matrix_tags, (image, sorted(matrix_tags))


def test_dockerfile_contract():
    text = (NOTEBOOK / "Dockerfile").read_text()
    assert "ARG BASE_IMAGE" in text
    assert "NB_USER=jovyan" in text
    assert "8888" in text
    start = (NOTEBOOK / "start.sh").read_text()
    assert "NB_PREFIX" in start  # operator URL-prefix contract
