"""Spawner backend: form → Notebook CR + PVCs → reconciled StatefulSet."""

import pytest

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.rbac import seed_cluster_roles
from kubeflow_tpu.apps.jupyter import TPU_RESOURCE, JupyterApp
from kubeflow_tpu.apps.tensorboards import TensorboardsApp
from kubeflow_tpu.controllers.notebook import NotebookController
from kubeflow_tpu.controllers.tensorboard import TensorboardController
from kubeflow_tpu.testing import FakeApiServer, NotFound
from kubeflow_tpu.web import TestClient

HDR = "x-goog-authenticated-user-email"
USER = "alice@x.co"


@pytest.fixture
def world():
    api = FakeApiServer()
    seed_cluster_roles(api)
    api.create(new_resource("Namespace", "team", ""))
    api.create(
        new_resource(
            "RoleBinding",
            "edit-alice",
            "team",
            spec={
                "roleRef": {"kind": "ClusterRole", "name": "kubeflow-edit"},
                "subjects": [{"kind": "User", "name": USER}],
            },
        )
    )
    nb_ctl = NotebookController(api)
    app = JupyterApp(api)
    client = TestClient(app, headers={HDR: f"accounts.google.com:{USER}"})
    return api, nb_ctl, client


def test_config_served(world):
    _, _, client = world
    cfg = client.get("/api/config").json()["config"]
    assert cfg["tpu"]["resource"] == TPU_RESOURCE
    assert cfg["image"]["options"]


def test_spawn_creates_cr_pvc_and_sts(world):
    api, ctl, client = world
    r = client.post(
        "/api/namespaces/team/notebooks",
        body={
            "name": "nb1",
            "image": "kubeflow-tpu/jax-notebook:0.4-tpu",
            "cpu": "2",
            "memory": "4Gi",
            "tpu": "4",
            "tpuTopology": "2x2",
            "dataVolumes": [
                {"type": "New", "name": "scratch", "size": "5Gi",
                 "mountPath": "/scratch"}
            ],
        },
    )
    assert r.status == 200, r.body
    # PVCs: templated workspace + data volume (default/app.py:36-68).
    assert api.get("PersistentVolumeClaim", "nb1-workspace", "team")
    scratch = api.get("PersistentVolumeClaim", "scratch", "team")
    assert scratch.spec["resources"]["requests"]["storage"] == "5Gi"

    nb = api.get("Notebook", "nb1", "team")
    assert nb.spec["resources"]["limits"][TPU_RESOURCE] == 4
    assert nb.spec["nodeSelector"]["cloud.google.com/tpu-topology"] == "2x2"

    ctl.controller.run_until_idle()
    sts = api.get("StatefulSet", "nb1", "team")
    pod_spec = sts.spec["template"]["spec"]
    mounts = pod_spec["containers"][0]["volumeMounts"]
    assert {m["mountPath"] for m in mounts} == {
        "/home/jovyan", "/scratch", "/dev/shm"
    }
    names = {v["name"] for v in pod_spec["volumes"]}
    assert names == {"nb1-workspace", "scratch", "dshm"}


def test_spawn_respects_readonly_field(world):
    api, _, client = world
    # Pin the image server-side; the client's choice must be ignored —
    # through BOTH the image field and the customImage escape hatch.
    app = JupyterApp(api)
    app.config["image"]["readOnly"] = True
    pinned = app.config["image"]["value"]
    c = TestClient(app, headers={HDR: f"accounts.google.com:{USER}"})
    c.post(
        "/api/namespaces/team/notebooks",
        body={"name": "nb2", "image": "evil/image:latest"},
    )
    assert api.get("Notebook", "nb2", "team").spec["image"] == pinned
    c.post(
        "/api/namespaces/team/notebooks",
        body={"name": "nb2b", "customImage": "evil/image:latest"},
    )
    assert api.get("Notebook", "nb2b", "team").spec["image"] == pinned


def test_custom_image_honored_when_not_pinned(world):
    api, _, client = world
    client.post(
        "/api/namespaces/team/notebooks",
        body={"name": "nb2c", "customImage": "my/研究:latest"},
    )
    assert api.get("Notebook", "nb2c", "team").spec["image"] == "my/研究:latest"


def test_bad_tpu_count_is_400(world):
    _, _, client = world
    r = client.post(
        "/api/namespaces/team/notebooks", body={"name": "nbx", "tpu": "two"}
    )
    assert r.status == 400


def test_list_stop_start_delete(world):
    api, ctl, client = world
    client.post("/api/namespaces/team/notebooks", body={"name": "nb1"})
    ctl.controller.run_until_idle()

    [row] = client.get("/api/namespaces/team/notebooks").json()["notebooks"]
    assert row["name"] == "nb1" and row["status"] == "waiting"

    # Stop: annotation lands, STS scales to 0 (culler.go:37 semantics).
    assert (
        client.patch(
            "/api/namespaces/team/notebooks/nb1", body={"stopped": True}
        ).status
        == 200
    )
    ctl.controller.run_until_idle()
    assert api.get("StatefulSet", "nb1", "team").spec["replicas"] == 0
    [row] = client.get("/api/namespaces/team/notebooks").json()["notebooks"]
    assert row["status"] == "stopped"

    # Restart.
    client.patch("/api/namespaces/team/notebooks/nb1", body={"stopped": False})
    ctl.controller.run_until_idle()
    assert api.get("StatefulSet", "nb1", "team").spec["replicas"] == 1

    # Delete cascades the STS via ownerReferences.
    client.delete("/api/namespaces/team/notebooks/nb1")
    ctl.controller.run_until_idle()
    with pytest.raises(NotFound):
        api.get("StatefulSet", "nb1", "team")
    # The workspace PVC survives deletion (PVC-backed workspaces outlive
    # the notebook, SURVEY.md §5 checkpoint row).
    assert api.get("PersistentVolumeClaim", "nb1-workspace", "team")


def test_poddefault_labels_flow_to_pod_template(world):
    api, ctl, client = world
    api.create(
        new_resource(
            "PodDefault",
            "tpu-tools",
            "team",
            spec={
                "selector": {"matchLabels": {"tpu-tools": "true"}},
                "desc": "mount TPU profiling tools",
            },
        )
    )
    pds = client.get("/api/namespaces/team/poddefaults").json()["poddefaults"]
    assert pds[0]["name"] == "tpu-tools"

    client.post(
        "/api/namespaces/team/notebooks",
        body={"name": "nb3", "configurations": ["tpu-tools"]},
    )
    ctl.controller.run_until_idle()
    sts = api.get("StatefulSet", "nb3", "team")
    assert sts.spec["template"]["metadata"]["labels"]["tpu-tools"] == "true"


def test_reserved_selector_label_cannot_be_overridden(world):
    """A PodDefault named 'notebook' must not clobber the STS selector."""
    api, ctl, client = world
    client.post(
        "/api/namespaces/team/notebooks",
        body={"name": "nb4", "configurations": ["notebook"]},
    )
    ctl.controller.run_until_idle()
    sts = api.get("StatefulSet", "nb4", "team")
    assert sts.spec["template"]["metadata"]["labels"]["notebook"] == "nb4"


def test_authz_denied_outside_namespace(world):
    _, _, client = world
    r = client.post("/api/namespaces/other/notebooks", body={"name": "nb"})
    assert r.status == 403


def test_tensorboards_crud(world):
    api, _, _ = world
    tb_ctl = TensorboardController(api)
    app = TensorboardsApp(api)
    c = TestClient(app, headers={HDR: f"accounts.google.com:{USER}"})

    r = c.post(
        "/api/namespaces/team/tensorboards",
        body={"name": "tb1", "logspath": "pvc://nb1-workspace/logs"},
    )
    assert r.status == 200, r.body
    tb_ctl.controller.run_until_idle()
    assert api.get("Deployment", "tb1", "team")

    rows = c.get("/api/namespaces/team/tensorboards").json()["tensorboards"]
    assert rows[0]["logspath"] == "pvc://nb1-workspace/logs"

    assert c.delete("/api/namespaces/team/tensorboards/tb1").status == 200
    tb_ctl.controller.run_until_idle()
    with pytest.raises(NotFound):
        api.get("Deployment", "tb1", "team")

    assert c.post("/api/namespaces/team/tensorboards", body={"name": "x"}).status == 400


# -- snapshots: the rok-variant flow ---------------------------------------


def _spawn(client, name, **extra):
    body = {
        "name": name,
        "image": "kubeflow-tpu/jax-notebook:latest",
        "cpu": "1",
        "memory": "1Gi",
        "tpu": "0",
        "workspaceVolume": {
            "type": "New", "name": "{name}-workspace", "size": "5Gi",
            "mountPath": "/home/jovyan", "accessMode": "ReadWriteOnce",
        },
        "configurations": [],
    }
    body.update(extra)
    return client.post("/api/namespaces/team/notebooks", body)


def test_snapshot_and_restore_flow(world):
    """The rok flow end-to-end: spawn → snapshot the workspace → spawn a
    second notebook restoring from the snapshot (PVC dataSource)."""
    api, nb_ctl, client = world
    assert _spawn(client, "nb1").status == 200

    resp = client.post(
        "/api/namespaces/team/snapshots",
        {"pvc": "nb1-workspace", "name": "snap1"},
    )
    assert resp.status == 200, resp.json()
    snap = resp.json()["snapshot"]
    assert snap["status"]["readyToUse"] is True
    assert snap["status"]["restoreSize"] == "5Gi"

    listed = client.get("/api/namespaces/team/snapshots").json()["snapshots"]
    assert [s["name"] for s in listed] == ["snap1"]
    assert listed[0]["ready"] and listed[0]["source"] == "nb1-workspace"

    assert _spawn(
        client, "nb2",
        workspaceVolume={
            "type": "Snapshot", "name": "{name}-workspace",
            "snapshot": "snap1", "mountPath": "/home/jovyan",
        },
    ).status == 200
    pvc = api.get("PersistentVolumeClaim", "nb2-workspace", "team")
    assert pvc.spec["dataSource"] == {
        "kind": "VolumeSnapshot", "name": "snap1"
    }
    # Size restored from the snapshot when the form didn't give one.
    assert pvc.spec["resources"]["requests"]["storage"] == "5Gi"

    assert client.delete("/api/namespaces/team/snapshots/snap1").status == 200
    assert client.get("/api/namespaces/team/snapshots").json()["snapshots"] == []


def test_snapshot_error_paths(world):
    api, _, client = world
    # Snapshot of a PVC that doesn't exist.
    assert client.post(
        "/api/namespaces/team/snapshots", {"pvc": "nope"}
    ).status == 404
    assert client.post(
        "/api/namespaces/team/snapshots", {}
    ).status == 400
    # Restore from a missing snapshot.
    assert _spawn(
        client, "nb3",
        workspaceVolume={"type": "Snapshot", "name": "{name}-workspace",
                         "snapshot": "ghost"},
    ).status == 400
    # Restore from a not-ready snapshot.
    _spawn(client, "nb4")
    client.post("/api/namespaces/team/snapshots",
                {"pvc": "nb4-workspace", "name": "cold"})
    snap = api.get("VolumeSnapshot", "cold", "team").thaw()
    snap.status["readyToUse"] = False
    api.update_status(snap)
    assert _spawn(
        client, "nb5",
        workspaceVolume={"type": "Snapshot", "name": "{name}-workspace",
                         "snapshot": "cold"},
    ).status == 400
    # Snapshot volume without a snapshot name.
    assert _spawn(
        client, "nb6",
        workspaceVolume={"type": "Snapshot", "name": "{name}-workspace"},
    ).status == 400


def test_snapshot_restore_onto_existing_pvc_is_409(world):
    """Restoring onto a name whose PVC already exists must fail loudly —
    silently reusing the old claim would skip the restore entirely."""
    _, _, client = world
    _spawn(client, "nb7")  # creates nb7-workspace
    client.post("/api/namespaces/team/snapshots",
                {"pvc": "nb7-workspace", "name": "s7"})
    client.delete("/api/namespaces/team/notebooks/nb7")
    resp = _spawn(
        client, "nb7",
        workspaceVolume={"type": "Snapshot", "name": "{name}-workspace",
                         "snapshot": "s7"},
    )
    assert resp.status == 409, resp.json()
