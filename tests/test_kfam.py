"""kfam access-management API: profiles, contributor bindings, authz."""

import pytest

from kubeflow_tpu.api.rbac import (
    make_cluster_role_binding,
    seed_cluster_roles,
    subject_access_review,
)
from kubeflow_tpu.apps.kfam import KfamApp
from kubeflow_tpu.controllers.profile import ProfileController
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.web import TestClient

HDR = "x-goog-authenticated-user-email"


def client(app, user):
    return TestClient(app, headers={HDR: f"accounts.google.com:{user}"})


@pytest.fixture
def world():
    api = FakeApiServer()
    seed_cluster_roles(api)
    api.create(make_cluster_role_binding("admin", "kubeflow-admin", "admin@x.co"))
    ctl = ProfileController(api)
    app = KfamApp(api)
    return api, ctl, app


def test_create_profile_self_service(world):
    api, ctl, app = world
    r = client(app, "alice@x.co").post(
        "/kfam/v1/profiles", body={"metadata": {"name": "alice"}}
    )
    assert r.status == 200, r.body
    ctl.controller.run_until_idle()
    assert api.get("Namespace", "alice", "").metadata.annotations["owner"] == (
        "alice@x.co"
    )


def test_cannot_create_profile_for_other_user(world):
    _, _, app = world
    r = client(app, "mallory@x.co").post(
        "/kfam/v1/profiles",
        body={
            "metadata": {"name": "victim"},
            "spec": {"owner": {"kind": "User", "name": "alice@x.co"}},
        },
    )
    assert r.status == 403


def test_admin_can_create_for_other_user(world):
    api, ctl, app = world
    r = client(app, "admin@x.co").post(
        "/kfam/v1/profiles",
        body={
            "metadata": {"name": "bob"},
            "spec": {"owner": {"kind": "User", "name": "bob@x.co"}},
        },
    )
    assert r.status == 200
    assert api.get("Profile", "bob").spec["owner"]["name"] == "bob@x.co"


def test_contributor_binding_lifecycle(world):
    api, ctl, app = world
    client(app, "alice@x.co").post(
        "/kfam/v1/profiles", body={"metadata": {"name": "alice"}}
    )
    ctl.controller.run_until_idle()

    # Owner shares her namespace with bob as editor.
    binding = {
        "user": {"kind": "User", "name": "bob@x.co"},
        "referredNamespace": "alice",
        "roleRef": {"kind": "ClusterRole", "name": "edit"},
    }
    r = client(app, "alice@x.co").post("/kfam/v1/bindings", body=binding)
    assert r.status == 200, r.body

    # The pair exists: RBAC + mesh policy (bindings.go:76-128 parity).
    # The namespace also carries the profile controller's ns-owner policy
    # (profile_controller.go:190 parity) — select the contributor's.
    assert subject_access_review(api, "bob@x.co", "create", "notebooks", "alice")
    [ap] = [
        p for p in api.list("AuthorizationPolicy", "alice")
        if p.metadata.name != "ns-owner"
    ]
    assert ap.spec["rules"][0]["from"][0]["source"]["principals"] == ["bob@x.co"]

    listed = client(app, "alice@x.co").get("/kfam/v1/bindings?namespace=alice")
    assert [b["user"]["name"] for b in listed.json()["bindings"]] == ["bob@x.co"]

    # DELETE requires the binding in the body; bodyless is a 400.
    assert client(app, "alice@x.co").delete("/kfam/v1/bindings").status == 400
    r = client(app, "alice@x.co").request(
        "DELETE", "/kfam/v1/bindings", body=binding
    )
    assert r.status == 200
    assert not subject_access_review(
        api, "bob@x.co", "create", "notebooks", "alice"
    )
    assert [p.metadata.name for p in api.list("AuthorizationPolicy", "alice")] == ["ns-owner"]


def test_non_owner_cannot_bind(world):
    api, ctl, app = world
    client(app, "alice@x.co").post(
        "/kfam/v1/profiles", body={"metadata": {"name": "alice"}}
    )
    ctl.controller.run_until_idle()
    r = client(app, "mallory@x.co").post(
        "/kfam/v1/bindings",
        body={
            "user": {"kind": "User", "name": "mallory@x.co"},
            "referredNamespace": "alice",
            "roleRef": {"kind": "ClusterRole", "name": "edit"},
        },
    )
    assert r.status == 403


def test_query_cluster_admin(world):
    _, _, app = world
    assert client(app, "admin@x.co").get("/kfam/v1/role/clusteradmin").json() is True
    assert (
        client(app, "alice@x.co")
        .get("/kfam/v1/role/clusteradmin?user=alice@x.co")
        .json()
        is False
    )


def test_profile_delete_cascades_contributor_bindings(world):
    """Deleting a profile must not leave grants behind for a future
    same-named profile (the bindings are owner-ref'd to the Namespace)."""
    api, ctl, app = world
    client(app, "alice@x.co").post(
        "/kfam/v1/profiles", body={"metadata": {"name": "team"}}
    )
    ctl.controller.run_until_idle()
    client(app, "alice@x.co").post(
        "/kfam/v1/bindings",
        body={
            "user": {"kind": "User", "name": "bob@x.co"},
            "referredNamespace": "team",
            "roleRef": {"kind": "ClusterRole", "name": "edit"},
        },
    )
    assert subject_access_review(api, "bob@x.co", "create", "notebooks", "team")

    r = client(app, "alice@x.co").delete("/kfam/v1/profiles/team")
    assert r.status == 200
    ctl.controller.run_until_idle()
    assert api.list("RoleBinding", "team") == []
    assert api.list("AuthorizationPolicy", "team") == []
    assert not subject_access_review(
        api, "bob@x.co", "create", "notebooks", "team"
    )


def test_read_bindings_scoped_for_non_admins(world):
    api, ctl, app = world
    client(app, "alice@x.co").post(
        "/kfam/v1/profiles", body={"metadata": {"name": "alice"}}
    )
    ctl.controller.run_until_idle()
    # Unscoped enumeration by a non-admin is forbidden.
    assert client(app, "mallory@x.co").get("/kfam/v1/bindings").status == 403
    # Your own bindings are always visible; admins see everything.
    assert (
        client(app, "mallory@x.co")
        .get("/kfam/v1/bindings?user=mallory@x.co")
        .status
        == 200
    )
    assert client(app, "admin@x.co").get("/kfam/v1/bindings").status == 200


def test_binding_names_do_not_collide(world):
    from kubeflow_tpu.apps.kfam import _binding_name

    assert _binding_name("bob@x.co", "edit") != _binding_name("bob.x.co", "edit")


def test_client_cannot_override_owner_via_spec(world):
    api, ctl, app = world
    r = client(app, "alice@x.co").post(
        "/kfam/v1/profiles",
        body={"metadata": {"name": "sneaky"}, "spec": {"owner": None}},
    )
    assert r.status == 200
    assert api.get("Profile", "sneaky").spec["owner"]["name"] == "alice@x.co"


def test_unsupported_role_rejected(world):
    _, ctl, app = world
    client(app, "alice@x.co").post(
        "/kfam/v1/profiles", body={"metadata": {"name": "alice"}}
    )
    ctl.controller.run_until_idle()
    r = client(app, "alice@x.co").post(
        "/kfam/v1/bindings",
        body={
            "user": {"kind": "User", "name": "bob@x.co"},
            "referredNamespace": "alice",
            "roleRef": {"kind": "ClusterRole", "name": "admin"},
        },
    )
    assert r.status == 400
