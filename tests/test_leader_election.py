"""Leader election + write fencing.

The reference gets this from controller-runtime for free — every
controller ships `-enable-leader-election`
(`notebook-controller/main.go:51-62`, `profile-controller/main.go:52-69`)
so N replicas run with exactly one active. These tests pin our
equivalent: Lease CAS acquisition (two candidates can never both win a
term), expiry-driven takeover within the lease TTL, graceful release,
step-down on renewal failure, and the part K8s itself does NOT give you —
lease-generation write fencing at the storage boundary, so a deposed
leader's in-flight writes land as Conflicts, not corruption. The
process-level half (SIGKILL the leader, standby takes over, no duplicate
side effects) lives in tests/e2e/test_leader_ha_e2e.py.
"""

import threading
import time

import pytest

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.controllers.leader import LEASE_KIND, LeaderElector
from kubeflow_tpu.testing.apiserver_http import ApiServerApp, HttpApiClient
from kubeflow_tpu.testing.fake_apiserver import Conflict, FakeApiServer
from kubeflow_tpu.web.wsgi import serve


def _elector(api, identity, **kw):
    kw.setdefault("lease_duration", 0.6)
    kw.setdefault("renew_deadline", 0.4)
    kw.setdefault("retry_period", 0.05)
    return LeaderElector(api, "test-controller", identity, **kw)


def _backdate(api, name="test-controller", by=10.0):
    """Simulate the holder going silent for `by` seconds (crash or
    partition) without waiting wall-clock time."""
    lease = api.get(LEASE_KIND, name, "").thaw()
    lease.spec["renewTime"] = time.time() - by
    api.update(lease)


def test_first_candidate_creates_and_holds():
    api = FakeApiServer()
    a = _elector(api, "replica-a")
    assert a._try_acquire_or_renew()
    assert a.transitions == 1
    lease = api.get(LEASE_KIND, "test-controller", "")
    assert lease.spec["holderIdentity"] == "replica-a"


def test_standby_cannot_steal_live_lease():
    api = FakeApiServer()
    a, b = _elector(api, "a"), _elector(api, "b")
    assert a._try_acquire_or_renew()
    assert not b._try_acquire_or_renew()
    # Holder renews freely; generation is stable within a term.
    assert a._try_acquire_or_renew()
    assert a.transitions == 1


def test_expired_lease_transfers_with_new_generation():
    api = FakeApiServer()
    a, b = _elector(api, "a"), _elector(api, "b")
    assert a._try_acquire_or_renew()
    _backdate(api)
    assert b._try_acquire_or_renew()
    assert b.transitions == 2  # new term = new fencing token
    # The deposed holder cannot renew into the new term.
    assert not a._try_acquire_or_renew()


def test_concurrent_candidates_one_winner():
    """CAS property: N candidates racing for an expired lease produce
    exactly one winner per round (resourceVersion preconditions)."""
    api = FakeApiServer()
    seed = _elector(api, "seed")
    assert seed._try_acquire_or_renew()
    _backdate(api)
    candidates = [_elector(api, f"c{i}") for i in range(8)]
    barrier = threading.Barrier(len(candidates))
    wins = []

    def race(e):
        barrier.wait()
        if e._try_acquire_or_renew():
            wins.append(e.identity)

    threads = [threading.Thread(target=race, args=(e,)) for e in candidates]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=5)
    assert len(wins) == 1, wins


def test_hold_steps_down_when_deposed():
    api = FakeApiServer()
    a, b = _elector(api, "a"), _elector(api, "b")
    stop = threading.Event()
    assert a.acquire(stop)
    _backdate(api)
    assert b._try_acquire_or_renew()
    t0 = time.monotonic()
    a.hold(stop)  # returns only on loss (stop never set)
    assert not a.is_leading()
    assert time.monotonic() - t0 < 5.0


def test_release_enables_instant_takeover():
    api = FakeApiServer()
    a, b = _elector(api, "a"), _elector(api, "b")
    stop = threading.Event()
    assert a.acquire(stop)
    a.release()
    # No TTL wait: the cleared holder is immediately acquirable.
    assert b._try_acquire_or_renew()
    assert b.transitions == 2


def test_run_reports_loss_vs_clean_stop():
    api = FakeApiServer()
    a = _elector(api, "a")
    stop = threading.Event()
    started = threading.Event()
    result = {}

    def runner():
        result["lost"] = a.run(stop, lambda e: started.set())

    t = threading.Thread(target=runner)
    t.start()
    assert started.wait(5)
    stop.set()
    t.join(timeout=5)
    assert result["lost"] is False  # clean stop, not deposition

    b = _elector(api, "b")
    stop2 = threading.Event()
    started2 = threading.Event()

    def runner2():
        result["lost2"] = b.run(stop2, lambda e: started2.set())

    t2 = threading.Thread(target=runner2)
    t2.start()
    assert started2.wait(5)
    _backdate(api)
    c = _elector(api, "c")
    assert c._try_acquire_or_renew()
    t2.join(timeout=10)
    assert result["lost2"] is True  # deposed → caller must exit


# -- fencing ---------------------------------------------------------------


def test_fenced_write_rejected_in_process():
    api = FakeApiServer()
    a = _elector(api, "a")
    assert a._try_acquire_or_renew()
    guard = ("", "test-controller", "a", a.transitions)
    # Guarded writes land while the term is live.
    api.create(new_resource("Widget", "w1"), lease_guard=guard)
    # Depose a; the old guard now fences every write form.
    _backdate(api)
    b = _elector(api, "b")
    assert b._try_acquire_or_renew()
    with pytest.raises(Conflict, match="fenced"):
        api.create(new_resource("Widget", "w2"), lease_guard=guard)
    w1 = api.get("Widget", "w1").thaw()
    w1.spec["touched"] = True
    with pytest.raises(Conflict, match="fenced"):
        api.update(w1, lease_guard=guard)
    with pytest.raises(Conflict, match="fenced"):
        api.update_status(w1, lease_guard=guard)
    with pytest.raises(Conflict, match="fenced"):
        api.delete("Widget", "w1", lease_guard=guard)
    with pytest.raises(Conflict, match="fenced"):
        api.apply(new_resource("Widget", "w1", spec={"v": 2}),
                  lease_guard=guard)
    # The new term's guard works.
    guard_b = ("", "test-controller", "b", b.transitions)
    api.create(new_resource("Widget", "w2"), lease_guard=guard_b)


def test_lease_writes_exempt_from_fencing():
    """The election protocol must stay able to transfer ownership: a
    renewal/acquisition is never fenced by a stale guard the same client
    still has armed."""
    api = FakeApiServer()
    a = _elector(api, "a")
    assert a._try_acquire_or_renew()
    lease = api.get(LEASE_KIND, "test-controller", "").thaw()
    lease.spec["renewTime"] = time.time()
    # Stale guard on a Lease write: exempt, must succeed.
    api.update(lease, lease_guard=("", "test-controller", "zombie", 99))


def test_fencing_over_http_facade():
    """The partition story end-to-end over the real transport: leader A
    arms its guard on the client; A goes silent (backdated lease); B
    acquires; A's resumed write is rejected with Conflict while B's
    writes land."""
    api = FakeApiServer()
    server, _ = serve(ApiServerApp(api), host="127.0.0.1", port=0)
    base = f"http://127.0.0.1:{server.server_port}"
    client_a = HttpApiClient(base)
    client_b = HttpApiClient(base)
    try:
        a = _elector(client_a, "a")
        assert a._try_acquire_or_renew()
        client_a.set_lease_guard(("", "test-controller", "a",
                                  a.transitions))
        client_a.create(new_resource("Widget", "pre-partition"))
        _backdate(api)
        b = _elector(client_b, "b")
        assert b._try_acquire_or_renew()
        client_b.set_lease_guard(("", "test-controller", "b",
                                  b.transitions))
        with pytest.raises(Conflict, match="fenced"):
            client_a.create(new_resource("Widget", "stale-write"))
        client_b.create(new_resource("Widget", "successor-write"))
        names = {w.metadata.name for w in api.list("Widget")}
        assert names == {"pre-partition", "successor-write"}
    finally:
        client_a.close()
        client_b.close()
        server.shutdown()


def test_hold_treats_term_change_as_loss():
    """A leader that silently lost and RE-acquired (new generation)
    while parked must step down, not carry on: its armed fencing guard
    is from the dead term and every guarded write would Conflict forever
    — a livelock, since its renewals (exempt) would keep the lease."""
    api = FakeApiServer()
    a = _elector(api, "a")
    stop = threading.Event()
    assert a.acquire(stop)
    first_term = a.transitions
    # Simulate the parked leader's world moving on: b takes an expired
    # lease (gen+1), then releases; a's next renewal re-acquires gen+2.
    _backdate(api)
    b = _elector(api, "b")
    assert b._try_acquire_or_renew()
    b.release()
    t0 = time.monotonic()
    a.hold(stop)  # must return as LOSS despite successful re-acquisition
    assert not a.is_leading()
    assert a.transitions != first_term
    assert time.monotonic() - t0 < 5.0
