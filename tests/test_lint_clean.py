"""Tier-1 gate: the full kftpu-lint AST engine runs clean on the repo.

One test, the whole engine, every rule: any unsuppressed,
un-baselined finding in `kubeflow_tpu/` (or the e2e workers, for the
rules scoped there) fails CI with the exact file:line list. This is
the same run as `python -m kubeflow_tpu.ci lint` — keep them in sync
by construction (both call `lint_repo`).
"""

import subprocess
import sys

from kubeflow_tpu.ci.lint import lint_repo


def test_repo_lint_clean():
    result = lint_repo()
    assert result.clean, "\n" + result.render()


def test_repo_lint_clean_with_concurrency():
    """The whole-program concurrency pass (lock-order graph,
    blocking-under-lock, cv-wait, leaks, untimed joins) also runs clean
    — every rollout finding was FIXED, not baselined, so the shipped
    baseline stays empty."""
    result = lint_repo(concurrency=True)
    assert result.clean, "\n" + result.render()


def test_repo_lint_output_is_byte_stable():
    """Deflake guard: two full engine runs render identical bytes
    (sorted findings, sorted file discovery, __pycache__/generated
    skipped deterministically)."""
    a, b = lint_repo(), lint_repo()
    assert a.render() == b.render()
    assert a.to_json() == b.to_json()


def test_concurrency_lint_output_is_byte_stable():
    """The concurrency pass iterates fixed-point summaries and a global
    edge graph — all of it over sorted keys, so two runs must render
    identical bytes too."""
    a = lint_repo(concurrency=True)
    b = lint_repo(concurrency=True)
    assert a.render() == b.render()
    assert a.to_json() == b.to_json()


def test_lint_cli_exits_zero_on_clean_repo():
    """The acceptance-criteria invocation, exactly as CI runs it."""
    result = subprocess.run(
        [sys.executable, "-m", "kubeflow_tpu.ci", "lint"],
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert "0 finding(s)" in result.stdout


def test_lint_cli_concurrency_exits_zero_on_clean_repo():
    """The concurrency acceptance invocation, exactly as CI runs it."""
    result = subprocess.run(
        [
            sys.executable, "-m", "kubeflow_tpu.ci", "lint",
            "--concurrency",
        ],
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert "0 finding(s)" in result.stdout


def test_lint_cli_json_and_rule_flags():
    import json

    result = subprocess.run(
        [
            sys.executable, "-m", "kubeflow_tpu.ci", "lint", "--json",
            "--rule", "no-bare-except",
        ],
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    doc = json.loads(result.stdout)
    assert doc["findings"] == []


def test_lint_cli_list_rules_names_the_catalog():
    result = subprocess.run(
        [
            sys.executable, "-m", "kubeflow_tpu.ci", "lint",
            "--list-rules",
        ],
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stderr
    for rule in (
        "host-sync-in-jit", "thaw-before-mutate", "lock-discipline",
        "no-bare-except", "no-interrupt-swallow",
        "no-deepcopy-hot-path", "endpoint-list-clients",
        "scalar-psum-only", "flash-blockwise", "fused-kernel-streams",
        "lock-order-cycle", "blocking-under-lock", "cv-wait-no-loop",
        "lock-leak", "untimed-join",
    ):
        assert rule in result.stdout, result.stdout
