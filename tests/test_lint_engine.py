"""kftpu-lint engine + per-rule fixture tests (ISSUE 8).

Every shipped rule demonstrates a fixture-verified true positive AND
true negative (`tests/lint_fixtures/<case>/` is a miniature repo tree,
so path-scoped rules see realistic paths), plus the suppression,
unused-suppression, baseline, generated-file and determinism
machinery.
"""

import json
import pathlib

import pytest

from kubeflow_tpu.ci.lint import all_rules, lint_files
from kubeflow_tpu.ci.lint.engine import (
    CONCURRENCY_RULE_IDS,
    Finding,
    load_baseline,
)

FIXTURES = pathlib.Path(__file__).resolve().parent / "lint_fixtures"


def run_case(case: str, rules=None, baseline=None):
    root = FIXTURES / case
    assert root.is_dir(), f"missing fixture tree {root}"
    return lint_files(
        sorted(root.rglob("*.py")), root=root, rules=rules,
        baseline=baseline,
    )


# -- per-rule true positives / true negatives -------------------------------

TP_CASES = [
    # (fixture tree, rule id, expected finding count)
    ("host_sync_tp", "host-sync-in-jit", 5),
    ("thaw_tp", "thaw-before-mutate", 4),
    ("lock_tp", "lock-discipline", 4),
    ("bare_except_tp", "no-bare-except", 2),
    ("interrupt_tp", "no-interrupt-swallow", 2),
    ("deepcopy_tp", "no-deepcopy-hot-path", 2),
    # A renamed hot path must not silently drop its guard.
    ("deepcopy_missing", "no-deepcopy-hot-path", 1),
    ("endpoint_tp", "endpoint-list-clients", 6),
    # Config threaded through a helper param: caught by the file-level
    # backstop (config-driven entry point, no endpoints_from_env).
    ("endpoint_backstop", "endpoint-list-clients", 1),
    ("psum_tp", "scalar-psum-only", 1),
    ("flash_tp", "flash-blockwise", 2),
    ("fused_tp", "fused-kernel-streams", 1),
    # Whole-program concurrency pass (auto-enabled when named in rules=).
    ("lock_order_tp", "lock-order-cycle", 1),
    # One direct prim + one reached through an intra-class call.
    ("blocking_lock_tp", "blocking-under-lock", 2),
    ("cv_wait_tp", "cv-wait-no-loop", 1),
    ("lock_leak_tp", "lock-leak", 1),
    # Thread join + queue join, both untimed.
    ("untimed_join_tp", "untimed-join", 2),
]

TN_CASES = [
    ("host_sync_tn", "host-sync-in-jit"),
    ("thaw_tn", "thaw-before-mutate"),
    ("lock_tn", "lock-discipline"),
    ("bare_except_tn", "no-bare-except"),
    ("interrupt_tn", "no-interrupt-swallow"),
    ("deepcopy_tn", "no-deepcopy-hot-path"),
    ("endpoint_tn", "endpoint-list-clients"),
    ("psum_tn", "scalar-psum-only"),
    ("flash_tn", "flash-blockwise"),
    ("flash_tn", "fused-kernel-streams"),
    ("lock_order_tn", "lock-order-cycle"),
    ("blocking_lock_tn", "blocking-under-lock"),
    ("cv_wait_tn", "cv-wait-no-loop"),
    ("lock_leak_tn", "lock-leak"),
    ("untimed_join_tn", "untimed-join"),
]


@pytest.mark.parametrize("case,rule,count", TP_CASES)
def test_rule_true_positive(case, rule, count):
    result = run_case(case, rules=[rule])
    got = [f for f in result.findings if f.rule == rule]
    assert len(got) == count, result.render()
    # Findings carry real line numbers inside the fixture file.
    assert all(f.line > 0 for f in got)


@pytest.mark.parametrize("case,rule", TN_CASES)
def test_rule_true_negative(case, rule):
    result = run_case(case, rules=[rule])
    assert result.clean, result.render()


def test_every_shipped_rule_has_fixture_coverage():
    """The catalog contract: a rule without a true-positive fixture is
    a rule nobody proved fires."""
    covered = {rule for _, rule, _ in TP_CASES}
    shipped = set(all_rules()) | set(CONCURRENCY_RULE_IDS)
    assert shipped == covered, shipped ^ covered


# -- suppressions -----------------------------------------------------------


def test_suppression_silences_the_finding():
    result = run_case("suppressed")
    assert result.clean, result.render()
    assert [f.rule for f in result.suppressed] == ["no-bare-except"]


def test_unused_suppression_is_a_finding():
    result = run_case("unused_suppression")
    assert [f.rule for f in result.findings] == ["unused-suppression"]


def test_unknown_rule_in_disable_comment_is_flagged(tmp_path):
    tree = tmp_path / "kubeflow_tpu" / "web"
    tree.mkdir(parents=True)
    (tree / "x.py").write_text(
        '"""Doc."""\nx = 1  # kftpu-lint: disable=no-such-rule\n'
    )
    result = lint_files(
        [tree / "x.py"], root=tmp_path, baseline=None
    )
    assert [f.rule for f in result.findings] == ["unused-suppression"]
    assert "no-such-rule" in result.findings[0].message


def test_generated_files_are_skipped():
    result = run_case("generated")
    assert result.clean and not result.suppressed, result.render()


def test_disable_syntax_quoted_in_a_string_is_not_a_suppression():
    """Documentation showing the suppression syntax inside a string
    literal must neither suppress nor count as unused."""
    result = run_case("suppression_in_string")
    assert result.clean and not result.suppressed, result.render()


def test_pycache_is_skipped(tmp_path):
    from kubeflow_tpu.ci.lint.engine import default_files

    pkg = tmp_path / "kubeflow_tpu" / "__pycache__"
    pkg.mkdir(parents=True)
    (pkg / "junk.py").write_text("except_me = True\n")
    (tmp_path / "kubeflow_tpu" / "ok.py").write_text('"""Doc."""\n')
    files = default_files(tmp_path)
    assert [p.name for p in files] == ["ok.py"]


# -- baseline ---------------------------------------------------------------


def _write_baseline(path: pathlib.Path, entries) -> pathlib.Path:
    path.write_text(json.dumps({"version": 1, "findings": entries}))
    return path


def test_baseline_grandfathers_matching_findings(tmp_path):
    baseline = _write_baseline(
        tmp_path / "b.json",
        [
            {
                "path": "kubeflow_tpu/parallel/pipeline.py",
                "rule": "scalar-psum-only",
                "message": (
                    "`lax.psum(outputs, ...)` — the pipeline hot "
                    "path's only cross-pp all-reduce is the scalar "
                    "loss (docs/perf.md)"
                ),
                "why": "fixture: grandfathered for this test",
            }
        ],
    )
    result = run_case("psum_tp", baseline=baseline)
    assert result.clean, result.render()
    assert [f.rule for f in result.baselined] == ["scalar-psum-only"]


def test_stale_baseline_entry_is_a_finding(tmp_path):
    baseline = _write_baseline(
        tmp_path / "b.json",
        [
            {
                "path": "kubeflow_tpu/parallel/pipeline.py",
                "rule": "scalar-psum-only",
                "message": "does not match anything",
                "why": "obsolete",
            }
        ],
    )
    result = run_case("psum_tn", baseline=baseline)
    assert [f.rule for f in result.findings] == ["stale-baseline"]


def test_baseline_entry_requires_written_justification(tmp_path):
    baseline = _write_baseline(
        tmp_path / "b.json",
        [{"path": "a.py", "rule": "r", "message": "m"}],  # no `why`
    )
    with pytest.raises(ValueError, match="justification"):
        load_baseline(baseline)


def test_unknown_rule_filter_raises():
    with pytest.raises(ValueError, match="unknown rule"):
        run_case("psum_tn", rules=["not-a-rule"])


# -- determinism (the deflake guard) ---------------------------------------


def test_output_is_byte_stable_and_order_independent():
    """Same tree, two runs, reversed input order: identical rendered
    bytes — lint output must never depend on filesystem enumeration
    or dict ordering."""
    root = FIXTURES / "endpoint_tp"
    files = sorted(root.rglob("*.py"))
    a = lint_files(files, root=root, baseline=None)
    b = lint_files(list(reversed(files)), root=root, baseline=None)
    assert a.render() == b.render()
    assert a.to_json() == b.to_json()
    # Findings are sorted on the full (path, line, rule, message) key.
    assert a.findings == sorted(a.findings)


def test_findings_render_file_line_rule():
    f = Finding("kubeflow_tpu/x.py", 3, "no-bare-except", "msg")
    assert f.render() == "kubeflow_tpu/x.py:3: [no-bare-except] msg"
    assert f.to_dict() == {
        "path": "kubeflow_tpu/x.py",
        "line": 3,
        "rule": "no-bare-except",
        "message": "msg",
    }
