"""Open-loop load harness (ISSUE 17): the generator that drives
`bench.py --workload serving`.

An open-loop harness is only trustworthy if (a) its schedules are
deterministic (seeded — chaos replays and CI reruns see the same
arrival process), (b) its merge arithmetic is exact, and (c) the
multi-process engine actually holds an offered rate instead of
silently degrading into a closed loop (coordinated omission). The
bench's `serving_offered_rate_error` row gates (c) at scale; these
tests pin the mechanics at unit size.
"""

import time

import pytest

from kubeflow_tpu.testing import loadgen
from kubeflow_tpu.testing.loadgen import (
    ERROR,
    OK,
    SHED,
    TrafficClass,
    arrival_schedule,
    assign_classes,
    plan_rate,
)


# -- schedules ---------------------------------------------------------------


def test_poisson_schedule_is_seeded_and_monotonic():
    a = arrival_schedule(100.0, 2000, seed=7)
    b = arrival_schedule(100.0, 2000, seed=7)
    c = arrival_schedule(100.0, 2000, seed=8)
    assert a == b
    assert a != c
    assert all(x <= y for x, y in zip(a, a[1:]))
    # Mean inter-arrival gap ~ 1/rate (law of large numbers, loose).
    mean_gap = a[-1] / (len(a) - 1)
    assert 0.8 / 100.0 < mean_gap < 1.2 / 100.0


def test_uniform_schedule_is_a_metronome():
    assert arrival_schedule(50.0, 5, seed=0, process="uniform") == [
        0.0, 1 / 50.0, 2 / 50.0, 3 / 50.0, 4 / 50.0
    ]


def test_schedule_rejects_bad_inputs():
    with pytest.raises(ValueError, match="rate"):
        arrival_schedule(0.0, 10, seed=0)
    with pytest.raises(ValueError, match="process"):
        arrival_schedule(10.0, 10, seed=0, process="bursty")


def test_class_assignment_is_seeded_and_weighted():
    classes = [
        TrafficClass("hot", weight=4.0),
        TrafficClass("cold", weight=1.0),
    ]
    a = assign_classes(classes, 5000, seed=3)
    assert a == assign_classes(classes, 5000, seed=3)
    assert a != assign_classes(classes, 5000, seed=4)
    hot_share = a.count(0) / len(a)
    assert 0.75 < hot_share < 0.85  # 4:1 weights
    with pytest.raises(ValueError):
        assign_classes([], 10, seed=0)


def test_plan_rate():
    assert plan_rate(600, 30.0) == 20.0


# -- merge arithmetic --------------------------------------------------------


def test_merge_counts_and_rate_are_exact():
    """Hand-built records: a metronome at 10/s with zero lag must merge
    to achieved == offered (error 0), with per-class outcome counts and
    latency percentiles taken only over OK records."""
    classes = [TrafficClass("m", priority="critical")]
    # (cls_idx, offset, lag, latency_s, outcome)
    records = [(0, i / 10.0, 0.0, 0.010, OK) for i in range(20)]
    records[4] = (0, 0.4, 0.0, 0.500, SHED)  # shed latency must not count
    records[9] = (0, 0.9, 0.0, 0.900, ERROR)
    report = loadgen._merge(records, classes, rate=10.0)
    assert report.fired == 20
    assert (report.ok, report.shed, report.error) == (18, 1, 1)
    assert report.offered_rate_error == 0.0
    assert report.achieved_rate == 10.0
    (cls,) = report.classes
    assert (cls.ok, cls.shed, cls.error) == (18, 1, 1)
    assert cls.p50_ms == 10.0 and cls.p99_ms == 10.0  # OK records only


def test_merge_by_model_collapses_priority_streams():
    classes = [
        TrafficClass("m", priority="critical"),
        TrafficClass("m", priority="batch"),
        TrafficClass("other"),
    ]
    records = [
        (0, 0.0, 0.0, 0.010, OK),
        (1, 0.1, 0.0, 0.050, SHED),
        (2, 0.2, 0.0, 0.020, OK),
    ]
    by_model = loadgen._merge(records, classes, rate=10.0).by_model()
    assert set(by_model) == {"m", "other"}
    assert by_model["m"].count == 2
    assert by_model["m"].shed == 1


def test_merge_slow_start_shows_as_rate_error():
    """Coordinated omission guard: arrivals that fired LATE (lag) must
    stretch the measured span and show up as offered-rate error — a
    harness that blames its own stalls on the fleet is lying."""
    classes = [TrafficClass("m")]
    records = [
        (0, i / 100.0, 0.05 * i, 0.001, OK) for i in range(100)
    ]  # each fire 50ms later than the last: 5x the scheduled span
    report = loadgen._merge(records, classes, rate=100.0)
    assert report.achieved_rate < 25.0
    assert report.offered_rate_error > 0.75
    assert report.fire_lag_p99_ms > 1000.0


# -- engines -----------------------------------------------------------------


def test_threaded_run_fires_everything_and_maps_outcomes():
    calls = []

    def target(cls):
        calls.append(cls.model)
        if cls.model == "shedme":
            return "shed"
        if cls.model == "broken":
            raise RuntimeError("kaput")
        return "ok"

    report = loadgen.run_open_loop_threaded(
        target,
        [
            TrafficClass("fine", weight=2.0),
            TrafficClass("shedme"),
            TrafficClass("broken"),
        ],
        rate=500.0, total=200, seed=5, concurrency=16,
    )
    assert report.fired == 200 == len(calls)
    assert report.ok + report.shed + report.error == 200
    by_model = report.by_model()
    assert by_model["shedme"].shed == by_model["shedme"].count
    assert by_model["broken"].error == by_model["broken"].count
    assert by_model["fine"].ok == by_model["fine"].count


def test_multiprocess_noop_holds_offered_rate():
    """The real engine: spawn workers, shared monotonic start, no-op
    target. Everything scheduled fires exactly once, and the achieved
    rate tracks the offered rate (the bench gates 5% at scale; unit
    scale on a busy CI box gets a looser 25%)."""
    t0 = time.monotonic()
    report = loadgen.run_open_loop(
        {"mode": "noop", "work_us": 20},
        [TrafficClass("a", weight=3.0), TrafficClass("b")],
        rate=400.0, total=240, seed=11, workers=2, concurrency=8,
        process="uniform", start_delay_s=0.2,
    )
    elapsed = time.monotonic() - t0
    assert report.fired == 240
    assert report.ok == 240
    assert report.offered_rate_error < 0.25, report
    assert report.duration_s > 0.4  # ~240/400s of schedule actually ran
    assert elapsed < 60.0
    assert {c.model for c in report.classes} == {"a", "b"}
    assert sum(c.count for c in report.classes) == 240
