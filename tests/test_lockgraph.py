"""Dynamic lock-graph witness (`testing/lockgraph.py`).

The witness is the runtime half of kftpu-race: it must name locks
exactly as the static model does (allocation site, MRO defining class),
record acquisition-order edges, detect observed cycles, and fail loudly
when a run exercises an edge the static graph is missing — that last
assertion is the feedback loop that keeps `ci/lint/concurrency.py`
honest, so these tests fabricate both failure modes directly.
"""

import threading

import pytest

from kubeflow_tpu.testing.lockgraph import (
    ENV_FLAG,
    LockGraphWitness,
    maybe_witness,
)
from kubeflow_tpu.utils.metrics import MetricsRegistry

REG_LOCK = "kubeflow_tpu/utils/metrics.py::MetricsRegistry._lock"
METRIC_LOCK = "kubeflow_tpu/utils/metrics.py::_Metric._lock"


def test_witness_names_locks_by_defining_class():
    """Locks allocated from package code are instrumented and named by
    allocation site — including the MRO rule: a Gauge's lock is named
    for `_Metric`, the class whose __init__ allocates it, matching the
    static model exactly."""
    with LockGraphWitness() as witness:
        registry = MetricsRegistry()
        gauge = registry.gauge("g", "test gauge")
        with registry._lock:
            with gauge._lock:
                pass
    assert (REG_LOCK, METRIC_LOCK) in witness.edges


def test_locks_allocated_outside_the_package_stay_real():
    with LockGraphWitness() as witness:
        lock = threading.Lock()  # tests/ is not package code
        with lock:
            pass
    assert not hasattr(lock, "_kftpu_name")
    assert witness.edges == frozenset()


def test_condition_wrapping_a_package_lock_aliases_it():
    """Condition(existing_lock) introduces no new node: edges taken
    through the condition attribute to the lock it wraps."""
    with LockGraphWitness() as witness:
        registry = MetricsRegistry()
        gauge = registry.gauge("g", "test gauge")
        cv = threading.Condition(registry._lock)
        with gauge._lock:
            with cv:
                pass
    assert (METRIC_LOCK, REG_LOCK) in witness.edges


def test_uninstall_restores_the_real_factories():
    real = (threading.Lock, threading.RLock, threading.Condition)
    with LockGraphWitness():
        assert threading.Lock is not real[0]
    assert (threading.Lock, threading.RLock, threading.Condition) == real


def test_assert_acyclic_detects_observed_cycle():
    witness = LockGraphWitness()
    witness.record_edge("a.py::A._l", "a.py::B._l")
    witness.record_edge("a.py::B._l", "a.py::A._l")
    with pytest.raises(AssertionError, match="cycle"):
        witness.assert_acyclic()


def test_assert_acyclic_passes_on_a_dag():
    witness = LockGraphWitness()
    witness.record_edge("a.py::A._l", "a.py::B._l")
    witness.record_edge("a.py::A._l", "a.py::C._l")
    witness.record_edge("a.py::B._l", "a.py::C._l")
    witness.assert_acyclic()


def test_subset_check_fires_on_an_edge_the_static_graph_lacks():
    witness = LockGraphWitness()
    edge = ("x.py::Fab._a", "x.py::Fab._b")
    witness.record_edge(*edge)
    with pytest.raises(AssertionError, match="Fab._a -> x.py::Fab._b"):
        witness.assert_subset_of_static(frozenset())
    witness.assert_subset_of_static(frozenset({edge}))  # covered: fine


def test_maybe_witness_is_inert_without_the_env_flag(monkeypatch):
    monkeypatch.delenv(ENV_FLAG, raising=False)
    real_lock = threading.Lock
    with maybe_witness() as witness:
        assert witness is None
        assert threading.Lock is real_lock


def test_maybe_witness_asserts_on_exit_when_enabled(monkeypatch):
    monkeypatch.setenv(ENV_FLAG, "1")
    with pytest.raises(AssertionError, match="cycle"):
        with maybe_witness() as witness:
            assert witness is not None
            witness.record_edge("a.py::A._l", "a.py::B._l")
            witness.record_edge("a.py::B._l", "a.py::A._l")
    assert not hasattr(threading.Lock, "_kftpu_name")


def test_maybe_witness_skips_assertions_when_the_body_raises(monkeypatch):
    """A failing workload must surface ITS error, not a witness
    assertion stacked on top of it."""
    monkeypatch.setenv(ENV_FLAG, "1")
    with pytest.raises(RuntimeError, match="workload"):
        with maybe_witness() as witness:
            witness.record_edge("a.py::A._l", "a.py::B._l")
            witness.record_edge("a.py::B._l", "a.py::A._l")
            raise RuntimeError("workload died")
