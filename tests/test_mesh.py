import jax
import numpy as np
import pytest

from kubeflow_tpu.parallel import MeshSpec, build_mesh
from kubeflow_tpu.parallel.mesh import AXES, local_mesh_spec


def test_resolve_wildcard():
    spec = MeshSpec(dp=-1, tp=2).resolve(8)
    assert spec.dp == 4 and spec.tp == 2
    assert spec.data_parallelism == 4


def test_resolve_exact():
    spec = MeshSpec(dp=2, fsdp=2, tp=2).resolve(8)
    assert spec.sizes() == (1, 2, 2, 1, 1, 2)


def test_resolve_rejects_bad_product():
    with pytest.raises(ValueError):
        MeshSpec(dp=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(dp=-1, tp=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(dp=-1, fsdp=-1).resolve(8)


def test_build_mesh_axes(devices):
    mesh = build_mesh(MeshSpec(dp=2, fsdp=2, tp=2), devices)
    assert mesh.axis_names == AXES
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 2
    assert mesh.devices.size == 8


def test_build_mesh_default_is_all_dp(devices):
    mesh = build_mesh(devices=devices)
    assert mesh.shape["dp"] == 8


def test_local_mesh_spec():
    assert local_mesh_spec(8, tp=2).fsdp == 4
    with pytest.raises(ValueError):
        local_mesh_spec(8, tp=3)


def test_mesh_runs_sharded_compute(mesh8):
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    xs = jax.device_put(x, NamedSharding(mesh8, P(("dp", "fsdp"), None)))
    y = jax.jit(lambda a: (a * 2).sum())(xs)
    assert float(y) == float(x.sum() * 2)
