"""MeshSpec/build_mesh axis inference and validation."""
import jax
import numpy as np
import pytest

from kubeflow_tpu.parallel import MeshSpec, build_mesh
from kubeflow_tpu.parallel.mesh import AXES, local_mesh_spec


def test_resolve_wildcard():
    spec = MeshSpec(dp=-1, tp=2).resolve(8)
    assert spec.dp == 4 and spec.tp == 2
    assert spec.data_parallelism == 4


def test_resolve_exact():
    spec = MeshSpec(dp=2, fsdp=2, tp=2).resolve(8)
    assert spec.sizes() == (1, 2, 2, 1, 1, 2)


def test_resolve_rejects_bad_product():
    with pytest.raises(ValueError):
        MeshSpec(dp=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(dp=-1, tp=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(dp=-1, fsdp=-1).resolve(8)


def test_build_mesh_axes(devices):
    mesh = build_mesh(MeshSpec(dp=2, fsdp=2, tp=2), devices)
    assert mesh.axis_names == AXES
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 2
    assert mesh.devices.size == 8


def test_build_mesh_default_is_all_dp(devices):
    mesh = build_mesh(devices=devices)
    assert mesh.shape["dp"] == 8


def test_local_mesh_spec():
    assert local_mesh_spec(8, tp=2).fsdp == 4
    with pytest.raises(ValueError):
        local_mesh_spec(8, tp=3)


def test_mesh_runs_sharded_compute(mesh8):
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    xs = jax.device_put(x, NamedSharding(mesh8, P(("dp", "fsdp"), None)))
    y = jax.jit(lambda a: (a * 2).sum())(xs)
    assert float(y) == float(x.sum() * 2)


def test_hybrid_mesh_two_slices(devices):
    """2 slices x 4 chips: dp spans DCN, fsdp/tp ride ICI in-slice."""
    from kubeflow_tpu.parallel.mesh import build_hybrid_mesh

    mesh = build_hybrid_mesh(
        MeshSpec(fsdp=2, tp=2), MeshSpec(dp=2), devices
    )
    assert mesh.axis_names == AXES
    assert mesh.shape["dp"] == 2
    assert mesh.shape["fsdp"] == 2 and mesh.shape["tp"] == 2
    assert mesh.devices.size == 8
    # The dp axis is the slice boundary: within one dp index, all devices
    # come from the same consecutive-device "slice".
    arr = mesh.devices.reshape(2, 4)  # dp, (fsdp*tp)
    ids0 = {d.id for d in arr[0].flat}
    ids1 = {d.id for d in arr[1].flat}
    assert ids0 == {0, 1, 2, 3} and ids1 == {4, 5, 6, 7}


def test_hybrid_mesh_runs_collectives(devices):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubeflow_tpu.parallel.mesh import build_hybrid_mesh

    mesh = build_hybrid_mesh(MeshSpec(fsdp=4), MeshSpec(dp=2), devices)
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    xs = jax.device_put(x, NamedSharding(mesh, P(("dp", "fsdp"), None)))
    y = jax.jit(lambda a: a.sum())(xs)
    assert float(y) == float(x.sum())


def test_hybrid_mesh_rejects_wildcard_dcn(devices):
    from kubeflow_tpu.parallel.mesh import build_hybrid_mesh

    with pytest.raises(ValueError, match="explicit"):
        build_hybrid_mesh(MeshSpec(fsdp=4), MeshSpec(dp=-1), devices)


def test_hybrid_mesh_bad_slice_division(devices):
    from kubeflow_tpu.parallel.mesh import build_hybrid_mesh

    with pytest.raises(ValueError, match="divisible"):
        build_hybrid_mesh(MeshSpec(fsdp=2), MeshSpec(dp=3), devices)
