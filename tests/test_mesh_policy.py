"""Mesh AuthorizationPolicy: profile-owner parity + web-tier enforcement.

VERDICT round-1 item #7: the reference creates the owner's Istio policy
at namespace creation (`profile_controller.go:190`); kfam only covered
contributors here. These tests pin owner-policy creation, the Istio
ALLOW-semantics evaluator, and the fail-closed web gate.
"""

import pytest

from kubeflow_tpu.api import new_resource
from kubeflow_tpu.api.rbac import (
    make_cluster_role_binding,
    seed_cluster_roles,
)
from kubeflow_tpu.apps.kfam import KfamApp
from kubeflow_tpu.controllers.profile import KIND, ProfileController
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.web.authz import ensure_authorized
from kubeflow_tpu.web.mesh import ensure_mesh_admits, mesh_admits
from kubeflow_tpu.web.wsgi import HttpError, TestClient


@pytest.fixture
def api():
    api = FakeApiServer()
    seed_cluster_roles(api)
    return api


def _profile(name="team-a", owner="alice@example.com"):
    return new_resource(
        KIND, name, "default",
        spec={"owner": {"kind": "User", "name": owner}},
    )


def test_profile_creates_owner_authorization_policy(api):
    ctl = ProfileController(api)
    api.create(_profile())
    ctl.controller.run_until_idle()

    ap = api.get("AuthorizationPolicy", "ns-owner", "team-a")
    assert ap.spec["action"] == "ALLOW"
    assert ap.spec["rules"][0]["from"][0]["source"]["principals"] == [
        "alice@example.com"
    ]
    # Owned by the namespace: dies with the profile's cascade.
    ns = api.get("Namespace", "team-a", "")
    assert ap.metadata.owner_references[0]["uid"] == ns.metadata.uid


def test_mesh_semantics():
    api = FakeApiServer()
    # No policies → open (hand-made/system namespaces stay reachable).
    assert mesh_admits(api, "anyone@example.com", "plain-ns")
    api.create(
        new_resource(
            "AuthorizationPolicy", "ns-owner", "team-a",
            spec={
                "action": "ALLOW",
                "rules": [{"from": [{"source": {"principals": [
                    "alice@example.com"]}}]}],
            },
        )
    )
    assert mesh_admits(api, "alice@example.com", "team-a")
    assert not mesh_admits(api, "mallory@example.com", "team-a")
    # A rule with no `from` admits all sources (Istio semantics).
    api.create(
        new_resource(
            "AuthorizationPolicy", "open-door", "team-b",
            spec={"action": "ALLOW", "rules": [{}]},
        )
    )
    assert mesh_admits(api, "anyone@example.com", "team-b")


def test_rbac_without_mesh_policy_fails_closed(api):
    """A user holding an RBAC grant but no mesh policy is stopped at the
    web tier — the exact gap VERDICT #7 describes, fail-closed."""
    ctl = ProfileController(api)
    api.create(_profile())  # owner alice; creates the ns-owner policy
    ctl.controller.run_until_idle()
    # Hand Bob RBAC directly (bypassing kfam, so no mesh policy).
    api.create(
        new_resource(
            "RoleBinding", "rogue-grant", "team-a",
            spec={
                "roleRef": {"kind": "ClusterRole", "name": "kubeflow-edit"},
                "subjects": [{"kind": "User",
                              "name": "bob@example.com"}],
            },
        )
    )
    ensure_authorized(api, "alice@example.com", "list", "notebooks",
                      "team-a")
    with pytest.raises(HttpError) as err:
        ensure_authorized(api, "bob@example.com", "list", "notebooks",
                          "team-a")
    assert err.value.status == 403
    assert "mesh policy" in err.value.message


def test_kfam_binding_restores_mesh_access(api):
    """The production contributor flow: kfam's binding creates both the
    RoleBinding and the mesh policy, so the web tier admits them."""
    ctl = ProfileController(api)
    api.create(_profile())
    ctl.controller.run_until_idle()
    kfam = TestClient(
        KfamApp(api),
        headers={
            "x-goog-authenticated-user-email":
                "accounts.google.com:alice@example.com"
        },
    )
    resp = kfam.post(
        "/kfam/v1/bindings",
        body={
            "user": {"kind": "User", "name": "carol@example.com"},
            "referredNamespace": "team-a",
            "roleRef": {"kind": "ClusterRole", "name": "edit"},
        },
    )
    assert resp.status == 200, resp.body
    ensure_authorized(api, "carol@example.com", "list", "notebooks",
                      "team-a")


def test_cluster_admin_bypasses_mesh(api):
    api.create(
        make_cluster_role_binding("boot", "kubeflow-admin",
                                  "root@example.com")
    )
    ctl = ProfileController(api)
    api.create(_profile())
    ctl.controller.run_until_idle()
    ensure_mesh_admits(api, "root@example.com", "team-a")  # no raise


# -- rule fidelity: methods/paths/wildcards/DENY (servicerole_types.go:38-75)


def _policy(ns, name, rules, action="ALLOW"):
    return new_resource(
        "AuthorizationPolicy", name, ns,
        spec={"action": action, "rules": rules},
    )


def test_mesh_method_constraint():
    """A GET-only rule admits reads and refuses writes — the viewer
    scoping kfam now attaches (`ROLE_MESH_METHODS`)."""
    api = FakeApiServer()
    api.create(_policy("team", "viewer", [{
        "from": [{"source": {"principals": ["v@example.com"]}}],
        "to": [{"operation": {"methods": ["GET"]}}],
    }]))
    assert mesh_admits(api, "v@example.com", "team", method="GET")
    assert not mesh_admits(api, "v@example.com", "team", method="POST")
    assert not mesh_admits(api, "other@example.com", "team", method="GET")


def test_mesh_path_constraint_with_wildcards():
    """Paths use Istio's exact/prefix/suffix forms
    (servicerole_types.go:33-41 documents the same matching)."""
    api = FakeApiServer()
    api.create(_policy("team", "scoped", [{
        "from": [{"source": {"principals": ["v@example.com"]}}],
        "to": [{"operation": {"paths": ["/api/notebooks*", "*/healthz"]}}],
    }]))
    ok = lambda p: mesh_admits(api, "v@example.com", "team", path=p)
    assert ok("/api/notebooks")
    assert ok("/api/notebooks/nb1")
    assert ok("/anything/healthz")
    assert not ok("/api/secrets")


def test_mesh_principal_wildcards():
    api = FakeApiServer()
    api.create(_policy("team", "sa", [{
        "from": [{"source": {"principals": ["system:serviceaccount:team:*"]}}],
    }]))
    assert mesh_admits(api, "system:serviceaccount:team:runner", "team")
    assert not mesh_admits(api, "system:serviceaccount:prod:runner", "team")


def test_mesh_deny_wins_over_allow():
    """Istio evaluation order: DENY policies are checked first and win."""
    api = FakeApiServer()
    api.create(_policy("team", "allow-all", [{}]))
    api.create(_policy("team", "block-mallory", [{
        "from": [{"source": {"principals": ["mallory@example.com"]}}],
    }], action="DENY"))
    assert mesh_admits(api, "alice@example.com", "team")
    assert not mesh_admits(api, "mallory@example.com", "team")


def test_mesh_deny_scoped_to_operation():
    """A DENY on POST leaves GET open — maintenance-freeze idiom."""
    api = FakeApiServer()
    api.create(_policy("team", "freeze-writes", [{
        "to": [{"operation": {"methods": ["POST", "PUT", "DELETE"]}}],
    }], action="DENY"))
    assert mesh_admits(api, "anyone@example.com", "team", method="GET")
    assert not mesh_admits(api, "anyone@example.com", "team", method="POST")


def test_mesh_deny_all_idiom():
    """`rules: []` on an ALLOW policy matches nobody but flips the
    namespace into enforce mode — Istio's deny-all idiom, now
    representable and distinct from allow-all (`rules: [{}]`)."""
    api = FakeApiServer()
    api.create(_policy("locked", "deny-all", []))
    assert not mesh_admits(api, "anyone@example.com", "locked")
    assert not mesh_admits(api, "owner@example.com", "locked", method="GET")


def test_viewer_post_refused_at_web_tier(api):
    """E2E through the real apps: kfam binds dana as view; the jupyter
    backend serves her GETs and refuses her POST — at the mesh gate with
    a method-scoped policy, backed by the GET-only RBAC role."""
    from kubeflow_tpu.apps.jupyter import JupyterApp

    ctl = ProfileController(api)
    api.create(_profile())
    ctl.controller.run_until_idle()
    owner_hdr = {
        "x-goog-authenticated-user-email":
            "accounts.google.com:alice@example.com"
    }
    kfam = TestClient(KfamApp(api), headers=owner_hdr)
    resp = kfam.post(
        "/kfam/v1/bindings",
        body={
            "user": {"kind": "User", "name": "dana@example.com"},
            "referredNamespace": "team-a",
            "roleRef": {"kind": "ClusterRole", "name": "view"},
        },
    )
    assert resp.status == 200, resp.body
    [ap] = [
        p for p in api.list("AuthorizationPolicy", "team-a")
        if p.metadata.annotations.get("user") == "dana@example.com"
    ]
    assert ap.spec["rules"][0]["to"] == [
        {"operation": {"methods": ["GET"]}}
    ]

    dana = TestClient(JupyterApp(api), headers={
        "x-goog-authenticated-user-email":
            "accounts.google.com:dana@example.com"
    })
    assert dana.get("/api/namespaces/team-a/notebooks").status == 200
    denied = dana.post(
        "/api/namespaces/team-a/notebooks",
        body={"name": "nb", "image": "img"},
    )
    assert denied.status == 403, denied.body
    # The mesh rule alone refuses the write even for a principal whose
    # RBAC would allow it (defense in depth, evaluated directly):
    assert not mesh_admits(api, "dana@example.com", "team-a", method="POST")


def test_method_scoped_deny_fails_closed_without_method():
    """ADVICE r3: a method-constrained DENY rule matches a caller that
    presents NO method (in-process checks without a request) — absent
    context fails closed, the opposite of silently skipping the rule
    (in Istio every request carries a method; only our in-process
    callers can lack one)."""
    from kubeflow_tpu.api.objects import new_resource

    api = FakeApiServer()
    api.create(new_resource(
        "AuthorizationPolicy", "no-writes", "team-a",
        spec={
            "action": "DENY",
            "rules": [{
                "from": [{"source": {"principals": ["mallory@x.co"]}}],
                "to": [{"operation": {"methods": ["POST", "DELETE"]}}],
            }],
        },
    ))
    # With a method: normal Istio semantics.
    assert not mesh_admits(api, "mallory@x.co", "team-a", method="POST")
    assert mesh_admits(api, "mallory@x.co", "team-a", method="GET")
    # WITHOUT one: the DENY still bites (fail closed).
    assert not mesh_admits(api, "mallory@x.co", "team-a")
    # ALLOW-side evaluation is unchanged: no allow policies = admit.
    assert mesh_admits(api, "someone-else@x.co", "team-a")
