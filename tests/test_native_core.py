"""Tests for the native control-plane core bindings (libkftpu_core).

The C++-level semantics are covered in native/src/core_test.cc (ctest);
these tests cover the ctypes layer, the NativeApiServer adapter, and —
most importantly — that real controllers run unmodified on the compiled
control plane. (test_fake_apiserver.py additionally runs the full
storage-semantics suite against both backends.)
"""

import threading
import time

import pytest

from kubeflow_tpu.api import new_resource
from kubeflow_tpu.controllers.runtime import Controller, Result, _PyWorkQueue
from kubeflow_tpu.native.apiserver import NativeApiServer
from kubeflow_tpu.native.core import WorkQueue


@pytest.fixture(params=["native", "python"])
def wq(request):
    if request.param == "native":
        return WorkQueue(base_backoff=0.01, max_backoff=0.08)
    return _PyWorkQueue(base_backoff=0.01, max_backoff=0.08)


class TestWorkQueue:
    def test_dedup_and_fifo(self, wq):
        wq.add("a")
        wq.add("a")
        wq.add("b")
        assert len(wq) == 2
        assert wq.get() == "a"
        assert wq.get() == "b"
        assert wq.get() is None
        wq.done("a")
        wq.done("b")

    def test_inflight_readd_lands_after_done(self, wq):
        wq.add("k")
        assert wq.get() == "k"
        wq.add("k")  # arrives while processing
        assert wq.get() is None  # not concurrently reconcilable
        wq.done("k")
        assert wq.get() == "k"  # dirty re-add surfaces now
        wq.done("k")

    def test_sooner_supersedes(self, wq):
        wq.add("k", after=60.0)
        assert wq.get() is None
        wq.add("k")  # sooner wins
        assert wq.get() == "k"
        wq.done("k")

    def test_error_backoff_doubles_and_caps(self, wq):
        assert wq.requeue_error("k") == pytest.approx(0.01)
        assert wq.requeue_error("k") == pytest.approx(0.02)
        assert wq.requeue_error("k") == pytest.approx(0.04)
        assert wq.requeue_error("k") == pytest.approx(0.08)
        assert wq.requeue_error("k") == pytest.approx(0.08)
        wq.forget("k")
        assert wq.requeue_error("k") == pytest.approx(0.01)

    def test_blocking_get_sees_delayed_key(self, wq):
        wq.add("k", after=0.05)
        t0 = time.monotonic()
        assert wq.get(timeout=2.0) == "k"
        assert time.monotonic() - t0 >= 0.04
        wq.done("k")

    def test_next_ready_in(self, wq):
        assert wq.next_ready_in() is None
        wq.add("k", after=10.0)
        eta = wq.next_ready_in()
        assert 9.0 < eta <= 10.0

    def test_threaded_workers_cover_all_keys(self, wq):
        seen = set()
        lock = threading.Lock()

        def worker():
            while True:
                key = wq.get(timeout=0.2)
                if key is None:
                    return
                with lock:
                    seen.add(key)
                wq.done(key)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for i in range(100):
            wq.add(f"k{i}")
        for t in threads:
            t.join()
        assert seen == {f"k{i}" for i in range(100)}


class TestControllerOnNativeApiServer:
    """A real reconcile loop on the compiled store + compiled workqueue."""

    def test_reconcile_creates_owned_child(self):
        api = NativeApiServer()

        def reconcile(api, key):
            ns, name = key
            from kubeflow_tpu.testing.fake_apiserver import NotFound

            try:
                job = api.get("TpuJob", name, ns)
            except NotFound:
                return None
            from kubeflow_tpu.api.objects import owner_ref

            child = new_resource("Pod", f"{name}-0", ns)
            child.metadata.owner_references = [owner_ref(job)]
            try:
                api.create(child)
            except Exception:
                pass
            return Result()

        c = Controller(api, "TpuJob", reconcile, owns=("Pod",))
        api.create(new_resource("TpuJob", "j", "ml", spec={"workers": 1}))
        c.run_until_idle()
        assert api.get("Pod", "j-0", "ml") is not None
        # Deleting the job cascades to the pod through the C++ store.
        api.delete("TpuJob", "j", "ml")
        assert api.list("Pod", "ml") == []

    def test_error_backoff_then_recovery(self):
        api = NativeApiServer()
        calls = {"n": 0}

        def flaky(api, key):
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("transient")
            return None

        c = Controller(
            api, "Widget", flaky,
            workqueue=WorkQueue(base_backoff=0.005, max_backoff=0.02),
        )
        api.create(new_resource("Widget", "w"))
        deadline = time.monotonic() + 5.0
        while calls["n"] < 3 and time.monotonic() < deadline:
            c.process_one(timeout=0.05)
        assert calls["n"] == 3

    def test_requeue_after_is_delayed(self):
        api = NativeApiServer()

        def periodic(api, key):
            return Result(requeue_after=30.0)

        c = Controller(api, "Widget", periodic)
        api.create(new_resource("Widget", "w"))
        assert c.run_until_idle() == 1  # second pass not yet due
        assert c.has_pending()
