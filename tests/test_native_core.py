"""Tests for the native control-plane core bindings (libkftpu_core).

The C++-level semantics are covered in native/src/core_test.cc (ctest);
these tests cover the ctypes layer, the NativeApiServer adapter, and —
most importantly — that real controllers run unmodified on the compiled
control plane. (test_fake_apiserver.py additionally runs the full
storage-semantics suite against both backends.)
"""

import threading
import time

import pytest

from kubeflow_tpu.api import new_resource
from kubeflow_tpu.controllers.runtime import Controller, Result, _PyWorkQueue
from kubeflow_tpu.native.apiserver import NativeApiServer
from kubeflow_tpu.native.core import WorkQueue


@pytest.fixture(params=["native", "python"])
def wq(request):
    if request.param == "native":
        return WorkQueue(base_backoff=0.01, max_backoff=0.08)
    return _PyWorkQueue(base_backoff=0.01, max_backoff=0.08)


class TestWorkQueue:
    def test_dedup_and_fifo(self, wq):
        wq.add("a")
        wq.add("a")
        wq.add("b")
        assert len(wq) == 2
        assert wq.get() == "a"
        assert wq.get() == "b"
        assert wq.get() is None
        wq.done("a")
        wq.done("b")

    def test_inflight_readd_lands_after_done(self, wq):
        wq.add("k")
        assert wq.get() == "k"
        wq.add("k")  # arrives while processing
        assert wq.get() is None  # not concurrently reconcilable
        wq.done("k")
        assert wq.get() == "k"  # dirty re-add surfaces now
        wq.done("k")

    def test_sooner_supersedes(self, wq):
        wq.add("k", after=60.0)
        assert wq.get() is None
        wq.add("k")  # sooner wins
        assert wq.get() == "k"
        wq.done("k")

    def test_error_backoff_doubles_and_caps(self, wq):
        assert wq.requeue_error("k") == pytest.approx(0.01)
        assert wq.requeue_error("k") == pytest.approx(0.02)
        assert wq.requeue_error("k") == pytest.approx(0.04)
        assert wq.requeue_error("k") == pytest.approx(0.08)
        assert wq.requeue_error("k") == pytest.approx(0.08)
        wq.forget("k")
        assert wq.requeue_error("k") == pytest.approx(0.01)

    def test_blocking_get_sees_delayed_key(self, wq):
        wq.add("k", after=0.05)
        t0 = time.monotonic()
        assert wq.get(timeout=2.0) == "k"
        assert time.monotonic() - t0 >= 0.04
        wq.done("k")

    def test_next_ready_in(self, wq):
        assert wq.next_ready_in() is None
        wq.add("k", after=10.0)
        eta = wq.next_ready_in()
        assert 9.0 < eta <= 10.0

    def test_threaded_workers_cover_all_keys(self, wq):
        seen = set()
        lock = threading.Lock()

        def worker():
            while True:
                key = wq.get(timeout=0.2)
                if key is None:
                    return
                with lock:
                    seen.add(key)
                wq.done(key)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for i in range(100):
            wq.add(f"k{i}")
        for t in threads:
            t.join()
        assert seen == {f"k{i}" for i in range(100)}


class TestControllerOnNativeApiServer:
    """A real reconcile loop on the compiled store + compiled workqueue."""

    def test_reconcile_creates_owned_child(self):
        api = NativeApiServer()

        def reconcile(api, key):
            ns, name = key
            from kubeflow_tpu.testing.fake_apiserver import NotFound

            try:
                job = api.get("TpuJob", name, ns)
            except NotFound:
                return None
            from kubeflow_tpu.api.objects import owner_ref

            child = new_resource("Pod", f"{name}-0", ns)
            child.metadata.owner_references = [owner_ref(job)]
            try:
                api.create(child)
            except Exception:
                pass
            return Result()

        c = Controller(api, "TpuJob", reconcile, owns=("Pod",))
        api.create(new_resource("TpuJob", "j", "ml", spec={"workers": 1}))
        c.run_until_idle()
        assert api.get("Pod", "j-0", "ml") is not None
        # Deleting the job cascades to the pod through the C++ store.
        api.delete("TpuJob", "j", "ml")
        assert api.list("Pod", "ml") == []

    def test_error_backoff_then_recovery(self):
        api = NativeApiServer()
        calls = {"n": 0}

        def flaky(api, key):
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("transient")
            return None

        c = Controller(
            api, "Widget", flaky,
            workqueue=WorkQueue(base_backoff=0.005, max_backoff=0.02),
        )
        api.create(new_resource("Widget", "w"))
        deadline = time.monotonic() + 5.0
        while calls["n"] < 3 and time.monotonic() < deadline:
            c.process_one(timeout=0.05)
        assert calls["n"] == 3

    def test_requeue_after_is_delayed(self):
        api = NativeApiServer()

        def periodic(api, key):
            return Result(requeue_after=30.0)

        c = Controller(api, "Widget", periodic)
        api.create(new_resource("Widget", "w"))
        assert c.run_until_idle() == 1  # second pass not yet due
        assert c.has_pending()


def test_native_store_lease_fencing_parity():
    """Write fencing holds on the native backend exactly as on
    FakeApiServer (shared check_lease_guard contract): a stale guard is
    fenced on every write form, the current term's guard passes, and
    Lease writes are exempt."""
    from kubeflow_tpu.controllers.leader import LeaderElector
    from kubeflow_tpu.testing.fake_apiserver import Conflict

    api = NativeApiServer()
    a = LeaderElector(api, "native-ctl", "a",
                      lease_duration=5.0, renew_deadline=3.0,
                      retry_period=0.05)
    assert a._try_acquire_or_renew()
    guard_a = ("", "native-ctl", "a", a.transitions)
    api.create(new_resource("Widget", "w1", spec={"v": 1}),
               lease_guard=guard_a)

    # Depose a (backdate) and let b acquire a new term. The backdating
    # update deliberately carries a guard that is ABOUT to be stale:
    # Lease-kind writes must be exempt from fencing (the election
    # protocol has to stay able to transfer ownership) — this is the
    # exemption actually exercised, not just claimed.
    lease = api.get("Lease", "native-ctl", "")
    lease.spec = dict(lease.spec)
    lease.spec["renewTime"] = 0.0
    api.update(lease, lease_guard=("", "native-ctl", "zombie", 99))
    b = LeaderElector(api, "native-ctl", "b",
                      lease_duration=5.0, renew_deadline=3.0,
                      retry_period=0.05)
    assert b._try_acquire_or_renew()

    with pytest.raises(Conflict, match="fenced"):
        api.create(new_resource("Widget", "w2"), lease_guard=guard_a)
    w1 = api.get("Widget", "w1")
    w1.spec["v"] = 2
    with pytest.raises(Conflict, match="fenced"):
        api.update(w1, lease_guard=guard_a)
    with pytest.raises(Conflict, match="fenced"):
        api.delete("Widget", "w1", lease_guard=guard_a)
    guard_b = ("", "native-ctl", "b", b.transitions)
    api.create(new_resource("Widget", "w2"), lease_guard=guard_b)
    assert {w.metadata.name for w in api.list("Widget")} == {"w1", "w2"}


def test_native_backend_behind_http_facade():
    """Drop-in means behind the FACADE too: the native store serves the
    HTTP apiserver's list (rv bookmark), streaming watch, cluster-scope
    CRUD, and lease fencing — previously list/watch 500'd (no
    current_rv/events_since surface) and cluster-scoped gets missed
    (namespace '' was coerced to 'default' in C++)."""
    import time

    from kubeflow_tpu.testing.apiserver_http import (
        ApiServerApp,
        HttpApiClient,
    )
    from kubeflow_tpu.web.wsgi import serve

    api = NativeApiServer()
    server, _ = serve(ApiServerApp(api), host="127.0.0.1", port=0)
    client = HttpApiClient(
        f"http://127.0.0.1:{server.server_port}",
        watch_poll_timeout=1.0, watch_retry=0.05,
    )
    try:
        client.create(new_resource("Node", "n0", "",
                                   spec={"pool": "v5e", "chips": 4}))
        assert client.get("Node", "n0", "").spec["chips"] == 4
        # "" lists exactly the cluster scope.
        assert [n.metadata.name
                for n in client.list("Node", namespace="")] == ["n0"]
        seen = []
        client.watch(lambda ev, o: seen.append((ev, o.metadata.name)),
                     "Widget")
        time.sleep(0.3)
        client.create(new_resource("Widget", "streamed", "default",
                                   spec={}))
        deadline = time.monotonic() + 10
        while ("ADDED", "streamed") not in seen \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert ("ADDED", "streamed") in seen, seen
    finally:
        client.close()
        server.shutdown()


def test_native_deleted_events_get_fresh_rv():
    """FakeApiServer parity pinned at the C++ boundary: a watcher whose
    bookmark is the object's last-seen rv must still observe its
    deletion — the DELETED event carries a FRESH resourceVersion, not
    the stale one (events_since(bookmark) would otherwise skip it and
    the watcher caches the object forever)."""
    api = NativeApiServer()
    a = api.create(new_resource("Widget", "a", spec={}))
    api.create(new_resource("Widget", "b", spec={}))
    bookmark = api.current_rv
    api.delete("Widget", "a")
    events, rv = api.events_since(bookmark)
    assert [(e, o.metadata.name) for _, e, o in events] == [
        ("DELETED", "a")
    ]
    assert rv > bookmark > a.metadata.resource_version
