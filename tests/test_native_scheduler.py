"""Tests for the native (C++) gang scheduler via its ctypes bindings, and
its integration with the TpuJob operator."""

import pytest

from kubeflow_tpu.api import make_tpujob, new_resource
from kubeflow_tpu.api.tpujob import KIND
from kubeflow_tpu.controllers.tpujob import TpuJobController
from kubeflow_tpu.native import GangScheduler, PlacementError
from kubeflow_tpu.testing import FakeApiServer


@pytest.fixture(scope="module")
def sched_cls():
    return GangScheduler  # first use triggers the cmake build


def test_place_contiguous_row(sched_cls):
    s = sched_cls()
    for i in range(4):
        s.add_node(f"host-{i}", "v5e-4x4", x=i, y=0, chips=4)
    nodes, cost = s.place_gang("j", "v5e-4x4", 4, 4)
    assert nodes == ["host-0", "host-1", "host-2", "host-3"]
    assert cost == 3  # three single-hop ICI links between consecutive ranks
    assert s.free_chips("v5e-4x4") == 0


def test_all_or_nothing(sched_cls):
    s = sched_cls()
    s.add_node("a", "p", chips=4)
    s.add_node("b", "p", x=1, chips=4)
    with pytest.raises(PlacementError):
        s.place_gang("big", "p", 3, 4)  # only 2 hosts' worth
    assert s.free_chips("p") == 8  # nothing was reserved


def test_release_restores_capacity(sched_cls):
    s = sched_cls()
    s.add_node("a", "p", chips=8)
    s.place_gang("j", "p", 2, 4)
    assert s.free_chips("p") == 0
    assert s.release_gang("j") == 2
    assert s.free_chips("p") == 8


def test_prefers_adjacent_nodes(sched_cls):
    s = sched_cls()
    # 2x2 mesh; one corner taken -> pair should land on adjacent nodes.
    coords = {(0, 0): "n00", (1, 0): "n10", (0, 1): "n01", (1, 1): "n11"}
    for (x, y), name in coords.items():
        s.add_node(name, "p", x=x, y=y, chips=4)
    s.place_gang("corner", "p", 1, 4)  # takes n00 (row-major first)
    nodes, cost = s.place_gang("pair", "p", 2, 4)
    assert cost == 1, (nodes, cost)


def test_operator_places_gang_on_nodes():
    api = FakeApiServer()
    ctl = TpuJobController(api)
    for i in range(4):
        api.create(
            new_resource(
                "Node", f"tpu-host-{i}", "",
                spec={"pool": "4x4", "x": i, "y": 0, "chips": 4},
            )
        )
    api.create(make_tpujob("train", replicas=4, tpu_chips_per_worker=4,
                           topology="4x4"))
    ctl.controller.run_until_idle()
    node_names = [
        api.get("Pod", f"train-worker-{i}").spec["nodeName"] for i in range(4)
    ]
    assert node_names == [f"tpu-host-{i}" for i in range(4)]
    reasons = [e.spec["reason"] for e in api.list("Event")]
    assert "GangPlaced" in reasons


def test_operator_unschedulable_requeues():
    api = FakeApiServer()
    ctl = TpuJobController(api)
    api.create(
        new_resource("Node", "only", "", spec={"pool": "4x4", "chips": 4})
    )
    api.create(make_tpujob("big", replicas=4, tpu_chips_per_worker=4,
                           topology="4x4"))
    ctl.controller.run_until_idle()
    job = api.get(KIND, "big")
    assert job.status["phase"] == "Pending"
    assert api.list("Pod", label_selector={"kubeflow-tpu.org/job": "big"}) == []
    reasons = [e.spec["reason"] for e in api.list("Event")]
    assert "Unschedulable" in reasons
    # capacity frees once another job's nodes appear
    for i in range(1, 4):
        api.create(
            new_resource("Node", f"n{i}", "",
                         spec={"pool": "4x4", "x": i, "chips": 4})
        )
    ctl.controller.enqueue(("default", "big"))
    ctl.controller.run_until_idle()
    pods = api.list("Pod", label_selector={"kubeflow-tpu.org/job": "big"})
    assert len(pods) == 4


def test_operator_without_nodes_still_works():
    api = FakeApiServer()
    ctl = TpuJobController(api)
    api.create(make_tpujob("j", replicas=2, topology="2x2"))
    ctl.controller.run_until_idle()
    pods = api.list("Pod", label_selector={"kubeflow-tpu.org/job": "j"})
    assert len(pods) == 2
    assert "nodeName" not in pods[0].spec


def test_new_controller_sees_existing_reservations():
    """Operator restart must not double-book: a fresh controller rebuilds
    scheduler state from pods' observed nodeName."""
    api = FakeApiServer()
    for i in range(2):
        api.create(new_resource(
            "Node", f"n{i}", "", spec={"pool": "2x2", "x": i, "chips": 4}))
    ctl1 = TpuJobController(api)
    api.create(make_tpujob("a", replicas=2, tpu_chips_per_worker=4,
                           topology="2x2"))
    ctl1.controller.run_until_idle()

    ctl2 = TpuJobController(api)  # "restarted" operator, empty memory
    api.create(make_tpujob("b", replicas=1, tpu_chips_per_worker=4,
                           topology="2x2"))
    ctl2.controller.run_until_idle()
    assert api.get(KIND, "b").status.get("reason") == "Unschedulable"
    assert api.list("Pod", label_selector={"kubeflow-tpu.org/job": "b"}) == []
    # Event recorded once per stuck episode, not once per retry.
    ctl2.controller.run_until_idle()
    n_ev = sum(1 for e in api.list("Event")
               if e.spec["reason"] == "Unschedulable")
    assert n_ev == 1


def test_torus_wrap_beats_manhattan_at_the_seam(sched_cls):
    """v5e pod slices wrap their ICI links: with the pool declared as a
    torus, a ring across the seam (x=0 .. x=5) is ONE hop and wins; the
    flat-Manhattan model picks a physically worse pair (ctest carries
    the same golden in scheduler_test.cc)."""
    def fresh():
        s = sched_cls()
        s.add_node("t0", "6x1", x=0, y=0, chips=4)
        s.add_node("t5", "6x1", x=5, y=0, chips=4)
        s.add_node("t2b", "6x1", x=2, y=1, chips=4)
        return s

    flat = fresh()
    nodes, cost = flat.place_gang("flat", "6x1", 2, 4)
    assert (nodes, cost) == (["t5", "t2b"], 4)  # the seam looked 5 wide

    wrapped = fresh()
    wrapped.set_pool_topology("6x1", 6, 1)
    nodes, cost = wrapped.place_gang("wrap", "6x1", 2, 4)
    assert (nodes, cost) == (["t0", "t5"], 1)  # one wrap hop


def test_operator_declares_torus_from_pool_shape():
    """The controller parses 'WxH'-shaped pool names into torus dims, so
    a seam-crossing gang gets the wrap-aware placement end to end (the
    GangPlaced event carries the ring cost)."""
    api = FakeApiServer()
    for name, x, y in (("t0", 0, 0), ("t5", 5, 0), ("t2b", 2, 1)):
        api.create(new_resource(
            "Node", name, "", spec={"pool": "6x2", "x": x, "y": y,
                                    "chips": 4}))
    ctl = TpuJobController(api)
    api.create(make_tpujob("seam", replicas=2, tpu_chips_per_worker=4,
                           topology="6x2"))
    ctl.controller.run_until_idle()
    pods = api.list("Pod", label_selector={"kubeflow-tpu.org/job": "seam"})
    assert sorted(p.spec["nodeName"] for p in pods) == ["t0", "t5"]
    placed = [e for e in api.list("Event")
              if e.spec["reason"] == "GangPlaced"]
    assert placed and "ring cost 1" in placed[0].spec["message"]


def test_torus_not_declared_when_coords_overflow_shape():
    """8 linearly-numbered hosts in a pool *named* 4x4 do not form that
    grid — declaring the torus would alias x=0 onto x=4 (0 hops apart).
    The operator only trusts the name when the coordinates fit it."""
    api = FakeApiServer()
    for i in range(8):
        api.create(new_resource(
            "Node", f"n{i}", "", spec={"pool": "v5e-4x4", "x": i, "y": 0,
                                       "chips": 4}))
    ctl = TpuJobController(api)
    api.create(make_tpujob("lin", replicas=2, tpu_chips_per_worker=4,
                           topology="v5e-4x4"))
    ctl.controller.run_until_idle()
    pods = api.list("Pod", label_selector={"kubeflow-tpu.org/job": "lin"})
    # Flat-grid adjacency: consecutive hosts, never a mod-4 alias pair.
    assert sorted(p.spec["nodeName"] for p in pods) == ["n0", "n1"]


# -- round 5: the compiled scheduler is the ONLY scheduler ------------------


def test_topology_less_gang_routes_through_compiled_scheduler():
    """Round-5 verdict item 5: a gang that omits spec.topology used to
    bypass the native scheduler entirely. Now it places through the same
    compiled path on whichever pool fits (most free chips first), with
    the invocation counter as evidence."""
    api = FakeApiServer()
    for i in range(4):
        api.create(new_resource(
            "Node", f"n{i}", "", spec={"pool": "v5e", "x": i, "chips": 4}))
    ctl = TpuJobController(api)
    api.create(make_tpujob("plain", replicas=2, tpu_chips_per_worker=4))
    ctl.controller.run_until_idle()
    pods = api.list("Pod", label_selector={"kubeflow-tpu.org/job": "plain"})
    assert len(pods) == 2
    # Placed (nodeName assigned), through the scheduler, not unplaced.
    assert {p.spec["nodeName"] for p in pods} <= {f"n{i}" for i in range(4)}
    assert ctl.gang_placements.value(backend="native") >= 1
    ev = [e for e in api.list("Event") if e.spec["reason"] == "GangPlaced"]
    assert len(ev) == 1


def test_topology_less_gang_tries_all_pools():
    """Pool 'a' is full; a topology-less gang lands on pool 'b'."""
    api = FakeApiServer()
    api.create(new_resource(
        "Node", "a0", "", spec={"pool": "a", "x": 0, "chips": 4}))
    for i in range(2):
        api.create(new_resource(
            "Node", f"b{i}", "", spec={"pool": "b", "x": i, "chips": 8}))
    ctl = TpuJobController(api)
    api.create(make_tpujob("filler", replicas=1, tpu_chips_per_worker=4,
                           topology="a"))
    ctl.controller.run_until_idle()
    api.create(make_tpujob("roamer", replicas=2, tpu_chips_per_worker=8))
    ctl.controller.run_until_idle()
    pods = api.list("Pod", label_selector={"kubeflow-tpu.org/job": "roamer"})
    assert {p.spec["nodeName"] for p in pods} == {"b0", "b1"}


def test_linear_pool_declared_as_ring():
    """An unshaped pool whose nodes form a 1xN line (the launcher's
    seeded default) is a 1xN torus: a ring spanning the full pool pays
    the wraparound hop, not N-1 flat hops."""
    api = FakeApiServer()
    for i in range(4):
        api.create(new_resource(
            "Node", f"n{i}", "", spec={"pool": "v5e", "x": i, "chips": 4}))
    ctl = TpuJobController(api)
    api.create(make_tpujob("ring", replicas=4, tpu_chips_per_worker=4))
    ctl.controller.run_until_idle()
    ev = [e for e in api.list("Event") if e.spec["reason"] == "GangPlaced"]
    assert len(ev) == 1
    # 4 ranks around a 4-ring: 3 consecutive-hop links of cost 1 each
    # (flat line would read the same here; the wrap shows when rank0 and
    # rank3 are adjacent in ring cost, covered by the parity test below).
    assert "ring cost 3" in ev[0].spec["message"]


def test_python_twin_matches_native_golden():
    """Golden parity: the Python twin IS the executable spec of
    scheduler.cc — identical assignments and ring costs across
    randomized pools, reservations, and torus shapes."""
    import random

    from kubeflow_tpu.native import PyGangScheduler

    rng = random.Random(7)
    for case in range(25):
        native, py = GangScheduler(), PyGangScheduler()
        w = rng.randint(1, 5)
        h = rng.randint(1, 3)
        chips = rng.choice([4, 8])
        nodes = []
        for x in range(w):
            for y in range(h):
                name = f"n{x}-{y}"
                nodes.append(name)
                for s in (native, py):
                    s.add_node(name, "pool", x=x, y=y, chips=chips)
        if rng.random() < 0.6:
            for s in (native, py):
                s.set_pool_topology("pool", w, h)
        # Random pre-existing reservations.
        for name in nodes:
            if rng.random() < 0.3:
                held = rng.randint(1, chips)
                for s in (native, py):
                    s.reserve("old", name, held)
        workers = rng.randint(1, max(1, w * h))
        per = rng.choice([0, 1, chips // 2, chips])
        try:
            a_native = native.place_gang("g", "pool", workers, per)
            a_py = py.place_gang("g", "pool", workers, per)
        except PlacementError:
            with pytest.raises(PlacementError):
                py.place_gang("g2", "pool", workers, per)
            continue
        assert a_native == a_py, f"case {case}: {a_native} != {a_py}"
        assert native.free_chips("pool") == py.free_chips("pool")
        # Release symmetry.
        assert native.release_gang("g") == py.release_gang("g")
        assert native.free_chips("pool") == py.free_chips("pool")
