"""Slice-health watchdog: lost nodes fail their pods, gangs restart, and
training resumes — the failure-detection tier the reference lacked
(SURVEY.md §5)."""

import numpy as np
import pytest

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.controllers.nodehealth import (
    REASON_NODE_LOST,
    NodeHealthController,
)
from kubeflow_tpu.controllers.tpujob import LABEL_JOB, TpuJobController
from kubeflow_tpu.testing import FakeApiServer


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


@pytest.fixture
def world():
    api = FakeApiServer()
    clock = FakeClock()
    health = NodeHealthController(api, grace_seconds=30.0, clock=clock)
    jobs = TpuJobController(api)
    return api, health, jobs, clock


def _add_node(api, name, ready=True):
    # 8 chips: since round 5 even topology-less gangs place through the
    # compiled scheduler, so a RECREATED 2x4-chip gang must actually fit
    # on the surviving node(s) — phantom unplaced pods are gone.
    node = new_resource("Node", name, spec={"pool": "v5e", "chips": 8})
    node.status["ready"] = ready
    created = api.create(node)
    fresh = api.get("Node", name).thaw()
    fresh.status["ready"] = ready
    api.update_status(fresh)
    return created


def _drain(*controllers):
    for _ in range(50):
        for c in controllers:
            # Event dispatch is async (dispatcher thread); settle
            # detection must drain it before concluding "idle".
            c.controller._flush_events()
        if not any(c.controller.process_one() for c in controllers):
            return
    raise AssertionError("controllers did not settle")


def _make_running_gang(api, jobs, replicas=2):
    for i in range(replicas):
        _add_node(api, f"n{i}")
    job = new_resource(
        "TpuJob", "train", "ml",
        spec={"replicas": replicas, "image": "img", "command": ["run"],
              "maxRestarts": 2},
    )
    api.create(job)
    jobs.controller.run_until_idle()
    pods = api.list("Pod", "ml", label_selector={LABEL_JOB: "train"})
    assert len(pods) == replicas
    # Bind pods to nodes and mark Running (kubelet's role).
    for i, pod in enumerate(sorted(pods, key=lambda p: p.metadata.name)):
        fresh = api.get("Pod", pod.metadata.name, "ml").thaw()
        fresh.spec["nodeName"] = f"n{i}"
        api.update(fresh)
        fresh = api.get("Pod", pod.metadata.name, "ml").thaw()
        fresh.status["phase"] = "Running"
        api.update_status(fresh)
    jobs.controller.run_until_idle()
    assert api.get("TpuJob", "train", "ml").status["phase"] == "Running"


def test_ready_nodes_do_nothing(world):
    api, health, jobs, _ = world
    _make_running_gang(api, jobs)
    health.controller.run_until_idle()
    phases = [p.status["phase"] for p in api.list("Pod", "ml")]
    assert phases == ["Running", "Running"]


def test_node_deletion_fails_pods_and_restarts_gang(world):
    api, health, jobs, _ = world
    _make_running_gang(api, jobs)
    api.delete("Node", "n1")
    _drain(health, jobs)
    # The watchdog failed the stranded pod; the operator then tore the
    # gang down and recreated it (incarnation bumped).
    job = api.get("TpuJob", "train", "ml")
    assert job.status["restarts"] == 1
    pods = api.list("Pod", "ml", label_selector={LABEL_JOB: "train"})
    assert len(pods) == 2  # fresh gang
    assert all(p.status.get("phase") is None for p in pods)
    assert health.nodes_lost.value() == 1


def test_notready_waits_out_grace_period(world):
    api, health, jobs, clock = world
    _make_running_gang(api, jobs)
    fresh = api.get("Node", "n0").thaw()
    fresh.status["ready"] = False
    api.update_status(fresh)
    health.controller.run_until_idle()
    # Within grace: nothing failed yet, a timed recheck is pending.
    assert all(
        p.status["phase"] == "Running" for p in api.list("Pod", "ml")
    )
    assert health.controller.has_pending()
    # Node recovers before the grace expires: pods untouched.
    fresh = api.get("Node", "n0").thaw()
    fresh.status["ready"] = True
    api.update_status(fresh)
    clock.t += 31.0
    health.controller.run_until_idle()
    assert all(
        p.status["phase"] == "Running" for p in api.list("Pod", "ml")
    )


def test_notready_past_grace_fails_pods(world):
    api, health, jobs, clock = world
    _make_running_gang(api, jobs)
    fresh = api.get("Node", "n0").thaw()
    fresh.status["ready"] = False
    api.update_status(fresh)
    health.controller.run_until_idle()
    clock.t += 31.0
    # The timed requeue is not due in wall-clock terms; drive the key
    # directly (the controller's clock is injected, the queue's is not).
    health.controller.enqueue(("default", "n0"))
    _drain(health, jobs)
    job = api.get("TpuJob", "train", "ml")
    assert job.status["restarts"] == 1


def test_lost_node_pod_carries_reason(world):
    api, health, jobs, _ = world
    _make_running_gang(api, jobs)
    # Stop the job controller from reacting so we can inspect the pod.
    api.delete("Node", "n1")
    health.controller.run_until_idle()
    pods = [
        p for p in api.list("Pod", "ml")
        if p.spec.get("nodeName") == "n1"
    ]
    assert pods and pods[0].status["reason"] == REASON_NODE_LOST
    assert "preemption" in pods[0].status["message"]


def test_exhausted_restarts_terminal(world):
    api, health, jobs, _ = world
    _make_running_gang(api, jobs)

    def kill_and_drain(node):
        api.delete("Node", node)
        _drain(health, jobs)
        # Rebind the fresh gang across surviving nodes and mark Running
        # (the kubelet stand-in).
        alive = [n.metadata.name for n in api.list("Node")]
        pods = api.list("Pod", "ml", label_selector={LABEL_JOB: "train"})
        for i, pod in enumerate(sorted(pods, key=lambda p: p.metadata.name)):
            fresh = api.get("Pod", pod.metadata.name, "ml").thaw()
            if not fresh.spec.get("nodeName"):
                fresh.spec["nodeName"] = alive[i % len(alive)]
                api.update(fresh)
            fresh = api.get("Pod", pod.metadata.name, "ml").thaw()
            if fresh.status.get("phase") is None:
                fresh.status["phase"] = "Running"
                api.update_status(fresh)
        _drain(health, jobs)

    def hosts():
        # Where the gang actually runs — placement (compiled scheduler)
        # chooses, so the test kills whatever node hosts pods instead of
        # assuming a binding.
        return sorted({
            p.spec["nodeName"]
            for p in api.list("Pod", "ml",
                              label_selector={LABEL_JOB: "train"})
            if p.spec.get("nodeName")
        })

    _add_node(api, "spare")
    kill_and_drain(hosts()[0])  # restart 1 (gang re-places on survivors)
    assert api.get("TpuJob", "train", "ml").status["restarts"] == 1
    kill_and_drain(hosts()[0])  # restart 2 — at maxRestarts
    assert api.get("TpuJob", "train", "ml").status["restarts"] == 2
    for node in hosts():        # no budget left
        api.delete("Node", node)
    _drain(health, jobs)
    assert api.get("TpuJob", "train", "ml").status["phase"] == "Failed"
