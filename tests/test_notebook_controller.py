"""Notebook controller: reconcile to STS/Service/VirtualService, culling."""
import pytest

from kubeflow_tpu.api import new_resource
from kubeflow_tpu.controllers.notebook import (
    KIND,
    STOP_ANNOTATION,
    CullerConfig,
    NotebookController,
)
from kubeflow_tpu.testing import FakeApiServer


@pytest.fixture
def api():
    return FakeApiServer()


def _make_nb(api, name="nb", ns="user1", **spec):
    return api.create(new_resource(KIND, name, ns, spec=spec))


def test_children_created(api):
    ctl = NotebookController(api)
    _make_nb(api, image="jax-notebook:1")
    ctl.controller.run_until_idle()

    sts = api.get("StatefulSet", "nb", "user1")
    assert sts.spec["replicas"] == 1
    container = sts.spec["template"]["spec"]["containers"][0]
    assert container["image"] == "jax-notebook:1"
    assert {"name": "NB_PREFIX", "value": "/notebook/user1/nb"} in container["env"]

    svc = api.get("Service", "nb", "user1")
    assert svc.spec["ports"][0] == {"port": 80, "targetPort": 8888}

    vs = api.get("VirtualService", "notebook-user1-nb", "user1")
    assert vs.spec["http"][0]["match"][0]["uri"]["prefix"] == "/notebook/user1/nb/"
    assert ctl.created_total.value() == 1


def test_stop_annotation_scales_to_zero(api):
    ctl = NotebookController(api)
    _make_nb(api)
    ctl.controller.run_until_idle()
    nb = api.get(KIND, "nb", "user1").thaw()
    nb.metadata.annotations[STOP_ANNOTATION] = "now"
    api.update(nb)
    ctl.controller.run_until_idle()
    assert api.get("StatefulSet", "nb", "user1").spec["replicas"] == 0


def test_status_mirrors_pod(api):
    ctl = NotebookController(api)
    _make_nb(api)
    ctl.controller.run_until_idle()
    pod = new_resource("Pod", "nb-0", "user1", labels={"notebook": "nb"})
    api.create(pod)
    pod = api.get("Pod", "nb-0", "user1").thaw()
    pod.status["phase"] = "Running"
    api.update_status(pod)
    ctl.controller.run_until_idle()
    status = api.get(KIND, "nb", "user1").status
    assert status["readyReplicas"] == 1
    assert status["containerState"] == "Running"
    assert ctl.running.value() == 1


def _run_pod(api, name="nb-0", ns="user1", nb="nb"):
    api.create(new_resource("Pod", name, ns, labels={"notebook": nb},
                            spec={"containers": [{"name": "nb"}]}))
    pod = api.get("Pod", name, ns).thaw()
    pod.status["phase"] = "Running"
    api.update_status(pod)


def test_culler_stops_idle_notebook(api):
    clock = {"now": 10_000.0}
    ctl = NotebookController(
        api,
        culler=CullerConfig(enabled=True, idle_seconds=600),
        activity_probe=lambda nb: 9000.0,  # idle for 1000s
        clock=lambda: clock["now"],
    )
    _make_nb(api)
    _run_pod(api)  # culling only applies to a running workload
    ctl.controller.run_until_idle()
    nb = api.get(KIND, "nb", "user1")
    assert STOP_ANNOTATION in nb.metadata.annotations
    assert ctl.culled_total.value() == 1
    ctl.controller.run_until_idle()
    assert api.get("StatefulSet", "nb", "user1").spec["replicas"] == 0


def test_culler_spares_active_notebook(api):
    ctl = NotebookController(
        api,
        culler=CullerConfig(enabled=True, idle_seconds=600),
        activity_probe=lambda nb: 9900.0,
        clock=lambda: 10_000.0,
    )
    _make_nb(api)
    ctl.controller.run_until_idle()
    assert STOP_ANNOTATION not in api.get(KIND, "nb", "user1").metadata.annotations


def test_unreachable_probe_fails_safe(api):
    ctl = NotebookController(
        api,
        culler=CullerConfig(enabled=True, idle_seconds=0),
        activity_probe=lambda nb: None,
    )
    _make_nb(api)
    _run_pod(api)
    ctl.controller.run_until_idle()
    assert STOP_ANNOTATION not in api.get(KIND, "nb", "user1").metadata.annotations


def test_pending_notebook_not_culled(api):
    ctl = NotebookController(
        api,
        culler=CullerConfig(enabled=True, idle_seconds=0),
        activity_probe=lambda nb: 0.0,  # "idle forever"
    )
    _make_nb(api)  # no running pod yet
    ctl.controller.run_until_idle()
    assert STOP_ANNOTATION not in api.get(KIND, "nb", "user1").metadata.annotations


# -- production activity probes --------------------------------------------


def test_http_activity_probe_reads_jupyter_status():
    """The culler.go:138 probe against a real HTTP endpoint serving the
    Jupyter /api/status shape."""
    import json as _json

    from kubeflow_tpu.controllers.notebook import (
        http_activity_probe,
        route_prefix,
    )
    from kubeflow_tpu.web.wsgi import App, json_response, serve

    nb = new_resource("Notebook", "nb", "team")

    app = App("fake-jupyter")
    app.add_route(
        f"{route_prefix(nb)}/api/status",
        lambda req: json_response(
            {"last_activity": "2026-01-02T03:04:05.000000Z"}
        ),
    )
    server, _ = serve(app, host="127.0.0.1", port=0)
    try:
        probe = http_activity_probe(
            base_url=lambda _nb: f"http://127.0.0.1:{server.server_port}"
        )
        stamp = probe(nb)
    finally:
        server.shutdown()
    import datetime

    want = datetime.datetime(
        2026, 1, 2, 3, 4, 5, tzinfo=datetime.timezone.utc
    ).timestamp()
    assert stamp == want


def test_http_activity_probe_fail_safe():
    from kubeflow_tpu.controllers.notebook import http_activity_probe

    nb = new_resource("Notebook", "nb", "team")
    # Nothing listening: unreachable => None (never cull on probe failure).
    probe = http_activity_probe(
        base_url=lambda _nb: "http://127.0.0.1:1", timeout=0.2
    )
    assert probe(nb) is None


def test_tpu_duty_probe_counts_busy_chips_as_activity():
    from kubeflow_tpu.controllers.notebook import tpu_duty_probe

    api = FakeApiServer()
    nb = new_resource("Notebook", "nb", "team")
    node = new_resource("Node", "tpu-0", "", spec={"chips": 4})
    node.status["tpuDutyCycle"] = 0.9
    api.create(node)
    pod = new_resource(
        "Pod", "nb-0", "team",
        spec={"nodeName": "tpu-0", "containers": [
            {"name": "nb", "resources": {"limits": {"google.com/tpu": 4}}}
        ]},
        labels={"notebook": "nb"},
    )
    pod.status["phase"] = "Running"
    api.create(pod)

    now = {"t": 1000.0}
    probe = tpu_duty_probe(api, clock=lambda: now["t"])
    assert probe(nb) == 1000.0  # busy TPU = active right now

    # A CPU-only notebook on the same (busy) node must NOT ride the
    # co-tenant's duty cycle.
    cpu_nb = new_resource("Notebook", "cpu-nb", "team")
    cpu_pod = new_resource(
        "Pod", "cpu-nb-0", "team",
        spec={"nodeName": "tpu-0", "containers": [{"name": "nb"}]},
        labels={"notebook": "cpu-nb"},
    )
    cpu_pod.status["phase"] = "Running"
    api.create(cpu_pod)
    assert probe(cpu_nb) is None
    fresh = api.get("Node", "tpu-0", "").thaw()
    fresh.status["tpuDutyCycle"] = 0.0
    api.update_status(fresh)
    assert probe(nb) is None  # idle chips: no claimed activity


def test_combined_probe_takes_latest_and_culler_respects_it():
    """A notebook idle in Jupyter but running TPU kernels must NOT be
    culled; once the chips idle too, it is."""
    from kubeflow_tpu.controllers.notebook import (
        CullerConfig,
        STOP_ANNOTATION,
        combined_probe,
        tpu_duty_probe,
    )

    api = FakeApiServer()
    now = {"t": 10_000.0}
    jupyter_last = {"t": 0.0}  # idle in the UI since t=0
    ctl = NotebookController(
        api,
        culler=CullerConfig(enabled=True, idle_seconds=100.0),
        activity_probe=combined_probe(
            lambda nb: jupyter_last["t"],
            tpu_duty_probe(api, clock=lambda: now["t"]),
        ),
        clock=lambda: now["t"],
    )
    api.create(new_resource("Notebook", "nb", "team", spec={"image": "i"}))
    node = new_resource("Node", "tpu-0", "", spec={"chips": 4})
    node.status["tpuDutyCycle"] = 0.8
    api.create(node)
    ctl.controller.run_until_idle()
    pod = new_resource(
        "Pod", "nb-0", "team",
        spec={"nodeName": "tpu-0", "containers": [
            {"name": "nb", "resources": {"limits": {"google.com/tpu": 4}}}
        ]},
        labels={"notebook": "nb"},
    )
    pod.status["phase"] = "Running"
    api.create(pod)
    ctl.controller.run_until_idle()
    nb = api.get("Notebook", "nb", "team")
    assert STOP_ANNOTATION not in nb.metadata.annotations  # chips busy

    fresh = api.get("Node", "tpu-0", "").thaw()
    fresh.status["tpuDutyCycle"] = 0.0
    api.update_status(fresh)
    now["t"] += 200.0  # idle everywhere, past IDLE_TIME
    ctl.controller.enqueue(("team", "nb"))
    ctl.controller.run_until_idle()
    nb = api.get("Notebook", "nb", "team")
    assert STOP_ANNOTATION in nb.metadata.annotations  # culled
