"""Notebook controller: reconcile to STS/Service/VirtualService, culling."""
import pytest

from kubeflow_tpu.api import new_resource
from kubeflow_tpu.controllers.notebook import (
    KIND,
    STOP_ANNOTATION,
    CullerConfig,
    NotebookController,
)
from kubeflow_tpu.testing import FakeApiServer


@pytest.fixture
def api():
    return FakeApiServer()


def _make_nb(api, name="nb", ns="user1", **spec):
    return api.create(new_resource(KIND, name, ns, spec=spec))


def test_children_created(api):
    ctl = NotebookController(api)
    _make_nb(api, image="jax-notebook:1")
    ctl.controller.run_until_idle()

    sts = api.get("StatefulSet", "nb", "user1")
    assert sts.spec["replicas"] == 1
    container = sts.spec["template"]["spec"]["containers"][0]
    assert container["image"] == "jax-notebook:1"
    assert {"name": "NB_PREFIX", "value": "/notebook/user1/nb"} in container["env"]

    svc = api.get("Service", "nb", "user1")
    assert svc.spec["ports"][0] == {"port": 80, "targetPort": 8888}

    vs = api.get("VirtualService", "notebook-user1-nb", "user1")
    assert vs.spec["http"][0]["match"][0]["uri"]["prefix"] == "/notebook/user1/nb/"
    assert ctl.created_total.value() == 1


def test_stop_annotation_scales_to_zero(api):
    ctl = NotebookController(api)
    _make_nb(api)
    ctl.controller.run_until_idle()
    nb = api.get(KIND, "nb", "user1")
    nb.metadata.annotations[STOP_ANNOTATION] = "now"
    api.update(nb)
    ctl.controller.run_until_idle()
    assert api.get("StatefulSet", "nb", "user1").spec["replicas"] == 0


def test_status_mirrors_pod(api):
    ctl = NotebookController(api)
    _make_nb(api)
    ctl.controller.run_until_idle()
    pod = new_resource("Pod", "nb-0", "user1", labels={"notebook": "nb"})
    api.create(pod)
    pod = api.get("Pod", "nb-0", "user1")
    pod.status["phase"] = "Running"
    api.update_status(pod)
    ctl.controller.run_until_idle()
    status = api.get(KIND, "nb", "user1").status
    assert status["readyReplicas"] == 1
    assert status["containerState"] == "Running"
    assert ctl.running.value() == 1


def _run_pod(api, name="nb-0", ns="user1", nb="nb"):
    api.create(new_resource("Pod", name, ns, labels={"notebook": nb},
                            spec={"containers": [{"name": "nb"}]}))
    pod = api.get("Pod", name, ns)
    pod.status["phase"] = "Running"
    api.update_status(pod)


def test_culler_stops_idle_notebook(api):
    clock = {"now": 10_000.0}
    ctl = NotebookController(
        api,
        culler=CullerConfig(enabled=True, idle_seconds=600),
        activity_probe=lambda nb: 9000.0,  # idle for 1000s
        clock=lambda: clock["now"],
    )
    _make_nb(api)
    _run_pod(api)  # culling only applies to a running workload
    ctl.controller.run_until_idle()
    nb = api.get(KIND, "nb", "user1")
    assert STOP_ANNOTATION in nb.metadata.annotations
    assert ctl.culled_total.value() == 1
    ctl.controller.run_until_idle()
    assert api.get("StatefulSet", "nb", "user1").spec["replicas"] == 0


def test_culler_spares_active_notebook(api):
    ctl = NotebookController(
        api,
        culler=CullerConfig(enabled=True, idle_seconds=600),
        activity_probe=lambda nb: 9900.0,
        clock=lambda: 10_000.0,
    )
    _make_nb(api)
    ctl.controller.run_until_idle()
    assert STOP_ANNOTATION not in api.get(KIND, "nb", "user1").metadata.annotations


def test_unreachable_probe_fails_safe(api):
    ctl = NotebookController(
        api,
        culler=CullerConfig(enabled=True, idle_seconds=0),
        activity_probe=lambda nb: None,
    )
    _make_nb(api)
    _run_pod(api)
    ctl.controller.run_until_idle()
    assert STOP_ANNOTATION not in api.get(KIND, "nb", "user1").metadata.annotations


def test_pending_notebook_not_culled(api):
    ctl = NotebookController(
        api,
        culler=CullerConfig(enabled=True, idle_seconds=0),
        activity_probe=lambda nb: 0.0,  # "idle forever"
    )
    _make_nb(api)  # no running pod yet
    ctl.controller.run_until_idle()
    assert STOP_ANNOTATION not in api.get(KIND, "nb", "user1").metadata.annotations
