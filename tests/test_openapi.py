"""OpenAPI drift gates: docs/api/*.yaml must match the live route tables.

The reference ships a swagger spec for its deploy service
(`bootstrap/api/swagger.yaml`) and kfam is swagger-generated; our specs
are checked in and this gate fails CI the moment a route and its spec
disagree (VERDICT round-1 item #6).
"""

import pathlib

import pytest
import yaml

from kubeflow_tpu.apps.kfam import KfamApp
from kubeflow_tpu.controllers.webhook import MutatingWebhookApp
from kubeflow_tpu.deploy.provisioner import FakeCloud
from kubeflow_tpu.deploy.server import DeployServer
from kubeflow_tpu.testing.apiserver_http import ApiServerApp
from kubeflow_tpu.testing.fake_apiserver import FakeApiServer
from kubeflow_tpu.web.openapi import (
    route_table,
    skeleton,
    spec_drift,
    spec_operations,
)

DOCS = pathlib.Path(__file__).resolve().parent.parent / "docs" / "api"


def _apps():
    api = FakeApiServer()
    return {
        "apiserver.yaml": ApiServerApp(api),
        "kfam.yaml": KfamApp(api),
        "deploy.yaml": DeployServer(api, FakeCloud(api)),
        "webhook.yaml": MutatingWebhookApp(lambda obj, op: obj),
    }


@pytest.mark.parametrize("spec_file", ["apiserver.yaml", "kfam.yaml",
                                       "deploy.yaml", "webhook.yaml"])
def test_spec_matches_routes(spec_file):
    app = _apps()[spec_file]
    spec = yaml.safe_load((DOCS / spec_file).read_text())
    drift = spec_drift(app, spec)
    assert not drift, "\n".join(drift)


@pytest.mark.parametrize("spec_file", ["apiserver.yaml", "kfam.yaml",
                                       "deploy.yaml", "webhook.yaml"])
def test_spec_is_valid_openapi3_shape(spec_file):
    spec = yaml.safe_load((DOCS / spec_file).read_text())
    assert spec["openapi"].startswith("3.")
    assert spec["info"]["title"] and spec["info"]["version"]
    assert spec_operations(spec)
    for path, ops in spec["paths"].items():
        assert path.startswith("/")
        for method, op in ops.items():
            assert "responses" in op, f"{method} {path} has no responses"
            # Every templated path parameter is declared.
            declared = {
                p["name"]
                for p in op.get("parameters", [])
                if p.get("in") == "path"
            }
            import re

            for param in re.findall(r"\{([a-zA-Z_][a-zA-Z0-9_]*)\}", path):
                assert param in declared, (
                    f"{method} {path}: path param {param!r} undeclared"
                )


def test_drift_gate_catches_both_directions():
    api = FakeApiServer()
    app = ApiServerApp(api)
    spec = skeleton(app, "t")
    assert spec_drift(app, spec) == []
    # Route removed from the spec → flagged.
    broken = yaml.safe_load(yaml.safe_dump(spec))
    broken["paths"].pop("/debug/traces")
    assert any("route not in spec" in d for d in spec_drift(app, broken))
    # Spec documents a route that does not exist → flagged.
    broken2 = yaml.safe_load(yaml.safe_dump(spec))
    broken2["paths"]["/ghost"] = {
        "get": {"responses": {"200": {"description": "x"}}}
    }
    assert any("missing route" in d for d in spec_drift(app, broken2))


def test_route_table_extraction():
    api = FakeApiServer()
    routes = route_table(ApiServerApp(api))
    assert ("get", "/apis/{kind}") in routes
    assert ("put", "/apis/{kind}/{ns}/{name}/status") in routes
    assert ("get", "/healthz") in routes
