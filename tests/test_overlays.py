"""Overlay engine — the kustomize-overlay analog over generated bundles
(the reference's per-component `config/{default,overlays}` kustomize
tree, applied by kfctl's K8S phase)."""

import pathlib

import pytest

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.ci.application_util import render_overlaid_yaml
from kubeflow_tpu.deploy.bundles import bundle_resources
from kubeflow_tpu.deploy.kfdef import default_spec
from kubeflow_tpu.deploy.overlays import (
    ImageRule,
    Overlay,
    Patch,
    apply_overlay,
    strategic_merge,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


# -- strategic merge -------------------------------------------------------


def test_merge_dicts_recursively():
    out = strategic_merge(
        {"a": {"x": 1, "y": 2}, "b": 3}, {"a": {"y": 9, "z": 8}}
    )
    assert out == {"a": {"x": 1, "y": 9, "z": 8}, "b": 3}


def test_merge_null_deletes():
    assert strategic_merge({"a": 1, "b": 2}, {"a": None}) == {"b": 2}


def test_merge_named_lists_by_name():
    base = [{"name": "c1", "image": "a"}, {"name": "c2", "image": "b"}]
    patch = [{"name": "c2", "image": "B"}, {"name": "c3", "image": "c"}]
    out = strategic_merge(base, patch)
    assert out == [
        {"name": "c1", "image": "a"},
        {"name": "c2", "image": "B"},
        {"name": "c3", "image": "c"},
    ]


def test_merge_plain_lists_replace():
    assert strategic_merge({"l": [1, 2]}, {"l": [3]}) == {"l": [3]}


# -- overlay application ---------------------------------------------------


def _deploy(name="web", image="repo/app:v1"):
    return new_resource(
        "Deployment",
        name,
        "kubeflow",
        spec={
            "replicas": 1,
            "template": {
                "spec": {"containers": [{"name": name, "image": image}]}
            },
        },
    )


def test_prefix_namespace_labels_and_cluster_scope():
    overlay = Overlay(
        name_prefix="dev-", namespace="kubeflow-dev",
        common_labels={"env": "dev"},
    )
    ns_scoped = _deploy()
    cluster = new_resource("ClusterRole", "admin", "")
    out = apply_overlay([ns_scoped, cluster], overlay)
    assert out[0].metadata.name == "dev-web"
    assert out[0].metadata.namespace == "kubeflow-dev"
    assert out[0].metadata.labels["env"] == "dev"
    assert out[1].metadata.namespace == ""  # cluster scope preserved
    # Inputs untouched.
    assert ns_scoped.metadata.name == "web"


def test_image_rules_rewrite_everywhere():
    overlay = Overlay(
        images=(ImageRule("repo/app", new_tag="v2"),
                ImageRule("repo/other", new_name="mirror/other")),
    )
    out = apply_overlay(
        [_deploy(), _deploy("other", "repo/other:v1")], overlay
    )
    assert (
        out[0].spec["template"]["spec"]["containers"][0]["image"]
        == "repo/app:v2"
    )
    assert (
        out[1].spec["template"]["spec"]["containers"][0]["image"]
        == "mirror/other:v1"
    )


def test_patch_targets_original_name_before_prefix():
    overlay = Overlay(
        name_prefix="dev-",
        patches=(Patch(target_kind="Deployment", target_name="web",
                       patch={"spec": {"replicas": 5}}),),
    )
    out = apply_overlay([_deploy()], overlay)
    assert out[0].metadata.name == "dev-web"
    assert out[0].spec["replicas"] == 5


def test_patch_glob_and_kind_filter():
    overlay = Overlay(
        patches=(Patch(target_kind="Deployment", target_name="*web*",
                       patch={"spec": {"replicas": 3}}),),
    )
    deploy, svc = _deploy(), new_resource("Service", "web", "kubeflow",
                                          spec={"ports": []})
    out = apply_overlay([deploy, svc], overlay)
    assert out[0].spec["replicas"] == 3
    assert "replicas" not in out[1].spec


def test_common_labels_reach_pod_template_and_selector():
    overlay = Overlay(common_labels={"env": "dev"})
    out = apply_overlay([_deploy()], overlay)
    assert out[0].metadata.labels["env"] == "dev"
    assert out[0].spec["template"]["metadata"]["labels"]["env"] == "dev"
    assert out[0].spec["selector"]["matchLabels"]["env"] == "dev"


def test_namespace_transformer_renames_namespace_resource():
    overlay = Overlay(name_prefix="dev-", namespace="kubeflow-dev")
    ns = new_resource("Namespace", "kubeflow", "")
    out = apply_overlay([ns, _deploy()], overlay)
    # The Namespace resource becomes the target namespace, unprefixed —
    # so the namespace every workload moved into actually exists.
    assert out[0].metadata.name == "kubeflow-dev"
    assert out[1].metadata.namespace == "kubeflow-dev"


def test_rename_fixes_virtualservice_references():
    overlay = Overlay(name_prefix="dev-", namespace="kubeflow-dev")
    svc = new_resource("Service", "dash", "kubeflow", spec={"ports": []})
    vs = new_resource(
        "VirtualService",
        "dash",
        "kubeflow",
        spec={
            "gateways": ["kubeflow/kubeflow-gateway"],
            "http": [{"route": [{"destination": {
                "host": "dash.kubeflow.svc.cluster.local"}}]}],
        },
    )
    gw = new_resource("Gateway", "kubeflow-gateway", "kubeflow", spec={})
    out = apply_overlay([svc, vs, gw], overlay)
    vs2 = out[1]
    assert vs2.spec["http"][0]["route"][0]["destination"]["host"] == (
        "dev-dash.kubeflow-dev.svc.cluster.local"
    )
    assert vs2.spec["gateways"] == ["kubeflow-dev/dev-kubeflow-gateway"]


def test_images_pin_patch_introduced_containers():
    """kustomize transformer order: images run AFTER patches, so a
    container a patch adds is still tag-pinned."""
    overlay = Overlay(
        images=(ImageRule("repo/app", new_tag="v2"),),
        patches=(Patch(target_kind="Deployment", patch={"spec": {
            "template": {"spec": {"containers": [
                {"name": "sidecar", "image": "repo/app:latest"}]}}}}),),
    )
    out = apply_overlay([_deploy()], overlay)
    images = {
        c["name"]: c["image"]
        for c in out[0].spec["template"]["spec"]["containers"]
    }
    assert images == {"web": "repo/app:v2", "sidecar": "repo/app:v2"}


def test_image_rule_port_and_digest():
    rule = ImageRule("localhost:5000/app", new_tag="v2")
    assert rule.rewrite("localhost:5000/app:v1") == "localhost:5000/app:v2"
    assert rule.rewrite("localhost:5000/other:v1") == "localhost:5000/other:v1"
    digest = ImageRule("repo/app", new_tag="v3")
    assert digest.rewrite("repo/app@sha256:abc") == "repo/app:v3"
    keep = ImageRule("repo/app", new_name="mirror/app")
    assert keep.rewrite("repo/app@sha256:abc") == "mirror/app@sha256:abc"


def test_unknown_overlay_key_raises():
    with pytest.raises(ValueError, match="unknown overlay keys"):
        Overlay.from_dict({"commonLabel": {"env": "dev"}})


# -- integration: PlatformSpec + shipped overlays --------------------------


def test_platformspec_overlays_flow_through_bundles():
    spec = default_spec()
    spec.overlays = [
        {"namePrefix": "dev-", "commonLabels": {"env": "dev"}}
    ]
    resources = bundle_resources(spec)
    assert resources, "bundles rendered"
    assert all(r.metadata.name.startswith("dev-") for r in resources)
    assert all(r.metadata.labels.get("env") == "dev" for r in resources)
    # Round-trips through YAML (the KfDef surface).
    from kubeflow_tpu.deploy.kfdef import PlatformSpec

    again = PlatformSpec.from_yaml(spec.to_yaml())
    assert again.overlays == spec.overlays


def test_shipped_dev_overlay_renders():
    out = render_overlaid_yaml(
        "centraldashboard", [str(REPO / "manifests/overlays/dev.yaml")]
    )
    assert "dev-centraldashboard" in out
    assert "kubeflow-dev" in out
    assert "LOG_LEVEL" in out


def test_shipped_prod_overlay_renders():
    out = render_overlaid_yaml(
        "jupyter-web-app", [str(REPO / "manifests/overlays/prod.yaml")]
    )
    assert ":v1.0.0" in out


def test_overlay_load_rejects_non_mapping(tmp_path):
    bad = tmp_path / "bad.yaml"
    bad.write_text("- just\n- a list\n")
    with pytest.raises(ValueError, match="mapping"):
        Overlay.load(bad)


def test_nested_overlay_keys_validated():
    with pytest.raises(ValueError, match="image-rule"):
        Overlay.from_dict({"images": [{"name": "a", "tag": "v2"}]})
    with pytest.raises(ValueError, match="patch target"):
        Overlay.from_dict({"patches": [{"target": {"labelSelector": "x"},
                                        "patch": {}}]})
    with pytest.raises(ValueError, match="patch keys"):
        Overlay.from_dict({"patches": [{"merge": {}}]})
