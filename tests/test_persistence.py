"""Durable-store tests: the control plane survives losing its process.

The reference never had to build this layer — it rides etcd (its envtest
fixture spins a real etcd+apiserver, `profile-controller/controllers/
suite_test.go:29-54`). These tests pin the equivalent property for our
WAL+snapshot persistence: kill the server object, rebuild it over the
same directory, and the CRs, resourceVersions, and watch-recovery
semantics are intact. Both backends (native wal.cc and the pure-Python
twin) are exercised.
"""

import json
import os

import pytest

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.testing import persist
from kubeflow_tpu.testing.fake_apiserver import (
    FakeApiServer,
    Gone,
    Invalid,
    NotFound,
)

BACKENDS = ["python", "native"]


@pytest.fixture(params=BACKENDS)
def backend(request):
    if request.param == "native":
        pytest.importorskip("kubeflow_tpu.native.core")
    return request.param


def _server(tmp_path, backend, **kw):
    return FakeApiServer(
        persist_dir=str(tmp_path / "state"), wal_backend=backend, **kw
    )


def test_cold_start_from_empty_dir(tmp_path, backend):
    api = _server(tmp_path, backend)
    assert api.current_rv == 0
    assert api.list("ConfigMap") == []
    api.create(new_resource("ConfigMap", "a", spec={"k": "v"}))
    api.close()
    # The directory now holds the versioned format.
    snap = json.loads((tmp_path / "state" / "snapshot.json").read_text())
    assert snap["format"] == persist.FORMAT


def test_restart_restores_objects_and_rv(tmp_path, backend):
    api = _server(tmp_path, backend)
    api.create(new_resource("ConfigMap", "a", spec={"k": "v1"}))
    b = api.create(new_resource("TpuJob", "train", spec={"replicas": 4})).thaw()
    b.spec["replicas"] = 8
    api.update(b)
    job = api.get("TpuJob", "train").thaw()
    job.status = {"phase": "Running"}
    api.update_status(job)
    api.create(new_resource("ConfigMap", "gone", spec={}))
    api.delete("ConfigMap", "gone")
    rv_before = api.current_rv
    uid_before = api.get("TpuJob", "train").metadata.uid
    del api  # no close(): simulate the process dying without a checkpoint

    api2 = _server(tmp_path, backend)
    assert api2.current_rv == rv_before
    restored = api2.get("TpuJob", "train")
    assert restored.spec == {"replicas": 8}
    assert restored.status == {"phase": "Running"}
    assert restored.metadata.uid == uid_before
    assert restored.metadata.generation == 2
    assert api2.get("ConfigMap", "a").spec == {"k": "v1"}
    with pytest.raises(NotFound):
        api2.get("ConfigMap", "gone")
    # Writes continue with monotonic rvs (no reuse of pre-crash numbers).
    c = api2.create(new_resource("ConfigMap", "after", spec={}))
    assert c.metadata.resource_version == rv_before + 1


def test_restart_preserves_finalizers_and_deletion_timestamp(
    tmp_path, backend
):
    api = _server(tmp_path, backend)
    obj = new_resource("Profile", "team", spec={})
    obj.metadata.finalizers = ["profile-finalizer"]
    api.create(obj)
    api.delete("Profile", "team")  # parks: finalizer pending
    del api

    api2 = _server(tmp_path, backend)
    parked = api2.get("Profile", "team")
    assert parked.metadata.deletion_timestamp is not None
    assert parked.metadata.finalizers == ["profile-finalizer"]
    # Clearing the finalizer post-restart completes the delete.
    parked = parked.thaw()
    parked.metadata.finalizers = []
    api2.update(parked)
    with pytest.raises(NotFound):
        api2.get("Profile", "team")


def test_watch_bookmark_from_before_restart_gets_gone(tmp_path, backend):
    api = _server(tmp_path, backend)
    api.create(new_resource("ConfigMap", "a", spec={}))
    api.create(new_resource("ConfigMap", "b", spec={}))
    old_rv = 1  # a watcher that saw only the first event
    del api

    api2 = _server(tmp_path, backend)
    # Pre-restart bookmarks can't be served from the fresh journal: the
    # informer contract is 410 Gone → relist, never a silent gap.
    with pytest.raises(Gone):
        api2.events_since(old_rv)
    # The current rv is a valid resume point.
    events, rv = api2.events_since(api2.current_rv)
    assert events == [] and rv == api2.current_rv
    api2.create(new_resource("ConfigMap", "c", spec={}))
    events, _ = api2.events_since(rv)
    assert [e[1] for e in events] == ["ADDED"]


def test_snapshot_compaction_truncates_wal(tmp_path, backend):
    api = _server(tmp_path, backend, snapshot_every=5)
    for i in range(12):
        api.create(new_resource("ConfigMap", f"cm-{i}", spec={"i": i}))
    wal_lines = [
        line
        for line in (tmp_path / "state" / "wal.log").read_text().splitlines()
        if line
    ]
    # 12 appends with a snapshot every 5: the WAL holds only the tail.
    assert len(wal_lines) == 2
    del api

    api2 = _server(tmp_path, backend)
    assert len(api2.list("ConfigMap")) == 12
    assert api2.current_rv == 12


def test_torn_tail_is_dropped(tmp_path, backend):
    api = _server(tmp_path, backend)
    api.create(new_resource("ConfigMap", "a", spec={}))
    api.create(new_resource("ConfigMap", "b", spec={}))
    del api
    wal = tmp_path / "state" / "wal.log"
    # Crash mid-append: the final record is half-written.
    wal.write_bytes(wal.read_bytes()[:-20])

    api2 = _server(tmp_path, backend)
    assert [r.metadata.name for r in api2.list("ConfigMap")] == ["a"]
    assert api2.current_rv == 1


def test_future_format_is_refused(tmp_path, backend):
    api = _server(tmp_path, backend)
    api.create(new_resource("ConfigMap", "a", spec={}))
    api.close()
    snap_path = tmp_path / "state" / "snapshot.json"
    snap = json.loads(snap_path.read_text())
    snap["format"] = persist.FORMAT + 1
    snap_path.write_text(json.dumps(snap))
    with pytest.raises(Invalid, match="format"):
        _server(tmp_path, backend)


def test_graceful_close_then_reopen(tmp_path, backend):
    api = _server(tmp_path, backend)
    api.create(new_resource("ConfigMap", "a", spec={}))
    api.close()
    # close() checkpointed: everything lives in the snapshot, WAL empty.
    assert (tmp_path / "state" / "wal.log").read_text() == ""
    api2 = _server(tmp_path, backend)
    assert api2.get("ConfigMap", "a").metadata.name == "a"


def test_crash_between_snapshot_and_truncate_is_safe(tmp_path, backend):
    """Stale pre-snapshot WAL records (legal after a crash inside
    snapshot()) are skipped by rv on replay, not double-applied."""
    api = _server(tmp_path, backend)
    obj = api.create(new_resource("ConfigMap", "a", spec={"v": 1})).thaw()
    obj.spec["v"] = 2
    api.update(obj)
    api.checkpoint()
    del api
    state = tmp_path / "state"
    # Re-prepend the pre-snapshot records the truncate removed, with an
    # OLD object payload — replay must ignore them (rv <= snapshot rv).
    stale = {
        "rv": 1,
        "event": "ADDED",
        "object": new_resource("ConfigMap", "a", spec={"v": 666}).to_dict(),
    }
    existing = (state / "wal.log").read_text()
    (state / "wal.log").write_text(json.dumps(stale) + "\n" + existing)

    api2 = _server(tmp_path, backend)
    assert api2.get("ConfigMap", "a").spec == {"v": 2}


def test_non_durable_server_has_no_side_effects(tmp_path):
    api = FakeApiServer()
    api.create(new_resource("ConfigMap", "a", spec={}))
    api.checkpoint()  # no-op without persistence
    api.close()
    assert list(tmp_path.iterdir()) == []


def test_pywal_matches_native_layout(tmp_path):
    """Both backends write the same on-disk layout: a directory written
    by one restores under the other (operators can move between images
    with and without the native toolchain)."""
    pytest.importorskip("kubeflow_tpu.native.core")
    api = _server(tmp_path, "native")
    api.create(new_resource("ConfigMap", "a", spec={"k": "v"}))
    api.checkpoint()
    api.create(new_resource("ConfigMap", "b", spec={}))
    api.close()

    api2 = _server(tmp_path, "python")
    assert {r.metadata.name for r in api2.list("ConfigMap")} == {"a", "b"}
    api2.create(new_resource("ConfigMap", "c", spec={}))
    api2.close()

    api3 = _server(tmp_path, "native")
    assert {r.metadata.name for r in api3.list("ConfigMap")} == {
        "a", "b", "c",
    }


def test_acked_write_after_torn_tail_survives_next_restart(
    tmp_path, backend
):
    """The torn tail is REPAIRED on restore (folded into a snapshot), so
    a post-restart acked write can't glue onto the partial line and be
    silently dropped by the restart after that."""
    api = _server(tmp_path, backend)
    api.create(new_resource("ConfigMap", "a", spec={}))
    api.create(new_resource("ConfigMap", "b", spec={}))
    del api
    wal = tmp_path / "state" / "wal.log"
    wal.write_bytes(wal.read_bytes()[:-20])  # crash mid-append of 'b'

    api2 = _server(tmp_path, backend)
    api2.create(new_resource("ConfigMap", "c", spec={}))
    del api2

    api3 = _server(tmp_path, backend)
    assert {r.metadata.name for r in api3.list("ConfigMap")} == {"a", "c"}


def test_wal_failure_fail_stops_the_store(tmp_path, backend):
    """ADVICE r4: a WAL append that raises must never leave the mutation
    observable — the client got an error, so the write must not be
    visible now (divergence from the log) nor vanish-later (a restart
    dropping state a reader already saw). The store fail-stops: every
    subsequent op raises Unavailable, and close() must NOT snapshot the
    divergent in-memory state over the intact log."""
    from kubeflow_tpu.testing.fake_apiserver import Unavailable

    api = _server(tmp_path, backend)
    api.create(new_resource("ConfigMap", "good", spec={"k": "v"}))

    class _Boom(RuntimeError):
        pass

    real_wal = api._wal

    class _BrokenWal:
        def append(self, line):
            raise _Boom("disk full")

        def snapshot(self, text):
            raise _Boom("disk full")

        def close(self):
            real_wal.close()

    api._wal = _BrokenWal()
    with pytest.raises(Unavailable):
        api.create(new_resource("ConfigMap", "lost", spec={"k": "v"}))
    # Errored write is unobservable: reads refuse rather than serve the
    # diverged map.
    for op in (
        lambda: api.get("ConfigMap", "lost"),
        lambda: api.get("ConfigMap", "good"),
        lambda: api.list("ConfigMap"),
        lambda: api.create(new_resource("ConfigMap", "later")),
        lambda: api.delete("ConfigMap", "good"),
    ):
        with pytest.raises(Unavailable):
            op()
    # close() must not legitimize the divergence via a snapshot.
    api.close()
    reopened = _server(tmp_path, backend)
    assert reopened.get("ConfigMap", "good").spec["k"] == "v"
    with pytest.raises(NotFound):
        reopened.get("ConfigMap", "lost")
    reopened.close()


def test_writer_racing_a_fail_stop_cannot_commit_unlogged(tmp_path, backend):
    """A writer that passed create()'s unlocked precheck before another
    thread fail-stopped must still error (not journal/deliver an event
    that was never WAL'd): _emit re-checks under the lock."""
    from kubeflow_tpu.testing.fake_apiserver import Unavailable

    api = _server(tmp_path, backend)
    api._broken = RuntimeError("disk full")  # as _fail_stop_locked leaves it
    api._wal.close()
    api._wal = None
    with api._lock:
        with pytest.raises(Unavailable):
            # Direct _emit: the state a post-precheck writer reaches.
            api._emit("ADDED", new_resource("ConfigMap", "racy"))
