"""SPMD pipeline parallelism: GPipe and interleaved (circular) schedules
over the pp axis via shard_map + ppermute, with the last-stage loss path
(scalar-only cross-pp traffic) — the reference has none (SURVEY.md §2.2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.parallel import (
    MeshSpec,
    build_mesh,
    bubble_fraction,
    pipeline_schedule,
    spmd_pipeline,
)


def _stage_fn(params, x):
    # One residual MLP stage: x + relu(x @ w1) @ w2.
    return x + jax.nn.relu(x @ params["w1"]) @ params["w2"]


def _stacked_params(key, n_stages, d, hidden):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (n_stages, d, hidden)) * 0.1,
        "w2": jax.random.normal(k2, (n_stages, hidden, d)) * 0.1,
    }


def _sequential(params, x):
    for s in range(params["w1"].shape[0]):
        x = _stage_fn(jax.tree_util.tree_map(lambda p: p[s], params), x)
    return x


@pytest.mark.parametrize("pp,microbatches", [(2, 2), (2, 4), (4, 4), (4, 8)])
def test_pipeline_matches_sequential(pp, microbatches):
    mesh = build_mesh(MeshSpec(dp=1, pp=pp), jax.devices()[:pp])
    params = _stacked_params(jax.random.PRNGKey(0), pp, 8, 16)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 8))
    out = jax.jit(
        lambda p, x: spmd_pipeline(
            _stage_fn, p, x, mesh=mesh, num_microbatches=microbatches
        )
    )(params, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(params, x)),
        rtol=1e-5, atol=1e-5,
    )


def test_pipeline_composes_with_dp():
    """dp x pp: the batch shards over dp while stages split over pp."""
    mesh = build_mesh(MeshSpec(dp=2, pp=2), jax.devices()[:4])
    params = _stacked_params(jax.random.PRNGKey(2), 2, 4, 8)
    x = jax.random.normal(jax.random.PRNGKey(3), (8, 4))
    out = jax.jit(
        lambda p, x: spmd_pipeline(
            _stage_fn, p, x, mesh=mesh, num_microbatches=2
        )
    )(params, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(params, x)),
        rtol=1e-5, atol=1e-5,
    )


def test_pipeline_gradients_match_sequential():
    """ppermute transposes cleanly: training through the pipeline gives
    the same gradients as the unpipelined program."""
    mesh = build_mesh(MeshSpec(dp=1, pp=2), jax.devices()[:2])
    params = _stacked_params(jax.random.PRNGKey(4), 2, 4, 8)
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 4))

    def loss_pipe(p):
        y = spmd_pipeline(_stage_fn, p, x, mesh=mesh, num_microbatches=2)
        return jnp.sum(y**2)

    def loss_seq(p):
        return jnp.sum(_sequential(p, x) ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    g_seq = jax.grad(loss_seq)(params)
    for leaf_p, leaf_s in zip(
        jax.tree_util.tree_leaves(g_pipe), jax.tree_util.tree_leaves(g_seq)
    ):
        np.testing.assert_allclose(
            np.asarray(leaf_p), np.asarray(leaf_s), rtol=1e-4, atol=1e-5
        )


def test_single_stage_degenerates():
    mesh = build_mesh(MeshSpec(dp=1, pp=1), jax.devices()[:1])
    params = _stacked_params(jax.random.PRNGKey(6), 1, 4, 8)
    x = jax.random.normal(jax.random.PRNGKey(7), (4, 4))
    out = spmd_pipeline(_stage_fn, params, x, mesh=mesh, num_microbatches=2)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(params, x)), rtol=1e-6
    )


def test_validation_errors():
    mesh = build_mesh(MeshSpec(dp=1, pp=2), jax.devices()[:2])
    params = _stacked_params(jax.random.PRNGKey(8), 3, 4, 8)  # wrong S
    x = jnp.zeros((4, 4))
    with pytest.raises(ValueError, match="stacked"):
        spmd_pipeline(_stage_fn, params, x, mesh=mesh, num_microbatches=2)
    good = _stacked_params(jax.random.PRNGKey(8), 2, 4, 8)
    with pytest.raises(ValueError, match="microbatches"):
        spmd_pipeline(_stage_fn, good, x, mesh=mesh, num_microbatches=3)


def test_degenerate_single_stage_still_validates_microbatches():
    """A config that errors on pp>1 must not silently pass on pp=1: the
    microbatch-divisibility check runs BEFORE the degenerate single-stage
    early return."""
    mesh = build_mesh(MeshSpec(dp=1, pp=1), jax.devices()[:1])
    params = _stacked_params(jax.random.PRNGKey(8), 1, 4, 8)
    with pytest.raises(ValueError, match="microbatches"):
        spmd_pipeline(
            _stage_fn, params, jnp.zeros((4, 4)), mesh=mesh,
            num_microbatches=3,
        )


def test_interleave_validation_errors():
    mesh = build_mesh(MeshSpec(dp=1, pp=2), jax.devices()[:2])
    x = jnp.zeros((4, 4))
    # Stacked dim must equal interleave * pp.
    two = _stacked_params(jax.random.PRNGKey(8), 2, 4, 8)
    with pytest.raises(ValueError, match="interleave"):
        spmd_pipeline(
            _stage_fn, two, x, mesh=mesh, num_microbatches=2, interleave=2
        )
    # A wrapped microbatch re-enters rank 0 M ticks after injection but
    # only arrives after pp — M < pp would deadlock into garbage.
    four = _stacked_params(jax.random.PRNGKey(8), 4, 4, 8)
    with pytest.raises(ValueError, match="interleaved schedule needs"):
        spmd_pipeline(
            _stage_fn, four, x, mesh=mesh, num_microbatches=1, interleave=2
        )


def test_bubble_fraction():
    assert bubble_fraction(4, 4) == pytest.approx(3 / 7)
    assert bubble_fraction(1, 8) == 0.0
    # More microbatches amortize the bubble.
    assert bubble_fraction(4, 32) < bubble_fraction(4, 8)


def test_bubble_fraction_interleaved():
    # The v=1 values are the original GPipe formula, pinned unchanged.
    assert bubble_fraction(4, 4, interleave=1) == pytest.approx(3 / 7)
    assert bubble_fraction(8, 16, interleave=1) == pytest.approx(7 / 23)
    assert bubble_fraction(1, 8, interleave=1) == 0.0
    # Same stage count on pp = S/v ranks: the bubble shrinks ~v x.
    assert bubble_fraction(4, 4, interleave=2) == pytest.approx(1 / 9)
    assert bubble_fraction(4, 4, interleave=2) < bubble_fraction(4, 4)
    assert bubble_fraction(8, 8, interleave=4) == pytest.approx(1 / 33)
    # interleave must divide the stage count.
    with pytest.raises(ValueError, match="multiple of interleave"):
        bubble_fraction(4, 4, interleave=3)


def test_pipeline_schedule_accounting():
    s = pipeline_schedule(4, 8, interleave=2)
    assert s["pp"] == 2 and s["loop_ticks"] == 8 * 2 + 1
    assert s["stage_ticks"] == pytest.approx(8.5)
    assert s["model_stage_ticks"] == pytest.approx(8 + 4 / 2 - 1)
    assert s["stage_ticks"] <= s["model_stage_ticks"]
    # GPipe meets the model exactly.
    g = pipeline_schedule(4, 8, interleave=1)
    assert g["loop_ticks"] == 11
    assert g["stage_ticks"] == g["model_stage_ticks"] == 11


@pytest.mark.parametrize(
    "pp,v,microbatches", [(2, 2, 2), (2, 2, 4), (4, 2, 8), (2, 3, 4)]
)
def test_interleaved_pipeline_matches_sequential(pp, v, microbatches):
    """Circular schedule, v non-adjacent slices per rank: same math as
    running the v*pp stages sequentially."""
    mesh = build_mesh(MeshSpec(dp=1, pp=pp), jax.devices()[:pp])
    params = _stacked_params(jax.random.PRNGKey(0), pp * v, 8, 16)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 8))
    out = jax.jit(
        lambda p, x: spmd_pipeline(
            _stage_fn, p, x, mesh=mesh, num_microbatches=microbatches,
            interleave=v,
        )
    )(params, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(params, x)),
        rtol=1e-5, atol=1e-5,
    )


def _mse(out, tgt, lp):
    return jnp.mean((out - tgt) ** 2)


@pytest.mark.parametrize("pp,v,dp", [(4, 1, 1), (2, 2, 1), (2, 2, 2)])
def test_pipeline_loss_and_grads_match_single_rank(pp, v, dp):
    """Grad parity (the scalar-only loss path): pp=2 and pp=4, with and
    without interleave, match the pp=1 single-rank reference's loss AND
    gradients — the ppermute transposes carry exactly the cotangents the
    terminal all-reduce used to."""
    params = _stacked_params(jax.random.PRNGKey(0), 4, 8, 16)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 8))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (8, 8))

    ref_mesh = build_mesh(MeshSpec(dp=1, pp=1), jax.devices()[:1])
    # pp=1, interleave=4: the degenerate ring still runs the circular
    # schedule; it doubles as the single-rank reference for the loss
    # contract (and equals plain sequential + mse).
    ref = jax.jit(
        jax.value_and_grad(
            lambda p: spmd_pipeline(
                _stage_fn, p, x, mesh=ref_mesh, num_microbatches=4,
                interleave=4, loss_fn=_mse, targets=tgt,
            )
        )
    )(params)
    seq_loss = jnp.mean((_sequential(params, x) - tgt) ** 2)
    np.testing.assert_allclose(float(ref[0]), float(seq_loss), rtol=1e-6)

    mesh = build_mesh(MeshSpec(dp=dp, pp=pp), jax.devices()[:pp * dp])
    loss, grads = jax.jit(
        jax.value_and_grad(
            lambda p: spmd_pipeline(
                _stage_fn, p, x, mesh=mesh, num_microbatches=4,
                interleave=v, loss_fn=_mse, targets=tgt,
            )
        )
    )(params)
    np.testing.assert_allclose(float(loss), float(ref[0]), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(grads), jax.tree_util.tree_leaves(ref[1])
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        )


# -- pipelined transformer --------------------------------------------------


def test_pipelined_transformer_matches_flat():
    """Same Block weights, pipelined schedule: logits must match the flat
    TransformerLM when the stacked params are the flat layers restacked."""
    from kubeflow_tpu.models.transformer import (
        PipelinedTransformerLM,
        TransformerConfig,
        TransformerLM,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=16, n_layers=4, n_heads=2, head_dim=8,
        d_ff=32, remat=False, dtype=jnp.float32, attention_impl="dense",
    )
    mesh = build_mesh(MeshSpec(dp=2, pp=2), jax.devices()[:4])
    tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 8), 0, 64)

    pipe = PipelinedTransformerLM(cfg, n_stages=2, num_microbatches=2,
                                  mesh=mesh)
    variables = jax.jit(pipe.init)(jax.random.PRNGKey(1), tokens)
    logits_pipe = jax.jit(lambda v, t: pipe.apply(v, t))(variables, tokens)

    # Rebuild the flat model's params from the stacked stage params:
    # stages/blocks/layer_i[stage s] -> layer_{s*per_stage + i}.
    flat = TransformerLM(cfg)
    stacked = variables["params"]["stages"]["blocks"]
    flat_params = {
        "embedding": variables["params"]["embedding"],
        "ln_final": variables["params"]["ln_final"],
    }
    per_stage = cfg.n_layers // 2
    for s in range(2):
        for i in range(per_stage):
            flat_params[f"layer_{s * per_stage + i}"] = (
                jax.tree_util.tree_map(
                    lambda p: p[s], stacked[f"layer_{i}"]
                )
            )
    logits_flat = flat.apply({"params": flat_params}, tokens)
    np.testing.assert_allclose(
        np.asarray(logits_pipe), np.asarray(logits_flat),
        rtol=2e-4, atol=2e-4,
    )


def test_pipelined_transformer_trains():
    """The pipelined model trains end-to-end through the Trainer (loss
    decreases) on a dp x pp mesh."""
    from kubeflow_tpu.models.transformer import (
        PipelinedTransformerLM,
        TransformerConfig,
    )
    from kubeflow_tpu.train import SyntheticTokens, TrainConfig, Trainer

    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_layers=2, n_heads=2, head_dim=8,
        d_ff=32, remat=False, dtype=jnp.float32, attention_impl="dense",
    )
    mesh = build_mesh(MeshSpec(dp=2, pp=2), jax.devices()[:4])
    model = PipelinedTransformerLM(cfg, n_stages=2, num_microbatches=2,
                                   mesh=mesh)
    config = TrainConfig(batch_size=8, learning_rate=0.05, warmup_steps=1,
                         total_steps=8, optimizer="adamw")
    trainer = Trainer(
        model, config, mesh,
        example_input_shape=(4, 8),
        input_key="tokens", label_key="labels",
        example_input_dtype=jnp.int32,
    )
    state = trainer.init_state(jax.random.PRNGKey(0))
    data = SyntheticTokens(mesh, 8, seq_len=8, vocab_size=32)
    step = trainer.make_train_step()
    losses = []
    for batch in data:
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
        if len(losses) >= 8:
            break
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_pipelined_transformer_validation():
    from kubeflow_tpu.models.transformer import (
        PipelinedTransformerLM,
        TransformerConfig,
    )

    cfg = TransformerConfig(vocab_size=16, d_model=8, n_layers=3,
                            n_heads=1, head_dim=8, d_ff=16, remat=False)
    tokens = jnp.zeros((4, 4), jnp.int32)
    with pytest.raises(ValueError, match="stages"):
        PipelinedTransformerLM(cfg, n_stages=2, num_microbatches=2).init(
            jax.random.PRNGKey(0), tokens
        )
    moe = TransformerConfig(vocab_size=16, d_model=8, n_layers=2,
                            n_heads=1, head_dim=8, d_ff=16, num_experts=2)
    with pytest.raises(ValueError, match="MoE"):
        PipelinedTransformerLM(moe, n_stages=2, num_microbatches=2).init(
            jax.random.PRNGKey(0), tokens
        )


def test_pipeline_composes_with_tp_and_fsdp():
    """The full 3D layout: stages over pp, weights over fsdp, heads/mlp
    over tp — one traced program, XLA inserts every collective."""
    from kubeflow_tpu.models.transformer import (
        PipelinedTransformerLM,
        TransformerConfig,
    )
    from kubeflow_tpu.train import SyntheticTokens, TrainConfig, Trainer

    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_layers=2, n_heads=2, head_dim=8,
        d_ff=32, remat=False, dtype=jnp.float32, attention_impl="dense",
    )
    mesh = build_mesh(MeshSpec(fsdp=2, pp=2, tp=2), jax.devices()[:8])
    model = PipelinedTransformerLM(cfg, n_stages=2, num_microbatches=2,
                                   mesh=mesh)
    trainer = Trainer(
        model,
        TrainConfig(batch_size=8, learning_rate=0.05, warmup_steps=1,
                    total_steps=6, optimizer="adamw", fsdp_params=True),
        mesh,
        example_input_shape=(4, 8),
        input_key="tokens", label_key="labels",
        example_input_dtype=jnp.int32,
    )
    state = trainer.init_state(jax.random.PRNGKey(0))
    # Stage-stacked weights really shard over pp AND fsdp AND tp.
    wq = state.params["stages"]["blocks"]["layer_0"]["attn"]["wq"]["kernel"]
    spec = str(wq.sharding.spec)
    assert "pp" in spec and "tp" in spec and "fsdp" in spec, spec
    data = SyntheticTokens(mesh, 8, seq_len=8, vocab_size=32)
    step = trainer.make_train_step()
    losses = []
    for batch in data:
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
        if len(losses) >= 6:
            break
    assert all(np.isfinite(losses)) and losses[-1] < losses[0], losses


# -- last-stage loss path (scalar-only cross-pp) ----------------------------


def _tiny_lm_cfg(**kw):
    from kubeflow_tpu.models.transformer import TransformerConfig

    base = dict(
        vocab_size=64, d_model=16, n_layers=4, n_heads=2, head_dim=8,
        d_ff=32, remat=False, dtype=jnp.float32, attention_impl="dense",
    )
    base.update(kw)
    return TransformerConfig(**base)


@pytest.mark.parametrize("v", [1, 2])
def test_pipelined_loss_and_grads_match_flat(v):
    """pp=2, with and without interleave: the pipelined loss path's loss
    AND gradients match the flat (single-stage) TransformerLM's
    cross-entropy on the restacked weights."""
    import flax.linen as nn

    from kubeflow_tpu.models.transformer import (
        PipelinedTransformerLM,
        TransformerLM,
    )
    from kubeflow_tpu.train.trainer import softmax_cross_entropy

    cfg = _tiny_lm_cfg()
    n_stages = 2 * v
    mesh = build_mesh(MeshSpec(dp=2, pp=2), jax.devices()[:4])
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 8), 0, 64)
    labels = jax.random.randint(jax.random.PRNGKey(9), (8, 8), 0, 64)

    pipe = PipelinedTransformerLM(
        cfg, n_stages=n_stages, num_microbatches=4, mesh=mesh, interleave=v
    )
    params = nn.meta.unbox(
        jax.jit(pipe.init)(jax.random.PRNGKey(1), tokens)
    )["params"]
    loss_p, grads_p = jax.jit(
        jax.value_and_grad(
            lambda p: pipe.apply({"params": p}, tokens, labels=labels)
        )
    )(params)

    flat = TransformerLM(cfg)
    stacked = params["stages"]["blocks"]
    per_stage = cfg.n_layers // n_stages
    flat_params = {
        "embedding": params["embedding"],
        "ln_final": params["ln_final"],
    }
    for s in range(n_stages):
        for i in range(per_stage):
            flat_params[f"layer_{s * per_stage + i}"] = (
                jax.tree_util.tree_map(lambda p: p[s], stacked[f"layer_{i}"])
            )
    loss_f, grads_f = jax.jit(
        jax.value_and_grad(
            lambda p: softmax_cross_entropy(
                flat.apply({"params": p}, tokens), labels
            )
        )
    )(flat_params)
    np.testing.assert_allclose(float(loss_p), float(loss_f), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grads_p["embedding"]),
        np.asarray(grads_f["embedding"]),
        rtol=2e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(grads_p["ln_final"]["scale"]),
        np.asarray(grads_f["ln_final"]["scale"]),
        rtol=2e-4, atol=1e-5,
    )
    for s in range(n_stages):
        for i in range(per_stage):
            g_p = jax.tree_util.tree_map(
                lambda p: p[s], grads_p["stages"]["blocks"][f"layer_{i}"]
            )
            g_f = grads_f[f"layer_{s * per_stage + i}"]
            for a, b in zip(
                jax.tree_util.tree_leaves(g_p),
                jax.tree_util.tree_leaves(g_f),
            ):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
                )


@pytest.mark.parametrize("v", [1, 2])
def test_pipeline_loss_scalar_only_cross_pp_collectives(v):
    """Collective-accounting regression (the wire contract), now a
    thin wrapper over the `pipeline-wire-v{1,2}` rows of the kftpu-lint
    program-contract table (ISSUE 8, `ci/lint/contracts.py`): the
    compiled fwd+bwd of the pipelined loss path contains NO all-reduce
    at or above one microbatch's activations ([mb, S, d_model] — the
    shapes make even that outweigh the largest weight buffer), moves
    activations by collective-permute, and loops exactly the published
    schedule's tick count."""
    from kubeflow_tpu.ci.lint.contracts import run_contract

    run_contract(f"pipeline-wire-v{v}")


def test_grad_accumulation_matches_full_batch():
    """TrainConfig.accum_steps on a NON-pp mesh: one train step with
    accumulation produces the same loss, accuracy, and updated params as
    the full-batch step (mean of equal microbatch means)."""
    from kubeflow_tpu.models.transformer import TransformerLM
    from kubeflow_tpu.train import SyntheticTokens, TrainConfig, Trainer

    cfg = _tiny_lm_cfg(n_layers=2, vocab_size=32)
    mesh = build_mesh(MeshSpec(dp=2), jax.devices()[:2])
    batch = next(iter(SyntheticTokens(mesh, 8, seq_len=8, vocab_size=32)))
    results = {}
    for accum in (1, 4):
        config = TrainConfig(
            batch_size=8, learning_rate=0.1, warmup_steps=1,
            total_steps=4, optimizer="sgd", accum_steps=accum,
        )
        trainer = Trainer(
            TransformerLM(cfg, mesh=mesh), config, mesh,
            example_input_shape=(4, 8), input_key="tokens",
            label_key="labels", example_input_dtype=jnp.int32,
        )
        state = trainer.init_state(jax.random.PRNGKey(0))
        state, metrics = trainer.make_train_step()(state, batch)
        results[accum] = (state, metrics)
    for a, b in zip(
        jax.tree_util.tree_leaves(results[1][0].params),
        jax.tree_util.tree_leaves(results[4][0].params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6
        )
    for key in ("loss", "accuracy"):
        np.testing.assert_allclose(
            float(results[1][1][key]), float(results[4][1][key]), rtol=1e-5
        )


def test_grad_accumulation_threads_batch_stats():
    """BN models under accum_steps: each microbatch's batch_stats update
    builds on the previous tick's (sequential-small-batch semantics) —
    the step's final stats must equal manually folding the microbatches
    through the model one after another, not just the last microbatch's
    update of the starting stats."""
    from kubeflow_tpu.models.resnet import tiny_resnet
    from kubeflow_tpu.train import SyntheticImages, TrainConfig, Trainer

    mesh = build_mesh(MeshSpec(dp=1), jax.devices()[:1])
    config = TrainConfig(
        batch_size=8, learning_rate=0.1, warmup_steps=1, total_steps=4,
        accum_steps=2,
    )
    trainer = Trainer(
        tiny_resnet(), config, mesh, example_input_shape=(2, 32, 32, 3)
    )
    state = trainer.init_state(jax.random.PRNGKey(0))
    batch = next(iter(SyntheticImages(
        mesh, batch_size=8, image_size=32, num_classes=10,
        dtype=jnp.float32,
    )))
    # Manual fold FIRST (the train step donates and deletes `state`'s
    # buffers): microbatch 1 with the starting stats, microbatch 2 with
    # microbatch 1's updated stats.
    stats = state.batch_stats
    for i in range(2):
        mb = batch["image"][i * 4:(i + 1) * 4]
        _, out = state.apply_fn(
            {"params": state.params, "batch_stats": stats}, mb,
            train=True, mutable=["batch_stats"],
        )
        stats = out["batch_stats"]
    stats = jax.tree_util.tree_map(np.asarray, stats)

    new_state, _ = trainer.make_train_step()(state, batch)
    for a, b in zip(
        jax.tree_util.tree_leaves(new_state.batch_stats),
        jax.tree_util.tree_leaves(stats),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_pipelined_interleaved_trains_with_accumulation():
    """The full composition: interleaved schedule + last-stage loss
    through the Trainer (loss_in_model) + gradient accumulation on top —
    loss decreases, eval works."""
    from kubeflow_tpu.models.transformer import PipelinedTransformerLM
    from kubeflow_tpu.train import SyntheticTokens, TrainConfig, Trainer

    cfg = _tiny_lm_cfg(vocab_size=32)
    mesh = build_mesh(MeshSpec(dp=2, pp=2), jax.devices()[:4])
    model = PipelinedTransformerLM(
        cfg, n_stages=4, num_microbatches=2, mesh=mesh, interleave=2
    )
    config = TrainConfig(
        batch_size=8, learning_rate=0.05, warmup_steps=1, total_steps=8,
        optimizer="adamw", label_smoothing=0.0, train_metrics="loss",
        loss_in_model=True, accum_steps=2,
    )
    trainer = Trainer(
        model, config, mesh, example_input_shape=(4, 8),
        input_key="tokens", label_key="labels",
        example_input_dtype=jnp.int32,
    )
    state = trainer.init_state(jax.random.PRNGKey(0))
    data = SyntheticTokens(mesh, 8, seq_len=8, vocab_size=32)
    step = trainer.make_train_step()
    losses = []
    for batch in data:
        state, m = step(state, batch)
        assert "accuracy" not in m  # no logits on this path
        losses.append(float(m["loss"]))
        if len(losses) >= 8:
            break
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    eval_metrics = trainer.make_eval_step()(state, batch)
    assert np.isfinite(float(eval_metrics["loss"]))


def test_loss_in_model_config_validation():
    from kubeflow_tpu.train import TrainConfig

    with pytest.raises(ValueError, match="train_metrics"):
        TrainConfig(loss_in_model=True)
    with pytest.raises(ValueError, match="label_smoothing"):
        TrainConfig(loss_in_model=True, train_metrics="loss")
    with pytest.raises(ValueError, match="accum_steps"):
        TrainConfig(accum_steps=0)
    with pytest.raises(ValueError, match="accumulation"):
        TrainConfig(batch_size=6, accum_steps=4)
    # The valid combination constructs.
    TrainConfig(
        loss_in_model=True, train_metrics="loss", label_smoothing=0.0,
        accum_steps=2, batch_size=8,
    )
