"""SPMD pipeline parallelism: GPipe schedule over the pp axis via
shard_map + ppermute (the reference has none — SURVEY.md §2.2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.parallel import (
    MeshSpec,
    build_mesh,
    bubble_fraction,
    spmd_pipeline,
)


def _stage_fn(params, x):
    # One residual MLP stage: x + relu(x @ w1) @ w2.
    return x + jax.nn.relu(x @ params["w1"]) @ params["w2"]


def _stacked_params(key, n_stages, d, hidden):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (n_stages, d, hidden)) * 0.1,
        "w2": jax.random.normal(k2, (n_stages, hidden, d)) * 0.1,
    }


def _sequential(params, x):
    for s in range(params["w1"].shape[0]):
        x = _stage_fn(jax.tree_util.tree_map(lambda p: p[s], params), x)
    return x


@pytest.mark.parametrize("pp,microbatches", [(2, 2), (2, 4), (4, 4), (4, 8)])
def test_pipeline_matches_sequential(pp, microbatches):
    mesh = build_mesh(MeshSpec(dp=1, pp=pp), jax.devices()[:pp])
    params = _stacked_params(jax.random.PRNGKey(0), pp, 8, 16)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 8))
    out = jax.jit(
        lambda p, x: spmd_pipeline(
            _stage_fn, p, x, mesh=mesh, num_microbatches=microbatches
        )
    )(params, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(params, x)),
        rtol=1e-5, atol=1e-5,
    )


def test_pipeline_composes_with_dp():
    """dp x pp: the batch shards over dp while stages split over pp."""
    mesh = build_mesh(MeshSpec(dp=2, pp=2), jax.devices()[:4])
    params = _stacked_params(jax.random.PRNGKey(2), 2, 4, 8)
    x = jax.random.normal(jax.random.PRNGKey(3), (8, 4))
    out = jax.jit(
        lambda p, x: spmd_pipeline(
            _stage_fn, p, x, mesh=mesh, num_microbatches=2
        )
    )(params, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(params, x)),
        rtol=1e-5, atol=1e-5,
    )


def test_pipeline_gradients_match_sequential():
    """ppermute transposes cleanly: training through the pipeline gives
    the same gradients as the unpipelined program."""
    mesh = build_mesh(MeshSpec(dp=1, pp=2), jax.devices()[:2])
    params = _stacked_params(jax.random.PRNGKey(4), 2, 4, 8)
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 4))

    def loss_pipe(p):
        y = spmd_pipeline(_stage_fn, p, x, mesh=mesh, num_microbatches=2)
        return jnp.sum(y**2)

    def loss_seq(p):
        return jnp.sum(_sequential(p, x) ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    g_seq = jax.grad(loss_seq)(params)
    for leaf_p, leaf_s in zip(
        jax.tree_util.tree_leaves(g_pipe), jax.tree_util.tree_leaves(g_seq)
    ):
        np.testing.assert_allclose(
            np.asarray(leaf_p), np.asarray(leaf_s), rtol=1e-4, atol=1e-5
        )


def test_single_stage_degenerates():
    mesh = build_mesh(MeshSpec(dp=1, pp=1), jax.devices()[:1])
    params = _stacked_params(jax.random.PRNGKey(6), 1, 4, 8)
    x = jax.random.normal(jax.random.PRNGKey(7), (4, 4))
    out = spmd_pipeline(_stage_fn, params, x, mesh=mesh, num_microbatches=2)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(params, x)), rtol=1e-6
    )


def test_validation_errors():
    mesh = build_mesh(MeshSpec(dp=1, pp=2), jax.devices()[:2])
    params = _stacked_params(jax.random.PRNGKey(8), 3, 4, 8)  # wrong S
    x = jnp.zeros((4, 4))
    with pytest.raises(ValueError, match="stacked"):
        spmd_pipeline(_stage_fn, params, x, mesh=mesh, num_microbatches=2)
    good = _stacked_params(jax.random.PRNGKey(8), 2, 4, 8)
    with pytest.raises(ValueError, match="microbatches"):
        spmd_pipeline(_stage_fn, good, x, mesh=mesh, num_microbatches=3)


def test_bubble_fraction():
    assert bubble_fraction(4, 4) == pytest.approx(3 / 7)
    assert bubble_fraction(1, 8) == 0.0
    # More microbatches amortize the bubble.
    assert bubble_fraction(4, 32) < bubble_fraction(4, 8)


# -- pipelined transformer --------------------------------------------------


def test_pipelined_transformer_matches_flat():
    """Same Block weights, pipelined schedule: logits must match the flat
    TransformerLM when the stacked params are the flat layers restacked."""
    from kubeflow_tpu.models.transformer import (
        PipelinedTransformerLM,
        TransformerConfig,
        TransformerLM,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=16, n_layers=4, n_heads=2, head_dim=8,
        d_ff=32, remat=False, dtype=jnp.float32, attention_impl="dense",
    )
    mesh = build_mesh(MeshSpec(dp=2, pp=2), jax.devices()[:4])
    tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 8), 0, 64)

    pipe = PipelinedTransformerLM(cfg, n_stages=2, num_microbatches=2,
                                  mesh=mesh)
    variables = jax.jit(pipe.init)(jax.random.PRNGKey(1), tokens)
    logits_pipe = jax.jit(lambda v, t: pipe.apply(v, t))(variables, tokens)

    # Rebuild the flat model's params from the stacked stage params:
    # stages/blocks/layer_i[stage s] -> layer_{s*per_stage + i}.
    flat = TransformerLM(cfg)
    stacked = variables["params"]["stages"]["blocks"]
    flat_params = {
        "embedding": variables["params"]["embedding"],
        "ln_final": variables["params"]["ln_final"],
    }
    per_stage = cfg.n_layers // 2
    for s in range(2):
        for i in range(per_stage):
            flat_params[f"layer_{s * per_stage + i}"] = (
                jax.tree_util.tree_map(
                    lambda p: p[s], stacked[f"layer_{i}"]
                )
            )
    logits_flat = flat.apply({"params": flat_params}, tokens)
    np.testing.assert_allclose(
        np.asarray(logits_pipe), np.asarray(logits_flat),
        rtol=2e-4, atol=2e-4,
    )


def test_pipelined_transformer_trains():
    """The pipelined model trains end-to-end through the Trainer (loss
    decreases) on a dp x pp mesh."""
    from kubeflow_tpu.models.transformer import (
        PipelinedTransformerLM,
        TransformerConfig,
    )
    from kubeflow_tpu.train import SyntheticTokens, TrainConfig, Trainer

    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_layers=2, n_heads=2, head_dim=8,
        d_ff=32, remat=False, dtype=jnp.float32, attention_impl="dense",
    )
    mesh = build_mesh(MeshSpec(dp=2, pp=2), jax.devices()[:4])
    model = PipelinedTransformerLM(cfg, n_stages=2, num_microbatches=2,
                                   mesh=mesh)
    config = TrainConfig(batch_size=8, learning_rate=0.05, warmup_steps=1,
                         total_steps=8, optimizer="adamw")
    trainer = Trainer(
        model, config, mesh,
        example_input_shape=(4, 8),
        input_key="tokens", label_key="labels",
        example_input_dtype=jnp.int32,
    )
    state = trainer.init_state(jax.random.PRNGKey(0))
    data = SyntheticTokens(mesh, 8, seq_len=8, vocab_size=32)
    step = trainer.make_train_step()
    losses = []
    for batch in data:
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
        if len(losses) >= 8:
            break
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_pipelined_transformer_validation():
    from kubeflow_tpu.models.transformer import (
        PipelinedTransformerLM,
        TransformerConfig,
    )

    cfg = TransformerConfig(vocab_size=16, d_model=8, n_layers=3,
                            n_heads=1, head_dim=8, d_ff=16, remat=False)
    tokens = jnp.zeros((4, 4), jnp.int32)
    with pytest.raises(ValueError, match="stages"):
        PipelinedTransformerLM(cfg, n_stages=2, num_microbatches=2).init(
            jax.random.PRNGKey(0), tokens
        )
    moe = TransformerConfig(vocab_size=16, d_model=8, n_layers=2,
                            n_heads=1, head_dim=8, d_ff=16, num_experts=2)
    with pytest.raises(ValueError, match="MoE"):
        PipelinedTransformerLM(moe, n_stages=2, num_microbatches=2).init(
            jax.random.PRNGKey(0), tokens
        )


def test_pipeline_composes_with_tp_and_fsdp():
    """The full 3D layout: stages over pp, weights over fsdp, heads/mlp
    over tp — one traced program, XLA inserts every collective."""
    from kubeflow_tpu.models.transformer import (
        PipelinedTransformerLM,
        TransformerConfig,
    )
    from kubeflow_tpu.train import SyntheticTokens, TrainConfig, Trainer

    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_layers=2, n_heads=2, head_dim=8,
        d_ff=32, remat=False, dtype=jnp.float32, attention_impl="dense",
    )
    mesh = build_mesh(MeshSpec(fsdp=2, pp=2, tp=2), jax.devices()[:8])
    model = PipelinedTransformerLM(cfg, n_stages=2, num_microbatches=2,
                                   mesh=mesh)
    trainer = Trainer(
        model,
        TrainConfig(batch_size=8, learning_rate=0.05, warmup_steps=1,
                    total_steps=6, optimizer="adamw", fsdp_params=True),
        mesh,
        example_input_shape=(4, 8),
        input_key="tokens", label_key="labels",
        example_input_dtype=jnp.int32,
    )
    state = trainer.init_state(jax.random.PRNGKey(0))
    # Stage-stacked weights really shard over pp AND fsdp AND tp.
    wq = state.params["stages"]["blocks"]["layer_0"]["attn"]["wq"]["kernel"]
    spec = str(wq.sharding.spec)
    assert "pp" in spec and "tp" in spec and "fsdp" in spec, spec
    data = SyntheticTokens(mesh, 8, seq_len=8, vocab_size=32)
    step = trainer.make_train_step()
    losses = []
    for batch in data:
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
        if len(losses) >= 6:
            break
    assert all(np.isfinite(losses)) and losses[-1] < losses[0], losses
