"""CI smoke for `bench.py --workload pipeline` (docs/perf.md): the bench
must run end-to-end on the CPU dryrun mesh, report measured stage ticks
within the `M + S/v - 1` model for both schedules, keep the scalar-only
cross-pp contract (zero activation-sized all-reduces), and emit
driver-parsable JSON with non-null vs_baseline for the schedule metrics
(the BASELINE.json pipeline baselines)."""

import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_pipeline_bench_smoke_ticks_and_wire_contract():
    result = subprocess.run(
        [
            sys.executable, "bench.py", "--workload", "pipeline",
            "--steps", "1", "--warmup-steps", "1",
        ],
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True,
        text=True,
        timeout=540,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    metrics = {}
    for line in result.stdout.splitlines():
        if line.startswith("{"):
            m = json.loads(line)
            # The driver's parse contract — same shape as every bench.
            assert set(m) == {"metric", "value", "unit", "vs_baseline"}, m
            metrics[m["metric"]] = m
    for v in (1, 2):
        ticks = metrics[f"pipeline_stage_ticks_v{v}"]
        # Measured (from the traced program) within the model roofline,
        # and vs_baseline non-null because BASELINE.json records the
        # model baselines.
        assert ticks["vs_baseline"] is not None
        assert ticks["vs_baseline"] <= 1.0, ticks
        wires = metrics[f"pipeline_fullact_allreduces_v{v}"]
        assert wires["value"] == 0, wires
        assert wires["vs_baseline"] == 0.0, wires
        assert metrics[f"pipeline_lm_tokens_per_sec_v{v}"]["value"] > 0
    # Interleave strictly beats GPipe's tick count at this shape.
    assert (
        metrics["pipeline_stage_ticks_v2"]["value"]
        < metrics["pipeline_stage_ticks_v1"]["value"]
    )
