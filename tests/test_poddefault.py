"""PodDefault admission: selector matching, conflict-safe injection."""
import pytest

from kubeflow_tpu.api import new_resource
from kubeflow_tpu.controllers import poddefault
from kubeflow_tpu.testing import FakeApiServer


@pytest.fixture
def api():
    srv = FakeApiServer()
    poddefault.register(srv)
    return srv


def _poddefault(name, ns="user1", **spec):
    return new_resource(poddefault.KIND, name, ns, spec=spec)


def _pod(name="p", ns="user1", labels=None, env=None):
    return new_resource(
        "Pod", name, ns,
        spec={"containers": [{"name": "main", "env": list(env or [])}]},
        labels=labels or {},
    )


def test_matching_poddefault_injected(api):
    api.create(_poddefault(
        "tpu-env",
        selector={"matchLabels": {"add-tpu-env": "true"}},
        env=[{"name": "TPU_ACCEL", "value": "v5e"}],
        volumes=[{"name": "cache", "emptyDir": {}}],
        volumeMounts=[{"name": "cache", "mountPath": "/cache"}],
        annotations={"sidecar.istio.io/inject": "false"},
    ))
    created = api.create(_pod(labels={"add-tpu-env": "true"}))
    c = created.spec["containers"][0]
    assert {"name": "TPU_ACCEL", "value": "v5e"} in c["env"]
    assert c["volumeMounts"][0]["mountPath"] == "/cache"
    assert created.spec["volumes"][0]["name"] == "cache"
    assert created.metadata.annotations["sidecar.istio.io/inject"] == "false"
    assert (
        created.metadata.annotations["poddefault.kubeflow-tpu.org/tpu-env"]
        == "applied"
    )


def test_non_matching_ignored(api):
    api.create(_poddefault(
        "x", selector={"matchLabels": {"match": "yes"}},
        env=[{"name": "A", "value": "1"}],
    ))
    created = api.create(_pod(labels={"match": "no"}))
    assert created.spec["containers"][0]["env"] == []


def test_existing_pod_values_win(api):
    api.create(_poddefault(
        "x", selector={"matchLabels": {"m": "y"}},
        env=[{"name": "A", "value": "default"}],
    ))
    created = api.create(
        _pod(labels={"m": "y"}, env=[{"name": "A", "value": "explicit"}])
    )
    assert created.spec["containers"][0]["env"] == [
        {"name": "A", "value": "explicit"}
    ]


def test_conflicting_defaults_skip_injection(api):
    api.create(_poddefault(
        "a", selector={"matchLabels": {"m": "y"}},
        env=[{"name": "X", "value": "1"}],
    ))
    api.create(_poddefault(
        "b", selector={"matchLabels": {"m": "y"}},
        env=[{"name": "X", "value": "2"}],
    ))
    created = api.create(_pod(labels={"m": "y"}))
    assert created.spec["containers"][0]["env"] == []
    assert "conflict" in created.metadata.annotations[
        "poddefault.kubeflow-tpu.org/conflict"
    ] or "X" in created.metadata.annotations[
        "poddefault.kubeflow-tpu.org/conflict"
    ]


def test_tpujob_pods_get_poddefaults(api):
    # Integration: the operator's gang pods pass through admission too.
    from kubeflow_tpu.api import make_tpujob
    from kubeflow_tpu.controllers.tpujob import TpuJobController

    api.create(_poddefault(
        "creds", ns="default",
        selector={"matchLabels": {"kubeflow-tpu.org/job": "j"}},
        env=[{"name": "GCS_KEY", "value": "/secrets/key.json"}],
    ))
    ctl = TpuJobController(api)
    api.create(make_tpujob("j", replicas=2))
    ctl.controller.run_until_idle()
    env = {
        e["name"]: e["value"]
        for e in api.get("Pod", "j-worker-0").spec["containers"][0]["env"]
    }
    assert env["GCS_KEY"] == "/secrets/key.json"
    assert env["TPUJOB_NUM_PROCESSES"] == "2"
