"""Profile controller: namespace/RBAC/quota reconcile, plugins, finalizer."""
import pytest

from kubeflow_tpu.api import new_resource
from kubeflow_tpu.controllers.profile import (
    EDITOR_SA,
    FINALIZER,
    KIND,
    VIEWER_SA,
    ProfileController,
)
from kubeflow_tpu.testing import FakeApiServer, NotFound


@pytest.fixture
def api():
    return FakeApiServer()


def _profile(name="alice", owner="alice@example.com", **extra):
    spec = {"owner": {"kind": "User", "name": owner}, **extra}
    return new_resource(KIND, name, "default", spec=spec)


def test_profile_provisions_namespace(api):
    ctl = ProfileController(api)
    api.create(_profile())
    ctl.controller.run_until_idle()

    ns = api.get("Namespace", "alice", "")
    assert ns.metadata.labels["istio-injection"] == "enabled"
    assert ns.metadata.annotations["owner"] == "alice@example.com"
    assert api.get("ServiceAccount", EDITOR_SA, "alice")
    assert api.get("ServiceAccount", VIEWER_SA, "alice")
    rb = api.get("RoleBinding", "namespaceAdmin", "alice")
    assert rb.spec["subjects"][0]["name"] == "alice@example.com"
    assert api.get(KIND, "alice").status["condition"] == "Ready"
    assert FINALIZER in api.get(KIND, "alice").metadata.finalizers


def test_tpu_resource_quota(api):
    ctl = ProfileController(api)
    api.create(
        _profile(
            resourceQuotaSpec={"hard": {"google.com/tpu": 16, "cpu": "64"}}
        )
    )
    ctl.controller.run_until_idle()
    rq = api.get("ResourceQuota", "kf-resource-quota", "alice")
    assert rq.spec["hard"]["google.com/tpu"] == 16


def test_foreign_namespace_not_taken_over(api):
    api.create(new_resource("Namespace", "bob", ""))  # pre-existing, unowned
    ctl = ProfileController(api)
    api.create(_profile(name="bob", owner="mallory@example.com"))
    ctl.controller.run_until_idle()
    assert api.get(KIND, "bob").status["condition"] == "Failed"
    with pytest.raises(NotFound):
        api.get("ServiceAccount", EDITOR_SA, "bob")


def test_delete_revokes_plugins_and_cascades(api):
    revoked = []

    class FakePlugin:
        name = "TestPlugin"

        def apply(self, api_, profile):
            pass

        def revoke(self, api_, profile):
            revoked.append(profile.metadata.name)

    ctl = ProfileController(api, plugins={"TestPlugin": FakePlugin()})
    api.create(_profile(plugins=[{"kind": "TestPlugin"}]))
    ctl.controller.run_until_idle()
    api.delete(KIND, "alice")
    ctl.controller.run_until_idle()
    assert revoked == ["alice"]
    with pytest.raises(NotFound):
        api.get(KIND, "alice")
    with pytest.raises(NotFound):
        api.get("Namespace", "alice", "")


def test_unknown_plugin_warns_but_provisions(api):
    ctl = ProfileController(api)
    api.create(_profile(plugins=[{"kind": "NoSuchPlugin"}]))
    ctl.controller.run_until_idle()
    assert api.get(KIND, "alice").status["condition"] == "Ready"
    assert ctl.failures.value(severity="unknown_plugin") >= 1
    reasons = [e.spec["reason"] for e in api.list("Event")]
    assert "UnknownPlugin" in reasons


def test_profile_quota_full_scope_end_to_end(api):
    """Round-5 verdict item 4, through the tenant path: a Profile's
    resourceQuotaSpec with object-count, storage, and requests caps is
    materialized AND enforced — the N+1th PVC is rejected, a
    requests-only pod is correctly metered, and status.used publishes."""
    from kubeflow_tpu.controllers import quota
    from kubeflow_tpu.controllers.quota import QuotaExceeded

    quota.register(api)
    ctl = ProfileController(api)
    api.create(_profile(resourceQuotaSpec={"hard": {
        "persistentvolumeclaims": 2,
        "requests.storage": "30Gi",
        "cpu": "2",
        "pods": 10,
    }}))
    ctl.controller.run_until_idle()

    def pvc(name, storage):
        return new_resource(
            "PersistentVolumeClaim", name, "alice",
            spec={"resources": {"requests": {"storage": storage}}},
        )

    api.create(pvc("ws1", "10Gi"))
    api.create(pvc("ws2", "10Gi"))
    with pytest.raises(QuotaExceeded, match="persistentvolumeclaims"):
        api.create(pvc("ws3", "1Gi"))

    # Requests-only pod: metered against the bare cpu cap (the round-4
    # bypass was exactly this shape slipping through).
    api.create(new_resource(
        "Pod", "req-only", "alice",
        spec={"containers": [{"name": "w",
                              "resources": {"requests": {"cpu": "1500m"}}}]},
    ))
    with pytest.raises(QuotaExceeded, match="'cpu'"):
        api.create(new_resource(
            "Pod", "req-only-2", "alice",
            spec={"containers": [{"name": "w",
                                  "resources": {"requests": {"cpu": "1"}}}]},
        ))

    import time as _t

    deadline = _t.monotonic() + 5
    while _t.monotonic() < deadline:  # used publishes asynchronously
        rq = api.get("ResourceQuota", "kf-resource-quota", "alice")
        used = rq.status.get("used", {})
        if (
            used.get("persistentvolumeclaims") == 2
            and used.get("cpu") == "1500m"
        ):
            break
        _t.sleep(0.02)
    assert rq.status["used"]["persistentvolumeclaims"] == 2
    assert rq.status["used"]["cpu"] == "1500m"
    assert rq.status["used"]["requests.storage"] == 20 * 1024 ** 3
