"""Profile controller: namespace/RBAC/quota reconcile, plugins, finalizer."""
import pytest

from kubeflow_tpu.api import new_resource
from kubeflow_tpu.controllers.profile import (
    EDITOR_SA,
    FINALIZER,
    KIND,
    VIEWER_SA,
    ProfileController,
)
from kubeflow_tpu.testing import FakeApiServer, NotFound


@pytest.fixture
def api():
    return FakeApiServer()


def _profile(name="alice", owner="alice@example.com", **extra):
    spec = {"owner": {"kind": "User", "name": owner}, **extra}
    return new_resource(KIND, name, "default", spec=spec)


def test_profile_provisions_namespace(api):
    ctl = ProfileController(api)
    api.create(_profile())
    ctl.controller.run_until_idle()

    ns = api.get("Namespace", "alice", "")
    assert ns.metadata.labels["istio-injection"] == "enabled"
    assert ns.metadata.annotations["owner"] == "alice@example.com"
    assert api.get("ServiceAccount", EDITOR_SA, "alice")
    assert api.get("ServiceAccount", VIEWER_SA, "alice")
    rb = api.get("RoleBinding", "namespaceAdmin", "alice")
    assert rb.spec["subjects"][0]["name"] == "alice@example.com"
    assert api.get(KIND, "alice").status["condition"] == "Ready"
    assert FINALIZER in api.get(KIND, "alice").metadata.finalizers


def test_tpu_resource_quota(api):
    ctl = ProfileController(api)
    api.create(
        _profile(
            resourceQuotaSpec={"hard": {"google.com/tpu": 16, "cpu": "64"}}
        )
    )
    ctl.controller.run_until_idle()
    rq = api.get("ResourceQuota", "kf-resource-quota", "alice")
    assert rq.spec["hard"]["google.com/tpu"] == 16


def test_foreign_namespace_not_taken_over(api):
    api.create(new_resource("Namespace", "bob", ""))  # pre-existing, unowned
    ctl = ProfileController(api)
    api.create(_profile(name="bob", owner="mallory@example.com"))
    ctl.controller.run_until_idle()
    assert api.get(KIND, "bob").status["condition"] == "Failed"
    with pytest.raises(NotFound):
        api.get("ServiceAccount", EDITOR_SA, "bob")


def test_delete_revokes_plugins_and_cascades(api):
    revoked = []

    class FakePlugin:
        name = "TestPlugin"

        def apply(self, api_, profile):
            pass

        def revoke(self, api_, profile):
            revoked.append(profile.metadata.name)

    ctl = ProfileController(api, plugins={"TestPlugin": FakePlugin()})
    api.create(_profile(plugins=[{"kind": "TestPlugin"}]))
    ctl.controller.run_until_idle()
    api.delete(KIND, "alice")
    ctl.controller.run_until_idle()
    assert revoked == ["alice"]
    with pytest.raises(NotFound):
        api.get(KIND, "alice")
    with pytest.raises(NotFound):
        api.get("Namespace", "alice", "")


def test_unknown_plugin_warns_but_provisions(api):
    ctl = ProfileController(api)
    api.create(_profile(plugins=[{"kind": "NoSuchPlugin"}]))
    ctl.controller.run_until_idle()
    assert api.get(KIND, "alice").status["condition"] == "Ready"
    assert ctl.failures.value(severity="unknown_plugin") >= 1
    reasons = [e.spec["reason"] for e in api.list("Event")]
    assert "UnknownPlugin" in reasons
