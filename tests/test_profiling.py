"""Profiler + metrics-logging (SURVEY.md §5 tracing row: the reference
served profiles via Tensorboard but never captured them; here capture is
part of the training loop)."""

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.models.resnet import tiny_resnet
from kubeflow_tpu.parallel import MeshSpec, build_mesh
from kubeflow_tpu.train import (
    MetricsLogger,
    PhaseRoofline,
    Profiler,
    ProfileSchedule,
    SyntheticImages,
    TrainConfig,
    Trainer,
    annotated_scope,
    fit,
    time_phase,
)


def test_schedule_validation():
    with pytest.raises(ValueError):
        ProfileSchedule(start_step=-1).validate()
    with pytest.raises(ValueError):
        ProfileSchedule(num_steps=0).validate()


def test_windowed_capture_writes_tb_profile_layout(tmp_path, devices):
    """The trace must land where TensorBoard's profile plugin looks:
    <logdir>/plugins/profile/<run>/ — that dir is what a Tensorboard CR's
    logspath serves."""
    mesh = build_mesh(MeshSpec(dp=2), devices[:2])
    config = TrainConfig(batch_size=4, total_steps=6, warmup_steps=1)
    trainer = Trainer(
        tiny_resnet(), config, mesh, example_input_shape=(2, 32, 32, 3)
    )
    data = SyntheticImages(
        mesh, batch_size=4, image_size=32, num_classes=10, dtype=jnp.float32
    )
    profiler = Profiler(
        tmp_path / "logs", ProfileSchedule(start_step=2, num_steps=2)
    )
    result = fit(
        trainer, data, total_steps=6, profiler=profiler, log_every=100
    )
    assert result.steps_done == 6
    assert profiler.trace_written
    profile_dir = tmp_path / "logs" / "plugins" / "profile"
    runs = list(profile_dir.iterdir())
    assert runs, "no profile run directory written"
    traces = list(runs[0].glob("*"))
    assert traces, "profile run dir is empty"


def test_close_is_crash_safe(tmp_path):
    profiler = Profiler(tmp_path, ProfileSchedule(start_step=0, num_steps=100))
    profiler.before_step(0)  # trace live
    with annotated_scope("region"):
        jnp.ones((4, 4)).sum().block_until_ready()
    profiler.close()  # must stop cleanly even though window isn't done
    assert profiler.trace_written
    # And close again is a no-op.
    profiler.close()
    # A finished profiler never restarts.
    profiler.before_step(50)
    assert not profiler._active


def test_resume_shifts_profile_window(tmp_path):
    """A resumed run (first step 480) must still skip its warmup/compile
    steps before tracing — the schedule is relative to the process's
    first step, not absolute."""
    profiler = Profiler(tmp_path, ProfileSchedule(start_step=2, num_steps=1))
    profiler.before_step(480)
    assert not profiler._active  # 480 is this process's compile step
    profiler.after_step(480)
    profiler.before_step(481)
    assert not profiler._active
    profiler.after_step(481)
    profiler.before_step(482)  # 480 + start_step(2)
    assert profiler._active
    profiler.after_step(482)
    assert profiler.trace_written


def test_metrics_logger_roundtrip(tmp_path):
    logger = MetricsLogger(tmp_path / "logs")
    logger(10, {"loss": 1.5})
    logger(20, {"loss": 1.1})
    rows = logger.read()
    assert [r["step"] for r in rows] == [10, 20]
    assert all("ts" in r for r in rows)


# -- per-phase roofline (ISSUE 7) -------------------------------------------


def test_phase_roofline_math_and_bounds():
    """The mechanical roofline's arithmetic and the bound classifier
    (same convention as the hand-built docs/architecture.md table):
    achieved TF/s = TFLOP/s-of-wall-clock, achieved GB/s likewise, and
    the binding resource follows the dominant utilization."""
    roof = PhaseRoofline(peak_tflops=200.0, peak_gbps=800.0)
    # 10 TFLOP in 100 ms = 100 TF/s (50%); 8 GB in 100 ms = 80 GB/s
    # (10%): compute dominates by 0.4 -> MXU-side.
    mxu = roof.add("fwd", ms=100.0, tflop=10.0, gb=8.0)
    assert mxu["achieved_tflops"] == 100.0 and mxu["achieved_gbps"] == 80.0
    assert mxu["bound_by"] == "MXU-side"
    # ~0 TFLOP, 72 GB in 100 ms = 720 GB/s (90%) vs 0% compute -> HBM.
    hbm = roof.add("optimizer", ms=100.0, tflop=0.0, gb=72.0)
    assert hbm["bound_by"] == "HBM"
    # 64% compute vs 69% bandwidth (the r05 backward) -> mixed, HBM
    # dominant.
    mixed = roof.add("bwd", ms=100.0, tflop=12.8, gb=55.2)
    assert mixed["bound_by"] == "mixed → HBM"
    # The step's saturated resource is the longest phase's bound.
    roof.phases[-1] = roof.phases[-1].__class__("bwd", 300.0, 12.8, 55.2)
    assert roof.saturated().startswith("bwd:")
    # Table renders the Round-5 columns.
    table = roof.table()
    assert table.splitlines()[0] == (
        "| phase | ms | TFLOP | GB moved | achieved | bound by |"
    )
    assert "MXU-side" in table and "HBM" in table


def test_time_phase_fenced_timer():
    """time_phase returns positive wall-clock ms for a jitted fn and
    fences through a scalar device_get (it must not explode on pytree
    outputs either)."""
    f = jax.jit(lambda x: (x * 2.0, {"aux": x.sum()}))
    x = jnp.ones((32, 32))
    ms = time_phase(f, x, warmup=1, steps=2)
    assert ms > 0.0
