"""The kftpu-lint program pass: every contract in the table holds.

The traced-program half of the analyzer (`ci/lint/contracts.py`): the
train step, the interleaved pipeline, the fused flash grad, and the
serving batch each trace/compile once, and the declarative assertions
(collective counts/sizes, no [S, S] buffers, fused-kernel streams,
remat no-forward-rerun, schedule accounting) run over the result.
Parametrized per contract so a failure names its program.
"""

import pytest

from kubeflow_tpu.ci.lint.contracts import CONTRACTS, run_contract


@pytest.mark.parametrize(
    "name", [c.name for c in CONTRACTS]
)
def test_program_contract(name):
    run_contract(name)


def test_contract_table_is_complete():
    """The programs the ISSUEs name stay covered, and contract names
    are unique (findings key on them)."""
    names = [c.name for c in CONTRACTS]
    assert len(names) == len(set(names))
    for required in (
        "train-step-dp", "pipeline-wire-v1", "pipeline-wire-v2",
        "fused-flash-grad", "serving-batch", "elastic-resize",
        "serving-batch-continuous", "serving-multiplex",
    ):
        assert required in names
