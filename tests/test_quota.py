"""ResourceQuota enforcement — the quota admission the reference got
from the real apiserver's built-in controller and we must provide
ourselves (`controllers/quota.py`). The profile controller materializes
the caps; admission makes them real."""

import pytest

from kubeflow_tpu.api import make_tpujob
from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.tpujob import KIND
from kubeflow_tpu.controllers import quota
from kubeflow_tpu.controllers.quota import QuotaExceeded
from kubeflow_tpu.controllers.tpujob import LABEL_JOB, TpuJobController
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.testing.fake_apiserver import Invalid


def _pod(name, ns="team", chips=4, node=None):
    spec = {
        "containers": [
            {"name": "w",
             "resources": {"limits": {"google.com/tpu": chips}}}
        ],
    }
    if node:
        spec["nodeName"] = node
    return new_resource("Pod", name, ns, spec=spec)


def _quota(api, ns="team", chips=8):
    api.create(new_resource(
        "ResourceQuota", "kf-resource-quota", ns,
        spec={"hard": {"google.com/tpu": chips}},
    ))


def test_pod_over_quota_rejected():
    api = FakeApiServer()
    quota.register(api)
    _quota(api, chips=8)
    api.create(_pod("a", chips=4))
    api.create(_pod("b", chips=4))
    with pytest.raises(QuotaExceeded) as err:
        api.create(_pod("c", chips=1))
    assert "used 8 + requested 1 > hard cap 8" in str(err.value)
    # QuotaExceeded IS Invalid: the HTTP facade maps it to 422.
    assert isinstance(err.value, Invalid)


def test_terminal_pods_release_budget():
    api = FakeApiServer()
    quota.register(api)
    _quota(api, chips=4)
    api.create(_pod("a", chips=4))
    done = api.get("Pod", "a", "team").thaw()
    done.status["phase"] = "Succeeded"
    api.update_status(done)
    api.create(_pod("b", chips=4))  # fits now


def test_unmetered_namespace_and_zero_ask_pass():
    api = FakeApiServer()
    quota.register(api)
    api.create(_pod("free", ns="open", chips=16))  # no quota object
    _quota(api, chips=0)
    api.create(new_resource("Pod", "cpu-only", "team",
                            spec={"containers": [{"name": "w"}]}))


def test_update_does_not_double_count_self():
    api = FakeApiServer()
    quota.register(api)
    _quota(api, chips=4)
    api.create(_pod("a", chips=4))
    pod = api.get("Pod", "a", "team").thaw()
    pod.spec["nodeName"] = "n0"
    api.update(pod)  # re-admission must exclude its own usage


def test_gang_over_quota_holds_pending_episode():
    """All-or-nothing cuts both ways: if worker #2 busts the budget,
    worker #1 must not be left running — the job parks in a
    QuotaExceeded Pending episode and recovers when budget frees."""
    api = FakeApiServer()
    quota.register(api)
    _quota(api, ns="default", chips=4)
    ctl = TpuJobController(api, quota_retry_seconds=0.05)
    api.create(make_tpujob(
        "gang", replicas=2, tpu_chips_per_worker=4, command=("true",),
    ))
    for _ in range(6):
        ctl.controller.run_until_idle()
    job = api.get(KIND, "gang")
    assert job.status.get("reason") == "QuotaExceeded"
    assert job.status.get("phase") == "Pending"
    assert api.list("Pod", "default",
                    label_selector={LABEL_JOB: "gang"}) == []
    reasons = {e.spec["reason"] for e in api.list("Event", "default")}
    assert "QuotaExceeded" in reasons

    # The budget doubles (profile edit); the next pass starts the gang.
    rq = api.get("ResourceQuota", "kf-resource-quota", "default").thaw()
    rq.spec["hard"]["google.com/tpu"] = 8
    api.update(rq)
    import time as _time

    _time.sleep(0.1)  # past the quota retry gate
    ctl.controller.enqueue(("default", "gang"))
    for _ in range(6):
        ctl.controller.run_until_idle()
    job = api.get(KIND, "gang")
    assert len(api.list("Pod", "default",
                        label_selector={LABEL_JOB: "gang"})) == 2
    assert job.status.get("reason") is None


def test_materializer_contains_quota_rejection():
    """An over-quota notebook STS must not starve other workloads'
    materialization, and the tenant gets a PodRejected event."""
    from kubeflow_tpu.runtime import WorkloadMaterializer

    api = FakeApiServer()
    quota.register(api)
    _quota(api, ns="team", chips=0)
    api.create(new_resource("StatefulSet", "greedy", "team", spec={
        "replicas": 1,
        "template": {"spec": {"containers": [
            {"name": "nb",
             "resources": {"limits": {"google.com/tpu": 4}}}]}},
    }))
    api.create(new_resource("StatefulSet", "modest", "team", spec={
        "replicas": 1,
        "template": {"spec": {"containers": [{"name": "nb"}]}},
    }))
    m = WorkloadMaterializer(api)
    for _ in range(3):
        m.step()
    pods = {p.metadata.name for p in api.list("Pod", "team")}
    assert any(p.startswith("modest") for p in pods), pods
    assert not any(p.startswith("greedy") for p in pods), pods
    reasons = {e.spec["reason"] for e in api.list("Event", "team")}
    assert "PodRejected" in reasons
    # Episode-deduped: repeated steps don't spam events.
    count = sum(
        1 for e in api.list("Event", "team")
        if e.spec["reason"] == "PodRejected"
    )
    assert count == 1


# -- K8s quantity parsing (the grammar corev1 ResourceQuotaSpec carries,
# `profile-controller/api/v1/profile_types.go:36-44`) -----------------------


@pytest.mark.parametrize(
    "value,expected",
    [
        (4, 4.0),
        ("2", 2.0),
        ("1.5", 1.5),
        ("500m", 0.5),
        ("2500m", 2.5),
        ("1k", 1000.0),
        ("1M", 1e6),
        ("2G", 2e9),
        ("1Ki", 1024.0),
        ("128Mi", 128 * 2**20),
        ("128Gi", 128 * 2**30),
        ("1Ti", 2**40),
        ("2E", 2e18),
        ("1e3", 1000.0),
        ("  64  ", 64.0),
    ],
)
def test_parse_quantity_table(value, expected):
    from kubeflow_tpu.api.objects import parse_quantity

    assert parse_quantity(value) == expected


@pytest.mark.parametrize("bad", ["", "Gi", "xMi", "4x4", "12GiB", True, None])
def test_parse_quantity_rejects_garbage(bad):
    from kubeflow_tpu.api.objects import parse_quantity

    with pytest.raises((ValueError, TypeError)):
        parse_quantity(bad)


# -- cpu/memory metering (round-3 verdict: the caps profiles create were
# decorative for everything but chips) --------------------------------------


def _host_pod(name, ns="team", cpu=None, memory=None):
    limits = {}
    if cpu is not None:
        limits["cpu"] = cpu
    if memory is not None:
        limits["memory"] = memory
    return new_resource(
        "Pod", name, ns,
        spec={"containers": [{"name": "w", "resources": {"limits": limits}}]},
    )


def test_memory_cap_rejects_over_ask_pod():
    api = FakeApiServer()
    quota.register(api)
    api.create(new_resource(
        "ResourceQuota", "kf-resource-quota", "team",
        spec={"hard": {"memory": "4Gi"}},
    ))
    api.create(_host_pod("a", memory="3Gi"))
    with pytest.raises(QuotaExceeded) as err:
        api.create(_host_pod("b", memory="2Gi"))
    assert "memory" in str(err.value) and "4Gi" in str(err.value)
    api.create(_host_pod("c", memory="1Gi"))  # exactly fits


def test_cpu_cap_meters_millicores():
    api = FakeApiServer()
    quota.register(api)
    api.create(new_resource(
        "ResourceQuota", "kf-resource-quota", "team",
        spec={"hard": {"cpu": "2"}},
    ))
    api.create(_host_pod("a", cpu="1500m"))
    with pytest.raises(QuotaExceeded):
        api.create(_host_pod("b", cpu="750m"))
    api.create(_host_pod("c", cpu="500m"))  # 1.5 + 0.5 == 2.0 fits


def test_memory_capped_gang_holds_quota_episode():
    """A gang whose per-worker memory ask busts the profile's cap parks
    in the same QuotaExceeded Pending episode chips do — the full
    ResourceQuotaSpec is enforced, not just the TPU row."""
    api = FakeApiServer()
    quota.register(api)
    api.create(new_resource(
        "ResourceQuota", "kf-resource-quota", "default",
        spec={"hard": {"memory": "4Gi"}},
    ))
    ctl = TpuJobController(api, quota_retry_seconds=0.05)
    api.create(make_tpujob(
        "gang", replicas=2, tpu_chips_per_worker=0, command=("true",),
        resources=(("memory", "3Gi"),),
    ))
    for _ in range(6):
        ctl.controller.run_until_idle()
    job = api.get(KIND, "gang")
    assert job.status.get("reason") == "QuotaExceeded"
    assert job.status.get("phase") == "Pending"
    assert api.list("Pod", "default",
                    label_selector={LABEL_JOB: "gang"}) == []


# -- strict-spec admission + invalid-spec teardown (ADVICE r3) --------------


def test_strict_spec_enforced_at_admission():
    """A typo'd spec field is a 422 at submit time (create AND update),
    not a Failed job at reconcile time."""
    from kubeflow_tpu.controllers import tpujob as tpujob_mod

    api = FakeApiServer()
    tpujob_mod.register_admission(api)
    bad = make_tpujob("j", replicas=1, tpu_chips_per_worker=0,
                      command=("true",))
    bad.spec["template"] = {}  # the classic K8s-shaped typo
    with pytest.raises(Invalid, match="template"):
        api.create(bad)
    good = make_tpujob("j", replicas=1, tpu_chips_per_worker=0,
                       command=("true",))
    created = api.create(good).thaw()
    created.spec["replicsa"] = 2
    with pytest.raises(Invalid, match="replicsa"):
        api.update(created)


def test_invalid_stored_spec_tears_down_gang_pods():
    """A job whose STORED spec stops parsing (validation tightened across
    an upgrade) goes Failed AND releases its pods — otherwise its chips
    are pinned forever (Failed gangs are invisible to preemption)."""
    api = FakeApiServer()
    ctl = TpuJobController(api)
    api.create(make_tpujob(
        "j", replicas=2, tpu_chips_per_worker=4, command=("sleep", "60"),
    ))
    ctl.controller.run_until_idle()
    assert len(api.list("Pod", "default",
                        label_selector={LABEL_JOB: "j"})) == 2
    # The spec rots in storage (no admission hook on this store).
    job = api.get(KIND, "j").thaw()
    job.spec["surprise"] = True
    api.update(job)
    ctl.controller.run_until_idle()
    job = api.get(KIND, "j")
    assert job.status.get("phase") == "Failed"
    assert api.list("Pod", "default",
                    label_selector={LABEL_JOB: "j"}) == []


def test_exact_fit_milli_values_admit():
    """Quota math is integer milli-units, not binary floats: three 100m
    pods exactly fill a 300m cap (0.1*3 > 0.3 in float64 — the
    spurious-rejection bug class real K8s avoids the same way)."""
    api = FakeApiServer()
    quota.register(api)
    api.create(new_resource(
        "ResourceQuota", "kf-resource-quota", "team",
        spec={"hard": {"cpu": "300m"}},
    ))
    for name in ("a", "b", "c"):
        api.create(_host_pod(name, cpu="100m"))
    with pytest.raises(QuotaExceeded):
        api.create(_host_pod("d", cpu="1m"))


def test_negative_limit_is_rejected_not_credited():
    """A negative 'limit' would SUBTRACT from quota usage (reproduced in
    review round 3): it must 422 at admission, never admit."""
    api = FakeApiServer()
    quota.register(api)
    api.create(new_resource(
        "ResourceQuota", "kf-resource-quota", "team",
        spec={"hard": {"cpu": "4"}},
    ))
    with pytest.raises(Invalid):
        api.create(_host_pod("neg", cpu="-100"))
    # And the bypass it would have enabled stays closed.
    with pytest.raises(QuotaExceeded):
        api.create(_host_pod("big", cpu="100"))


def test_garbage_cap_or_stored_limit_is_422_not_500():
    """A malformed hard cap (profile resourceQuotaSpec passes through
    verbatim) or a garbage limit on a pre-quota pod maps to Invalid with
    the culprit named — never a raw ValueError crash-loop."""
    api = FakeApiServer()
    api.create(_host_pod("old", cpu="plenty"))  # admitted pre-quota
    quota.register(api)
    api.create(new_resource(
        "ResourceQuota", "kf-resource-quota", "team",
        spec={"hard": {"cpu": "4"}},
    ))
    with pytest.raises(Invalid, match="old"):
        api.create(_host_pod("new", cpu="1"))
    # Malformed cap: also a clean 422.
    rq = api.get("ResourceQuota", "kf-resource-quota", "team").thaw()
    rq.spec["hard"]["cpu"] = "lots"
    api.update(rq)
    api.delete("Pod", "old", "team")
    with pytest.raises(Invalid, match="lots"):
        api.create(_host_pod("new2", cpu="1"))


# -- round 5: full ResourceQuotaSpec scope ----------------------------------


def _wait_used(api, pred, ns="team", timeout=5.0):
    """status.used publishes asynchronously (debounced publisher thread);
    poll for the expected value."""
    import time as _t

    deadline = _t.monotonic() + timeout
    while _t.monotonic() < deadline:
        api.flush()
        rq = api.get("ResourceQuota", "kf-resource-quota", ns)
        if pred(rq.status):
            return rq
        _t.sleep(0.02)
    raise AssertionError(f"status.used never converged: {rq.status}")


def _hard(api, hard, ns="team"):
    api.create(new_resource(
        "ResourceQuota", "kf-resource-quota", ns, spec={"hard": hard},
    ))


def _pvc(name, storage="10Gi", ns="team"):
    return new_resource(
        "PersistentVolumeClaim", name, ns,
        spec={"resources": {"requests": {"storage": storage}}},
    )


def _pod_rr(name, ns="team", requests=None, limits=None):
    res = {}
    if requests:
        res["requests"] = requests
    if limits:
        res["limits"] = limits
    return new_resource(
        "Pod", name, ns,
        spec={"containers": [{"name": "w", "resources": res}]},
    )


def test_requests_only_pod_is_metered():
    """THE round-4 hole: a pod sized via requests (no limits) slipped
    every cap. Bare keys are the corev1 requests shorthand and meter it."""
    api = FakeApiServer()
    quota.register(api)
    _hard(api, {"cpu": "2"})
    api.create(_pod_rr("a", requests={"cpu": "1500m"}))
    with pytest.raises(QuotaExceeded, match="used 1.5 \\+ requested 1"):
        api.create(_pod_rr("b", requests={"cpu": "1"}))


def test_requests_default_from_limits():
    """K8s defaulting: a limits-only pod counts against requests caps
    (absent requests inherit limits) — round-4 behavior preserved."""
    api = FakeApiServer()
    quota.register(api)
    _hard(api, {"requests.memory": "1Gi"})
    api.create(_pod_rr("a", limits={"memory": "768Mi"}))
    with pytest.raises(QuotaExceeded):
        api.create(_pod_rr("b", limits={"memory": "512Mi"}))


def test_limits_cap_meters_limits_and_requests_fallback():
    api = FakeApiServer()
    quota.register(api)
    _hard(api, {"limits.cpu": "4"})
    api.create(_pod_rr("a", limits={"cpu": "3"}))
    # requests-only pod still counts against a limits cap (the symmetric
    # bypass, closed via the documented fallback relaxation).
    with pytest.raises(QuotaExceeded):
        api.create(_pod_rr("b", requests={"cpu": "2"}))


def test_prefixed_cap_requires_specification():
    """K8s quota admission: under an explicit requests.cpu cap, a pod
    naming neither requests nor limits for cpu is rejected outright —
    unmeterable pods can't fly under the cap."""
    api = FakeApiServer()
    quota.register(api)
    _hard(api, {"requests.cpu": "4"})
    with pytest.raises(Invalid, match="must specify requests.cpu"):
        api.create(_pod_rr("naked"))
    # Bare-key caps tolerate it (chips-only gang pods under a cpu cap).
    api2 = FakeApiServer()
    quota.register(api2)
    _hard(api2, {"cpu": "4"}, ns="team")
    api2.create(_pod_rr("naked"))


def test_pod_count_quota():
    api = FakeApiServer()
    quota.register(api)
    _hard(api, {"pods": 2})
    api.create(_pod_rr("a"))
    api.create(_pod_rr("b"))
    with pytest.raises(QuotaExceeded, match="'pods'"):
        api.create(_pod_rr("c"))
    # Terminal pods release count budget.
    done = api.get("Pod", "a", "team").thaw()
    done.status["phase"] = "Failed"
    api.update_status(done)
    api.create(_pod_rr("c"))


def test_pvc_count_quota_rejects_nplus1():
    api = FakeApiServer()
    quota.register(api)
    _hard(api, {"persistentvolumeclaims": 2})
    api.create(_pvc("v1"))
    api.create(_pvc("v2"))
    with pytest.raises(QuotaExceeded, match="persistentvolumeclaims"):
        api.create(_pvc("v3"))
    api.delete("PersistentVolumeClaim", "v1", "team")
    api.create(_pvc("v3"))  # freed


def test_requests_storage_quota():
    api = FakeApiServer()
    quota.register(api)
    _hard(api, {"requests.storage": "30Gi"})
    api.create(_pvc("v1", "20Gi"))
    with pytest.raises(QuotaExceeded, match="requests.storage"):
        api.create(_pvc("v2", "20Gi"))
    api.create(_pvc("v2", "10Gi"))  # exact fit


def test_generic_count_quota():
    """count/<resource> meters any stored kind (K8s object-count
    quotas), including CamelCase kinds via the explicit inverse map."""
    api = FakeApiServer()
    quota.register(api)
    _hard(api, {"count/notebooks": 1, "count/tpujobs": 1})
    api.create(new_resource("Notebook", "nb1", "team", spec={}))
    with pytest.raises(QuotaExceeded, match="count/notebooks"):
        api.create(new_resource("Notebook", "nb2", "team", spec={}))
    api.create(make_tpujob("j1", replicas=1, namespace="team"))
    with pytest.raises(QuotaExceeded, match="count/tpujobs"):
        api.create(make_tpujob("j2", replicas=1, namespace="team"))


def test_status_used_published():
    """The K8s quota controller's status surface: hard + used appear on
    the quota object and track pod/PVC lifecycle."""
    api = FakeApiServer()
    quota.register(api)
    _hard(api, {"cpu": "4", "pods": 5, "requests.storage": "100Gi",
                "persistentvolumeclaims": 3})
    api.create(_pod_rr("a", requests={"cpu": "1500m"}))
    api.create(_pvc("v1", "10Gi"))
    rq = _wait_used(
        api,
        lambda st: st.get("used", {}).get("pods") == 1
        and st.get("used", {}).get("persistentvolumeclaims") == 1,
    )
    assert rq.status["hard"]["pods"] == 5
    assert rq.status["used"]["cpu"] == "1500m"
    assert rq.status["used"]["persistentvolumeclaims"] == 1
    assert rq.status["used"]["requests.storage"] == 10 * 1024 ** 3
    api.delete("Pod", "a", "team")
    rq = _wait_used(api, lambda st: st.get("used", {}).get("pods") == 0)
    assert rq.status["used"]["cpu"] == 0


def test_update_to_terminal_pod_is_not_charged():
    """K8s excludes terminal pods from every quota scope: an UPDATE to a
    finished pod in a FULL namespace must not be rejected as if it were
    a new live pod (usage correctly excludes it; the ask must too)."""
    api = FakeApiServer()
    quota.register(api)
    _hard(api, {"pods": 1, "cpu": "1"})
    api.create(_pod_rr("live", requests={"cpu": "1"}))
    done = _pod_rr("done", requests={"cpu": "1"})
    done.status["phase"] = "Succeeded"
    # Create of an already-terminal pod (runtime materialization) and
    # updates to it are both exempt.
    api.create(done)
    fresh = api.get("Pod", "done", "team").thaw()
    fresh.metadata.labels["archived"] = "yes"
    api.update(fresh)
