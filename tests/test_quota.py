"""ResourceQuota enforcement — the quota admission the reference got
from the real apiserver's built-in controller and we must provide
ourselves (`controllers/quota.py`). The profile controller materializes
the caps; admission makes them real."""

import pytest

from kubeflow_tpu.api import make_tpujob
from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.api.tpujob import KIND
from kubeflow_tpu.controllers import quota
from kubeflow_tpu.controllers.quota import QuotaExceeded
from kubeflow_tpu.controllers.tpujob import LABEL_JOB, TpuJobController
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.testing.fake_apiserver import Invalid


def _pod(name, ns="team", chips=4, node=None):
    spec = {
        "containers": [
            {"name": "w",
             "resources": {"limits": {"google.com/tpu": chips}}}
        ],
    }
    if node:
        spec["nodeName"] = node
    return new_resource("Pod", name, ns, spec=spec)


def _quota(api, ns="team", chips=8):
    api.create(new_resource(
        "ResourceQuota", "kf-resource-quota", ns,
        spec={"hard": {"google.com/tpu": chips}},
    ))


def test_pod_over_quota_rejected():
    api = FakeApiServer()
    quota.register(api)
    _quota(api, chips=8)
    api.create(_pod("a", chips=4))
    api.create(_pod("b", chips=4))
    with pytest.raises(QuotaExceeded) as err:
        api.create(_pod("c", chips=1))
    assert "used 8 + requested 1 > hard cap 8" in str(err.value)
    # QuotaExceeded IS Invalid: the HTTP facade maps it to 422.
    assert isinstance(err.value, Invalid)


def test_terminal_pods_release_budget():
    api = FakeApiServer()
    quota.register(api)
    _quota(api, chips=4)
    api.create(_pod("a", chips=4))
    done = api.get("Pod", "a", "team")
    done.status["phase"] = "Succeeded"
    api.update_status(done)
    api.create(_pod("b", chips=4))  # fits now


def test_unmetered_namespace_and_zero_ask_pass():
    api = FakeApiServer()
    quota.register(api)
    api.create(_pod("free", ns="open", chips=16))  # no quota object
    _quota(api, chips=0)
    api.create(new_resource("Pod", "cpu-only", "team",
                            spec={"containers": [{"name": "w"}]}))


def test_update_does_not_double_count_self():
    api = FakeApiServer()
    quota.register(api)
    _quota(api, chips=4)
    api.create(_pod("a", chips=4))
    pod = api.get("Pod", "a", "team")
    pod.spec["nodeName"] = "n0"
    api.update(pod)  # re-admission must exclude its own usage


def test_gang_over_quota_holds_pending_episode():
    """All-or-nothing cuts both ways: if worker #2 busts the budget,
    worker #1 must not be left running — the job parks in a
    QuotaExceeded Pending episode and recovers when budget frees."""
    api = FakeApiServer()
    quota.register(api)
    _quota(api, ns="default", chips=4)
    ctl = TpuJobController(api, quota_retry_seconds=0.05)
    api.create(make_tpujob(
        "gang", replicas=2, tpu_chips_per_worker=4, command=("true",),
    ))
    for _ in range(6):
        ctl.controller.run_until_idle()
    job = api.get(KIND, "gang")
    assert job.status.get("reason") == "QuotaExceeded"
    assert job.status.get("phase") == "Pending"
    assert api.list("Pod", "default",
                    label_selector={LABEL_JOB: "gang"}) == []
    reasons = {e.spec["reason"] for e in api.list("Event", "default")}
    assert "QuotaExceeded" in reasons

    # The budget doubles (profile edit); the next pass starts the gang.
    rq = api.get("ResourceQuota", "kf-resource-quota", "default")
    rq.spec["hard"]["google.com/tpu"] = 8
    api.update(rq)
    import time as _time

    _time.sleep(0.1)  # past the quota retry gate
    ctl.controller.enqueue(("default", "gang"))
    for _ in range(6):
        ctl.controller.run_until_idle()
    job = api.get(KIND, "gang")
    assert len(api.list("Pod", "default",
                        label_selector={LABEL_JOB: "gang"})) == 2
    assert job.status.get("reason") is None


def test_materializer_contains_quota_rejection():
    """An over-quota notebook STS must not starve other workloads'
    materialization, and the tenant gets a PodRejected event."""
    from kubeflow_tpu.runtime import WorkloadMaterializer

    api = FakeApiServer()
    quota.register(api)
    _quota(api, ns="team", chips=0)
    api.create(new_resource("StatefulSet", "greedy", "team", spec={
        "replicas": 1,
        "template": {"spec": {"containers": [
            {"name": "nb",
             "resources": {"limits": {"google.com/tpu": 4}}}]}},
    }))
    api.create(new_resource("StatefulSet", "modest", "team", spec={
        "replicas": 1,
        "template": {"spec": {"containers": [{"name": "nb"}]}},
    }))
    m = WorkloadMaterializer(api)
    for _ in range(3):
        m.step()
    pods = {p.metadata.name for p in api.list("Pod", "team")}
    assert any(p.startswith("modest") for p in pods), pods
    assert not any(p.startswith("greedy") for p in pods), pods
    reasons = {e.spec["reason"] for e in api.list("Event", "team")}
    assert "PodRejected" in reasons
    # Episode-deduped: repeated steps don't spam events.
    count = sum(
        1 for e in api.list("Event", "team")
        if e.spec["reason"] == "PodRejected"
    )
    assert count == 1
