"""Tests for the native data plane: record files, prefetching loader,
typed datasets, gang sharding, and mesh delivery."""

import numpy as np
import pytest

from kubeflow_tpu.native.dataloader import (
    RecordLoader,
    RecordWriter,
    stat_record_file,
)
from kubeflow_tpu.parallel.distributed import ProcessEnv
from kubeflow_tpu.train.records import (
    Field,
    RecordDataset,
    RecordSpec,
    write_records,
)

SPEC = RecordSpec.of(image=("uint8", (4, 4, 3)), label=("int32", ()))


def _write(tmp_path, name, n, offset=0):
    path = tmp_path / name
    write_records(
        str(path),
        SPEC,
        (
            {
                "image": np.full((4, 4, 3), (offset + i) % 255, np.uint8),
                "label": np.int32(offset + i),
            }
            for i in range(n)
        ),
    )
    return str(path)


def test_writer_and_stat(tmp_path):
    path = _write(tmp_path, "a.rec", 5)
    record_bytes, count = stat_record_file(path)
    assert record_bytes == SPEC.record_bytes == 4 * 4 * 3 + 4
    assert count == 5


def test_writer_rejects_wrong_size(tmp_path):
    with RecordWriter(str(tmp_path / "w.rec"), 16) as w:
        with pytest.raises(ValueError):
            w.append(b"short")


def test_loader_single_epoch_exact_coverage(tmp_path):
    path = _write(tmp_path, "a.rec", 10)
    loader = RecordLoader(path, batch_size=4, epochs=1, drop_remainder=False)
    seen = []
    for raw, n in loader:
        batch = SPEC.decode_batch(raw[:n])
        seen.extend(batch["label"].tolist())
    assert sorted(seen) == list(range(10))


def test_dataset_decodes_fields(tmp_path):
    path = _write(tmp_path, "a.rec", 8)
    ds = RecordDataset(path, SPEC, batch_size=4, epochs=1)
    batch = next(iter(ds))
    assert batch["image"].shape == (4, 4, 4, 3)
    assert batch["label"].shape == (4,)
    # Image pixel content matches the label it was written with.
    assert int(batch["image"][0, 0, 0, 0]) == int(batch["label"][0]) % 255


def test_dataset_spec_mismatch_rejected(tmp_path):
    path = _write(tmp_path, "a.rec", 4)
    wrong = RecordSpec.of(image=("uint8", (2, 2, 3)), label=("int32", ()))
    with pytest.raises(ValueError, match="spec decodes"):
        RecordDataset(path, wrong, batch_size=2)


def test_gang_sharding_partitions_records(tmp_path):
    path = _write(tmp_path, "a.rec", 24)
    labels = {}
    for rank in range(3):
        env = ProcessEnv(
            coordinator="c:1", num_processes=3, process_id=rank
        )
        ds = RecordDataset(
            path, SPEC, batch_size=24, process_env=env, epochs=1
        )
        assert ds.local_batch_size == 8
        assert ds.shard_records == 8
        got = [int(x) for b in ds for x in b["label"]]
        labels[rank] = set(got)
    union = set().union(*labels.values())
    assert union == set(range(24))
    assert labels[0] & labels[1] == set()  # disjoint shards


def test_global_batch_must_divide(tmp_path):
    path = _write(tmp_path, "a.rec", 8)
    env = ProcessEnv(coordinator="c:1", num_processes=3, process_id=0)
    with pytest.raises(ValueError, match="divide"):
        RecordDataset(path, SPEC, batch_size=8, process_env=env)


def test_multi_file_and_shuffle_determinism(tmp_path):
    a = _write(tmp_path, "a.rec", 6)
    b = _write(tmp_path, "b.rec", 6, offset=6)

    def labels(seed):
        ds = RecordDataset(
            [a, b], SPEC, batch_size=12, shuffle_buffer=12, seed=seed,
            epochs=1,
        )
        return [int(x) for batch in ds for x in batch["label"]]

    assert sorted(labels(3)) == list(range(12))
    assert labels(3) == labels(3)
    assert labels(3) != labels(4)


def test_device_iter_shards_on_mesh(mesh8):
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        import pathlib

        path = _write(pathlib.Path(d), "a.rec", 16)
        ds = RecordDataset(path, SPEC, batch_size=8, epochs=1)
        batch = next(ds.device_iter(mesh8))
        assert batch["image"].shape == (8, 4, 4, 3)
        # The batch dim is sharded over the mesh's batch axes.
        assert len(batch["image"].sharding.device_set) > 1
