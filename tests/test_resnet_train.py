"""ResNet trainer end-to-end on a sharded CPU mesh: loss goes down."""
import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.resnet import resnet50, tiny_resnet
from kubeflow_tpu.parallel import MeshSpec, build_mesh
from kubeflow_tpu.train import SyntheticImages, TrainConfig, Trainer


def _trainer(mesh, **cfg):
    config = TrainConfig(
        batch_size=16,
        learning_rate=0.1,
        warmup_steps=2,
        total_steps=20,
        **cfg,
    )
    model = tiny_resnet()
    return Trainer(
        model, config, mesh, example_input_shape=(2, 32, 32, 3)
    )


def test_resnet50_param_count():
    # The canonical ResNet-50 has 25.56M params; catches block-wiring bugs.
    model = resnet50()
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)))
    )
    import flax

    n = sum(
        np.prod(x.shape)
        for x in jax.tree_util.tree_leaves(flax.linen.meta.unbox(variables["params"]))
    )
    assert 25_500_000 < n < 25_620_000, f"param count {n}"


def test_train_step_decreases_loss(mesh8):
    trainer = _trainer(mesh8)
    state = trainer.init_state(jax.random.PRNGKey(0))
    data = SyntheticImages(
        mesh8, batch_size=16, image_size=32, num_classes=10, dtype=jnp.float32
    )
    step = trainer.make_train_step()
    it = iter(data)
    losses = []
    for _ in range(10):
        state, metrics = step(state, next(it))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses
    assert int(state.step) == 10


def test_state_is_sharded_fsdp(mesh8):
    trainer = _trainer(mesh8)
    state = trainer.init_state(jax.random.PRNGKey(0))
    # The stem conv kernel (3,3,3,8): conv_out=8 sharded over fsdp=2.
    stem = state.params["conv_stem"]["kernel"]
    spec = stem.sharding.spec
    assert "fsdp" in str(spec), spec
    # Momentum inherits the same sharding (boxes survive optax.init).
    mu = jax.tree_util.tree_leaves(
        state.opt_state, is_leaf=lambda x: hasattr(x, "sharding")
    )
    assert any("fsdp" in str(m.sharding.spec) for m in mu if m.ndim > 1)


def test_eval_step(mesh8):
    trainer = _trainer(mesh8)
    state = trainer.init_state(jax.random.PRNGKey(0))
    data = SyntheticImages(
        mesh8, batch_size=16, image_size=32, num_classes=10, dtype=jnp.float32
    )
    metrics = trainer.make_eval_step()(state, next(iter(data)))
    assert 0.0 <= float(metrics["accuracy"]) <= 1.0
