"""RL subsystem units + the in-process actor–learner integration loop.

The integration test here is the tentpole's proof shape at unit scale:
a CR-materialized policy fleet (ServingDeployment → controller →
in-proc replicas behind the router), actors rolling out through the
batcher, a stock guarded `fit()` learner on the replay queue, and
weight publication riding checkpoint-save → modelVersion bump →
drain-roll — observed in-band by the actors. `bench.py --workload rl`
runs the same loop bigger and under chaos.
"""

import numpy as np
import pytest

from kubeflow_tpu.rl.env import (
    EnvConfig,
    VectorEnv,
    rollout,
)
from kubeflow_tpu.rl.replay import ReplayQueue, ReplayStalled


def fixed_predict(env_cfg, version=1):
    """Deterministic stand-in for the serving stack in unit tests."""

    def predict(obs):
        return obs[:, : env_cfg.n_actions].copy(), version

    return predict


# -- env ------------------------------------------------------------------


def test_rollout_is_pure_function_of_seed_salt_index():
    cfg = EnvConfig(seed=11, horizon=4, n_envs=3)
    env_a, env_b = VectorEnv(cfg), VectorEnv(cfg)
    ta = rollout(env_a, fixed_predict(cfg), 5, salt=2)
    tb = rollout(env_b, fixed_predict(cfg), 5, salt=2)
    np.testing.assert_array_equal(ta.obs, tb.obs)
    np.testing.assert_array_equal(ta.actions, tb.actions)
    np.testing.assert_array_equal(ta.rewards, tb.rewards)
    # Different salt (the guard's rollback perturbation) must change the
    # trajectory; different index must too.
    tc = rollout(env_a, fixed_predict(cfg), 5, salt=3)
    assert not np.array_equal(ta.obs, tc.obs)
    td = rollout(env_a, fixed_predict(cfg), 6, salt=2)
    assert not np.array_equal(ta.obs, td.obs)


def test_trajectory_transitions_pack_action_and_return():
    cfg = EnvConfig(seed=0, horizon=2, n_envs=2)
    env = VectorEnv(cfg)
    traj = rollout(env, fixed_predict(cfg, version=7), 0)
    assert traj.policy_version == 7
    batch = traj.transitions()
    assert batch["obs"].shape == (4, cfg.obs_dim)
    assert batch["target"].shape == (4, 2)
    np.testing.assert_array_equal(
        batch["target"][:, 0].astype(np.int32),
        traj.actions.reshape(-1),
    )
    np.testing.assert_array_equal(
        batch["target"][:, 1], traj.rewards.reshape(-1)
    )


def test_optimal_policy_earns_full_return():
    cfg = EnvConfig(seed=3, horizon=5, n_envs=4)
    env = VectorEnv(cfg)
    obs = env.observe(0, 0)
    rewards = env.rewards(obs, env.optimal_actions(obs))
    np.testing.assert_array_equal(rewards, np.ones(cfg.n_envs))


# -- replay queue ---------------------------------------------------------


def _batch(i):
    return {"obs": np.full((4, 2), i, np.float32),
            "target": np.zeros((4, 2), np.float32)}


def test_replay_fifo_order_and_position():
    q = ReplayQueue(capacity=4, stall_timeout_s=5)
    claims = [q.claim() for _ in range(3)]
    # Out-of-order pushes (two actors racing) still yield in order.
    for i in [2, 0, 1]:
        idx, salt = claims[i]
        assert q.push(idx, salt, version=1, batch=_batch(idx))
    got = [next(q)["obs"][0, 0] for _ in range(3)]
    assert got == [0, 1, 2]
    assert q.state_dict() == {"position": 3, "salt": 0}


def test_replay_resume_continues_claims_and_rejects_stale_pushes():
    q = ReplayQueue(capacity=4, stall_timeout_s=5)
    stale = q.claim()  # in flight across the restore boundary
    q.load_state_dict({"position": 7, "salt": 2})
    # The pre-restore ticket bounces: wrong salt AND index < position.
    assert not q.push(stale[0], stale[1], version=1, batch=_batch(0))
    assert q.rejected_pushes == 1
    # Fresh claims continue exactly at the restored position.
    idx, salt = q.claim()
    assert (idx, salt) == (7, 2)
    assert q.push(idx, salt, version=1, batch=_batch(7))
    next(q)
    assert q.state_dict() == {"position": 8, "salt": 2}


def test_replay_perturb_invalidates_buffered_work():
    q = ReplayQueue(capacity=4, stall_timeout_s=5)
    idx, salt = q.claim()
    assert q.push(idx, salt, version=1, batch=_batch(idx))
    q.perturb(5)
    # Buffered pre-perturb work is gone; the index is re-claimable with
    # the new salt (the retried trajectory must differ).
    idx2, salt2 = q.claim()
    assert (idx2, salt2) == (0, 5)


def test_replay_abandoned_claim_is_reissued():
    q = ReplayQueue(capacity=4, stall_timeout_s=5)
    a = q.claim()
    b = q.claim()
    q.abandon(a[0], a[1])  # actor died mid-rollout
    # Reissued before any new index — no permanent gap for the
    # in-order learner to stall behind.
    assert q.claim() == (a[0], a[1])
    assert q.push(a[0], a[1], version=1, batch=_batch(0))
    assert q.push(b[0], b[1], version=1, batch=_batch(1))
    next(q), next(q)


def test_replay_staleness_bound_drops_stale_and_stalls_loudly():
    q = ReplayQueue(capacity=8, staleness_bound=2, stall_timeout_s=0.3)
    for _ in range(4):
        idx, salt = q.claim()
        q.push(idx, salt, version=1, batch=_batch(idx))
    # Learner far ahead of the behavior policy: everything buffered is
    # past the bound — dropped (counted), never trained on; with the
    # backlog cleared and nothing fresh arriving, the stall is loud.
    q.note_learner_step(20)
    with pytest.raises(ReplayStalled):
        next(q)
    assert q.stale_dropped == 4
    assert q.state_dict()["position"] == 4  # drops still retire indices
    # A fresh trajectory (actors past the publish) trains normally.
    idx, salt = q.claim()
    q.push(idx, salt, version=20, batch=_batch(idx))
    assert next(q) is not None
    assert q.stale_dropped == 4


def test_replay_within_bound_trajectories_are_not_dropped():
    q = ReplayQueue(capacity=8, staleness_bound=5, stall_timeout_s=1)
    idx, salt = q.claim()
    q.push(idx, salt, version=6, batch=_batch(idx))
    q.note_learner_step(10)  # 11 - 6 = 5 <= bound: admissible
    assert next(q) is not None
    assert q.stale_dropped == 0


def test_replay_backpressure_at_claim_never_wedges_a_held_ticket():
    """The out-of-order-full deadlock shape: one actor holds the head
    index while another fills the buffer. Backpressure must land on the
    NEXT claim, not on the held ticket's push — otherwise the in-order
    learner waits on a gap whose owner waits on the learner."""
    q = ReplayQueue(capacity=2, stall_timeout_s=5)
    head = q.claim()       # actor A: slow rollout, holds index 0
    other = q.claim()      # actor B: index 1
    assert q.push(other[0], other[1], version=1, batch=_batch(1))
    # B's NEXT claim is outside [position, position+capacity) and must
    # block — verify without threads by checking the window directly.
    assert q._next_claim == q.state_dict()["position"] + q.capacity
    # A's push of the head index always has room.
    assert q.push(head[0], head[1], version=1, batch=_batch(0))
    assert next(q)["obs"][0, 0] == 0
    assert next(q)["obs"][0, 0] == 1
    # Learner progress reopened the window.
    assert q.claim() == (2, 0)


# -- the integration loop -------------------------------------------------


def test_actor_learner_loop_end_to_end(tmp_path, devices):
    """CR-materialized fleet + real fit() + publication drain-rolls."""
    import jax

    from kubeflow_tpu.api import serving as serving_api
    from kubeflow_tpu.controllers.serving import ServingDeploymentController
    from kubeflow_tpu.parallel import MeshSpec, build_mesh
    from kubeflow_tpu.rl.loop import RLConfig, build_learner, run_actor_learner
    from kubeflow_tpu.rl.policy import PolicyCheckpointPublisher
    from kubeflow_tpu.serving.replica import LocalReplicaRuntime
    from kubeflow_tpu.serving.router import Router
    from kubeflow_tpu.testing.fake_apiserver import FakeApiServer
    from kubeflow_tpu.train import Checkpointer, FitResult
    from kubeflow_tpu.rl.replay import ReplayQueue as RQ

    cfg = RLConfig(
        env=EnvConfig(seed=5, horizon=4, n_envs=8, obs_dim=8, n_actions=4),
        hidden=16,
        total_steps=24,
        publish_every=8,
        staleness_bound=16,
        n_actors=2,
        learning_rate=0.05,
    )
    mesh = build_mesh(MeshSpec(dp=2), devices[:2])
    trainer = build_learner(cfg, mesh)
    cpu0 = jax.devices("cpu")[0]
    publisher = PolicyCheckpointPublisher(
        str(tmp_path / "ckpt"),
        trainer.abstract_state,
        obs_dim=cfg.env.obs_dim,
        n_actions=cfg.env.n_actions,
        hidden=cfg.hidden,
        device=cpu0,
    )
    api = FakeApiServer()
    router = Router()
    ctl = ServingDeploymentController(
        api, runtime=LocalReplicaRuntime(router, publisher)
    )
    api.create(serving_api.make_serving_deployment(
        "pol", model="policy", replicas=2, max_batch=8,
        batch_timeout_ms=1.0,
    ))
    ctl.controller.run_until_idle()
    assert len(router.ready_names()) == 2

    ckpt = Checkpointer(
        str(tmp_path / "ckpt"),
        save_interval_steps=cfg.publish_every,
    )
    queue = RQ(
        capacity=cfg.replay_capacity,
        staleness_bound=cfg.staleness_bound,
        mesh=mesh,
        stall_timeout_s=60,
    )
    try:
        result = run_actor_learner(
            api=api,
            deployment="pol",
            router=router,
            trainer=trainer,
            checkpointer=ckpt,
            queue=queue,
            cfg=cfg,
            reconcile=ctl.controller.run_until_idle,
        )
    finally:
        ckpt.close()

    assert isinstance(result.fit_result, FitResult)
    assert result.fit_result.steps_done == cfg.total_steps
    # Publications happened at every publish boundary and each was
    # observed by the actors in-band (version column) after the roll.
    versions = [p.version for p in result.publishes]
    assert versions == [8, 16, 24]
    assert len(result.publish_latencies) == 3, result.publishes
    assert all(s >= 0 for s in result.publish_latencies)
    # The fleet really rolled: replicas now serve the final version.
    dep = api.get(serving_api.KIND, "pol", "default")
    assert int(dep.spec["modelVersion"]) == 24
    for rname in router.ready_names():
        assert router.replica(rname).version == 24
    # Actors made progress through the serving stack; every retired
    # index is accounted for as either a learner batch or a counted
    # staleness drop — nothing vanishes.
    assert result.actor_steps > 0
    assert (
        queue.state_dict()["position"]
        == cfg.total_steps + result.stale_dropped
    )
    # No request left mid-flight in the fleet.
    assert router.stats()["outstanding"] == 0
