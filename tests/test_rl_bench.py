"""CI smoke for `bench.py --workload rl` (ISSUE 12): the actor–learner
bench must run end-to-end at tiny scale — the coupled loop over the real
serving fleet, the contention measurement, and the seeded-chaos StudyJob
soak — and every headline row must resolve a real vs_baseline ratio
against BASELINE.json's published rl_* entries."""

import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_rl_bench_smoke_rows_resolve_baseline():
    result = subprocess.run(
        [
            sys.executable, "bench.py", "--workload", "rl",
            "--rl-steps", "24",
            "--rl-publish-every", "8",
            "--chaos-seed", "7",
        ],
        cwd=REPO,
        env={
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        },
        capture_output=True,
        text=True,
        timeout=280,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    metrics = [
        json.loads(line)
        for line in result.stdout.splitlines()
        if line.startswith("{")
    ]
    assert metrics, f"no metric lines in:\n{result.stdout}"
    by_name = {}
    for m in metrics:
        # The driver's parse contract — same shape as every other bench.
        assert set(m) == {"metric", "value", "unit", "vs_baseline"}, m
        assert isinstance(m["value"], (int, float)) and m["value"] > 0, m
        by_name[m["metric"]] = m

    # Every headline row resolves a ratio vs the published baseline.
    for name in (
        "rl_studies_per_hour",
        "rl_learner_mfu_under_actor_traffic",
        "rl_actor_steps_per_sec",
        "rl_policy_publish_to_actor_seconds",
    ):
        assert name in by_name, (name, sorted(by_name))
        assert by_name[name]["vs_baseline"] is not None, by_name[name]

    # The contention ratio is a fraction of the solo step rate, and the
    # publish->actor latency is wall-clock seconds, not a counter.
    assert 0 < by_name["rl_learner_mfu_under_actor_traffic"]["value"] <= 1.5
    assert by_name["rl_policy_publish_to_actor_seconds"]["value"] < 60

    # The soak's repro contract: the seed is printed up front, the chaos
    # schedule covered every RL fault class, and the study-loss gate held
    # (nonzero exit would have tripped above).
    assert "# rl soak seed=7" in result.stderr
    assert "'actor_kill': 1" in result.stderr
    assert "'learner_kill': 1" in result.stderr
    assert "'trial_kill': 1" in result.stderr
    assert "zero lost studies" in result.stderr
