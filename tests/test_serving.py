"""Model-server tests — the in-process analog of the reference's
golden-prediction serving E2E (`testing/test_tf_serving.py:60-156`)."""

import json

import jax
import numpy as np
import pytest

from kubeflow_tpu.models.resnet import tiny_resnet
from kubeflow_tpu.serving import ModelRepository, ModelServerApp, Servable
from kubeflow_tpu.web import TestClient


@pytest.fixture(scope="module")
def model():
    module = tiny_resnet(num_classes=10)
    variables = jax.jit(module.init)(
        jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32)
    )
    return module, variables


@pytest.fixture(scope="module")
def client(model):
    module, variables = model
    servable = Servable.from_module(
        "mnist", module, variables, max_batch=8, train=False
    )
    repo = ModelRepository([servable])
    return TestClient(ModelServerApp(repo))


def _instances(n, seed=0):
    rng = np.random.RandomState(seed)
    return rng.rand(n, 32, 32, 3).astype(np.float32).tolist()


def test_model_status(client):
    resp = client.get("/v1/models/mnist")
    assert resp.status == 200
    status = resp.json()["model_version_status"][0]
    assert status["state"] == "AVAILABLE"
    assert status["status"]["error_code"] == "OK"


def test_unknown_model_404(client):
    assert client.get("/v1/models/nope").status == 404
    assert client.post("/v1/models/nope:predict", {"instances": [[1]]}).status == 404


def test_predict_golden(client, model):
    """The reference compares REST predictions to a golden JSON with
    tolerance 0.001 (`test_tf_serving.py:40-58,107-118`). Our golden is the
    direct (unbatched, unpadded) module apply — the server's bucket padding
    must not change the numbers."""
    module, variables = model
    instances = _instances(3)
    resp = client.post("/v1/models/mnist:predict", {"instances": instances})
    assert resp.status == 200, resp.body
    got = np.asarray(resp.json()["predictions"])
    want = np.asarray(
        module.apply(variables, np.asarray(instances, np.float32), train=False)
    )
    assert got.shape == (3, 10)
    np.testing.assert_allclose(got, want, atol=1e-3)


def test_predict_oversized_batch_chunks(client):
    # 19 instances > max_batch=8: chunked 8+8+3, order preserved.
    instances = _instances(19, seed=1)
    resp = client.post("/v1/models/mnist:predict", {"instances": instances})
    assert resp.status == 200
    preds = np.asarray(resp.json()["predictions"])
    assert preds.shape == (19, 10)
    # Same instance -> same prediction regardless of position/chunk.
    solo = client.post(
        "/v1/models/mnist:predict", {"instances": instances[17:18]}
    )
    np.testing.assert_allclose(
        preds[17], np.asarray(solo.json()["predictions"])[0], atol=1e-3
    )


def test_predict_validation(client):
    assert client.post("/v1/models/mnist:predict", {}).status == 400
    assert (
        client.post("/v1/models/mnist:predict", {"instances": []}).status == 400
    )
    assert (
        client.post("/v1/models/mnist:frobnicate", {"instances": [[1]]}).status
        == 400
    )
    bad_shape = client.post(
        "/v1/models/mnist:predict", {"instances": [[1.0, 2.0]]}
    )
    assert bad_shape.status == 400


def test_models_list_and_metrics(client):
    assert client.get("/v1/models").json() == {"models": ["mnist"]}
    metrics = client.get("/metrics")
    assert metrics.status == 200
    assert b"serving_requests_total" in metrics.body


def test_from_checkpoint_roundtrip(tmp_path, model):
    """Servable restores params written by the training Checkpointer and
    reports the checkpoint step as its version."""
    from kubeflow_tpu.train.checkpoint import Checkpointer

    module, variables = model
    ckpt = Checkpointer(tmp_path / "ckpt", save_interval_steps=1)
    ckpt.save(7, variables, force=True)
    ckpt.wait()
    ckpt.close()

    servable = Servable.from_checkpoint(
        "restored",
        module,
        tmp_path / "ckpt",
        np.zeros((1, 32, 32, 3), np.float32),
        max_batch=4,
        train=False,
    )
    assert servable.version == 7
    want = np.asarray(
        module.apply(variables, np.zeros((2, 32, 32, 3), np.float32), train=False)
    )
    got = servable.predict(np.zeros((2, 32, 32, 3), np.float32))
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_hot_swap_version(model):
    module, variables = model
    repo = ModelRepository(
        [Servable.from_module("m", module, variables, version=1, train=False)]
    )
    client = TestClient(ModelServerApp(repo))
    assert (
        client.get("/v1/models/m").json()["model_version_status"][0]["version"]
        == "1"
    )
    repo.load(Servable.from_module("m", module, variables, version=2, train=False))
    # Both versions stay live; unversioned requests serve the newest.
    assert [
        s["version"]
        for s in client.get("/v1/models/m").json()["model_version_status"]
    ] == ["1", "2"]


def test_predictions_are_json_serializable(client):
    resp = client.post(
        "/v1/models/mnist:predict", {"instances": _instances(1)}
    )
    json.dumps(resp.json())  # must not raise


# -- model versions (TF-Serving /versions/<v> surface) ---------------------


@pytest.fixture()
def versioned_client(model):
    module, variables = model
    v1 = Servable.from_module("m", module, variables, version=1,
                              max_batch=8, train=False)
    # Version 2: same module, different params -> different predictions.
    variables2 = jax.jit(module.init)(
        jax.random.PRNGKey(7), np.zeros((1, 32, 32, 3), np.float32)
    )
    v2 = Servable.from_module("m", module, variables2, version=2,
                              max_batch=8, train=False)
    repo = ModelRepository([v1, v2])
    return TestClient(ModelServerApp(repo)), repo


def test_unversioned_status_lists_all_versions(versioned_client):
    client, _ = versioned_client
    resp = client.get("/v1/models/m")
    versions = [s["version"] for s in resp.json()["model_version_status"]]
    assert versions == ["1", "2"]


def test_versioned_predict_and_latest_default(versioned_client):
    client, _ = versioned_client
    instances = _instances(2)
    p1 = client.post("/v1/models/m/versions/1:predict",
                     {"instances": instances}).json()["predictions"]
    p2 = client.post("/v1/models/m/versions/2:predict",
                     {"instances": instances}).json()["predictions"]
    latest = client.post("/v1/models/m:predict",
                         {"instances": instances}).json()["predictions"]
    assert np.allclose(latest, p2)  # unversioned = newest
    assert not np.allclose(p1, p2)  # versions genuinely differ


def test_versioned_status_and_404s(versioned_client):
    client, _ = versioned_client
    resp = client.get("/v1/models/m/versions/2")
    assert [s["version"] for s in resp.json()["model_version_status"]] == ["2"]
    assert client.get("/v1/models/m/versions/9").status == 404
    assert client.post("/v1/models/m/versions/9:predict",
                       {"instances": _instances(1)}).status == 404
    assert client.get("/v1/models/m/versions/two").status == 400


def test_unload_rolls_back_to_previous(versioned_client):
    client, repo = versioned_client
    instances = _instances(2)
    p1 = client.post("/v1/models/m/versions/1:predict",
                     {"instances": instances}).json()["predictions"]
    repo.unload("m", 2)
    latest = client.post("/v1/models/m:predict",
                         {"instances": instances}).json()["predictions"]
    assert np.allclose(latest, p1)  # rollback: latest is v1 again
    repo.unload("m", 1)
    assert client.get("/v1/models/m").status == 404
