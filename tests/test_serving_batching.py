"""Dynamic batching scheduler — the TF-Serving batcher analog.

The reference's serving story leans on TF-Serving, whose batching
scheduler merges concurrent requests into one accelerator execution
(`docs_dev/tf_serving.md` deploys it; batch-1 inference leaves the MXU
nearly idle). These tests pin the scheduler semantics on
`serving.BatchingQueue`: concurrent callers share one execution, each
gets exactly its rows, the timeout bounds latency, errors stay inside
their flush, and backpressure rejects instead of queueing unboundedly.
"""

import threading
import time

import numpy as np
import pytest

from kubeflow_tpu.serving import (
    BatchingConfig,
    BatchingQueue,
    ModelRepository,
    ModelServerApp,
)
from kubeflow_tpu.serving.batching import QueueFull
from kubeflow_tpu.web import TestClient


class CountingServable:
    """Identity 'model' that records every underlying execution."""

    name = "ident"
    version = 1

    def __init__(self, fail_batches=()):
        self.calls: list[int] = []
        self.fail_batches = set(fail_batches)
        self._lock = threading.Lock()

    def predict(self, instances):
        batch = np.asarray(instances)
        with self._lock:
            self.calls.append(batch.shape[0])
            if len(self.calls) - 1 in self.fail_batches:
                raise RuntimeError("injected device fault")
        return batch * 2.0


def _concurrent(queue, inputs):
    """Submit each input from its own thread; return results in order."""
    results = [None] * len(inputs)
    errors = [None] * len(inputs)

    def call(i):
        try:
            results[i] = queue.predict(inputs[i])
        except BaseException as e:
            errors[i] = e

    threads = [
        threading.Thread(target=call, args=(i,)) for i in range(len(inputs))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    return results, errors


def test_concurrent_singles_share_one_execution():
    model = CountingServable()
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=8, timeout_ms=50.0)
    )
    try:
        inputs = [np.full((1, 4), float(i)) for i in range(8)]
        results, errors = _concurrent(queue, inputs)
        assert errors == [None] * 8
        # Everyone got exactly their own rows back.
        for i, out in enumerate(results):
            np.testing.assert_array_equal(out, np.full((1, 4), 2.0 * i))
        # ...via far fewer device executions than callers (a full batch
        # flushes as one; stragglers may ride a second flush).
        assert len(model.calls) <= 2, model.calls
        assert sum(model.calls) == 8
    finally:
        queue.close()


def test_timeout_flushes_partial_batch():
    model = CountingServable()
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=64, timeout_ms=30.0)
    )
    try:
        t0 = time.monotonic()
        out = queue.predict(np.ones((2, 3)))
        elapsed = time.monotonic() - t0
        np.testing.assert_array_equal(out, 2 * np.ones((2, 3)))
        # Flushed by the window, not by filling 64.
        assert elapsed < 5.0
        assert model.calls == [2]
    finally:
        queue.close()


def test_multi_instance_requests_batch_and_split():
    model = CountingServable()
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=8, timeout_ms=50.0)
    )
    try:
        inputs = [np.full((n, 2), float(n)) for n in (3, 2, 3)]
        results, errors = _concurrent(queue, inputs)
        assert errors == [None] * 3
        for n, out in zip((3, 2, 3), results):
            assert out.shape == (n, 2)
            np.testing.assert_array_equal(out, np.full((n, 2), 2.0 * n))
        assert sum(model.calls) == 8
    finally:
        queue.close()


def test_error_contained_to_its_flush():
    model = CountingServable(fail_batches={0})
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=4, timeout_ms=20.0)
    )
    try:
        _, errors = _concurrent(
            queue, [np.ones((1, 2)) for _ in range(4)]
        )
        assert all(isinstance(e, RuntimeError) for e in errors)
        # The queue survives: the NEXT flush succeeds.
        out = queue.predict(np.ones((1, 2)))
        np.testing.assert_array_equal(out, 2 * np.ones((1, 2)))
    finally:
        queue.close()


def test_backpressure_rejects_when_full():
    gate = threading.Event()

    class SlowServable(CountingServable):
        def predict(self, instances):
            gate.wait(10)
            return super().predict(instances)

    model = SlowServable()
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=2, timeout_ms=1.0, max_pending=4)
    )
    try:
        # Fill the in-flight flush (2) + the pending queue (4), then one
        # more must bounce.
        threads = []
        for _ in range(6):
            t = threading.Thread(
                target=lambda: queue.predict(np.ones((1, 1)))
            )
            t.start()
            threads.append(t)
        deadline = time.monotonic() + 5
        while queue._pending_count < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        with pytest.raises(QueueFull):
            queue.predict(np.ones((1, 1)))
        gate.set()
        for t in threads:
            t.join(timeout=10)
    finally:
        gate.set()
        queue.close()


def test_oversized_request_passes_through():
    model = CountingServable()
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=4, timeout_ms=5.0, max_pending=64)
    )
    try:
        out = queue.predict(np.ones((11, 2)))
        assert out.shape == (11, 2)
    finally:
        queue.close()


def test_server_routes_predict_through_batcher():
    """HTTP tier: concurrent posts to :predict share executions, and the
    batcher's metrics are exposed on /metrics."""
    model = CountingServable()
    repo = ModelRepository([model])
    app = ModelServerApp(
        repo, batching=BatchingConfig(max_batch=8, timeout_ms=50.0)
    )
    client = TestClient(app)
    try:
        outs = [None] * 8

        def post(i):
            outs[i] = client.post(
                "/v1/models/ident:predict",
                {"instances": [[float(i), 0.0]]},
            )

        threads = [
            threading.Thread(target=post, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        for i, resp in enumerate(outs):
            assert resp.status == 200, resp.body
            assert resp.json()["predictions"] == [[2.0 * i, 0.0]]
        assert len(model.calls) <= 2, model.calls
        metrics = client.get("/metrics").body.decode()
        assert "serving_batches_total" in metrics
    finally:
        app.close_batchers()


def test_server_without_batching_is_direct():
    model = CountingServable()
    app = ModelServerApp(ModelRepository([model]))
    client = TestClient(app)
    assert client.post(
        "/v1/models/ident:predict", {"instances": [[1.0]]}
    ).status == 200
    assert model.calls == [1]


def test_mixed_signatures_grouped_not_failed():
    """A flush holding incompatible shapes runs one execution per
    signature group — a client's odd shape never fails its neighbors
    (TF-Serving batches per signature the same way)."""
    model = CountingServable()
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=8, timeout_ms=50.0)
    )
    try:
        inputs = [
            np.ones((1, 2)), np.ones((1, 3)), np.ones((1, 2)) * 5,
        ]
        results, errors = _concurrent(queue, inputs)
        assert errors == [None] * 3, errors
        assert results[0].shape == (1, 2)
        assert results[1].shape == (1, 3)
        np.testing.assert_array_equal(results[2], np.full((1, 2), 10.0))
        # Two signature groups → at most 2 executions (maybe split by
        # timing, but never a crash or cross-failure).
        assert sum(model.calls) == 3
    finally:
        queue.close()


def test_oversized_request_admitted_when_idle():
    """Backpressure gates on what's already queued: a request larger
    than max_pending on an idle server is admitted and chunked, not
    bounced into a futile retry loop."""
    model = CountingServable()
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=4, timeout_ms=5.0, max_pending=8)
    )
    try:
        out = queue.predict(np.ones((20, 2)))
        assert out.shape == (20, 2)
    finally:
        queue.close()


def test_closed_queue_raises_queue_closed():
    from kubeflow_tpu.serving.batching import QueueClosed

    model = CountingServable()
    queue = BatchingQueue(model, BatchingConfig(timeout_ms=1.0))
    queue.close()
    with pytest.raises(QueueClosed):
        queue.predict(np.ones((1, 1)))


def test_reload_swaps_queue_to_current_generation():
    """The repository is the authority: after a same-version reload the
    batcher serves the NEW servable, and the old generation's queue is
    replaced exactly once (no ping-pong)."""
    gen1, gen2 = CountingServable(), CountingServable()
    repo = ModelRepository([gen1])
    app = ModelServerApp(
        repo, batching=BatchingConfig(max_batch=4, timeout_ms=5.0)
    )
    client = TestClient(app)
    try:
        assert client.post(
            "/v1/models/ident:predict", {"instances": [[1.0]]}
        ).status == 200
        assert sum(gen1.calls) == 1

        repo.load(gen2)  # same name/version: a rollout reload
        assert client.post(
            "/v1/models/ident:predict", {"instances": [[1.0]]}
        ).status == 200
        assert sum(gen2.calls) == 1  # served by the new generation
        assert sum(gen1.calls) == 1  # old one never touched again
        assert app._batchers[("ident", 1)].servable is gen2
    finally:
        app.close_batchers()


def test_unload_prunes_stale_queue():
    """An unloaded version's queue must not pin its weights + scheduler
    thread forever — the next predict prunes it."""
    a = CountingServable()

    class B(CountingServable):
        name = "other"

    b = B()
    repo = ModelRepository([a, b])
    app = ModelServerApp(
        repo, batching=BatchingConfig(max_batch=4, timeout_ms=5.0)
    )
    client = TestClient(app)
    try:
        client.post("/v1/models/ident:predict", {"instances": [[1.0]]})
        client.post("/v1/models/other:predict", {"instances": [[1.0]]})
        assert ("ident", 1) in app._batchers
        repo.unload("ident", 1)
        client.post("/v1/models/other:predict", {"instances": [[1.0]]})
        assert ("ident", 1) not in app._batchers
    finally:
        app.close_batchers()
