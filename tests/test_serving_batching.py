"""Dynamic batching scheduler — the TF-Serving batcher analog.

The reference's serving story leans on TF-Serving, whose batching
scheduler merges concurrent requests into one accelerator execution
(`docs_dev/tf_serving.md` deploys it; batch-1 inference leaves the MXU
nearly idle). These tests pin the scheduler semantics on
`serving.BatchingQueue`: concurrent callers share one execution, each
gets exactly its rows, the timeout bounds latency, errors stay inside
their flush, and backpressure rejects instead of queueing unboundedly.
"""

import threading
import time

import numpy as np
import pytest

from kubeflow_tpu.serving import (
    BatchingConfig,
    BatchingQueue,
    ModelRepository,
    ModelServerApp,
)
from kubeflow_tpu.serving.batching import QueueFull
from kubeflow_tpu.web import TestClient


class CountingServable:
    """Identity 'model' that records every underlying execution."""

    name = "ident"
    version = 1

    def __init__(self, fail_batches=()):
        self.calls: list[int] = []
        self.fail_batches = set(fail_batches)
        self._lock = threading.Lock()

    def predict(self, instances):
        batch = np.asarray(instances)
        with self._lock:
            self.calls.append(batch.shape[0])
            if len(self.calls) - 1 in self.fail_batches:
                raise RuntimeError("injected device fault")
        return batch * 2.0


def _concurrent(queue, inputs):
    """Submit each input from its own thread; return results in order."""
    results = [None] * len(inputs)
    errors = [None] * len(inputs)

    def call(i):
        try:
            results[i] = queue.predict(inputs[i])
        except BaseException as e:
            errors[i] = e

    threads = [
        threading.Thread(target=call, args=(i,)) for i in range(len(inputs))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    return results, errors


def test_concurrent_singles_share_one_execution():
    model = CountingServable()
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=8, timeout_ms=50.0)
    )
    try:
        inputs = [np.full((1, 4), float(i)) for i in range(8)]
        results, errors = _concurrent(queue, inputs)
        assert errors == [None] * 8
        # Everyone got exactly their own rows back.
        for i, out in enumerate(results):
            np.testing.assert_array_equal(out, np.full((1, 4), 2.0 * i))
        # ...via far fewer device executions than callers (a full batch
        # flushes as one; stragglers may ride a second flush).
        assert len(model.calls) <= 2, model.calls
        assert sum(model.calls) == 8
    finally:
        queue.close()


def test_timeout_flushes_partial_batch():
    model = CountingServable()
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=64, timeout_ms=30.0)
    )
    try:
        t0 = time.monotonic()
        out = queue.predict(np.ones((2, 3)))
        elapsed = time.monotonic() - t0
        np.testing.assert_array_equal(out, 2 * np.ones((2, 3)))
        # Flushed by the window, not by filling 64.
        assert elapsed < 5.0
        assert model.calls == [2]
    finally:
        queue.close()


def test_multi_instance_requests_batch_and_split():
    model = CountingServable()
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=8, timeout_ms=50.0)
    )
    try:
        inputs = [np.full((n, 2), float(n)) for n in (3, 2, 3)]
        results, errors = _concurrent(queue, inputs)
        assert errors == [None] * 3
        for n, out in zip((3, 2, 3), results):
            assert out.shape == (n, 2)
            np.testing.assert_array_equal(out, np.full((n, 2), 2.0 * n))
        assert sum(model.calls) == 8
    finally:
        queue.close()


def test_error_contained_to_its_flush():
    model = CountingServable(fail_batches={0})
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=4, timeout_ms=20.0)
    )
    try:
        _, errors = _concurrent(
            queue, [np.ones((1, 2)) for _ in range(4)]
        )
        assert all(isinstance(e, RuntimeError) for e in errors)
        # The queue survives: the NEXT flush succeeds.
        out = queue.predict(np.ones((1, 2)))
        np.testing.assert_array_equal(out, 2 * np.ones((1, 2)))
    finally:
        queue.close()


def test_backpressure_rejects_when_full():
    gate = threading.Event()

    class SlowServable(CountingServable):
        def predict(self, instances):
            gate.wait(10)
            return super().predict(instances)

    model = SlowServable()
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=2, timeout_ms=1.0, max_pending=4)
    )
    try:
        # Fill the in-flight flush (2) + the pending queue (4), then one
        # more must bounce.
        threads = []
        for _ in range(6):
            t = threading.Thread(
                target=lambda: queue.predict(np.ones((1, 1)))
            )
            t.start()
            threads.append(t)
        deadline = time.monotonic() + 5
        while queue._pending_count < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        with pytest.raises(QueueFull):
            queue.predict(np.ones((1, 1)))
        gate.set()
        for t in threads:
            t.join(timeout=10)
    finally:
        gate.set()
        queue.close()


def test_oversized_request_passes_through():
    model = CountingServable()
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=4, timeout_ms=5.0, max_pending=64)
    )
    try:
        out = queue.predict(np.ones((11, 2)))
        assert out.shape == (11, 2)
    finally:
        queue.close()


def test_server_routes_predict_through_batcher():
    """HTTP tier: concurrent posts to :predict share executions, and the
    batcher's metrics are exposed on /metrics."""
    model = CountingServable()
    repo = ModelRepository([model])
    app = ModelServerApp(
        repo, batching=BatchingConfig(max_batch=8, timeout_ms=50.0)
    )
    client = TestClient(app)
    try:
        outs = [None] * 8

        def post(i):
            outs[i] = client.post(
                "/v1/models/ident:predict",
                {"instances": [[float(i), 0.0]]},
            )

        threads = [
            threading.Thread(target=post, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        for i, resp in enumerate(outs):
            assert resp.status == 200, resp.body
            assert resp.json()["predictions"] == [[2.0 * i, 0.0]]
        assert len(model.calls) <= 2, model.calls
        metrics = client.get("/metrics").body.decode()
        assert "serving_batches_total" in metrics
    finally:
        app.close_batchers()


def test_server_without_batching_is_direct():
    model = CountingServable()
    app = ModelServerApp(ModelRepository([model]))
    client = TestClient(app)
    assert client.post(
        "/v1/models/ident:predict", {"instances": [[1.0]]}
    ).status == 200
    assert model.calls == [1]


def test_mixed_signatures_grouped_not_failed():
    """A flush holding incompatible shapes runs one execution per
    signature group — a client's odd shape never fails its neighbors
    (TF-Serving batches per signature the same way)."""
    model = CountingServable()
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=8, timeout_ms=50.0)
    )
    try:
        inputs = [
            np.ones((1, 2)), np.ones((1, 3)), np.ones((1, 2)) * 5,
        ]
        results, errors = _concurrent(queue, inputs)
        assert errors == [None] * 3, errors
        assert results[0].shape == (1, 2)
        assert results[1].shape == (1, 3)
        np.testing.assert_array_equal(results[2], np.full((1, 2), 10.0))
        # Two signature groups → at most 2 executions (maybe split by
        # timing, but never a crash or cross-failure).
        assert sum(model.calls) == 3
    finally:
        queue.close()


def test_oversized_request_admitted_when_idle():
    """Backpressure gates on what's already queued: a request larger
    than max_pending on an idle server is admitted and chunked, not
    bounced into a futile retry loop."""
    model = CountingServable()
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=4, timeout_ms=5.0, max_pending=8)
    )
    try:
        out = queue.predict(np.ones((20, 2)))
        assert out.shape == (20, 2)
    finally:
        queue.close()


def test_closed_queue_raises_queue_closed():
    from kubeflow_tpu.serving.batching import QueueClosed

    model = CountingServable()
    queue = BatchingQueue(model, BatchingConfig(timeout_ms=1.0))
    queue.close()
    with pytest.raises(QueueClosed):
        queue.predict(np.ones((1, 1)))


def test_reload_swaps_queue_to_current_generation():
    """The repository is the authority: after a same-version reload the
    batcher serves the NEW servable, and the old generation's queue is
    replaced exactly once (no ping-pong)."""
    gen1, gen2 = CountingServable(), CountingServable()
    repo = ModelRepository([gen1])
    app = ModelServerApp(
        repo, batching=BatchingConfig(max_batch=4, timeout_ms=5.0)
    )
    client = TestClient(app)
    try:
        assert client.post(
            "/v1/models/ident:predict", {"instances": [[1.0]]}
        ).status == 200
        assert sum(gen1.calls) == 1

        repo.load(gen2)  # same name/version: a rollout reload
        assert client.post(
            "/v1/models/ident:predict", {"instances": [[1.0]]}
        ).status == 200
        assert sum(gen2.calls) == 1  # served by the new generation
        assert sum(gen1.calls) == 1  # old one never touched again
        assert app._batchers[("ident", 1)].servable is gen2
    finally:
        app.close_batchers()


class GatedServable(CountingServable):
    """Blocks executions of a chosen signature until released — the
    choreography hook for deterministic continuous-batching tests."""

    def __init__(self, gate_width):
        super().__init__()
        self.gate = threading.Event()
        self.gate_width = gate_width
        self.shapes: list[tuple] = []

    def predict(self, instances):
        batch = np.asarray(instances)
        with self._lock:
            self.shapes.append(batch.shape)
        if batch.shape[1] == self.gate_width:
            self.gate.wait(10)
        return batch * 2.0


def _drive_continuous(continuous: bool):
    """Two-signature choreography: a gated width-2 group executes while
    a width-3 request arrives AFTER the cut — under continuous batching
    the width-3 group about to run admits it late (one (2, 3) call);
    under cut-and-wait it waits for its own flush (two (1, 3) calls)."""
    model = GatedServable(gate_width=2)
    queue = BatchingQueue(
        model,
        BatchingConfig(
            max_batch=2, timeout_ms=2000.0, continuous=continuous
        ),
    )
    try:
        results, errors = [None] * 3, [None] * 3

        def call(i, x):
            try:
                results[i] = queue.predict(x)
            except BaseException as e:  # pragma: no cover - diagnostics
                errors[i] = e

        t_x = threading.Thread(target=call, args=(0, np.ones((1, 2))))
        t_x.start()
        deadline = time.monotonic() + 5
        while queue._pending_count < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        t_y1 = threading.Thread(target=call, args=(1, np.ones((1, 3))))
        t_y1.start()  # rows hit max_batch → cut {x, y1}
        while (
            not any(s[1] == 2 for s in model.shapes)
            and time.monotonic() < deadline
        ):
            time.sleep(0.005)
        # The flush is executing (width-2 gated); y2 arrives post-cut.
        t_y2 = threading.Thread(target=call, args=(2, np.ones((1, 3))))
        t_y2.start()
        while queue._pending_count < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        model.gate.set()
        for t in (t_x, t_y1, t_y2):
            t.join(timeout=10)
        assert errors == [None] * 3, errors
        for r in results:
            assert r is not None
        return model.shapes
    finally:
        model.gate.set()
        queue.close()


def test_continuous_batching_admits_late_arrival():
    shapes = _drive_continuous(continuous=True)
    # y1 + late-admitted y2 merged into one width-3 execution.
    assert (2, 3) in shapes, shapes


def test_cut_and_wait_mode_never_admits_late():
    shapes = _drive_continuous(continuous=False)
    assert (2, 3) not in shapes, shapes
    assert shapes.count((1, 3)) == 2, shapes


def test_queue_gauges_scrape_through_registry():
    from kubeflow_tpu.utils.metrics import MetricsRegistry

    metrics = MetricsRegistry()
    model = CountingServable()
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=4, timeout_ms=5.0), metrics
    )
    try:
        queue.predict(np.ones((1, 2)))
        text = metrics.expose_text()
        assert "serving_queue_depth" in text
        assert "serving_inflight_batches" in text
        assert "serving_batch_late_admitted_total" in text
        stats = queue.stats()
        assert stats["queue_depth"] == 0 and stats["inflight"] == 0
        assert stats["queue_wait_ms"] >= 0.0
    finally:
        queue.close()


def test_kill_fails_inflight_and_queued_callers():
    """`kill()` is the SIGKILL analog: in-flight and queued callers all
    fail immediately with QueueClosed (→ ReplicaGone at the router), no
    caller is left waiting on an event that never fires."""
    from kubeflow_tpu.serving.batching import QueueClosed

    model = GatedServable(gate_width=2)
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=1, timeout_ms=1000.0)
    )
    try:
        _, errors = [None] * 3, [None] * 3
        done = [None] * 3

        def call(i):
            try:
                done[i] = queue.predict(np.ones((1, 2)))
            except BaseException as e:
                errors[i] = e

        threads = [
            threading.Thread(target=call, args=(i,)) for i in range(3)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5
        while not model.shapes and time.monotonic() < deadline:
            time.sleep(0.005)

        queue.kill()
        for t in threads:
            t.join(timeout=10)
        assert all(isinstance(e, QueueClosed) for e in errors), errors
        with pytest.raises(QueueClosed):
            queue.predict(np.ones((1, 2)))
    finally:
        model.gate.set()
        queue.close()


def test_queue_full_maps_to_429_with_retry_after():
    """Boundary regression (ISSUE 11 satellite): backpressure surfaces
    as an honest HTTP 429 carrying Retry-After, not a 500."""
    gate = threading.Event()
    executing = threading.Event()

    class SlowServable(CountingServable):
        def predict(self, instances):
            executing.set()
            gate.wait(10)
            return super().predict(instances)

    model = SlowServable()
    app = ModelServerApp(
        ModelRepository([model]),
        batching=BatchingConfig(
            max_batch=1, timeout_ms=3000.0, max_pending=1
        ),
    )
    client = TestClient(app)
    try:
        def fill():
            client.post(
                "/v1/models/ident:predict", {"instances": [[1.0]]}
            )

        # Sequenced fill so the slot accounting is deterministic: the
        # first request must be CUT into execution (pending back to 0)
        # before the second is posted, or the second eats the QueueFull
        # the probe below is asserting on.
        threads = [threading.Thread(target=fill) for _ in range(2)]
        threads[0].start()
        assert executing.wait(10)
        threads[1].start()
        queue = None
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            queue = next(iter(app._batchers.values()), None)
            if queue is not None and queue._pending_count >= 1:
                break
            time.sleep(0.01)
        assert queue is not None and queue._pending_count >= 1

        resp = client.post(
            "/v1/models/ident:predict", {"instances": [[1.0]]}
        )
        assert resp.status == 429, resp.body
        headers = dict(resp.headers)
        # One flush window (3s here) spread ±50% by the seeded jitter —
        # fractional seconds on purpose (docs/serving.md §admission).
        assert 1.5 <= float(headers["Retry-After"]) <= 4.5
        assert "full" in resp.json()["log"]
        gate.set()
        for t in threads:
            t.join(timeout=10)
    finally:
        gate.set()
        app.close_batchers()


def test_admit_late_keeps_mismatched_pending_in_order():
    """`_admit_late` pulls ONLY signature-compatible entries; everything
    else must stay pending IN ARRIVAL ORDER, or the next cut would stop
    honoring the oldest caller's timeout deadline."""
    model = CountingServable()
    # Huge window so submitted entries sit pending while the test drives
    # the admission scan directly.
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=8, timeout_ms=10_000.0)
    )
    try:
        inputs = [
            np.full((1, 4), 1.0),  # mismatch, arrived first
            np.full((1, 3), 2.0),  # the only width-3 entry
            np.full((1, 4), 3.0),  # mismatch, arrived last
        ]
        results = [None] * 3
        threads = []
        for i, x in enumerate(inputs):
            t = threading.Thread(
                target=lambda i=i, x=x: results.__setitem__(
                    i, queue.predict(x)
                )
            )
            t.start()
            threads.append(t)
            deadline = time.monotonic() + 5
            while (
                queue._pending_count < i + 1
                and time.monotonic() < deadline
            ):
                time.sleep(0.005)

        taken = queue._admit_late(("ident", 1, (3,), "<f8"), 0)
        assert [e.instances.shape for e in taken] == [(1, 3)]
        with queue._cv:
            kept = [float(e.instances[0, 0]) for e in queue._pending]
            assert kept == [1.0, 3.0]  # arrival order survived the scan
            assert queue._pending_count == 2
            assert taken[0] in queue._inflight  # kill() coverage moved too
        # Complete the admitted caller the way _run_group would, then let
        # close() drain the two kept entries through a normal flush.
        taken[0].result = taken[0].instances * 2.0
        taken[0].event.set()
        queue.close()
        for t in threads:
            t.join(timeout=10)
        for x, out in zip(inputs, results):
            np.testing.assert_array_equal(out, x * 2.0)
    finally:
        queue.close()


def test_admit_late_updates_queue_wait_ewma():
    """Late-admitted entries must feed the queue-wait EWMA the same way
    cut entries do — the autoscaler reads stats()['queue_wait_ms'], and
    a continuous-batching replica whose admissions all ride the late
    path would otherwise report zero wait forever."""
    model = CountingServable()
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=8, timeout_ms=10_000.0)
    )
    try:
        holder = [None]
        t = threading.Thread(
            target=lambda: holder.__setitem__(
                0, queue.predict(np.ones((1, 3)))
            )
        )
        t.start()
        deadline = time.monotonic() + 5
        while queue._pending_count < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert queue.stats()["queue_wait_ms"] == 0.0
        time.sleep(0.03)  # accrue measurable queue wait
        taken = queue._admit_late(("ident", 1, (3,), "<f8"), 0)
        assert len(taken) == 1
        assert queue.stats()["queue_wait_ms"] > 0.0
        taken[0].result = taken[0].instances * 2.0
        taken[0].event.set()
        t.join(timeout=10)
        np.testing.assert_array_equal(holder[0], np.ones((1, 3)) * 2.0)
    finally:
        queue.close()


def test_kill_racing_late_admission_strands_no_caller():
    """A late-admitted entry is in-flight from the moment it leaves
    pending; a kill() landing while its flush executes must fail it like
    any other in-flight caller — never leave it parked on an event
    nobody will set."""
    from kubeflow_tpu.serving.batching import QueueClosed

    class TwoGateServable(CountingServable):
        """Gates BOTH signatures so the test controls exactly when the
        late-admitting width-3 group starts and blocks."""

        def __init__(self):
            super().__init__()
            self.gates = {2: threading.Event(), 3: threading.Event()}
            self.shapes: list[tuple] = []

        def predict(self, instances):
            batch = np.asarray(instances)
            with self._lock:
                self.shapes.append(batch.shape)
            gate = self.gates.get(batch.shape[1])
            if gate is not None:
                gate.wait(10)
            return batch * 2.0

    model = TwoGateServable()
    queue = BatchingQueue(
        model, BatchingConfig(max_batch=2, timeout_ms=2000.0)
    )
    results, errors = [None] * 3, [None] * 3

    def call(i, x):
        try:
            results[i] = queue.predict(x)
        except BaseException as e:
            errors[i] = e

    try:
        deadline = time.monotonic() + 5
        t_x = threading.Thread(target=call, args=(0, np.ones((1, 2))))
        t_x.start()
        while queue._pending_count < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        t_y1 = threading.Thread(target=call, args=(1, np.ones((1, 3))))
        t_y1.start()  # rows hit max_batch -> cut {x, y1}
        while (
            not any(s[1] == 2 for s in model.shapes)
            and time.monotonic() < deadline
        ):
            time.sleep(0.005)
        # Width-2 group is executing (gated); y2 arrives post-cut and
        # will be admitted late by the width-3 group.
        t_y2 = threading.Thread(target=call, args=(2, np.ones((1, 3))))
        t_y2.start()
        while queue._pending_count < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        model.gates[2].set()  # width-3 group now admits y2 and executes
        while (
            (2, 3) not in model.shapes and time.monotonic() < deadline
        ):
            time.sleep(0.005)
        assert (2, 3) in model.shapes, model.shapes

        queue.kill()  # lands while the late-admitted flush is gated
        model.gates[3].set()
        for t in (t_x, t_y1, t_y2):
            t.join(timeout=10)
            assert not t.is_alive()  # the stranding regression
        np.testing.assert_array_equal(results[0], np.ones((1, 2)) * 2.0)
        assert isinstance(errors[1], QueueClosed), errors
        assert isinstance(errors[2], QueueClosed), errors
    finally:
        for gate in model.gates.values():
            gate.set()
        queue.close()


def test_unload_prunes_stale_queue():
    """An unloaded version's queue must not pin its weights + scheduler
    thread forever — the next predict prunes it."""
    a = CountingServable()

    class B(CountingServable):
        name = "other"

    b = B()
    repo = ModelRepository([a, b])
    app = ModelServerApp(
        repo, batching=BatchingConfig(max_batch=4, timeout_ms=5.0)
    )
    client = TestClient(app)
    try:
        client.post("/v1/models/ident:predict", {"instances": [[1.0]]})
        client.post("/v1/models/other:predict", {"instances": [[1.0]]})
        assert ("ident", 1) in app._batchers
        repo.unload("ident", 1)
        client.post("/v1/models/other:predict", {"instances": [[1.0]]})
        assert ("ident", 1) not in app._batchers
    finally:
        app.close_batchers()


# -- per-model isolation through the registry (ISSUE 17) ---------------------


def test_slow_model_does_not_delay_idle_models_flush():
    """Multiplexing isolation: one model wedged mid-execution (and with
    work queued behind it) must not add a microsecond of queueing to a
    sibling model's flush — per-model queues, per-model workers."""
    from kubeflow_tpu.serving import ServableRegistry

    wedge = threading.Event()

    class SlowServable(CountingServable):
        name = "slow"

        def predict(self, instances):
            wedge.wait(10)
            return super().predict(instances)

    fast = CountingServable()
    fast.name = "fast"

    def factory(rspec):
        return SlowServable() if rspec["model"] == "slow" else fast

    registry = ServableRegistry(
        factory,
        batching=BatchingConfig(max_batch=4, timeout_ms=5.0),
    )
    registry.ensure({"model": "slow"})
    registry.ensure({"model": "fast"})
    x = np.ones((1, 2))
    threads = [
        threading.Thread(
            target=lambda: registry.predict("slow", x), daemon=True
        )
        for _ in range(3)
    ]
    try:
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5
        while (
            registry.stats()["models"]["slow"].get("inflight", 0) == 0
            and time.monotonic() < deadline
        ):
            time.sleep(0.005)

        t0 = time.monotonic()
        out = registry.predict("fast", x)
        elapsed = time.monotonic() - t0
        np.testing.assert_array_equal(out, x * 2.0)
        assert not wedge.is_set()  # slow was STILL wedged throughout
        # Generous bound: page-in + one flush window, nowhere near the
        # 10s the slow model's gate would impose if queues were shared.
        assert elapsed < 2.0, f"idle model's flush took {elapsed:.2f}s"
    finally:
        wedge.set()
        for t in threads:
            t.join(timeout=10)
        registry.close()


def test_kill_during_page_in_fails_only_that_model():
    """kill(model) while its page-in is in flight: the claiming caller
    and every caller parked on the load fail with QueueClosed; sibling
    models keep serving; the killed model pages back in on the next
    request (generation fencing, no resurrect of the dead load)."""
    from kubeflow_tpu.serving import ServableRegistry
    from kubeflow_tpu.serving.batching import QueueClosed

    in_factory = threading.Event()
    release = threading.Event()

    def factory(rspec):
        if rspec["model"] == "wedged":
            in_factory.set()
            release.wait(10)

            class Wedged(CountingServable):
                name = "wedged"

            return Wedged()
        ok = CountingServable()
        ok.name = "ok"
        return ok

    registry = ServableRegistry(
        factory,
        batching=BatchingConfig(max_batch=4, timeout_ms=5.0),
    )
    registry.ensure({"model": "wedged"})
    registry.ensure({"model": "ok"})
    x = np.ones((1, 2))
    registry.predict("ok", x)  # sibling resident before the fun starts

    errors = [None, None]

    def call(i):
        try:
            registry.predict("wedged", x)
        except BaseException as e:
            errors[i] = e

    claimer = threading.Thread(target=call, args=(0,))
    parked = threading.Thread(target=call, args=(1,))
    try:
        claimer.start()
        assert in_factory.wait(5)  # page-in is now in flight
        parked.start()
        time.sleep(0.05)  # let the second caller park on ready

        registry.kill("wedged")

        # Parked caller dies immediately — it is not waiting on the
        # factory, only on the entry's ready event.
        parked.join(timeout=5)
        assert not parked.is_alive()
        assert isinstance(errors[1], QueueClosed), errors
        assert "page-in" in str(errors[1])

        # The sibling never noticed.
        np.testing.assert_array_equal(registry.predict("ok", x), x * 2.0)

        # The claimer unwinds once the wedged factory returns into a
        # bumped generation — its load is discarded, not installed.
        release.set()
        claimer.join(timeout=5)
        assert not claimer.is_alive()
        assert isinstance(errors[0], QueueClosed), errors

        # And the model is not poisoned: next request pages it back in.
        np.testing.assert_array_equal(
            registry.predict("wedged", x), x * 2.0
        )
    finally:
        release.set()
        registry.close()
