"""CI smoke for `bench.py --workload serving --serving-dataplane-only`
(ISSUE 11): the multi-replica data-plane bench must run end-to-end at
tiny scale — steady latency, overload goodput, the drain-based roll,
the binary-wire phase (ISSUE 15), and the replica-kill chaos gate — and
every headline row must resolve a real vs_baseline ratio against
BASELINE.json's published serving_* entries."""

import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_serving_dataplane_bench_smoke_rows_resolve_baseline():
    result = subprocess.run(
        [
            sys.executable, "bench.py", "--workload", "serving",
            "--serving-dataplane-only",
            "--serving-clients", "32",
            "--serving-requests", "64",
            "--serving-replicas", "2",
            "--serving-chaos", "local",
            "--chaos-seed", "3",
        ],
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True,
        text=True,
        timeout=280,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    metrics = [
        json.loads(line)
        for line in result.stdout.splitlines()
        if line.startswith("{")
    ]
    assert metrics, f"no metric lines in:\n{result.stdout}"
    by_name = {}
    for m in metrics:
        # The driver's parse contract — same shape as every other bench.
        assert set(m) == {"metric", "value", "unit", "vs_baseline"}, m
        assert isinstance(m["value"], (int, float)) and m["value"] > 0, m
        by_name[m["metric"]] = m

    # Every headline row resolves a ratio vs the published baseline.
    for name in (
        "serving_p50_latency_ms",
        "serving_p99_latency_ms",
        "serving_goodput_under_overload",
        "serving_checkpoint_roll_seconds",
    ):
        assert name in by_name, (name, sorted(by_name))
        assert by_name[name]["vs_baseline"] is not None, by_name[name]

    # The wire row (ISSUE 15) resolves against the published JSON-path
    # bytes, so vs_baseline IS the binary/JSON ratio — and the bench
    # itself hard-fails above the 0.35x gate, so a resolving row means
    # the gate was actually evaluated.
    wire = by_name["serving_wire_bytes_per_request"]
    assert wire["vs_baseline"] is not None, wire
    assert wire["vs_baseline"] <= 0.35, wire
    assert "# serving wire:" in result.stderr

    # The chaos gate ran (nonzero exit would have tripped above) and
    # published its acked-request count; it is a gate, not a ratio.
    chaos = by_name["serving_chaos_acked_requests"]
    assert chaos["value"] == 64
    assert "failed=0" in chaos["unit"]
    assert "coverage={'replica_kill': 1}" in result.stderr

    # ISSUE 17 front-door rows: multiplex p99 + measured page-in resolve
    # against published baselines; the open-loop fidelity row is a hard
    # gate (the bench exits nonzero above 5% offered-rate error, so a
    # row at all means the harness held its schedule).
    for name in (
        "serving_multiplex_p99_ms",
        "serving_page_in_seconds",
        "serving_priority_p99_at_2x_ms",
    ):
        assert name in by_name, (name, sorted(by_name))
        assert by_name[name]["vs_baseline"] is not None, by_name[name]
    fidelity = by_name["serving_offered_rate_error"]
    assert fidelity["value"] <= 0.05, fidelity
    assert "# serving multiplex:" in result.stderr
    assert "# serving priority:" in result.stderr
