"""ServingDeployment reconciliation (`controllers/serving.py`) and the
replica worker loop (`serving/__main__.py`).

The CR declares the fleet; the controller materializes one owned
ServingReplica object per index (the config-push channel — replica
workers watch their own object, PR 2 machinery), aggregates per-replica
readiness into status, converges replica count to the autoscale target,
and runs a drain-based one-at-a-time roll on a modelVersion bump. All
tests drive `run_until_idle()` against a scripted runtime so convergence
is deterministic.
"""

import threading
import time

import pytest

from kubeflow_tpu.api import serving as serving_api
from kubeflow_tpu.controllers.serving import ServingDeploymentController
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.testing.fake_apiserver import NotFound


class FakeRuntime:
    """Scripted materialization backend: every replica is a dict."""

    def __init__(self):
        self.replicas: dict[str, dict] = {}
        self.rolls: list[str] = []
        self.stopped: list[str] = []

    def names(self):
        return list(self.replicas)

    def ensure(self, name, rspec):
        self.replicas.setdefault(
            name,
            {
                "ready": True,
                "version": int(rspec.get("modelVersion") or 1),
                "queue_depth": 0,
                "inflight": 0,
                "queue_wait_ms": 0.0,
            },
        )

    def stop(self, name):
        self.replicas.pop(name, None)
        self.stopped.append(name)

    def roll(self, name, rspec):
        self.replicas[name]["version"] = int(rspec["modelVersion"])
        self.rolls.append(name)
        return 0.01

    def stats(self, name):
        return self.replicas.get(name)


@pytest.fixture()
def harness():
    api = FakeApiServer()
    runtime = FakeRuntime()
    controller = ServingDeploymentController(api, runtime=runtime)
    return api, runtime, controller


def converge(controller):
    controller.controller.run_until_idle()


def dep_status(api, name="fleet"):
    return api.get(serving_api.KIND, name, "default").status


def test_create_materializes_replicas_and_status(harness):
    api, runtime, controller = harness
    api.create(
        serving_api.make_serving_deployment("fleet", replicas=3)
    )
    converge(controller)

    names = [serving_api.replica_name("fleet", i) for i in range(3)]
    assert sorted(runtime.replicas) == names
    for rname in names:
        robj = api.get(serving_api.REPLICA_KIND, rname, "default")
        assert (
            robj.metadata.labels[serving_api.LABEL_DEPLOYMENT] == "fleet"
        )
        assert robj.metadata.owner_references[0]["name"] == "fleet"
        assert robj.spec["batching"]["continuous"] is True
        assert robj.status["ready"] is True  # stamped back for kubectl
    status = dep_status(api)
    assert status["phase"] == "Available"
    assert status["readyReplicas"] == 3
    assert [r["name"] for r in status["replicas"]] == names


def test_scale_down_stops_and_deletes(harness):
    api, runtime, controller = harness
    api.create(
        serving_api.make_serving_deployment("fleet", replicas=3)
    )
    converge(controller)

    dep = api.get(serving_api.KIND, "fleet", "default").thaw()
    spec = dict(dep.spec)
    spec["replicas"] = 1
    dep.spec = spec
    api.update(dep)
    converge(controller)

    assert sorted(runtime.replicas) == [
        serving_api.replica_name("fleet", 0)
    ]
    assert len(runtime.stopped) == 2
    with pytest.raises(NotFound):
        api.get(
            serving_api.REPLICA_KIND,
            serving_api.replica_name("fleet", 2),
            "default",
        )
    assert dep_status(api)["readyReplicas"] == 1


def test_autoscale_tracks_queue_depth(harness):
    api, runtime, controller = harness
    api.create(
        serving_api.make_serving_deployment(
            "fleet",
            replicas=1,
            autoscale={
                "min_replicas": 1,
                "max_replicas": 4,
                "target_queue_depth": 10,
            },
        )
    )
    converge(controller)
    assert len(runtime.replicas) == 1

    # Queue pressure: 25 queued+executing over target 10 → 3 replicas.
    r0 = serving_api.replica_name("fleet", 0)
    runtime.replicas[r0]["queue_depth"] = 20
    runtime.replicas[r0]["inflight"] = 5
    controller.controller.enqueue(("default", "fleet"))
    converge(controller)
    assert len(runtime.replicas) == 3
    assert dep_status(api)["targetReplicas"] == 3

    # Pressure gone → back to min (never below it).
    runtime.replicas[r0]["queue_depth"] = 0
    runtime.replicas[r0]["inflight"] = 0
    controller.controller.enqueue(("default", "fleet"))
    converge(controller)
    assert len(runtime.replicas) == 1
    assert dep_status(api)["targetReplicas"] == 1


def test_model_version_bump_rolls_each_replica(harness):
    api, runtime, controller = harness
    api.create(
        serving_api.make_serving_deployment(
            "fleet", replicas=3, model_version=1
        )
    )
    converge(controller)

    dep = api.get(serving_api.KIND, "fleet", "default").thaw()
    spec = dict(dep.spec)
    spec["modelVersion"] = 2
    dep.spec = spec
    api.update(dep)
    converge(controller)

    assert len(runtime.rolls) == 3
    assert all(
        r["version"] == 2 for r in runtime.replicas.values()
    )
    # The config push rode the replica objects too.
    robj = api.get(
        serving_api.REPLICA_KIND,
        serving_api.replica_name("fleet", 0),
        "default",
    )
    assert robj.spec["modelVersion"] == 2


def test_roll_defers_while_a_sibling_is_down(harness):
    api, runtime, controller = harness
    api.create(
        serving_api.make_serving_deployment(
            "fleet", replicas=2, model_version=1
        )
    )
    converge(controller)

    # One replica is already not ready: taking another out for the roll
    # would be an outage, so the roll must wait.
    r1 = serving_api.replica_name("fleet", 1)
    runtime.replicas[r1]["ready"] = False
    dep = api.get(serving_api.KIND, "fleet", "default").thaw()
    spec = dict(dep.spec)
    spec["modelVersion"] = 2
    dep.spec = spec
    api.update(dep)
    converge(controller)
    assert runtime.rolls == []

    runtime.replicas[r1]["ready"] = True
    controller.controller.enqueue(("default", "fleet"))
    converge(controller)
    assert len(runtime.rolls) == 2


def test_invalid_spec_is_terminal_failed(harness):
    api, runtime, controller = harness
    dep = serving_api.make_serving_deployment("fleet", replicas=1)
    spec = dict(dep.spec)
    spec["replicas"] = -2
    dep.spec = spec
    api.create(dep)
    converge(controller)

    status = dep_status(api)
    assert status["phase"] == "Failed"
    assert "replicas" in status["reason"]
    assert runtime.replicas == {}


def test_delete_tears_down_fleet(harness):
    api, runtime, controller = harness
    api.create(
        serving_api.make_serving_deployment("fleet", replicas=2)
    )
    converge(controller)
    assert len(runtime.replicas) == 2

    api.delete(serving_api.KIND, "fleet", "default")
    converge(controller)
    assert runtime.replicas == {}
    assert api.list(serving_api.REPLICA_KIND, "default") == []


def test_config_push_updates_replica_spec(harness):
    api, runtime, controller = harness
    api.create(
        serving_api.make_serving_deployment(
            "fleet", replicas=1, batch_timeout_ms=5.0
        )
    )
    converge(controller)

    dep = api.get(serving_api.KIND, "fleet", "default").thaw()
    spec = dict(dep.spec)
    spec["batching"] = {**spec["batching"], "timeoutMs": 9.0}
    dep.spec = spec
    api.update(dep)
    converge(controller)

    robj = api.get(
        serving_api.REPLICA_KIND,
        serving_api.replica_name("fleet", 0),
        "default",
    )
    assert robj.spec["batching"]["timeoutMs"] == 9.0


# -- the replica worker loop (`python -m kubeflow_tpu.serving`) -------------


class FakeServable:
    def __init__(self, name, version):
        self.name = name
        self.version = version


class FakeRepository:
    def __init__(self):
        self.models: dict[str, FakeServable] = {}
        self.loads = 0

    def get(self, name):
        return self.models[name]

    def load(self, servable):
        self.models[servable.name] = servable
        self.loads += 1


def build_servable(rspec):
    return FakeServable(
        rspec.get("model", "demo"), int(rspec.get("modelVersion") or 1)
    )


def make_replica_object(api, version=1):
    from kubeflow_tpu.api.objects import new_resource

    api.create(
        new_resource(
            serving_api.REPLICA_KIND,
            "r0",
            "default",
            spec={"model": "demo", "modelVersion": version},
        )
    )


def test_sync_replica_once_loads_and_stamps_status():
    from kubeflow_tpu.serving.__main__ import sync_replica_once

    api = FakeApiServer()
    make_replica_object(api, version=3)
    repo = FakeRepository()

    live = sync_replica_once(
        api, "r0", "default", repo,
        build_servable=build_servable,
        endpoint="127.0.0.1:9999",
        queue_stats=lambda: {"queue_depth": 7, "inflight": 2},
    )
    assert live == 3
    assert repo.loads == 1
    status = api.get(serving_api.REPLICA_KIND, "r0", "default").status
    assert status["ready"] is True
    assert status["version"] == 3
    assert status["endpoint"] == "127.0.0.1:9999"
    assert status["queueDepth"] == 7 and status["inflight"] == 2

    # Idempotent: a second sync at the same version does not reload.
    sync_replica_once(
        api, "r0", "default", repo, build_servable=build_servable
    )
    assert repo.loads == 1


def test_sync_replica_once_none_when_object_gone():
    from kubeflow_tpu.serving.__main__ import sync_replica_once

    api = FakeApiServer()
    repo = FakeRepository()
    assert (
        sync_replica_once(
            api, "r0", "default", repo, build_servable=build_servable
        )
        is None
    )


def test_run_replica_hot_swaps_on_config_push_and_exits_on_delete():
    from kubeflow_tpu.serving.__main__ import run_replica

    api = FakeApiServer()
    make_replica_object(api, version=1)
    repo = FakeRepository()
    t = threading.Thread(
        target=run_replica,
        args=(api, "r0", "default", repo),
        kwargs={"build_servable": build_servable, "heartbeat_s": 0.05},
        daemon=True,
    )
    t.start()

    deadline = time.monotonic() + 5
    while repo.loads == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert repo.models["demo"].version == 1

    # The controller bumps modelVersion on the replica object; the
    # worker's watch reacts — the hot-swap config push, no polling.
    robj = api.get(serving_api.REPLICA_KIND, "r0", "default").thaw()
    robj.spec = {**robj.spec, "modelVersion": 2}
    api.update(robj)
    deadline = time.monotonic() + 5
    while (
        repo.models["demo"].version != 2 and time.monotonic() < deadline
    ):
        time.sleep(0.01)
    assert repo.models["demo"].version == 2

    # Deployment deleted → object gone → the worker loop returns.
    api.delete(serving_api.REPLICA_KIND, "r0", "default")
    t.join(timeout=5)
    assert not t.is_alive()


# -- observed-latency autoscale signal ------------------------------------


def test_autoscale_target_latency_and_depth_agreement():
    """Unit contract for the two-signal policy: scale-up wins."""
    spec = serving_api.AutoscaleSpec(
        min_replicas=1, max_replicas=8,
        target_queue_depth=10, target_latency_ms=50.0,
    )
    # Agreement: both signals want 3.
    assert spec.target(25, p99_latency_ms=140.0, current_replicas=1) == 3
    # Conflict, latency higher: shallow queues must not mask a p99
    # breach (slow-drain pathology).
    assert spec.target(5, p99_latency_ms=200.0, current_replicas=2) == 8
    # Conflict, depth higher: fast batches must not mask a backlog.
    assert spec.target(60, p99_latency_ms=10.0, current_replicas=2) == 6
    # Latency signal off (0) or unmeasured (None): depth-only.
    off = serving_api.AutoscaleSpec(
        min_replicas=1, max_replicas=8, target_queue_depth=10,
    )
    assert off.target(5, p99_latency_ms=500.0, current_replicas=2) == 1
    assert spec.target(5, p99_latency_ms=None, current_replicas=2) == 1


def test_autoscale_scales_out_on_observed_latency(harness):
    """Controller path: rolling p99 queue wait above targetLatencyMs
    scales the fleet out even though queues are shallow."""
    api, runtime, controller = harness
    api.create(
        serving_api.make_serving_deployment(
            "fleet",
            replicas=1,
            autoscale={
                "min_replicas": 1,
                "max_replicas": 4,
                "target_queue_depth": 100,
                "target_latency_ms": 50.0,
            },
        )
    )
    converge(controller)
    assert len(runtime.replicas) == 1

    r0 = serving_api.replica_name("fleet", 0)
    runtime.replicas[r0]["queue_wait_ms"] = 150.0  # 3x the target
    controller.controller.enqueue(("default", "fleet"))
    converge(controller)
    # The fake's wait signal never improves, so the proportional policy
    # keeps compounding until it hits the ceiling — queues stayed at
    # depth 0 the whole time, so this is purely the latency signal.
    assert dep_status(api)["targetReplicas"] == 4
    assert len(runtime.replicas) == 4


def test_scale_down_stabilization_prevents_flap(harness):
    """A transient pressure dip inside the stabilization window must not
    shrink the fleet (flap-free scale-down); once the window drains of
    high targets, scale-down proceeds — and scale-up stays immediate."""
    api, runtime, _ = harness
    now = [1000.0]
    controller = ServingDeploymentController(
        api, runtime=runtime, clock=lambda: now[0]
    )
    api.create(
        serving_api.make_serving_deployment(
            "fleet",
            replicas=1,
            autoscale={
                "min_replicas": 1,
                "max_replicas": 4,
                "target_queue_depth": 10,
                "scale_down_stabilization_s": 30.0,
            },
        )
    )
    converge(controller)
    r0 = serving_api.replica_name("fleet", 0)
    runtime.replicas[r0]["queue_depth"] = 40  # → 4 replicas
    controller.controller.enqueue(("default", "fleet"))
    converge(controller)
    assert len(runtime.replicas) == 4

    # The burst pauses for one reconcile: raw target collapses to 1 but
    # the window still holds the 4 — the fleet must not move.
    runtime.replicas[r0]["queue_depth"] = 0
    now[0] += 5.0
    controller.controller.enqueue(("default", "fleet"))
    converge(controller)
    assert len(runtime.replicas) == 4
    assert dep_status(api)["targetReplicas"] == 4
    assert runtime.stopped == []

    # Pressure returns mid-window: scale-up needs no window to pass —
    # the fleet is already at 4 and stays there.
    runtime.replicas[r0]["queue_depth"] = 40
    now[0] += 5.0
    controller.controller.enqueue(("default", "fleet"))
    converge(controller)
    assert len(runtime.replicas) == 4

    # Quiet past the whole window: the high samples age out and the
    # fleet finally settles to min.
    runtime.replicas[r0]["queue_depth"] = 0
    now[0] += 31.0
    controller.controller.enqueue(("default", "fleet"))
    converge(controller)
    assert len(runtime.replicas) == 1
    assert dep_status(api)["targetReplicas"] == 1


def test_stabilization_field_roundtrip_and_validation():
    spec = serving_api.ServingDeploymentSpec(
        autoscale=serving_api.AutoscaleSpec(
            max_replicas=4, scale_down_stabilization_s=30.0
        )
    )
    d = spec.to_dict()
    assert d["autoscale"]["scaleDownStabilizationSeconds"] == 30.0
    parsed = serving_api.ServingDeploymentSpec.from_dict(d)
    assert parsed.autoscale.scale_down_stabilization_s == 30.0
    # Absent field defaults off (existing CRs parse unchanged).
    no_window = serving_api.ServingDeploymentSpec.from_dict(
        {"autoscale": {"maxReplicas": 2}}
    )
    assert no_window.autoscale.scale_down_stabilization_s == 0.0
    with pytest.raises(ValueError, match="scaleDownStabilization"):
        serving_api.AutoscaleSpec(scale_down_stabilization_s=-1).validate()


# -- runtime: process -----------------------------------------------------


def test_runtime_field_roundtrip_and_validation():
    spec = serving_api.ServingDeploymentSpec(runtime="process")
    assert spec.to_dict()["runtime"] == "process"
    parsed = serving_api.ServingDeploymentSpec.from_dict(spec.to_dict())
    assert parsed.runtime == "process"
    # Default stays local (existing CRs parse unchanged).
    assert serving_api.ServingDeploymentSpec.from_dict({}).runtime == "local"
    with pytest.raises(ValueError, match="runtime"):
        serving_api.ServingDeploymentSpec(runtime="docker").validate()
    with pytest.raises(ValueError, match="targetLatency"):
        serving_api.ServingDeploymentSpec.from_dict(
            {"autoscale": {"targetLatency": 5}}
        )


def test_process_spec_routes_to_process_runtime():
    """`spec.runtime: process` materializes via the process runtime;
    local specs keep using the in-process one; teardown sweeps both."""
    api = FakeApiServer()
    local, procs = FakeRuntime(), FakeRuntime()
    controller = ServingDeploymentController(
        api, runtime=local, process_runtime=procs
    )
    api.create(
        serving_api.make_serving_deployment(
            "pfleet", replicas=2, runtime="process"
        )
    )
    api.create(serving_api.make_serving_deployment("lfleet", replicas=1))
    converge(controller)
    assert sorted(procs.replicas) == [
        serving_api.replica_name("pfleet", 0),
        serving_api.replica_name("pfleet", 1),
    ]
    assert sorted(local.replicas) == [serving_api.replica_name("lfleet", 0)]

    api.delete(serving_api.KIND, "pfleet", "default")
    converge(controller)
    assert procs.replicas == {}
    assert local.replicas != {}  # the local fleet is untouched


def test_process_spec_without_process_runtime_degrades_to_local():
    api = FakeApiServer()
    local = FakeRuntime()
    controller = ServingDeploymentController(api, runtime=local)
    api.create(
        serving_api.make_serving_deployment(
            "pfleet", replicas=1, runtime="process"
        )
    )
    converge(controller)
    assert sorted(local.replicas) == [serving_api.replica_name("pfleet", 0)]


# -- multiplexed fleets: CR -> replicas -> status (ISSUE 17) -----------------


class MuxRuntime(FakeRuntime):
    """FakeRuntime whose replicas carry per-model registry stats, the
    shape MultiModelReplica.stats() exposes to the controller."""

    def __init__(self):
        super().__init__()
        self.rspecs: dict[str, dict] = {}

    def ensure(self, name, rspec):
        self.rspecs[name] = dict(rspec)
        if name in self.replicas:
            return
        models = {
            m["name"]: {
                "state": "resident",
                "version": int(m.get("modelVersion") or 1),
                "page_ins": 1,
            }
            for m in rspec.get("models", [])
        }
        self.replicas[name] = {
            "ready": True,
            "version": 1,
            "queue_depth": 0,
            "inflight": 0,
            "queue_wait_ms": 0.0,
            "models": models,
            "resident": len(models),
        }

    def roll(self, name, rspec):
        for m in rspec.get("models", []):
            row = self.replicas[name]["models"][m["name"]]
            if row["state"] == "resident":
                row["version"] = int(m.get("modelVersion") or 1)
        self.rolls.append(name)
        return 0.01


def make_mux_deployment(**kwargs):
    return serving_api.make_serving_deployment(
        "mux",
        replicas=2,
        models=[
            {"name": "alpha", "modelVersion": 1},
            {"name": "beta", "modelVersion": 1, "priority": "batch"},
        ],
        **kwargs,
    )


def test_multiplexed_spec_flows_to_replicas():
    api = FakeApiServer()
    runtime = MuxRuntime()
    controller = ServingDeploymentController(api, runtime=runtime)
    api.create(make_mux_deployment(max_resident=1))
    converge(controller)

    assert len(runtime.replicas) == 2
    for rspec in runtime.rspecs.values():
        assert [m["name"] for m in rspec["models"]] == ["alpha", "beta"]
        assert rspec["paging"] == {"maxResident": 1}
    # Replica objects carry the same catalog (the worker's channel).
    robj = api.get(
        serving_api.REPLICA_KIND, serving_api.replica_name("mux", 0),
        "default",
    )
    assert [m["name"] for m in robj.spec["models"]] == ["alpha", "beta"]


def test_multiplexed_status_aggregates_per_model():
    api = FakeApiServer()
    runtime = MuxRuntime()
    controller = ServingDeploymentController(api, runtime=runtime)
    api.create(make_mux_deployment())
    converge(controller)

    status = api.get(serving_api.KIND, "mux", "default").status
    by_name = {m["name"]: m for m in status["models"]}
    assert set(by_name) == {"alpha", "beta"}
    assert by_name["alpha"]["residentReplicas"] == 2
    assert by_name["alpha"]["version"] == 1
    assert by_name["alpha"]["pageIns"] == 2  # one per replica
    assert all(r["resident"] == 2 for r in status["replicas"])


def test_multiplexed_roll_targets_only_stale_resident_models():
    api = FakeApiServer()
    runtime = MuxRuntime()
    controller = ServingDeploymentController(api, runtime=runtime)
    api.create(make_mux_deployment())
    converge(controller)
    assert runtime.rolls == []

    # beta pages out on replica 1: a version bump for beta must NOT
    # roll that replica (its next page-in loads the new version free).
    runtime.replicas[serving_api.replica_name("mux", 1)]["models"][
        "beta"
    ] = {"state": "registered", "version": 0, "page_ins": 1}

    dep = api.get(serving_api.KIND, "mux", "default").thaw()
    dep.spec = dict(dep.spec)
    models = [dict(m) for m in dep.spec["models"]]
    models[1]["modelVersion"] = 2  # bump beta only
    dep.spec["models"] = models
    api.update(dep)
    converge(controller)

    # Only replica 0 (beta resident + stale) rolled.
    assert runtime.rolls == [serving_api.replica_name("mux", 0)]
    events = [
        e.spec for e in api.list("Event", "default")
        if e.spec.get("reason") == "ReplicaRolled"
    ]
    assert events and "beta -> version 2" in events[-1]["message"]
    # And alpha was never named: it is not stale.
    assert "alpha" not in events[-1]["message"]


def test_sync_replica_once_multimodel_loads_catalog():
    from kubeflow_tpu.serving.__main__ import sync_replica_once
    from kubeflow_tpu.api.objects import new_resource

    api = FakeApiServer()
    api.create(
        new_resource(
            serving_api.REPLICA_KIND,
            "r0",
            "default",
            spec={
                "model": "demo",
                "maxBatch": 8,
                "models": [
                    {"name": "alpha", "modelVersion": 3},
                    {"name": "beta", "modelVersion": 5},
                ],
            },
        )
    )
    repo = FakeRepository()
    live = sync_replica_once(
        api, "r0", "default", repo, build_servable=build_servable
    )
    assert live == 5  # max across the catalog
    assert sorted(repo.models) == ["alpha", "beta"]
    assert repo.models["alpha"].version == 3
    status = api.get(serving_api.REPLICA_KIND, "r0", "default").status
    assert status["models"] == {"alpha": 3, "beta": 5}

    # Idempotent: same versions -> no reloads.
    sync_replica_once(
        api, "r0", "default", repo, build_servable=build_servable
    )
    assert repo.loads == 2


def test_models_and_paging_field_roundtrip_and_validation():
    spec = serving_api.ServingDeploymentSpec(
        models=(
            serving_api.ModelEntry(name="alpha", model_version=2),
            serving_api.ModelEntry(
                name="beta", priority="batch", quota_rate=5.0,
                quota_burst=10.0,
            ),
        ),
        max_resident=1,
    )
    d = spec.to_dict()
    assert [m["name"] for m in d["models"]] == ["alpha", "beta"]
    assert d["models"][1]["priority"] == "batch"
    assert d["models"][1]["quotaRate"] == 5.0
    assert d["paging"] == {"maxResident": 1}
    parsed = serving_api.ServingDeploymentSpec.from_dict(d)
    assert parsed.models == spec.models
    assert parsed.max_resident == 1
    # Absent fields default to a single-model spec (old CRs parse).
    legacy = serving_api.ServingDeploymentSpec.from_dict({})
    assert legacy.models == () and legacy.max_resident == 0

    with pytest.raises(ValueError, match="unique"):
        serving_api.ServingDeploymentSpec(
            models=(
                serving_api.ModelEntry(name="a"),
                serving_api.ModelEntry(name="a"),
            )
        ).validate()
    with pytest.raises(ValueError, match="priority"):
        serving_api.ModelEntry(name="a", priority="vip").validate()
    with pytest.raises(ValueError, match="maxResident"):
        serving_api.ServingDeploymentSpec(max_resident=-1).validate()
    # Unknown fields inside a model entry are rejected (fat-finger
    # protection, same policy as the spec root).
    with pytest.raises(ValueError, match="unknown"):
        serving_api.ServingDeploymentSpec.from_dict(
            {"models": [{"name": "a", "quotaRte": 1}]}
        )
    with pytest.raises(ValueError, match="unknown"):
        serving_api.ServingDeploymentSpec.from_dict(
            {"paging": {"maxResidnt": 1}}
        )
