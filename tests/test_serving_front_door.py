"""Multi-model front door HTTP surface (ISSUE 17).

`FrontDoorApp` is one HTTP boundary over the drain-aware `Router` for a
whole multiplexed fleet: the ``/v1/models/<m>`` path segment selects
the servable, priority/tenant ride headers, and every router verdict
maps onto an honest status code (429 + jittered fractional Retry-After
for sheds, 404 for an unknown model, 400 for client errors, 503 for a
dead fleet). These tests drive the real registry → replica → router
stack behind the app — no mocks on the serving path.
"""

import numpy as np
import pytest

from kubeflow_tpu.serving import (
    AdmissionController,
    BatchingConfig,
    FrontDoorApp,
    MultiModelReplica,
    PagingConfig,
    QuotaSpec,
    Router,
    ServableRegistry,
)
from kubeflow_tpu.serving import wire
from kubeflow_tpu.serving.server import PRIORITY_HEADER, TENANT_HEADER
from kubeflow_tpu.utils.metrics import MetricsRegistry
from kubeflow_tpu.web import TestClient


class Doubler:
    def __init__(self, name):
        self.name = name
        self.version = 1

    def predict(self, instances):
        return np.asarray(instances, dtype=np.float32) * 2.0


@pytest.fixture()
def stack():
    metrics = MetricsRegistry()
    admission = AdmissionController(
        quotas={"capped": QuotaSpec(rate=0.001, burst=1.0)},
        metrics=metrics,
    )
    router = Router(metrics, admission=admission, retry_jitter_seed=42)
    registries = []
    for i in range(2):
        registry = ServableRegistry(
            lambda rspec: Doubler(rspec["model"]),
            batching=BatchingConfig(max_batch=4, timeout_ms=2.0),
            paging=PagingConfig(max_resident=1),
            metrics=metrics,
        )
        for model in ("alpha", "beta"):
            registry.ensure({"model": model})
        registries.append(registry)
        router.add(MultiModelReplica(f"fd-{i}", registry))
    app = FrontDoorApp(router, metrics=metrics)
    yield app, TestClient(app), router
    for name in list(router.replica_names()):
        replica = router.replica(name)
        router.remove(name)
        replica.close()


def test_models_list_aggregates_catalog(stack):
    app, client, _ = stack
    resp = client.get("/v1/models")
    assert resp.status == 200
    assert resp.json() == {"models": ["alpha", "beta"]}


def test_predict_selects_model_from_path(stack):
    app, client, _ = stack
    for model in ("alpha", "beta"):
        resp = client.post(
            f"/v1/models/{model}:predict",
            {"instances": [[1.0, 2.0]]},
        )
        assert resp.status == 200, resp.body
        assert resp.json()["predictions"] == [[2.0, 4.0]]


def test_binary_predict_roundtrip(stack):
    app, client, _ = stack
    x = np.ones((2, 3), np.float32)
    resp = client.post(
        "/v1/models/alpha:predict",
        raw=wire.encode_tensor(x),
        content_type=wire.TENSOR_CONTENT_TYPE,
        headers={"Accept": wire.TENSOR_CONTENT_TYPE},
    )
    assert resp.status == 200, resp.body
    assert resp.content_type == wire.TENSOR_CONTENT_TYPE
    np.testing.assert_array_equal(wire.decode_tensor(resp.body), x * 2.0)


def test_model_status_reports_residency(stack):
    app, client, _ = stack
    client.post("/v1/models/alpha:predict", {"instances": [[1.0]]})
    resp = client.get("/v1/models/alpha")
    assert resp.status == 200
    body = resp.json()
    assert body["resident_replicas"] >= 1
    assert body["model_version_status"][0]["state"] == "AVAILABLE"
    assert client.get("/v1/models/ghost").status == 404


def test_unknown_model_predict_is_404(stack):
    app, client, _ = stack
    resp = client.post(
        "/v1/models/ghost:predict", {"instances": [[1.0]]}
    )
    assert resp.status == 404


def test_unknown_priority_is_400_not_shed(stack):
    app, client, router = stack
    shed_before = router.shed_total.value()
    resp = client.post(
        "/v1/models/alpha:predict",
        {"instances": [[1.0]]},
        headers={PRIORITY_HEADER: "vip"},
    )
    assert resp.status == 400
    assert router.shed_total.value() == shed_before  # client error != shed


def test_quota_shed_is_429_with_fractional_retry_after(stack):
    app, client, router = stack
    acked_before = router.acked_total.value()
    first = client.post(
        "/v1/models/alpha:predict",
        {"instances": [[1.0]]},
        headers={TENANT_HEADER: "capped"},
    )
    assert first.status == 200  # the burst token
    resp = client.post(
        "/v1/models/alpha:predict",
        {"instances": [[1.0]]},
        headers={TENANT_HEADER: "capped"},
    )
    assert resp.status == 429, resp.body
    retry_after = dict(resp.headers)["Retry-After"]
    assert "." in retry_after  # fractional seconds, docs/serving.md
    assert float(retry_after) > 0.0
    # One acked request total: the shed was refused pre-ack.
    assert router.acked_total.value() == acked_before + 1
    assert router.shed_total.value() >= 1


def test_bad_tensor_frame_is_400_with_invalid_counter(stack):
    app, client, _ = stack
    before = app.request_count.value(model="alpha", outcome="invalid")
    resp = client.post(
        "/v1/models/alpha:predict",
        raw=b"KFT1 definitely not a frame",
        content_type=wire.TENSOR_CONTENT_TYPE,
    )
    assert resp.status == 400
    after = app.request_count.value(model="alpha", outcome="invalid")
    assert after == before + 1


def test_empty_instances_is_400(stack):
    app, client, _ = stack
    resp = client.post("/v1/models/alpha:predict", {"instances": []})
    assert resp.status == 400


def test_dead_fleet_is_503(stack):
    app, client, router = stack
    for name in router.replica_names():
        router.replica(name).kill()
    resp = client.post(
        "/v1/models/alpha:predict", {"instances": [[1.0]]}
    )
    assert resp.status == 503


def test_metrics_endpoint_exposes_front_door_counters(stack):
    app, client, _ = stack
    client.post("/v1/models/alpha:predict", {"instances": [[1.0]]})
    text = client.get("/metrics").body.decode()
    assert "serving_front_door_requests_total" in text
    assert "serving_page_ins_total" in text


def test_cr_catalog_quota_reaches_the_front_door():
    """End-to-end wiring regression: a `quotaRate` declared in the CR's
    models[] must actually shed at the HTTP boundary — through the
    ServingDeployment controller, the LocalReplicaRuntime hook, and
    the router's per-model bucket — not sit decorative in the spec.
    (Caught by a live-server drive: the spec fields validated and
    round-tripped but nothing consumed them.)"""
    from kubeflow_tpu.api import serving as serving_api
    from kubeflow_tpu.controllers.serving import (
        ServingDeploymentController,
    )
    from kubeflow_tpu.serving.replica import LocalReplicaRuntime
    from kubeflow_tpu.testing import FakeApiServer

    metrics = MetricsRegistry()
    router = Router(metrics, retry_jitter_seed=7)
    runtime = LocalReplicaRuntime(
        router, lambda rspec: Doubler(rspec["model"]), metrics
    )
    api = FakeApiServer()
    controller = ServingDeploymentController(
        api, runtime=runtime, metrics=metrics
    )
    api.create(serving_api.make_serving_deployment(
        "fd", replicas=1,
        models=[
            {"name": "alpha", "quotaRate": 0.001, "quotaBurst": 1.0},
            {"name": "beta", "priority": "batch"},
        ],
    ))
    controller.controller.run_until_idle()
    try:
        app = FrontDoorApp(router, metrics=metrics)
        client = TestClient(app)
        body = {"instances": [[1.0]]}

        # Burst of 1: first request lands, second sheds honestly.
        assert client.post(
            "/v1/models/alpha:predict", body
        ).status == 200
        resp = client.post("/v1/models/alpha:predict", body)
        assert resp.status == 429
        assert float(dict(resp.headers)["Retry-After"]) > 0
        # beta carries no quota — and its catalog-declared "batch"
        # class resolves when the request names none (an unknown
        # class here would be a 400).
        assert client.post(
            "/v1/models/beta:predict", body
        ).status == 200
    finally:
        for name in list(router.replica_names()):
            replica = router.replica(name)
            router.remove(name)
            replica.close()
