"""Multi-model servable registry: LRU weight paging + lifecycle (ISSUE 17).

`serving/registry.py` is the tentpole's per-replica core: one catalog
of N models, each with its own continuous-batch queue, with at most
``max_resident`` holding live weights. These tests pin:

- lazy page-in on first request, measured (`page_ins`,
  `last_page_in_s`, the `serving_page_ins_total` counter);
- LRU eviction at the residency limit, preferring idle victims;
- a paged-out model transparently paging back in on its next request;
- roll semantics: eager reload for a resident model, spec-only update
  for a paged-out one, and the page-in-racing-roll interaction from the
  docs/serving.md failure matrix (the roll waits the load out — no
  caller is stranded on a discarded generation);
- whole-registry kill: everything dies crisply, nothing resurrects.

Per-model isolation under load (slow-model / kill-during-page-in) is
pinned next door in tests/test_serving_batching.py.
"""

import threading
import time

import numpy as np
import pytest

from kubeflow_tpu.serving import (
    BatchingConfig,
    ModelNotFound,
    PagingConfig,
    ServableRegistry,
)
from kubeflow_tpu.serving.batching import QueueClosed


class Recorder:
    """Factory + servable in one: records every build so tests can
    assert exactly when page-ins happened."""

    def __init__(self):
        self.builds: list[str] = []
        self._lock = threading.Lock()

    def __call__(self, rspec: dict):
        name = rspec["model"]
        with self._lock:
            self.builds.append(name)
        outer = self

        class _Servable:
            def __init__(self):
                self.name = name
                self.version = int(rspec.get("modelVersion", 0) or 1)

            def predict(self, instances):
                return np.asarray(instances) * 2.0

        del outer
        return _Servable()


def make_registry(max_resident=0, factory=None):
    return ServableRegistry(
        factory or Recorder(),
        batching=BatchingConfig(max_batch=4, timeout_ms=2.0),
        paging=PagingConfig(max_resident=max_resident),
    )


X = np.ones((1, 3))


def test_page_in_is_lazy_and_measured():
    factory = Recorder()
    registry = make_registry(factory=factory)
    try:
        registry.ensure({"model": "a"})
        assert factory.builds == []  # registration loads nothing
        row = registry.stats()["models"]["a"]
        assert row["state"] == "registered" and row["page_ins"] == 0

        np.testing.assert_array_equal(registry.predict("a", X), X * 2.0)
        assert factory.builds == ["a"]
        row = registry.stats()["models"]["a"]
        assert row["state"] == "resident"
        assert row["page_ins"] == 1
        assert row["last_page_in_s"] >= 0.0
        assert registry.page_ins_total.value(model="a") == 1

        registry.predict("a", X)  # resident: no rebuild
        assert factory.builds == ["a"]
    finally:
        registry.close()


def test_unknown_model_is_model_not_found():
    registry = make_registry()
    try:
        with pytest.raises(ModelNotFound):
            registry.predict("ghost", X)
    finally:
        registry.close()


def test_lru_evicts_least_recently_used():
    factory = Recorder()
    registry = make_registry(max_resident=2, factory=factory)
    try:
        for name in ("a", "b", "c"):
            registry.ensure({"model": name})
        registry.predict("a", X)
        time.sleep(0.01)  # monotonic last_used ordering
        registry.predict("b", X)
        time.sleep(0.01)
        registry.predict("c", X)  # residency limit: "a" pages out

        stats = registry.stats()
        assert stats["resident"] == 2
        assert stats["models"]["a"]["state"] == "registered"
        assert stats["models"]["b"]["state"] == "resident"
        assert stats["models"]["c"]["state"] == "resident"
        assert registry.page_outs_total.value(model="a") == 1

        # The paged-out model serves again — one more (measured) build.
        np.testing.assert_array_equal(registry.predict("a", X), X * 2.0)
        assert factory.builds == ["a", "b", "c", "a"]
        assert registry.stats()["models"]["a"]["page_ins"] == 2
        # ...and its page-in evicted the new LRU, "b".
        assert registry.stats()["models"]["b"]["state"] == "registered"
    finally:
        registry.close()


def test_predict_touch_refreshes_lru_rank():
    registry = make_registry(max_resident=2)
    try:
        for name in ("a", "b", "c"):
            registry.ensure({"model": name})
        registry.predict("a", X)
        time.sleep(0.01)
        registry.predict("b", X)
        time.sleep(0.01)
        registry.predict("a", X)  # touch: "b" is now the LRU
        time.sleep(0.01)
        registry.predict("c", X)
        stats = registry.stats()["models"]
        assert stats["a"]["state"] == "resident"
        assert stats["b"]["state"] == "registered"
    finally:
        registry.close()


def test_roll_resident_reloads_eagerly():
    factory = Recorder()
    registry = make_registry(factory=factory)
    try:
        registry.ensure({"model": "a", "modelVersion": 1})
        registry.predict("a", X)
        registry.roll("a", {"model": "a", "modelVersion": 7})
        # Still resident, new generation, no request needed.
        row = registry.stats()["models"]["a"]
        assert row["state"] == "resident" and row["version"] == 7
        assert factory.builds == ["a", "a"]
    finally:
        registry.close()


def test_roll_paged_out_updates_spec_only():
    factory = Recorder()
    registry = make_registry(factory=factory)
    try:
        registry.ensure({"model": "a", "modelVersion": 1})
        registry.roll("a", {"model": "a", "modelVersion": 7})
        assert factory.builds == []  # not resident: nothing loads
        registry.predict("a", X)
        assert registry.stats()["models"]["a"]["version"] == 7
    finally:
        registry.close()


def test_roll_waits_out_inflight_page_in():
    """Failure matrix: page-in-racing-roll. The roll must wait the
    in-flight load out, then swap — the caller parked on the first
    page-in completes against the generation it claimed, and the
    post-roll version is the rolled spec's."""
    release = threading.Event()
    in_factory = threading.Event()
    recorder = Recorder()

    def factory(rspec):
        if not in_factory.is_set():
            in_factory.set()
            release.wait(10)
        return recorder(rspec)

    registry = make_registry(factory=factory)
    try:
        registry.ensure({"model": "a", "modelVersion": 1})
        results = []

        def first_caller():
            results.append(registry.predict("a", X))

        t = threading.Thread(target=first_caller)
        t.start()
        assert in_factory.wait(5)  # page-in v1 is in flight

        rolled = threading.Thread(
            target=lambda: registry.roll(
                "a", {"model": "a", "modelVersion": 2}
            )
        )
        rolled.start()
        time.sleep(0.05)
        assert rolled.is_alive()  # parked behind the load, not yanking it

        release.set()
        t.join(timeout=10)
        rolled.join(timeout=10)
        assert not t.is_alive() and not rolled.is_alive()
        assert len(results) == 1  # the racing caller was answered
        assert registry.stats()["models"]["a"]["version"] == 2
    finally:
        release.set()
        registry.close()


def test_kill_registry_is_terminal():
    registry = make_registry()
    try:
        registry.ensure({"model": "a"})
        registry.predict("a", X)
        registry.kill()
        with pytest.raises(QueueClosed):
            registry.predict("a", X)
        assert registry.stats()["closed"]
    finally:
        registry.close()


def test_factory_failure_surfaces_and_does_not_poison():
    """A failed page-in reports its error to the waiting callers but
    leaves the catalog entry retryable — the next request tries again."""
    boom = [True]
    recorder = Recorder()

    def factory(rspec):
        if boom[0]:
            raise RuntimeError("checkpoint store down")
        return recorder(rspec)

    registry = make_registry(factory=factory)
    try:
        registry.ensure({"model": "a"})
        with pytest.raises(RuntimeError, match="checkpoint store down"):
            registry.predict("a", X)
        assert registry.stats()["models"]["a"]["state"] == "registered"
        boom[0] = False
        np.testing.assert_array_equal(registry.predict("a", X), X * 2.0)
    finally:
        registry.close()
