"""Drain-aware router contracts (`serving/router.py`).

The router replaces TF-Serving's external L7 balancer (docs/parity.md
carries the deviation): spread by least-outstanding, idempotent retry on
replica death, honest load shedding with Retry-After, and the drain /
roll choreography a zero-downtime checkpoint swap rides on. The chaos
bench gates `acked == completed + failed, failed == 0`; these tests pin
the same accounting at unit scale, including every arm of the drain
matrix (in-flight completes, no new admissions, re-admit after swap,
kill-mid-drain falls back to a survivor).
"""

import threading
import time

import pytest

from kubeflow_tpu.serving.router import (
    NoReadyReplicas,
    Overloaded,
    ReplicaGone,
    ReplicaOverloaded,
    Router,
)


class FakeReplica:
    """Scriptable replica: gate to hold requests in flight, kill to make
    every (current and future) call die with ReplicaGone, fail_once to
    script a single scripted exception."""

    def __init__(self, name, capacity=8):
        self.name = name
        self.capacity = capacity
        self.calls = 0
        self.gate = None
        self.fail_once = None
        self._killed = threading.Event()
        self._lock = threading.Lock()

    def kill(self):
        self._killed.set()
        if self.gate is not None:
            self.gate.set()

    def predict(self, x, model=None):
        with self._lock:
            self.calls += 1
            fail, self.fail_once = self.fail_once, None
        if fail is not None:
            raise fail
        if self.gate is not None:
            self.gate.wait(10)
        if self._killed.is_set():
            raise ReplicaGone(f"{self.name} killed")
        return ("ok", self.name, x)

    def stats(self):
        return {"ready": not self._killed.is_set()}


def make_fleet(n=2, capacity=8):
    router = Router()
    replicas = [FakeReplica(f"r{i}", capacity) for i in range(n)]
    for r in replicas:
        router.add(r)
    return router, replicas


def counts(router):
    return {
        "acked": router.acked_total.value(),
        "completed": router.completed_total.value(),
        "failed": router.failed_total.value(),
        "shed": router.shed_total.value(),
    }


def test_spread_prefers_least_outstanding():
    router, (a, b) = make_fleet(2)
    a.gate = threading.Event()  # first request parks on a replica...

    t = threading.Thread(target=router.predict, args=(1,))
    t.start()
    deadline = time.monotonic() + 5
    while a.calls + b.calls == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    busy, idle = (a, b) if a.calls else (b, a)

    idle.gate = None
    # ...so the next one lands on the idle sibling, not round-robin luck.
    _, served_by, _ = router.predict(2)
    assert served_by == idle.name
    busy.gate.set()
    t.join(timeout=5)


def test_retry_on_replica_death_idempotent():
    router, (a, b) = make_fleet(2)
    a.fail_once = ReplicaGone("connection reset")
    b.fail_once = None

    results = {router.predict(i)[1] for i in range(4)}
    # Whichever replica died, everything completed on the survivor.
    assert results  # no exception escaped
    c = counts(router)
    assert c["acked"] == 4 and c["completed"] == 4
    assert c["failed"] == 0
    assert router.retried_total.value() == 1
    # The dead replica is out of the ready set.
    assert len(router.ready_names()) == 1


def test_non_idempotent_death_fails_fast():
    router, (a, b) = make_fleet(2)
    a.fail_once = ReplicaGone("reset")
    b.fail_once = ReplicaGone("reset")
    with pytest.raises(ReplicaGone):
        router.predict(1, idempotent=False)
    c = counts(router)
    assert c["failed"] == 1 and c["completed"] == 0
    assert c["acked"] == 1  # acked, then honestly accounted as failed


def test_model_error_propagates_without_retry():
    router, (a, b) = make_fleet(2)
    a.fail_once = ValueError("bad input shape")
    b.fail_once = ValueError("bad input shape")
    with pytest.raises(ValueError):
        router.predict(1)
    # Exactly one replica executed: a request failing on its merits must
    # not burn the fleet retrying it.
    assert a.calls + b.calls == 1
    assert counts(router)["failed"] == 1


def test_no_replicas_raises_no_ready():
    router = Router()
    with pytest.raises(NoReadyReplicas):
        router.predict(1)


def test_shed_with_retry_after_when_at_capacity():
    router, (a, b) = make_fleet(2, capacity=1)
    a.gate = threading.Event()
    b.gate = threading.Event()
    threads = [
        threading.Thread(target=router.predict, args=(i,))
        for i in range(2)
    ]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 5
    while a.calls + b.calls < 2 and time.monotonic() < deadline:
        time.sleep(0.005)

    with pytest.raises(Overloaded) as exc:
        router.predict(99)
    assert exc.value.retry_after > 0
    c = counts(router)
    assert c["shed"] == 1
    assert c["acked"] == 2  # the shed request was never acknowledged
    a.gate.set()
    b.gate.set()
    for t in threads:
        t.join(timeout=5)
    assert counts(router)["completed"] == 2


def test_replica_overloaded_tries_sibling():
    router, (a, b) = make_fleet(2)
    a.fail_once = ReplicaOverloaded("queue full")
    b.fail_once = ReplicaOverloaded("queue full")
    # One of them refuses; the other (whose fail already fired or not)
    # may refuse too — but a second pass succeeds within the deadline.
    assert router.predict(1)[0] == "ok"
    assert counts(router)["failed"] == 0


# -- the drain matrix -------------------------------------------------------


def test_drain_waits_for_inflight_then_blocks_admission():
    router, (a, b) = make_fleet(2)
    a.gate = threading.Event()
    t = threading.Thread(target=router.predict, args=(1,))
    t.start()
    deadline = time.monotonic() + 5
    while a.calls + b.calls == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    busy, other = (a, b) if a.calls else (b, a)

    drained = []
    dt = threading.Thread(
        target=lambda: drained.append(router.drain(busy.name, timeout=10))
    )
    dt.start()
    time.sleep(0.05)
    assert not drained  # in-flight work pins the drain

    # No new admissions to the draining replica: traffic flows to the
    # sibling the whole time.
    before = busy.calls
    for i in range(3):
        assert router.predict(i)[1] == other.name
    assert busy.calls == before

    busy.gate.set()  # in-flight request completes...
    dt.join(timeout=5)
    assert drained == [True]  # ...and the drain observes it
    assert counts(router)["failed"] == 0


def test_roll_swaps_quiesced_and_readmits():
    router, (a, b) = make_fleet(2)
    a.gate = threading.Event()
    t = threading.Thread(target=router.predict, args=(1,))
    t.start()
    deadline = time.monotonic() + 5
    while a.calls + b.calls == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    busy = a if a.calls else b

    quiesced = []

    def swap():
        # Router.roll's contract: swap_fn runs with zero in-flight work.
        quiesced.append(router.stats()["replicas"][busy.name]["outstanding"])

    threading.Timer(0.05, busy.gate.set).start()
    out_of_rotation = router.roll(busy.name, swap, timeout=10)
    t.join(timeout=5)
    assert quiesced == [0]
    assert out_of_rotation >= 0.0
    # Re-admitted: the rolled replica serves traffic again.
    assert busy.name in router.ready_names()
    busy.gate = None
    served = {router.predict(i)[1] for i in range(8)}
    assert busy.name in served


def test_kill_mid_drain_falls_back_to_survivor():
    router, (a, b) = make_fleet(2)
    a.gate = threading.Event()
    results = []
    t = threading.Thread(
        target=lambda: results.append(router.predict(1))
    )
    t.start()
    deadline = time.monotonic() + 5
    while a.calls + b.calls == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    busy, other = (a, b) if a.calls else (b, a)
    other.gate = None

    drained = []
    dt = threading.Thread(
        target=lambda: drained.append(router.drain(busy.name, timeout=10))
    )
    dt.start()
    time.sleep(0.05)
    busy.kill()  # SIGKILL mid-drain: in-flight dies with ReplicaGone

    t.join(timeout=5)
    dt.join(timeout=5)
    # The in-flight request failed over to the survivor — acked work is
    # never dropped — and the drain still completed.
    assert results and results[0][1] == other.name
    assert drained == [True]
    c = counts(router)
    assert c["acked"] == c["completed"] == 1
    assert c["failed"] == 0
    assert router.retried_total.value() == 1


def test_all_draining_is_overloaded_not_dead():
    router, (a, b) = make_fleet(2)
    router.drain(a.name, timeout=1)
    router.drain(b.name, timeout=1)
    with pytest.raises(Overloaded):
        router.predict(1)
    router.admit(a.name)
    assert router.predict(1)[0] == "ok"


# -- priority admission + quotas + jittered backoff (ISSUE 17) ---------------


def test_unknown_priority_class_is_value_error():
    from kubeflow_tpu.serving import AdmissionController

    router, _ = make_fleet(n=1)
    router.admission = AdmissionController()
    with pytest.raises(ValueError, match="unknown priority"):
        router.predict("x", priority="vip")


def test_batch_sheds_at_its_ceiling_while_critical_passes():
    """Headroom ladder: with fleet occupancy parked at the batch
    ceiling (0.5x slots), batch sheds pre-ack while critical still
    dispatches — the reserved slots are critical's to spend."""
    from kubeflow_tpu.serving import AdmissionController

    router, replicas = make_fleet(n=1, capacity=8)
    router.admission = AdmissionController()
    gate = threading.Event()
    replicas[0].gate = gate
    holders = [
        threading.Thread(target=lambda: router.predict("x"))
        for _ in range(4)
    ]
    try:
        for t in holders:
            t.start()
        deadline = time.monotonic() + 5
        while (
            router.stats()["outstanding"] < 4
            and time.monotonic() < deadline
        ):
            time.sleep(0.005)
        assert router.stats()["outstanding"] == 4  # == 0.5 * 8 slots

        shed_before = counts(router)["shed"]
        with pytest.raises(Overloaded) as excinfo:
            router.predict("x", priority="batch")
        assert "headroom" in str(excinfo.value)
        assert excinfo.value.retry_after > 0
        after = counts(router)
        assert after["shed"] == shed_before + 1
        # An honest shed is never acked.
        assert after["acked"] == 4

        done = []
        t = threading.Thread(
            target=lambda: done.append(
                router.predict("x", priority="critical")
            )
        )
        t.start()
        time.sleep(0.1)
        gate.set()
        t.join(timeout=10)
        assert done and done[0][0] == "ok"
    finally:
        gate.set()
        for t in holders:
            t.join(timeout=10)


def test_tenant_quota_bucket_charges_once_per_request():
    """Token-bucket quota: burst tokens spend one per REQUEST — a
    dispatch retry after a replica death must not double-charge — and
    an empty bucket sheds with a time-to-next-token hint."""
    from kubeflow_tpu.serving import AdmissionController, QuotaSpec

    clock = [100.0]
    admission = AdmissionController(
        quotas={"acme": QuotaSpec(rate=1.0, burst=2.0)},
        clock=lambda: clock[0],
    )
    router, replicas = make_fleet(n=2)
    router.admission = admission

    # First request eats a token AND a dispatch retry (replica death
    # mid-flight, respread to the survivor) — still one token.
    replicas[0].fail_once = ReplicaGone("boom")
    replicas[1].fail_once = ReplicaOverloaded("full")
    out = router.predict("x", tenant="acme")
    assert out[0] == "ok"
    router.predict("x", tenant="acme")  # second token
    with pytest.raises(Overloaded) as excinfo:
        router.predict("x", tenant="acme")
    assert "over quota" in str(excinfo.value)
    # Hint ~1s to the next token, spread [0.5, 1.5]x by the jitter.
    assert 0.4 <= excinfo.value.retry_after <= 1.6

    clock[0] += 1.0  # refill exactly one token
    router.predict("x", tenant="acme")
    with pytest.raises(Overloaded):
        router.predict("x", tenant="acme")
    # Untenanted traffic is uncapped throughout.
    assert router.predict("x")[0] == "ok"


def test_retry_after_jitter_is_seeded_and_spread():
    """Shed hints are deterministic per seed (chaos replays) but spread
    across [0.5, 1.5]x base (no synchronized retry wave)."""

    def shed_sequence(seed, n=8):
        router = Router(retry_jitter_seed=seed)
        hints = []
        for _ in range(n):
            try:
                router.predict("x")
            except NoReadyReplicas:
                pass
            try:
                raise Overloaded("probe", retry_after=router._retry_hint())
            except Overloaded as e:
                hints.append(e.retry_after)
        return hints

    a, b, c = shed_sequence(7), shed_sequence(7), shed_sequence(11)
    assert a == b  # same seed -> same schedule
    assert a != c
    base = Router().retry_after_s
    assert all(0.5 * base <= h <= 1.5 * base for h in a)
    spread = max(a) - min(a)
    assert spread > 0.1 * base  # actually jittered, not constant


def test_model_policy_wires_catalog_quota_and_priority():
    """CR catalog → router: `set_model_policy` turns models[].quotaRate
    into a live per-model bucket (key "model:<name>") and models[].
    priority into the default class for requests that name none — the
    wiring the ServingDeployment controller pushes on every reconcile,
    so a quotaRate in the CR is enforcement, not decoration."""
    from kubeflow_tpu.api.serving import ModelEntry

    clock = [100.0]
    router, _ = make_fleet(n=2)
    router.set_model_policy([
        ModelEntry("alpha", quota_rate=1.0, quota_burst=2.0),
        ModelEntry("beta", priority="batch"),
    ])
    assert router.admission is not None
    router.admission._clock = lambda: clock[0]
    # Re-stamp the bucket onto the injected clock.
    router.admission.set_quota(
        "model:alpha", router.admission.quotas["model:alpha"]
    )

    router.predict("x", model="alpha")
    router.predict("x", model="alpha")  # burst spent
    with pytest.raises(Overloaded) as excinfo:
        router.predict("x", model="alpha")
    assert "over quota" in str(excinfo.value)
    router.predict("x", model="beta")  # no quota on beta

    # Resync idempotence: an unchanged catalog must NOT refill the
    # bucket (set_quota would re-grant the burst every 50ms resync).
    router.set_model_policy([
        ModelEntry("alpha", quota_rate=1.0, quota_burst=2.0),
        ModelEntry("beta", priority="batch"),
    ])
    with pytest.raises(Overloaded):
        router.predict("x", model="alpha")

    # priority=None defers to the catalog class; beta declared "batch",
    # which check_priority sheds first under pressure — here just pin
    # that the resolved class reaches the headroom gate (unknown class
    # would raise ValueError, "standard" fallback for alpha).
    router.predict("x", model="beta", priority=None)
    clock[0] += 10.0
    router.predict("x", model="alpha", priority=None)

    # Dropping the quota from the catalog removes the bucket.
    router.set_model_policy([ModelEntry("alpha"), ModelEntry("beta")])
    assert "model:alpha" not in router.admission.quotas
    for _ in range(5):
        router.predict("x", model="alpha")


def test_model_quota_shed_refunds_tenant_token():
    """All-or-nothing multi-bucket charge: when the model bucket sheds,
    the tenant token charged first is refunded — a capped model must
    not silently drain its tenants' quotas."""
    from kubeflow_tpu.serving import AdmissionController, QuotaSpec

    clock = [100.0]
    admission = AdmissionController(
        quotas={
            "acme": QuotaSpec(rate=1.0, burst=5.0),
            "model:m": QuotaSpec(rate=0.001, burst=1.0),
        },
        clock=lambda: clock[0],
    )
    router, _ = make_fleet(n=2)
    router.admission = admission

    router.predict("x", model="m", tenant="acme")  # spends both
    for _ in range(3):  # model bucket empty; tenant must NOT drain
        with pytest.raises(Overloaded) as excinfo:
            router.predict("x", model="m", tenant="acme")
        assert "model:m" in str(excinfo.value)
    # 4 tenant tokens remain: all spent on an uncapped model.
    for _ in range(4):
        router.predict("x", model="other", tenant="acme")
    with pytest.raises(Overloaded) as excinfo:
        router.predict("x", model="other", tenant="acme")
    assert "'acme' over quota" in str(excinfo.value)
