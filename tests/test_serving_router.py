"""Drain-aware router contracts (`serving/router.py`).

The router replaces TF-Serving's external L7 balancer (docs/parity.md
carries the deviation): spread by least-outstanding, idempotent retry on
replica death, honest load shedding with Retry-After, and the drain /
roll choreography a zero-downtime checkpoint swap rides on. The chaos
bench gates `acked == completed + failed, failed == 0`; these tests pin
the same accounting at unit scale, including every arm of the drain
matrix (in-flight completes, no new admissions, re-admit after swap,
kill-mid-drain falls back to a survivor).
"""

import threading
import time

import pytest

from kubeflow_tpu.serving.router import (
    NoReadyReplicas,
    Overloaded,
    ReplicaGone,
    ReplicaOverloaded,
    Router,
)


class FakeReplica:
    """Scriptable replica: gate to hold requests in flight, kill to make
    every (current and future) call die with ReplicaGone, fail_once to
    script a single scripted exception."""

    def __init__(self, name, capacity=8):
        self.name = name
        self.capacity = capacity
        self.calls = 0
        self.gate = None
        self.fail_once = None
        self._killed = threading.Event()
        self._lock = threading.Lock()

    def kill(self):
        self._killed.set()
        if self.gate is not None:
            self.gate.set()

    def predict(self, x):
        with self._lock:
            self.calls += 1
            fail, self.fail_once = self.fail_once, None
        if fail is not None:
            raise fail
        if self.gate is not None:
            self.gate.wait(10)
        if self._killed.is_set():
            raise ReplicaGone(f"{self.name} killed")
        return ("ok", self.name, x)

    def stats(self):
        return {"ready": not self._killed.is_set()}


def make_fleet(n=2, capacity=8):
    router = Router()
    replicas = [FakeReplica(f"r{i}", capacity) for i in range(n)]
    for r in replicas:
        router.add(r)
    return router, replicas


def counts(router):
    return {
        "acked": router.acked_total.value(),
        "completed": router.completed_total.value(),
        "failed": router.failed_total.value(),
        "shed": router.shed_total.value(),
    }


def test_spread_prefers_least_outstanding():
    router, (a, b) = make_fleet(2)
    a.gate = threading.Event()  # first request parks on a replica...

    t = threading.Thread(target=router.predict, args=(1,))
    t.start()
    deadline = time.monotonic() + 5
    while a.calls + b.calls == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    busy, idle = (a, b) if a.calls else (b, a)

    idle.gate = None
    # ...so the next one lands on the idle sibling, not round-robin luck.
    _, served_by, _ = router.predict(2)
    assert served_by == idle.name
    busy.gate.set()
    t.join(timeout=5)


def test_retry_on_replica_death_idempotent():
    router, (a, b) = make_fleet(2)
    a.fail_once = ReplicaGone("connection reset")
    b.fail_once = None

    results = {router.predict(i)[1] for i in range(4)}
    # Whichever replica died, everything completed on the survivor.
    assert results  # no exception escaped
    c = counts(router)
    assert c["acked"] == 4 and c["completed"] == 4
    assert c["failed"] == 0
    assert router.retried_total.value() == 1
    # The dead replica is out of the ready set.
    assert len(router.ready_names()) == 1


def test_non_idempotent_death_fails_fast():
    router, (a, b) = make_fleet(2)
    a.fail_once = ReplicaGone("reset")
    b.fail_once = ReplicaGone("reset")
    with pytest.raises(ReplicaGone):
        router.predict(1, idempotent=False)
    c = counts(router)
    assert c["failed"] == 1 and c["completed"] == 0
    assert c["acked"] == 1  # acked, then honestly accounted as failed


def test_model_error_propagates_without_retry():
    router, (a, b) = make_fleet(2)
    a.fail_once = ValueError("bad input shape")
    b.fail_once = ValueError("bad input shape")
    with pytest.raises(ValueError):
        router.predict(1)
    # Exactly one replica executed: a request failing on its merits must
    # not burn the fleet retrying it.
    assert a.calls + b.calls == 1
    assert counts(router)["failed"] == 1


def test_no_replicas_raises_no_ready():
    router = Router()
    with pytest.raises(NoReadyReplicas):
        router.predict(1)


def test_shed_with_retry_after_when_at_capacity():
    router, (a, b) = make_fleet(2, capacity=1)
    a.gate = threading.Event()
    b.gate = threading.Event()
    threads = [
        threading.Thread(target=router.predict, args=(i,))
        for i in range(2)
    ]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 5
    while a.calls + b.calls < 2 and time.monotonic() < deadline:
        time.sleep(0.005)

    with pytest.raises(Overloaded) as exc:
        router.predict(99)
    assert exc.value.retry_after > 0
    c = counts(router)
    assert c["shed"] == 1
    assert c["acked"] == 2  # the shed request was never acknowledged
    a.gate.set()
    b.gate.set()
    for t in threads:
        t.join(timeout=5)
    assert counts(router)["completed"] == 2


def test_replica_overloaded_tries_sibling():
    router, (a, b) = make_fleet(2)
    a.fail_once = ReplicaOverloaded("queue full")
    b.fail_once = ReplicaOverloaded("queue full")
    # One of them refuses; the other (whose fail already fired or not)
    # may refuse too — but a second pass succeeds within the deadline.
    assert router.predict(1)[0] == "ok"
    assert counts(router)["failed"] == 0


# -- the drain matrix -------------------------------------------------------


def test_drain_waits_for_inflight_then_blocks_admission():
    router, (a, b) = make_fleet(2)
    a.gate = threading.Event()
    t = threading.Thread(target=router.predict, args=(1,))
    t.start()
    deadline = time.monotonic() + 5
    while a.calls + b.calls == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    busy, other = (a, b) if a.calls else (b, a)

    drained = []
    dt = threading.Thread(
        target=lambda: drained.append(router.drain(busy.name, timeout=10))
    )
    dt.start()
    time.sleep(0.05)
    assert not drained  # in-flight work pins the drain

    # No new admissions to the draining replica: traffic flows to the
    # sibling the whole time.
    before = busy.calls
    for i in range(3):
        assert router.predict(i)[1] == other.name
    assert busy.calls == before

    busy.gate.set()  # in-flight request completes...
    dt.join(timeout=5)
    assert drained == [True]  # ...and the drain observes it
    assert counts(router)["failed"] == 0


def test_roll_swaps_quiesced_and_readmits():
    router, (a, b) = make_fleet(2)
    a.gate = threading.Event()
    t = threading.Thread(target=router.predict, args=(1,))
    t.start()
    deadline = time.monotonic() + 5
    while a.calls + b.calls == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    busy = a if a.calls else b

    quiesced = []

    def swap():
        # Router.roll's contract: swap_fn runs with zero in-flight work.
        quiesced.append(router.stats()["replicas"][busy.name]["outstanding"])

    threading.Timer(0.05, busy.gate.set).start()
    out_of_rotation = router.roll(busy.name, swap, timeout=10)
    t.join(timeout=5)
    assert quiesced == [0]
    assert out_of_rotation >= 0.0
    # Re-admitted: the rolled replica serves traffic again.
    assert busy.name in router.ready_names()
    busy.gate = None
    served = {router.predict(i)[1] for i in range(8)}
    assert busy.name in served


def test_kill_mid_drain_falls_back_to_survivor():
    router, (a, b) = make_fleet(2)
    a.gate = threading.Event()
    results = []
    t = threading.Thread(
        target=lambda: results.append(router.predict(1))
    )
    t.start()
    deadline = time.monotonic() + 5
    while a.calls + b.calls == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    busy, other = (a, b) if a.calls else (b, a)
    other.gate = None

    drained = []
    dt = threading.Thread(
        target=lambda: drained.append(router.drain(busy.name, timeout=10))
    )
    dt.start()
    time.sleep(0.05)
    busy.kill()  # SIGKILL mid-drain: in-flight dies with ReplicaGone

    t.join(timeout=5)
    dt.join(timeout=5)
    # The in-flight request failed over to the survivor — acked work is
    # never dropped — and the drain still completed.
    assert results and results[0][1] == other.name
    assert drained == [True]
    c = counts(router)
    assert c["acked"] == c["completed"] == 1
    assert c["failed"] == 0
    assert router.retried_total.value() == 1


def test_all_draining_is_overloaded_not_dead():
    router, (a, b) = make_fleet(2)
    router.drain(a.name, timeout=1)
    router.drain(b.name, timeout=1)
    with pytest.raises(Overloaded):
        router.predict(1)
    router.admit(a.name)
    assert router.predict(1)[0] == "ok"
