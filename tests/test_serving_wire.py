"""Binary tensor wire protocol + pooled keep-alive transport (ISSUE 15).

Three layers under test:

- `serving/wire.py` framing: roundtrip across dtypes/shapes, rejection
  of corrupt frames, endianness normalization.
- Server negotiation (`ModelServerApp`): tensor-framed requests decode
  without JSON, responses answer in the negotiated format, and the JSON
  surface stays byte-identical for TF-Serving parity clients.
- `HttpReplica` transport: the keep-alive pool actually pools, a stale
  idle socket is silently replaced (pre-write only), a failure after
  bytes hit the wire still raises ReplicaGone and invalidates the pool
  (the crisp-death contract the router's retry accounting needs), and a
  JSON-only server triggers the sticky negotiation fallback.
"""

import http.client
import socket
import threading

import jax
import numpy as np
import pytest

from kubeflow_tpu.models.resnet import tiny_resnet
from kubeflow_tpu.serving import (
    ModelRepository,
    ModelServerApp,
    ReplicaGone,
    Router,
    Servable,
)
from kubeflow_tpu.serving import wire
from kubeflow_tpu.serving.replica import HttpReplica
from kubeflow_tpu.web import App, HttpError, TestClient, json_response
from kubeflow_tpu.web.wsgi import _Http11Handler, serve


# -- framing -----------------------------------------------------------------


@pytest.mark.parametrize(
    "arr",
    [
        np.arange(12, dtype=np.float32).reshape(3, 4),
        np.arange(6, dtype=np.int8).reshape(2, 3, 1),
        np.array([[True, False]]),
        np.arange(4, dtype=np.float16).reshape(4, 1),
        np.float64(3.5),  # scalar: empty dims segment
        np.zeros((0, 7), np.float32),  # empty batch still frames
    ],
)
def test_roundtrip(arr):
    out = wire.decode_tensor(wire.encode_tensor(arr))
    assert out.dtype == np.asarray(arr).dtype.newbyteorder("=")
    assert out.shape == np.asarray(arr).shape
    np.testing.assert_array_equal(out, arr)


def test_decoded_view_is_readonly_over_frame():
    frame = wire.encode_tensor(np.arange(4, dtype=np.float32))
    out = wire.decode_tensor(frame)
    assert not out.flags.writeable  # frombuffer view, copy to mutate


def test_big_endian_normalized():
    be = np.arange(3, dtype=">f4")
    out = wire.decode_tensor(wire.encode_tensor(be))
    assert out.dtype.byteorder in ("<", "=")
    np.testing.assert_array_equal(out, be.astype("<f4"))


def test_object_dtype_refused():
    with pytest.raises(wire.WireFormatError):
        wire.encode_tensor(np.array([object()]))


@pytest.mark.parametrize(
    "data",
    [
        b"",
        b"KFT",
        b"NOPE" + b"\x00" * 20,
        b"KFT1\xff\xff\xff\xff",  # header length > _MAX_HEADER
        b"KFT1\x10\x00\x00\x00<f4:",  # truncated header
        wire.encode_tensor(np.zeros(4, np.float32))[:-3],  # short payload
        wire.encode_tensor(np.zeros(4, np.float32)) + b"xx",  # long payload
        b"KFT1\x07\x00\x00\x00<f4:a,b",  # non-integer dims
        b"KFT1\x06\x00\x00\x00nope:1",  # unknown dtype
    ],
)
def test_corrupt_frames_refused(data):
    with pytest.raises(wire.WireFormatError):
        wire.decode_tensor(data)


def _frame(header: bytes, payload: bytes = b"") -> bytes:
    """Hand-build a frame around an arbitrary (hostile) header."""
    return b"KFT1" + len(header).to_bytes(4, "little") + header + payload


# Frames that are structurally intact — magic, length, ascii header —
# but whose header is hostile (ISSUE 17 satellite). Each must die as a
# WireFormatError in decode_tensor, never as a raw ValueError out of
# np.dtype/reshape.
HOSTILE_FRAMES = [
    _frame(b"<U4:2", b"\x00" * 32),  # str dtype
    _frame(b"object:1", b"\x00" * 8),  # object dtype
    _frame(b"|V8:1", b"\x00" * 8),  # void/record dtype
    _frame(b"<M8[s]:2", b"\x00" * 16),  # datetime dtype
    _frame(b"<f4:-1,4", b"\x00" * 16),  # negative dim -> inferred reshape
    _frame(b"<f4:2,,2", b"\x00" * 16),  # empty dims component
    # int64-wrap collision: 4 * 4611686018427387905 == 2**64 + 4, so a
    # wrapping product "matches" this 4-byte payload and reshape gets a
    # 2**62-element shape. math.prod must catch it as a mismatch.
    _frame(b"<f4:4611686018427387905", b"\x00" * 4),
]


@pytest.mark.parametrize("data", HOSTILE_FRAMES)
def test_hostile_headers_refused(data):
    with pytest.raises(wire.WireFormatError):
        wire.decode_tensor(data)


@pytest.mark.parametrize(
    "arr",
    [
        np.array(["a", "b"]),  # str
        np.array([b"x"]),  # bytes
        np.zeros(2, dtype="M8[s]"),  # datetime
    ],
)
def test_non_numeric_encode_refused(arr):
    with pytest.raises(wire.WireFormatError):
        wire.encode_tensor(arr)


def test_negotiation_helpers():
    tensor, js = wire.TENSOR_CONTENT_TYPE, "application/json"
    assert wire.is_tensor_request({"content-type": tensor})
    assert wire.is_tensor_request({"content-type": f"{tensor}; q=1"})
    assert not wire.is_tensor_request({"content-type": js})
    assert not wire.is_tensor_request({})
    # Accept tensor wins; explicit JSON Accept loses; no Accept follows
    # the request's own content type.
    assert wire.wants_tensor_response({"accept": tensor})
    assert not wire.wants_tensor_response(
        {"accept": js, "content-type": tensor}
    )
    assert wire.wants_tensor_response({"content-type": tensor})
    assert not wire.wants_tensor_response({"content-type": js})


# -- server negotiation ------------------------------------------------------


@pytest.fixture(scope="module")
def model():
    module = tiny_resnet(num_classes=10)
    variables = jax.jit(module.init)(
        jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32)
    )
    return module, variables


@pytest.fixture(scope="module")
def app(model):
    module, variables = model
    servable = Servable.from_module(
        "mnist", module, variables, max_batch=8, train=False
    )
    return ModelServerApp(ModelRepository([servable]))


@pytest.fixture(scope="module")
def client(app):
    return TestClient(app)


def _batch(n, seed=0):
    rng = np.random.RandomState(seed)
    return rng.rand(n, 32, 32, 3).astype(np.float32)


def test_binary_predict_matches_json(client):
    x = _batch(3)
    json_resp = client.post(
        "/v1/models/mnist:predict", {"instances": x.tolist()}
    )
    assert json_resp.status == 200
    bin_resp = client.post(
        "/v1/models/mnist:predict",
        raw=wire.encode_tensor(x),
        content_type=wire.TENSOR_CONTENT_TYPE,
        headers={"Accept": wire.TENSOR_CONTENT_TYPE},
    )
    assert bin_resp.status == 200, bin_resp.body
    assert bin_resp.content_type == wire.TENSOR_CONTENT_TYPE
    got = wire.decode_tensor(bin_resp.body)
    assert got.shape == (3, 10)
    np.testing.assert_allclose(
        got, np.asarray(json_resp.json()["predictions"]), atol=1e-3
    )


def test_binary_request_json_accept_gets_json(client):
    resp = client.post(
        "/v1/models/mnist:predict",
        raw=wire.encode_tensor(_batch(1)),
        content_type=wire.TENSOR_CONTENT_TYPE,
        headers={"Accept": "application/json"},
    )
    assert resp.status == 200
    assert np.asarray(resp.json()["predictions"]).shape == (1, 10)


def test_json_request_tensor_accept_gets_frame(client):
    resp = client.post(
        "/v1/models/mnist:predict",
        {"instances": _batch(1).tolist()},
        headers={"Accept": wire.TENSOR_CONTENT_TYPE},
    )
    assert resp.status == 200
    assert wire.decode_tensor(resp.body).shape == (1, 10)


def test_json_surface_unchanged(client):
    """TF-Serving parity: a plain JSON request gets the same envelope
    as before the protocol landed — application/json, predictions key."""
    resp = client.post(
        "/v1/models/mnist:predict", {"instances": _batch(1).tolist()}
    )
    assert resp.status == 200
    assert resp.content_type == "application/json"
    assert set(resp.json()) == {"predictions"}


def test_bad_frame_is_400(client):
    resp = client.post(
        "/v1/models/mnist:predict",
        raw=b"KFT1 this is not a frame",
        content_type=wire.TENSOR_CONTENT_TYPE,
    )
    assert resp.status == 400


def test_scalar_frame_is_400(client):
    resp = client.post(
        "/v1/models/mnist:predict",
        raw=wire.encode_tensor(np.float32(1.0)),
        content_type=wire.TENSOR_CONTENT_TYPE,
    )
    assert resp.status == 400  # no leading batch dimension


@pytest.mark.parametrize("data", HOSTILE_FRAMES)
def test_hostile_frame_is_clean_400_with_counter(client, app, data):
    """Server boundary for the hostile headers: a clean 400 (not an
    unhandled ValueError 500 out of the WSGI handler) and an invalid
    request-counter bump the dashboards can alert on."""
    before = app.request_count.value(model="mnist", outcome="invalid")
    resp = client.post(
        "/v1/models/mnist:predict",
        raw=data,
        content_type=wire.TENSOR_CONTENT_TYPE,
    )
    assert resp.status == 400, resp.body
    after = app.request_count.value(model="mnist", outcome="invalid")
    assert after == before + 1


# -- pooled transport over a real server -------------------------------------


@pytest.fixture()
def live_server(app):
    server, thread = serve(app, host="127.0.0.1", port=0)
    try:
        yield f"127.0.0.1:{server.server_port}"
    finally:
        server.shutdown()
        thread.join(timeout=10)


def test_pool_reuses_one_connection(live_server):
    replica = HttpReplica("r", live_server, "mnist")
    x = _batch(1)
    for _ in range(10):
        out = replica.predict(x)
        assert out.shape == (1, 10)
    stats = replica.transport_stats()
    assert stats["dials"] == 1, stats  # conn-per-request would dial 10x
    assert replica._binary_confirmed  # frames negotiated, not JSON
    replica.close()


def test_stale_idle_socket_replaced_prewrite(live_server):
    """An idle pooled socket the peer closed (readable EOF before any
    request bytes) is silently discarded and redialed — NOT surfaced as
    ReplicaGone, because nothing was ever sent on it."""
    replica = HttpReplica("r", live_server, "mnist")
    a, b = socket.socketpair()
    dead = http.client.HTTPConnection("127.0.0.1", 1)
    dead.sock = a
    b.close()  # EOF pending on a -> checkout must reject it
    with replica._pool_lock:
        replica._idle.append(dead)
    out = replica.predict(_batch(1))
    assert out.shape == (1, 10)
    assert replica.transport_stats()["generation"] == 0  # no death signal
    replica.close()


def test_failure_after_bytes_is_replica_gone():
    """A peer that accepts, reads, and resets mid-exchange is a dead
    replica: ReplicaGone (no transparent retry), pool invalidated."""
    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(4)
    port = lst.getsockname()[1]

    def acceptor():
        try:
            while True:
                conn, _ = lst.accept()
                conn.close()  # reset after the request is written
        except OSError:
            pass

    t = threading.Thread(target=acceptor, daemon=True)
    t.start()
    replica = HttpReplica("r", f"127.0.0.1:{port}", "mnist", timeout=5.0)
    try:
        with pytest.raises(ReplicaGone):
            replica.predict(_batch(1))
        assert replica.transport_stats()["generation"] >= 1
        assert replica.transport_stats()["idle"] == 0
    finally:
        lst.close()
        replica.close()


def test_stats_probes_model_state(live_server):
    assert HttpReplica("r", live_server, "mnist").stats() == {
        "ready": True
    }
    # Listening but not serving this model: wedged, not ready — the
    # seed hardcoded {"ready": True} here.
    assert HttpReplica("r", live_server, "absent").stats() == {
        "ready": False
    }


def test_stats_dead_endpoint_not_ready():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    replica = HttpReplica("r", f"127.0.0.1:{port}", "mnist", timeout=2.0)
    assert replica.stats() == {"ready": False}


def test_json_only_server_sticky_fallback():
    """A server that 4xx's tensor frames (the pre-protocol surface) is
    detected on the first exchange; the replica drops to JSON for good
    and the request still succeeds."""

    legacy = App("legacy-model-server")

    @legacy.route("/v1/models/<name>", methods=("POST",))
    def old_predict(req):
        if "json" not in (req.headers.get("content-type") or ""):
            raise HttpError(400, "expected JSON")
        n = len(req.json()["instances"])
        return json_response({"predictions": [[0.0]] * n})

    server, thread = serve(legacy, host="127.0.0.1", port=0)
    try:
        replica = HttpReplica(
            "r", f"127.0.0.1:{server.server_port}", "mnist:predict"
        )
        out = replica.predict(_batch(2))
        assert out.shape == (2, 1)
        assert replica._binary is False  # sticky: no frame retry per call
        replica.close()
    finally:
        server.shutdown()
        thread.join(timeout=10)


def test_router_drain_invalidates_pool():
    calls = []

    class FakeReplica:
        name, capacity = "f", 4

        def predict(self, x):
            return np.asarray(x)

        def invalidate_pool(self):
            calls.append("invalidate")

    router = Router()
    router.add(FakeReplica())
    assert router.drain("f")
    assert calls == ["invalidate"]


def test_wsgi_handler_disables_nagle():
    # StreamRequestHandler applies TCP_NODELAY from this class attr;
    # small predict responses must not eat Nagle/delayed-ACK stalls.
    assert _Http11Handler.disable_nagle_algorithm is True
