"""Gang-worker sidecar sequencing + HTTP apiserver facade."""

import pathlib
import subprocess
import sys
import threading

import pytest

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.sidecar import SIGCONT_FILE, SIGTERM_FILE, SidecarController
from kubeflow_tpu.sidecar.controller import local_dir_uploader
from kubeflow_tpu.testing import FakeApiServer
from kubeflow_tpu.testing.apiserver_http import ApiServerApp, HttpApiClient
from kubeflow_tpu.testing.fake_apiserver import NotFound
from kubeflow_tpu.web.wsgi import serve


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += dt


def make_job(api, phase=None):
    job = api.create(new_resource("TpuJob", "job1", "team"))
    if phase:
        job = job.thaw()
        job.status["phase"] = phase
        api.update_status(job)
    return job


def controller(api, tmp_path, **kw):
    clock = FakeClock()
    kw.setdefault("clock", clock)
    kw.setdefault("sleep", clock.sleep)
    kw.setdefault("poll_seconds", 1.0)
    kw.setdefault("timeout_seconds", 30.0)
    return (
        SidecarController(
            workdir=tmp_path / "sig", job_name="job1", namespace="team",
            api=api, **kw
        ),
        clock,
    )


def test_wait_ready_gates_on_probes(tmp_path):
    api = FakeApiServer()
    state = {"device": False, "coord": False, "downloaded": False}
    ctl, clock = controller(
        api,
        tmp_path,
        device_probe=lambda: state["device"],
        coordinator_probe=lambda: state["coord"],
        download=lambda: state.__setitem__("downloaded", True),
    )

    # Flip the probes as "time" passes.
    orig_sleep = clock.sleep

    def sleep(dt):
        orig_sleep(dt)
        if clock.t >= 2:
            state["device"] = True
        if clock.t >= 4:
            state["coord"] = True

    ctl.sleep = sleep
    ctl.wait_ready()
    assert state["downloaded"]
    assert ctl.has_signal(SIGCONT_FILE)
    assert not ctl.has_signal(SIGTERM_FILE)


def test_wait_ready_times_out(tmp_path):
    ctl, _ = controller(FakeApiServer(), tmp_path, device_probe=lambda: False)
    with pytest.raises(TimeoutError):
        ctl.wait_ready()


def test_wait_done_signals_on_terminal_phase(tmp_path):
    api = FakeApiServer()
    make_job(api, phase="Running")
    ctl, clock = controller(api, tmp_path)

    def flip():
        job = api.get("TpuJob", "job1", "team").thaw()
        job.status["phase"] = "Succeeded"
        api.update_status(job)

    orig_sleep = clock.sleep

    def sleep(dt):
        orig_sleep(dt)
        if clock.t >= 3:
            flip()

    ctl.sleep = sleep
    assert ctl.wait_done() == "Succeeded"
    assert ctl.has_signal(SIGTERM_FILE)


def test_vanished_job_is_failed(tmp_path):
    """Master object gone ⇒ terminate (controller.py:95-99 semantics)."""
    api = FakeApiServer()
    ctl, _ = controller(api, tmp_path)
    assert ctl.wait_done() == "Failed"
    assert ctl.has_signal(SIGTERM_FILE)


def test_transient_poll_errors_do_not_kill_watch(tmp_path):
    """An apiserver blip mid-watch must not crash the sidecar — a dead
    sidecar never writes SIGTERM and the main container hangs forever."""
    api = FakeApiServer()
    make_job(api, phase="Running")
    ctl, clock = controller(api, tmp_path)

    real_get = api.get
    calls = {"n": 0}

    def flaky_get(*a, **kw):
        calls["n"] += 1
        if calls["n"] <= 2:
            raise ConnectionRefusedError("apiserver restarting")
        if calls["n"] >= 4:
            job = real_get("TpuJob", "job1", "team").thaw()
            job.status["phase"] = "Succeeded"
            return job
        return real_get(*a, **kw)

    ctl.api = type("A", (), {"get": staticmethod(flaky_get)})()
    assert ctl.wait_done() == "Succeeded"
    assert ctl.has_signal(SIGTERM_FILE)


def test_malformed_coordinator_fails_fast(tmp_path):
    with pytest.raises(ValueError, match="host:port"):
        SidecarController(
            workdir=tmp_path, job_name="j", coordinator="myhost"
        )


def test_watch_timeout_forces_sigterm(tmp_path):
    api = FakeApiServer()
    make_job(api, phase="Running")  # never terminates
    ctl, _ = controller(api, tmp_path, timeout_seconds=5.0)
    assert ctl.wait_done() == "Failed"
    assert ctl.has_signal(SIGTERM_FILE)


def test_artifact_upload(tmp_path):
    api = FakeApiServer()
    make_job(api, phase="Succeeded")
    results = tmp_path / "results"
    results.mkdir()
    (results / "metrics.json").write_text("{}")
    store = tmp_path / "store"
    ctl, _ = controller(api, tmp_path, upload=local_dir_uploader(store))
    assert ctl.run(results_dir=results) == "Succeeded"
    assert (store / "metrics.json").exists()


# -- HTTP facade ----------------------------------------------------------


@pytest.fixture
def http_api():
    api = FakeApiServer()
    server, _ = serve(ApiServerApp(api), host="127.0.0.1", port=0)
    client = HttpApiClient(f"http://127.0.0.1:{server.server_port}")
    yield api, client
    server.shutdown()


def test_http_facade_crud(http_api):
    api, client = http_api
    created = client.create(
        new_resource("TpuJob", "j", "team", labels={"a": "b"})
    )
    assert created.metadata.uid

    got = client.get("TpuJob", "j", "team")
    assert got.metadata.name == "j"

    got.status["phase"] = "Running"
    client.update_status(got)
    assert api.get("TpuJob", "j", "team").status["phase"] == "Running"

    assert [r.metadata.name for r in client.list("TpuJob", "team")] == ["j"]
    assert client.list("TpuJob", "team", label_selector={"a": "b"})
    assert not client.list("TpuJob", "team", label_selector={"a": "x"})

    # Cluster-scoped objects round-trip through the '_' segment.
    client.create(new_resource("Namespace", "ns1", ""))
    assert client.get("Namespace", "ns1", "").metadata.name == "ns1"

    client.delete("TpuJob", "j", "team")
    with pytest.raises(NotFound):
        client.get("TpuJob", "j", "team")


def test_http_facade_conflict_mapping(http_api):
    _, client = http_api
    client.create(new_resource("TpuJob", "j", "team"))
    from kubeflow_tpu.testing.fake_apiserver import AlreadyExists, Conflict

    with pytest.raises(AlreadyExists):
        client.create(new_resource("TpuJob", "j", "team"))

    stale = client.get("TpuJob", "j", "team")
    fresh = client.get("TpuJob", "j", "team")
    fresh.metadata.labels["x"] = "y"
    client.update(fresh)
    stale.metadata.labels["x"] = "z"
    with pytest.raises(Conflict):
        client.update(stale)


def test_sidecar_cli_against_http_apiserver(tmp_path):
    """Cross-process: the sidecar CLI watches a real HTTP apiserver."""
    api = FakeApiServer()
    job = api.create(new_resource("TpuJob", "job1", "team")).thaw()
    job.status["phase"] = "Running"
    api.update_status(job)
    server, _ = serve(ApiServerApp(api), host="127.0.0.1", port=0)
    url = f"http://127.0.0.1:{server.server_port}"

    def finish_soon():
        import time

        time.sleep(1.0)
        fresh = api.get("TpuJob", "job1", "team").thaw()
        fresh.status["phase"] = "Succeeded"
        api.update_status(fresh)

    threading.Thread(target=finish_soon, daemon=True).start()
    proc = subprocess.run(
        [
            sys.executable, "-m", "kubeflow_tpu.sidecar",
            "--workdir", str(tmp_path / "sig"),
            "--job", "job1", "--namespace", "team",
            "--apiserver", url,
            "--poll-seconds", "0.2", "--timeout-seconds", "30",
            "--skip-device-probe",
        ],
        capture_output=True,
        text=True,
        timeout=60,
        cwd=pathlib.Path(__file__).parent.parent,
    )
    server.shutdown()
    assert proc.returncode == 0, proc.stderr
    assert "Succeeded" in proc.stdout
    assert (tmp_path / "sig" / SIGCONT_FILE).exists()
    assert (tmp_path / "sig" / SIGTERM_FILE).exists()
