"""Small components: availability prober (#25), echo server (#19),
static config server (#20)."""

from kubeflow_tpu.apps.echo import EchoApp
from kubeflow_tpu.apps.probe import AvailabilityProber, ProberApp
from kubeflow_tpu.apps.staticserver import StaticConfigApp
from kubeflow_tpu.web import TestClient
from kubeflow_tpu.web.wsgi import serve


# -- prober ----------------------------------------------------------------


def test_prober_gauges_flip_with_target_health():
    health = {"ok": True}
    prober = AvailabilityProber(
        "http://target/healthz", probe=lambda url: health["ok"]
    )
    assert prober.probe_once() is True
    client = TestClient(ProberApp(prober))
    text = client.get("/metrics").body.decode()
    assert 'kubeflow_availability{url="http://target/healthz"} 1' in text

    health["ok"] = False
    assert prober.probe_once() is False
    text = client.get("/metrics").body.decode()
    assert 'kubeflow_availability{url="http://target/healthz"} 0' in text
    assert "kubeflow_probe_failures_total" in text


def test_prober_survives_raising_probe():
    def bad_probe(url):
        raise RuntimeError("dns exploded")

    prober = AvailabilityProber("http://x", probe=bad_probe)
    assert prober.probe_once() is False  # no exception escapes


def test_prober_against_live_endpoint():
    """The real flow (`kubeflow-readiness.py`): HTTP-probe a served app."""
    target = EchoApp()
    server, _ = serve(target, host="127.0.0.1", port=0)
    try:
        prober = AvailabilityProber(
            f"http://127.0.0.1:{server.server_port}/healthz"
        )
        assert prober.probe_once() is True
    finally:
        server.shutdown()
        server.server_close()  # unbind: probe fails fast, not on timeout
    assert prober.probe_once() is False  # server gone


# -- echo ------------------------------------------------------------------


def test_echo_reflects_request():
    client = TestClient(
        EchoApp(), headers={"x-goog-authenticated-user-email": "a@b.co"}
    )
    resp = client.post("/some/deep/path?x=1", {"k": "v"})
    body = resp.json()
    assert body["method"] == "POST"
    assert body["path"] == "/some/deep/path"
    assert body["query"] == {"x": "1"}
    assert '"k"' in body["body"]
    assert (
        body["headers"]["x-goog-authenticated-user-email"] == "a@b.co"
    )


# -- static config server --------------------------------------------------


def test_static_serves_files_with_content_type(tmp_path):
    (tmp_path / "cfg").mkdir()
    (tmp_path / "cfg" / "links.json").write_text('{"menuLinks": []}')
    (tmp_path / "index.html").write_text("<html></html>")
    client = TestClient(StaticConfigApp(tmp_path))

    resp = client.get("/cfg/links.json")
    assert resp.status == 200
    assert resp.json() == {"menuLinks": []}
    assert ("Content-Type", "application/json") in resp.headers

    assert client.get("/").status == 200  # index.html default
    assert client.get("/missing.yaml").status == 404


def test_static_blocks_path_traversal(tmp_path):
    (tmp_path / "serve").mkdir()
    (tmp_path / "secret.txt").write_text("s3cr3t")
    client = TestClient(StaticConfigApp(tmp_path / "serve"))
    resp = client.get("/../secret.txt")
    assert resp.status in (403, 404)
    assert b"s3cr3t" not in resp.body
